"""Benchmark: MNIST-shaped epoch wall-clock on the available accelerator.

Primary metric (BASELINE.json): "MNIST epoch wall-clock (s)". The reference
baseline is the serial C trainer at ~99 s per 60k-sample epoch (gcc -O2,
BASELINE.md — the only variant that both compiles and actually reads its
data). vs_baseline reports the speedup factor (baseline / ours, >1 is
faster than the reference).

Training config mirrors the reference loop semantics: its exact model
(cnn.c:416-428), batch 32 == its accumulator period, lr 0.1, SGD — on
60,000 MNIST-shaped samples (synthetic stripes; no network access for real
MNIST, and identical compute per step either way).

Prints exactly one JSON line on stdout.
"""

from __future__ import annotations

import json
import time

REFERENCE_EPOCH_S = 99.0  # BASELINE.md: serial C, ~1.65 ms/sample x 60k


def main() -> None:
    import jax
    import jax.numpy as jnp

    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.data.pipeline import epoch_batches, normalize_images, one_hot
    from mpi_cuda_cnn_tpu.models.initializers import get_initializer
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.parallel.dp import dp_shard_batch, make_dp_train_step, replicate
    from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
    from mpi_cuda_cnn_tpu.train.trainer import make_loss_fn

    batch_size = 32
    ds = synthetic_stripes(num_train=60_000, num_test=32)

    mesh = make_mesh({DATA_AXIS: 1}, devices=jax.devices()[:1])
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    optimizer = make_optimizer(0.1)
    state = replicate(
        {"params": params, "opt_state": optimizer.init(params),
         "step": jnp.zeros((), jnp.int32)},
        mesh,
    )
    step = make_dp_train_step(make_loss_fn(model), optimizer, mesh)

    train_x = normalize_images(ds.train_images)
    train_y = one_hot(ds.train_labels, ds.num_classes)

    import numpy as np

    rng = np.random.default_rng(0)
    batches = [
        dp_shard_batch((jnp.asarray(bx), jnp.asarray(by)), mesh)
        for bx, by in epoch_batches(train_x, train_y, batch_size, rng=rng)
    ]

    # Warmup: compile + a few steady-state steps.
    for bx, by in batches[:10]:
        state, m = step(state, bx, by)
    jax.block_until_ready((state, m))

    t0 = time.perf_counter()
    for bx, by in batches:
        state, m = step(state, bx, by)
    jax.block_until_ready((state, m))
    epoch_s = time.perf_counter() - t0

    print(json.dumps({
        "metric": "mnist_epoch_wallclock",
        "value": round(epoch_s, 3),
        "unit": "s",
        "vs_baseline": round(REFERENCE_EPOCH_S / epoch_s, 2),
    }))


if __name__ == "__main__":
    main()
