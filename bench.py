"""Benchmark: MNIST-shaped epoch wall-clock on the available accelerator.

Primary metric (BASELINE.json): "MNIST epoch wall-clock (s)". The reference
baseline is the serial C trainer at ~99 s per 60k-sample epoch (gcc -O2,
BASELINE.md — the only variant that both compiles and actually reads its
data). vs_baseline reports the speedup factor (baseline / ours, >1 is
faster than the reference).

Training config mirrors the reference loop semantics: its exact model
(cnn.c:416-428), batch 32 == its accumulator period, lr 0.1, SGD — on
60,000 MNIST-shaped samples (synthetic stripes; no network access for real
MNIST, and identical compute per step either way). Runs the real product
path: Trainer with the scanned-epoch SPMD program (HBM-resident dataset,
one device dispatch per epoch).

Prints exactly one JSON line on stdout.
"""

from __future__ import annotations

import json
import sys
import time

REFERENCE_EPOCH_S = 99.0  # BASELINE.md: serial C, ~1.65 ms/sample x 60k


def _run() -> None:
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ds = synthetic_stripes(num_train=60_000, num_test=32)
    cfg = Config(
        model="reference_cnn",
        epochs=1,
        batch_size=32,   # cnn.c:449 accumulator period
        lr=0.1,          # cnn.c:446
        eval_every=0,
        log_every=10**9,  # single scan dispatch per epoch
        num_devices=1,
    )
    trainer = Trainer(
        get_model("reference_cnn"), ds, cfg, metrics=MetricsLogger(echo=False)
    )

    trainer.run_epoch(0)  # warmup: stages the dataset + compiles the scan
    # Best of 3 measured epochs: the TPU tunnel in this environment adds
    # run-to-run dispatch jitter (~15%); the minimum is the steady state.
    times = []
    for epoch in (1, 2, 3):
        t0 = time.perf_counter()
        trainer.run_epoch(epoch)
        times.append(time.perf_counter() - t0)
    epoch_s = min(times)
    median_s = sorted(times)[len(times) // 2]

    print(json.dumps({
        "metric": "mnist_epoch_wallclock",
        "value": round(epoch_s, 3),
        "unit": "s",
        "vs_baseline": round(REFERENCE_EPOCH_S / epoch_s, 2),
        "median_s": round(median_s, 3),
        "note": "value = best of 3 epochs; median_s = median of the same 3",
    }))


def main() -> None:
    # The TPU tunnel in this environment occasionally drops a remote-compile
    # RPC mid-body (jaxlib surfaces it as a generic runtime error, so the
    # except is deliberately broad); a retry re-hits the compile cache and
    # succeeds. Deterministic failures cost two extra runs, then propagate.
    attempts = 3
    for attempt in range(1, attempts + 1):
        try:
            _run()
            return
        except Exception as exc:  # noqa: BLE001
            if attempt == attempts:
                raise
            print(f"bench attempt {attempt} failed: {exc!r}", file=sys.stderr)
            time.sleep(5.0)


if __name__ == "__main__":
    main()
