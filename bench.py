"""Benchmark: MNIST-shaped epoch wall-clock on the available accelerator.

Primary metric (BASELINE.json): "MNIST epoch wall-clock (s)". The reference
baseline is the serial C trainer at ~99 s per 60k-sample epoch (gcc -O2,
BASELINE.md — the only variant that both compiles and actually reads its
data). vs_baseline reports the speedup factor (baseline / ours, >1 is
faster than the reference).

Training config mirrors the reference loop semantics: its exact model
(cnn.c:416-428), batch 32 == its accumulator period, lr 0.1, SGD — on
60,000 MNIST-shaped samples (synthetic stripes; no network access for real
MNIST, and identical compute per step either way). Runs the real product
path: Trainer with the scanned-epoch SPMD program (HBM-resident dataset,
one device dispatch per epoch).

Prints exactly one JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import sys
import time

REFERENCE_EPOCH_S = 99.0  # BASELINE.md: serial C, ~1.65 ms/sample x 60k

ATTEMPT_TIMEOUT_S = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", 240.0))
TOTAL_TIMEOUT_S = float(os.environ.get("BENCH_TOTAL_TIMEOUT_S", 540.0))


def _run() -> None:
    hang = float(os.environ.get("BENCH_CHILD_HANG_S", 0) or 0)
    if hang:
        # Test hook (tests/test_bench_contract.py): simulate a backend
        # that hangs at init, deterministically on any machine.
        time.sleep(hang)
    dev = os.environ.get("BENCH_DEVICE")
    if dev:
        # The JAX_PLATFORMS env var can be intercepted by a pre-registered
        # TPU plugin (see cli.py); in-process config selection always works.
        import jax

        jax.config.update("jax_platforms", dev)
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.obs.schema import make_record
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    _t0 = time.perf_counter()

    ds = synthetic_stripes(num_train=60_000, num_test=32)
    cfg = Config(
        model="reference_cnn",
        epochs=1,
        batch_size=32,   # cnn.c:449 accumulator period
        lr=0.1,          # cnn.c:446
        eval_every=0,
        log_every=10**9,  # single scan dispatch per epoch
        num_devices=1,
    )
    trainer = Trainer(
        get_model("reference_cnn"), ds, cfg, metrics=MetricsLogger(echo=False)
    )

    trainer.run_epoch(0)  # warmup: stages the dataset + compiles the scan
    # Median of 5 measured epochs: the TPU tunnel in this environment
    # adds run-to-run dispatch jitter (spreads up to ~36% observed), and
    # every shipped measurement bug in this repo's history erred in the
    # optimistic direction (utils/sync.py docstring) — the median is the
    # honest steady state; the fastest epoch stays as a secondary field.
    times = []
    for epoch in (1, 2, 3, 4, 5):
        t0 = time.perf_counter()
        trainer.run_epoch(epoch)
        times.append(time.perf_counter() - t0)
    times.sort()
    epoch_s = times[len(times) // 2]

    # Informational: the epoch's DEVICE time (Trainer.device_epoch_seconds
    # — the one shared two-point implementation). The primary metric
    # stays the wall-clock the baseline was measured in; this field
    # documents how much of it is the remote-tunnel dispatch (~80% for
    # this model). Cost guard: the FIRST pass runs ~19 extra epochs and
    # the sub-15 ms retry ~144 more (ADVICE round 5: the old 19-epoch
    # guard ignored the retry); the whole measurement gets one explicit
    # wall-clock budget, enforced inside the method, so a jittery-tunnel
    # day cannot eat the attempt timeout and discard the already-measured
    # headline. The non-TPU gate lives inside the shared method.
    device_s = None
    device_budget_s = min(30.0, ATTEMPT_TIMEOUT_S / 4)
    if 19 * epoch_s < device_budget_s:
        est = trainer.device_epoch_seconds(budget_s=device_budget_s)
        device_s = round(est, 4) if est is not None else None

    # Compiled-program accounting (obs/cost.py): FLOPs/collectives of
    # the scanned-epoch program actually benchmarked — derived, never
    # hand-typed. XLA counts the scan BODY once (static HLO), so the
    # number is ~one step's FLOPs; the epoch estimate multiplies by the
    # step count. Telemetry must not sink the benchmark: any failure
    # degrades to nulls.
    step_flops = epoch_flops_est = collectives = None
    try:
        from mpi_cuda_cnn_tpu.obs import cost as obs_cost
        from mpi_cuda_cnn_tpu.parallel.dp import dp_shard_perm

        nsteps = trainer.steps_per_epoch
        perm = (trainer._epoch_order(0)[: nsteps * cfg.batch_size]
                .reshape(nsteps, cfg.batch_size).astype("int32"))
        costs = obs_cost.try_analyze(
            trainer._scan_epoch_fn, trainer.state, trainer._dev_images,
            trainer._dev_labels, dp_shard_perm(perm, trainer.mesh),
        )
        if costs is not None:
            step_flops = costs.flops
            epoch_flops_est = costs.flops * nsteps if costs.flops else None
            collectives = costs.collectives
    except Exception:
        pass

    print(json.dumps(make_record(
        "bench", time.perf_counter() - _t0,
        metric="mnist_epoch_wallclock",
        value=round(epoch_s, 3),
        unit="s",
        vs_baseline=round(REFERENCE_EPOCH_S / epoch_s, 2),
        best_s=round(times[0], 3),
        device_epoch_s=device_s,
        step_flops=step_flops,
        epoch_flops_est=epoch_flops_est,
        collectives=collectives,
        note="value = median of 5 wall-clock epochs (one tunnel "
             "dispatch each); device_epoch_s = two-point on-device "
             "epoch time (dispatch window cancelled)",
    )))


def main() -> None:
    # The TPU tunnel in this environment occasionally drops a remote-compile
    # RPC mid-body, and a dead backend can HANG (not fail) inside C-level
    # init where no Python signal handler runs. Each attempt therefore runs
    # in a subprocess with a hard timeout; the parent never imports jax, so
    # whatever happens it prints exactly one JSON line on stdout (round-2
    # lesson: BENCH_r02 was rc=124 with parsed=null after a 25-minute hang).
    import subprocess

    # Real OS clock on purpose: this bounds a subprocess that can HANG
    # in C-level init, and the parent must never import the package
    # (so utils/clock is unreachable).
    # mctpu: disable=MCT002
    deadline = time.monotonic() + TOTAL_TIMEOUT_S
    errors = []
    for attempt in range(1, 4):
        budget = min(ATTEMPT_TIMEOUT_S,
                     deadline - time.monotonic())  # mctpu: disable=MCT002
        if budget <= 10.0:
            errors.append("total wall-clock budget exhausted")
            break
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--child"],
                capture_output=True, text=True, timeout=budget,
            )
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: timed out after {budget:.0f}s")
            continue
        if proc.returncode == 0 and proc.stdout.strip():
            sys.stdout.write(proc.stdout.strip().splitlines()[-1] + "\n")
            return
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        errors.append(f"attempt {attempt}: rc={proc.returncode} " + " | ".join(tail))
        time.sleep(2.0)
    # Literal schema stamp (obs.schema shape) — the parent must never
    # import jax, which importing the package would do.
    print(json.dumps({
        "schema": 1,
        "event": "bench",
        "t": 0.0,
        "metric": "mnist_epoch_wallclock",
        "value": None,
        "unit": "s",
        "vs_baseline": None,
        "error": "; ".join(errors)[-1500:],
    }))
    sys.exit(1)


if __name__ == "__main__":
    if "--child" in sys.argv:
        _run()
    else:
        main()
