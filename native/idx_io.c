/* IDX container loading for the native driver.
 *
 * Implements the MNIST IDX format as documented in SURVEY.md §3.5
 * (4-byte header {u16 magic==0, u8 type==0x08, u8 ndims}, big-endian u32
 * dims, raw payload). Unlike three of the reference's four variants
 * (which allocate the payload and never read it — SURVEY.md 2.8), a
 * short read here is a hard error.
 */
#include "mct.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define MC_IDX_MAX_DIMS 4

typedef struct {
    uint32_t dims[MC_IDX_MAX_DIMS];
    int ndims;
    uint8_t *data;
    size_t count;
} McIdx;

static int idx_load(const char *path, McIdx *out)
{
    memset(out, 0, sizeof(*out));
    FILE *f = fopen(path, "rb");
    if (!f) {
        fprintf(stderr, "mct: cannot open %s\n", path);
        return -1;
    }
    uint8_t hdr[4];
    if (fread(hdr, 1, 4, f) != 4)
        goto bad;
    /* magic (2 bytes) must be zero; element type must be unsigned byte */
    if (hdr[0] != 0 || hdr[1] != 0 || hdr[2] != 0x08)
        goto bad;
    out->ndims = hdr[3];
    if (out->ndims < 1 || out->ndims > MC_IDX_MAX_DIMS)
        goto bad;

    out->count = 1;
    for (int d = 0; d < out->ndims; d++) {
        uint8_t b[4];
        if (fread(b, 1, 4, f) != 4)
            goto bad;
        out->dims[d] = ((uint32_t)b[0] << 24) | ((uint32_t)b[1] << 16) |
                       ((uint32_t)b[2] << 8) | (uint32_t)b[3];
        /* overflow-checked product: dims stay consistent with count */
        if (out->dims[d] && out->count > SIZE_MAX / out->dims[d])
            goto bad;
        out->count *= out->dims[d];
    }
    out->data = malloc(out->count ? out->count : 1);
    if (!out->data)
        goto bad;
    if (fread(out->data, 1, out->count, f) != out->count) {
        fprintf(stderr, "mct: truncated payload in %s\n", path);
        free(out->data);
        out->data = NULL;
        fclose(f);
        return -1;
    }
    fclose(f);
    return 0;
bad:
    fprintf(stderr, "mct: bad IDX file %s\n", path);
    fclose(f);
    return -1;
}

int mc_dataset_load(McDataset *ds, const char *const paths[4])
{
    McIdx tri, trl, tei, tel;
    memset(ds, 0, sizeof(*ds));
    if (idx_load(paths[0], &tri) || idx_load(paths[1], &trl) ||
        idx_load(paths[2], &tei) || idx_load(paths[3], &tel))
        return 111;

    if (tri.ndims < 3 || tei.ndims < 3 || trl.ndims != 1 || tel.ndims != 1 ||
        tri.dims[0] != trl.dims[0] || tei.dims[0] != tel.dims[0]) {
        fprintf(stderr, "mct: inconsistent dataset shapes\n");
        return 111;
    }
    ds->n_train = (int)tri.dims[0];
    ds->n_test = (int)tei.dims[0];
    ds->h = (int)tri.dims[1];
    ds->w = (int)tri.dims[2];
    ds->c = tri.ndims == 4 ? (int)tri.dims[3] : 1;
    ds->n_classes = 10;
    ds->train_images = tri.data;
    ds->train_labels = trl.data;
    ds->test_images = tei.data;
    ds->test_labels = tel.data;
    return 0;
}

void mc_dataset_free(McDataset *ds)
{
    free(ds->train_images);
    free(ds->train_labels);
    free(ds->test_images);
    free(ds->test_labels);
    memset(ds, 0, sizeof(*ds));
}
