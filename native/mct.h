/* mct.h — shared types for the native driver.
 *
 * This is the C side of the framework: a from-scratch f32/NHWC CPU trainer
 * that serves as the numerical reference for the JAX/TPU path (the
 * `--device=cpu|tpu` driver the north star asks for, BASELINE.json).
 * It reimplements the *semantics* documented in SURVEY.md for the
 * reference trainer (cnn.c) with a different architecture: flat parameter
 * arena + layer descriptor table instead of a linked list of structs,
 * NHWC instead of CHW, f32 instead of double, batched minibatch steps
 * instead of per-sample accumulation.
 */
#ifndef MCT_H
#define MCT_H

#include <stddef.h>
#include <stdint.h>

/* ------------------------------------------------------------------ */
/* Dataset: images uint8 NHW(C), labels uint8.                         */

typedef struct {
    uint8_t *train_images, *train_labels, *test_images, *test_labels;
    int n_train, n_test;
    int h, w, c;        /* per-image geometry */
    int n_classes;
} McDataset;

/* Loads the 4-file IDX contract (train-img train-lab test-img test-lab).
 * Returns 0 on success, 111 on any file/format problem (the reference's
 * exit code for data errors). */
int mc_dataset_load(McDataset *ds, const char *const paths[4]);
void mc_dataset_free(McDataset *ds);

/* ------------------------------------------------------------------ */
/* Model: a table of layer descriptors over one contiguous f32 arena.  */

typedef enum { MC_CONV, MC_DENSE, MC_MAXPOOL } McKind;
typedef enum { MC_ACT_NONE, MC_ACT_RELU, MC_ACT_TANH } McAct;

typedef struct {
    McKind kind;
    int k, stride, pad;     /* conv / pool geometry */
    int units;              /* conv out-channels or dense width */
    McAct act;
    /* derived at build time: */
    int ih, iw, ic;         /* input extent  (dense: ic = flat width) */
    int oh, ow, oc;         /* output extent (dense: oc = units)      */
    size_t w_off, b_off;    /* offsets into the parameter arena       */
    size_t nw, nb;          /* parameter counts                       */
} McLayer;

#define MC_MAX_LAYERS 32

typedef struct {
    McLayer layers[MC_MAX_LAYERS];
    int n_layers;
    int in_h, in_w, in_c, n_classes;
    float *params;          /* arena of size n_params */
    float *grads;           /* same layout            */
    size_t n_params;
} McModel;

/* Build a preset ("reference_cnn" or "lenet5_relu") for the given input
 * geometry. Returns 0 on success. */
int mc_model_build(McModel *m, const char *preset, int h, int w, int c,
                   int n_classes);
void mc_model_init_params(McModel *m, uint64_t seed);
void mc_model_free(McModel *m);

/* ------------------------------------------------------------------ */
/* Training.                                                           */

typedef struct {
    float lr;
    int epochs, batch;
    uint64_t seed;
    int log_every;          /* batches between progress lines */
    const char *golden_dir; /* when set: dump golden tensors, 1 batch */
} McTrainCfg;

typedef struct {
    int ntests, ncorrect;
    double train_seconds;
} McResult;

int mc_train(McModel *m, const McDataset *ds, const McTrainCfg *cfg,
             McResult *out);
int mc_eval(const McModel *m, const McDataset *ds, int *ncorrect);

/* ------------------------------------------------------------------ */
/* RNG: xorshift128+ — the driver's documented, reproducible source of
 * randomness (init + shuffling). Distinct from the Python path's keyed
 * jax.random; parity testing loads dumped params instead of replaying
 * RNG streams. */

typedef struct { uint64_t s0, s1; } McRng;
void mc_rng_seed(McRng *r, uint64_t seed);
uint64_t mc_rng_next(McRng *r);
float mc_rng_uniform(McRng *r);              /* [0, 1) */
float mc_rng_irwin_hall(McRng *r);           /* ~N(0,1), 4-uniform sum */

#endif /* MCT_H */
