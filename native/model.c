/* model.c — model construction, parameter arena, init RNG. */
#include "mct.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* RNG: xorshift128+ (documented, portable, fast). Irwin-Hall(4)*1.724
 * matches the distribution family of the framework's "irwin_hall"
 * initializer (models/initializers.py).                               */

void mc_rng_seed(McRng *r, uint64_t seed)
{
    /* splitmix64 expansion of the seed into two nonzero state words */
    uint64_t z = seed + 0x9E3779B97F4A7C15ull;
    for (int i = 0; i < 2; i++) {
        z ^= z >> 30; z *= 0xBF58476D1CE4E5B9ull;
        z ^= z >> 27; z *= 0x94D049BB133111EBull;
        z ^= z >> 31;
        if (i == 0) r->s0 = z | 1; else r->s1 = z | 1;
        z += 0x9E3779B97F4A7C15ull;
    }
}

uint64_t mc_rng_next(McRng *r)
{
    uint64_t a = r->s0, b = r->s1;
    r->s0 = b;
    a ^= a << 23;
    a ^= a >> 17;
    a ^= b ^ (b >> 26);
    r->s1 = a;
    return a + b;
}

float mc_rng_uniform(McRng *r)
{
    return (float)((mc_rng_next(r) >> 40) * (1.0 / 16777216.0));
}

float mc_rng_irwin_hall(McRng *r)
{
    float s = mc_rng_uniform(r) + mc_rng_uniform(r) +
              mc_rng_uniform(r) + mc_rng_uniform(r);
    return (s - 2.0f) * 1.724f;
}

/* ------------------------------------------------------------------ */

static McLayer conv(int units, int k, int stride, int pad, McAct act)
{
    McLayer l = {0};
    l.kind = MC_CONV; l.units = units; l.k = k; l.stride = stride;
    l.pad = pad; l.act = act;
    return l;
}

static McLayer dense(int units, McAct act)
{
    McLayer l = {0};
    l.kind = MC_DENSE; l.units = units; l.act = act;
    return l;
}

static McLayer maxpool(int k)
{
    McLayer l = {0};
    l.kind = MC_MAXPOOL; l.k = k;
    return l;
}

int mc_model_build(McModel *m, const char *preset, int h, int w, int c,
                   int n_classes)
{
    memset(m, 0, sizeof(*m));
    m->in_h = h; m->in_w = w; m->in_c = c; m->n_classes = n_classes;
    int n = 0;
    McLayer *L = m->layers;

    if (strcmp(preset, "reference_cnn") == 0) {
        /* The surveyed trainer's exact topology (SURVEY.md 2.10). */
        L[n++] = conv(16, 3, 2, 1, MC_ACT_RELU);
        L[n++] = conv(32, 3, 2, 1, MC_ACT_RELU);
        L[n++] = dense(200, MC_ACT_TANH);
        L[n++] = dense(200, MC_ACT_TANH);
        L[n++] = dense(n_classes, MC_ACT_NONE);
    } else if (strcmp(preset, "lenet5_relu") == 0) {
        L[n++] = conv(32, 5, 1, 2, MC_ACT_RELU);
        L[n++] = maxpool(2);
        L[n++] = conv(64, 5, 1, 0, MC_ACT_RELU);
        L[n++] = maxpool(2);
        L[n++] = dense(256, MC_ACT_RELU);
        L[n++] = dense(128, MC_ACT_RELU);
        L[n++] = dense(n_classes, MC_ACT_NONE);
    } else {
        fprintf(stderr, "mct: unknown model preset '%s'\n", preset);
        return -1;
    }
    m->n_layers = n;

    /* Derive geometry and arena offsets. */
    size_t off = 0;
    int ih = h, iw = w, ic = c;
    for (int i = 0; i < n; i++) {
        McLayer *l = &L[i];
        l->ih = ih; l->iw = iw; l->ic = ic;
        switch (l->kind) {
        case MC_CONV:
            l->oh = (ih + 2 * l->pad - l->k) / l->stride + 1;
            l->ow = (iw + 2 * l->pad - l->k) / l->stride + 1;
            l->oc = l->units;
            l->nw = (size_t)l->k * l->k * ic * l->oc;
            l->nb = l->oc;
            break;
        case MC_DENSE:
            l->ic = ih * iw * ic;      /* reads the previous output flat */
            l->ih = l->iw = 1;
            l->oh = l->ow = 1;
            l->oc = l->units;
            l->nw = (size_t)l->ic * l->oc;
            l->nb = l->oc;
            break;
        case MC_MAXPOOL:
            l->oh = ih / l->k; l->ow = iw / l->k; l->oc = ic;
            l->nw = l->nb = 0;
            break;
        }
        l->w_off = off; off += l->nw;
        l->b_off = off; off += l->nb;
        ih = l->oh; iw = l->ow; ic = l->oc;
    }
    for (int i = 0; i < n; i++) {
        if (L[i].oc > 4096) {   /* ops.c stack accumulators (MC_MAX_WIDTH) */
            fprintf(stderr, "mct: layer %d width %d exceeds 4096\n", i, L[i].oc);
            return -1;
        }
    }
    m->n_params = off;
    m->params = calloc(off, sizeof(float));
    m->grads = calloc(off, sizeof(float));
    if (!m->params || !m->grads)
        return -1;
    return 0;
}

void mc_model_init_params(McModel *m, uint64_t seed)
{
    /* Weights ~ IrwinHall * 0.1, biases zero — the init scheme documented
     * for the surveyed trainer (SURVEY.md 2.2/2.10), drawn from this
     * driver's own RNG stream. One stream, layer-major: identical across
     * any number of workers by construction. */
    McRng rng;
    mc_rng_seed(&rng, seed);
    for (int i = 0; i < m->n_layers; i++) {
        McLayer *l = &m->layers[i];
        for (size_t j = 0; j < l->nw; j++)
            m->params[l->w_off + j] = 0.1f * mc_rng_irwin_hall(&rng);
        /* biases stay zero (calloc) */
    }
}

void mc_model_free(McModel *m)
{
    free(m->params);
    free(m->grads);
    memset(m, 0, sizeof(*m));
}
