/* tpu_abi.h — the stable C ABI between the native driver and the
 * JAX/TPU runtime (SURVEY.md §7 stage 6: a thin 5-function boundary so
 * the Python path never depends on the C driver and vice versa).
 *
 * Implemented by tpu_abi.c via embedded CPython calling
 * mpi_cuda_cnn_tpu.runtime_abi. All functions return 0 on success.
 */
#ifndef MCT_TPU_ABI_H
#define MCT_TPU_ABI_H

#ifdef __cplusplus
extern "C" {
#endif

/* Start the runtime and build model+dataset+trainer from a JSON config
 * (same schema as utils/config.py::Config). */
int mct_tpu_init(const char *config_json);

/* Run one training epoch; writes a JSON metrics line into buf. */
int mct_tpu_train_epoch(char *buf, int buflen);

/* Evaluate; writes {"ntests":N,"ncorrect":M} into buf. */
int mct_tpu_eval(char *buf, int buflen);

/* Checkpoint save/load. */
int mct_tpu_save(const char *path);
int mct_tpu_load(const char *path);

/* LM family (the long-context transformer, train/lm_trainer.py):
 * lm_init takes an LMConfig JSON (utils/config.py::LMConfig); lm_train
 * runs the configured steps + eval and writes the result JSON
 * ({"steps_run":..,"final_loss":..,"eval_ppl":..,"tokens_per_s":..})
 * into buf. Uses the same embedded runtime as the CNN entry points. */
int mct_tpu_lm_init(const char *config_json);
int mct_tpu_lm_train(char *buf, int buflen);

/* Tear down the embedded runtime. */
int mct_tpu_shutdown(void);

#ifdef __cplusplus
}
#endif

#endif /* MCT_TPU_ABI_H */
