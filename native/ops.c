/* ops.c — batched f32 NHWC compute kernels for the native CPU path.
 *
 * Semantics mirror the framework's JAX ops (and through them the behavior
 * documented for the reference trainer in SURVEY.md §2.3-2.5): direct
 * convolution with zero padding, dense MACs, relu/tanh, stable softmax
 * with cross-entropy seeding d(logits) = (p - onehot)/N. Layouts and
 * batching are this framework's own (NHWC, minibatch-major).
 */
#include "mct.h"

#include <math.h>
#include <string.h>

/* Forward MACs accumulate in double: this path is the framework's
 * numerical reference, so its forward must be closer to exact than the
 * accelerator's f32/bf16 (sequential f32 over a 1568-wide dense layer
 * already drifts ~1e-2). Widest layer supported on the stack: */
#define MC_MAX_WIDTH 4096

void mc_conv_fwd(const float *x, const float *w, const float *b, float *y,
                 int n, int ih, int iw, int ic, int oh, int ow, int oc,
                 int k, int stride, int pad, McAct act)
{
    for (int s = 0; s < n; s++) {
        const float *xs = x + (size_t)s * ih * iw * ic;
        float *ys = y + (size_t)s * oh * ow * oc;
        for (int oy = 0; oy < oh; oy++)
        for (int ox = 0; ox < ow; ox++) {
            float *yp = ys + ((size_t)oy * ow + ox) * oc;
            double acc[MC_MAX_WIDTH];
            for (int f = 0; f < oc; f++)
                acc[f] = b[f];
            for (int ky = 0; ky < k; ky++) {
                int iy = oy * stride + ky - pad;
                if (iy < 0 || iy >= ih) continue;
                for (int kx = 0; kx < k; kx++) {
                    int ix = ox * stride + kx - pad;
                    if (ix < 0 || ix >= iw) continue;
                    const float *xp = xs + ((size_t)iy * iw + ix) * ic;
                    const float *wp = w + (((size_t)ky * k + kx) * ic) * oc;
                    for (int ci = 0; ci < ic; ci++) {
                        double xv = xp[ci];
                        const float *wc = wp + (size_t)ci * oc;
                        for (int f = 0; f < oc; f++)
                            acc[f] += xv * wc[f];
                    }
                }
            }
            if (act == MC_ACT_RELU)
                for (int f = 0; f < oc; f++)
                    yp[f] = acc[f] > 0.0 ? (float)acc[f] : 0.f;
            else if (act == MC_ACT_TANH)
                for (int f = 0; f < oc; f++)
                    yp[f] = (float)tanh(acc[f]);
            else
                for (int f = 0; f < oc; f++)
                    yp[f] = (float)acc[f];
        }
    }
}

/* gy arrives as d(loss)/d(pre-activation) already (caller folds the
 * activation derivative using the stored activations). */
void mc_conv_bwd(const float *x, const float *w, const float *gy,
                 float *gx, float *gw, float *gb,
                 int n, int ih, int iw, int ic, int oh, int ow, int oc,
                 int k, int stride, int pad)
{
    if (gx)
        memset(gx, 0, sizeof(float) * (size_t)n * ih * iw * ic);
    for (int s = 0; s < n; s++) {
        const float *xs = x + (size_t)s * ih * iw * ic;
        const float *gs = gy + (size_t)s * oh * ow * oc;
        float *gxs = gx ? gx + (size_t)s * ih * iw * ic : NULL;
        for (int oy = 0; oy < oh; oy++)
        for (int ox = 0; ox < ow; ox++) {
            const float *gp = gs + ((size_t)oy * ow + ox) * oc;
            for (int f = 0; f < oc; f++)
                gb[f] += gp[f];
            for (int ky = 0; ky < k; ky++) {
                int iy = oy * stride + ky - pad;
                if (iy < 0 || iy >= ih) continue;
                for (int kx = 0; kx < k; kx++) {
                    int ix = ox * stride + kx - pad;
                    if (ix < 0 || ix >= iw) continue;
                    const float *xp = xs + ((size_t)iy * iw + ix) * ic;
                    float *gxp = gxs ? gxs + ((size_t)iy * iw + ix) * ic : NULL;
                    float *gwp = gw + (((size_t)ky * k + kx) * ic) * oc;
                    const float *wp = w + (((size_t)ky * k + kx) * ic) * oc;
                    for (int ci = 0; ci < ic; ci++) {
                        float xv = xp[ci];
                        float acc = 0.f;
                        float *gwc = gwp + (size_t)ci * oc;
                        const float *wc = wp + (size_t)ci * oc;
                        for (int f = 0; f < oc; f++) {
                            gwc[f] += xv * gp[f];
                            acc += wc[f] * gp[f];
                        }
                        if (gxp)
                            gxp[ci] += acc;
                    }
                }
            }
        }
    }
}

void mc_dense_fwd(const float *x, const float *w, const float *b, float *y,
                  int n, int din, int dout, McAct act)
{
    for (int s = 0; s < n; s++) {
        const float *xs = x + (size_t)s * din;
        float *ys = y + (size_t)s * dout;
        double acc[MC_MAX_WIDTH];
        for (int o = 0; o < dout; o++)
            acc[o] = b[o];
        for (int i = 0; i < din; i++) {
            double xv = xs[i];
            const float *wr = w + (size_t)i * dout;
            for (int o = 0; o < dout; o++)
                acc[o] += xv * wr[o];
        }
        if (act == MC_ACT_RELU)
            for (int o = 0; o < dout; o++)
                ys[o] = acc[o] > 0.0 ? (float)acc[o] : 0.f;
        else if (act == MC_ACT_TANH)
            for (int o = 0; o < dout; o++)
                ys[o] = (float)tanh(acc[o]);
        else
            for (int o = 0; o < dout; o++)
                ys[o] = (float)acc[o];
    }
}

void mc_dense_bwd(const float *x, const float *w, const float *gy,
                  float *gx, float *gw, float *gb,
                  int n, int din, int dout)
{
    if (gx)
        memset(gx, 0, sizeof(float) * (size_t)n * din);
    for (int s = 0; s < n; s++) {
        const float *xs = x + (size_t)s * din;
        const float *gs = gy + (size_t)s * dout;
        float *gxs = gx ? gx + (size_t)s * din : NULL;
        for (int o = 0; o < dout; o++)
            gb[o] += gs[o];
        for (int i = 0; i < din; i++) {
            float xv = xs[i];
            float *gwr = gw + (size_t)i * dout;
            const float *wr = w + (size_t)i * dout;
            float acc = 0.f;
            for (int o = 0; o < dout; o++) {
                gwr[o] += xv * gs[o];
                acc += wr[o] * gs[o];
            }
            if (gxs)
                gxs[i] = acc;
        }
    }
}

/* Non-overlapping max pooling; amax records flat argmax offsets for bwd. */
void mc_maxpool_fwd(const float *x, float *y, int32_t *amax,
                    int n, int ih, int iw, int c, int k)
{
    int oh = ih / k, ow = iw / k;
    for (int s = 0; s < n; s++) {
        const float *xs = x + (size_t)s * ih * iw * c;
        float *ys = y + (size_t)s * oh * ow * c;
        int32_t *as = amax + (size_t)s * oh * ow * c;
        for (int oy = 0; oy < oh; oy++)
        for (int ox = 0; ox < ow; ox++)
        for (int ch = 0; ch < c; ch++) {
            float best = -1e30f;
            int32_t besti = 0;
            for (int ky = 0; ky < k; ky++)
            for (int kx = 0; kx < k; kx++) {
                int32_t off = (int32_t)(((size_t)(oy * k + ky) * iw +
                                         (ox * k + kx)) * c + ch);
                float v = xs[off];
                if (v > best) { best = v; besti = off; }
            }
            size_t oi = ((size_t)oy * ow + ox) * c + ch;
            ys[oi] = best;
            as[oi] = besti;
        }
    }
}

void mc_maxpool_bwd(const int32_t *amax, const float *gy, float *gx,
                    int n, int ih, int iw, int c, int k)
{
    int oh = ih / k, ow = iw / k;
    memset(gx, 0, sizeof(float) * (size_t)n * ih * iw * c);
    for (int s = 0; s < n; s++) {
        const float *gs = gy + (size_t)s * oh * ow * c;
        const int32_t *as = amax + (size_t)s * oh * ow * c;
        float *gxs = gx + (size_t)s * ih * iw * c;
        size_t total = (size_t)oh * ow * c;
        for (size_t i = 0; i < total; i++)
            gxs[as[i]] += gs[i];
    }
}

/* Stable softmax over logits; returns mean CE loss and writes
 * d(logits) = (p - onehot)/n into glogits. */
float mc_softmax_ce(const float *logits, const uint8_t *labels,
                    float *glogits, float *probs_out, int n, int nc)
{
    float loss = 0.f;
    for (int s = 0; s < n; s++) {
        const float *ls = logits + (size_t)s * nc;
        float *gs = glogits + (size_t)s * nc;
        float mx = ls[0];
        for (int j = 1; j < nc; j++)
            if (ls[j] > mx) mx = ls[j];
        float z = 0.f;
        for (int j = 0; j < nc; j++)
            z += expf(ls[j] - mx);
        for (int j = 0; j < nc; j++) {
            float p = expf(ls[j] - mx) / z;
            if (probs_out)
                probs_out[(size_t)s * nc + j] = p;
            gs[j] = (p - (j == labels[s] ? 1.f : 0.f)) / (float)n;
            if (j == labels[s])
                loss += -logf(p > 1e-30f ? p : 1e-30f);
        }
    }
    return loss / (float)n;
}

/* Fold the activation derivative into gy, using stored activations y:
 * relu: gy *= (y > 0); tanh: gy *= (1 - y^2) — the activation-value forms
 * the framework shares with the surveyed reference (SURVEY.md 2.2). */
void mc_act_bwd(const float *y, float *gy, size_t count, McAct act)
{
    if (act == MC_ACT_RELU) {
        for (size_t i = 0; i < count; i++)
            if (y[i] <= 0.f) gy[i] = 0.f;
    } else if (act == MC_ACT_TANH) {
        for (size_t i = 0; i < count; i++)
            gy[i] *= 1.f - y[i] * y[i];
    }
}
