/* tpu_abi.c — embedded-CPython implementation of the TPU ABI.
 *
 * The TPU twin of the reference's CUDA host wrapper role
 * (forward_convolution_layer, CUDAcnn.cu:198-218) at the *runtime* level:
 * instead of per-call cudaMalloc/H2D/D2H round-trips, the device state
 * lives inside the JAX runtime for the whole run and the C driver only
 * exchanges small JSON strings across the boundary.
 */
#include "tpu_abi.h"

#include <Python.h>
#include <stdio.h>

static PyObject *g_mod;    /* mpi_cuda_cnn_tpu.runtime_abi */

static int call_str_ret(const char *fn, const char *arg, char *buf, int buflen)
{
    if (!g_mod) {
        fprintf(stderr, "mct: TPU runtime not initialized\n");
        return -1;
    }
    PyObject *r = arg
        ? PyObject_CallMethod(g_mod, fn, "s", arg)
        : PyObject_CallMethod(g_mod, fn, NULL);
    if (!r) {
        PyErr_Print();
        return -1;
    }
    if (buf && buflen > 0) {
        const char *s = PyUnicode_Check(r) ? PyUnicode_AsUTF8(r) : "";
        snprintf(buf, (size_t)buflen, "%s", s ? s : "");
    }
    Py_DECREF(r);
    return 0;
}

/* Bring up the embedded interpreter + runtime module once; shared by the
 * CNN and LM init entry points. */
static int ensure_runtime(void)
{
    if (g_mod)
        return 0;
    if (!Py_IsInitialized()) {
        /* Honor PYTHONPATH etc. so the venv's site-packages resolve; the
         * build target and README document the expected environment. */
        Py_InitializeEx(0);
    }
    PyObject *name = PyUnicode_FromString("mpi_cuda_cnn_tpu.runtime_abi");
    g_mod = PyImport_Import(name);
    Py_DECREF(name);
    if (!g_mod) {
        PyErr_Print();
        fprintf(stderr,
                "mct: cannot import mpi_cuda_cnn_tpu.runtime_abi "
                "(set PYTHONPATH to the repo root)\n");
        return -1;
    }
    return 0;
}

int mct_tpu_init(const char *config_json)
{
    if (ensure_runtime())
        return -1;
    return call_str_ret("init", config_json, NULL, 0);
}

int mct_tpu_train_epoch(char *buf, int buflen)
{
    return call_str_ret("train_epoch", NULL, buf, buflen);
}

int mct_tpu_eval(char *buf, int buflen)
{
    return call_str_ret("evaluate", NULL, buf, buflen);
}

int mct_tpu_save(const char *path)
{
    return call_str_ret("save", path, NULL, 0);
}

int mct_tpu_load(const char *path)
{
    return call_str_ret("load", path, NULL, 0);
}

int mct_tpu_lm_init(const char *config_json)
{
    if (ensure_runtime())
        return -1;
    return call_str_ret("lm_init", config_json, NULL, 0);
}

int mct_tpu_lm_train(char *buf, int buflen)
{
    return call_str_ret("lm_train", NULL, buf, buflen);
}

int mct_tpu_shutdown(void)
{
    Py_XDECREF(g_mod);
    g_mod = NULL;
    if (Py_IsInitialized())
        Py_Finalize();
    return 0;
}
