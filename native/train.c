/* train.c — minibatch training loop, eval, golden-tensor dump.
 *
 * Semantics: epoch permutation over the training set, batch-mean
 * softmax-CE gradient, plain SGD — the batched equivalence of the
 * surveyed per-sample/accumulate-32 schedule (SURVEY.md §7 hard-part (a)).
 * Progress lines and the final "ntests=, ncorrect=" line keep the
 * reference's observable output format (SURVEY.md §5.5).
 */
#define _POSIX_C_SOURCE 199309L   /* clock_gettime under -std=c11 */

#include "mct.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

/* forward decls from ops.c */
void mc_conv_fwd(const float *, const float *, const float *, float *,
                 int, int, int, int, int, int, int, int, int, int, McAct);
void mc_conv_bwd(const float *, const float *, const float *,
                 float *, float *, float *,
                 int, int, int, int, int, int, int, int, int, int);
void mc_dense_fwd(const float *, const float *, const float *, float *,
                  int, int, int, McAct);
void mc_dense_bwd(const float *, const float *, const float *,
                  float *, float *, float *, int, int, int);
void mc_maxpool_fwd(const float *, float *, int32_t *, int, int, int, int, int);
void mc_maxpool_bwd(const int32_t *, const float *, float *,
                    int, int, int, int, int);
float mc_softmax_ce(const float *, const uint8_t *, float *, float *, int, int);
void mc_act_bwd(const float *, float *, size_t, McAct);

typedef struct {
    float *acts[MC_MAX_LAYERS + 1];  /* acts[0] = input batch */
    int32_t *amax[MC_MAX_LAYERS];
    float *ga, *gb_buf;              /* ping-pong activation grads */
    size_t max_act;
    int batch;
} McWork;

static size_t layer_out_count(const McLayer *l, int n)
{
    return (size_t)n * l->oh * l->ow * l->oc;
}

static int work_alloc(McWork *w, const McModel *m, int batch)
{
    memset(w, 0, sizeof(*w));
    w->batch = batch;
    size_t in_count = (size_t)batch * m->in_h * m->in_w * m->in_c;
    w->acts[0] = malloc(in_count * sizeof(float));
    w->max_act = in_count;
    for (int i = 0; i < m->n_layers; i++) {
        size_t c = layer_out_count(&m->layers[i], batch);
        w->acts[i + 1] = malloc(c * sizeof(float));
        if (m->layers[i].kind == MC_MAXPOOL)
            w->amax[i] = malloc(c * sizeof(int32_t));
        if (c > w->max_act)
            w->max_act = c;
        if (!w->acts[i + 1])
            return -1;
    }
    w->ga = malloc(w->max_act * sizeof(float));
    w->gb_buf = malloc(w->max_act * sizeof(float));
    return (w->acts[0] && w->ga && w->gb_buf) ? 0 : -1;
}

static void work_free(McWork *w, const McModel *m)
{
    for (int i = 0; i <= m->n_layers; i++)
        free(w->acts[i]);
    for (int i = 0; i < m->n_layers; i++)
        free(w->amax[i]);
    free(w->ga);
    free(w->gb_buf);
}

static void forward(const McModel *m, McWork *w, int n)
{
    for (int i = 0; i < m->n_layers; i++) {
        const McLayer *l = &m->layers[i];
        const float *x = w->acts[i];
        float *y = w->acts[i + 1];
        switch (l->kind) {
        case MC_CONV:
            mc_conv_fwd(x, m->params + l->w_off, m->params + l->b_off, y,
                        n, l->ih, l->iw, l->ic, l->oh, l->ow, l->oc,
                        l->k, l->stride, l->pad, l->act);
            break;
        case MC_DENSE:
            mc_dense_fwd(x, m->params + l->w_off, m->params + l->b_off, y,
                         n, l->ic, l->oc, l->act);
            break;
        case MC_MAXPOOL:
            mc_maxpool_fwd(x, y, w->amax[i], n, l->ih, l->iw, l->ic, l->k);
            break;
        }
    }
}

/* w->ga must hold d(loss)/d(logits) on entry; fills m->grads. */
static void backward(const McModel *m, McWork *w, int n)
{
    float *gy = w->ga, *gx = w->gb_buf;
    for (int i = m->n_layers - 1; i >= 0; i--) {
        const McLayer *l = &m->layers[i];
        const float *x = w->acts[i];
        const float *y = w->acts[i + 1];
        float *gx_out = i > 0 ? gx : NULL;
        switch (l->kind) {
        case MC_CONV:
            mc_act_bwd(y, gy, layer_out_count(l, n), l->act);
            mc_conv_bwd(x, m->params + l->w_off, gy, gx_out,
                        m->grads + l->w_off, m->grads + l->b_off,
                        n, l->ih, l->iw, l->ic, l->oh, l->ow, l->oc,
                        l->k, l->stride, l->pad);
            break;
        case MC_DENSE:
            mc_act_bwd(y, gy, layer_out_count(l, n), l->act);
            mc_dense_bwd(x, m->params + l->w_off, gy, gx_out,
                         m->grads + l->w_off, m->grads + l->b_off,
                         n, l->ic, l->oc);
            break;
        case MC_MAXPOOL:
            if (gx_out)
                mc_maxpool_bwd(w->amax[i], gy, gx_out,
                               n, l->ih, l->iw, l->ic, l->k);
            break;
        }
        float *t = gy; gy = gx; gx = t;  /* ping-pong */
    }
}

static void normalize_batch(const McDataset *ds, const uint8_t *images,
                            const int *order, int start, int n, float *out)
{
    size_t px = (size_t)ds->h * ds->w * ds->c;
    for (int s = 0; s < n; s++) {
        const uint8_t *src = images + (size_t)order[start + s] * px;
        float *dst = out + (size_t)s * px;
        for (size_t j = 0; j < px; j++)
            dst[j] = (float)src[j] / 255.0f;
    }
}

static void sgd_step(McModel *m, float lr)
{
    for (size_t j = 0; j < m->n_params; j++) {
        m->params[j] -= lr * m->grads[j];
        m->grads[j] = 0.f;
    }
}

static int dump_f32(const char *dir, const char *name, const float *p,
                    size_t count)
{
    char path[1024];
    snprintf(path, sizeof path, "%s/%s", dir, name);
    FILE *f = fopen(path, "wb");
    if (!f) return -1;
    size_t wr = fwrite(p, sizeof(float), count, f);
    fclose(f);
    return wr == count ? 0 : -1;
}

static int golden_dump(McModel *m, const McDataset *ds, const McTrainCfg *cfg,
                       McWork *w)
{
    /* One deterministic batch (first cfg->batch samples, in order):
     * dump params, inputs, labels, logits, loss, grads — the parity
     * fixtures tests/test_golden_c.py replays through the JAX ops. */
    const char *dir = cfg->golden_dir;
    int n = cfg->batch <= ds->n_train ? cfg->batch : ds->n_train;
    int *order = malloc(sizeof(int) * n);
    for (int i = 0; i < n; i++) order[i] = i;
    normalize_batch(ds, ds->train_images, order, 0, n, w->acts[0]);
    forward(m, w, n);
    const McLayer *last = &m->layers[m->n_layers - 1];
    float loss = mc_softmax_ce(w->acts[m->n_layers], ds->train_labels,
                               w->ga, NULL, n, last->oc);
    backward(m, w, n);

    char path[1024];
    int rc = 0;
    /* Per-layer activations, for layerwise parity checks/debugging. */
    for (int i = 0; i < m->n_layers; i++) {
        char nm[64];
        snprintf(nm, sizeof nm, "act_%d.f32", i);
        rc |= dump_f32(dir, nm, w->acts[i + 1],
                       layer_out_count(&m->layers[i], n));
    }
    rc |= dump_f32(dir, "params.f32", m->params, m->n_params);
    rc |= dump_f32(dir, "batch_x.f32", w->acts[0],
                   (size_t)n * ds->h * ds->w * ds->c);
    rc |= dump_f32(dir, "logits.f32", w->acts[m->n_layers],
                   (size_t)n * last->oc);
    rc |= dump_f32(dir, "grads.f32", m->grads, m->n_params);
    snprintf(path, sizeof path, "%s/batch_y.u8", dir);
    FILE *f = fopen(path, "wb");
    if (f) { fwrite(ds->train_labels, 1, n, f); fclose(f); } else rc = -1;
    snprintf(path, sizeof path, "%s/meta.txt", dir);
    f = fopen(path, "w");
    if (f) {
        fprintf(f, "loss %.9g\nn_params %zu\nbatch %d\nh %d\nw %d\nc %d\n",
                (double)loss, m->n_params, n, ds->h, ds->w, ds->c);
        fclose(f);
    } else rc = -1;
    free(order);
    return rc;
}

int mc_eval(const McModel *m, const McDataset *ds, int *ncorrect)
{
    enum { EB = 256 };
    McWork w;
    if (work_alloc(&w, m, EB))
        return -1;
    const McLayer *last = &m->layers[m->n_layers - 1];
    int order[EB];
    int good = 0;
    for (int start = 0; start < ds->n_test; start += EB) {
        int n = ds->n_test - start < EB ? ds->n_test - start : EB;
        for (int i = 0; i < n; i++) order[i] = start + i;
        normalize_batch(ds, ds->test_images, order, 0, n, w.acts[0]);
        forward(m, &w, n);
        const float *logits = w.acts[m->n_layers];
        for (int s = 0; s < n; s++) {
            const float *ls = logits + (size_t)s * last->oc;
            int arg = 0;
            for (int j = 1; j < last->oc; j++)
                if (ls[j] > ls[arg]) arg = j;
            if (arg == ds->test_labels[start + s])
                good++;
        }
    }
    work_free(&w, m);
    *ncorrect = good;
    return 0;
}

int mc_train(McModel *m, const McDataset *ds, const McTrainCfg *cfg,
             McResult *out)
{
    if (cfg->batch < 1 || cfg->batch > ds->n_train) {
        fprintf(stderr, "mct: batch %d invalid for %d train samples\n",
                cfg->batch, ds->n_train);
        return -1;
    }
    McWork w;
    if (work_alloc(&w, m, cfg->batch))
        return -1;

    if (cfg->golden_dir) {
        int rc = golden_dump(m, ds, cfg, &w);
        work_free(&w, m);
        return rc;
    }

    int *order = malloc(sizeof(int) * ds->n_train);
    for (int i = 0; i < ds->n_train; i++)
        order[i] = i;
    McRng rng;
    mc_rng_seed(&rng, cfg->seed ^ 0xA5A5A5A5u);
    const McLayer *last = &m->layers[m->n_layers - 1];
    uint8_t *batch_labels = malloc(cfg->batch);

    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);

    int nbatches = ds->n_train / cfg->batch;
    for (int epoch = 0; epoch < cfg->epochs; epoch++) {
        /* Fisher-Yates epoch permutation */
        for (int i = ds->n_train - 1; i > 0; i--) {
            int j = (int)(mc_rng_next(&rng) % (uint64_t)(i + 1));
            int t = order[i]; order[i] = order[j]; order[j] = t;
        }
        double running = 0.0;
        for (int b = 0; b < nbatches; b++) {
            normalize_batch(ds, ds->train_images, order, b * cfg->batch,
                            cfg->batch, w.acts[0]);
            for (int s = 0; s < cfg->batch; s++)
                batch_labels[s] = ds->train_labels[order[b * cfg->batch + s]];
            forward(m, &w, cfg->batch);
            running += mc_softmax_ce(w.acts[m->n_layers], batch_labels,
                                     w.ga, NULL, cfg->batch, last->oc);
            backward(m, &w, cfg->batch);
            sgd_step(m, cfg->lr);
            if (cfg->log_every && (b + 1) % cfg->log_every == 0) {
                fprintf(stderr, "epoch %d batch %d/%d loss %.5f\n",
                        epoch, b + 1, nbatches, running / (b + 1));
            }
        }
        fprintf(stderr, "epoch %d done, mean loss %.5f\n",
                epoch, running / nbatches);
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);

    int good = 0;
    if (mc_eval(m, ds, &good))
        return -1;
    /* The reference's one benchmark line (SURVEY.md §3.4). */
    fprintf(stderr, "ntests=%d, ncorrect=%d\n", ds->n_test, good);

    if (out) {
        out->ntests = ds->n_test;
        out->ncorrect = good;
        out->train_seconds = (double)(t1.tv_sec - t0.tv_sec) +
                             1e-9 * (double)(t1.tv_nsec - t0.tv_nsec);
    }
    free(order);
    free(batch_labels);
    work_free(&w, m);
    return 0;
}
