/* driver.c — the native CLI.
 *
 * Keeps the surveyed 4-positional-IDX-path contract and exit codes
 * (100 bad usage, 111 data errors — SURVEY.md 2.16) and adds the
 * north star's --device switch (BASELINE.json): the CPU path is the
 * in-process f32 trainer (ops.c/model.c/train.c, the numerical
 * reference), the TPU path dispatches through the embedded JAX runtime
 * (tpu_abi.c).
 *
 *   mctpu train-img train-lab test-img test-lab [options]
 *     --device=cpu|tpu|jax|jax-cpu  (default cpu; jax = embedded runtime
 *                           on whatever backend it finds, jax-cpu = the
 *                           same pinned to CPU for accelerator-free tests)
 *     --model=NAME          (default reference_cnn)
 *     --epochs=N --lr=F --batch=N --seed=N --log-every=N
 *     --golden-dir=DIR      (cpu only: dump parity fixtures and exit)
 *     --save=DIR --load=DIR (embedded runtime only: checkpoint round-trip)
 *
 *   mctpu lm [options]     — the LM family through the same embedded
 *     runtime (mct_tpu_lm_init/lm_train -> train/lm_trainer.py):
 *     --device=tpu|jax|jax-cpu --corpus=STR --dim=N --depth=N --heads=N
 *     --kv-heads=N --pos=learned|rope --moe-experts=N --moe-top-k=N
 *     --ce-chunk=N --grad-accum=N --grad-clip=F
 *     --seq-len=N --steps=N --batch=N --lr=F --seed=N
 *     --mesh-shape=STR --compute-dtype=float32|bfloat16
 */
#include "mct.h"
#include "tpu_abi.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef struct {
    const char *paths[4];
    const char *device, *model, *golden_dir, *save_dir, *load_dir;
    McTrainCfg tcfg;
} Args;

static int parse_args(int argc, char **argv, Args *a)
{
    memset(a, 0, sizeof(*a));
    a->device = "cpu";
    a->model = "reference_cnn";
    a->tcfg.lr = 0.1f;       /* the surveyed defaults (SURVEY.md §5.6) */
    a->tcfg.epochs = 10;
    a->tcfg.batch = 32;
    a->tcfg.seed = 0;
    a->tcfg.log_every = 200;

    int npos = 0;
    for (int i = 1; i < argc; i++) {
        const char *s = argv[i];
        if (strncmp(s, "--device=", 9) == 0) a->device = s + 9;
        else if (strncmp(s, "--model=", 8) == 0) a->model = s + 8;
        else if (strncmp(s, "--epochs=", 9) == 0) a->tcfg.epochs = atoi(s + 9);
        else if (strncmp(s, "--lr=", 5) == 0) a->tcfg.lr = (float)atof(s + 5);
        else if (strncmp(s, "--batch=", 8) == 0) a->tcfg.batch = atoi(s + 8);
        else if (strncmp(s, "--seed=", 7) == 0) a->tcfg.seed = (uint64_t)atoll(s + 7);
        else if (strncmp(s, "--log-every=", 12) == 0) a->tcfg.log_every = atoi(s + 12);
        else if (strncmp(s, "--golden-dir=", 13) == 0) a->golden_dir = s + 13;
        else if (strncmp(s, "--save=", 7) == 0) a->save_dir = s + 7;
        else if (strncmp(s, "--load=", 7) == 0) a->load_dir = s + 7;
        else if (s[0] == '-') {
            fprintf(stderr, "mct: unknown option %s\n", s);
            return -1;
        } else if (npos < 4) {
            a->paths[npos++] = s;
        } else {
            return -1;
        }
    }
    if (a->tcfg.batch < 1 || a->tcfg.epochs < 0 || a->tcfg.lr <= 0.f) {
        fprintf(stderr, "mct: invalid --batch/--epochs/--lr\n");
        return -1;
    }
    return npos == 4 ? 0 : -1;
}

/* Append src to dst as a JSON string body (escaping '\' and '"').
 * Returns 0, or -1 when dst would overflow. */
static int json_escape_into(char *dst, size_t cap, size_t *pos, const char *src)
{
    for (; *src; src++) {
        if (*pos + 3 >= cap)
            return -1;
        if (*src == '"' || *src == '\\')
            dst[(*pos)++] = '\\';
        dst[(*pos)++] = *src;
    }
    dst[*pos] = '\0';
    return 0;
}

/* Append `,"key":"<escaped val>"` (no leading comma when first) to the
 * JSON being built in dst. Returns 0, or -1 on overflow. */
static int append_json_str(char *dst, size_t cap, size_t *pos,
                           const char *key, const char *val, int first)
{
    int nw = snprintf(dst + *pos, cap - *pos, "%s\"%s\":\"",
                      first ? "" : ",", key);
    if (nw < 0 || *pos + (size_t)nw >= cap)
        return -1;
    *pos += (size_t)nw;
    if (json_escape_into(dst, cap, pos, val))
        return -1;
    if (*pos + 2 >= cap)
        return -1;
    dst[(*pos)++] = '"';
    dst[*pos] = '\0';
    return 0;
}

static int run_cpu(const Args *a)
{
    McDataset ds;
    int rc = mc_dataset_load(&ds, a->paths);
    if (rc)
        return rc;

    McModel m;
    if (mc_model_build(&m, a->model, ds.h, ds.w, ds.c, ds.n_classes)) {
        mc_dataset_free(&ds);
        return 2;
    }
    mc_model_init_params(&m, a->tcfg.seed);
    fprintf(stderr, "mct: model=%s params=%zu device=cpu\n",
            a->model, m.n_params);

    McTrainCfg cfg = a->tcfg;
    cfg.golden_dir = a->golden_dir;
    McResult res = {0};
    rc = mc_train(&m, &ds, &cfg, &res);
    if (rc == 0 && !a->golden_dir)
        fprintf(stderr, "mct: train %.2fs, accuracy %.4f\n",
                res.train_seconds,
                res.ntests ? (double)res.ncorrect / res.ntests : 0.0);

    mc_model_free(&m);
    mc_dataset_free(&ds);
    return rc ? 1 : 0;
}

static int run_tpu(const Args *a)
{
    char cfg[4096], buf[1024];
    /* --device=tpu demands an accelerator; --device=jax takes whatever
     * backend the embedded runtime finds; --device=jax-cpu pins the
     * embedded runtime to CPU (exercises the full C<->JAX boundary
     * deterministically, with no accelerator required). */
    const char *dev = strcmp(a->device, "tpu") == 0 ? "tpu"
                    : strcmp(a->device, "jax-cpu") == 0 ? "cpu"
                    : "auto";
    /* Build the JSON config for utils/config.py::Config, escaping paths
     * and checking for truncation. */
    size_t pos = 0;
    const char *keys[4] = {"train_images", "train_labels",
                           "test_images", "test_labels"};
    pos += (size_t)snprintf(cfg + pos, sizeof cfg - pos, "{\"dataset\":\"idx\"");
    for (int i = 0; i < 4; i++)
        if (append_json_str(cfg, sizeof cfg, &pos, keys[i], a->paths[i], 0))
            goto toolong;
    {
        int nw = snprintf(cfg + pos, sizeof cfg - pos,
                          ",\"model\":\"%s\",\"epochs\":%d,\"lr\":%g,"
                          "\"batch_size\":%d,\"seed\":%llu,\"device\":\"%s\","
                          "\"log_every\":1000000000}",
                          a->model, a->tcfg.epochs, (double)a->tcfg.lr,
                          a->tcfg.batch, (unsigned long long)a->tcfg.seed, dev);
        if (nw < 0 || pos + (size_t)nw >= sizeof cfg)
            goto toolong;
    }

    if (mct_tpu_init(cfg))
        return 1;
    if (a->load_dir && mct_tpu_load(a->load_dir))
        return 1;
    for (int e = 0; e < a->tcfg.epochs; e++) {
        if (mct_tpu_train_epoch(buf, sizeof buf))
            return 1;
        fprintf(stderr, "mct[tpu]: %s\n", buf);
    }
    if (mct_tpu_eval(buf, sizeof buf))
        return 1;
    fprintf(stderr, "mct[tpu]: %s\n", buf);
    if (a->save_dir && mct_tpu_save(a->save_dir))
        return 1;
    mct_tpu_shutdown();
    return 0;
toolong:
    fprintf(stderr, "mct: config too long (paths exceed %zu bytes)\n",
            sizeof cfg);
    return 100;
}

static int run_lm(int argc, char **argv)
{
    /* Defaults mirror utils/config.py::LMConfig where the C driver sets
     * them at all; everything else falls to the dataclass defaults. */
    const char *device = "jax-cpu", *corpus = "synthetic";
    const char *mesh = "data", *dtype = "float32", *posenc = "learned";
    int dim = 64, depth = 2, heads = 4, seq = 128, steps = 50, batch = 4;
    int kv_heads = 0, moe_experts = 0, moe_top_k = 1, ce_chunk = 0;
    int grad_accum = 1;
    double lr = 3e-4, grad_clip = 0.0;
    long long seed = 0;

    for (int i = 2; i < argc; i++) {
        const char *s = argv[i];
        if (strncmp(s, "--device=", 9) == 0) device = s + 9;
        else if (strncmp(s, "--corpus=", 9) == 0) corpus = s + 9;
        else if (strncmp(s, "--mesh-shape=", 13) == 0) mesh = s + 13;
        else if (strncmp(s, "--compute-dtype=", 16) == 0) dtype = s + 16;
        else if (strncmp(s, "--pos=", 6) == 0) posenc = s + 6;
        else if (strncmp(s, "--dim=", 6) == 0) dim = atoi(s + 6);
        else if (strncmp(s, "--depth=", 8) == 0) depth = atoi(s + 8);
        else if (strncmp(s, "--heads=", 8) == 0) heads = atoi(s + 8);
        else if (strncmp(s, "--kv-heads=", 11) == 0) kv_heads = atoi(s + 11);
        else if (strncmp(s, "--moe-experts=", 14) == 0)
            moe_experts = atoi(s + 14);
        else if (strncmp(s, "--moe-top-k=", 12) == 0)
            moe_top_k = atoi(s + 12);
        else if (strncmp(s, "--ce-chunk=", 11) == 0) ce_chunk = atoi(s + 11);
        else if (strncmp(s, "--seq-len=", 10) == 0) seq = atoi(s + 10);
        else if (strncmp(s, "--steps=", 8) == 0) steps = atoi(s + 8);
        else if (strncmp(s, "--batch=", 8) == 0) batch = atoi(s + 8);
        else if (strncmp(s, "--lr=", 5) == 0) lr = atof(s + 5);
        else if (strncmp(s, "--grad-accum=", 13) == 0)
            grad_accum = atoi(s + 13);
        else if (strncmp(s, "--grad-clip=", 12) == 0) {
            /* strtod + end-pointer, not atof: 0 is a LEGAL clip value
             * (disabled), so a malformed number silently parsing to 0
             * would turn a typo into "no clipping" — the one numeric
             * flag where garbage cannot be caught by a range check. */
            char *end;
            grad_clip = strtod(s + 12, &end);
            if (end == s + 12 || *end != '\0') {
                fprintf(stderr, "mct: bad --grad-clip value %s\n", s + 12);
                return 100;
            }
        }
        else if (strncmp(s, "--seed=", 7) == 0) seed = atoll(s + 7);
        else {
            fprintf(stderr, "mct: unknown lm option %s\n", s);
            return 100;
        }
    }
    /* !(x >= 0) rather than x < 0: NaN fails BOTH orderings, and a
     * non-finite value would reach snprintf's %g as "nan"/"inf" — not
     * JSON — surfacing as an opaque parse error instead of exit 100. */
    if (dim < 1 || depth < 1 || heads < 1 || seq < 2 || steps < 1 ||
        batch < 1 || !(lr > 0.0) || !isfinite(lr) || kv_heads < 0 ||
        moe_experts < 0 || moe_top_k < 1 || ce_chunk < 0 ||
        grad_accum < 1 || !(grad_clip >= 0.0) || !isfinite(grad_clip)) {
        fprintf(stderr, "mct: invalid lm hyperparameters\n");
        return 100;
    }
    const char *dev = strcmp(device, "jax-cpu") == 0 ? "cpu"
                    : strcmp(device, "tpu") == 0 ? "tpu" : "auto";

    /* Every user string goes through append_json_str — a quote or
     * backslash in any of them must not be able to break out of its
     * JSON value (no key injection past the C-side validation). */
    char cfg[2048], buf[1024];
    size_t pos = 0;
    const char *svals[4] = {corpus, mesh, dtype, posenc};
    const char *skeys[4] = {"corpus", "mesh_shape", "compute_dtype", "pos"};
    pos += (size_t)snprintf(cfg + pos, sizeof cfg - pos, "{");
    for (int i = 0; i < 4; i++)
        if (append_json_str(cfg, sizeof cfg, &pos, skeys[i], svals[i],
                            i == 0))
            goto toolong;
    {
        int nw = snprintf(cfg + pos, sizeof cfg - pos,
            ",\"dim\":%d,\"depth\":%d,\"heads\":%d,\"kv_heads\":%d,"
            "\"moe_experts\":%d,\"moe_top_k\":%d,\"ce_chunk\":%d,"
            "\"grad_accum\":%d,\"grad_clip\":%g,\"seq_len\":%d,"
            "\"steps\":%d,\"batch_size\":%d,\"lr\":%g,\"seed\":%lld,"
            "\"device\":\"%s\",\"log_every\":0,\"lr_schedule\":"
            "\"constant\",\"warmup_steps\":0}",
            dim, depth, heads, kv_heads, moe_experts, moe_top_k, ce_chunk,
            grad_accum, grad_clip, seq, steps, batch, lr, seed, dev);
        if (nw < 0 || pos + (size_t)nw >= sizeof cfg)
            goto toolong;
    }
    if (mct_tpu_lm_init(cfg))
        return 1;
    if (mct_tpu_lm_train(buf, sizeof buf))
        return 1;
    fprintf(stderr, "mct[lm]: %s\n", buf);
    mct_tpu_shutdown();
    return 0;
toolong:
    fprintf(stderr, "mct: lm config too long\n");
    return 100;
}

int main(int argc, char **argv)
{
    if (argc > 1 && strcmp(argv[1], "lm") == 0)
        return run_lm(argc, argv);
    Args a;
    if (parse_args(argc, argv, &a)) {
        fprintf(stderr,
                "usage: mctpu train-images train-labels test-images "
                "test-labels [--device=cpu|tpu|jax|jax-cpu] [--model=NAME] "
                "[--epochs=N] [--lr=F] [--batch=N] [--seed=N] "
                "[--save=DIR] [--load=DIR]\n");
        return 100;   /* the surveyed bad-usage exit code */
    }
    if (strcmp(a.device, "tpu") == 0 || strcmp(a.device, "jax") == 0 ||
        strcmp(a.device, "jax-cpu") == 0)
        return run_tpu(&a);
    if (strcmp(a.device, "cpu") == 0)
        return run_cpu(&a);
    fprintf(stderr, "mct: unknown device '%s'\n", a.device);
    return 100;
}
