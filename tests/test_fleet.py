"""Failure-aware serving fleet (serve/fleet.py + serve/router.py,
ISSUE 7): deterministic dispatch over N replicas, replica lifecycle
(crash / heartbeat detection / backoff restart / circuit breaking /
elastic join / graceful leave), and exactly-once re-dispatch with
generation-token fencing — all on FakeClock, bitwise-reproducible.

SimCompute makes the proofs sharp: token j of request rid is a pure
32-bit mix of (rid, j, salt, prompt length), so "zero double-generated
tokens" is not a statistical claim — any fence leak would put a
wrong-position token into the authoritative output and break exact
equality with the closed-form expectation."""

import json

import numpy as np
import pytest

from mpi_cuda_cnn_tpu.faults import (
    FakeClock,
    FaultInjector,
    parse_plan,
    validate_plan_sites,
)
from mpi_cuda_cnn_tpu.serve.fleet import (
    Fleet,
    SimCompute,
    make_fleet_workload,
)
from mpi_cuda_cnn_tpu.serve.router import Router, stable_hash

VOCAB = 512


def expected_out(req, *, salt=0, n=None, vocab=VOCAB):
    """SimCompute's closed form: the tokens request `req` must end
    with, independent of which replicas served it or how often it was
    preempted / re-dispatched."""
    n = req.max_new_tokens if n is None else n
    return [
        ((req.rid * 1000003 + j * 2654435761 + salt * 97
          + int(req.prompt.size) * 8191) & 0xFFFFFFFF) % vocab
        for j in range(n)
    ]


def workload(n=300, rate=800.0, seed=0, sessions=0, **kw):
    kw.setdefault("vocab", VOCAB)
    kw.setdefault("prompt_min", 8)
    kw.setdefault("prompt_max", 48)
    kw.setdefault("out_min", 4)
    kw.setdefault("out_max", 32)
    return make_fleet_workload(n=n, rate=rate, seed=seed,
                               sessions=sessions, **kw)


def sim_fleet(*, replicas=4, plan=None, seed=0, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("num_pages", 33)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_len", 96)
    kw.setdefault("check_every", 8)
    return Fleet(
        lambda name: SimCompute(vocab=VOCAB, chunk=16, salt=seed),
        replicas=replicas,
        faults=FaultInjector(plan) if plan else None,
        **kw,
    )


CRASH_PLAN = ("replica_crash@fleet.tick:40?replica=1&zombie_ticks=4;"
              "replica_crash@fleet.tick:120?replica=2;"
              "replica_join@fleet.tick:160")


# ------------------------------------------------- the storm acceptance


def test_storm_all_terminal_and_bitwise_deterministic():
    """The acceptance shape at tier-1 size: a seeded Poisson storm on a
    4-replica fleet with two injected crashes (one a zombie) and one
    elastic join. Every request reaches a terminal status, and two
    identical-seed runs are BITWISE equal in dispatch trace, per-status
    totals, and every authoritative output (the CI storm re-proves this
    at 10^5 requests through `mctpu compare`)."""
    results = []
    for _ in range(2):
        res = sim_fleet(plan=CRASH_PLAN).run(workload())
        assert all(r.terminal for r in res.requests)
        assert res.crashes == 2 and res.joins == 1
        assert res.redispatches > 0
        results.append(res)
    a, b = results
    assert a.dispatch_trace == b.dispatch_trace
    assert a.status_counts() == b.status_counts()
    assert a.outputs() == b.outputs()
    assert a.trace_crc == b.trace_crc
    assert a.ticks == b.ticks


def test_zero_double_generation_under_zombie_crash():
    """The fencing proof: a crashed-but-partitioned replica keeps
    stepping for zombie_ticks after failover, and every commit it
    attempts must be refused. The authoritative output of every
    finished request equals SimCompute's closed form EXACTLY — one
    leaked commit would insert a wrong-position token — and the zombie
    provably attempted commits (fenced_discards > 0)."""
    res = sim_fleet(plan=CRASH_PLAN).run(workload())
    assert res.fenced_discards > 0
    for r in res.finished_requests():
        assert r.out == expected_out(r), f"request {r.rid}"
        assert len(r.out) == r.max_new_tokens


def test_crash_fleet_outputs_equal_crash_free_fleet():
    """Crash-vs-crash-free equivalence: the same seeded workload run on
    an identical fleet WITHOUT faults produces identical outputs for
    every request (re-dispatch recovers the schedule's work without
    corrupting any request, affected or not)."""
    reqs_a, reqs_b = workload(), workload()
    crash = sim_fleet(plan=CRASH_PLAN).run(reqs_a)
    clean = sim_fleet(plan=None).run(reqs_b)
    assert clean.redispatches == 0 and clean.crashes == 0
    outs_crash, outs_clean = crash.outputs(), clean.outputs()
    affected = {rid for (_, rid, _, _, kind) in crash.dispatch_trace
                if kind == "redispatch"}
    assert affected, "the crash plan must strand at least one request"
    for rid in outs_clean:
        assert outs_crash[rid] == outs_clean[rid], f"request {rid}"
    assert crash.status_counts() == clean.status_counts()


def test_redispatch_exactly_once_per_failover():
    """Exactly-once: with a single crash, every stranded request
    appears in the dispatch trace exactly once as a redispatch, and
    the redispatched set is exactly the set the failover harvested
    (replica_log's `dead` event records it)."""
    fleet = sim_fleet(plan="replica_crash@fleet.tick:50?replica=1")
    res = fleet.run(workload())
    redis = [rid for (_, rid, _, _, kind) in res.dispatch_trace
             if kind == "redispatch"]
    assert len(redis) == len(set(redis)), "a request re-dispatched twice"
    dead = [e for e in res.replica_log if e["kind"] == "dead"]
    assert len(dead) == 1
    assert sorted(redis) == dead[0]["stranded"]
    # Fences moved forward: each redispatch carries a higher epoch than
    # the original dispatch of the same rid.
    epochs = {}
    for (_, rid, _, epoch, kind) in res.dispatch_trace:
        if kind == "redispatch":
            assert epoch > epochs[rid]
        epochs[rid] = epoch


def test_discard_redispatch_restarts_from_prompt():
    """redispatch="discard" drops the dead replica's partial output and
    regenerates from the prompt; the final outputs still equal the
    closed form (same tokens, regenerated), and the affected requests
    spend strictly more decode work than under "resume"."""
    plan = "replica_crash@fleet.tick:60?replica=0"
    resume = sim_fleet(plan=plan, redispatch="resume").run(workload())
    discard = sim_fleet(plan=plan, redispatch="discard").run(workload())
    for res in (resume, discard):
        for r in res.finished_requests():
            assert r.out == expected_out(r), f"request {r.rid}"
    assert discard.redispatches == resume.redispatches
    assert discard.decode_ticks + discard.prefill_chunks >= \
        resume.decode_ticks + resume.prefill_chunks


def test_storm_100k_scale():
    """The full 10^5-request acceptance storm (slow; CI runs the same
    shape twice through `mctpu fleet-bench` + `mctpu compare` at 0%
    structural tolerance). Here: all terminal, zero double generation
    at scale."""
    reqs = workload(n=100_000, rate=2000.0)
    plan = ("replica_crash@fleet.tick:4000?replica=1&zombie_ticks=4;"
            "replica_crash@fleet.tick:12000?replica=2;"
            "replica_join@fleet.tick:20000")
    res = sim_fleet(replicas=4, slots=8, plan=plan,
                    check_every=256).run(reqs)
    assert len(res.requests) == 100_000
    assert all(r.terminal for r in res.requests)
    assert res.crashes == 2 and res.joins == 1 and res.redispatches > 0
    for r in res.finished_requests():
        assert r.out == expected_out(r)


# ------------------------------------------------- lifecycle mechanics


def test_heartbeat_detection_lag():
    """A crash is detected by heartbeat staleness, not by the fault:
    the `dead` event lands exactly heartbeat_miss ticks after the
    crash (the replica misses its beat at the crash tick and the next
    miss-1 ticks; the check runs before beats, so missed = lag - 1)."""
    fleet = sim_fleet(plan="replica_crash@fleet.tick:30?replica=1",
                      heartbeat_miss=5)
    res = fleet.run(workload(n=120))
    crash = next(e for e in res.replica_log if e["kind"] == "crash")
    dead = next(e for e in res.replica_log if e["kind"] == "dead")
    assert crash["tick"] == 30
    assert dead["tick"] == 30 + 5


def test_heartbeat_miss_one_never_kills_a_healthy_replica():
    """The tightest legal detector (heartbeat_miss=1) must not declare
    live, beating replicas dead — the staleness check runs before the
    tick's beats, so a healthy member's lag of 1 is zero MISSED beats."""
    res = sim_fleet(replicas=2, heartbeat_miss=1).run(workload(n=60))
    assert {r.status for r in res.requests} == {"finished"}
    assert not any(e["kind"] == "dead" for e in res.replica_log)
    # And it still detects a real crash, one tick after it.
    crashed = sim_fleet(replicas=2, heartbeat_miss=1,
                        plan="replica_crash@fleet.tick:20?replica=1")
    res = crashed.run(workload(n=60))
    dead = next(e for e in res.replica_log if e["kind"] == "dead")
    assert dead["tick"] == 21
    assert {r.status for r in res.requests} == {"finished"}


def test_backoff_restart_rejoins_and_serves():
    """A crashed replica rejoins after utils/retry.backoff_delay and
    receives new dispatches (fresh incarnation, empty pools)."""
    fleet = sim_fleet(plan="replica_crash@fleet.tick:40?replica=1",
                      backoff_base=0.01)
    res = fleet.run(workload())
    kinds = [e["kind"] for e in res.replica_log if e["name"] == "r1"]
    assert kinds == ["crash", "dead", "restart_scheduled", "restart"]
    sched = next(e for e in res.replica_log
                 if e["kind"] == "restart_scheduled")
    assert sched["delay_s"] > 0
    restart_tick = next(e["tick"] for e in res.replica_log
                        if e["kind"] == "restart")
    assert any(name == "r1" and tick >= restart_tick
               for (tick, _, name, _, _) in res.dispatch_trace)
    assert res.replicas_final == 4


def test_circuit_breaker_removes_flapping_replica():
    """A replica that keeps crashing exhausts max_flaps and is
    permanently removed (circuit open) — the fleet keeps serving on
    the survivors and every request still terminates."""
    plan = ("replica_crash@fleet.tick:20?replica=1;"
            "replica_crash@fleet.tick:60?replica=1")
    fleet = sim_fleet(plan=plan, max_flaps=1)
    res = fleet.run(workload())
    assert res.crashes == 2
    assert res.circuit_opens == 1
    assert res.restarts == 1          # only the first crash earned one
    assert res.replicas_final == 3    # r1 never came back
    assert any(e["kind"] == "circuit_open" for e in res.replica_log)
    assert all(r.terminal for r in res.requests)
    assert {r.status for r in res.requests} == {"finished"}


def test_elastic_join_takes_load():
    """replica_join scales out mid-storm: the joined replica appears in
    the dispatch trace after its join tick and the fleet ends larger."""
    fleet = sim_fleet(replicas=2,
                      plan="replica_join@fleet.tick:30?replicas=2")
    res = fleet.run(workload())
    assert res.joins == 2 and res.replicas_final == 4
    joined = {e["name"] for e in res.replica_log if e["kind"] == "join"}
    assert joined == {"r2", "r3"}
    served = {name for (_, _, name, _, _) in res.dispatch_trace}
    assert joined <= served


def test_graceful_leave_drains_without_redispatch():
    """replica_leave stops new dispatches immediately but the leaving
    replica finishes its in-flight work — a drain is not a failover, so
    nothing is re-dispatched and nothing is lost."""
    fleet = sim_fleet(replicas=3,
                      plan="replica_leave@fleet.tick:50?replica=1")
    res = fleet.run(workload())
    assert res.leaves == 1 and res.redispatches == 0
    assert res.replicas_final == 2
    drain = next(e for e in res.replica_log
                 if e["kind"] == "drain_complete")
    leave = next(e for e in res.replica_log if e["kind"] == "leave")
    assert drain["tick"] >= leave["tick"]
    assert not any(name == "r1" and tick > leave["tick"]
                   for (tick, _, name, _, _) in res.dispatch_trace)
    assert {r.status for r in res.requests} == {"finished"}


def test_empty_fleet_waits_for_a_scheduled_join():
    """Losing every replica is not a dead end while the fault plan
    still schedules a replica_join: the fleet ticks through the gap
    and the joined replica serves everything — requests are failed
    terminally only when NO capacity can ever arrive."""
    plan = ("replica_crash@fleet.tick:5?replica=0;"
            "replica_join@fleet.tick:60")
    res = sim_fleet(replicas=1, max_flaps=0, plan=plan).run(workload(n=40))
    assert res.replicas_final == 1 and res.joins == 1
    assert {r.status for r in res.requests} == {"finished"}


def test_all_replicas_lost_fails_remaining_terminally():
    """Losing every replica with the breaker open must still land every
    request in a terminal status — the stranded remainder fails with an
    explicit reason instead of hanging the loop."""
    plan = ("replica_crash@fleet.tick:10?replica=0;"
            "replica_crash@fleet.tick:10?replica=1")
    fleet = sim_fleet(replicas=2, max_flaps=0, plan=plan)
    res = fleet.run(workload(n=80))
    assert res.replicas_final == 0 and res.circuit_opens == 2
    assert all(r.terminal for r in res.requests)
    failed = [r for r in res.requests if r.status == "failed"]
    assert failed and all(r.fail_reason == "fleet has no replicas"
                          for r in failed)
    # A future arrival fails AT its arrival, never before it: a
    # finished_at earlier than arrival would emit negative latency_ms
    # into the obs request records.
    assert all(r.finished_at >= r.arrival for r in failed)


def test_fleet_cancel_reaches_the_holding_replica():
    """Fleet.cancel(rid) lands on BOTH the authoritative request and
    the replica-local copy in flight (distinct objects), fleet-wide:
    the request leaves with status 'cancelled' and fewer tokens than
    its budget. Invoked mid-run from the fleet sink (the loop calls
    sinks every tick), the way a client-side abort arrives."""
    reqs = workload(n=40)
    fleet = sim_fleet(replicas=2)

    def sink(rec):
        if rec["tick"] == 5:
            fleet.cancel(reqs[0].rid)
            fleet.cancel(10**9)  # unknown rid: no-op, no raise
    fleet.fleet_sink = sink
    res = fleet.run(reqs)
    assert all(r.terminal for r in res.requests)
    victim = next(r for r in res.requests if r.rid == reqs[0].rid)
    assert victim.status == "cancelled"
    assert len(victim.out) < victim.max_new_tokens
    assert sum(1 for r in res.requests if r.status == "cancelled") == 1


def test_draining_replica_crash_completes_the_leave():
    """A replica asked to leave that then crashes must NOT be
    restarted: the crash completes the departure (its in-flight work
    fails over normally), instead of the backoff restart resurrecting
    it as a dispatch-taking member against the operator's intent."""
    plan = ("replica_leave@fleet.tick:20?replica=1;"
            "replica_crash@fleet.tick:40?replica=1")
    res = sim_fleet(replicas=3, plan=plan).run(workload())
    assert res.leaves == 1 and res.crashes == 1
    assert res.restarts == 0 and res.replicas_final == 2
    kinds = [e["kind"] for e in res.replica_log if e["name"] == "r1"]
    assert kinds == ["leave", "crash", "dead"]
    dead = next(e for e in res.replica_log if e["kind"] == "dead")
    assert dead.get("draining") is True
    assert all(r.terminal for r in res.requests)
    assert not any(name == "r1" and kind == "redispatch"
                   for (_, _, name, _, kind) in res.dispatch_trace)


# ------------------------------------------------- dispatch policies


def test_session_affinity_keeps_sessions_on_one_replica():
    """The session policy rendezvous-hashes each session onto one
    replica: every dispatch of a session lands on the same member, and
    a crash moves ONLY the dead replica's sessions."""
    reqs = workload(n=200, sessions=12)
    res = sim_fleet(policy="session").run(workload(n=200, sessions=12))
    by_session = {}
    rid_session = {r.rid: r.session for r in reqs}
    for (_, rid, name, _, kind) in res.dispatch_trace:
        assert kind == "dispatch"
        by_session.setdefault(rid_session[rid], set()).add(name)
    assert all(len(names) == 1 for names in by_session.values())
    assert len(set().union(*by_session.values())) > 1

    crashed = sim_fleet(policy="session",
                        plan="replica_crash@fleet.tick:40?replica=1",
                        max_flaps=0).run(workload(n=200, sessions=12))
    home = {s: next(iter(n)) for s, n in by_session.items()}
    for (_tick, rid, name, _, _kind) in crashed.dispatch_trace:
        s = rid_session[rid]
        if home[s] != "r1":
            # Sessions not homed on the dead replica never move.
            assert name == home[s], f"session {s} moved to {name}"


def test_least_loaded_spreads_a_burst():
    """Least-loaded dispatch reads the per-replica telemetry gauges
    plus same-tick pending dispatches, so a burst arriving within one
    tick spreads across the fleet instead of dog-piling one replica."""
    res = sim_fleet(replicas=4).run(workload(n=64, rate=0.0))
    first_tick = [name for (tick, _, name, _, _) in res.dispatch_trace
                  if tick == 0]
    assert len(set(first_tick)) == 4


def test_rendezvous_hash_is_process_stable():
    """stable_hash must not depend on Python's randomized str hash —
    pin a few values so a restart cannot unseat every session."""
    assert stable_hash("s", "r0") == stable_hash("s", "r0")
    assert stable_hash(7, "r1") != stable_hash(7, "r2")
    # Golden values: process-independence means these never drift.
    assert stable_hash("session-a", "r0") == 1166997687
    assert stable_hash(0, "r1") == 1570464646


def test_router_rejects_bad_config():
    with pytest.raises(ValueError, match="policy"):
        Router("round_robin")
    with pytest.raises(ValueError, match="heartbeat_miss"):
        Router(heartbeat_miss=0)
    with pytest.raises(ValueError, match="at least one replica"):
        sim_fleet(replicas=0)
    with pytest.raises(ValueError, match="redispatch"):
        sim_fleet(redispatch="retry")


def test_fleet_rejects_structurally_impossible_requests():
    """Admission-impossible requests die at run() entry with a clear
    error, fleet-wide, before any replica sees them."""
    fleet = sim_fleet()
    bad = workload(n=4)
    bad[2].max_new_tokens = 200  # prompt + new > max_len 96
    with pytest.raises(ValueError, match="exceeds max_len"):
        fleet.run(bad)


# ------------------------------------------------- fault-plan surface


def test_replica_fault_sites_validate_per_surface():
    """`replica_crash@serve.tick` on plain serve-bench (or any site the
    chosen subcommand never registers) errors at validation time
    instead of silently never firing."""
    plan = parse_plan("replica_crash@fleet.tick:10?replica=1")
    validate_plan_sites(plan, "fleet-bench")  # ok
    with pytest.raises(ValueError, match="never reached"):
        validate_plan_sites(plan, "serve-bench")
    with pytest.raises(ValueError, match="never reached"):
        validate_plan_sites("slow@serve.tick:3?s=0.1", "fleet-bench")
    with pytest.raises(ValueError, match="never reached"):
        validate_plan_sites("crash@train.step:2", "serve-bench")
    validate_plan_sites("crash@train.step:2", "train")
    # Kinds are validated per site too: a legal site with a kind its
    # consumer ignores would fire and silently do nothing.
    with pytest.raises(ValueError, match="never applied"):
        validate_plan_sites("replica_crash@train.step:2", "train")
    with pytest.raises(ValueError, match="never applied"):
        validate_plan_sites("nan@serve.tick:3", "serve-bench")
    with pytest.raises(ValueError, match="never applied"):
        validate_plan_sites("squeeze@fleet.tick:3?pages=2&ticks=2",
                            "fleet-bench")
    validate_plan_sites("nan@train.batch:1;preempt@train.step:9", "train")
    validate_plan_sites("squeeze@serve.tick:2?pages=2&ticks=3",
                        "serve-bench")
    # The LM trainer has no train.batch hook: nan@train.batch is valid
    # on the CNN surface but must error on train-lm (it would validate
    # then silently never fire — the exact hole this closes).
    with pytest.raises(ValueError, match="never reached"):
        validate_plan_sites("nan@train.batch:3", "train-lm")
    validate_plan_sites("preempt@train.step:9;crash@ckpt.manifest:1",
                        "train-lm")


def test_redispatch_is_never_backpressure_rejected():
    """A harvested request re-dispatched after a crash keeps its
    first-admission mark, so the surviving replica's queue bound
    (enforce_queue_bound exempts admitted_at-bearing requests) treats
    it as in-flight work, never as a fresh arrival it may reject —
    dropping tokens the fleet already served would break the
    exactly-once contract."""
    fleet = sim_fleet(replicas=2, max_queue=2,
                      plan="replica_crash@fleet.tick:6?replica=1")
    res = fleet.run(workload(n=40, rate=4000.0))
    assert res.crashes == 1 and res.redispatches > 0
    served_then_rejected = [
        r for r in res.requests if r.status == "rejected" and r.out
    ]
    assert not served_then_rejected, served_then_rejected
    # A re-dispatched rid that was merely QUEUED on the dead replica is
    # a fresh arrival at the survivor and may be backpressure-rejected;
    # one that was admitted (it has committed tokens) must finish. The
    # storm must actually exercise that case for this test to bite.
    redispatched = {rid for _, rid, _, _, kind in res.dispatch_trace
                    if kind == "redispatch"}
    finished = {r.rid for r in res.requests if r.status == "finished"}
    assert redispatched & finished, "no re-dispatched request finished"


def test_crash_fault_naming_unknown_replica_errors_loudly():
    """A crash/leave fault naming a replica that has NEVER joined the
    fleet (e.g. replica=7 on a 4-replica run) raises at fire time
    instead of silently never firing — the same contract argparse-time
    site validation pins, extended to the target: a resilience run must
    never report crashes=0 because of a typo'd index."""
    fleet = sim_fleet(plan="replica_crash@fleet.tick:10?replica=7")
    with pytest.raises(ValueError, match="never joined"):
        fleet.run(workload(n=8))
    fleet = sim_fleet(plan="replica_leave@fleet.tick:10?replica=9")
    with pytest.raises(ValueError, match="never joined"):
        fleet.run(workload(n=8))


def test_fleet_bench_cli_rejects_wrong_site():
    from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main

    with pytest.raises(SystemExit) as exc:
        fleet_bench_main(["--fault-plan", "slow@serve.tick:3?s=0.1"])
    assert exc.value.code == 2


# ------------------------------------------------- obs + CLI round trip


def test_fleet_bench_cli_e2e_trace_and_compare(tmp_path):
    """`mctpu fleet-bench` -> `mctpu trace` -> `mctpu compare` round
    trip: the run's telemetry reconstructs every request consistently
    across the re-dispatch, and two identical-seed runs pass the CI
    fleet gate (exact structural equality) while a different-seed run
    fails it."""
    import os

    from mpi_cuda_cnn_tpu.obs.regress import compare_main
    from mpi_cuda_cnn_tpu.obs.timeline import trace_main
    from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main

    args = ["--replicas", "3", "--requests", "80", "--rate", "500",
            "--fault-plan",
            "replica_crash@fleet.tick:30?replica=1&zombie_ticks=2",
            "--seed", "3"]
    runs = []
    for tag in ("a", "b"):
        path = str(tmp_path / f"fleet_{tag}.jsonl")
        assert fleet_bench_main([*args, "--metrics-jsonl", path]) == 0
        runs.append(path)
    assert trace_main([runs[0]]) == 0

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gate = os.path.join(repo, "ci", "fleet_gate.json")
    assert compare_main([*runs, "--gate", gate]) == 0

    drifted = str(tmp_path / "fleet_c.jsonl")
    assert fleet_bench_main(["--replicas", "3", "--requests", "80",
                             "--rate", "500", "--seed", "4",
                             "--metrics-jsonl", drifted]) == 0
    assert compare_main([runs[0], drifted, "--gate", gate]) == 1


def test_fleet_metrics_registry_and_sinks():
    """Telemetry opt-in: registry counters agree with the result's
    structural counts, the fleet sink sees every tick, and the
    replica tick sink's modes cover every incarnation that stepped."""
    from mpi_cuda_cnn_tpu.obs.metrics import MetricsRegistry

    from mpi_cuda_cnn_tpu.faults import FakeClock

    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    fleet_recs, tick_recs = [], []
    fleet = Fleet(
        lambda name: SimCompute(vocab=VOCAB, chunk=16, salt=0),
        replicas=3, slots=4, num_pages=33, page_size=8, max_len=96,
        faults=FaultInjector("replica_crash@fleet.tick:25?replica=0"),
        clock=clock, registry=reg,
        fleet_sink=fleet_recs.append, replica_tick_sink=tick_recs.append,
    )
    res = fleet.run(workload(n=120))
    assert len(fleet_recs) == res.ticks
    assert reg.counters["fleet.dispatches"].value == res.dispatches
    assert reg.counters["fleet.redispatches"].value == res.redispatches
    assert reg.counters["fleet.replica_crash"].value == 1
    modes = {r["mode"] for r in tick_recs}
    assert {"fleet/r0", "fleet/r1", "fleet/r2"} <= modes
    # Per-status totals seen by the registry match the result.
    fin = reg.counters.get("serve.requests_finished")
    assert fin is not None
    assert fin.value == res.status_counts()["finished"]


def test_fleet_summary_is_json_serializable():
    res = sim_fleet(plan=CRASH_PLAN).run(workload(n=100))
    s = json.loads(json.dumps(res.summary()))
    assert s["mode"] == "fleet"
    assert s["requests"] == 100
    assert s["dispatches"] == 100
    assert s["crashes"] == 2
    recs = res.request_records()
    assert len(recs) == 100 and all(r["mode"] == "fleet" for r in recs)


# ------------------------------------------------- engine-backed fleet


def test_single_replica_fleet_matches_paged_engine_run():
    """ReplicaCore.step is engine.run's continuous-mode tick body with
    the idle/fault/watchdog handling hoisted into the fleet loop — this
    pins the two drivers against each other so a rule change in one
    (emit timing, finish ordering, sweep placement, chunking) cannot
    silently diverge single-engine and fleet serving: the same workload
    through PagedEngine.run and through a 1-replica engine-backed fleet
    must finish every request with identical outputs, statuses, and
    prefill-chunk counts."""
    import jax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
    from mpi_cuda_cnn_tpu.serve.fleet import EngineCompute

    model = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)
    params = model.init(jax.random.key(0))
    geom = dict(slots=2, num_pages=13, page_size=8, max_len=48)

    def reqs():
        return make_fleet_workload(n=12, vocab=13, prompt_min=4,
                                   prompt_max=10, out_min=4, out_max=10,
                                   rate=300.0, seed=3)

    engine = PagedEngine(model, params, prefill_chunk=8, **geom)
    clock = FakeClock()
    eng = engine.run(reqs(), mode="continuous", time_fn=clock,
                     sleep_fn=clock.advance)
    fleet = Fleet(
        lambda name: EngineCompute(PagedEngine(model, params,
                                               prefill_chunk=8, **geom)),
        replicas=1, **geom,
    ).run(reqs())

    assert {r.status for r in eng.requests} == {"finished"}
    assert fleet.status_counts() == {"finished": 12}
    eng_outs = {r.rid: list(r.out) for r in eng.requests}
    assert fleet.outputs() == eng_outs
    # Chunk counts are per-request structure (ceil(prompt/chunk) each)
    # and must agree; decode TICK counts are batching density — a
    # function of admission cadence (fleet tick clock vs engine.run's
    # arrival-driven sleeps), legitimately different between drivers.
    assert fleet.prefill_chunks == eng.prefill_chunks


@pytest.mark.parametrize("redispatch", ["resume", "discard"])
def test_engine_fleet_crash_outputs_match_crash_free(redispatch):
    """The model-backed fleet (one PagedEngine per replica, shared
    weights): a crash mid-storm re-dispatches in-flight requests to the
    surviving replica, and every finished output is BITWISE equal to
    the crash-free fleet's — cross-replica resume re-prefills prompt +
    committed tokens through the same jitted programs (the PR-3
    recompute-preemption parity, now across replicas)."""
    import jax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
    from mpi_cuda_cnn_tpu.serve.fleet import EngineCompute

    model = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)
    params = model.init(jax.random.key(0))

    def factory(name):
        return EngineCompute(PagedEngine(
            model, params, slots=2, num_pages=13, page_size=8,
            prefill_chunk=8, max_len=48,
        ))

    def build(plan):
        # max_flaps=0: the crashed replica never rejoins, so the test
        # compiles three engine incarnations instead of four.
        return Fleet(factory, replicas=2, slots=2, num_pages=13,
                     page_size=8, max_len=48, redispatch=redispatch,
                     max_flaps=0,
                     faults=FaultInjector(plan) if plan else None)

    def reqs():
        return make_fleet_workload(n=10, vocab=13, prompt_min=4,
                                   prompt_max=10, out_min=4, out_max=10,
                                   rate=300.0, seed=1)

    crash = build("replica_crash@fleet.tick:8?replica=0").run(reqs())
    clean = build(None).run(reqs())
    assert crash.crashes == 1
    assert crash.status_counts() == clean.status_counts()
    assert {r.status for r in clean.requests} == {"finished"}
    outs_crash, outs_clean = crash.outputs(), clean.outputs()
    for rid, out in outs_clean.items():
        assert outs_crash[rid] == out, f"request {rid}"


# ------------------------------------------------- lossy transport (ISSUE 20)


def transport_fleet(*, replicas=4, plan=None, seed=0, **kw):
    kw.setdefault("transport", True)
    return sim_fleet(replicas=replicas, plan=plan, seed=seed, **kw)


def test_transport_zero_fault_bus_matches_direct_fleet_bitwise():
    """The parity acceptance: with zero transport faults the bus-routed
    fleet is BITWISE-equal to the direct-call fleet per request —
    dispatch trace, statuses, every authoritative output, tick count.
    Zero-fault delivery is inline (send() invokes the handler
    synchronously), so this holds by construction, and the wire
    counters prove no message ever queued. state_crc legitimately
    differs (the bus folds its digest as a 6th component); trace_crc
    is the request-level criterion."""
    direct = sim_fleet().run(workload())
    bus = transport_fleet().run(workload())
    assert bus.dispatch_trace == direct.dispatch_trace
    assert bus.status_counts() == direct.status_counts()
    assert bus.outputs() == direct.outputs()
    assert bus.trace_crc == direct.trace_crc
    assert bus.ticks == direct.ticks
    s = bus.summary()
    assert s["msgs_sent"] > 0 and s["msgs_sent"] == s["msgs_delivered"]
    for k in ("msgs_dropped", "msgs_duped", "msgs_delayed",
              "msgs_deduped", "retransmits", "lease_refusals",
              "partitions"):
        assert s[k] == 0, k
    # Direct-mode summaries carry the same keys, pinned to zero.
    assert all(direct.summary()[k] == 0 for k in
               ("msgs_sent", "retransmits", "lease_refusals"))


PARTITION_PLAN = (
    "msg_delay@fleet.transport:10?count=4&ticks=5&kind=dispatch;"
    "partition@fleet.transport:30?replica=1&ticks=12;"
    "msg_dup@fleet.transport:60?count=2;"
    "msg_drop@fleet.transport:70?count=3&kind=commit;"
    "replica_crash@fleet.tick:90?replica=2&zombie_ticks=3")


def test_partition_false_positive_death_heals_exactly_once():
    """The partition e2e at tier-1 scale: a 12-tick window isolates a
    LIVE replica (heartbeat_miss=3, so the router declares it dead —
    failure detection is fallible, late is not dead), its in-flight
    work is re-dispatched, the isolated replica keeps serving into the
    void until its lease expires and then REFUSES its own commits, and
    on heal every stale commit is lease/fence-refused: every request
    terminal exactly once, every finished output token-for-token equal
    to the SimCompute closed form, zero double generation. Composed
    with message delay / dup / drop and a real zombie crash so the
    false-positive path is proven against the true-positive one."""
    results = []
    for _ in range(2):
        res = transport_fleet(plan=PARTITION_PLAN).run(workload())
        results.append(res)
    a, b = results
    assert all(r.terminal for r in a.requests)
    assert len(a.requests) == 300
    for r in a.finished_requests():
        assert r.out == expected_out(r)
    # The false positive really happened: r1 was declared dead (and
    # torn down / restarted) without ever crashing...
    r1 = [e["kind"] for e in a.replica_log if e.get("name") == "r1"]
    assert "dead" in r1 and "crash" not in r1
    # ...while it was ISOLATED, not gone — and its post-lease commit
    # attempts were refused, which is the zero-double-generation
    # mechanism under partitions.
    assert "isolated" in r1 and "isolated_end" in r1
    assert a.lease_refusals > 0
    assert a.redispatches > 0
    # Partition lifecycle reached the transport log (open then heal).
    kinds = [e["kind"] for e in a.transport_log]
    assert kinds.count("partition_open") == 1
    assert kinds.count("partition_heal") == 1
    # Wire accounting: messages really dropped (partition + msg_drop),
    # duplicated (msg_dup), delayed (msg_delay), deduplicated, and
    # retransmitted — with conservation at quiesce.
    s = a.summary()
    for k in ("msgs_dropped", "msgs_duped", "msgs_delayed",
              "msgs_deduped", "retransmits"):
        assert s[k] > 0, k
    assert s["partitions"] == 1
    assert (s["msgs_sent"] == s["msgs_delivered"] + s["msgs_deduped"]
            + s["msgs_dropped"])
    # The true-positive leg still holds alongside.
    assert a.crashes == 1
    # Bitwise determinism across the identical-seed twin.
    assert a.dispatch_trace == b.dispatch_trace
    assert a.status_counts() == b.status_counts()
    assert a.outputs() == b.outputs()
    assert a.trace_crc == b.trace_crc
    assert a.summary()["state_crc"] == b.summary()["state_crc"]
    assert a.lease_refusals == b.lease_refusals


def test_transport_storm_100k_partition_scale():
    """The full 10^5-request transport acceptance storm (slow; CI runs
    the same shape twice through `mctpu fleet-bench --transport` +
    `mctpu compare` at 0% tolerance): one partition + heal isolating a
    live replica, one false-positive death, one zombie crash — all
    terminal exactly once, zero double generation at scale."""
    reqs = workload(n=100_000, rate=2000.0)
    plan = ("partition@fleet.transport:4000?replica=1&ticks=12;"
            "msg_dup@fleet.transport:12000?count=3;"
            "replica_crash@fleet.tick:20000?replica=2&zombie_ticks=4")
    res = transport_fleet(replicas=4, slots=8, plan=plan,
                          check_every=256).run(reqs)
    assert len(res.requests) == 100_000
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r)
    assert res.lease_refusals > 0
    assert res.crashes == 1 and res.partitions == 1
    assert res.redispatches > 0
    s = res.summary()
    assert (s["msgs_sent"] == s["msgs_delivered"] + s["msgs_deduped"]
            + s["msgs_dropped"])


def test_heartbeat_detection_is_derived_from_message_loss():
    """The heartbeat bugfix satellite: under the bus, `dead` is a
    DERIVED effect of heartbeat messages not arriving — not a
    privileged side channel. (a) A real crash is detected with exactly
    the same lag as the direct-call fleet (the back-compat the
    existing detection-lag test pins); (b) dropping ONLY r1's hb
    messages on the wire produces a false-positive death of a healthy
    replica — pure message loss, no fault at the replica — and the
    run still ends exactly-once with closed-form outputs."""
    miss = 5
    fleet = transport_fleet(
        plan="replica_crash@fleet.tick:30?replica=1", heartbeat_miss=miss)
    res = fleet.run(workload(n=120))
    crash = next(e for e in res.replica_log if e["kind"] == "crash")
    dead = next(e for e in res.replica_log if e["kind"] == "dead")
    assert crash["tick"] == 30
    assert dead["tick"] == 30 + miss
    # (b) targeted drop: enough consecutive hb losses to cross the
    # staleness window kill a replica that never stopped working.
    lossy = transport_fleet(
        plan="msg_drop@fleet.transport:30?kind=hb&replica=1&count=10",
        heartbeat_miss=3)
    res = lossy.run(workload(n=200))
    r1 = [e["kind"] for e in res.replica_log if e.get("name") == "r1"]
    assert "dead" in r1 and "crash" not in r1 and "isolated" in r1
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r)
    assert res.summary()["msgs_dropped"] >= 10
