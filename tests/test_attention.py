"""Attention ops + sequence parallelism (ring / Ulysses) parity tests.

The reference has no attention (SURVEY.md §5.7); these cover the
long-context capability. All sequence-parallel forms are EXACT — parity
against the single-device oracle on the 8-virtual-device CPU mesh, for
both causal and bidirectional masks, forward and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.ops.attention import attention, blockwise_attention
from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
from mpi_cuda_cnn_tpu.parallel.sp import (
    SEQ_AXIS,
    make_ring_attention,
    make_ring_flash_attention,
    make_ulysses_attention,
)

B, S, H, D = 2, 64, 8, 16


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, S, H, D)), dtype)
    return mk(), mk(), mk()


def _seq_mesh(n=8):
    return make_mesh({SEQ_AXIS: n}, devices=jax.devices()[:n])


def test_attention_matches_naive_softmax():
    q, k, v = _qkv()
    got = attention(q, k, v)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_attention_causal_ignores_future():
    q, k, v = _qkv()
    out1 = attention(q, k, v, causal=True)
    # Clobber the future keys/values: causal output must not change.
    k2 = k.at[:, S // 2 :].set(123.0)
    v2 = v.at[:, S // 2 :].set(-7.0)
    out2 = attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(
        np.asarray(out1[:, : S // 2]), np.asarray(out2[:, : S // 2]),
        rtol=1e-5, atol=1e-5,
    )


def test_online_softmax_fully_masked_rows_yield_zeros():
    """A row masked out of EVERY block must finalize to zeros, not to a
    mean over masked keys (regression: exp(NEG_INF - NEG_INF) = 1)."""
    from mpi_cuda_cnn_tpu.ops.attention import (
        finalize_online,
        init_online,
        online_softmax_block,
    )

    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 4, 1, 8)), jnp.float32)
               for _ in range(3))
    mask = jnp.ones((4, 4), bool).at[0, :].set(False)  # row 0 sees nothing
    carry = online_softmax_block(init_online(q), q, k, v, mask)
    out = finalize_online(carry, q.dtype)
    np.testing.assert_allclose(np.asarray(out[0, 0]), 0.0, atol=1e-6)
    assert np.all(np.isfinite(np.asarray(out)))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [8, 16, 64])
def test_blockwise_matches_full(causal, block):
    q, k, v = _qkv(seed=1)
    got = blockwise_attention(q, k, v, block_size=block, causal=causal)
    want = attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_parity(causal):
    q, k, v = _qkv(seed=2)
    mesh = _seq_mesh()
    ring = make_ring_attention(mesh)
    got = ring(q, k, v, causal=causal)
    want = attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_parity(causal):
    q, k, v = _qkv(seed=3)
    mesh = _seq_mesh()
    uly = make_ulysses_attention(mesh)
    got = uly(q, k, v, causal=causal)
    want = attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("maker", [make_ring_attention, make_ulysses_attention])
def test_sp_gradients_match_oracle(maker):
    """ppermute/all_to_all differentiate: d(loss)/d(q,k,v) must match the
    single-device oracle's gradients."""
    q, k, v = _qkv(seed=4)
    mesh = _seq_mesh()
    sp = maker(mesh)

    def loss_sp(q, k, v):
        return jnp.sum(sp(q, k, v, causal=True) ** 2)

    def loss_oracle(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    gs = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def _qkv_flash(seed=0, s=1024, b=1, h=2, d=16):
    """Shards of 128 per device on the 8-mesh — the flash kernel's
    minimum block granularity (s_local % 128 == 0)."""
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_parity(causal):
    """Ring with the fused flash kernel as the per-hop fold == oracle."""
    q, k, v = _qkv_flash(seed=6)
    mesh = _seq_mesh()
    ring = make_ring_flash_attention(mesh)
    got = ring(q, k, v, causal=causal)
    want = attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ring_flash_bf16_partials_merge_in_f32():
    """bf16 inputs: per-hop partials must stay f32 through the merge
    (out_f32) — the output should track the f32 oracle within bf16
    input-rounding error, not accumulate per-hop truncation."""
    q, k, v = _qkv_flash(seed=8)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    mesh = _seq_mesh()
    ring = make_ring_flash_attention(mesh)
    got = ring(qb, kb, vb, causal=True).astype(jnp.float32)
    want = attention(qb.astype(jnp.float32), kb.astype(jnp.float32),
                     vb.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_ring_flash_gradients_match_oracle():
    """The custom-VJP backward ring (rotating dk/dv accumulators, fused
    flash backward per hop) == the oracle's gradients."""
    q, k, v = _qkv_flash(seed=7)
    mesh = _seq_mesh()
    ring = make_ring_flash_attention(mesh)

    def loss_sp(q, k, v):
        return jnp.sum(ring(q, k, v, causal=True) ** 2)

    def loss_oracle(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    gs = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_oracle, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_ring_attention_long_sequence_small_shards():
    """S = 1024 over 8 devices: each device only ever holds 128-long k/v
    blocks — the O(S/P) memory point of ring attention."""
    rng = np.random.default_rng(5)
    q, k, v = (jnp.asarray(rng.standard_normal((1, 1024, 2, 8)), jnp.float32)
               for _ in range(3))
    mesh = _seq_mesh()
    ring = make_ring_attention(mesh)
    got = ring(q, k, v, causal=True)
    want = attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    rng = np.random.default_rng(6)
    q = k = v = jnp.asarray(rng.standard_normal((B, S, 6, D)), jnp.float32)
    mesh = _seq_mesh()
    uly = make_ulysses_attention(mesh)
    with pytest.raises(ValueError, match="heads"):
        uly(q, k, v)
