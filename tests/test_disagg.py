"""Disaggregated prefill/decode serving with crash-safe page-granular
KV handoff (ISSUE 13, serve/handoff.py + serve/fleet.py).

THE acceptance shapes live here:
- a 2-pool storm with crashes (one prefill replica killed MID-HANDOFF),
  a pool-collapse degradation, and injected transfer corruption
  completes with zero lost/duplicated requests, finished outputs
  exactly equal to the unified fleet's per request, run-vs-run bitwise
  (dispatch CRC + blame CRC — the CI disagg gate re-proves this at
  10^5 requests);
- every transfer-integrity failure (kv_corrupt, handoff_drop, dead
  sender, dead receiver, corrupted resume context) resolves to
  exactly-once re-prefill — garbage is never decoded;
- blame conservation holds with handoff_wait as its own category, and
  the trace's phase-transition marker is ordered before the decode
  pool's first emission.

SimCompute keeps the proofs sharp (token j of rid is a closed form),
and the engine-backed twin proves the handed-off decode — including
through prefix sharing — is BITWISE the unified one.
"""

import json

import numpy as np
import pytest

from mpi_cuda_cnn_tpu.faults import FaultInjector, parse_plan, \
    validate_plan_sites
from mpi_cuda_cnn_tpu.obs.causal import BlameAccumulator
from mpi_cuda_cnn_tpu.serve.fleet import (
    Fleet,
    SimCompute,
    make_fleet_workload,
    parse_pools,
)
from mpi_cuda_cnn_tpu.serve.handoff import (
    context_crc,
    context_tokens,
    page_crcs,
    verify_page_crcs,
)

VOCAB = 512
POOLS = {"prefill": 2, "decode": 2}


def expected_out(req, *, salt=0, n=None, vocab=VOCAB):
    n = req.max_new_tokens if n is None else n
    return [
        ((req.rid * 1000003 + j * 2654435761 + salt * 97
          + int(req.prompt.size) * 8191) & 0xFFFFFFFF) % vocab
        for j in range(n)
    ]


def workload(n=400, rate=800.0, seed=0, **kw):
    kw.setdefault("vocab", VOCAB)
    kw.setdefault("prompt_min", 8)
    kw.setdefault("prompt_max", 48)
    kw.setdefault("out_min", 4)
    kw.setdefault("out_max", 32)
    return make_fleet_workload(n=n, rate=rate, seed=seed, **kw)


def disagg_fleet(*, pools=POOLS, plan=None, seed=0, handoff_ticks=2, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("num_pages", 33)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_len", 96)
    kw.setdefault("check_every", 8)
    return Fleet(
        lambda name: SimCompute(vocab=VOCAB, chunk=16, salt=seed),
        pools=pools, handoff_ticks=handoff_ticks,
        faults=FaultInjector(plan) if plan else None,
        **kw,
    )


def unified_fleet(*, replicas=4, plan=None, seed=0, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("num_pages", 33)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_len", 96)
    kw.setdefault("check_every", 8)
    return Fleet(
        lambda name: SimCompute(vocab=VOCAB, chunk=16, salt=seed),
        replicas=replicas,
        faults=FaultInjector(plan) if plan else None,
        **kw,
    )


# The acceptance fault plan: a prefill replica (r0) killed while
# transfers are in flight, a decode-pool collapse (both decode
# replicas), and an elastic decode join. handoff_ticks=2 keeps a crash
# window open on every transfer.
CRASH_PLAN = ("replica_crash@fleet.tick:40?replica=0&zombie_ticks=4;"
              "pool_crash@fleet.tick:120?pool=decode;"
              "replica_join@fleet.tick:200?pool=decode")


# ------------------------------------------------------- protocol unit


def test_parse_pools_grammar():
    assert parse_pools("prefill:2,decode:3") == {"prefill": 2, "decode": 3}
    for bad in ("prefill:2", "decode:1", "prefill:0,decode:1",
                "prefill:2,decode:1,prefill:1", "warmup:1,decode:1",
                "prefill,decode:1"):
        with pytest.raises(ValueError):
            parse_pools(bad)


def test_page_crcs_cover_exactly_the_cached_rows():
    """The integrity stamp is a pure function of the token ids whose KV
    rows each page holds — rows 0..cached-1 only (the in-flight token
    is not yet a cache row), page-granular, order-sensitive."""
    prompt = np.arange(10, dtype=np.int32)
    toks = context_tokens(prompt, [99, 98])
    crcs = page_crcs(toks, cached=11, page_size=4)
    assert len(crcs) == 3  # ceil(11 / 4)
    assert verify_page_crcs(crcs, toks, 11, 4)
    # The un-cached tail token is outside the stamp.
    assert crcs == page_crcs(context_tokens(prompt, [99, 77]), 11, 4)
    # Any cached-row change, page order change, or stamp flip refuses.
    other = context_tokens(np.arange(1, 11, dtype=np.int32), [99, 98])
    assert not verify_page_crcs(crcs, other, 11, 4)
    assert not verify_page_crcs(list(reversed(crcs)), toks, 11, 4)
    assert not verify_page_crcs([crcs[0] ^ 1, *crcs[1:]], toks, 11, 4)
    assert not verify_page_crcs(crcs[:-1], toks, 11, 4)
    assert context_crc(prompt, [99, 98]) != context_crc(prompt, [99, 97])


def test_slo_scheduler_owns_decode_pool_admission():
    """Each pool's SLOScheduler owns its own admission: the decode
    side's transfer binding enforces the tenant slot quota exactly as
    the prefill side's admit() does (ISSUE 13 — TTFT and TPOT budgets
    no longer share one gate)."""
    from mpi_cuda_cnn_tpu.serve.pool import PagePool
    from mpi_cuda_cnn_tpu.serve.scheduler import (
        Request,
        SLOPolicy,
        SLOScheduler,
    )

    pool = PagePool(16)
    sched = SLOScheduler(policy=SLOPolicy(slot_quota={"t0": 1}),
                         slots=3, pool=pool, page_size=4, max_len=32)
    r0 = Request(rid=0, prompt=np.arange(4), max_new_tokens=4, tenant="t0")
    r1 = Request(rid=1, prompt=np.arange(4), max_new_tokens=4, tenant="t0")
    r2 = Request(rid=2, prompt=np.arange(4), max_new_tokens=4, tenant="t1")
    owner = ("handoff", 0, 0)
    pages = pool.try_alloc(2, owner)
    assert sched.bind_transfer(r0, pages, cached=5, owner=owner,
                               now=0.0) is not None
    # Same tenant at quota: the transfer waits (bind refuses, nothing
    # changes); another tenant's transfer is unaffected.
    assert not sched.transfer_quota_ok(r1)
    pages1 = pool.try_alloc(2, ("handoff", 1, 1))
    assert sched.bind_transfer(r1, pages1, cached=5,
                               owner=("handoff", 1, 1), now=0.0) is None
    assert sched.transfer_quota_ok(r2)
    pool.free(pages1, ("handoff", 1, 1))
    sched.check()


# ------------------------------------------------- the storm acceptance


def test_disagg_storm_deterministic_and_outputs_equal_unified():
    """THE acceptance at tier-1 size: the 2-pool storm with a prefill
    replica killed mid-handoff, a decode-pool collapse, and a join
    completes every request; two identical-seed runs are BITWISE equal
    (dispatch trace, outputs, handoff/degradation counters); and every
    finished output equals the UNIFIED fleet's for the same workload —
    the split changes the schedule, never the tokens."""
    results = []
    for _ in range(2):
        res = disagg_fleet(plan=CRASH_PLAN).run(workload())
        assert all(r.terminal for r in res.requests)
        assert res.handoffs > 0 and res.crashes >= 3
        results.append(res)
    a, b = results
    assert a.dispatch_trace == b.dispatch_trace
    assert a.trace_crc == b.trace_crc and a.ticks == b.ticks
    assert a.outputs() == b.outputs()
    assert a.status_counts() == b.status_counts()
    assert (a.handoffs, a.handoffs_aborted, a.kv_refusals,
            a.degraded_unified) == (b.handoffs, b.handoffs_aborted,
                                    b.kv_refusals, b.degraded_unified)
    unified = unified_fleet().run(workload())
    outs_d, outs_u = a.outputs(), unified.outputs()
    for rid, out in outs_u.items():
        assert outs_d[rid] == out, f"request {rid}"
    # Zero double generation anywhere: the closed form is exact.
    for r in a.finished_requests():
        assert r.out == expected_out(r), f"request {r.rid}"
        assert len(r.out) == r.max_new_tokens


def test_prefill_replica_crash_mid_handoff_reprefills_exactly_once():
    """Sender dies with transfers in flight: the receiver's partial
    adoption is revoked (its pool stays clean — end-of-run check), the
    stranded requests re-prefill elsewhere exactly once, and no token
    is lost or doubled."""
    fleet = disagg_fleet(
        plan="replica_crash@fleet.tick:40?replica=0", handoff_ticks=5)
    res = fleet.run(workload())
    dead = [r for r in res.handoff_log
            if r["state"] == "aborted" and r["reason"] == "sender_dead"]
    assert dead, "no handoff was in flight at the crash — widen the window"
    assert res.handoffs_aborted >= len(dead)
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r), f"request {r.rid}"
    # Exactly-once: an aborted handoff's rid re-dispatches once per
    # abort, never twice for one abort event.
    redis = [rid for (_, rid, _, _, kind) in res.dispatch_trace
             if kind == "redispatch"]
    aborted_rids = [r["rid"] for r in res.handoff_log
                    if r["state"] == "aborted"]
    for rid in set(aborted_rids):
        assert redis.count(rid) >= aborted_rids.count(rid)


def test_decode_replica_crash_mid_handoff_releases_sender():
    """Receiver dies mid-copy: the sender's sealed pages are released
    (its pool proves clean at exit) and the router re-targets through
    the re-dispatch path — outputs stay exact."""
    fleet = disagg_fleet(
        pools={"prefill": 2, "decode": 1},
        plan="replica_crash@fleet.tick:20?replica=2", handoff_ticks=8)
    res = fleet.run(workload(n=250))
    dead = [r for r in res.handoff_log
            if r["state"] == "aborted" and r["reason"] == "receiver_dead"]
    assert dead, "no handoff targeted the receiver at its crash"
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r), f"request {r.rid}"


def test_kv_corrupt_handoff_is_refused_never_decoded():
    """A corrupted page fails CRC verification at adoption: the
    transfer is refused, the request re-prefills, and the final output
    is still the exact closed form — garbage never decodes."""
    plan = ("kv_corrupt@fleet.handoff:2?page=0;"
            "kv_corrupt@fleet.handoff:7")
    res = disagg_fleet(plan=plan).run(workload())
    assert res.kv_refusals == 2
    refused = [r for r in res.handoff_log
               if r["state"] == "aborted" and r["reason"] == "kv_corrupt"]
    assert len(refused) == 2
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r), f"request {r.rid}"
    # The refused rids finished anyway (re-prefilled elsewhere).
    for rec in refused:
        req = next(r for r in res.requests if r.rid == rec["rid"])
        assert req.status == "finished"


def test_handoff_drop_resolves_exactly_once():
    res = disagg_fleet(
        plan="handoff_drop@fleet.handoff:1").run(workload(n=200))
    dropped = [r for r in res.handoff_log
               if r["state"] == "aborted" and r["reason"] == "dropped"]
    assert len(dropped) == 1
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r)


def test_pool_collapse_degrades_to_unified_and_restores():
    """The decode pool emptying flips affected requests to unified
    serving (prefill replicas decode locally) instead of stalling —
    with degraded/restored obs events latched once per episode — and
    the fleet keeps completing requests throughout."""
    fleet = disagg_fleet(
        plan="pool_crash@fleet.tick:60?pool=decode",
        backoff_base=0.05)
    res = fleet.run(workload())
    assert res.degraded_unified > 0
    kinds = [(e["name"], e["kind"]) for e in res.replica_log
             if e["kind"] in ("degraded", "restored")]
    assert ("decode", "degraded") in kinds
    assert ("decode", "restored") in kinds  # restarts repopulated it
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r)


def test_prefill_pool_collapse_dispatches_unified():
    """The PREFILL pool emptying degrades new dispatches onto decode
    replicas, which serve them end to end (no handoff)."""
    fleet = disagg_fleet(
        pools={"prefill": 1, "decode": 2},
        plan="pool_crash@fleet.tick:30?pool=prefill",
        backoff_base=1.0)  # slow restart: the degradation window is wide
    res = fleet.run(workload(n=250))
    assert res.degraded_unified > 0
    assert ("prefill", "degraded") in [
        (e["name"], e["kind"]) for e in res.replica_log
        if e["kind"] == "degraded"]
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r)


def test_resume_context_crc_refuses_corrupt_committed_tokens():
    """The failover resume path now verifies the committed context it
    re-prefills (it used to re-adopt it unchecked): an injected
    kv_corrupt@fleet.resume forces the fallback to discard semantics —
    the tokens regenerate from the prompt and the final output is still
    exact."""
    plan = ("replica_crash@fleet.tick:40?replica=1;"
            "kv_corrupt@fleet.resume:0")
    res = unified_fleet(plan=plan).run(workload())
    assert res.kv_refusals == 1
    assert any(e["kind"] == "resume_refused" for e in res.events)
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r), f"request {r.rid}"


def test_cancel_mid_handoff_aborts_and_terminates():
    """A client cancel landing while the rid's KV is in flight aborts
    the transfer (both ends released) and the cancel rides the
    re-dispatch to a terminal 'cancelled' status."""
    fleet = disagg_fleet(handoff_ticks=8)
    reqs = workload(n=60, rate=300.0)
    target = {}

    def fleet_sink(rec):
        # Cancel the first rid whose handoff starts, the moment the
        # marker appears (sinks run mid-loop — the supported surface).
        if not target and rec.get("handoff_started"):
            rid = rec["handoff_started"][0][0]
            target["rid"] = rid
            fleet.cancel(rid)

    fleet.fleet_sink = fleet_sink
    res = fleet.run(reqs)
    assert target, "no handoff ever started"
    req = next(r for r in res.requests if r.rid == target["rid"])
    assert req.status == "cancelled"
    cancelled = [r for r in res.handoff_log
                 if r["state"] == "aborted" and r["reason"] == "cancelled"]
    assert cancelled and cancelled[0]["rid"] == target["rid"]
    assert all(r.terminal for r in res.requests)


def test_disagg_storm_100k_scale():
    """The full 10^5-request acceptance storm (CI runs the same shape
    twice through `mctpu fleet-bench` + `mctpu compare ci/disagg_gate`):
    2 pools, a prefill replica killed mid-handoff, a decode-pool
    collapse, a join — all terminal, zero lost/double tokens at scale,
    outputs equal to the unified fleet's."""
    plan = ("replica_crash@fleet.tick:4000?replica=0&zombie_ticks=4;"
            "pool_crash@fleet.tick:12000?pool=decode;"
            "replica_join@fleet.tick:20000?pool=decode")
    res = disagg_fleet(pools={"prefill": 2, "decode": 2}, slots=8,
                       plan=plan, check_every=256,
                       ).run(workload(n=100_000, rate=2000.0))
    assert len(res.requests) == 100_000
    assert all(r.terminal for r in res.requests)
    assert res.handoffs > 0 and res.handoffs_aborted > 0
    assert any(r["reason"] == "sender_dead" for r in res.handoff_log
               if r["state"] == "aborted"), "crash missed the window"
    assert res.degraded_unified > 0
    for r in res.finished_requests():
        assert r.out == expected_out(r)
    unified = unified_fleet(replicas=4, slots=8,
                            check_every=256).run(
        workload(n=100_000, rate=2000.0))
    outs_d, outs_u = res.outputs(), unified.outputs()
    for rid, out in outs_u.items():
        assert outs_d[rid] == out


# ------------------------------------------------------- obs round trip


def test_blame_handoff_wait_conserved():
    """`mctpu explain`'s new category: handoff wait is billed as its
    own blame with conservation preserved — every terminal request's
    categories still sum bitwise to its tick span through handoffs,
    aborts, crashes, and degradation."""
    acc = BlameAccumulator(detail=True)
    fleet = disagg_fleet(plan=CRASH_PLAN,
                         fleet_sink=acc.ingest_fleet,
                         replica_tick_sink=acc.ingest_tick)
    res = fleet.run(workload())
    assert acc.check("fleet") == []
    blames = acc.blames()["fleet"]
    assert len(blames) == len(res.requests)
    for b in blames.values():
        assert b.terminal and b.conserved
    totals = acc.summary_fields("fleet")["categories"]
    assert totals["handoff_wait"] > 0
    # Handed-off requests carry handoff_wait; aborted ones also replay.
    handed = {r["rid"] for r in res.handoff_log if r["state"] == "done"}
    assert any(blames[rid].cats["handoff_wait"] > 0 for rid in handed)
    aborted = {r["rid"] for r in res.handoff_log
               if r["state"] == "aborted"}
    assert any(blames[rid].cats["redispatch_replay"] > 0
               for rid in aborted)


def test_trace_marker_ordered_before_decode_pool_emission():
    """The fleet emits its record (with the handoff_done marker) before
    stepping replicas, so in the record stream the phase transition
    precedes the decode pool's first emission for the rid — the
    ordering `mctpu trace` anchors the lifecycle on."""
    records = []
    fleet = disagg_fleet(
        fleet_sink=lambda r: records.append({"event": "fleet", **r}),
        replica_tick_sink=lambda r: records.append({"event": "tick", **r}),
    )
    res = fleet.run(workload(n=80, rate=300.0))
    assert res.handoffs > 0
    done_idx = {}
    for i, rec in enumerate(records):
        if rec["event"] == "fleet":
            for rid, _dst in rec.get("handoff_done") or []:
                done_idx.setdefault(rid, i)
    assert done_idx
    dst_of = {r["rid"]: r["dst"] for r in res.handoff_log
              if r["state"] == "done"}
    checked = 0
    for i, rec in enumerate(records):
        if rec["event"] != "tick":
            continue
        for _slot, rid in rec.get("decoded") or []:
            if rid in done_idx and \
                    rec["mode"] == f"fleet/{dst_of[rid]}":
                assert done_idx[rid] < i, f"rid {rid}"
                done_idx.pop(rid)
                checked += 1
    assert checked > 0


def test_fleet_bench_cli_disagg_e2e_trace_explain_and_gate(tmp_path):
    """`mctpu fleet-bench --pools` -> trace -> explain -> compare round
    trip: the disagg run's telemetry reconstructs consistently across
    the handoff, blame conserves, and two identical-seed runs pass the
    CI disagg gate (exact equality on the handoff / degradation / blame
    counters) while a different seed fails it."""
    import os

    from mpi_cuda_cnn_tpu.obs.causal import explain_main
    from mpi_cuda_cnn_tpu.obs.regress import compare_main
    from mpi_cuda_cnn_tpu.obs.timeline import trace_main
    from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main

    args = ["--pools", "prefill:2,decode:2", "--handoff-ticks", "2",
            "--requests", "80", "--rate", "500",
            "--fault-plan",
            "replica_crash@fleet.tick:30?replica=0&zombie_ticks=2",
            "--seed", "3"]
    runs = []
    for tag in ("a", "b"):
        path = str(tmp_path / f"disagg_{tag}.jsonl")
        assert fleet_bench_main([*args, "--metrics-jsonl", path]) == 0
        runs.append(path)
    assert trace_main([runs[0]]) == 0
    assert explain_main([runs[0]]) == 0

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    gate = os.path.join(repo, "ci", "disagg_gate.json")
    assert compare_main([*runs, "--gate", gate]) == 0

    drifted = str(tmp_path / "disagg_c.jsonl")
    assert fleet_bench_main([*args[:-1], "4",
                             "--metrics-jsonl", drifted]) == 0
    assert compare_main([runs[0], drifted, "--gate", gate]) == 1


def test_fleet_bench_cli_rejects_bad_pools_and_sites():
    from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main

    assert fleet_bench_main(["--pools", "prefill:2"]) == 2
    with pytest.raises(SystemExit) as exc:
        fleet_bench_main(["--fault-plan", "handoff_drop@serve.tick:3"])
    assert exc.value.code == 2
    # The new kinds/sites validate per surface: fleet-bench accepts
    # them, serve-bench does not; raising kinds are not registered at
    # the polled sites (they would be inert there).
    plan = parse_plan("handoff_drop@fleet.handoff:1;"
                      "kv_corrupt@fleet.handoff:2?page=1;"
                      "kv_corrupt@fleet.resume:0;"
                      "pool_crash@fleet.tick:5?pool=decode")
    validate_plan_sites(plan, "fleet-bench")
    with pytest.raises(ValueError):
        validate_plan_sites(plan, "serve-bench")
    with pytest.raises(ValueError):
        validate_plan_sites(parse_plan("crash@fleet.handoff:1"),
                            "fleet-bench")
    with pytest.raises(ValueError):
        validate_plan_sites(parse_plan("handoff_drop@fleet.resume:1"),
                            "fleet-bench")


def test_pool_crash_on_unified_fleet_errors_loudly():
    """The inert-fault contract: pool-scoped faults on a fleet with no
    pools must raise at fire time, never silently no-op."""
    fleet = unified_fleet(plan="pool_crash@fleet.tick:5?pool=decode")
    with pytest.raises(ValueError, match="disaggregated"):
        fleet.run(workload(n=40))


def test_handoff_faults_on_unified_fleet_refused_at_construction():
    """fleet.handoff/fleet.resume are POLLED sites that only a pooled
    fleet reaches: a unified fleet must refuse such a plan up front
    (the silent-never-fires class the SITES validator exists for),
    both at the library layer and through the CLI."""
    from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main

    with pytest.raises(ValueError, match="silently never fire"):
        unified_fleet(plan="handoff_drop@fleet.handoff:0")
    with pytest.raises(ValueError, match="silently never fire"):
        unified_fleet(plan="kv_corrupt@fleet.handoff:0?page=1")
    assert fleet_bench_main(["--requests", "8", "--fault-plan",
                             "handoff_drop@fleet.handoff:0"]) == 1
    # fleet.resume stays legal on a unified fleet — failover resume
    # re-dispatches exist there (the backfill satellite's own test
    # drives it); only the never-reached handoff site is refused.
    unified_fleet(plan="kv_corrupt@fleet.resume:0")
    # ... but NOT under discard re-dispatch, which carries no
    # committed context to corrupt: refused up front, same contract.
    with pytest.raises(ValueError, match="silently never fire"):
        unified_fleet(plan="kv_corrupt@fleet.resume:0",
                      redispatch="discard")
    # --handoff-ticks without --pools would be silently ignored:
    # loud config error instead.
    assert fleet_bench_main(["--requests", "8",
                             "--handoff-ticks", "3"]) == 2


def test_degraded_unified_counts_unique_requests():
    """A request that degrades repeatedly (handoff aborted for an
    empty decode pool, then degraded again when its re-prefill
    completes against the still-empty pool) counts ONCE — the summary
    key means 'requests served unified', not 'degradation events'."""
    fleet = disagg_fleet(
        pools={"prefill": 2, "decode": 1},
        plan="replica_crash@fleet.tick:30?replica=2",
        handoff_ticks=4, backoff_base=2.0)  # long decode outage
    res = fleet.run(workload(n=200))
    assert res.degraded_unified > 0
    assert res.degraded_unified <= len(res.requests)
    assert all(r.terminal for r in res.requests)
    for r in res.finished_requests():
        assert r.out == expected_out(r)


def test_disagg_summary_and_handoff_records_schema():
    from mpi_cuda_cnn_tpu.obs.schema import make_record, validate_record

    res = disagg_fleet(plan=CRASH_PLAN).run(workload(n=150))
    s = json.loads(json.dumps(res.summary()))
    assert s["handoffs"] == res.handoffs > 0
    assert s["pools"] == {"prefill": 2, "decode": 2}
    for key in ("handoff_pages", "handoffs_aborted", "kv_refusals",
                "degraded_unified"):
        assert key in s
    for rec in res.handoff_log:
        validate_record(make_record("handoff", 0.0, **rec))
    # A unified fleet stamps the same keys as zeros (the gate contract:
    # every gated metric exists in every fleet-bench run).
    u = unified_fleet().run(workload(n=50))
    su = u.summary()
    assert su["handoffs"] == 0 and su["kv_refusals"] == 0
    assert "pools" not in su


# ------------------------------------------------- engine-backed parity


@pytest.mark.parametrize("prefix", [False, True])
def test_engine_disagg_outputs_match_unified_through_handoff(prefix):
    """The model-backed twin (one PagedEngine per replica, shared
    weights): KV pages handed prefill->decode through the cross-engine
    page copy decode to BITWISE the same tokens as the unified fleet —
    with prefix sharing on, the parity holds THROUGH a handoff whose
    block table leads with shared tree pages (the handoff-interleaved
    sharing case)."""
    import jax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
    from mpi_cuda_cnn_tpu.serve.fleet import EngineCompute

    model = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)
    params = model.init(jax.random.key(0))
    geom = dict(slots=2, num_pages=17, page_size=4, max_len=48)

    def reqs():
        return make_fleet_workload(n=14, vocab=13, prompt_min=6,
                                   prompt_max=12, out_min=4, out_max=10,
                                   rate=300.0, seed=3,
                                   prefix_mix=0.7 if prefix else 0.0)

    def factory(name):
        return EngineCompute(PagedEngine(model, params, prefill_chunk=8,
                                         **geom))

    disagg = Fleet(factory, pools={"prefill": 1, "decode": 1},
                   handoff_ticks=2, prefix=prefix, **geom).run(reqs())
    unified = Fleet(factory, replicas=2, prefix=prefix,
                    **geom).run(reqs())
    assert disagg.handoffs > 0
    assert disagg.status_counts() == {"finished": 14}
    assert disagg.outputs() == unified.outputs()
    if prefix:
        assert disagg.prefix["prefix_hits"] > 0
        # Sharing on vs off stays bitwise THROUGH the handoff.
        plain = Fleet(factory, pools={"prefill": 1, "decode": 1},
                      handoff_ticks=2, prefix=False, **geom).run(reqs())
        assert plain.outputs() == disagg.outputs()
