"""`mctpu chaos` — seeded fault-schedule search (ISSUE 19).

THE acceptance tests live here:
- plan grammar round trip: `faults.format_plan` is the exact inverse
  of `faults.parse_plan`, so every sampled schedule is a one-line
  `--fault-plan` repro;
- sampler contract: draws are seed-stable, always validate against the
  live fleet-bench site registry, and the axes sampler covers the
  whole prefix/spec/disagg/spill/autoscale matrix;
- clean episodes pass the FULL oracle (terminal-exactly-once,
  closed-form outputs, blame conservation, pool/tier clean exit,
  zero-drift replay, bitwise re-run);
- chaos CLI determinism: two identical-seed searches emit byte-equal
  record files and pass the CI gate (ci/chaos_gate.json) at 0%/equal;
- plant-a-bug: with the test-only skip-revoke toggle armed the search
  FINDS an invariant violation and ddmin-SHRINKS it to a <=2-entry
  minimal plan whose failure really is the plant (the same minimal
  plan passes with the plant off); the ISSUE 20 skip-dedup twin does
  the same for the transport bus's commit dedup, shrinking to a
  ONE-entry msg_dup plan;
- trace-driven replay (ROADMAP item 4): `--trace FILE` rebuilds a
  recorded request trail geometry-exact (ids, budgets, arrivals,
  tenants) on both benches, deterministically.
"""

import dataclasses
import json
import random
from pathlib import Path

import pytest

from mpi_cuda_cnn_tpu.chaos.cli import chaos_main
from mpi_cuda_cnn_tpu.chaos.episode import (
    EpisodeConfig,
    config_for,
    run_episode,
)
from mpi_cuda_cnn_tpu.chaos.sampler import (
    RAISING_KINDS,
    SURFACE,
    EpisodeAxes,
    sample_axes,
    sample_plan,
)
from mpi_cuda_cnn_tpu.chaos.shrink import shrink
from mpi_cuda_cnn_tpu.faults import (
    SITES,
    Fault,
    format_fault,
    format_plan,
    parse_plan,
    validate_plan_sites,
)
from mpi_cuda_cnn_tpu.obs.regress import compare_main
from mpi_cuda_cnn_tpu.serve.bench import (
    fleet_bench_main,
    load_trace,
    requests_from_trace,
    serve_bench_main,
)
from mpi_cuda_cnn_tpu.serve.fleet import make_fleet_workload

REPO = Path(__file__).resolve().parents[1]


# ------------------------------------------------- plan grammar round trip


def test_format_fault_spells_args_sorted():
    f = Fault(kind="replica_crash", site="fleet.tick", at=40,
              args={"zombie_ticks": 3, "replica": 1})
    assert (format_fault(f)
            == "replica_crash@fleet.tick:40?replica=1&zombie_ticks=3")
    assert format_fault(Fault(kind="io", site="fleet.tick", at=7,
                              args={})) == "io@fleet.tick:7"


def test_format_plan_round_trips_parse_plan():
    spec = ("replica_crash@fleet.tick:40?replica=1&zombie_ticks=3;"
            "kv_corrupt@fleet.handoff:2?page=1;"
            "replica_join@fleet.tick:90")
    plan = parse_plan(spec)
    assert parse_plan(format_plan(plan)) == plan
    # Idempotent spelling: formatting the re-parse changes nothing.
    assert format_plan(parse_plan(format_plan(plan))) == format_plan(plan)


def test_sampled_plans_round_trip_and_validate():
    """Property over the sampler's own draws: every sampled plan
    re-parses to an identical Fault list and passes the same registry
    validation `--fault-plan` applies at parse time."""
    for seed in range(40):
        rng = random.Random(f"round-trip:{seed}")
        axes = sample_axes(rng)
        spec = sample_plan(rng, axes, replicas=3)
        plan = parse_plan(spec)
        assert plan, spec
        assert format_plan(plan) == spec
        validate_plan_sites(plan, SURFACE)
        assert not any(f.kind in RAISING_KINDS for f in plan)


# --------------------------------------------------------- sampler contract


def test_sampler_seed_stable_and_covers_axes_matrix():
    rng_a, rng_b = random.Random("pin:1"), random.Random("pin:1")
    axes_a, axes_b = sample_axes(rng_a), sample_axes(rng_b)
    assert axes_a == axes_b
    assert sample_plan(rng_a, axes_a, replicas=3) == \
        sample_plan(rng_b, axes_b, replicas=3)
    # 50 draws must cover the whole episode-axes matrix (the ISSUE 19
    # CI run is 50 episodes — this pins that scale actually reaches
    # every axis).
    seen = {"pools": False, "unified": False, "prefix": False,
            "spill": False, "spec": False, "autoscale": False,
            "transport": False}
    for ep in range(50):
        axes = sample_axes(random.Random(f"mctpu-chaos:7:{ep}"))
        seen["pools"] |= axes.pools is not None
        seen["unified"] |= axes.pools is None
        seen["prefix"] |= axes.prefix
        seen["spill"] |= axes.spill
        seen["spec"] |= axes.spec != "off"
        seen["autoscale"] |= axes.autoscale
        seen["transport"] |= axes.transport
        if axes.spill:
            assert axes.prefix  # spill without the prefix tree is inert
        if axes.transport:
            # transport + pools is a Fleet constructor error (the
            # handoff plane is not bus-routed) — never samplable.
            assert axes.pools is None
    assert all(seen.values()), seen


def test_sampler_gates_sites_on_topology():
    """Unified episodes must never draw handoff/pool faults (the fleet
    rejects them as inert at construction) and spill-off episodes must
    never draw tier faults (they would silently not fire)."""
    for seed in range(30):
        rng = random.Random(f"gate:{seed}")
        plan = parse_plan(sample_plan(
            rng, EpisodeAxes(pools=None, prefix=True, spill=False),
            replicas=3))
        for f in plan:
            assert f.site == "fleet.tick" or f.site == "fleet.resume"
            assert f.kind != "pool_crash"
    # Transport-off axes never draw fleet.transport faults (inert at
    # construction); transport-on axes do reach the site.
    reached = False
    for seed in range(30):
        rng = random.Random(f"tgate:{seed}")
        plan = parse_plan(sample_plan(
            rng, EpisodeAxes(pools=None, transport=True), replicas=3))
        reached |= any(f.site == "fleet.transport" for f in plan)
    assert reached


# ------------------------------------------------------------- the oracle


def test_clean_episode_passes_full_oracle():
    cfg = config_for(
        11, "replica_crash@fleet.tick:9?replica=1;"
            "replica_join@fleet.tick:30;kv_corrupt@fleet.resume:0",
        EpisodeAxes(pools=None, prefix=True, spill=True,
                    spec="lookup", autoscale=True))
    res = run_episode(cfg)
    assert res.ok, res.violations
    assert res.row["replay_ticks"] > 0
    assert res.row["faults"] == 3
    for k in ("trace_crc", "state_crc", "blame_crc", "episode_crc"):
        assert isinstance(res.row[k], int)


def test_shrink_refuses_a_passing_episode():
    cfg = EpisodeConfig(seed=3, plan="replica_join@fleet.tick:20")
    with pytest.raises(ValueError, match="passing episode"):
        shrink(cfg)


# -------------------------------------------------- CLI determinism + gate


def test_chaos_cli_determinism_and_gate(tmp_path):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    argv = ["--episodes", "4", "--seed", "7"]
    assert chaos_main(argv + ["--metrics-jsonl", str(a)]) == 0
    assert chaos_main(argv + ["--metrics-jsonl", str(b)]) == 0
    # Byte-equal record files: the chaos timeline is episode-indexed —
    # no wall-clock anywhere in the emit path.
    assert a.read_bytes() == b.read_bytes()
    assert compare_main([str(a), str(b), "--gate",
                         str(REPO / "ci" / "chaos_gate.json")]) == 0


def test_chaos_plan_mode_replays_one_episode(tmp_path):
    out = tmp_path / "one.jsonl"
    rc = chaos_main(["--seed", "5", "--plan",
                     "replica_crash@fleet.tick:12?replica=0",
                     "--prefix", "--metrics-jsonl", str(out)])
    assert rc == 0
    rows = [json.loads(line) for line in out.read_text().splitlines()
            if not line.startswith("#")]
    assert [r["kind"] for r in rows] == ["episode", "summary"]
    assert rows[0]["plan"] == "replica_crash@fleet.tick:12?replica=0"
    assert rows[1]["violations"] == 0


def test_chaos_cli_rejects_bad_config():
    assert chaos_main(["--spill"]) == 2                 # spill sans prefix
    assert chaos_main(["--plan", "nonsense"]) == 2      # bad grammar


# ------------------------------------------------------------ plant-a-bug


def test_planted_bug_found_and_shrunk_to_minimal_plan(tmp_path):
    """THE ISSUE 19 plant-a-bug acceptance: with the test-only
    skip-revoke toggle armed, the seeded search must FIND an invariant
    violation and ddmin-shrink it to a <=2-entry minimal plan — and
    that minimal plan must fail BECAUSE of the plant (same plan, plant
    off, passes the full oracle)."""
    out = tmp_path / "chaos.jsonl"
    trails = tmp_path / "trails"
    rc = chaos_main(["--episodes", "2", "--seed", "7",
                     "--plant", "skip-revoke",
                     "--metrics-jsonl", str(out),
                     "--out-dir", str(trails)])
    assert rc == 1
    rows = [json.loads(line) for line in out.read_text().splitlines()
            if not line.startswith("#")]
    summary = rows[-1]
    assert summary["kind"] == "summary"
    assert summary["violations"] >= 1
    min_plan = summary["min_plan"]
    assert len(parse_plan(min_plan)) <= 2
    assert summary["shrink_probes"] >= 1
    # Both trails of the minimal episode landed, pre-wired for diverge.
    assert (trails / "chaos_min_a.jsonl").exists()
    assert (trails / "chaos_min_b.jsonl").exists()
    # The violation is the plant's, not the schedule's: the SAME
    # minimal episode (same sampled axes, recomputed from the same
    # per-ordinal stream the CLI uses) passes with the toggle off ...
    ep = summary["failed_episode"]
    axes = sample_axes(random.Random(f"mctpu-chaos:7:{ep}"))
    cfg = config_for(7 * 100003 + ep, min_plan, axes)
    assert run_episode(cfg).ok
    # ... and fails (replay drift) with it on.
    planted = run_episode(dataclasses.replace(cfg, plant="skip-revoke"))
    assert {v["check"] for v in planted.violations} == {"replay"}


def test_transport_canary_found_and_shrunk_to_one_entry(tmp_path):
    """The ISSUE 20 plant-a-bug acceptance: with the skip-dedup toggle
    armed (the bus stops deduplicating commit messages), the seeded
    search must catch the exactly-once violation on a transport
    episode — a duplicated commit applies twice, so the authoritative
    output diverges from the SimCompute closed form — and ddmin-shrink
    it to a ONE-entry msg_dup plan. The same minimal plan passes the
    full oracle with the plant off: dedup is load-bearing, and this
    canary proves the oracle would see it break."""
    out = tmp_path / "chaos.jsonl"
    rc = chaos_main(["--episodes", "1", "--seed", "7",
                     "--plant", "skip-dedup",
                     "--metrics-jsonl", str(out)])
    assert rc == 1
    rows = [json.loads(line) for line in out.read_text().splitlines()
            if not line.startswith("#")]
    summary = rows[-1]
    assert summary["violations"] >= 1
    min_plan = parse_plan(summary["min_plan"])
    assert len(min_plan) == 1
    assert min_plan[0].kind == "msg_dup"
    assert min_plan[0].site == "fleet.transport"
    ep = summary["failed_episode"]
    axes = sample_axes(random.Random(f"mctpu-chaos:7:{ep}"))
    assert axes.transport
    cfg = config_for(7 * 100003 + ep, summary["min_plan"], axes)
    assert run_episode(cfg).ok
    planted = run_episode(dataclasses.replace(cfg, plant="skip-dedup"))
    assert "outputs" in {v["check"] for v in planted.violations}


# ------------------------------------------------- trace-driven replay (b)


def _record_fleet_trail(path, *, tenants=2, requests=16):
    rc = fleet_bench_main([
        "--requests", str(requests), "--replicas", "2", "--rate", "40",
        "--vocab", "64", "--prompt-min", "4", "--prompt-max", "40",
        "--out-min", "4", "--out-max", "16",
        "--tenants", str(tenants), "--compute", "sim",
        "--metrics-jsonl", str(path)])
    assert rc == 0


def test_load_trace_rebuilds_geometry_exactly(tmp_path):
    trail = tmp_path / "trail.jsonl"
    _record_fleet_trail(trail)
    rows = load_trace(str(trail))
    want = make_fleet_workload(n=16, vocab=64, prompt_min=4,
                               prompt_max=40, out_min=4, out_max=16,
                               rate=40.0, seed=0, tenants=2)
    assert len(rows) == len(want)
    for row, req in zip(rows, sorted(want, key=lambda r: r.arrival)):
        assert row["id"] == req.rid
        assert row["prompt_tokens"] == int(req.prompt.size)
        assert row["max_new_tokens"] == req.max_new_tokens
        assert row["arrival_s"] == pytest.approx(req.arrival, abs=5e-4)
        assert row["tenant"] == req.tenant
    reqs = requests_from_trace(rows, vocab=64, seed=0)
    assert [r.rid for r in reqs] == [row["id"] for row in rows]
    assert all(int(r.prompt.size) == row["prompt_tokens"]
               for r, row in zip(reqs, rows))
    # Fresh objects per call — the per-mode regeneration contract.
    again = requests_from_trace(rows, vocab=64, seed=0)
    assert all(x is not y for x, y in zip(reqs, again))


def test_fleet_bench_trace_replay_deterministic(tmp_path):
    trail = tmp_path / "trail.jsonl"
    _record_fleet_trail(trail)
    a, b = tmp_path / "ra.jsonl", tmp_path / "rb.jsonl"
    argv = ["--trace", str(trail), "--replicas", "2", "--compute", "sim",
            "--log", "summary"]
    assert fleet_bench_main(argv + ["--metrics-jsonl", str(a)]) == 0
    assert fleet_bench_main(argv + ["--metrics-jsonl", str(b)]) == 0

    def summary_of(p):
        recs = [json.loads(line) for line in p.read_text().splitlines()
                if not line.startswith("#")]
        return next(r for r in recs if r.get("event") == "serve")

    sa, sb = summary_of(a), summary_of(b)
    assert sa["requests"] == 16
    assert sa["trace_crc"] == sb["trace_crc"]
    assert sa["state_crc"] == sb["state_crc"]


def test_trace_loud_config_errors(tmp_path):
    trail = tmp_path / "trail.jsonl"
    trail.write_text("")  # empty: no request records
    assert fleet_bench_main(["--trace", str(trail)]) == 2
    assert serve_bench_main(["--trace", str(trail),
                             "--prefix-mix", "0.5"]) == 2
    assert fleet_bench_main(["--trace", str(trail),
                             "--prefix-mix", "0.5"]) == 2
    assert serve_bench_main(["--trace", str(tmp_path / "absent.jsonl")
                             ]) == 2
