"""Grouped-query attention + rotary embeddings (round-2 capability).

GQA contract: q (B,S,H,D) with k/v (B,S,Hkv,D), Hkv | H — the oracle
computes it by group reshape, the flash kernel zero-copy via block index
maps, the ring/Ulysses SP bodies by fold-time repeat. RoPE: explicit
absolute positions (SP-shard-exact), f32 angles, no position table.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.ops.attention import attention, repeat_kv, rope
from mpi_cuda_cnn_tpu.ops.pallas_attention import flash_attention


def _qkv(b, s, h, hkv, d, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype)
    return q, k, v


@pytest.mark.parametrize("hkv", [1, 2])
def test_gqa_oracle_matches_repeated_mha(hkv):
    """GQA == MHA with kv heads explicitly repeated per group."""
    q, k, v = _qkv(2, 64, 4, hkv, 32)
    got = attention(q, k, v, causal=True)
    want = attention(q, repeat_kv(k, 4), repeat_kv(v, 4), causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("hkv", [1, 2])
@pytest.mark.parametrize("causal", [False, True])
def test_gqa_flash_matches_oracle(hkv, causal):
    q, k, v = _qkv(1, 256, 4, hkv, 64, seed=1)
    got = flash_attention(q, k, v, causal)
    want = attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gqa_flash_gradients_match_oracle():
    q, k, v = _qkv(1, 128, 4, 2, 64, seed=2)

    def f(fn):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    got = f(lambda q, k, v: flash_attention(q, k, v, True))
    want = f(lambda q, k, v: attention(q, k, v, causal=True))
    for a, b in zip(got, want):
        assert a.shape == b.shape  # dk/dv keep the Hkv head count
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_rope_properties():
    """Relative-position property: the attention score between two
    rotated vectors depends only on their position DIFFERENCE."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((1, 1, 1, 32)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((1, 1, 1, 32)), jnp.float32)

    def score(px, py):
        xr = rope(x, jnp.array([px]))
        yr = rope(y, jnp.array([py]))
        return float(jnp.sum(xr * yr))

    assert score(3, 7) == pytest.approx(score(10, 14), abs=1e-4)
    assert score(0, 4) == pytest.approx(score(100, 104), abs=1e-4)
    # Position 0 is the identity rotation.
    np.testing.assert_allclose(
        np.asarray(rope(x, jnp.array([0]))), np.asarray(x), atol=1e-6
    )


@pytest.mark.parametrize("kv_heads,pos", [(2, "learned"), (0, "rope"),
                                          (2, "rope"), (1, "rope")])
def test_lm_variants_train_and_decode(kv_heads, pos):
    """Every (GQA, RoPE) variant trains (loss drops on the cyclic task)
    and its KV-cache decode matches the teacher-forced forward."""
    import optax

    from mpi_cuda_cnn_tpu.models.generate import decode_step, init_cache

    model = TransformerLM(vocab=17, dim=32, heads=4, depth=2, max_seq=64,
                          kv_heads=kv_heads, pos=pos)
    params = model.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    start = rng.integers(0, 17, size=(4, 1))
    toks = jnp.asarray((start + np.arange(33)) % 17, jnp.int32)
    inputs, targets = toks[:, :-1], toks[:, 1:]

    def loss_fn(p):
        logits = model.apply(p, inputs)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], -1))

    opt = optax.adam(3e-3)
    state = opt.init(params)
    step = jax.jit(lambda p, s: _upd(p, s, loss_fn, opt))
    l0 = float(loss_fn(params))
    for _ in range(60):
        params, state, l = step(params, state)
    assert float(l) < l0 * 0.7

    # Cache shape reflects GQA; decode == teacher-forced forward.
    cache = init_cache(model, 4)
    assert cache[0]["k"].shape[2] == model.n_kv
    want = model.apply(params, inputs)
    got = []
    for i in range(8):
        logits, cache = decode_step(model, params, inputs[:, i], i, cache)
        got.append(logits)
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want[:, :8]),
                               rtol=2e-4, atol=2e-4)


def _upd(params, state, loss_fn, opt):
    import optax

    l, g = jax.value_and_grad(loss_fn)(params)
    u, state = opt.update(g, state, params)
    return optax.apply_updates(params, u), state, l


def test_gqa_rope_under_ring_sp():
    """GQA + RoPE composes with ring sequence parallelism: SP step loss
    == single-device loss (absolute positions via pos_offset feed rope)."""
    import optax

    from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
    from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS, make_sp_lm_train_step
    from mpi_cuda_cnn_tpu.train.lm import lm_loss

    model = TransformerLM(vocab=17, dim=32, heads=8, depth=2, max_seq=64,
                          kv_heads=2, pos="rope")
    params = model.init(jax.random.key(0))
    mesh = make_mesh({SEQ_AXIS: 8}, devices=jax.devices()[:8])
    opt = optax.sgd(0.1)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    step = make_sp_lm_train_step(model, opt, mesh, impl="ring", donate=False)
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(0, 17, (2, 65)), jnp.int32)
    want = float(lm_loss(model, params, toks[:, :-1], toks[:, 1:],
                         moe_aux_weight=0.0))
    _, m = step(state, toks[:, :-1], toks[:, 1:])
    assert float(m["loss"]) == pytest.approx(want, rel=1e-5)


def test_default_init_stream_unchanged():
    """The GQA/RoPE init refactor must not shift the default config's
    key stream: replay the DOCUMENTED round-1 draw order (tok_emb, pos,
    head, then per block qkv, wo, w1, w2 — init()'s key-budget
    contract) with jax.random directly and demand bitwise equality. A
    reorder of init's key draws fails here even though both calls run
    the same code. Unlike the original hard-coded golden floats
    (captured under a different jax RNG implementation than this
    container's 0.4.37 — a permanent seed failure), the replay is
    RNG-implementation-independent: both sides draw from the SAME
    installed generator."""
    import math

    m = TransformerLM(vocab=8, dim=16, heads=4, depth=1, max_seq=16)
    p = m.init(jax.random.key(42))
    keys = jax.random.split(jax.random.key(42), 3 + 4 * m.depth)
    scale = 1.0 / math.sqrt(m.dim)
    want = {
        "tok_emb": jax.random.normal(keys[0], (m.vocab, m.dim)) * scale,
        "pos_emb": jax.random.normal(keys[1], (m.max_seq, m.dim)) * scale,
        "head": jax.random.normal(keys[2], (m.dim, m.vocab)) / math.sqrt(m.dim),
        "wqkv": jax.random.normal(keys[3], (m.dim, 3 * m.dim))
        / math.sqrt(m.dim),
        "wo": jax.random.normal(keys[4], (m.dim, m.dim)) / math.sqrt(m.dim),
        "w1": jax.random.normal(keys[5], (m.dim, 4 * m.dim))
        / math.sqrt(m.dim),
        "w2": jax.random.normal(keys[6], (4 * m.dim, m.dim))
        / math.sqrt(4 * m.dim),
    }
    got = {
        "tok_emb": p["tok_emb"],
        "pos_emb": p["pos_emb"],
        "head": p["head"],
        "wqkv": p["blocks"][0]["wqkv"],
        "wo": p["blocks"][0]["wo"],
        "w1": p["blocks"][0]["w1"],
        "w2": p["blocks"][0]["w2"],
    }
    for name in want:
        np.testing.assert_array_equal(
            np.asarray(got[name]), np.asarray(want[name]), err_msg=name
        )


def test_kv_heads_must_be_positive_divisor():
    with pytest.raises(ValueError, match="positive divisor"):
        TransformerLM(heads=4, kv_heads=-1).n_kv
    with pytest.raises(ValueError, match="positive divisor"):
        TransformerLM(heads=4, kv_heads=3).n_kv


def test_gqa_rope_under_ring_flash_sp():
    """GQA + RoPE under ring_FLASH SP — the composition the docs steer
    GQA models to (the ring rotates the small Hkv buffers; the flash
    kernel serves them zero-copy). Loss AND gradients must match the
    single-device oracle."""
    import optax

    from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
    from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS, make_sp_lm_train_step
    from mpi_cuda_cnn_tpu.train.lm import lm_loss

    model = TransformerLM(vocab=17, dim=32, heads=8, depth=1, max_seq=256,
                          kv_heads=2, pos="rope")
    params = model.init(jax.random.key(0))
    mesh = make_mesh({SEQ_AXIS: 2}, devices=jax.devices()[:2])
    opt = optax.sgd(0.1)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    # s_local = 128 satisfies the flash block constraint on each shard.
    step = make_sp_lm_train_step(model, opt, mesh, impl="ring_flash",
                                 donate=False)
    rng = np.random.default_rng(2)
    toks = jnp.asarray(rng.integers(0, 17, (2, 257)), jnp.int32)
    want_loss, want_grads = jax.value_and_grad(
        lambda p: lm_loss(model, p, toks[:, :-1], toks[:, 1:],
                          moe_aux_weight=0.0)
    )(params)
    new_state, m = step(state, toks[:, :-1], toks[:, 1:])
    assert float(m["loss"]) == pytest.approx(float(want_loss), rel=1e-4)
    # Updated params = params - 0.1 * grads: compare through the update.
    import jax as _jax

    for a, b, p0 in zip(
        _jax.tree.leaves(new_state["params"]),
        _jax.tree.leaves(want_grads),
        _jax.tree.leaves(params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(p0) - 0.1 * np.asarray(b),
            rtol=1e-3, atol=1e-5,
        )


def test_gqa_under_ulysses_sp():
    """GQA under Ulysses all-to-all SP (the kv expand-then-shard branch):
    output must match the single-device GQA oracle."""
    from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
    from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS, make_ulysses_attention

    mesh = make_mesh({SEQ_AXIS: 4}, devices=jax.devices()[:4])
    q, k, v = _qkv(2, 64, 8, 2, 16, seed=6)
    fn = make_ulysses_attention(mesh)
    got = fn(q, k, v, causal=True)
    want = attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_grad_clip_bounds_update():
    """make_optimizer(grad_clip=c): the applied update's global norm is
    bounded by lr * c (adamw scales elementwise, so use sgd for an exact
    bound), and grad_clip=0 leaves gradients untouched."""
    import optax

    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer

    g = {"w": jnp.full((4, 4), 100.0)}
    params = {"w": jnp.zeros((4, 4))}
    tx = make_optimizer(0.1, opt="sgd", grad_clip=1.0)
    upd, _ = tx.update(g, tx.init(params), params)
    norm = float(optax.global_norm(upd))
    assert norm <= 0.1 + 1e-6
    tx0 = make_optimizer(0.1, opt="sgd", grad_clip=0.0)
    upd0, _ = tx0.update(g, tx0.init(params), params)
    np.testing.assert_allclose(np.asarray(upd0["w"]), -10.0, rtol=1e-6)
