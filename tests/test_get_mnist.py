"""The get_mnist poisoned-cache path (VERDICT round-5 weak #1): the
synthetic fallback must never be mistaken for real MNIST by a later run
— not by the fetcher's own `dest.exists()` cache check, and not by the
CLI loading the files.
"""

import gzip
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "scripts"))

import get_mnist  # noqa: E402  (scripts/get_mnist.py)

from mpi_cuda_cnn_tpu.data.datasets import (  # noqa: E402
    load_idx_dataset,
    synthetic_stripes,
    write_synthetic_idx,
)
from mpi_cuda_cnn_tpu.data.idx import IdxError, write_idx  # noqa: E402


def _tiny_synth(monkeypatch):
    """Shrink the fallback generator so the test doesn't build 60k
    images; the poisoning mechanics are size-independent."""
    real = synthetic_stripes

    def small(num_train=60_000, num_test=10_000, **kw):
        return real(num_train=64, num_test=16, **kw)

    # Patch BOTH import sites: the fetcher's fallback/hasher and any
    # direct callers in this test.
    import mpi_cuda_cnn_tpu.data.datasets as dsmod

    monkeypatch.setattr(dsmod, "synthetic_stripes", small)


def _fail_fetch(monkeypatch):
    def boom(url, timeout=0):
        raise OSError("no network in test")

    monkeypatch.setattr(get_mnist.urllib.request, "urlopen", boom)


def _fake_real_fetch(monkeypatch):
    """urlopen returning gzip'd fake-but-'real' IDX bytes (distinct from
    the synthetic fallback's)."""
    rng = np.random.default_rng(99)

    class Resp:
        def __init__(self, name):
            import tempfile

            shape = (8, 28, 28) if "images" in name else (8,)
            arr = rng.integers(0, 255, shape).astype(np.uint8)
            with tempfile.NamedTemporaryFile(suffix=".idx") as f:
                write_idx(f.name, arr)
                raw = Path(f.name).read_bytes()
            self._data = gzip.compress(raw)

        def read(self):
            return self._data

    def fake(url, timeout=0):
        name = url.rsplit("/", 1)[1].removesuffix(".gz")
        return Resp(name)

    monkeypatch.setattr(get_mnist.urllib.request, "urlopen", fake)


def test_fallback_writes_sentinel_and_refetch_replaces(tmp_path, monkeypatch):
    _tiny_synth(monkeypatch)
    _fail_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path)) == 0
    sentinel = tmp_path / get_mnist.SENTINEL
    assert sentinel.exists(), "synthetic fallback must mark the directory"
    poisoned_bytes = (tmp_path / get_mnist.FILES[0]).read_bytes()

    # Second run WITH network: the sentinel makes it ignore dest.exists()
    # — every file is re-fetched and the sentinel cleared.
    _fake_real_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path)) == 0
    assert not sentinel.exists()
    assert (tmp_path / get_mnist.FILES[0]).read_bytes() != poisoned_bytes


def test_legacy_poisoned_cache_detected_by_hash(tmp_path, monkeypatch):
    """A cache written by the PRE-sentinel fallback (synthetic bytes at
    the REAL fallback size, no marker) must still be recognized — via
    the recorded SYNTHETIC_SHA256S constants — and replaced. Also pins
    the constants against the deterministic generator itself, so numpy
    stream drift in a new container fails loudly here rather than
    silently weakening legacy detection."""
    ds = synthetic_stripes(num_train=60_000, num_test=10_000)
    paths = write_synthetic_idx(tmp_path, ds)  # what the old fallback did
    for p in paths.values():
        assert get_mnist._sha256(p) == get_mnist.SYNTHETIC_SHA256S[p.name]
    assert not (tmp_path / get_mnist.SENTINEL).exists()

    _fake_real_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path)) == 0
    # Files replaced: hashes no longer match the synthetic generator.
    for name in get_mnist.FILES:
        assert get_mnist._sha256(tmp_path / name) != \
            get_mnist.SYNTHETIC_SHA256S[name]


def test_real_cache_is_kept(tmp_path, monkeypatch):
    _tiny_synth(monkeypatch)
    _fake_real_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path)) == 0
    stamps = {n: (tmp_path / n).read_bytes() for n in get_mnist.FILES}

    _fail_fetch(monkeypatch)  # cached real files: no fetch needed
    assert get_mnist.main(str(tmp_path)) == 0
    assert not (tmp_path / get_mnist.SENTINEL).exists()
    for n, b in stamps.items():
        assert (tmp_path / n).read_bytes() == b


def test_loader_refuses_sentinel_directory(tmp_path, monkeypatch):
    """`make northstar` reaches load_idx_dataset with the four real
    filenames; a sentinel-marked directory must refuse loudly instead of
    labeling a synthetic run as MNIST."""
    _tiny_synth(monkeypatch)
    _fail_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path)) == 0
    paths = [tmp_path / n for n in get_mnist.FILES]
    with pytest.raises(IdxError, match="SYNTHETIC-DATA"):
        load_idx_dataset("mnist", *paths)
