"""The get_mnist poisoned-cache path (VERDICT round-5 weak #1): the
synthetic fallback must never be mistaken for real MNIST by a later run
— not by the fetcher's own `dest.exists()` cache check, and not by the
CLI loading the files.
"""

import gzip
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "scripts"))

import get_mnist  # noqa: E402  (scripts/get_mnist.py)

from mpi_cuda_cnn_tpu.data.datasets import (  # noqa: E402
    load_idx_dataset,
    synthetic_stripes,
    write_synthetic_idx,
)
from mpi_cuda_cnn_tpu.data.idx import IdxError, write_idx  # noqa: E402


def _tiny_synth(monkeypatch):
    """Shrink the fallback generator so the test doesn't build 60k
    images; the poisoning mechanics are size-independent."""
    real = synthetic_stripes

    def small(num_train=60_000, num_test=10_000, **kw):
        return real(num_train=64, num_test=16, **kw)

    # Patch BOTH import sites: the fetcher's fallback/hasher and any
    # direct callers in this test.
    import mpi_cuda_cnn_tpu.data.datasets as dsmod

    monkeypatch.setattr(dsmod, "synthetic_stripes", small)


def _fail_fetch(monkeypatch):
    def boom(url, timeout=0):
        raise OSError("no network in test")

    monkeypatch.setattr(get_mnist.urllib.request, "urlopen", boom)


def _fake_real_fetch(monkeypatch):
    """urlopen returning gzip'd fake-but-'real' IDX bytes (distinct from
    the synthetic fallback's)."""
    rng = np.random.default_rng(99)

    class Resp:
        def __init__(self, name):
            import tempfile

            shape = (8, 28, 28) if "images" in name else (8,)
            arr = rng.integers(0, 255, shape).astype(np.uint8)
            with tempfile.NamedTemporaryFile(suffix=".idx") as f:
                write_idx(f.name, arr)
                raw = Path(f.name).read_bytes()
            self._data = gzip.compress(raw)

        def read(self):
            return self._data

    def fake(url, timeout=0):
        name = url.rsplit("/", 1)[1].removesuffix(".gz")
        return Resp(name)

    monkeypatch.setattr(get_mnist.urllib.request, "urlopen", fake)


def _nosleep(_s):
    """Retry backoff without the wait (the no-network tests would
    otherwise pay the full exponential-backoff schedule per file)."""


def test_fallback_writes_sentinel_and_refetch_replaces(tmp_path, monkeypatch):
    _tiny_synth(monkeypatch)
    _fail_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path), sleep=_nosleep) == 0
    sentinel = tmp_path / get_mnist.SENTINEL
    assert sentinel.exists(), "synthetic fallback must mark the directory"
    poisoned_bytes = (tmp_path / get_mnist.FILES[0]).read_bytes()

    # Second run WITH network: the sentinel makes it ignore dest.exists()
    # — every file is re-fetched and the sentinel cleared.
    _fake_real_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path)) == 0
    assert not sentinel.exists()
    assert (tmp_path / get_mnist.FILES[0]).read_bytes() != poisoned_bytes


def test_legacy_poisoned_cache_detected_by_hash(tmp_path, monkeypatch):
    """A cache written by the PRE-sentinel fallback (synthetic bytes at
    the REAL fallback size, no marker) must still be recognized — via
    the recorded SYNTHETIC_SHA256S constants — and replaced. Also pins
    the constants against the deterministic generator itself, so numpy
    stream drift in a new container fails loudly here rather than
    silently weakening legacy detection."""
    ds = synthetic_stripes(num_train=60_000, num_test=10_000)
    paths = write_synthetic_idx(tmp_path, ds)  # what the old fallback did
    for p in paths.values():
        assert get_mnist._sha256(p) == get_mnist.SYNTHETIC_SHA256S[p.name]
    assert not (tmp_path / get_mnist.SENTINEL).exists()

    _fake_real_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path)) == 0
    # Files replaced: hashes no longer match the synthetic generator.
    for name in get_mnist.FILES:
        assert get_mnist._sha256(tmp_path / name) != \
            get_mnist.SYNTHETIC_SHA256S[name]


def test_real_cache_is_kept(tmp_path, monkeypatch):
    _tiny_synth(monkeypatch)
    _fake_real_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path)) == 0
    stamps = {n: (tmp_path / n).read_bytes() for n in get_mnist.FILES}

    _fail_fetch(monkeypatch)  # cached real files: no fetch needed
    assert get_mnist.main(str(tmp_path), sleep=_nosleep) == 0
    assert not (tmp_path / get_mnist.SENTINEL).exists()
    for n, b in stamps.items():
        assert (tmp_path / n).read_bytes() == b


def test_fetch_retries_flaky_opener_with_backoff():
    """ISSUE 4 satellite: a transient mirror failure must be retried
    with exponential backoff + jitter, via an injected flaky opener —
    no monkeypatching, no network, no real sleeping."""

    class Flaky:
        def __init__(self, fail_times):
            self.fail_times = fail_times
            self.calls = 0

        def __call__(self, url, timeout=0):
            self.calls += 1
            if self.calls <= self.fail_times:
                raise OSError(f"flaky failure {self.calls}")

            class Resp:
                def read(self_inner):
                    return b"payload"

            return Resp()

    delays = []
    opener = Flaky(fail_times=2)
    data = get_mnist.fetch_with_retry(
        "http://mirror/x.gz", opener=opener, tries=3,
        base_delay=0.5, sleep=delays.append, jitter=lambda: 0.5,
    )
    assert data == b"payload"
    assert opener.calls == 3
    # Exponential backoff with the fixed jitter: 0.5*2^0*1.5, 0.5*2^1*1.5.
    assert delays == [0.75, 1.5]

    # Exhausted tries re-raise the LAST error; sleeps stop after the
    # final attempt (two retries -> two waits).
    delays2 = []
    always = Flaky(fail_times=99)
    with pytest.raises(OSError, match="flaky failure 3"):
        get_mnist.fetch_with_retry(
            "http://mirror/x.gz", opener=always, tries=3,
            base_delay=0.5, sleep=delays2.append, jitter=lambda: 0.0,
        )
    assert always.calls == 3
    assert delays2 == [0.5, 1.0]


def test_main_recovers_from_transient_mirror_failures(tmp_path, monkeypatch):
    """main() threads the injected opener through: a mirror flaky ONCE
    per URL still yields a full real fetch (no synthetic fallback)."""
    _tiny_synth(monkeypatch)
    _fake_real_fetch(monkeypatch)
    import urllib.request as _ur

    real = _ur.urlopen  # the patched fake-real fetch

    calls = {}

    def flaky_once(url, timeout=0):
        n = calls.get(url, 0)
        calls[url] = n + 1
        if n == 0:
            raise OSError("transient mirror hiccup")
        return real(url, timeout=timeout)

    assert get_mnist.main(str(tmp_path), opener=flaky_once,
                          sleep=_nosleep) == 0
    # Every file fetched for real despite each URL failing once: no
    # sentinel, real bytes present.
    assert not (tmp_path / get_mnist.SENTINEL).exists()
    for name in get_mnist.FILES:
        assert (tmp_path / name).exists()


def test_loader_refuses_sentinel_directory(tmp_path, monkeypatch):
    """`make northstar` reaches load_idx_dataset with the four real
    filenames; a sentinel-marked directory must refuse loudly instead of
    labeling a synthetic run as MNIST."""
    _tiny_synth(monkeypatch)
    _fail_fetch(monkeypatch)
    assert get_mnist.main(str(tmp_path), sleep=_nosleep) == 0
    paths = [tmp_path / n for n in get_mnist.FILES]
    with pytest.raises(IdxError, match="SYNTHETIC-DATA"):
        load_idx_dataset("mnist", *paths)
