"""Augmentation (data/augment.py): unit semantics + trainer integration.

The reference has no augmentation (its pipeline is normalize + one-hot,
cnn.c:457-464); these tests cover the capability added for the north-star
accuracy target."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.data.augment import make_augment


def _batch(n=4, h=8, w=8, c=1, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).random((n, h, w, c)).astype(np.float32)
    )


def test_none_returns_none():
    assert make_augment("none") is None


def test_unknown_spec_raises():
    with pytest.raises(ValueError):
        make_augment("cutmix")


def test_shift_preserves_shape_dtype_and_is_deterministic():
    aug = make_augment("shift", pad=2)
    x = _batch()
    key = jax.random.key(7)
    y1, y2 = aug(key, x), aug(key, x)
    assert y1.shape == x.shape and y1.dtype == x.dtype
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    y3 = aug(jax.random.key(8), x)
    assert not np.array_equal(np.asarray(y1), np.asarray(y3))


def test_shift_pad0_is_identity():
    aug = make_augment("shift", pad=0)
    x = _batch()
    np.testing.assert_array_equal(np.asarray(aug(jax.random.key(0), x)), np.asarray(x))


def test_shift_is_a_translation():
    """Every augmented image must equal its source translated by some
    (dy, dx) in [-pad, pad]^2 with zero fill."""
    pad = 2
    aug = make_augment("shift", pad=pad)
    x = _batch(n=8)
    y = np.asarray(aug(jax.random.key(3), x))
    xp = np.pad(np.asarray(x), ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    h, w = x.shape[1], x.shape[2]
    for i in range(x.shape[0]):
        candidates = [
            xp[i, oy : oy + h, ox : ox + w]
            for oy in range(2 * pad + 1)
            for ox in range(2 * pad + 1)
        ]
        assert any(np.array_equal(y[i], c) for c in candidates), f"image {i}"


def test_flip_spec_flips_some_images():
    aug = make_augment("shift-flip", pad=0)  # isolate the flip
    x = _batch(n=64)
    y = np.asarray(aug(jax.random.key(0), x))
    xn = np.asarray(x)
    flipped = sum(
        np.array_equal(y[i], xn[i, :, ::-1, :]) and not np.array_equal(y[i], xn[i])
        for i in range(64)
    )
    kept = sum(np.array_equal(y[i], xn[i]) for i in range(64))
    assert flipped > 0 and kept > 0 and flipped + kept == 64


@pytest.mark.parametrize("scan", [True, False])
def test_trainer_with_augment_converges(scan):
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ds = synthetic_stripes(num_train=512, num_test=128)
    cfg = Config(epochs=2, augment="shift", aug_pad=1, eval_every=0,
                 log_every=10**9, batch_size=32, scan=scan)
    t = Trainer(get_model("reference_cnn"), ds, cfg,
                metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.test_accuracy >= 0.9


def test_trainer_augment_tp_mesh():
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ds = synthetic_stripes(num_train=256, num_test=64)
    cfg = Config(epochs=1, augment="shift", eval_every=0, log_every=10**9,
                 batch_size=32, mesh_shape="data:4,model:2")
    t = Trainer(get_model("reference_cnn"), ds, cfg,
                metrics=MetricsLogger(echo=False))
    em = t.run_epoch(0)
    assert np.isfinite(em["loss"])


def test_trainer_augment_on_pp_mesh_is_deterministic():
    """--augment composes with the pipeline path (applied in the step body
    on the flattened microbatches, keyed by (seed, step) like DP): the run
    trains, and two identical runs draw the identical transform stream."""
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ds = synthetic_stripes(num_train=64, num_test=32)
    cfg = Config(epochs=1, augment="shift", batch_size=32,
                 mesh_shape="pipe:2", seed=5, eval_every=0,
                 log_every=10**9, donate=False)

    def run():
        t = Trainer(get_model("reference_cnn"), ds, cfg,
                    metrics=MetricsLogger(echo=False))
        em = t.run_epoch(0)
        return em, jax.device_get(t.state["flat_params"])

    em1, p1 = run()
    em2, p2 = run()
    assert np.isfinite(em1["loss"])
    assert em1["loss"] == em2["loss"]  # same keyed augment stream
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
