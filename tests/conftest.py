"""Test harness config: run everything on a virtual 8-device CPU mesh.

Must run before the first `import jax` anywhere in the test process —
pytest imports conftest.py first, so setting the env here is sufficient
(SURVEY.md §4: multi-device DP tests runnable without a TPU).
"""

import os

# Force CPU regardless of ambient JAX_PLATFORMS — the suite must run
# identically on a TPU VM and a plain CI box; TPU execution is covered by
# bench.py and the driver's compile checks. This environment pre-imports jax
# at interpreter startup, so env vars alone are too late: also set the jax
# config directly (safe — no backend is initialized yet at conftest time).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Tests measured >=~7s on the CI box (pytest --durations, re-measured
# 2026-07-31). Skipped by default so the round-trip suite stays fast;
# `--runslow` (or `make test_all`) runs everything. Every subsystem
# keeps at least one fast representative in the default set — this list
# only trims the heavy variants (the biggest parity matrices, e2e
# trainer loops, multihost spawns).
SLOW_TESTS = {
    # Round-4 trim (VERDICT r3 item 8: the fast set missed the 5-min
    # bar): heaviest fast tests by measured duration, each with a fast
    # twin remaining — e.g. pp_lm keeps step_matches_serial[mesh_axes0] +
    # ce_chunk parity; tp_sp keeps step_matches_serial[0-learned-
    # mesh_axes0]; tp_pp_lm keeps its mesh_axes0 parity + rejects.
    "test_pp_lm.py::test_lm_trainer_pipeline_e2e",
    "test_pp_lm.py::test_pp_lm_flash_matches_oracle",
    "test_tp_sp.py::test_tp_sp_ring_flash_matches_serial",
    "test_tp_sp.py::test_tp_sp_grad_clip_matches_serial",
    "test_tp_sp.py::test_lm_trainer_tp_sp_e2e",
    "test_lm.py::test_chunked_ce_matches_dense[None]",
    "test_transformer.py::test_sp_step_with_chunked_ce_matches_dense",
    "test_tp_pp.py::test_tp_pp_pack_unpack_roundtrip",
    "test_tp_pp.py::test_trainer_fsdp_tp_matches_pure_dp",
    "test_models.py::test_presets_init_and_apply[lenet5]",
    "test_lm_trainer.py::test_sample_generates_within_budget",
    "test_pp.py::test_pp_loss_and_grads_match_serial[4-8]",
    "test_golden_c.py::test_c_lm_flags_reach_the_lm_trainer",
    "test_gqa_rope.py::test_lm_variants_train_and_decode[2-rope]",
    "test_pallas.py::test_conv_grad_parity[4-28-28-1-3-16-2-1]",
    "test_tp_pp_lm.py::test_tp_pp_lm_step_matches_serial[mesh_axes1-0-learned]",
    "test_tp_pp_lm.py::test_tp_pp_lm_step_matches_serial[mesh_axes2-2-rope]",
    "test_tp_pp_lm.py::test_tp_pp_lm_grad_clip_and_ce_chunk_match_serial",
    "test_tp_pp_lm.py::test_lm_trainer_tp_pp_e2e",
    # Second-tier trim to land the 1-2-core serial bar; every moved test
    # leaves a faster sibling covering the same subsystem (LM TP parity
    # additionally runs in the driver's dryrun path 9 on every round).
    "test_tp.py::test_lm_tp_state_is_sharded_and_step_matches_serial",
    "test_lm_trainer.py::test_cli_lm_subcommand",
    "test_attention.py::test_ring_flash_gradients_match_oracle",
    "test_lm.py::test_bf16_keeps_master_params_f32",
    "test_models.py::test_residual_odd_spatial_downsample",
    "test_pp.py::test_pp_composes_with_dp",
    "test_pp.py::test_pp_grad_clip_matches_optax[mesh_axes0-1-False]",
    "test_train.py::test_scan_chunked_logging",
    "test_train.py::test_bfloat16_training",
    "test_gqa_rope.py::test_lm_variants_train_and_decode[1-rope]",
    "test_pallas.py::test_conv_forward_parity[4-14-14-16-3-32-2-1]",
    "test_pallas.py::test_conv_forward_parity[4-28-28-1-3-16-2-1]",
    "test_tp.py::test_tp_trainer_end_to_end[False]",
    "test_tp.py::test_tp_trainer_matches_dp_trainer",
    "test_fsdp.py::test_fsdp_pp_matches_plain_pp[False-pipe:2,model:2,data:2]",
    # test_pp_lm_grad_clip_matches_serial stays FAST: the LM in-step
    # clip-norm assembly needs a default-suite representative (the
    # tp_sp/tp_pp_lm clip tests here are its slow siblings).
    "test_pp_lm.py::test_pp_lm_ce_chunk_matches_dense",
    "test_pp_lm.py::test_pp_lm_moe_single_microbatch_matches_serial",
    "test_flash_attention.py::test_flash_gradients_match_oracle[512-True]",
    "test_fsdp.py::test_lm_trainer_fsdp_sp_e2e",
    # Both FSDP x SP parity variants are slow; the driver's dryrun path
    # 13 runs the same step with a serial-parity assert every round, so
    # the composition keeps default-gate coverage outside pytest.
    "test_fsdp.py::test_lm_fsdp_sp_matches_replicated_sp[0.05]",
    "test_fsdp.py::test_lm_fsdp_sp_matches_replicated_sp[0.0]",
    "test_fsdp.py::test_lm_fsdp_step_matches_replicated",
    "test_pp_lm.py::test_sp_pp_lm_step_matches_serial[mesh_axes1]",
    "test_pp_lm.py::test_lm_trainer_sp_pp_e2e",
    "test_pp_lm.py::test_sp_pp_lm_moe_trains",
    # The 4D mesh runs in the driver's dryrun path 15 (serial-parity
    # asserted) every round besides these slow twins; the 16-device
    # all-four-axes composition is a spawned worker (own jax process).
    "test_4d_full.py::test_full_4d_mesh_16_devices_matches_serial",
    "test_tp_pp_lm.py::test_tp_pp_lm_4d_matches_serial",
    "test_tp_pp_lm.py::test_lm_trainer_4d_e2e",
    "test_tp_pp_lm.py::test_tp_pp_lm_checkpoint_resume",
    "test_step_resume.py::test_mid_epoch_resume_under_mesh[data:8]",
    # Elasticity (ISSUE 5): the CNN cross-width e2e variants and the
    # preemption mechanics stay fast; these two heavy twins run in the
    # explicit CI elasticity step (named ::-exactly, which overrides
    # this skip) and under --runslow.
    "test_elastic.py::test_lm_preempt_resume_across_widths_bitwise",
    "test_elastic.py::test_elastic_step_is_width_invariant_and_pmean_is_not",
    "test_elastic.py::test_elastic_augment_keys_on_canonical_shard",
    # Fleet (ISSUE 7): the tier-1-size storm + lifecycle/fencing tests
    # stay fast; the 10^5-request acceptance storm and the engine-backed
    # (jit-compiling) crash-parity twins run in the explicit CI fleet
    # step (named ::-exactly, which overrides this skip) and --runslow.
    "test_fleet.py::test_storm_100k_scale",
    "test_fleet.py::test_engine_fleet_crash_outputs_match_crash_free[resume]",
    "test_fleet.py::test_engine_fleet_crash_outputs_match_crash_free[discard]",
    # Disaggregated serving (ISSUE 13): same split — the tier-1-size
    # 2-pool storms, crash/corruption/degradation mechanics, and the
    # fast engine parity twin stay fast; the 10^5 acceptance storm and
    # the prefix-through-handoff engine parity run in the explicit CI
    # disagg step (named ::-exactly) and --runslow.
    "test_disagg.py::test_disagg_storm_100k_scale",
    "test_disagg.py::test_engine_disagg_outputs_match_unified_through_handoff[True]",
    # Speculative serving (ISSUE 14): the f32 bitwise parity, the
    # preemption+prefix composition, the tick-drop pin, the scheduler
    # rollback invariants, the sim-fleet parity, and the obs/CLI
    # round-trips stay fast; these heavy engine-compile twins (bf16/
    # int8 dtype matrix, the draft proposer, the engine-backed crash
    # and disagg-handoff parity legs) run in the explicit CI serving
    # step (named ::-exactly, which overrides this skip) and --runslow.
    "test_spec_serve.py::test_engine_spec_on_off_bitwise_parity[bfloat16]",
    "test_spec_serve.py::test_engine_spec_on_off_bitwise_parity[int8]",
    "test_spec_serve.py::test_engine_spec_draft_parity",
    "test_spec_serve.py::test_engine_fleet_spec_crash_parity",
    "test_spec_serve.py::test_engine_disagg_spec_parity_through_handoff",
    # Flight recorder (ISSUE 15): the engine/fleet/disagg replay
    # mechanics, tamper/legacy/diverge pins, and gate wiring stay
    # fast; the two reduced-scale storm twins of the CI determinism
    # gates (--spec lookup, --pools at 20k requests, full-log) run in
    # the explicit CI obs step (named ::-exactly) and --runslow — the
    # full-scale fleet storm replay is its own CI step.
    "test_replay.py::test_replay_spec_storm_twin",
    "test_replay.py::test_replay_disagg_storm_twin",
    # Host-tier spill (ISSUE 17): the engine/fleet parity legs, the
    # corrupt-refusal degradation, the bounded-LRU/CRC unit mechanics,
    # and the replay round-trips stay fast; the 10^5-request
    # determinism storm runs in the explicit CI serving step (named
    # ::-exactly, which overrides this skip) and --runslow.
    "test_host_tier.py::test_spill_determinism_storm_1e5_twice_bitwise",
    "test_models.py::test_residual_unprojectable_shape_rejected",
    "test_pp.py::test_pp_grad_clip_matches_optax[mesh_axes1-1-False]",
    "test_tp_pp.py::test_tp_pp_eval_forward_matches_apply",
    "test_pallas.py::test_model_pallas_backend_forward_parity",
    "test_train.py::test_pp_trainer_loop_path",
    "test_models.py::test_init_deterministic_across_calls",
    "test_accum_remat.py::test_grad_accum_matches_plain[data]",
    "test_accum_remat.py::test_grad_accum_matches_plain[data:4,model:2]",
    "test_accum_remat.py::test_remat_transformer_grads_match",
    "test_augment.py::test_trainer_augment_on_pp_mesh_is_deterministic",
    "test_bench_contract.py::test_bench_emits_error_json_when_attempts_time_out",
    "test_ep.py::test_top2_moe_lm_trains",
    "test_ep.py::test_ep_layer_trains",
    "test_ep.py::test_dispatch_at_most_one_slot_per_token",
    "test_flash_attention.py::test_flash_bf16_gradients_match_oracle",
    "test_fsdp.py::test_fsdp_pp_matches_plain_pp[True-pipe:2,data:4]",
    "test_fsdp.py::test_fsdp_pp_matches_plain_pp[False-pipe:2,data:4]",
    "test_fsdp.py::test_lm_trainer_fsdp_and_fsdp_tp",
    "test_pp_lm.py::test_pp_lm_remat_matches_plain",
    "test_pp_lm.py::test_lm_pipeline_checkpoint_resume",
    "test_pp_lm.py::test_pp_lm_step_matches_serial[mesh_axes1]",
    "test_pp_lm.py::test_pp_lm_step_matches_serial[mesh_axes2]",
    "test_tp.py::test_lm_trainer_accepts_model_axis",
    "test_tp_sp.py::test_tp_sp_step_matches_serial[2-rope-mesh_axes1]",
    "test_tp_sp.py::test_tp_sp_step_matches_serial[0-learned-mesh_axes2]",
    "test_tp_sp.py::test_tp_sp_step_matches_serial[0-learned-mesh_axes3]",
    "test_generate.py::test_decode_matches_inference_forward_moe_top2",
    "test_generate.py::test_generate_shapes_and_budget",
    "test_gqa_rope.py::test_gqa_flash_gradients_match_oracle",
    "test_gqa_rope.py::test_lm_variants_train_and_decode[0-rope]",
    "test_lm.py::test_bf16_loss_close_to_f32",
    "test_lm.py::test_chunked_ce_matches_dense[bfloat16]",
    "test_pallas.py::test_conv_grad_parity[4-14-14-16-3-32-2-1]",
    "test_pp.py::test_pp_loss_and_grads_match_serial[4-4]",
    "test_step_resume.py::test_mid_epoch_resume_under_mesh[pipe:2,data:2]",
    "test_tp_pp.py::test_tp_pp_step_matches_serial[mesh_axes1-4]",
    "test_transformer.py::test_sp_step_parity_with_single_device[ulysses]",
    "test_digits.py::test_accuracy_on_real_digits",
    "test_dp.py::test_dp_composes_with_pallas_backend",
    "test_flash_attention.py::test_flash_gradients_match_oracle[256-False]",
    "test_flash_attention.py::test_flash_gradients_match_oracle[512-False]",
    "test_fsdp.py::test_fsdp_e2e_train_and_eval",
    "test_fsdp.py::test_fsdp_matches_replicated_dp[False]",
    "test_fsdp.py::test_fsdp_matches_replicated_dp[True]",
    "test_generate.py::test_decode_matches_inference_forward_moe",
    "test_generate.py::test_decode_matches_training_forward",
    "test_generate.py::test_moe_inference_routing_is_per_token",
    "test_generate.py::test_trained_model_generates_the_cycle",
    "test_models.py::test_presets_init_and_apply[cifar3conv]",
    "test_models.py::test_presets_init_and_apply[lenet5_relu]",
    "test_models.py::test_presets_init_and_apply[resnet8]",
    "test_models.py::test_presets_init_and_apply[vgg_small]",
    "test_models.py::test_residual_downsample_to_1x1",
    "test_models.py::test_residual_gradients_flow_through_shortcut",
    "test_models.py::test_residual_identity_vs_projection",
    "test_multihost.py::test_two_process_dp_step",
    "test_multihost.py::test_two_process_ring_sp_lm_step",
    "test_multihost.py::test_two_process_pipeline_step",
    "test_multihost.py::test_two_process_4d_lm_step",
    "test_accum_remat.py::test_lm_grad_accum_matches_plain",
    "test_tp_sp.py::test_tp_sp_ulysses_matches_serial",
    "test_ep.py::test_ep_dp_lm_trains",
    "test_accum_remat.py::test_sp_grad_accum_matches_plain",
    "test_tp_pp_lm.py::test_tp_pp_lm_moe_m1_matches_serial",
    "test_tp_sp.py::test_tp_sp_moe_trains",
    "test_pallas.py::test_conv_bf16_parity[4-14-14-16-3-32-2-1]",
    "test_pallas.py::test_conv_bf16_parity[4-28-28-1-3-16-2-1]",
    "test_pallas.py::test_model_pallas_backend_trains",
    "test_pp.py::test_pp_loss_and_grads_match_serial[2-4]",
    "test_train.py::test_checkpoint_resume",
    "test_train.py::test_convergence_cifar3conv",
    "test_train.py::test_determinism_same_seed",
    "test_train.py::test_irwin_hall_reference_config",
    "test_train.py::test_pp_bfloat16_training",
    "test_train.py::test_pp_checkpoint_resume",
    "test_train.py::test_pp_rejects_bfloat16_params",
    "test_train.py::test_pp_trainer_end_to_end",
    "test_train.py::test_pp_trainer_matches_dp",
    "test_train.py::test_scan_matches_per_batch_loop",
    "test_gqa_rope.py::test_gqa_rope_under_ring_flash_sp",
    "test_gqa_rope.py::test_gqa_rope_under_ring_sp",
    "test_gqa_rope.py::test_lm_variants_train_and_decode[2-learned]",
    "test_lm.py::test_flash_impl_matches_oracle_in_step",
    "test_lm.py::test_train_step_learns_cyclic_task",
    "test_lm_trainer.py::test_checkpoint_resume_continues_at_step",
    "test_lm_trainer.py::test_data_seq_mesh_with_moe",
    "test_lm_trainer.py::test_sp_mesh_learns_synthetic_cycle",
    "test_step_resume.py::test_mid_epoch_resume_is_bitwise_exact[True]",
    "test_tp_pp.py::test_tp_pp_replicated_upstream_layers_match_serial",
    "test_tp_pp.py::test_tp_pp_step_matches_serial[mesh_axes0-2]",
    "test_tp_pp.py::test_trainer_accepts_tp_pp_mesh",
    "test_transformer.py::test_moe_lm_trains_under_ring_sp",
    "test_transformer.py::test_sp_dp_mesh_composes",
    "test_transformer.py::test_sp_step_parity_ring_flash",
    "test_transformer.py::test_sp_lm_learns_cyclic_task",
    "test_transformer.py::test_sp_remat_composition",
    "test_transformer.py::test_sp_step_parity_with_single_device[ring]",
}


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run the tests listed in conftest.SLOW_TESTS",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    # A test named explicitly on the command line (::-qualified) always
    # runs; other args in the same invocation still get the skip.
    # Nodeids are rootdir-relative with forward slashes, while CLI args
    # may be absolute or cwd-relative paths — normalize the arg's path
    # part against rootdir so `pytest /abs/tests/test_x.py::name` matches
    # exactly that file's test and nothing sharing its basename.
    def _normalize(arg):
        path, sep, rest = arg.partition("::")
        rel = os.path.relpath(os.path.abspath(path), str(config.rootdir))
        return rel.replace(os.sep, "/") + sep + rest

    explicit = tuple(_normalize(a) for a in config.args if "::" in a)

    def named_explicitly(item):
        nid = item.nodeid
        return any(nid == a or nid.startswith(a + "[") for a in explicit)

    skip = pytest.mark.skip(reason="slow; use --runslow (make test_all)")
    matched = set()
    for item in items:
        key = item.nodeid.split("/")[-1]
        if key in SLOW_TESTS:
            matched.add(key)
            if not named_explicitly(item):
                item.add_marker(skip)
    # A renamed/reparametrized test would silently rejoin the fast suite;
    # flag stale entries loudly. (Partial collection runs see a subset, so
    # only check when the whole suite was collected.)
    if len(items) > len(SLOW_TESTS) * 3:
        stale = SLOW_TESTS - matched
        if stale:
            import warnings

            warnings.warn(f"SLOW_TESTS entries match no test: {sorted(stale)}",
                          stacklevel=2)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
