"""Test harness config: run everything on a virtual 8-device CPU mesh.

Must run before the first `import jax` anywhere in the test process —
pytest imports conftest.py first, so setting the env here is sufficient
(SURVEY.md §4: multi-device DP tests runnable without a TPU).
"""

import os

# Force CPU regardless of ambient JAX_PLATFORMS — the suite must run
# identically on a TPU VM and a plain CI box; TPU execution is covered by
# bench.py and the driver's compile checks. This environment pre-imports jax
# at interpreter startup, so env vars alone are too late: also set the jax
# config directly (safe — no backend is initialized yet at conftest time).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
