"""Real-data accuracy: scikit-learn's bundled UCI digits (the only real
image data available without network — SURVEY.md §4's constraint) through
the north-star recipe."""

import numpy as np
import pytest

pytest.importorskip("sklearn")

from mpi_cuda_cnn_tpu.data.datasets import get_dataset, sklearn_digits


def test_digits_loader_shapes_and_determinism():
    ds = get_dataset("digits")
    assert ds.train_images.shape[1:] == (28, 28)
    assert ds.train_images.dtype == np.uint8
    assert ds.num_classes == 10
    assert len(ds.train_images) + len(ds.test_images) == 1797
    assert ds.train_images.max() > 200  # rescaled to 0-255
    ds2 = sklearn_digits()
    np.testing.assert_array_equal(ds.test_labels, ds2.test_labels)
    # Split is a partition: every source image lands in exactly one split.
    from sklearn.datasets import load_digits

    src = load_digits().images
    n_src_unique = len(set(map(bytes, (src * (255.0 / 16.0)).astype(np.uint8)
                               .reshape(len(src), -1))))
    combined = np.concatenate([
        ds.train_images.reshape(len(ds.train_images), -1),
        ds.test_images.reshape(len(ds.test_images), -1),
    ])
    assert len(combined) == len(src)  # no sample duplicated across splits
    # Upscale+pad is injective on distinct images, so unique counts match.
    assert len(set(map(bytes, combined))) == n_src_unique


def test_digits_native_8x8():
    ds = sklearn_digits(upscale=8)
    assert ds.train_images.shape[1:] == (8, 8)


def test_digits_rejects_tiny_upscale():
    with pytest.raises(ValueError, match="upscale"):
        sklearn_digits(upscale=4)


def test_accuracy_on_real_digits():
    """The accuracy demonstration on REAL handwritten digits. CPU budget:
    the reference's own architecture (cheap on CPU; measured 98.0% here)
    with the north-star optimizer recipe, no augmentation (covered in
    tests/test_augment.py). The full recipe — lenet5_relu + shift
    augmentation, 30 epochs — measured 99.4% on a v5e chip
    (make northstar_digits)."""
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ds = get_dataset("digits")
    cfg = Config(model="reference_cnn", init="he", epochs=20, batch_size=128,
                 lr=0.05, momentum=0.9, lr_schedule="cosine",
                 eval_every=0, log_every=10**9, num_devices=1)
    t = Trainer(get_model("reference_cnn"), ds, cfg,
                metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.test_accuracy >= 0.95, r.test_accuracy
