"""Transport-bus unit laws (ISSUE 20).

The laws the exactly-once proof leans on, each pinned in isolation
against a bare TransportBus (jax-free — no fleet, no engine):

- zero-fault delivery is INLINE: the handler runs synchronously inside
  send(), the ack clears the retransmit entry in the same call, and
  the wire is idle afterwards — the mechanism behind the bus-on ==
  direct-call bitwise-parity acceptance;
- at-least-once retransmission paces on `utils.retry.backoff_delay`
  (jitter pinned to zero, whole-tick ceilings, attempt plateau) and
  stops on ack;
- receiver-side dedup drops repeats by (rid, kind0, epoch[, pos]) key
  and RE-ACKS them (the retransmit means the first ack was lost);
- the skip-dedup chaos plant really disables the commit seen-check —
  the canary the chaos search must catch is load-bearing;
- a partition drops traffic in BOTH directions, at send and at delayed
  delivery, and heals on schedule with retransmits completing
  delivery exactly once;
- unregister purges unacked entries touching the endpoint while
  delayed copies stay in flight and count dropped at delivery;
- the conservation invariant `sent == delivered + deduped + dropped +
  inflight` holds through a seeded random fault walk (the same audit
  the replay mirror runs every tick);
- the fleet-level lease/transport config laws: lease_ticks defaults to
  heartbeat_miss + 2, must exceed heartbeat_miss, transport refuses
  --pools, and fleet.transport faults without the bus are inert-loud.
"""

import numpy as np
import pytest

from mpi_cuda_cnn_tpu.faults import FaultInjector
from mpi_cuda_cnn_tpu.serve.transport import (
    COUNTER_KEYS,
    TRANSPORT_SITE,
    TransportBus,
    transport_digest_tuple,
)
from mpi_cuda_cnn_tpu.utils.retry import backoff_delay


def _conserved(bus: TransportBus) -> bool:
    f = bus.record_fields()
    return (f["sent"]
            == f["delivered"] + f["deduped"] + f["dropped"] + f["inflight"])


def _bus(plan: str | None = None, **kw) -> TransportBus:
    faults = FaultInjector(plan) if plan else None
    return TransportBus(faults=faults, **kw)


def test_zero_fault_delivery_is_inline_and_acked():
    bus = _bus()
    got = []
    bus.register("router", lambda m, t: got.append((m.kind, m.payload)))
    bus.register("r0#0", lambda m, t: got.append((m.kind, m.payload)))
    bus.send("dispatch", "router", "r0#0", {"rid": 7}, tick=1,
             key=(7, "d", 0), reliable=True)
    # Handler ran synchronously inside send(); the inline ack already
    # cleared the retransmit entry — nothing left on the wire.
    assert got == [("dispatch", {"rid": 7})]
    assert not bus.busy()
    f = bus.record_fields()
    assert f["sent"] == 2 and f["delivered"] == 2  # dispatch + ack
    assert f["unacked"] == 0 and f["inflight"] == 0
    assert _conserved(bus)
    # Unreliable kinds skip the ack machinery entirely.
    bus.send("hb", "r0#0", "router", {"load": 1}, tick=2)
    assert got[-1] == ("hb", {"load": 1})
    assert not bus.busy()


def test_reliable_send_requires_a_key():
    bus = _bus()
    bus.register("router", lambda m, t: None)
    with pytest.raises(ValueError, match="dedup key"):
        bus.send("commit", "r0#0", "router", {}, tick=0, reliable=True)


def test_retransmit_paces_on_backoff_delay_and_stops_on_ack():
    bus = _bus(rto_base=2.0)
    bus.register("router", lambda m, t: None)
    # Destination not registered: every wire attempt drops, the sender
    # keeps retrying on the backoff schedule with no cap.
    bus.send("dispatch", "router", "r0#0", {"rid": 1}, tick=0,
             key=(1, "d", 0), reliable=True)
    assert bus.busy()
    due = []
    for tick in range(1, 40):
        before = bus.counters["retransmits"]
        bus.pump(tick)
        if bus.counters["retransmits"] > before:
            due.append(tick)
    # Attempt k retransmits _rto(k-1) ticks after attempt k-1, where
    # _rto is the jitterless backoff_delay ceiling'd to whole ticks.
    def rto(a):
        return min(32, max(1, -int(-backoff_delay(
            min(a, 5), base=2.0, jitter=lambda: 0.0) // 1)))

    expect, t = [], 0
    for a in range(len(due)):
        t += rto(a)
        expect.append(t)
    assert due == expect
    # Late registration: the next retransmit delivers, the ack lands,
    # and the wire goes quiet — at-least-once became exactly-once.
    got = []
    bus.register("r0#0", lambda m, t: got.append(m.payload["rid"]))
    for tick in range(40, 80):
        bus.pump(tick)
    assert got == [1]
    assert not bus.busy()
    assert _conserved(bus)


def test_dedup_drops_repeats_and_reacks():
    bus = _bus("msg_dup@fleet.transport:1?kind=commit&count=1")
    hits = []
    bus.register("router", lambda m, t: hits.append(m.key))
    bus.register("r0#0", lambda m, t: None)
    bus.apply_tick_faults(1)
    bus.send("commit", "r0#0", "router", {"tok": 3}, tick=1,
             key=(4, "c", 0, 0), reliable=True)
    # The dup delivered two wire copies; dedup let exactly one through
    # and RE-ACKED the repeat, so the sender's entry is still cleared.
    assert hits == [(4, "c", 0, 0)]
    c = bus.counters
    assert c["duped"] == 1 and c["deduped"] == 1
    assert not bus.busy()
    assert _conserved(bus)
    # A later send with the SAME key (a retransmit crossing its ack)
    # dedups again — and the re-ack clears the re-armed entry.
    bus.send("commit", "r0#0", "router", {"tok": 3}, tick=2,
             key=(4, "c", 0, 0), reliable=True)
    assert hits == [(4, "c", 0, 0)]
    assert bus.counters["deduped"] == 2
    assert not bus.busy()
    # release_keys drops the rid's store: the guard downstream (the
    # fleet's req.terminal check) takes over from there.
    bus.release_keys(4)
    bus.send("commit", "r0#0", "router", {"tok": 3}, tick=3,
             key=(4, "c", 0, 0), reliable=True)
    assert len(hits) == 2


def test_skip_dedup_plant_disables_commit_dedup_only():
    bus = _bus("msg_dup@fleet.transport:1?count=2",
               plant=lambda: "skip-dedup")
    hits = []
    bus.register("router", lambda m, t: hits.append(m.key))
    bus.register("r0#0", lambda m, t: None)
    bus.apply_tick_faults(1)
    bus.send("commit", "r0#0", "router", {}, tick=1,
             key=(1, "c", 0, 0), reliable=True)
    bus.send("terminal", "r0#0", "router", {}, tick=1,
             key=(1, "t", 0), reliable=True)
    # The plant bypasses the seen-check for COMMIT keys only: the duped
    # commit applies twice (the planted bug), the duped terminal still
    # dedups — the canary is scoped to the exactly-once token path.
    assert hits.count((1, "c", 0, 0)) == 2
    assert hits.count((1, "t", 0)) == 1


def test_partition_blocks_both_directions_then_heals():
    events = []
    bus = _bus("partition@fleet.transport:2?replica=0&ticks=3",
               on_event=lambda k, f: events.append((k, f["name"])))
    got = []
    bus.register("router", lambda m, t: got.append(("router", m.kind)))
    bus.register("r0#0", lambda m, t: got.append(("r0", m.kind)))
    bus.apply_tick_faults(2)
    assert bus.counters["partitions"] == 1
    assert events == [("partition_open", "r0")]
    bus.send("dispatch", "router", "r0#0", {}, tick=2,
             key=(9, "d", 0), reliable=True)
    bus.send("hb", "r0#0", "router", {}, tick=2)
    # Both directions dropped at the wire; the unreliable hb is gone
    # for good, the reliable dispatch waits on retransmission.
    assert got == []
    assert bus.counters["dropped"] == 2
    assert bus.busy()
    for tick in range(3, 16):
        bus.apply_tick_faults(tick)
        bus.pump(tick)
    # Healed at tick 5 (2 + 3): the first retransmit after the heal
    # (backoff-paced, tick 8) delivered the dispatch exactly once.
    assert ("partition_heal", "r0") in events
    assert got == [("r0", "dispatch")]
    assert not bus.busy()
    assert _conserved(bus)


def test_partition_drops_delayed_copy_at_delivery_time():
    bus = _bus("msg_delay@fleet.transport:1?ticks=3;"
               "partition@fleet.transport:2?replica=0&ticks=4")
    got = []
    bus.register("router", lambda m, t: None)
    bus.register("r0#0", lambda m, t: got.append(m.kind))
    bus.apply_tick_faults(1)
    bus.send("hb_ack", "router", "r0#0", {}, tick=1)
    assert got == [] and len(bus._delayed) == 1
    # The window opened while the copy was in flight: pump re-checks
    # partitions at the due tick and drops it there.
    bus.apply_tick_faults(2)
    for tick in range(2, 7):
        bus.pump(tick)
    assert got == []
    assert bus.counters["dropped"] == 1
    assert _conserved(bus)


def test_unregister_purges_unacked_but_not_delayed():
    bus = _bus("msg_delay@fleet.transport:1?kind=dispatch&ticks=2;"
               "msg_drop@fleet.transport:1?kind=commit")
    bus.register("router", lambda m, t: None)
    bus.register("r0#0", lambda m, t: None)
    bus.apply_tick_faults(1)
    bus.send("dispatch", "router", "r0#0", {}, tick=1,
             key=(1, "d", 0), reliable=True)   # delayed copy parked
    bus.send("commit", "r0#0", "router", {}, tick=1,
             key=(1, "c", 0, 0), reliable=True)  # dropped, unacked
    assert len(bus._delayed) == 1 and len(bus._unacked) == 2
    bus.unregister("r0#0")
    # Unacked entries touching the endpoint purged (as sender AND as
    # destination); the delayed copy stays — the network does not know
    # the process died — and drops at delivery for want of a handler.
    assert len(bus._unacked) == 0
    assert len(bus._delayed) == 1
    for tick in range(2, 5):
        bus.pump(tick)
    assert not bus.busy()
    assert bus.record_fields()["inflight"] == 0
    assert _conserved(bus)


def test_conservation_invariant_through_seeded_fault_walk():
    plan = ";".join(
        f"msg_{k}@fleet.transport:{t}?count=2"
        for t, k in enumerate(["drop", "dup", "delay", "drop", "dup"],
                              start=2))
    plan += ";partition@fleet.transport:6?replica=1&ticks=4"
    bus = _bus(plan)
    bus.register("router", lambda m, t: None)
    for name in ("r0#0", "r1#0", "r2#1"):
        bus.register(name, lambda m, t: None)
    rng = np.random.default_rng(20)
    kinds = ["dispatch", "commit", "terminal", "hb"]
    for tick in range(1, 30):
        bus.apply_tick_faults(tick)
        for _ in range(int(rng.integers(0, 4))):
            kind = kinds[int(rng.integers(len(kinds)))]
            dst = ["r0#0", "r1#0", "r2#1"][int(rng.integers(3))]
            rid = int(rng.integers(6))
            if kind == "hb":
                bus.send("hb", dst, "router", {}, tick=tick)
            elif kind == "dispatch":
                bus.send("dispatch", "router", dst, {}, tick=tick,
                         key=(rid, "d", 0), reliable=True)
            else:
                k0 = "c" if kind == "commit" else "t"
                key = ((rid, k0, 0, tick) if k0 == "c"
                       else (rid, k0, 0))
                bus.send(kind, dst, "router", {}, tick=tick,
                         key=key, reliable=True)
        bus.pump(tick)
        assert _conserved(bus), f"conservation broken at tick {tick}"
    for tick in range(30, 120):
        bus.apply_tick_faults(tick)
        bus.pump(tick)
        if not bus.busy():
            break
    assert not bus.busy()
    c = bus.counters
    assert c["dropped"] > 0 and c["duped"] > 0 and c["delayed"] > 0
    assert c["retransmits"] > 0 and c["partitions"] == 1
    assert _conserved(bus)
    # The digest folds every counter plus wire/link/partition state —
    # the spelling fleet_state_digest and the replay mirror share.
    d = transport_digest_tuple(bus.record_fields())
    assert d[0] == tuple(c[k] for k in COUNTER_KEYS)
    assert d[1] == 0 and d[3] and d[4] == ()


def test_rto_base_validates():
    with pytest.raises(ValueError, match="rto_base"):
        TransportBus(rto_base=0)


def test_fleet_lease_and_transport_config_laws():
    from mpi_cuda_cnn_tpu.serve.fleet import Fleet, SimCompute

    def factory(name):
        return SimCompute(vocab=32, chunk=8, salt=0)

    common = dict(slots=2, num_pages=9, page_size=4, max_len=24,
                  heartbeat_miss=3)
    # Default lease outlives the detection window by two ticks.
    f = Fleet(factory, replicas=2, transport=True, **common)
    assert f.lease_ticks == 5
    # A lease inside the detection window is refused loudly.
    with pytest.raises(ValueError, match="lease_ticks"):
        Fleet(factory, replicas=2, transport=True, lease_ticks=3,
              **common)
    # Scope cut: the handoff control plane is not bus-routed.
    with pytest.raises(ValueError, match="pools"):
        Fleet(factory, replicas=2, transport=True,
              pools="prefill:1,decode:1", **common)
    # Inert-fault contract: fleet.transport faults need the bus.
    with pytest.raises(ValueError, match="--transport"):
        Fleet(factory, replicas=2, transport=False,
              faults=FaultInjector(
                  "msg_drop@fleet.transport:3?count=1"),
              **common)
    # With the bus off lease bookkeeping is fully disabled.
    assert Fleet(factory, replicas=2, **common).lease_ticks == 0
