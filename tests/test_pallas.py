"""Pallas kernel parity tests (interpreter mode on the CPU mesh) against
the XLA oracle ops — the per-op parity strategy of SURVEY.md §7 stage 4."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.ops import conv2d, dense
from mpi_cuda_cnn_tpu.ops.pallas_ops import conv2d_pallas, dense_pallas


def _rand(*shape, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape).astype(np.float32)
    )


# ---------------------------------------------------------------------------
# Dense
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("m,k,n", [(32, 1568, 200), (5, 7, 3), (128, 128, 128)])
def test_dense_forward_parity(m, k, n):
    x, w, b = _rand(m, k), _rand(k, n, seed=1), _rand(n, seed=2)
    got = dense_pallas(x, w, b)
    want = dense(x, w, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def test_dense_grad_parity():
    x, w, b = _rand(16, 64), _rand(64, 10, seed=1), _rand(10, seed=2)

    def loss_p(x, w, b):
        return jnp.sum(dense_pallas(x, w, b) ** 2)

    def loss_o(x, w, b):
        return jnp.sum(dense(x, w, b) ** 2)

    gp = jax.grad(loss_p, argnums=(0, 1, 2))(x, w, b)
    go = jax.grad(loss_o, argnums=(0, 1, 2))(x, w, b)
    for a, b_ in zip(gp, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# Conv
# ---------------------------------------------------------------------------

CONV_CASES = [
    # (n, h, w, cin, kh, cout, stride, padding) — first two rows are the
    # reference's exact conv configs (cnn.c:417-418).
    (4, 28, 28, 1, 3, 16, 2, 1),
    (4, 14, 14, 16, 3, 32, 2, 1),
    (2, 8, 8, 3, 5, 4, 1, 2),
    (2, 6, 6, 2, 3, 3, 1, 0),
]


@pytest.mark.parametrize("n,h,w,cin,k,cout,stride,pad", CONV_CASES)
def test_conv_forward_parity(n, h, w, cin, k, cout, stride, pad):
    x = _rand(n, h, w, cin)
    wk = _rand(k, k, cin, cout, seed=1)
    got = conv2d_pallas(x, wk, stride, pad)
    want = conv2d(x, wk, stride=stride, padding=pad)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n,h,w,cin,k,cout,stride,pad", CONV_CASES)
def test_conv_grad_parity(n, h, w, cin, k, cout, stride, pad):
    x = _rand(n, h, w, cin)
    wk = _rand(k, k, cin, cout, seed=1)

    def loss_p(x, wk):
        return jnp.sum(conv2d_pallas(x, wk, stride, pad) ** 2)

    def loss_o(x, wk):
        return jnp.sum(conv2d(x, wk, stride=stride, padding=pad) ** 2)

    gp = jax.grad(loss_p, argnums=(0, 1))(x, wk)
    go = jax.grad(loss_o, argnums=(0, 1))(x, wk)
    # atol covers f32 accumulation-order noise on O(1e3)-magnitude sums.
    np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(go[0]), rtol=1e-4, atol=5e-3)
    np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(go[1]), rtol=1e-4, atol=5e-3)


# ---------------------------------------------------------------------------
# bf16: the packed-dtype path (16,128) tiling — regression for the Mosaic
# alignment failure the f32-only suite missed (dynamic sublane offsets and
# packed-vector reshapes are illegal on real TPUs; the kernels must route
# around both). Interpreter mode can't prove alignment, but it does pin the
# numerics of the exact code path the TPU compiles.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n,h,w,cin,k,cout,stride,pad", CONV_CASES)
def test_conv_bf16_parity(n, h, w, cin, k, cout, stride, pad):
    x = _rand(n, h, w, cin).astype(jnp.bfloat16)
    wk = (_rand(k, k, cin, cout, seed=1) * 0.1).astype(jnp.bfloat16)

    got = conv2d_pallas(x, wk, stride, pad)
    want = conv2d(x, wk, stride=stride, padding=pad)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-2,
    )

    def loss_p(x, wk):
        return jnp.sum(conv2d_pallas(x, wk, stride, pad).astype(jnp.float32) ** 2)

    def loss_o(x, wk):
        return jnp.sum(conv2d(x, wk, stride=stride, padding=pad).astype(jnp.float32) ** 2)

    gp = jax.grad(loss_p, argnums=(0, 1))(x, wk)
    go = jax.grad(loss_o, argnums=(0, 1))(x, wk)
    for a, b in zip(gp, go):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(np.abs(b).max(), 1.0)
        assert np.abs(a - b).max() / scale < 2e-2  # bf16 rounding band


def test_dense_bf16_parity():
    x = _rand(16, 64).astype(jnp.bfloat16)
    w = (_rand(64, 10, seed=1) * 0.1).astype(jnp.bfloat16)
    b = _rand(10, seed=2).astype(jnp.bfloat16)
    got = dense_pallas(x, w, b)
    want = dense(x, w, b)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-2,
    )


# ---------------------------------------------------------------------------
# End to end through the model API
# ---------------------------------------------------------------------------


def test_model_pallas_backend_forward_parity():
    from mpi_cuda_cnn_tpu.models.initializers import get_initializer
    from mpi_cuda_cnn_tpu.models.presets import get_model

    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    x = _rand(8, 28, 28, 1)
    got = model.apply(params, x, backend="pallas")
    want = model.apply(params, x, backend="xla")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4)


def test_model_pallas_backend_trains():
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ds = synthetic_stripes(num_train=128, num_test=64)
    cfg = Config(epochs=2, use_pallas=True, eval_every=0, log_every=10**9,
                 num_devices=1, batch_size=32)
    t = Trainer(get_model("reference_cnn"), ds, cfg,
                metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.test_accuracy >= 0.9


# ---------------------------------------------------------------------------
# Implicit-GEMM conv (pallas_conv_gemm.py): the deep-shape formulation —
# one (M, k*k*Cin) MXU contraction per tile instead of k*k half-filled
# K=Cin dots. Parity vs the oracle on stride-1 shapes incl. bf16 + grads.
# ---------------------------------------------------------------------------

GEMM_CASES = [
    # stride-1 only (the formulation's domain): a deep-ish shape, the
    # odd-channel VGG head, and a k5 'same' case.
    (2, 8, 8, 16, 3, 8, 1, 1),
    (2, 6, 6, 2, 3, 3, 1, 0),
    (2, 8, 8, 3, 5, 4, 1, 2),
]


@pytest.mark.parametrize("n,h,w,cin,k,cout,stride,pad", GEMM_CASES)
def test_conv_gemm_forward_parity(n, h, w, cin, k, cout, stride, pad):
    from mpi_cuda_cnn_tpu.ops.pallas_conv_gemm import conv2d_pallas_gemm

    x = _rand(n, h, w, cin)
    wk = _rand(k, k, cin, cout, seed=1)
    got = conv2d_pallas_gemm(x, wk, stride, pad)
    want = conv2d(x, wk, stride=stride, padding=pad)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_conv_gemm_grad_parity():
    from mpi_cuda_cnn_tpu.ops.pallas_conv_gemm import conv2d_pallas_gemm

    n, h, w, cin, k, cout, stride, pad = GEMM_CASES[0]
    x = _rand(n, h, w, cin)
    wk = _rand(k, k, cin, cout, seed=1)

    def loss_p(x, wk):
        return jnp.sum(conv2d_pallas_gemm(x, wk, stride, pad) ** 2)

    def loss_o(x, wk):
        return jnp.sum(conv2d(x, wk, stride=stride, padding=pad) ** 2)

    gp = jax.grad(loss_p, argnums=(0, 1))(x, wk)
    go = jax.grad(loss_o, argnums=(0, 1))(x, wk)
    np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(go[0]),
                               rtol=1e-4, atol=5e-3)
    np.testing.assert_allclose(np.asarray(gp[1]), np.asarray(go[1]),
                               rtol=1e-4, atol=5e-3)


def test_conv_gemm_bf16_parity_and_stride_rejection():
    from mpi_cuda_cnn_tpu.ops.pallas_conv_gemm import conv2d_pallas_gemm

    n, h, w, cin, k, cout, stride, pad = GEMM_CASES[0]
    x = _rand(n, h, w, cin).astype(jnp.bfloat16)
    wk = (_rand(k, k, cin, cout, seed=1) * 0.1).astype(jnp.bfloat16)
    got = conv2d_pallas_gemm(x, wk, stride, pad)
    want = conv2d(x, wk, stride=stride, padding=pad)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-2,
    )
    with pytest.raises(ValueError, match="stride-1"):
        conv2d_pallas_gemm(_rand(2, 8, 8, 4), _rand(3, 3, 4, 4, seed=1),
                           2, 1)
