"""Failure-aware serving (ISSUE 4): deadlines, cancellation,
backpressure, the preemption-livelock guard, injected page-pool
squeezes, and the tick watchdog — all deterministic on CPU via
faults.FakeClock (no wall-clock races).

The acceptance e2e lives here too: a serve run with an injected
page-pool squeeze + expiring deadlines completes every non-expired
request, fails/rejects the rest with terminal statuses, and ends with
the PagePool clean (zero leaked or double-booked pages — the engine
asserts it after every iteration AND at exit)."""

import numpy as np
import pytest

import jax

from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.serve.paged_cache import PagePool
from mpi_cuda_cnn_tpu.serve.scheduler import ContinuousScheduler, Request

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)


@pytest.fixture(scope="module")
def params():
    return MODEL.init(jax.random.key(0))


def _engine(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("num_pages", 13)
    kw.setdefault("page_size", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_len", 24)
    return PagedEngine(MODEL, params, **kw)


def _req(rid, plen=4, new=6, arrival=0.0, deadline=None):
    return Request(rid=rid, prompt=np.arange(plen) % 13, max_new_tokens=new,
                   arrival=arrival, deadline=deadline)


def _clock_run(engine, reqs, plan=None, mode="continuous", **kw):
    clock = FakeClock()
    faults = FaultInjector(plan, clock=clock) if plan else None
    res = engine.run(reqs, mode=mode, time_fn=clock,
                     sleep_fn=clock.advance, faults=faults, **kw)
    return res


def test_queued_deadline_expiry_drops_before_admission(params):
    """A request already past its deadline when the engine reaches it is
    dropped from the queue with zero tokens; the rest complete."""
    engine = _engine(params)
    reqs = [
        _req(0, new=4, deadline=100.0),
        _req(1, new=4, deadline=0.5),  # expires at the tick-0 jump
    ]
    res = _clock_run(engine, reqs, plan="slow@serve.tick:0?s=1.0")
    by = {r.rid: r for r in res.requests}
    assert by[0].status == "finished" and len(by[0].out) == 4
    assert by[1].status == "expired" and by[1].out == []
    assert by[1].finished_at is not None
    assert res.status_counts() == {"finished": 1, "expired": 1}
    assert any(e["kind"] == "request_expired" for e in res.events)


def test_inflight_deadline_abort_returns_pages(params):
    """A deadline passing MID-decode aborts the slot: emitted tokens
    stay, status goes terminal, and the pages go back through the
    ownership-checked pool free (engine checks the pool every tick)."""
    engine = _engine(params)
    reqs = [
        _req(0, new=12, deadline=100.0),
        _req(1, new=12, deadline=2.0),
    ]
    # Both admit and decode at t=0; tick 4's jump expires request 1.
    res = _clock_run(engine, reqs, plan="slow@serve.tick:4?s=5.0")
    by = {r.rid: r for r in res.requests}
    assert by[0].status == "finished" and len(by[0].out) == 12
    assert by[1].status == "expired"
    assert 0 < len(by[1].out) < 12  # partial progress preserved
    assert any(e["kind"] == "request_expired" for e in res.events)


def test_client_cancellation_queued_and_inflight(params):
    # Queued: cancel before the engine ever sees it -> zero tokens.
    engine = _engine(params)
    reqs = [_req(0, new=4), _req(1, new=4)]
    reqs[1].cancel()
    res = _clock_run(engine, reqs)
    by = {r.rid: r for r in res.requests}
    assert by[0].status == "finished"
    assert by[1].status == "cancelled" and by[1].out == []

    # In-flight: scheduler-level — cancel mid-decode, sweep aborts the
    # slot and the pool invariant holds.
    pool = PagePool(9)
    sched = ContinuousScheduler(slots=2, pool=pool, page_size=4, max_len=24)
    rs = [_req(0, plen=8, new=8), _req(1, plen=8, new=8)]
    sched.submit(rs)
    bound = sched.admit(0.0)
    assert len(bound) == 2
    for s in bound:
        s.cached = s.target
        s.req.out.append(1)
    rs[1].cancel()
    dropped = sched.sweep(1.0)
    assert [r.rid for r in dropped] == [1]
    assert rs[1].status == "cancelled"
    assert sched.slots[1].free
    pool.check()
    sched.finish(sched.slots[0], 2.0)
    pool.check()
    assert pool.free_pages == pool.usable


def test_bounded_queue_rejects_overflow(params):
    """Backpressure: with one slot and max_queue=1, a 3-request burst
    keeps one running + one waiting and REJECTS the rest with a
    terminal status — no unbounded queue memory."""
    engine = _engine(params, slots=1)
    reqs = [_req(i, new=3) for i in range(3)]
    res = _clock_run(engine, reqs, max_queue=1)
    by = {r.rid: r for r in res.requests}
    assert by[0].status == "finished"
    assert by[1].status == "finished"   # waited within the bound
    assert by[2].status == "rejected" and by[2].out == []
    assert by[2].fail_reason == "queue full"
    assert any(e["kind"] == "request_rejected" for e in res.events)


def test_scheduler_queue_bound_rejects_latest_arrivals():
    pool = PagePool(20)
    sched = ContinuousScheduler(slots=1, pool=pool, page_size=4,
                                max_len=24, max_queue=2)
    reqs = [_req(i, arrival=0.1 * i) for i in range(4)]
    sched.submit(reqs)
    rejected = sched.enforce_queue_bound(now=1.0)
    assert [r.rid for r in rejected] == [2, 3]  # latest arrivals go
    assert all(r.status == "rejected" for r in rejected)
    assert [r.rid for r in sched.queue] == [0, 1]
    # Not-yet-arrived requests never count against the bound.
    sched2 = ContinuousScheduler(slots=1, pool=PagePool(20), page_size=4,
                                 max_len=24, max_queue=2)
    sched2.submit([_req(i, arrival=10.0) for i in range(4)])
    assert sched2.enforce_queue_bound(now=0.0) == []


def test_queue_bound_never_rejects_preempted_requests():
    """Regression (review finding): a preempted request requeued at the
    head is NOT an arrival — the backpressure bound must neither count
    it nor evict it, or already-served work is silently dropped."""
    pool = PagePool(20)
    sched = ContinuousScheduler(slots=2, pool=pool, page_size=4,
                                max_len=24, max_queue=1)
    reqs = [_req(i, plen=4, new=8) for i in range(2)]
    sched.submit(reqs)
    bound = sched.admit(0.0)
    assert len(bound) == 2
    for s in bound:  # prefill done, one token out
        s.cached = s.target
        s.req.out.append(1)
    sched.preempt(sched.slots[1])
    sched.preempt(sched.slots[0])
    assert len(sched.queue) == 2  # both previously admitted, re-queued
    assert sched.enforce_queue_bound(now=1.0) == []
    assert all(r.status == "queued" for r in reqs)
    # A NEVER-admitted late arrival still counts toward the bound.
    late = [_req(10, arrival=0.5), _req(11, arrival=0.6)]
    sched.submit(late)
    rejected = sched.enforce_queue_bound(now=1.0)
    assert [r.rid for r in rejected] == [11]  # 10 fills the bound
    pool.check()


def test_train_batch_fault_forces_loop_path_and_fires():
    """Regression (review finding): a planned train.batch fault must
    not be silently inert on the default scanned path — the trainer
    falls back to per-batch stepping and the fault actually fires."""
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ds = synthetic_stripes(num_train=64, num_test=32)
    metrics = MetricsLogger(echo=False, capture=True)
    t = Trainer(
        get_model("reference_cnn"), ds,
        Config(dataset="synthetic", epochs=1, batch_size=16,
               num_devices=1, eval_every=0, log_every=0, scan=True),
        metrics=metrics, faults=FaultInjector("nan@train.batch:2"),
    )
    assert not t._use_scan()  # forced off the scanned path
    t.train()
    kinds = [r["kind"] for r in metrics.rows if r["event"] == "fault"]
    assert "injected_nan" in kinds  # the fault really fired


def test_livelock_guard_fails_oversized_context_terminally(params):
    """A request whose prompt fits the pool but whose GROWN context can
    never fit gets a terminal 'failed' status — not an endless
    preempt/requeue loop, and not a run-killing exception: the engine
    keeps serving everything else."""
    # 3 usable pages of 4 = 12 cache rows; request 1 grows to 16.
    engine = _engine(params, slots=2, num_pages=4, page_size=4, max_len=20)
    reqs = [_req(0, plen=4, new=2), _req(1, plen=6, new=10)]
    res = _clock_run(engine, reqs)
    by = {r.rid: r for r in res.requests}
    assert by[0].status == "finished" and len(by[0].out) == 2
    assert by[1].status == "failed"
    assert "cannot fit" in by[1].fail_reason
    assert 0 < len(by[1].out) < 10  # made real progress before failing
    assert any(e["kind"] == "request_failed" for e in res.events)


def test_livelock_guard_at_admission_for_grown_context():
    """The admission half: a preempted-and-requeued request whose grown
    context can never be readmitted is failed at the queue head instead
    of blocking FCFS forever."""
    pool = PagePool(4)  # 3 usable pages of 4
    sched = ContinuousScheduler(slots=1, pool=pool, page_size=4, max_len=24)
    grown = _req(0, plen=6, new=12)
    grown.out.extend([1] * 8)  # context 14; pages_for(15) = 4 > 3
    sched.queue.append(grown)  # as a preemption requeue would
    assert sched.admit(0.0) == []
    assert grown.status == "failed"
    assert grown in sched.dropped
    pool.check()


def test_watchdog_counts_slow_ticks(params):
    engine = _engine(params)
    res = _clock_run(engine, [_req(0, new=4)],
                     plan="slow@serve.tick:1?s=2.0", watchdog_s=0.5)
    assert res.watchdog_slow_ticks >= 1
    ev = [e for e in res.events if e["kind"] == "watchdog_slow_tick"]
    assert ev and ev[0]["seconds"] >= 2.0
    assert res.requests[0].status == "finished"


def test_static_mode_deadline_holds_reservation_until_drain(params):
    """Under static batching an aborted in-flight request keeps its
    reservation until the batch drains (the reserve-until-drain
    discipline) — it just stops decoding; the batch still completes and
    the pool ends clean."""
    engine = _engine(params, num_pages=13)
    reqs = [
        _req(0, new=10, deadline=100.0),
        _req(1, new=10, deadline=2.0),
    ]
    res = _clock_run(engine, reqs, plan="slow@serve.tick:4?s=5.0",
                     mode="static")
    by = {r.rid: r for r in res.requests}
    assert by[0].status == "finished" and len(by[0].out) == 10
    assert by[1].status == "expired" and len(by[1].out) < 10


def test_squeeze_plus_deadlines_acceptance_e2e(params):
    """THE serving acceptance: an injected page-pool squeeze + expiring
    deadlines. Every non-expired request completes, the rest leave with
    terminal statuses, and the pool ends clean — the engine asserts the
    no-leak/no-double-book invariant every iteration and at exit, with
    the squeeze's own pages ownership-checked back."""
    engine = _engine(params, slots=2, num_pages=13, page_size=4,
                     max_len=24)
    reqs = [
        _req(0, plen=8, new=10, deadline=100.0),
        _req(1, plen=8, new=10, deadline=100.0),
        _req(2, plen=8, new=10, deadline=3.0),  # dies during the squeeze
    ]
    # Tick 2: steal 6 pages for 6 ticks (starves decode growth and the
    # queue); tick 3: the clock jumps past request 2's deadline.
    res = _clock_run(
        engine, reqs,
        plan="squeeze@serve.tick:2?pages=6&ticks=6;slow@serve.tick:3?s=4.0",
    )
    by = {r.rid: r for r in res.requests}
    assert len(res.requests) == 3
    assert all(r.terminal for r in res.requests)
    assert by[2].status == "expired"
    for rid in (0, 1):
        assert by[rid].status == "finished", by[rid].status
        assert len(by[rid].out) == 10
    assert any(e["kind"] == "injected_squeeze" for e in res.events)
    assert any(e["kind"] == "request_expired" for e in res.events)


def test_fault_events_validate_and_report_robustness_table(params):
    """Engine fault events round-trip the obs schema and surface in the
    `mctpu report` robustness table."""
    from mpi_cuda_cnn_tpu.obs.report import render_markdown, summarize
    from mpi_cuda_cnn_tpu.obs.schema import make_record, validate_record

    engine = _engine(params)
    reqs = [_req(0, new=4, deadline=100.0), _req(1, new=4, deadline=0.5)]
    res = _clock_run(engine, reqs, plan="slow@serve.tick:0?s=1.0")
    records = [validate_record(make_record("fault", 0.1, **ev))
               for ev in res.events]
    records += [validate_record(make_record("request", 0.2, **rec))
                for rec in res.request_records()]
    s = summarize(records)
    assert s["robustness"]["by_kind"]["injected_slow"] == 1
    assert s["robustness"]["by_kind"]["request_expired"] == 1
    md = render_markdown(s)
    assert "robustness" in md
    # The per-request table covers aborted requests (null TTFT) without
    # blowing up, and counts statuses.
    row = s["requests"][0]
    assert row["statuses"] == {"finished": 1, "expired": 1}
    assert row["ttft_p50_ms"] is not None  # from the finished request


def test_serve_bench_cli_with_faults_and_deadlines(tmp_path):
    """The serve-bench surface end to end with the failure knobs: fault
    plan, deadlines, queue bound, watchdog. Generous real-time deadline
    so nothing expires on a slow CI box; the squeeze still fires."""
    import json

    from mpi_cuda_cnn_tpu.obs.schema import load_records
    from mpi_cuda_cnn_tpu.serve.bench import serve_bench_main

    sink = tmp_path / "serve.jsonl"
    rc = serve_bench_main([
        "--requests", "6", "--dim", "32", "--depth", "1", "--heads", "2",
        "--vocab", "64", "--max-seq", "128", "--prompt-min", "4",
        "--prompt-max", "12", "--out-min", "4", "--out-max", "12",
        "--slots", "2", "--page-size", "8", "--prefill-chunk", "8",
        "--deadline-ms", "60000", "--max-queue", "64",
        "--watchdog-ms", "60000",
        "--fault-plan", "squeeze@serve.tick:2?pages=2&ticks=3",
        "--metrics-jsonl", str(sink),
    ])
    assert rc == 0
    recs = load_records(sink, strict=True)
    serves = [r for r in recs if r["event"] == "serve"]
    assert len(serves) == 2
    for s in serves:
        assert s["statuses"] == {"finished": 6}
    faults = [r for r in recs if r["event"] == "fault"]
    # One injected squeeze per mode (fresh injector each).
    assert sum(r["kind"] == "injected_squeeze" for r in faults) == 2
    reqs = [r for r in recs if r["event"] == "request"]
    assert all(r["status"] == "finished" for r in reqs)
    assert len(json.dumps(serves[0])) > 0
