"""Causal critical-path attribution (ISSUE 11): `mctpu explain`.

THE acceptance tests live here:
- blame conservation: for every terminal request of a seeded fleet
  storm (crashes + zombie + preemptions + prefix sharing on), the blame
  categories sum BITWISE to the request's end-to-end tick span, and two
  identical-seed storms produce CRC-identical blame;
- `mctpu explain` exits 1 on any drift vs the engine's own records
  (tampered trail), 0 on a clean one, byte-pinned against the
  checked-in golden;
- the SLOScheduler quota skip-over wait is split out of the conflated
  queue-wait histogram (its own registry metric + report column).
"""

import json
from pathlib import Path

import pytest

import jax

from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs.causal import (
    CATEGORIES,
    BlameAccumulator,
    explain_main,
    worst_k,
)
from mpi_cuda_cnn_tpu.obs.metrics import MetricsRegistry
from mpi_cuda_cnn_tpu.obs.schema import (
    dump_records,
    make_record,
    validate_record,
)
from mpi_cuda_cnn_tpu.obs.timeline import trace_main
from mpi_cuda_cnn_tpu.serve.bench import make_workload
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.serve.fleet import Fleet, SimCompute, \
    make_fleet_workload
from mpi_cuda_cnn_tpu.serve.scheduler import SLOPolicy

REPO = Path(__file__).resolve().parents[1]
DATA = REPO / "tests" / "data"

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)


@pytest.fixture(scope="module")
def engine():
    params = MODEL.init(jax.random.key(0))
    # Pool far below the worst case: preemption lifecycles (and their
    # preempted-by blame edges) appear, not just the happy path.
    return PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                       prefill_chunk=8, max_len=40)


def _storm(seed=3, crash=True, detail=False):
    """A seeded sim-fleet storm with a zombie crash, an elastic join,
    preemption pressure (tight per-replica pools) and prefix sharing —
    every blame category except router_wait exercised. Returns
    (FleetResult, BlameAccumulator)."""
    acc = BlameAccumulator(detail=detail)
    plan = ("replica_crash@fleet.tick:40?replica=1&zombie_ticks=3;"
            "replica_join@fleet.tick:120") if crash else None
    fleet = Fleet(
        lambda name: SimCompute(vocab=64, chunk=8, salt=0),
        replicas=3, slots=2, num_pages=12, page_size=4, max_len=64,
        faults=FaultInjector(plan) if plan else None, clock=FakeClock(),
        tick_s=1e-3, prefix=True,
        fleet_sink=acc.ingest_fleet, replica_tick_sink=acc.ingest_tick,
    )
    reqs = make_fleet_workload(n=300, vocab=64, prompt_min=4,
                               prompt_max=24, out_min=4, out_max=24,
                               rate=500.0, seed=seed)
    return fleet.run(reqs), acc


# --------------------------------------------- conservation acceptance


def test_fleet_storm_blame_conserves_and_covers_every_category():
    """THE ISSUE 11 acceptance: every terminal request's categories sum
    bitwise to its end-to-end tick span through crashes, a zombie,
    preemptions, and prefix sharing — and the storm exercises self /
    queued-behind / preempted-by / redispatch-replay blame."""
    res, acc = _storm(detail=True)
    assert res.crashes == 1 and res.redispatches > 0
    assert res.preemptions > 0
    assert acc.check("fleet") == []
    blames = acc.blames()["fleet"]
    assert len(blames) == len(res.requests)
    for b in blames.values():
        assert b.terminal and b.conserved
        assert sum(b.cats.values()) == b.terminal_tick - b.start_tick
    totals = acc.summary_fields("fleet")["categories"]
    assert totals["self_compute"] > 0
    assert totals["queued_behind"] > 0
    assert totals["preempted_by"] > 0
    assert totals["redispatch_replay"] > 0
    # Preemption blame names the beneficiary; queue blame the holders.
    assert any(b.preemptors for b in blames.values())
    assert any(b.blockers for b in blames.values())
    # Replay blame lands exactly on requests the failover stranded.
    replayed = {b.rid for b in blames.values()
                if b.cats["redispatch_replay"]}
    redispatched = {t[1] for t in res.dispatch_trace
                    if t[4] == "redispatch"}
    assert replayed <= redispatched and replayed


def test_identical_seed_storms_blame_crc_identical():
    """Attribution is deterministic: two identical-seed storms fold to
    bitwise-identical blame (the CI gate's run-vs-run property), and a
    different seed does not."""
    _, a = _storm(seed=3)
    _, b = _storm(seed=3)
    assert a.crc("fleet") == b.crc("fleet")
    assert a.summary_fields("fleet") == b.summary_fields("fleet")
    _, c = _storm(seed=4)
    assert a.crc("fleet") != c.crc("fleet")


def test_engine_blame_conservation_and_blocker_edges(engine):
    """Single-engine form: a constrained pool forces page/slot blocks
    and preemptions; blame conserves per request and the blocker edges
    name real co-resident holders."""
    acc = BlameAccumulator(detail=True)
    clock = FakeClock()
    reqs = make_workload(n=8, vocab=13, prompt_min=4, prompt_max=8,
                         out_min=6, out_max=18, rate=40.0, seed=5)
    res = engine.run(reqs, mode="continuous", time_fn=clock,
                     sleep_fn=clock.advance, tick_sink=acc.ingest_tick)
    assert acc.check("continuous") == []
    blames = acc.blames()["continuous"]
    assert len(blames) == len(res.requests)
    rids = set(blames)
    for b in blames.values():
        assert b.conserved
        # A blocker/beneficiary is always another request of this run.
        assert set(b.blockers) <= rids
        assert set(b.preemptors) <= rids


def test_blame_record_is_schema_valid():
    _, acc = _storm()
    rec = make_record("blame", 0.0, **acc.summary_fields("fleet"))
    validate_record(rec)
    assert set(rec["categories"]) == set(CATEGORIES)
    assert rec["conserved"] is True


def test_blocked_note_change_splits_attribution():
    """A queued wait whose block note changes mid-wait bills each
    holder set (and reason) for the ticks it actually blocked — the
    newest note must not absorb the whole segment."""
    acc = BlameAccumulator(detail=True)

    def tick(i, **kw):
        acc.ingest_tick({"mode": "m", "tick": i, "now": float(i), **kw})

    tick(0, arrived=[1])
    tick(0, blocked=[[1, "quota", [2]]])
    for i in range(1, 6):  # 5 ticks quota-blocked behind rid 2
        tick(i, blocked=[[1, "quota", [2]]])
    tick(6, blocked=[[1, "pages", [3]]])  # then 1 tick behind rid 3
    tick(7, admitted=[[0, 1]])
    tick(9, finished=[1],
         terminal=[{"id": 1, "tenant": "default", "status": "finished",
                    "ttft_ms": 1.0, "tpot_ms": 1.0}])
    b = acc.blames()["m"][1]
    assert b.conserved and b.span_ticks == 9
    # rid 2 blocked ticks 0..6 (quota), rid 3 ticks 6..7 (pages).
    assert b.blockers == {2: 6, 3: 1}
    assert b.quota_ticks == 6
    assert b.cats["queued_behind"] == 7 and b.cats["self_compute"] == 2


# ------------------------------------------------------- worst-k selector


def test_worst_k_selector_orders_desc_and_drops_none():
    rows = [{"v": 3}, {"v": None}, {"v": 9}, {"v": 0}, {"v": 9}]
    got = worst_k(rows, lambda r: r["v"], 3)
    assert [r["v"] for r in got] == [9, 9, 3]
    assert worst_k(rows, lambda r: r["v"], 0) == []


# --------------------------------------------------- explain CLI + drift


def _engine_trail(engine, tmp_path, name="run.jsonl"):
    """A serve-bench-shaped JSONL (tick + request + serve + blame) from
    one FakeClock engine run. Everything arrives at t=0 (rate 0 — a
    FakeClock only advances on idle waits, so Poisson arrivals would
    serialize) and output lengths overflow the pool: blocked
    admissions and preemptions appear in the trail."""
    acc = BlameAccumulator()
    ticks = []
    clock = FakeClock()
    reqs = make_workload(n=8, vocab=13, prompt_min=4, prompt_max=8,
                         out_min=12, out_max=28, rate=0.0, seed=5)
    res = engine.run(reqs, mode="continuous", time_fn=clock,
                     sleep_fn=clock.advance,
                     tick_sink=lambda r: (acc.ingest_tick(r),
                                          ticks.append(dict(r))))
    records = [make_record("tick", t["now"], **t) for t in ticks]
    records += [make_record("request", clock.now, **r)
                for r in res.request_records()]
    records.append(make_record("serve", clock.now, **res.summary()))
    records.append(make_record("blame", clock.now,
                               **acc.summary_fields("continuous")))
    path = tmp_path / name
    dump_records(records, path)
    return records, path, res


def test_explain_cli_clean_run_exits_zero(engine, tmp_path, capsys):
    records, path, res = _engine_trail(engine, tmp_path)
    assert explain_main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "| blame (ticks) |" in out and "top blockers" in out
    assert explain_main([str(path), "--worst", "ttft", "-k", "3"]) == 0
    out = capsys.readouterr().out
    assert out.count("request ") == 3 and "conserved yes" in out
    assert explain_main([str(path), "--request", res.requests[0].rid,
                         "--format", "md"]) == 0
    assert explain_main([str(path), "--format", "json"]) == 0
    payload = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert payload["problems"] == [] and payload["inconsistent"] == []
    assert set(payload["aggregate"]["categories"]) == set(CATEGORIES)
    # Live == replay: the blame record stamped by the live fold and the
    # file-replay recomputation agree bitwise (the alerts_crc
    # discipline, ISSUE 8 -> 11).
    stamped = next(r for r in records if r["event"] == "blame")
    assert payload["aggregate"]["crc"] == stamped["crc"]
    assert payload["aggregate"]["categories"] == stamped["categories"]


def test_explain_exits_1_on_drift_vs_engine_records(engine, tmp_path):
    """Tampering with the trail must exit 1 — both halves: a request
    record disagreeing with the reconstruction (the trace-style drift)
    and a trail whose blame cannot conserve (a vanished terminal)."""
    records, _, _ = _engine_trail(engine, tmp_path)
    # Half 1: inflate one request record's output_tokens.
    tampered = [({**r, "output_tokens": r["output_tokens"] + 1}
                 if r["event"] == "request" else r) for r in records]
    p1 = tmp_path / "drift.jsonl"
    dump_records(tampered, p1)
    assert explain_main([str(p1)]) == 1
    # Half 2: drop one tick's finished entry — that rid never reaches a
    # terminal status in the trail, so its blame account is incomplete.
    dropped = False
    tampered2 = []
    for r in records:
        if not dropped and r["event"] == "tick" and r.get("finished"):
            r = {**r, "finished": r["finished"][1:],
                 "terminal": (r.get("terminal") or [])[1:]}
            dropped = True
        tampered2.append(r)
    assert dropped
    p2 = tmp_path / "lost.jsonl"
    dump_records(tampered2, p2)
    assert explain_main([str(p2)]) == 1


def test_explain_rejects_legacy_trail_without_causal_fields(engine,
                                                            tmp_path):
    """A pre-ISSUE-11 trail (tick records without arrived/blocked) is a
    config error (exit 2), not silently-wrong blame."""
    records, _, _ = _engine_trail(engine, tmp_path)
    legacy = [{k: v for k, v in r.items()
               if k not in ("arrived", "blocked", "preempted_for")}
              for r in records]
    path = tmp_path / "legacy.jsonl"
    dump_records(legacy, path)
    assert explain_main([str(path)]) == 2


def test_golden_explain_roundtrip(monkeypatch, capsys):
    """`mctpu explain` on the sample run is byte-for-byte the
    checked-in golden (regenerate via scripts/make_obs_sample.py)."""
    monkeypatch.chdir(REPO)
    rc = explain_main(["tests/data/sample_serve_run.jsonl",
                       "--worst", "ttft", "-k", "2"])
    assert rc == 0
    assert capsys.readouterr().out == \
        (DATA / "golden_serve_explain.md").read_text()


# ------------------------------------------------- trace --slowest N


def test_trace_slowest_selects_worst_by_latency(engine, tmp_path,
                                                capsys):
    records, path, res = _engine_trail(engine, tmp_path)
    assert trace_main([str(path), "--slowest", "2"]) == 0
    out = capsys.readouterr().out
    table = [ln for ln in out.splitlines()
             if ln.startswith("| ") and ln.split("|")[1].strip().isdigit()]
    assert len(table) == 2
    lat = {r["id"]: r["latency_ms"]
           for r in (rec for rec in records if rec["event"] == "request")}
    want = sorted(lat, key=lambda rid: -lat[rid])[:2]
    got = [int(ln.split("|")[1]) for ln in table]
    assert sorted(got) == sorted(want)


# ------------------------------------------- quota skip-over wait split


def test_quota_wait_split_from_capacity_wait(engine):
    """Satellite: under the SLOScheduler a quota-limited tenant's
    skip-over time lands in quota_wait_s (its own registry histogram
    and blame edge kind), while the unlimited tenant's stays zero."""
    registry = MetricsRegistry(clock=FakeClock())
    acc = BlameAccumulator()
    clock = FakeClock()
    reqs = make_workload(n=12, vocab=13, prompt_min=4, prompt_max=8,
                         out_min=6, out_max=14, rate=40.0, seed=7,
                         tenants=2)
    # Under a FakeClock busy ticks are instantaneous, so quota seconds
    # only accrue while something advances the clock — the staggered
    # slow faults ratchet it mid-run (the make_obs_sample recipe).
    faults = FaultInjector(
        ";".join(f"slow@serve.tick:{t}?s=0.05" for t in range(2, 40, 3)),
        clock=clock)
    res = engine.run(reqs, mode="continuous", time_fn=clock,
                     sleep_fn=clock.advance, registry=registry,
                     faults=faults, tick_sink=acc.ingest_tick,
                     policy=SLOPolicy(slot_quota={"t0": 1}))
    assert acc.check("continuous") == []
    quota = {r.rid: r.quota_wait_s for r in res.requests}
    t0 = [r for r in res.requests if r.tenant == "t0"]
    t1 = [r for r in res.requests if r.tenant == "t1"]
    assert len(t0) > 1 and t1
    assert any(quota[r.rid] > 0 for r in t0)  # skip-overs accrued
    assert all(quota[r.rid] == 0 for r in t1)  # unlimited tenant clean
    # The split registry metric exists, tenant-twinned, and only for
    # requests that actually waited on quota.
    h = registry.histograms.get("serve.queue_wait_quota_ms")
    assert h is not None and h.count == sum(1 for r in res.requests
                                            if r.quota_wait_s > 0)
    assert "serve.tenant.t0.queue_wait_quota_ms" in registry.histograms
    assert "serve.tenant.t1.queue_wait_quota_ms" not in registry.histograms
    # Blame sees the same skip-overs as the "quota" edge kind.
    assert acc.summary_fields("continuous")["quota_ticks"] > 0
    # The split is a SUBSET of the total queue wait, never extra time.
    for r in res.requests:
        if r.admitted_at is not None:
            assert r.quota_wait_s <= (r.admitted_at - r.arrival) + 1e-9
    # And the request records carry the column report renders.
    rec = res.request_records()[0]
    assert "queue_wait_quota_ms" in rec


def test_quota_wait_clamped_to_requests_own_presence():
    """A late arrival skipped right after a long admit gap must accrue
    only the time it actually existed, not the whole inter-admit gap —
    otherwise quota wait could exceed the total queue wait."""
    import numpy as np

    from mpi_cuda_cnn_tpu.serve.pool import PagePool
    from mpi_cuda_cnn_tpu.serve.scheduler import Request, SLOScheduler

    sched = SLOScheduler(
        policy=SLOPolicy(slot_quota={"t0": 1}),
        slots=2, pool=PagePool(16), page_size=4, max_len=32,
    )
    occupant = Request(rid=0, prompt=np.arange(4), max_new_tokens=4,
                       arrival=0.0, tenant="t0")
    sched.submit([occupant])
    sched.admit(0.0)  # t0 holds its one slot; _prev_admit_now = 0
    late = Request(rid=1, prompt=np.arange(4), max_new_tokens=4,
                   arrival=9.9, tenant="t0")
    sched.submit([late])
    sched.admit(10.0)  # gap = 10 s, but the request existed for 0.1 s
    assert late.quota_wait_s == pytest.approx(0.1)
