"""bench.py's capture contract (VERDICT round-2 item 1): the driver's
BENCH_r*.json must NEVER be rc=124-with-parsed-null again. The parent
process stays JAX-free and always prints exactly ONE JSON line — success
metrics or {"error": ...} — within its bounded wall-clock budget, even
when the backend hangs at init (the round-2 failure mode: a dead axon
tunnel blocks in C-level code where no Python signal handler runs)."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run_bench(env_extra, timeout):
    env = dict(os.environ, **env_extra)
    return subprocess.run(
        [sys.executable, str(REPO / "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )


def test_bench_emits_error_json_when_attempts_time_out():
    """A child attempt that outlives its cap must be KILLED and recorded.
    BENCH_CHILD_HANG_S makes the child hang deterministically on any
    machine (no assumption about how fast the real bench runs); the
    per-attempt cap sits above bench.py's 10 s minimum-budget floor so a
    real child is spawned and hits subprocess.TimeoutExpired — exactly
    the hang path that produced round 2's empty capture."""
    proc = _run_bench(
        {"BENCH_DEVICE": "cpu", "BENCH_CHILD_HANG_S": "300",
         "BENCH_ATTEMPT_TIMEOUT_S": "12", "BENCH_TOTAL_TIMEOUT_S": "26"},
        timeout=180,
    )
    assert proc.returncode == 1
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    out = json.loads(lines[0])
    assert out["metric"] == "mnist_epoch_wallclock"
    assert out["value"] is None
    # The child really ran and really got killed at its cap.
    assert "timed out after" in out["error"], out["error"]


def test_bench_budget_guard_skips_unspawnable_attempts():
    """A per-attempt budget under the 10 s floor never spawns a doomed
    child; the capture still ends in one JSON error line, fast."""
    proc = _run_bench(
        {"BENCH_DEVICE": "cpu", "BENCH_ATTEMPT_TIMEOUT_S": "2",
         "BENCH_TOTAL_TIMEOUT_S": "8"},
        timeout=60,
    )
    assert proc.returncode == 1
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["value"] is None and "budget" in out["error"]
