"""Elastic training (ISSUE 5): preemption-safe snapshots +
topology-change-tolerant resume.

The acceptance contract, all deterministic on CPU:

- THE cross-width bitwise e2e: a run preempted (injected ``preempt``
  fault) under one data-parallel width and resumed under another — a
  dp=4 -> dp=2 -> dp=8 chain for the CNN, dp=4 -> dp=2 -> dp=4 for the
  LM — lands bitwise on the uninterrupted single-width run, for both
  trainers and (CNN) both the scanned and per-batch paths. This only
  holds because the elastic step's gradient is a canonical balanced-tree
  reduction keyed by --elastic-width, not by the hardware
  (parallel/elastic.py);
- the width-invariance primitive itself: identical train-step results
  at dp=1/2/4 and a demonstration that the PLAIN pmean step does NOT
  have the property (the reason the machinery exists);
- preemption mechanics: the ``preempt`` fault kind parses, a real
  SIGTERM sets the guard and drains an orderly snapshot-exit
  (Preempted, code 75), the CLI maps it to the distinguished exit code,
  and the supervisor passes it through rather than burning restarts;
- topology metadata: the manifest records mesh + elastic width, a
  changed mesh logs a topology_change event, a changed elastic width is
  a hard error;
- multihost checkpoint discipline (mocked ProcessInfo): exactly one
  writer, barrier ordering, non-writers restore the same bytes.
"""

import os
import signal

import numpy as np
import pytest

import jax

from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
from mpi_cuda_cnn_tpu.faults import (
    EXIT_PREEMPTED,
    FaultInjector,
    Preempted,
    PreemptionGuard,
    parse_plan,
    supervise,
)
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.parallel.distributed import ProcessInfo
from mpi_cuda_cnn_tpu.parallel.elastic import (
    check_elastic_width,
    host_shard_rows,
    local_tree_reduce,
    tree_allreduce,
)
from mpi_cuda_cnn_tpu.train.checkpoint import (
    checkpoint_meta,
    restore_checkpoint,
    save_checkpoint,
)
from mpi_cuda_cnn_tpu.train.trainer import Trainer
from mpi_cuda_cnn_tpu.utils.config import Config, LMConfig
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _quiet(capture=False):
    return MetricsLogger(echo=False, capture=capture)


def _cfg(**kw):
    base = dict(
        dataset="synthetic", model="reference_cnn", epochs=2,
        batch_size=16, num_devices=0, eval_every=0, log_every=0,
        lr=0.05, seed=7, elastic_width=16,
    )
    base.update(kw)
    return Config(**base)


def _lm_cfg(**kw):
    base = dict(
        corpus="synthetic", dim=32, depth=2, heads=4, seq_len=32,
        steps=6, batch_size=8, log_every=0, warmup_steps=2,
        elastic_width=8, num_devices=0,
    )
    base.update(kw)
    return LMConfig(**base)


def _ds():
    return synthetic_stripes(num_train=64, num_test=32)  # 4 steps/epoch


def _params_of(t):
    return jax.device_get(t.state["params"])


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _trees_equal(a, b) -> bool:
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


# ------------------------------------------------------------ primitives


def test_check_elastic_width_rules():
    check_elastic_width(8, 16, 4)
    with pytest.raises(ValueError, match="power of two"):
        check_elastic_width(6, 12, 2)
    with pytest.raises(ValueError, match="divide batch_size"):
        check_elastic_width(8, 12, 2)
    with pytest.raises(ValueError, match="power-of-two data-axis"):
        check_elastic_width(16, 48, 3)
    with pytest.raises(ValueError, match=">= 2x"):
        check_elastic_width(4, 16, 4)  # would leave 1 microbatch/device


def test_tree_allreduce_sums_over_ranks(eight_devices):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))

    def body(x):
        return tree_allreduce({"v": x}, "data", 4)["v"]

    f = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("data"),
                              out_specs=P("data"), check_vma=False))
    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    got = jax.device_get(f(jax.device_put(
        x, NamedSharding(mesh, P("data")))))
    # Every rank ends with the elementwise sum of the four local blocks.
    want = np.tile(x.reshape(4, 2, 2).sum(axis=0), (4, 1))
    np.testing.assert_array_equal(got, want)


def test_local_tree_reduce_is_balanced_sum():
    x = np.arange(8, dtype=np.float32)
    got = local_tree_reduce({"v": x})["v"]
    assert float(got) == x.sum()


def test_host_shard_rows_partitions_exactly():
    rows = [host_shard_rows(16, i, 4) for i in range(4)]
    assert rows == [(0, 4), (4, 8), (8, 12), (12, 16)]
    with pytest.raises(ValueError, match="not divisible"):
        host_shard_rows(10, 0, 4)


def test_elastic_step_is_width_invariant_and_pmean_is_not(eight_devices):
    """The core numerics claim, isolated at one train step x 2: the
    elastic step's updated params are bitwise identical at dp=1/2/4;
    the plain pmean step's are not (which is WHY the elastic reduction
    exists — if this half ever starts passing, the plain step became
    width-invariant and the elastic machinery can be retired)."""
    ds = _ds()

    def run(n, elastic):
        cfg = _cfg(mesh_shape=f"data:{n}", epochs=1, scan=False,
                   elastic_width=16 if elastic else 0)
        t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
        t.run_epoch(0)
        return _params_of(t)

    elastic = [run(n, True) for n in (1, 2, 4)]
    assert _trees_equal(elastic[0], elastic[1])
    assert _trees_equal(elastic[0], elastic[2])
    plain = [run(n, False) for n in (1, 4)]
    assert not _trees_equal(plain[0], plain[1]), (
        "the plain pmean step became width-invariant — the elastic "
        "reduction may no longer be needed"
    )


def test_elastic_metrics_match_plain_scale():
    """Metrics keep their scale under the elastic step: every metric
    make_loss_fn returns is mean-semantics (etotal divides by its
    batch size — ops/losses.squared_error_total), so the mean over
    canonical microbatches equals the plain step's per-batch value and
    enabling elasticity cannot silently rescale the logged stream."""
    ds = _ds()
    ems = []
    for ew in (0, 16):
        cfg = _cfg(mesh_shape="data:1", epochs=1, scan=False,
                   elastic_width=ew)
        t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
        ems.append(t.run_epoch(0))
    assert ems[0]["etotal"] == pytest.approx(ems[1]["etotal"], rel=1e-4)
    assert ems[0]["loss"] == pytest.approx(ems[1]["loss"], rel=1e-4)


def test_elastic_augment_keys_on_canonical_shard(eight_devices):
    """Augmentation under the elastic step folds the GLOBAL canonical
    shard index into its key — not the device rank — so the augmented
    pixel stream (and therefore the trajectory) stays width-invariant."""
    ds = _ds()
    outs = []
    for n in (1, 4):
        cfg = _cfg(mesh_shape=f"data:{n}", epochs=1, scan=False,
                   augment="shift")
        t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
        t.run_epoch(0)
        outs.append(_params_of(t))
    assert _trees_equal(outs[0], outs[1])


# ------------------------------------------------- cross-width bitwise e2e


@pytest.mark.parametrize("scan", [True, False])
def test_cnn_preempt_resume_across_widths_bitwise(tmp_path, scan):
    """THE acceptance e2e (CNN, scan and loop paths): a run preempted
    at dp=4 (injected preempt fault -> snapshot -> exit 75), resumed at
    dp=2, preempted again, resumed at dp=8 to completion, is BITWISE
    equal to the uninterrupted single-width run — the full
    shrink-then-grow round trip on one checkpoint directory."""
    ds = _ds()
    full = Trainer(get_model("reference_cnn"), ds,
                   _cfg(scan=scan, mesh_shape="data:2"), metrics=_quiet())
    full.train()
    want = _params_of(full)

    ck = tmp_path / "ck"
    metrics = _quiet(capture=True)

    def attempt(width, plan):
        t = Trainer(
            get_model("reference_cnn"), ds,
            _cfg(scan=scan, mesh_shape=f"data:{width}",
                 checkpoint_dir=str(ck), checkpoint_every_steps=3,
                 resume=True),
            metrics=metrics,
            faults=FaultInjector(plan) if plan else None,
        )
        return t, t.train()

    with pytest.raises(Preempted):
        attempt(4, "preempt@train.step:3")
    assert (ck / "ckpt_3.npz").exists()
    with pytest.raises(Preempted):
        attempt(2, "preempt@train.step:6")
    assert (ck / "ckpt_6.npz").exists()
    t, res = attempt(8, None)

    assert res.final_step == full._global_step()
    _assert_trees_equal(want, _params_of(t))
    kinds = [r["kind"] for r in metrics.rows if r["event"] == "fault"]
    assert kinds.count("preempt") == 2
    assert kinds.count("injected_preempt") == 2
    # Both resumes crossed a topology change and said so.
    assert kinds.count("topology_change") == 2
    reasons = [r["reason"] for r in metrics.rows if r["event"] == "ckpt"]
    assert reasons.count("preempt") == 2
    assert reasons.count("resume") == 2


def test_lm_preempt_resume_across_widths_bitwise(tmp_path):
    """THE acceptance e2e (LM trainer): dp=4 -> preempt -> dp=2 ->
    preempt -> dp=4, bitwise equal to the uninterrupted run."""
    full = LMTrainerFactory(_lm_cfg(mesh_shape="data:2"))
    full.train()
    want = _params_of(full)

    ck = tmp_path / "ck"
    metrics = _quiet(capture=True)

    def attempt(width, plan):
        from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer

        t = LMTrainer(
            _lm_cfg(mesh_shape=f"data:{width}", checkpoint_dir=str(ck),
                    checkpoint_every=2, resume=True),
            metrics=metrics,
            faults=FaultInjector(plan) if plan else None,
        )
        return t, t.train()

    with pytest.raises(Preempted):
        attempt(4, "preempt@train.step:2")
    with pytest.raises(Preempted):
        attempt(2, "preempt@train.step:4")
    t, res = attempt(4, None)

    _assert_trees_equal(want, _params_of(t))
    kinds = [r["kind"] for r in metrics.rows if r["event"] == "fault"]
    assert kinds.count("preempt") == 2
    assert kinds.count("topology_change") == 2


def LMTrainerFactory(cfg, **kw):
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer

    return LMTrainer(cfg, metrics=_quiet(), **kw)


# ------------------------------------------------------ preemption mechanics


def test_preempt_kind_parses_and_fires_once():
    (f,) = parse_plan("preempt@train.step:3")
    assert (f.kind, f.site, f.at) == ("preempt", "train.step", 3)
    inj = FaultInjector("preempt@train.step:3")
    hits = inj.fire("train.step", 3)  # soft kind: returned, not raised
    assert [h.kind for h in hits] == ["preempt"]
    assert inj.fire("train.step", 3) == []


def test_sigterm_sets_guard_and_restores_handler():
    guard = PreemptionGuard()
    prev = signal.getsignal(signal.SIGTERM)
    with guard:
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.requested
        assert guard.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is prev


def test_sigterm_drains_orderly_snapshot_exit(tmp_path):
    """A real SIGTERM mid-run: the trainer finishes the in-flight step,
    writes the snapshot durably, and exits Preempted with code 75 —
    the checkpoint restores."""
    ds = _ds()
    guard = PreemptionGuard().install()
    try:
        t = Trainer(
            get_model("reference_cnn"), ds,
            _cfg(scan=False, checkpoint_dir=str(tmp_path / "ck"),
                 checkpoint_every_steps=0),
            metrics=_quiet(), preempt=guard,
        )
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(Preempted) as ei:
            t.train()
        assert ei.value.code == EXIT_PREEMPTED
    finally:
        guard.uninstall()
    # The snapshot landed at the first step boundary and restores.
    resumed = Trainer(
        get_model("reference_cnn"), ds,
        _cfg(scan=False, checkpoint_dir=str(tmp_path / "ck"), resume=True),
        metrics=_quiet(),
    )
    res = resumed.train()
    assert res.final_step == 8


def test_supervisor_passes_preemption_through():
    """A preemption is not a crash: supervise must NOT burn restarts
    replaying it in-process — the relaunch happens out-of-process, on
    the next placement."""
    attempts = []

    def attempt(n):
        attempts.append(n)
        raise Preempted("preempted at step 3")

    with pytest.raises(Preempted):
        supervise(attempt, max_restarts=3)
    assert attempts == [0]


def test_cli_preempt_exit_code_and_resume(tmp_path):
    """Through the CLI: an injected preemption exits EXIT_PREEMPTED
    (75) with the snapshot on disk; the relaunch with --resume
    completes and exits 0."""
    from mpi_cuda_cnn_tpu import cli

    args = [
        "train", "--dataset", "synthetic", "--model", "reference_cnn",
        "--epochs", "1", "--batch-size", "500", "--num-devices", "1",
        "--eval-every", "0", "--log-every", "0", "--device", "cpu",
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--checkpoint-every-steps", "1",
    ]
    rc = cli.main(args + ["--fault-plan", "preempt@train.step:2"])
    assert rc == EXIT_PREEMPTED
    assert (tmp_path / "ck" / "ckpt_2.npz").exists()
    assert cli.main(args + ["--resume"]) == 0


# ------------------------------------------------------- config validation


def test_elastic_width_rejects_sharded_state_meshes(eight_devices):
    ds = _ds()
    with pytest.raises(ValueError, match="pure data-parallel"):
        Trainer(get_model("reference_cnn"), ds,
                _cfg(mesh_shape="data:2,model:2"), metrics=_quiet())
    with pytest.raises(ValueError, match="pure data-parallel"):
        Trainer(get_model("reference_cnn"), ds,
                _cfg(mesh_shape="data:2", fsdp=True), metrics=_quiet())
    with pytest.raises(ValueError, match="grad-accum"):
        Trainer(get_model("reference_cnn"), ds,
                _cfg(mesh_shape="data:2", grad_accum=2), metrics=_quiet())
    with pytest.raises(ValueError, match="pure data-parallel"):
        LMTrainerFactory(_lm_cfg(mesh_shape="data:2,seq:2", seq_len=32))


def test_resume_with_changed_elastic_width_is_an_error(tmp_path):
    """The reduction tree is keyed by W0 — silently resuming with a
    different width would break the bitwise contract mid-run."""
    ds = _ds()
    ck = tmp_path / "ck"
    t = Trainer(get_model("reference_cnn"), ds,
                _cfg(epochs=1, checkpoint_dir=str(ck),
                     checkpoint_every_steps=2),
                metrics=_quiet())
    t.train()
    with pytest.raises(ValueError, match="elastic-width"):
        Trainer(get_model("reference_cnn"), ds,
                _cfg(epochs=1, elastic_width=8, checkpoint_dir=str(ck),
                     resume=True),
                metrics=_quiet()).train()


# -------------------------------------------------- checkpoint meta/multihost


def _state(seed=0):
    from mpi_cuda_cnn_tpu.models.initializers import get_initializer
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
    import jax.numpy as jnp

    model = get_model("reference_cnn")
    params = model.init(jax.random.key(seed), get_initializer("normal"))
    opt = make_optimizer(0.1, momentum=0.9)
    return {"params": params, "opt_state": opt.init(params),
            "step": jnp.asarray(7, jnp.int32)}


def test_manifest_records_topology_meta(tmp_path):
    meta = {"mesh": {"axes": {"data": 4}, "devices": 4},
            "elastic_width": 8, "process_count": 1}
    save_checkpoint(tmp_path, _state(), 3, meta=meta)
    assert checkpoint_meta(tmp_path, "ckpt_3.npz") == meta
    assert checkpoint_meta(tmp_path, "ckpt_999.npz") is None
    # Pruned checkpoints leave the meta table with their checksums.
    for step in (6, 9, 12):
        save_checkpoint(tmp_path, _state(), step, keep=2, meta=meta)
    import json

    mf = json.loads((tmp_path / "manifest.json").read_text())
    assert set(mf["meta"]) == {"ckpt_9.npz", "ckpt_12.npz"}


def test_prune_never_deletes_protected_checkpoint(tmp_path):
    """ISSUE 5 satellite: the checkpoint the current run resumed from
    survives keep-pruning — a crash before the next save always has a
    known-good restore point behind it."""
    state = _state()
    for step in range(6):
        save_checkpoint(tmp_path, state, step, keep=2,
                        protect="ckpt_0.npz")
    names = sorted(p.name for p in tmp_path.glob("ckpt_*.npz"))
    assert "ckpt_0.npz" in names
    assert names[-2:] == ["ckpt_4.npz", "ckpt_5.npz"]
    # The protected file stays restorable (its checksums were kept).
    restored = restore_checkpoint(tmp_path / "ckpt_0.npz", _state(1))
    _assert_trees_equal(jax.device_get(state), restored)


def test_multihost_exactly_one_writer_with_barrier_ordering(tmp_path):
    """ISSUE 5 satellite: mocked N=3 process set — process 0 is the
    only writer, every process meets the barrier, the writer's barrier
    fires AFTER its rename (so a non-writer that passed the barrier can
    rely on the file), and non-writers restore the same bytes."""
    state = _state()
    calls = []

    def barrier_for(pid):
        def barrier(name):
            calls.append((pid, name, (tmp_path / "ckpt_5.npz").exists()))
        return barrier

    # Non-writers: no file activity, one barrier visit each.
    for pid in (1, 2):
        p = ProcessInfo(pid, 3, 2, 6)
        path = save_checkpoint(tmp_path, state, 5, process=p,
                               barrier=barrier_for(pid))
        assert path.name == "ckpt_5.npz"
    assert not list(tmp_path.glob("*"))  # nothing written by non-writers
    # Writer: file + manifest land, THEN its barrier fires.
    p0 = ProcessInfo(0, 3, 2, 6)
    path = save_checkpoint(tmp_path, state, 5, process=p0,
                           barrier=barrier_for(0))
    assert [(pid, seen) for pid, _, seen in calls] == [
        (1, False), (2, False), (0, True),
    ]
    # Step-keyed fence: saves for different steps can never silently
    # rendezvous with each other.
    assert all(name == "ckpt_save_5" for _, name, _ in calls)
    # Every process (the non-writers included) restores the same bytes.
    restored = restore_checkpoint(path, _state(1))
    _assert_trees_equal(jax.device_get(state), restored)


def test_async_checkpointer_skips_write_on_non_writer(tmp_path):
    from mpi_cuda_cnn_tpu.train.checkpoint import AsyncCheckpointer

    hits = []
    ck = AsyncCheckpointer(tmp_path, process=ProcessInfo(1, 2, 4, 8),
                           barrier=lambda name: hits.append(name))
    ck.save(_state(), 3)
    ck.close()
    assert not list(tmp_path.glob("ckpt_*.npz"))
    assert hits == ["ckpt_save_3"]  # step-keyed fence
