"""Flash-attention Pallas kernel (ops/pallas_attention.py): parity with
the oracle in interpreter mode, gradients, block picking, shape guards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.ops.attention import attention
from mpi_cuda_cnn_tpu.ops.pallas_attention import _pick_block, flash_attention


def _qkv(b, s, h, d, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("b,s,h,d", [(2, 256, 2, 64), (1, 384, 4, 32),
                                     (1, 1024, 2, 128)])
def test_flash_matches_oracle(causal, b, s, h, d):
    q, k, v = _qkv(b, s, h, d)
    got = flash_attention(q, k, v, causal)
    want = attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [256, 512])
def test_flash_gradients_match_oracle(causal, s):
    """Gradient parity at default block caps (single-block at these
    sizes; the multi-block paths are covered below)."""
    q, k, v = _qkv(1, s, 2, 64, seed=1)

    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal) ** 2)

    def loss_o(q, k, v):
        return jnp.sum(attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_o, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_multiblock(monkeypatch, causal):
    """Force several q/k blocks so the backward's scratch accumulation,
    causal block-skip, and lse/dvec block index maps all run (the default
    caps would make s=512 a single block)."""
    import mpi_cuda_cnn_tpu.ops.pallas_attention as fa

    monkeypatch.setattr(fa, "BLK_Q", 128)
    monkeypatch.setattr(fa, "BLK_K", 128)
    q, k, v = _qkv(1, 512, 2, 64, seed=3)

    gf = jax.grad(
        lambda q, k, v: jnp.sum(fa.flash_attention(q, k, v, causal) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    go = jax.grad(
        lambda q, k, v: jnp.sum(attention(q, k, v, causal=causal) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(gf, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bf16_gradients_multiblock(monkeypatch, causal):
    """bf16 reads its OWN block caps (_blocks dtype dispatch): force
    several blocks through the bf16 kernels so the multi-block carry/
    skip/index paths of the production LM configuration are exercised,
    not just the single-block small-s cases."""
    import mpi_cuda_cnn_tpu.ops.pallas_attention as fa

    monkeypatch.setattr(fa, "BLK_Q_BF16", 128)
    monkeypatch.setattr(fa, "BLK_K_BF16", 128)
    q, k, v = _qkv(1, 512, 2, 64, seed=5)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))

    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            fa.flash_attention(q, k, v, causal).astype(jnp.float32) ** 2
        ),
        argnums=(0, 1, 2),
    )(qb, kb, vb)
    go = jax.grad(
        lambda q, k, v: jnp.sum(attention(q, k, v, causal=causal) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(gf, go):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_), rtol=8e-2, atol=8e-2
        )


def test_pick_block():
    assert _pick_block(8192, 512) == 512
    assert _pick_block(256, 512) == 256
    assert _pick_block(384, 512) == 384
    assert _pick_block(640, 512) == 128   # 640 = 5 * 128
    assert _pick_block(1024, 1024) == 1024


def test_flash_rejects_unaligned_seq():
    q, k, v = _qkv(1, 130, 2, 64)
    with pytest.raises(ValueError, match="multiple of 128"):
        flash_attention(q, k, v)


def test_flash_bf16_inputs_roundtrip():
    q, k, v = _qkv(1, 256, 2, 64, seed=2)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = flash_attention(qb, kb, vb, True)
    assert out.dtype == jnp.bfloat16
    want = attention(qb.astype(jnp.float32), kb.astype(jnp.float32),
                     vb.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(want), rtol=5e-2, atol=5e-2
    )


def test_flash_bf16_gradients_match_oracle():
    """bf16-native kernels (bf16 MXU operands, f32 accumulators): the
    fused backward must track the f32 oracle to bf16-rounding accuracy."""
    q, k, v = _qkv(2, 128, 2, 64, seed=3)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True).astype(jnp.float32) ** 2)

    def oracle(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    got = jax.grad(f, argnums=(0, 1, 2))(qb, kb, vb)
    want = jax.grad(oracle, argnums=(0, 1, 2))(
        qb.astype(jnp.float32), kb.astype(jnp.float32), vb.astype(jnp.float32)
    )
    for g, w in zip(got, want):
        assert g.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(w), rtol=8e-2, atol=8e-2
        )
