"""`mctpu lint` (mpi_cuda_cnn_tpu/analysis, ISSUE 10).

Per rule MCT001-MCT007: a fixture snippet that MUST fire (pinned rule
id AND line — deleting a rule's implementation fails its fixture test)
and a clean twin that MUST stay silent. Plus: the self-lint acceptance
(the shipped tree is finding-free under the checked-in manifest), the
suppression mechanics, the baseline round-trip, and the CLI contract
(exit codes 0/1/2, JSON format).
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from mpi_cuda_cnn_tpu.analysis import (
    ALL_RULES,
    LintError,
    all_rules,
    lint_paths,
    load_manifest,
    write_baseline,
)
from mpi_cuda_cnn_tpu.analysis.baseline import apply_baseline, load_baseline
from mpi_cuda_cnn_tpu.analysis.cli import lint_main
from mpi_cuda_cnn_tpu.analysis.manifest import HotLoop, Manifest

REPO = Path(__file__).resolve().parents[1]


def run_lint(tmp_path, files: dict[str, str], *, manifest=None,
             rules=None, paths=None):
    for rel, source in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(source)
    return lint_paths(paths or list(files), root=tmp_path,
                      manifest=manifest or Manifest(), rules=rules)


def keys(findings):
    return [(f.rule, f.path, f.line) for f in findings]


# -- rule registry ------------------------------------------------------


def test_all_seven_rules_registered():
    assert [cls.rule_id for cls in ALL_RULES] == [
        "MCT001", "MCT002", "MCT003", "MCT004", "MCT005", "MCT006",
        "MCT007",
    ]


# -- MCT001 jax-purity --------------------------------------------------


def test_mct001_fires_on_jax_import_and_unfree_first_party(tmp_path):
    src = ("import jax\n"
           "from .helper import thing\n"
           "def f():\n"
           "    import jax.numpy as jnp\n"
           "    return jnp, thing\n")
    found = run_lint(tmp_path, {"mod.py": src},
                     manifest=Manifest(jax_free=frozenset({"mod.py"})))
    assert keys(found) == [
        ("MCT001", "mod.py", 1),   # import jax
        ("MCT001", "mod.py", 2),   # first-party helper.py not declared
        ("MCT001", "mod.py", 4),   # lazy jax import is still a finding
    ]


def test_mct001_clean_twin(tmp_path):
    src = ("import dataclasses\n"
           "import numpy as np\n"
           "from .helper import thing\n")
    manifest = Manifest(jax_free=frozenset({"mod.py", "helper.py"}))
    assert run_lint(tmp_path, {"mod.py": src}, manifest=manifest) == []
    # And an UNDECLARED module may import jax freely.
    assert run_lint(tmp_path, {"other.py": "import jax\n"},
                    manifest=manifest) == []


# -- MCT002 clock discipline --------------------------------------------


def test_mct002_fires_on_wall_clock_read(tmp_path):
    src = ("import time\n"
           "deadline = time.monotonic() + 5\n"
           "t0 = time.time()\n")
    found = run_lint(tmp_path, {"mod.py": src})
    assert keys(found) == [("MCT002", "mod.py", 2), ("MCT002", "mod.py", 3)]


def test_mct002_catches_alias_and_from_import_evasion(tmp_path):
    """Aliased modules and from-imports resolve through the file's own
    import bindings — the spellings that used to slip past a literal
    dotted-chain match."""
    src = ("import time as t\n"
           "from datetime import datetime as dt\n"
           "from time import monotonic\n"
           "a = t.monotonic()\n"
           "b = dt.now()\n")
    found = run_lint(tmp_path, {"mod.py": src})
    assert keys(found) == [
        ("MCT002", "mod.py", 3),   # the from-import IS the evasion
        ("MCT002", "mod.py", 4),   # t.monotonic -> time.monotonic
        ("MCT002", "mod.py", 5),   # dt.now -> datetime.datetime.now
    ]


def test_mct002_clean_twin(tmp_path):
    # perf_counter is the injectable-clock default convention, and the
    # allowlisted clock module may read the real clock.
    src = ("import time\n"
           "def f(clock=time.perf_counter):\n"
           "    return clock()\n")
    assert run_lint(tmp_path, {"mod.py": src}) == []
    clock_src = "import time\nnow = time.monotonic()\n"
    manifest = Manifest(clock_modules=frozenset({"clock.py"}))
    assert run_lint(tmp_path, {"clock.py": clock_src},
                    manifest=manifest) == []


# -- MCT003 donation discipline -----------------------------------------


def test_mct003_fires_on_raw_donate_argnums(tmp_path):
    src = ("import jax\n"
           "step = jax.jit(lambda s: s, donate_argnums=(0,))\n")
    found = run_lint(tmp_path, {"mod.py": src})
    assert keys(found) == [("MCT003", "mod.py", 2)]
    # donate_argnames is the same violation.
    src2 = "f = g(donate_argnames=('state',))\n"
    assert keys(run_lint(tmp_path, {"m2.py": src2})) == \
        [("MCT003", "m2.py", 1)]


def test_mct003_clean_twin(tmp_path):
    # The donation module itself holds the one sanctioned spelling.
    src = ("import jax\n"
           "def donate_jit(fn, argnums=(0,), **kw):\n"
           "    return jax.jit(fn, donate_argnums=argnums, **kw)\n")
    manifest = Manifest(donation_module="donation.py")
    assert run_lint(tmp_path, {"donation.py": src},
                    manifest=manifest) == []
    # Callers using donate_jit are clean.
    assert run_lint(tmp_path, {"user.py": "step = donate_jit(f)\n"},
                    manifest=manifest) == []


# -- MCT004 RNG discipline ----------------------------------------------


def test_mct004_fires_on_global_rng(tmp_path):
    src = ("import random\n"
           "import numpy as np\n"
           "x = random.random()\n"
           "y = np.random.rand(3)\n"
           "np.random.seed(0)\n")
    found = run_lint(tmp_path, {"mod.py": src})
    assert keys(found) == [
        ("MCT004", "mod.py", 3),
        ("MCT004", "mod.py", 4),
        ("MCT004", "mod.py", 5),
    ]


def test_mct004_clean_twin(tmp_path):
    # Seeded generators everywhere; `from jax import random` binds the
    # SAME spelling to seeded-key threading and must not fire; tests
    # are exempt wholesale.
    src = ("import numpy as np\n"
           "rng = np.random.default_rng(0)\n"
           "g = np.random.Generator(np.random.PCG64(1))\n")
    assert run_lint(tmp_path, {"mod.py": src}) == []
    jax_src = ("from jax import random\n"
               "k = random.split(random.PRNGKey(0))\n")
    assert run_lint(tmp_path, {"m2.py": jax_src}) == []
    test_src = "import random\nx = random.random()\n"
    assert run_lint(tmp_path, {"test_m.py": test_src}) == []


# -- MCT005 schema-family cross-check -----------------------------------


def test_mct005_fires_on_unregistered_family(tmp_path):
    src = ("metrics.log(\"not_a_family\", step=1)\n"
           "rec = make_record(\"bogus_event\", 0.0, x=1)\n")
    found = run_lint(tmp_path, {"mod.py": src})
    assert keys(found) == [("MCT005", "mod.py", 1), ("MCT005", "mod.py", 2)]


def test_mct005_clean_twin(tmp_path):
    # Registered families (the LIVE obs.schema registry) are silent,
    # as are non-literal first args and unrelated .log call shapes.
    src = ("metrics.log(\"train\", step=1, loss=0.5)\n"
           "rec = make_record(\"bench\", 0.0, metric=\"m\", value=1)\n"
           "metrics.log(event, step=2)\n"
           "import math\n"
           "y = math.log(2.0)\n")
    assert run_lint(tmp_path, {"mod.py": src}) == []


# -- MCT006 fault-site cross-check --------------------------------------


def test_mct006_fires_on_unknown_site(tmp_path):
    src = ("for f in faults.fire(\"serve.tock\", i):\n"
           "    pass\n")
    found = run_lint(tmp_path, {"mod.py": src})
    assert keys(found) == [("MCT006", "mod.py", 1)]


def test_mct006_clean_twin(tmp_path):
    src = ("faults.fire(\"serve.tick\", i)\n"
           "faults.fire(\"fleet.tick\", t)\n"
           "faults.fire(site, t)\n")
    assert run_lint(tmp_path, {"mod.py": src}) == []


# -- MCT007 host-sync-in-hot-loop ---------------------------------------

HOT = Manifest(hot_loops={
    "mod.py": HotLoop(functions=frozenset({"run"}),
                      producers=frozenset({"self._tick"})),
})


def test_mct007_fires_on_device_value_sync(tmp_path):
    src = ("import numpy as np\n"
           "class E:\n"
           "    def run(self):\n"
           "        cache, nxt = self._tick(1)\n"
           "        a = int(nxt)\n"
           "        b = np.asarray(nxt)\n"
           "        c = nxt.item()\n"
           "        return a, b, c\n")
    found = run_lint(tmp_path, {"mod.py": src}, manifest=HOT)
    assert keys(found) == [
        ("MCT007", "mod.py", 5),
        ("MCT007", "mod.py", 6),
        ("MCT007", "mod.py", 7),
    ]


def test_mct007_clean_twin(tmp_path):
    # Reassignment from a non-producer clears taint (the engine.run
    # decode path: nxt is rebound to an already-host array); functions
    # outside the manifest's hot set are not scanned; host values
    # convert freely.
    src = ("class E:\n"
           "    def run(self):\n"
           "        cache, nxt = self._tick(1)\n"
           "        self.stash(nxt)\n"
           "        nxt = self.decode_host(2)\n"
           "        n = int(nxt)\n"
           "        return n, int(self.counter)\n"
           "    def cold(self):\n"
           "        _, nxt = self._tick(1)\n"
           "        return int(nxt)\n")
    assert run_lint(tmp_path, {"mod.py": src}, manifest=HOT) == []


# -- suppressions -------------------------------------------------------


def test_suppression_same_line_and_line_above(tmp_path):
    src = ("import time\n"
           "a = time.monotonic()  # mctpu: disable=MCT002\n"
           "# mctpu: disable=MCT002\n"
           "b = time.monotonic()\n"
           "c = time.monotonic()\n")
    found = run_lint(tmp_path, {"mod.py": src})
    # Only the unsuppressed line fires; a pragma covers ITS line and
    # the next code line, never further.
    assert keys(found) == [("MCT002", "mod.py", 5)]


def test_suppression_tolerates_trailing_prose(tmp_path):
    """A reason after the rule id — the natural spelling the README
    encourages — must not be swallowed into the token (a pragma that
    visibly exists but suppresses nothing is worse than none)."""
    src = ("import time\n"
           "a = time.monotonic()  # mctpu: disable=MCT002 injectable\n"
           "# mctpu: disable=MCT002, MCT004 both deliberate here\n"
           "b = time.monotonic()\n")
    assert run_lint(tmp_path, {"mod.py": src}) == []


def test_suppression_is_rule_specific(tmp_path):
    src = ("import time\n"
           "a = time.monotonic()  # mctpu: disable=MCT004\n")
    found = run_lint(tmp_path, {"mod.py": src})
    assert keys(found) == [("MCT002", "mod.py", 2)]


# -- baseline round-trip ------------------------------------------------


def test_baseline_round_trip(tmp_path):
    src = "import time\nx = time.time()\n"
    found = run_lint(tmp_path, {"mod.py": src})
    assert len(found) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(found, bl)
    known = load_baseline(bl)
    assert apply_baseline(found, known) == []
    # A NEW finding on another line is not absorbed by the baseline.
    src2 = "import time\nx = time.time()\ny = time.monotonic()\n"
    found2 = run_lint(tmp_path, {"mod.py": src2})
    left = apply_baseline(found2, known)
    assert keys(left) == [("MCT002", "mod.py", 3)]


def test_out_of_root_path_is_config_error(tmp_path):
    """A scanned path outside the root cannot key findings root-
    relatively — LintError (the CLI's exit-2 contract), never a raw
    ValueError traceback from relative_to."""
    outside = tmp_path / "elsewhere" / "mod.py"
    outside.parent.mkdir()
    outside.write_text("import time\n")
    root = tmp_path / "repo"
    root.mkdir()
    with pytest.raises(LintError, match="outside the repo root"):
        lint_paths([str(outside)], root=root, manifest=Manifest())


def test_baseline_rejects_bad_files(tmp_path):
    bad = tmp_path / "b.json"
    bad.write_text("{\"version\": 99, \"findings\": []}")
    with pytest.raises(LintError):
        load_baseline(bad)
    with pytest.raises(LintError):
        load_baseline(tmp_path / "missing.json")


# -- CLI ----------------------------------------------------------------


def _write_cli_fixture(tmp_path, source: str) -> Path:
    (tmp_path / "pyproject.toml").write_text("[project]\nname='x'\n")
    ci = tmp_path / "ci"
    ci.mkdir()
    manifest = ci / "lint_manifest.json"
    manifest.write_text(json.dumps({"paths": ["mod.py"], "jax_free": []}))
    (tmp_path / "mod.py").write_text(source)
    return manifest


def test_cli_exit_codes_and_json(tmp_path, capsys):
    manifest = _write_cli_fixture(tmp_path, "import time\nt = time.time()\n")
    rc = lint_main(["--manifest", str(manifest), "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [(f["rule"], f["path"], f["line"]) for f in out["findings"]] == \
        [("MCT002", "mod.py", 2)]
    # --rule filters; a rule that cannot fire here exits clean.
    assert lint_main(["--manifest", str(manifest), "--rule", "MCT004"]) == 0
    # Unknown rule / missing manifest are config errors (exit 2).
    assert lint_main(["--manifest", str(manifest), "--rule", "MCT999"]) == 2
    assert lint_main(["--manifest", str(tmp_path / "nope.json")]) == 2
    capsys.readouterr()


def test_cli_write_baseline_round_trip(tmp_path, capsys):
    manifest = _write_cli_fixture(tmp_path, "import time\nt = time.time()\n")
    bl = tmp_path / "ci" / "lint_baseline.json"
    assert lint_main(["--manifest", str(manifest),
                      "--write-baseline", str(bl)]) == 0
    assert lint_main(["--manifest", str(manifest),
                      "--baseline", str(bl)]) == 0
    # Without the baseline the finding still gates.
    assert lint_main(["--manifest", str(manifest)]) == 1
    capsys.readouterr()


# -- self-lint acceptance -----------------------------------------------


def test_shipped_tree_is_finding_free():
    """ISSUE 10 acceptance: `mctpu lint` reports ZERO findings on the
    shipped tree under the checked-in manifest — violations are fixed
    or carry a commented suppression at the site, never debt."""
    manifest = load_manifest(REPO / "ci" / "lint_manifest.json")
    findings = lint_paths(list(manifest.paths), root=REPO,
                          manifest=manifest, rules=all_rules())
    assert findings == [], "\n".join(f.render() for f in findings)


def test_checked_in_baseline_is_empty():
    known = load_baseline(REPO / "ci" / "lint_baseline.json")
    assert known == set(), (
        "ci/lint_baseline.json must stay a zero-entry baseline — fix "
        "or suppress new findings at the site instead"
    )
