"""Telemetry subsystem (obs/) tests.

Coverage per the subsystem's contract: cost-analysis FLOPs within
tolerance of a hand count on a tiny dense model, collective-count
extraction on a 2-device CPU-mesh psum step, JSONL schema round-trip,
a memory_stats smoke that skips cleanly on backends without allocator
stats, the StepTimer guard rails, and the MetricsLogger context
manager. The trainer-integration test drives the real CLI path the
acceptance criterion names.
"""

import json

import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from mpi_cuda_cnn_tpu import obs
from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger
from mpi_cuda_cnn_tpu.utils.profiling import StepTimer


# ---------------------------------------------------------------- cost


def test_cost_analysis_flops_match_hand_count():
    """XLA's flop count for one dense matmul must agree with the
    hand-derived 2*M*K*N within tolerance (the tolerance absorbs
    epsilon ops XLA counts around the dot)."""
    m, k, n = 32, 64, 128
    f = jax.jit(lambda x, w: jnp.dot(x, w))
    x = jnp.ones((m, k), jnp.float32)
    w = jnp.ones((k, n), jnp.float32)
    costs = obs.analyze(f, x, w)
    assert costs.flops is not None
    hand = 2 * m * k * n
    assert abs(costs.flops - hand) / hand < 0.1, (costs.flops, hand)
    assert costs.bytes_accessed and costs.bytes_accessed > 0


def test_cost_analysis_scales_with_batch():
    """Twice the batch must cost ~twice the FLOPs — the property that
    makes cost analysis usable as an MFU numerator."""
    f = jax.jit(lambda x, w: jnp.dot(x, w))
    w = jnp.ones((64, 64), jnp.float32)
    c1 = obs.analyze(f, jnp.ones((16, 64)), w)
    c2 = obs.analyze(f, jnp.ones((32, 64)), w)
    assert c1.flops and c2.flops
    assert abs(c2.flops / c1.flops - 2.0) < 0.2


def test_cost_analysis_counts_scan_body_once():
    """Documented gotcha (obs/cost.py): XLA's cost analysis counts
    static HLO, so a lax.scan body is counted ONCE regardless of trip
    count — producers of scanned-program records must therefore report
    counting='static-body' with steps_per_dispatch=1."""
    w = jnp.ones((32, 32), jnp.float32)

    def scan_n(n):
        f = jax.jit(lambda x: jax.lax.scan(
            lambda c, _: (jnp.dot(c, w), None), x, None, length=n)[0])
        return obs.analyze(f, jnp.ones((32, 32))).flops

    f1, f10 = scan_n(1), scan_n(10)
    assert f1 and f10
    assert f10 / f1 < 2.0, (f1, f10)  # NOT ~10x: body counted once


def test_collective_counts_on_2_device_psum_step(eight_devices):
    """A shard_map psum step on a 2-device CPU mesh: the jaxpr walk sees
    the explicit psum, the compiled HLO carries an all-reduce."""
    mesh = make_mesh({"data": 2}, devices=eight_devices[:2])

    def step(x):
        return lax.pmean(jnp.sum(x * x), "data")

    body = jax.shard_map(step, mesh=mesh, in_specs=(P("data"),),
                         out_specs=P(), check_vma=False)
    x = jnp.arange(8, dtype=jnp.float32)

    jx = obs.jaxpr_collective_counts(body, x)
    assert jx.get("psum", 0) >= 1, jx

    costs = obs.analyze(jax.jit(body), x)
    assert costs.collectives.get("all-reduce", 0) >= 1, costs.collectives


def test_hlo_collective_counts_dedups_async_pairs():
    txt = """
      %ar = f32[4] all-reduce-start(f32[4] %x), replica_groups={}
      %ad = f32[4] all-reduce-done(f32[4] %ar)
      %ag = f32[8] all-gather(f32[4] %y), dimensions={0}
    """
    counts = obs.hlo_collective_counts(txt)
    assert counts == {"all-reduce": 1, "all-gather": 1}


def test_peak_flops_and_mfu_degrade_off_tpu():
    assert obs.peak_flops("bfloat16", backend="cpu") is None
    assert obs.mfu(1e12, 1.0, None) is None
    peak = obs.peak_flops("bfloat16", backend="tpu")
    assert peak == obs.PEAK_TFLOPS["tpu_v5e_bf16"] * 1e12
    assert 0 < obs.mfu(peak / 2, 1.0, peak) == 0.5


# -------------------------------------------------------------- schema


def test_jsonl_schema_roundtrip(tmp_path):
    """write -> parse -> validate: the required keys survive, comment
    lines skip, and a bad record is rejected loudly."""
    path = tmp_path / "run.jsonl"
    with MetricsLogger(path, echo=False) as metrics:
        metrics.log("train", step=1, loss=1.25)
        metrics.log("step_phases", steps=4,
                    phases_ms={"dispatch": 1.0, "device": 0.5})
        metrics.log("program", label="step", flops=100.0,
                    collectives={"all-reduce": 1})
    with path.open("a") as fh:
        fh.write("# capture marker comment\n")

    records = obs.load_records(path, strict=True)
    assert [r["event"] for r in records] == ["train", "step_phases", "program"]
    for r in records:
        assert r["schema"] == obs.SCHEMA_VERSION
        assert obs.validate_record(r) is r

    with pytest.raises(ValueError, match="missing required keys"):
        obs.validate_record({"event": "train"})
    with pytest.raises(ValueError, match="missing keys"):
        obs.validate_record(obs.make_record("program", 0.0, label="x"))
    with pytest.raises(ValueError, match="must be an int"):
        obs.validate_record({"schema": "2", "event": "x", "t": 0.0})

    # Each logger open appends a '# run ...' boundary marker, so two runs
    # into one file stay separable: iter_runs splits on the markers and
    # the report renders per-run tables instead of blending runs.
    with MetricsLogger(path, echo=False) as metrics:
        metrics.log("train", step=2, loss=0.5)
    markers = [ln for ln in path.read_text().splitlines()
               if ln.startswith("# run ")]
    assert len(markers) == 2
    assert len(obs.load_records(path, strict=True)) == 4
    runs = list(obs.iter_runs(path, strict=True))
    assert [len(r) for r in runs] == [3, 1]

    # dump_records is the write-path twin: a dumped file reads back
    # identically (one run — no markers).
    copy = tmp_path / "copy.jsonl"
    obs.dump_records(obs.load_records(path, strict=True), copy)
    assert obs.load_records(copy, strict=True) == obs.load_records(
        path, strict=True
    )
    assert [len(r) for r in obs.iter_runs(copy)] == [4]


def test_metrics_logger_closes_on_exception(tmp_path):
    """The context manager must not leak the JSONL handle when the body
    raises — the records written before the failure stay readable."""
    path = tmp_path / "crash.jsonl"
    with pytest.raises(RuntimeError, match="boom"):
        with MetricsLogger(path, echo=False) as metrics:
            metrics.log("train", step=1, loss=0.5)
            raise RuntimeError("boom")
    assert not metrics.jsonl_enabled  # handle closed
    assert [r["event"] for r in obs.load_records(path)] == ["train"]


# -------------------------------------------------------------- device


def test_memory_stats_smoke():
    """Every backend: the snapshot has one entry per device and never
    raises. Backends without allocator stats (CPU) skip the value
    checks cleanly."""
    snap = obs.memory_snapshot()
    assert len(snap) == len(jax.devices())
    assert all({"id", "platform", "stats"} <= e.keys() for e in snap)
    if all(e["stats"] is None for e in snap):
        assert obs.hbm_peak_bytes() is None
        pytest.skip("backend exposes no memory_stats")
    peak = obs.hbm_peak_bytes()
    assert isinstance(peak, int) and peak > 0


# ------------------------------------------------------------- timers


def test_step_timer_guards_and_phases():
    t = StepTimer()
    with pytest.raises(RuntimeError, match="before start"):
        t.stop()
    t.start()
    with t.phase("data"):
        pass
    with t.phase("dispatch"):
        pass
    assert t.stop(2) >= 0.0
    with pytest.raises(RuntimeError):  # double stop
        t.stop()
    ms = t.phases_ms()
    assert set(ms) >= {"data", "dispatch"}
    t.reset()
    assert t.steps == 0 and t.total_s == 0.0 and t.phases_ms() == {}


def test_span_nesting_emits_joined_names(tmp_path):
    with MetricsLogger(tmp_path / "spans.jsonl", echo=False) as metrics:
        with obs.span("epoch", metrics=metrics):
            assert obs.current_path() == "epoch"
            with obs.span("eval", metrics=metrics):
                assert obs.current_path() == "epoch/eval"
        assert obs.current_path() == ""
    names = [r["name"] for r in obs.load_records(tmp_path / "spans.jsonl")]
    assert names == ["epoch/eval", "epoch"]  # inner closes first
    assert all(r["ms"] >= 0 for r in obs.load_records(tmp_path / "spans.jsonl"))


# ------------------------------------------------------------- report


def _telemetry_run(tmp_path):
    """A tiny REAL training run with the JSONL sink — the acceptance
    path: per-step records with phase timings, cost-analysis FLOPs, and
    collective counts, all in one file."""
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config

    path = tmp_path / "run.jsonl"
    ds = synthetic_stripes(num_train=128, num_test=32)
    cfg = Config(model="reference_cnn", epochs=1, batch_size=32,
                 log_every=2, eval_every=1, num_devices=1)
    with MetricsLogger(path, echo=False) as metrics:
        Trainer(get_model("reference_cnn"), ds, cfg, metrics=metrics).train()
    return path


def test_trainer_telemetry_and_report(tmp_path):
    path = _telemetry_run(tmp_path)
    records = obs.load_records(path, strict=True)
    by_event = {}
    for r in records:
        by_event.setdefault(r["event"], []).append(r)

    assert "train" in by_event
    prog = by_event["program"][0]
    assert prog["flops"] and prog["flops"] > 0
    assert isinstance(prog["collectives"], dict)
    phases = by_event["step_phases"][0]
    assert phases["steps"] > 0 and "dispatch" in phases["phases_ms"]
    assert by_event["memory"][0]["devices"]

    summary = obs.summarize(records)
    md = obs.render_markdown(summary)
    assert "step phases" in md and "program" in md and "flops" in md
    # The CLI form returns success and prints the same tables.
    from mpi_cuda_cnn_tpu.obs.report import report_main

    assert report_main([str(path)]) == 0


def test_cli_report_subcommand(tmp_path, capsys):
    path = tmp_path / "r.jsonl"
    with MetricsLogger(path, echo=False) as metrics:
        metrics.log("train", step=1, loss=2.0)
        metrics.log("train", step=2, loss=1.0)
    from mpi_cuda_cnn_tpu.cli import main

    assert main(["report", str(path), "--format", "json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["train"]["last_loss"] == 1.0
    assert main(["report", str(tmp_path / "missing.jsonl")]) == 1


def test_report_reads_pre_schema_capture_files(tmp_path):
    """PERF_capture.jsonl-style files (comments + schemaless rows) must
    keep parsing — the reader skips what it cannot validate."""
    path = tmp_path / "cap.jsonl"
    path.write_text(
        "# capture 2026-07-31T17:00:00Z\n"
        '{"capture_step": "probe", "rc": 0}\n'
        '{"bench": "lm", "tokens_per_s": 123}\n'
    )
    records = obs.load_records(path)
    assert len(records) == 2
    summary = obs.summarize(records)
    assert summary["events"] == {}
