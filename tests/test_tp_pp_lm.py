"""LM TP x PP (parallel/tp_pp_lm.py): Megatron sharding inside the GPipe
stages must be a layout choice — exact parity with the single-device LM
step — with blocks really sharded over BOTH 'pipe' (stack dim) and
'model' (heads/hidden), and the composition reachable from the trainer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    make_mesh,
)
from mpi_cuda_cnn_tpu.parallel.pp_lm import (
    pp_lm_microbatch,
    pp_lm_shard_batch,
)
from mpi_cuda_cnn_tpu.parallel.tp_pp_lm import (
    make_tp_pp_lm_state,
    make_tp_pp_lm_train_step,
    unstack_tp_blocks,
)
from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step


def _pieces(depth=4, batch=8, heads=4, kv_heads=0, pos="learned", seed=2):
    model = TransformerLM(vocab=32, dim=32, heads=heads, depth=depth,
                          max_seq=64, kv_heads=kv_heads, pos=pos)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(rng.integers(0, 32, (batch, 33)), jnp.int32)
    return model, opt, toks[:, :-1], toks[:, 1:]


@pytest.mark.parametrize("mesh_axes,kv_heads,pos", [
    ({PIPE_AXIS: 2, MODEL_AXIS: 2}, 0, "learned"),
    ({PIPE_AXIS: 2, MODEL_AXIS: 2, DATA_AXIS: 2}, 0, "learned"),
    ({PIPE_AXIS: 2, MODEL_AXIS: 2}, 2, "rope"),
])
def test_tp_pp_lm_step_matches_serial(mesh_axes, kv_heads, pos,
                                      eight_devices):
    """One GPipe x Megatron step == one single-device step: same loss,
    same updated params (after unstacking + de-TP), on pipe x model,
    pipe x model x data, and a GQA+RoPE variant."""
    model, opt, tokens, targets = _pieces(kv_heads=kv_heads, pos=pos)
    n = int(np.prod(list(mesh_axes.values())))
    mesh = make_mesh(mesh_axes, devices=jax.devices()[:n])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, want_m = serial_step(make_lm_state(model, opt, seed=0),
                                     tokens, targets)

    params = model.init(jax.random.key(0))
    state = make_tp_pp_lm_state(model, params, opt, mesh)
    # Blocks really live pipe x model sharded: stack dim over 'pipe',
    # head dim over 'model'.
    wo = state["params"]["blocks"]["wo"]  # (L, H, hd, d)
    shard = wo.addressable_shards[0].data
    assert shard.shape[0] == model.depth // mesh_axes[PIPE_AXIS]
    assert shard.shape[1] == model.heads // mesh_axes[MODEL_AXIS]

    step = make_tp_pp_lm_train_step(model, opt, mesh, state, donate=False)
    mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    got_state, got_m = step(state, *mb)

    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got = unstack_tp_blocks(jax.device_get(got_state["params"]), model)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_tp_pp_lm_grad_clip_and_ce_chunk_match_serial(eight_devices):
    """--grad-clip (in-step cross-rank norm: sliced leaves psummed over
    pipe AND model, ln leaves over pipe only, rest once) and --ce-chunk
    (chunked drain CE) under TP x PP both equal the serial step with
    optax clip — with a clip small enough to engage."""
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer

    model, _, tokens, targets = _pieces()
    clip = 0.05
    serial_opt = make_optimizer(0.1, grad_clip=clip)
    serial_step = make_lm_train_step(model, serial_opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, _ = serial_step(make_lm_state(model, serial_opt, seed=0),
                                tokens, targets)

    mesh = make_mesh({PIPE_AXIS: 2, MODEL_AXIS: 2},
                     devices=jax.devices()[:4])
    plain_opt = make_optimizer(0.1)  # clip happens IN the step
    params = model.init(jax.random.key(0))
    state = make_tp_pp_lm_state(model, params, plain_opt, mesh)
    step = make_tp_pp_lm_train_step(model, plain_opt, mesh, state,
                                    donate=False, grad_clip=clip,
                                    ce_chunk=16)
    mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    got_state, _ = step(state, *mb)
    got = unstack_tp_blocks(jax.device_get(got_state["params"]), model)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_tp_pp_lm_4d_matches_serial(eight_devices):
    """The FULL 4D mesh (pipe:2, model:2, seq:2): Megatron blocks inside
    GPipe stages with ring attention over the sequence shards on the
    local heads — still exactly the serial computation (loss + params;
    the ring is exact)."""
    from mpi_cuda_cnn_tpu.parallel.pp_lm import sp_pp_shard_batch

    model, opt, tokens, targets = _pieces()
    mesh = make_mesh({PIPE_AXIS: 2, MODEL_AXIS: 2, "seq": 2},
                     devices=jax.devices()[:8])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, want_m = serial_step(make_lm_state(model, opt, seed=0),
                                     tokens, targets)

    params = model.init(jax.random.key(0))
    state = make_tp_pp_lm_state(model, params, opt, mesh)
    step = make_tp_pp_lm_train_step(model, opt, mesh, state,
                                    donate=False, attn_impl="ring")
    mb = sp_pp_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    got_state, got_m = step(state, *mb)

    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got = unstack_tp_blocks(jax.device_get(got_state["params"]), model)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    # MoE on the FULL 4D mesh (ring fold + per-seq-shard local
    # dispatch): training-tested — finite, decreasing loss. A real MoE
    # model (experts sliced over 'model' inside the stacked stages), not
    # the dense one from _pieces.
    moe_model = TransformerLM(vocab=32, dim=32, heads=4, depth=4,
                              max_seq=64, moe_experts=2)
    moe_params = moe_model.init(jax.random.key(0))
    state4 = make_tp_pp_lm_state(moe_model, moe_params, opt, mesh)
    step4 = make_tp_pp_lm_train_step(moe_model, opt, mesh, state4,
                                     donate=False, attn_impl="ring")
    mb4 = sp_pp_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    first = None
    for _ in range(8):
        state4, m4 = step4(state4, *mb4)
        if first is None:
            first = float(m4["loss"])
    assert np.isfinite(float(m4["loss"])) and float(m4["loss"]) < first


def test_lm_trainer_4d_e2e(eight_devices):
    """The lm product loop trains on the full pipe:2,model:2,seq:2 mesh
    with --grad-clip and --ce-chunk, including eval and decode."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    cfg = LMConfig(corpus="synthetic", dim=32, depth=4, heads=4,
                   seq_len=64, steps=6, batch_size=4, log_every=0,
                   lr_schedule="constant", warmup_steps=0,
                   mesh_shape="pipe:2,model:2,seq:2", grad_clip=1.0,
                   ce_chunk=16, sample_tokens=4)
    t = LMTrainer(cfg, metrics=MetricsLogger(echo=False))
    assert t.attn_impl == "ring"
    r = t.train()
    assert r.steps_run == 6 and np.isfinite(r.eval_ppl)
    _, cont = t.sample(4)
    assert len(cont) == 4


def test_tp_pp_lm_checkpoint_resume(tmp_path, eight_devices):
    """Checkpoint/resume of the pipe x model PACKED + head-structured
    state: a run killed at step 4 and resumed finishes with the same
    step count, the restored state re-places onto the pipe x model
    sharded layout, and — cross-layout portability — the SAME checkpoint
    restores into a 4D pipe:2,model:2,seq:2 run (the 'seq' axis never
    shards parameters, so the state trees are identical)."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ck = str(tmp_path / "ck")
    base = dict(corpus="synthetic", dim=32, depth=2, heads=4, seq_len=64,
                batch_size=4, log_every=0, lr_schedule="constant",
                warmup_steps=0)
    LMTrainer(LMConfig(steps=4, checkpoint_dir=ck, checkpoint_every=4,
                       mesh_shape="pipe:2,model:2", **base),
              metrics=MetricsLogger(echo=False)).train()
    t = LMTrainer(LMConfig(steps=7, checkpoint_dir=ck, resume=True,
                           mesh_shape="pipe:2,model:2", **base),
                  metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.steps_run == 3  # resumed at 4, ran to 7
    wo = t.state["params"]["blocks"]["wo"]  # (L, H, hd, d)
    shard = wo.addressable_shards[0].data
    assert shard.shape[0] == 1 and shard.shape[1] == 2  # pipe x model

    t4 = LMTrainer(LMConfig(steps=9, checkpoint_dir=ck, resume=True,
                            mesh_shape="pipe:2,model:2,seq:2", **base),
                   metrics=MetricsLogger(echo=False))
    r4 = t4.train()
    assert r4.steps_run == 2 and np.isfinite(r4.eval_ppl)


def test_tp_pp_lm_rejects_bad_configs(eight_devices):
    model, opt, _, _ = _pieces(heads=2)
    mesh = make_mesh({PIPE_AXIS: 2, MODEL_AXIS: 4},
                     devices=jax.devices()[:8])
    params = model.init(jax.random.key(0))
    with pytest.raises(ValueError, match="divide"):
        make_tp_pp_lm_state(model, params, opt, mesh)  # 4 !| 2 heads


def test_tp_pp_lm_moe_m1_matches_serial(eight_devices):
    """MoE under TP x PP (round 4: TP inside every expert — hidden
    slices, replicated router): at M=1 the dispatch sees the full batch
    with the same capacity as the serial step, so one GPipe x Megatron
    step == one serial step exactly, and the expert stacks are really
    hidden-sliced over 'model'."""
    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64,
                          moe_experts=2)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(13)
    toks = jnp.asarray(rng.integers(0, 32, (4, 33)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]
    mesh = make_mesh({PIPE_AXIS: 2, MODEL_AXIS: 2},
                     devices=jax.devices()[:4])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, want_m = serial_step(make_lm_state(model, opt, seed=0),
                                     tokens, targets)

    params = model.init(jax.random.key(0))
    state = make_tp_pp_lm_state(model, params, opt, mesh)
    w1 = state["params"]["blocks"]["moe"]["w1"]  # (L, E, d, 4d)
    shard = w1.addressable_shards[0].data
    assert shard.shape[0] == 1 and shard.shape[-1] == 128 // 2
    step = make_tp_pp_lm_train_step(model, opt, mesh, state,
                                    donate=False, num_microbatches=1)
    mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 1), mesh)
    got_state, got_m = step(state, *mb)
    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got = unstack_tp_blocks(jax.device_get(got_state["params"]), model)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_lm_trainer_tp_pp_e2e(eight_devices):
    """The lm product loop trains on a pipe:2,model:2,data:2 (3D) mesh —
    including eval and decode, which unstack + de-TP the packed blocks —
    and 'seq' with 'pipe' still fails loudly."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    base = dict(corpus="synthetic", dim=32, depth=4, heads=4, seq_len=64,
                steps=8, batch_size=8, log_every=0,
                lr_schedule="constant", warmup_steps=0, sample_tokens=4)
    t = LMTrainer(LMConfig(mesh_shape="pipe:2,model:2,data:2", **base),
                  metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.steps_run == 8 and np.isfinite(r.eval_ppl)
    _, cont = t.sample(4)
    assert len(cont) == 4
    # pipe:2,seq:2,model:2 composes now (the 4D mesh —
    # test_lm_trainer_4d_e2e); --fsdp with 'pipe' stays rejected.
    with pytest.raises(ValueError, match="fsdp"):
        LMTrainer(LMConfig(mesh_shape="pipe:2,model:2,data:2", fsdp=True,
                           **base),
                  metrics=MetricsLogger(echo=False))
