"""Pipeline parallelism (parallel/pp.py) vs the unpipelined model.

The reference has no PP (SURVEY.md §2 checklist: "PP: absent"); these tests
pin the capability we add beyond parity: the GPipe schedule over a 'pipe'
mesh axis must compute EXACTLY the same loss, gradients, parameter updates,
and logits as the plain single-device model — pipelining is a schedule, not
a different computation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.initializers import get_initializer
from mpi_cuda_cnn_tpu.models.layers import Conv, Dense, Flatten, Sequential
from mpi_cuda_cnn_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    make_mesh,
)
from mpi_cuda_cnn_tpu.parallel.pp import (
    make_pipeline_plan,
    make_pp_forward,
    make_pp_state,
    make_pp_train_step,
    microbatch,
    pack_params,
    pp_shard_batch,
    unpack_params,
)
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
from mpi_cuda_cnn_tpu.train.trainer import make_loss_fn


def _small_model():
    return Sequential(
        layers=(
            Conv(4, kernel=3, stride=2, padding=1, activation="relu"),
            Conv(8, kernel=3, stride=2, padding=1, activation="relu"),
            Flatten(),
            Dense(32, activation="tanh"),
            Dense(10, activation=None),
        ),
        input_shape=(8, 8, 1),
        name="pp_test_net",
    )


def _data(rng, batch=16):
    x = jnp.asarray(rng.random((batch, 8, 8, 1), np.float32))
    labels = rng.integers(0, 10, batch)
    y = np.zeros((batch, 10), np.float32)
    y[np.arange(batch), labels] = 1.0
    return x, jnp.asarray(y)


@pytest.fixture(scope="module")
def setup():
    model = _small_model()
    params = model.init(jax.random.key(0), get_initializer("he"))
    return model, params


def test_plan_partitions_all_layers(setup):
    model, _ = setup
    for n_stages in (1, 2, 4, 5):
        plan = make_pipeline_plan(model, n_stages)
        flat = [i for stage in plan.stage_layers for i in stage]
        assert flat == list(range(len(model.layers)))
        assert all(stage for stage in plan.stage_layers)
        # contiguity: each stage starts where the previous ended
        assert plan.num_classes == 10


def test_pack_unpack_roundtrip(setup):
    model, params = setup
    plan = make_pipeline_plan(model, 4)
    packed = pack_params(plan, params)
    assert packed.shape == (4, plan.p_max)
    restored = unpack_params(plan, packed)
    for orig, rest in zip(params, restored):
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            orig, rest,
        )


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 4), (4, 8)])
def test_pp_loss_and_grads_match_serial(setup, eight_devices, rng, n_stages, n_micro):
    model, params = setup
    x, y = _data(rng)
    loss_fn = make_loss_fn(model)
    (ref_loss, ref_aux), ref_grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, x, y
    )

    plan = make_pipeline_plan(model, n_stages)
    mesh = make_mesh({PIPE_AXIS: n_stages}, devices=eight_devices[:n_stages])
    opt = make_optimizer(0.1)
    state = make_pp_state(plan, params, opt, mesh)
    step = make_pp_train_step(plan, opt, mesh, state, donate=False)
    x_mb, y_mb = pp_shard_batch(microbatch(x, y, n_micro), mesh)
    new_state, metrics = step(state, x_mb, y_mb)

    np.testing.assert_allclose(float(metrics["loss"]), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        float(metrics["etotal"]), float(ref_aux["etotal"]), rtol=1e-5
    )
    np.testing.assert_allclose(float(metrics["acc"]), float(ref_aux["acc"]), rtol=1e-6)

    # One SGD step at lr 0.1 on both sides -> identical params.
    import optax

    updates, _ = opt.update(ref_grads, opt.init(params), params)
    ref_next = optax.apply_updates(params, updates)
    pp_next = unpack_params(plan, np.asarray(new_state["flat_params"]))
    for a, b in zip(ref_next, pp_next):
        jax.tree.map(
            lambda u, v: np.testing.assert_allclose(
                np.asarray(u), np.asarray(v), rtol=1e-5, atol=1e-6
            ),
            a, b,
        )


def test_pp_composes_with_dp(setup, eight_devices, rng):
    """pipe:2 x data:4 — microbatches shard over 'data', grads pmean over it;
    the result must still equal the serial computation."""
    model, params = setup
    x, y = _data(rng, batch=16)
    loss_fn = make_loss_fn(model)
    ref_loss, _ = loss_fn(params, x, y)

    plan = make_pipeline_plan(model, 2)
    mesh = make_mesh({PIPE_AXIS: 2, DATA_AXIS: 4}, devices=eight_devices)
    opt = make_optimizer(0.1)
    state = make_pp_state(plan, params, opt, mesh)
    step = make_pp_train_step(plan, opt, mesh, state, donate=False)
    x_mb, y_mb = pp_shard_batch(microbatch(x, y, 2), mesh)
    new_state, metrics = step(state, x_mb, y_mb)
    np.testing.assert_allclose(float(metrics["loss"]), float(ref_loss), rtol=1e-5)
    assert int(new_state["step"]) == 1


def test_pp_forward_matches_apply(setup, eight_devices, rng):
    model, params = setup
    x, y = _data(rng)
    ref_logits = model.apply(params, x)

    plan = make_pipeline_plan(model, 4)
    mesh = make_mesh({PIPE_AXIS: 4}, devices=eight_devices[:4])
    fwd = make_pp_forward(plan, mesh)
    packed = jax.device_put(
        pack_params(plan, params),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(PIPE_AXIS, None)),
    )
    x_mb, _ = microbatch(x, y, 4)
    logits = fwd(packed, pp_shard_batch(x_mb, mesh))
    np.testing.assert_allclose(
        np.asarray(logits).reshape(ref_logits.shape),
        np.asarray(ref_logits),
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.parametrize("mesh_axes,n_model,fsdp", [
    ({PIPE_AXIS: 2}, 1, False),
    ({PIPE_AXIS: 2, DATA_AXIS: 2}, 1, False),
    ({PIPE_AXIS: 2, MODEL_AXIS: 2}, 2, False),
    ({PIPE_AXIS: 2, DATA_AXIS: 2}, 1, True),
    ({PIPE_AXIS: 2, MODEL_AXIS: 2, DATA_AXIS: 2}, 2, True),
])
def test_pp_grad_clip_matches_optax(setup, eight_devices, rng,
                                    mesh_axes, n_model, fsdp):
    """--grad-clip on the pipelined path (VERDICT r3 item 5): the in-step
    cross-rank global-norm clip — stage rows psummed over 'pipe', sliced
    TP segments over 'model', FSDP slices over 'data', the psum-repaired
    replicated segments counted once — must equal optax's
    clip_by_global_norm on the serial gradient, with a clip small enough
    to engage."""
    import optax

    model, params = setup
    x, y = _data(rng)
    clip = 0.05
    loss_fn = make_loss_fn(model)
    _, ref_grads = jax.value_and_grad(loss_fn, has_aux=True)(params, x, y)
    serial_opt = make_optimizer(0.1, grad_clip=clip)
    updates, _ = serial_opt.update(ref_grads, serial_opt.init(params), params)
    ref_next = optax.apply_updates(params, updates)

    n = int(np.prod(list(mesh_axes.values())))
    mesh = make_mesh(mesh_axes, devices=eight_devices[:n])
    n_data = mesh_axes.get(DATA_AXIS, 1)
    plan = make_pipeline_plan(model, 2, n_model=n_model,
                              fsdp_degree=n_data if fsdp else 1)
    opt = make_optimizer(0.1)  # clip happens IN the step
    state = make_pp_state(plan, params, opt, mesh)
    step = make_pp_train_step(plan, opt, mesh, state, donate=False,
                              grad_clip=clip)
    x_mb, y_mb = pp_shard_batch(microbatch(x, y, 2), mesh)
    new_state, _ = step(state, x_mb, y_mb)

    pp_next = unpack_params(plan, jax.device_get(new_state["flat_params"]))
    for a, b in zip(ref_next, pp_next):
        jax.tree.map(
            lambda u, v: np.testing.assert_allclose(
                np.asarray(u), np.asarray(v), rtol=1e-5, atol=1e-6
            ),
            a, b,
        )


def test_pp_training_reduces_loss(setup, eight_devices, rng):
    """A few pipelined steps on a fixed batch must drive the loss down —
    the end-to-end sanity the reference only ever eyeballed (SURVEY.md §4)."""
    model, params = setup
    x, y = _data(rng, batch=32)
    plan = make_pipeline_plan(model, 4)
    mesh = make_mesh({PIPE_AXIS: 4}, devices=eight_devices[:4])
    opt = make_optimizer(0.5)
    state = make_pp_state(plan, params, opt, mesh)
    step = make_pp_train_step(plan, opt, mesh, state, donate=False)
    x_mb, y_mb = pp_shard_batch(microbatch(x, y, 4), mesh)
    first = None
    for _ in range(30):
        state, metrics = step(state, x_mb, y_mb)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.5
