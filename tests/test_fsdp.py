"""FSDP (parallel/fsdp.py): spec selection, real sharding, and exact
parity with replicated DP on the 8-virtual-device CPU mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
from mpi_cuda_cnn_tpu.models.initializers import get_initializer
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.parallel.fsdp import fsdp_specs, make_fsdp_state
from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, make_mesh
from mpi_cuda_cnn_tpu.train.trainer import Trainer
from mpi_cuda_cnn_tpu.utils.config import Config
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _quiet():
    return MetricsLogger(echo=False)


def _mesh(n=8):
    return make_mesh({DATA_AXIS: n}, devices=jax.devices()[:n])


def test_specs_shard_largest_divisible_dim():
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    specs = fsdp_specs(params, _mesh())
    # fc1: (1568, 200) -> largest dim 1568 % 8 == 0 -> shard dim 0.
    assert specs[2]["w"] == P(DATA_AXIS, None)
    # conv1 kernel (3, 3, 1, 16): 16 % 8 == 0 -> shard the channel dim.
    assert specs[0]["w"] == P(None, None, None, DATA_AXIS)
    # conv1 bias (16,) divisible -> sharded; a (10,) head bias would not be.
    assert specs[0]["b"] == P(DATA_AXIS)
    assert specs[4]["b"] == P()  # output bias (10,) % 8 != 0


def test_state_is_actually_sharded():
    """Per-device bytes for the big FC kernel must be 1/8 of the full
    array — the memory claim FSDP exists for."""
    import optax

    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    mesh = _mesh()
    state = make_fsdp_state(params, optax.sgd(0.1, momentum=0.9), mesh)
    w = state["params"][2]["w"]  # (1568, 200)
    shard = w.addressable_shards[0].data
    assert shard.shape == (1568 // 8, 200)
    # Momentum buffer inherits the same sharding leaf-for-leaf.
    mu = jax.tree.leaves(state["opt_state"])  # trace_state.mu leaves
    mu_w = [m for m in mu if getattr(m, "shape", None) == w.shape]
    assert mu_w and mu_w[0].addressable_shards[0].data.shape == (196, 200)


@pytest.mark.parametrize("scan", [True, False])
def test_fsdp_matches_replicated_dp(scan, eight_devices):
    """Sharding placement must not change the math: one epoch under FSDP
    == one epoch under replicated DP (same seed, same permutation)."""
    ds = synthetic_stripes(num_train=256, num_test=64)
    base = dict(model="reference_cnn", epochs=1, batch_size=32, seed=11,
                eval_every=0, log_every=10**9, scan=scan, donate=False,
                momentum=0.9)

    def run(fsdp):
        t = Trainer(get_model("reference_cnn"), ds, Config(fsdp=fsdp, **base),
                    metrics=_quiet())
        em = t.run_epoch(0)
        return jax.device_get(t.state["params"]), em

    p_dp, m_dp = run(False)
    p_fsdp, m_fsdp = run(True)
    np.testing.assert_allclose(m_dp["loss"], m_fsdp["loss"], rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_dp), jax.tree.leaves(p_fsdp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_fsdp_e2e_train_and_eval(eight_devices):
    ds = synthetic_stripes(num_train=512, num_test=128)
    cfg = Config(model="lenet5", init="he", epochs=2, fsdp=True,
                 eval_every=0, log_every=10**9)
    t = Trainer(get_model("lenet5"), ds, cfg, metrics=_quiet())
    assert t.train().test_accuracy >= 0.9


def test_fsdp_composes_with_model_axis(eight_devices):
    """FSDP x TP (round-2): a data:4,model:2 mesh with --fsdp builds and
    trains (combined specs: features over 'model', rest over 'data');
    exact parity vs pure DP is covered in test_tp_pp.py."""
    ds = synthetic_stripes(num_train=64, num_test=32)
    cfg = Config(batch_size=32, fsdp=True, mesh_shape="data:4,model:2",
                 epochs=1, eval_every=0, log_every=0, scan=False)
    t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    em = t.run_epoch(0)
    assert np.isfinite(em["loss"])


@pytest.mark.parametrize("scan,mesh_shape", [
    (True, "pipe:2,data:4"),
    (False, "pipe:2,data:4"),
    # The TRIPLE composition FSDP x TP x PP: all_gather over 'data' +
    # masked psum repair over 'model' + psum_scatter (advisor r3: the
    # path was reachable but untested).
    (False, "pipe:2,model:2,data:2"),
])
def test_fsdp_pp_matches_plain_pp(scan, mesh_shape, eight_devices):
    """FSDP x PP (ZeRO rows over 'data' inside each pipe stage, with or
    without a TP 'model' axis): the all-gather/reduce-scatter pair must
    be placement, not math — params after an epoch match the
    replicated-row run on the same mesh."""
    ds = synthetic_stripes(num_train=128, num_test=32)
    base = dict(model="reference_cnn", epochs=1, batch_size=32, seed=9,
                eval_every=0, log_every=10**9, mesh_shape=mesh_shape,
                scan=scan, donate=False)

    def run(fsdp):
        t = Trainer(get_model("reference_cnn"), ds, Config(fsdp=fsdp, **base),
                    metrics=_quiet())
        em = t.run_epoch(0)
        return em, jax.device_get(t.state["flat_params"])

    em_pp, p_pp = run(False)
    em_z, p_z = run(True)
    np.testing.assert_allclose(em_pp["loss"], em_z["loss"], rtol=1e-5)
    # FSDP pads P_max to a multiple of the data-axis size; compare the
    # unpadded prefix (the padding rows are zeros + zero grads).
    w = min(p_pp.shape[-1], p_z.shape[-1])
    np.testing.assert_allclose(
        np.asarray(p_pp)[..., :w], np.asarray(p_z)[..., :w],
        rtol=2e-4, atol=2e-5,
    )


def test_fsdp_pp_state_is_row_sharded(eight_devices):
    """The memory claim: each device holds 1/n_data of its stage's packed
    row (params AND optimizer buffers), not the full row. Eval must work
    off the sharded rows too (make_pp_forward gathers them over 'data')."""
    ds = synthetic_stripes(num_train=64, num_test=32)
    cfg = Config(batch_size=32, fsdp=True, mesh_shape="pipe:2,data:4",
                 epochs=1, eval_every=0, log_every=0)
    t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    flat = t.state["flat_params"]
    S, p_max = flat.shape
    assert p_max % 4 == 0
    shard = flat.addressable_shards[0].data
    assert shard.shape == (S // 2, p_max // 4)
    ntests, ncorrect = t.evaluate()
    assert ntests == 32 and 0 <= ncorrect <= ntests


# ---------------------------------------------------------------------------
# LM family under FSDP (generic fsdp_specs over the transformer pytree)
# ---------------------------------------------------------------------------


def test_lm_fsdp_step_matches_replicated(eight_devices):
    """ZeRO placement for the LM: one step with FSDP-sharded params ==
    the replicated-DP step (loss + params), and the big matmuls are
    really sharded."""
    import jax.numpy as jnp
    import optax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS
    from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64)
    opt = optax.sgd(0.1)
    step = make_lm_train_step(model, opt, attn_impl="oracle", seq_len=32,
                              donate=False)
    rng = np.random.default_rng(6)
    toks = jnp.asarray(rng.integers(0, 32, (8, 33)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]

    base = make_lm_state(model, opt, seed=0)
    want_state, want_m = step(base, tokens, targets)

    mesh = _mesh()
    z_state = make_fsdp_state(model.init(jax.random.key(0)), opt, mesh)
    w1 = z_state["params"]["blocks"][0]["w1"]  # (32, 128): shard 128 over 8
    assert w1.addressable_shards[0].data.shape == (32, 128 // 8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = NamedSharding(mesh, P(DATA_AXIS))
    got_state, got_m = step(
        z_state, jax.device_put(tokens, spec), jax.device_put(targets, spec)
    )
    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(got_state["params"])),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("clip", [0.0, 0.05])
def test_lm_fsdp_sp_matches_replicated_sp(clip, eight_devices):
    """FSDP x SP (ZeRO x ring): the manual all_gather / psum_scatter
    pair inside the SP shard_map must be placement, not math — one step
    with data-sharded params on data:2,seq:2 equals the replicated-param
    SP step (loss + params), the state is really sharded, and (clip
    variant, slow set) the in-step cross-rank grad-clip equals optax's
    clip on the replicated path."""
    import jax.numpy as jnp
    import optax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.dp import replicate
    from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS, make_sp_lm_train_step
    from mpi_cuda_cnn_tpu.train.lm import make_lm_state
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64)
    mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 2}, devices=jax.devices()[:4])
    rng = np.random.default_rng(8)
    toks = jnp.asarray(rng.integers(0, 32, (4, 33)), jnp.int32)
    from jax.sharding import NamedSharding

    bspec = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    tokens = jax.device_put(toks[:, :-1], bspec)
    targets = jax.device_put(toks[:, 1:], bspec)

    opt = make_optimizer(0.1, grad_clip=clip)  # optax-side clip
    rep_step = make_sp_lm_train_step(
        model, opt, mesh, impl="ring", data_axis=DATA_AXIS,
        donate=False,
    )
    rep_state = replicate(make_lm_state(model, opt, seed=0), mesh)
    want_state, want_m = rep_step(rep_state, tokens, targets)

    plain_opt = make_optimizer(0.1)  # clip happens IN the step
    z_state = make_fsdp_state(
        model.init(jax.random.key(0)),
        plain_opt if clip else opt, mesh,
    )
    from mpi_cuda_cnn_tpu.parallel.fsdp import state_specs

    w1 = z_state["params"]["blocks"][0]["w1"]  # (32, 128): 128 over 2
    assert w1.addressable_shards[0].data.shape == (32, 128 // 2)
    specs = state_specs(z_state)
    z_step = make_sp_lm_train_step(
        model, plain_opt if clip else opt, mesh, impl="ring",
        data_axis=DATA_AXIS, donate=False, state_specs=specs,
        grad_clip=clip,
    )
    got_state, got_m = z_step(z_state, tokens, targets)
    np.testing.assert_allclose(float(got_m["loss"]),
                               float(want_m["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree.leaves(jax.device_get(got_state["params"])),
        jax.tree.leaves(jax.device_get(want_state["params"])),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_lm_trainer_fsdp_sp_e2e(eight_devices):
    """The lm product loop trains with --fsdp on a data:2,seq:2 mesh
    (ZeRO x ring through the trainer), including eval and decode."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig

    cfg = LMConfig(corpus="synthetic", dim=32, depth=2, heads=4,
                   seq_len=64, steps=6, batch_size=4, log_every=0,
                   lr_schedule="constant", warmup_steps=0, fsdp=True,
                   grad_clip=1.0, mesh_shape="data:2,seq:2",
                   sample_tokens=4)
    t = LMTrainer(cfg, metrics=_quiet())
    r = t.train()
    assert r.steps_run == 6 and np.isfinite(r.eval_ppl)
    _, cont = t.sample(4)
    assert len(cont) == 4


def test_lm_trainer_fsdp_and_fsdp_tp(eight_devices):
    """The lm product loop trains under --fsdp on data:8 AND under
    FSDP x TP on data:2,model:4; TP x SP with --fsdp stays rejected
    (fsdp + 'seq' alone composes — test_lm_trainer_fsdp_sp_e2e)."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig

    base = dict(corpus="synthetic", dim=32, depth=1, heads=4, seq_len=64,
                steps=8, batch_size=8, log_every=0,
                lr_schedule="constant", warmup_steps=0, fsdp=True)
    for mesh_shape in ("data:8", "data:2,model:4"):
        t = LMTrainer(LMConfig(mesh_shape=mesh_shape, **base),
                      metrics=_quiet())
        w1 = t.state["params"]["blocks"][0]["w1"]  # (32, 128)
        if mesh_shape == "data:8":
            # plain ZeRO: largest dim (128) over 'data'.
            assert w1.addressable_shards[0].data.shape == (32, 128 // 8)
        else:
            # FSDP x TP: columns over 'model' (Megatron base), the
            # largest REMAINING dim (rows) over 'data'.
            assert w1.addressable_shards[0].data.shape == (32 // 2, 128 // 4)
        r = t.train()
        assert r.steps_run == 8 and np.isfinite(r.final_loss)
    with pytest.raises(ValueError, match="does not compose"):
        LMTrainer(LMConfig(mesh_shape="seq:2,model:2,data:2", **base),
                  metrics=_quiet())
