"""Flight-recorder replay + first-divergence localization (ISSUE 15).

The acceptance surface:

- `mctpu replay` folds a trail back into the reconstructed state
  machine and the recomputed digest matches the stamped `state_crc` at
  EVERY tick — engine trails (static + continuous + prefix sharing +
  speculation + preemptions + expiries: the checked-in sample) and
  fleet trails (crashes + a partitioned zombie + elastic join + prefix
  + spec + SLO scheduling + disaggregated handoffs with injected
  drops/corruption), byte-pinned against the golden rendering.
- A single perturbed record makes replay exit 1 naming the tick, and
  `mctpu diverge` report exactly the perturbed tick, the affected
  rid(s), and a nonempty state delta.
- Legacy trails (pre-ISSUE-15, no `state_crc`) and tickless summary
  logs exit 2 with the one-line config-error contract.
- `state_crc` is always stamped in serve/fleet summaries, flattened by
  `mctpu compare`, pinned at 0%/equal in the determinism gates, and a
  crc/equal gate failure prints the `mctpu diverge` invocation.

The two reduced-scale storm TWINS of the CI determinism gates
(--spec lookup, --pools) are slow-marked and ::-named in the CI obs
step; the full-scale fleet storm replay runs as its own CI step.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
from pathlib import Path

import pytest

from mpi_cuda_cnn_tpu.obs.diverge import diverge_main
from mpi_cuda_cnn_tpu.obs.regress import compare_main, metrics_from_records
from mpi_cuda_cnn_tpu.obs.replay import replay_main
from mpi_cuda_cnn_tpu.obs.schema import dump_records, load_records
from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main

REPO = Path(__file__).resolve().parents[1]
DATA = Path(__file__).parent / "data"
SAMPLE = DATA / "sample_serve_run.jsonl"

STORM_FAULTS = ("replica_crash@fleet.tick:40?replica=1&zombie_ticks=4;"
                "replica_crash@fleet.tick:120?replica=2;"
                "replica_join@fleet.tick:200")


def _run(main, argv):
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = main(argv)
    return rc, out.getvalue(), err.getvalue()


def _sim_storm(path, *extra, requests=300, seed=2, log="full"):
    rc, _out, err = _run(fleet_bench_main, [
        "--replicas", "3", "--requests", str(requests), "--rate", "500",
        "--seed", str(seed), "--log", log,
        "--metrics-jsonl", str(path), *extra,
    ])
    assert rc == 0, err
    return load_records(path)


@pytest.fixture(scope="module")
def storm_pair(tmp_path_factory):
    """ONE identical-seed pair of full-log crash/zombie/join storms,
    shared by the replay, diverge, and gate-wiring tests below (each
    generating its own would dominate the tier-1 budget)."""
    root = tmp_path_factory.mktemp("storm_pair")
    a, b = root / "a.jsonl", root / "b.jsonl"
    _sim_storm(a, "--fault-plan", STORM_FAULTS)
    _sim_storm(b, "--fault-plan", STORM_FAULTS)
    return a, b


# ------------------------------------------------ golden + engine trail


def test_golden_replay_roundtrip(monkeypatch, capsys):
    """`mctpu replay` on the checked-in sample run (engine static +
    continuous with prefix sharing, speculation, preemptions, slow
    faults, and expiries) cross-checks every tick digest and renders
    byte-for-byte the golden (regenerate via make_obs_sample.py)."""
    monkeypatch.chdir(REPO)
    assert replay_main(["tests/data/sample_serve_run.jsonl"]) == 0
    assert capsys.readouterr().out == \
        (DATA / "golden_serve_replay.md").read_text()


def test_replay_at_tick_renders_midrun_state(monkeypatch):
    monkeypatch.chdir(REPO)
    rc, out, _ = _run(replay_main,
                      ["tests/data/sample_serve_run.jsonl",
                       "--at-tick", "10", "--format", "json"])
    assert rc == 0
    state = json.loads(out)["state"]
    # Mid-run: something is actually in flight in at least one mode.
    assert any(state[m]["slots"] for m in ("static", "continuous"))


def test_replay_detects_perturbed_record(tmp_path):
    """Dropping one decoded entry from one tick makes replay exit 1
    naming that exact tick — the flight-recorder tamper check."""
    records = load_records(SAMPLE)
    tick = None
    for rec in records:
        # The static stream's decode ticks (the continuous half's
        # decodes ride the spec round entries instead).
        if rec.get("event") == "tick" and rec.get("mode") == "static" \
                and len(rec.get("decoded") or []) > 1:
            rec["decoded"] = rec["decoded"][1:]
            tick = rec["tick"]
            break
    assert tick is not None
    p = tmp_path / "tampered.jsonl"
    dump_records(records, p)
    rc, _out, err = _run(replay_main, [str(p)])
    assert rc == 1
    assert f"tick {tick}" in err and "drift" in err.lower()


# ------------------------------------------------ legacy/config errors


def test_replay_legacy_trail_exits_2(tmp_path):
    """A pre-ISSUE-15 trail (tick records without state_crc) is a
    one-line config error, exit 2 — never a traceback (the explain
    legacy-trail contract)."""
    records = load_records(SAMPLE)
    for rec in records:
        rec.pop("state_crc", None)
    p = tmp_path / "legacy.jsonl"
    dump_records(records, p)
    rc, _out, err = _run(replay_main, [str(p)])
    assert rc == 2
    assert "state_crc" in err and "regenerate" in err
    assert "Traceback" not in err
    # diverge inherits the same contract on either input.
    rc, _out, err = _run(diverge_main, [str(SAMPLE), str(p)])
    assert rc == 2
    assert "state_crc" in err


def test_replay_tickless_summary_exits_2(tmp_path):
    records = [r for r in load_records(SAMPLE)
               if r.get("event") not in ("tick", "fleet")]
    p = tmp_path / "summary_only.jsonl"
    dump_records(records, p)
    rc, _out, err = _run(replay_main, [str(p)])
    assert rc == 2
    assert "no tick trail" in err


# ------------------------------------------------ fleet trails


def test_fleet_prefspec_storm_replays_bitwise(tmp_path):
    """The fleet determinism storm's shape in miniature — two crashes
    (one partitioned zombie), an elastic join, prefix sharing, and
    speculative decoding — replays with zero digest drift at every
    fleet/replica tick."""
    p = tmp_path / "storm.jsonl"
    _sim_storm(p, "--prefix-cache", "--prefix-mix", "0.5",
               "--spec", "lookup", "--spec-k", "4",
               "--fault-plan", STORM_FAULTS)
    rc, out, err = _run(replay_main, [str(p)])
    assert rc == 0, err
    assert "zero drift" in out


def test_fleet_slo_deadline_storm_replays_bitwise(tmp_path):
    p = tmp_path / "slo.jsonl"
    _sim_storm(p, "--scheduler", "slo", "--tenants", "3",
               "--tenant-priority", "t0=2", "--tenant-quota", "t1=slots:2",
               "--deadline-ms", "150", "--max-queue", "8",
               requests=200, seed=3)
    rc, _out, err = _run(replay_main, [str(p)])
    assert rc == 0, err


def test_disagg_storm_with_handoff_faults_replays_bitwise(tmp_path):
    """The 2-pool form: KV handoffs (placement, re-target, completion),
    an injected dropped transfer, an injected corrupted page set, and a
    corrupted resume context — every abort path's page accounting
    reconstructs exactly."""
    p = tmp_path / "disagg.jsonl"
    rc, _out, err = _run(fleet_bench_main, [
        "--pools", "prefill:1,decode:2", "--handoff-ticks", "2",
        "--requests", "200", "--rate", "400", "--seed", "3",
        "--log", "full", "--metrics-jsonl", str(p),
        "--fault-plan", "handoff_drop@fleet.handoff:3;"
                        "kv_corrupt@fleet.handoff:7;"
                        "kv_corrupt@fleet.resume:0",
    ])
    assert rc == 0, err
    rc, _out, err = _run(replay_main, [str(p)])
    assert rc == 0, err


def test_empty_fleet_mass_failure_replays_bitwise(tmp_path):
    """Total outage: the lone replica crashes with its circuit opened;
    the router-attributed mass-failure record (and the emptied dispatch
    queues) replay against the stamped router digest."""
    p = tmp_path / "massfail.jsonl"
    rc, _out, err = _run(fleet_bench_main, [
        "--replicas", "1", "--requests", "40", "--rate", "200",
        "--seed", "5", "--max-flaps", "0", "--log", "full",
        "--metrics-jsonl", str(p),
        "--fault-plan", "replica_crash@fleet.tick:10?replica=0",
    ])
    assert rc == 0, err
    rc, _out, err = _run(replay_main, [str(p)])
    assert rc == 0, err


# ------------------------------------------------ diverge


def test_diverge_identical_trails_exit_0(storm_pair):
    a, b = storm_pair
    rc, out, _err = _run(diverge_main, [str(a), str(b)])
    assert rc == 0
    assert "no divergence" in out
    # The same storm replays clean (the crash/zombie/join shape without
    # prefix/spec — the base-fleet leg of the replay matrix).
    rc, _out, err = _run(replay_main, [str(a)])
    assert rc == 0, err


def test_diverge_pins_perturbed_tick_rid_and_delta(storm_pair, tmp_path):
    """THE acceptance pin: a single perturbed record localizes to
    exactly its tick, names the affected rid, and the state delta is
    nonempty (the rid's slot extent differs between the two sides)."""
    a = storm_pair[0]
    b = tmp_path / "b.jsonl"
    records = load_records(a)
    tick = rid = None
    for rec in records:
        if rec.get("event") == "tick" and rec.get("tick", 0) > 30 \
                and len(rec.get("decoded") or []) > 1:
            rid = rec["decoded"][0][1]
            rec["decoded"] = rec["decoded"][1:]
            tick = rec["tick"]
            break
    assert tick is not None
    dump_records(records, b)
    rc, out, _err = _run(diverge_main, [str(a), str(b), "--format", "json"])
    assert rc == 1
    report = json.loads(out)
    assert report["divergence"]["tick"] == tick
    assert rid in report["divergence"]["rids"]
    assert report["delta"], "state delta must be nonempty"
    assert any(f"rid {rid}" in line for line in report["delta"])
    # The md rendering carries the same anchors.
    rc, out, _err = _run(diverge_main, [str(a), str(b)])
    assert rc == 1
    assert f"tick {tick}" in out and str(rid) in out


# ------------------------------------------------ gate wiring


def test_state_crc_stamped_flattened_gated_and_seed_stable(storm_pair):
    """state_crc is an always-stamped summary key, `mctpu compare`
    flattens it, identical-seed storms chain the identical value, and
    all three determinism gates pin it at 0%/equal."""
    a, b = storm_pair
    ra, rb = load_records(a), load_records(b)
    sa = [r for r in ra if r.get("event") == "serve"][0]
    sb = [r for r in rb if r.get("event") == "serve"][0]
    assert isinstance(sa["state_crc"], int)
    assert sa["state_crc"] == sb["state_crc"]
    flat = metrics_from_records(ra)
    assert "serve.fleet.state_crc" in flat
    for gate in ("fleet_gate", "spec_gate", "disagg_gate"):
        spec = json.loads((REPO / "ci" / f"{gate}.json").read_text())
        assert spec["metrics"]["serve.fleet.state_crc"] == \
            {"tol_pct": 0, "direction": "equal"}


def test_compare_crc_failure_prints_diverge_hint(storm_pair, tmp_path):
    """A failed *_crc/equal gate between two trail-carrying runs names
    the exact `mctpu diverge A B` next step."""
    a = storm_pair[0]
    b = tmp_path / "b.jsonl"
    records = load_records(a)
    # A genuinely diverged twin: perturb one scheduling event AND the
    # summary chain (what a real nondeterminism would do).
    for rec in records:
        if rec.get("event") == "serve":
            rec["state_crc"] ^= 1
    dump_records(records, b)
    gate = tmp_path / "gate.json"
    gate.write_text(json.dumps({"metrics": {
        "serve.fleet.state_crc": {"tol_pct": 0, "direction": "equal"}}}))
    rc, _out, err = _run(compare_main, [str(a), str(b), "--gate", str(gate)])
    assert rc == 1
    assert f"mctpu diverge {a} {b}" in err
    # Without tick trails (summary-only files) the hint says to re-run
    # at --log full instead of naming an impossible invocation.
    a2, b2 = tmp_path / "a2.jsonl", tmp_path / "b2.jsonl"
    for src, dst in ((a, a2), (b, b2)):
        dump_records([r for r in load_records(src)
                      if r.get("event") not in ("tick", "fleet")], dst)
    rc, _out, err = _run(compare_main,
                         [str(a2), str(b2), "--gate", str(gate)])
    assert rc == 1
    assert "--log full" in err


# ------------------------------------------------ storm twins (slow)


def test_replay_spec_storm_twin(tmp_path):
    """Reduced-scale twin of the CI spec determinism storm: prefix +
    --spec lookup + crashes (zombie) + join at 20k requests, full-log,
    replayed with zero drift (slow; ::-named in the CI obs step — the
    full-scale fleet form runs as its own CI step)."""
    p = tmp_path / "spec_storm.jsonl"
    rc, _out, err = _run(fleet_bench_main, [
        "--replicas", "4", "--requests", "20000", "--rate", "2000",
        "--slots", "8", "--seed", "0", "--spec", "lookup", "--spec-k", "8",
        "--prefix-cache", "--prefix-mix", "0.5", "--log", "full",
        "--metrics-jsonl", str(p),
        "--fault-plan", "replica_crash@fleet.tick:800?replica=1&zombie_ticks=4;"
                        "replica_crash@fleet.tick:2400?replica=2;"
                        "replica_join@fleet.tick:4000",
    ])
    assert rc == 0, err
    rc, out, err = _run(replay_main, [str(p)])
    assert rc == 0, err
    assert "zero drift" in out


def test_replay_disagg_storm_twin(tmp_path):
    """Reduced-scale twin of the CI disagg determinism storm: 2+2
    pools, a prefill replica killed mid-handoff as a zombie, a decode-
    pool collapse, and a decode join at 20k requests — the handoff
    protocol's whole page-accounting surface replays bitwise (slow)."""
    p = tmp_path / "disagg_storm.jsonl"
    rc, _out, err = _run(fleet_bench_main, [
        "--pools", "prefill:2,decode:2", "--handoff-ticks", "2",
        "--requests", "20000", "--rate", "2000", "--slots", "8",
        "--seed", "0", "--log", "full", "--metrics-jsonl", str(p),
        "--fault-plan", "replica_crash@fleet.tick:800?replica=0&zombie_ticks=4;"
                        "pool_crash@fleet.tick:2400?pool=decode;"
                        "replica_join@fleet.tick:4000?pool=decode",
    ])
    assert rc == 0, err
    rc, out, err = _run(replay_main, [str(p)])
    assert rc == 0, err
    assert "zero drift" in out


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-v"] + sys.argv[1:]))
