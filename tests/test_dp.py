"""Data-parallelism tests on the 8-device virtual CPU mesh.

Validates the *intended* semantics of the reference's MPI layer
(SURVEY.md 2.6): synchronous gradient averaging, synchronized init,
DP result == single-device result on the same global batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mpi_cuda_cnn_tpu.models.initializers import get_initializer
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.parallel.dp import (
    dp_shard_batch,
    make_dp_eval_step,
    make_dp_train_step,
    replicate,
)
from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
from mpi_cuda_cnn_tpu.train.trainer import make_loss_fn


def _setup(mesh, batch=16, seed=0):
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(seed), get_initializer("normal"))
    optimizer = make_optimizer(0.1)
    state = replicate(
        {"params": params, "opt_state": optimizer.init(params),
         "step": jnp.zeros((), jnp.int32)},
        mesh,
    )
    loss_fn = make_loss_fn(model)
    step = make_dp_train_step(loss_fn, optimizer, mesh, donate=False)
    rng = np.random.default_rng(42)
    x = jnp.asarray(rng.random((batch, 28, 28, 1), np.float32))
    y = np.zeros((batch, 10), np.float32)
    y[np.arange(batch), rng.integers(0, 10, batch)] = 1
    return model, state, step, x, jnp.asarray(y), loss_fn


def test_mesh_shapes(eight_devices):
    mesh = make_mesh({"data": 8})
    assert mesh.shape == {"data": 8}
    mesh2 = make_mesh({"data": 4, "model": 2})
    assert mesh2.shape == {"data": 4, "model": 2}


def test_dp8_equals_single_device(eight_devices):
    """8-way DP on a global batch must produce the same updated params as
    one device on the full batch — the correctness statement the
    reference's buggy allreduce failed (SURVEY.md 2.6a/b)."""
    mesh8 = make_mesh({"data": 8})
    mesh1 = make_mesh({"data": 1}, devices=jax.devices()[:1])

    _, state8, step8, x, y, _ = _setup(mesh8)
    _, state1, step1, _, _, _ = _setup(mesh1)

    s8, m8 = step8(state8, *dp_shard_batch((x, y), mesh8))
    s1, m1 = step1(state1, *dp_shard_batch((x, y), mesh1))

    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s8["params"]), jax.tree.leaves(s1["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_dp_grads_are_replicated_after_step(eight_devices):
    """After pmean every device must hold identical params (the reference
    never re-synchronized its divergent replicas, bug 2.6c)."""
    mesh = make_mesh({"data": 8})
    _, state, step, x, y, _ = _setup(mesh)
    new_state, _ = step(state, *dp_shard_batch((x, y), mesh))
    w = new_state["params"][0]["w"]
    shards = [np.asarray(s.data) for s in w.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_dp_batch_sharding_layout(eight_devices):
    mesh = make_mesh({"data": 8})
    x = jnp.zeros((32, 28, 28, 1))
    xs = dp_shard_batch(x, mesh)
    assert xs.sharding.spec == P("data")
    assert xs.addressable_shards[0].data.shape == (4, 28, 28, 1)


def test_dp_eval_step(eight_devices):
    mesh = make_mesh({"data": 8})
    model, state, _, x, _, _ = _setup(mesh)
    predict = lambda p, xx: model.apply(p, xx)
    ev = make_dp_eval_step(predict, mesh)
    logits = ev(state["params"], dp_shard_batch(x, mesh))
    ref = model.apply(jax.device_get(state["params"]), x)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_dp_loss_decreases(eight_devices):
    mesh = make_mesh({"data": 8})
    _, state, step, x, y, _ = _setup(mesh)
    batch = dp_shard_batch((x, y), mesh)
    losses = []
    for _ in range(10):
        state, m = step(state, *batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_dp_composes_with_pallas_backend(eight_devices):
    """Device kernels + data parallelism together — the capability the
    reference's CUDA+MPI variant aimed at and never reached (it does not
    compile: SURVEY.md §0 table, 2.15). Pallas kernels inside the
    shard_map-ed DP step must match the XLA-oracle DP step."""
    mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    optimizer = make_optimizer(0.1)

    def fresh_state():
        return replicate(
            {"params": params, "opt_state": optimizer.init(params),
             "step": jnp.zeros((), jnp.int32)},
            mesh,
        )

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.random((8, 28, 28, 1), np.float32))
    y = np.zeros((8, 10), np.float32)
    y[np.arange(8), rng.integers(0, 10, 8)] = 1
    batch = dp_shard_batch((x, jnp.asarray(y)), mesh)

    step_p = make_dp_train_step(
        make_loss_fn(model, backend="pallas"), optimizer, mesh, donate=False
    )
    step_o = make_dp_train_step(make_loss_fn(model), optimizer, mesh, donate=False)
    sp, mp = step_p(fresh_state(), *batch)
    so, mo = step_o(fresh_state(), *batch)

    np.testing.assert_allclose(float(mp["loss"]), float(mo["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(sp["params"]), jax.tree.leaves(so["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_uneven_batch_rejected(eight_devices):
    """batch not divisible by data axis must fail loudly, not silently
    mis-shard (the reference silently truncates its shard bounds,
    cnnmpi.c:457)."""
    mesh = make_mesh({"data": 8})
    _, state, step, *_ = _setup(mesh)
    x = jnp.zeros((12, 28, 28, 1))
    y = jnp.zeros((12, 10))
    # XLA surfaces the shape mismatch differently across versions
    # (ValueError vs XlaRuntimeError, sometimes with an empty message)
    # — the broad catch is deliberate (noqa'd), the behavior under test
    # is that the mis-sharded step REFUSES, whatever the lineage.
    with pytest.raises(Exception):  # noqa: B017
        jax.block_until_ready(step(state, *dp_shard_batch((x, y), mesh)))
