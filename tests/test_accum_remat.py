"""Gradient accumulation (--grad-accum) and rematerialization (--remat):
both must be pure implementation choices — identical math, different
memory/FLOPs — so every test here is an exact-parity assertion."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.train.trainer import Trainer
from mpi_cuda_cnn_tpu.utils.config import Config
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _quiet():
    return MetricsLogger(echo=False)


def _ds():
    return synthetic_stripes(num_train=256, num_test=64)


def _final_params(cfg, ds):
    t = Trainer(get_model(cfg.model), ds, cfg, metrics=_quiet())
    em = t.run_epoch(0)
    params = jax.device_get(
        t.state["params"] if "params" in t.state else t.state["flat_params"]
    )
    return params, em


def _assert_trees_close(a, b, rtol=2e-4, atol=2e-5):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


@pytest.mark.parametrize("mesh_shape", ["data", "data:4,model:2"])
def test_grad_accum_matches_plain(mesh_shape, eight_devices):
    """grad_accum=4 must produce the same averaged gradient — and thus the
    same params after an epoch — as one full-batch step (same batch
    permutation by construction: same seed, same steps_per_epoch)."""
    ds = _ds()
    base = dict(model="reference_cnn", epochs=1, batch_size=32, seed=7,
                eval_every=0, log_every=10**9, mesh_shape=mesh_shape,
                donate=False)
    p_plain, m_plain = _final_params(Config(**base), ds)
    p_accum, m_accum = _final_params(Config(grad_accum=4, **base), ds)
    _assert_trees_close(p_plain, p_accum)
    # The logged metrics are per-sample-normalized (squared_error_total
    # divides by batch, losses.py), so accumulation must not rescale them.
    for key in ("loss", "etotal", "acc"):
        np.testing.assert_allclose(m_plain[key], m_accum[key], rtol=1e-4)


def test_grad_accum_rejects_indivisible():
    ds = _ds()
    cfg = Config(batch_size=32, grad_accum=5, num_devices=1)
    with pytest.raises(ValueError, match="grad_accum"):
        Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())


def test_grad_accum_rejected_on_pp_mesh(eight_devices):
    ds = _ds()
    cfg = Config(batch_size=32, grad_accum=2, mesh_shape="pipe:2")
    with pytest.raises(ValueError, match="grad-accum"):
        Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())


def test_pp_remat_matches_plain_pp(eight_devices):
    """--remat on the pipeline path (jax.checkpoint around each stage fn)
    must change the backward schedule, not the math: params after an epoch
    on a pipe:2 mesh match the non-remat pipelined run."""
    ds = _ds()
    base = dict(model="reference_cnn", epochs=1, batch_size=32, seed=11,
                eval_every=0, log_every=10**9, mesh_shape="pipe:2",
                donate=False)
    p_plain, _ = _final_params(Config(**base), ds)
    p_remat, _ = _final_params(Config(remat=True, **base), ds)
    _assert_trees_close(p_plain, p_remat, rtol=1e-6, atol=1e-7)


def test_remat_matches_plain(eight_devices):
    """jax.checkpoint changes the schedule, not the function: params after
    an epoch must match the non-remat run bit-for-bit-ish."""
    ds = _ds()
    base = dict(model="reference_cnn", epochs=1, batch_size=32, seed=3,
                eval_every=0, log_every=10**9, donate=False)
    p_plain, _ = _final_params(Config(**base), ds)
    p_remat, _ = _final_params(Config(remat=True, **base), ds)
    _assert_trees_close(p_plain, p_remat, rtol=1e-6, atol=1e-7)


def test_remat_transformer_grads_match():
    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab=11, dim=16, heads=2, depth=2, max_seq=32)
    params = model.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 11, (2, 16)), jnp.int32
    )
    tgts = jnp.roll(toks, -1, axis=1)

    def loss(params, remat):
        logits = model.apply(params, toks, remat=remat)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, tgts[..., None], -1))

    g0 = jax.grad(lambda p: loss(p, False))(params)
    g1 = jax.grad(lambda p: loss(p, True))(params)
    _assert_trees_close(g0, g1, rtol=1e-5, atol=1e-7)


def test_lm_grad_accum_matches_plain(eight_devices):
    """--grad-accum on the LM step: per-chunk value_and_grad accumulated
    in a scan must equal the full-batch step exactly (equal chunks make
    the mean of chunk-means the batch mean), on a single device AND
    under FSDP (the GSPMD placement reuses the same step); the shard_map
    meshes reject the flag loudly."""
    import optax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.fsdp import make_fsdp_state
    from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(9)
    toks = jnp.asarray(rng.integers(0, 32, (8, 33)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]

    plain = make_lm_train_step(model, opt, attn_impl="oracle", seq_len=32,
                               donate=False)
    want_state, want_m = plain(make_lm_state(model, opt, seed=0),
                               tokens, targets)

    accum = make_lm_train_step(model, opt, attn_impl="oracle", seq_len=32,
                               donate=False, grad_accum=4)
    got_state, got_m = accum(make_lm_state(model, opt, seed=0),
                             tokens, targets)
    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(got_state["params"])),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)

    # FSDP x accum: ZeRO placement + the same chunked step.
    mesh = make_mesh({DATA_AXIS: 2}, devices=jax.devices()[:2])
    from jax.sharding import NamedSharding, PartitionSpec as P

    z_state = make_fsdp_state(model.init(jax.random.key(0)), opt, mesh)
    spec = NamedSharding(mesh, P(DATA_AXIS))
    got_z, m_z = accum(
        z_state, jax.device_put(tokens, spec), jax.device_put(targets, spec)
    )
    np.testing.assert_allclose(float(m_z["loss"]), float(want_m["loss"]),
                               rtol=1e-5)

    base = dict(corpus="synthetic", dim=32, depth=1, heads=4, seq_len=64,
                steps=2, batch_size=8, log_every=0, lr_schedule="constant",
                warmup_steps=0, grad_accum=2)
    with pytest.raises(ValueError, match="grad-accum"):
        LMTrainer(LMConfig(mesh_shape="pipe:2", **base),
                  metrics=MetricsLogger(echo=False))
    for mesh_shape in ("data:2", "data:2,seq:2"):
        r = LMTrainer(LMConfig(mesh_shape=mesh_shape, **base),
                      metrics=MetricsLogger(echo=False)).train()
        assert r.steps_run == 2 and np.isfinite(r.final_loss)


def test_sp_grad_accum_matches_plain(eight_devices):
    """--grad-accum INSIDE the SP shard_map (round 4: the ring
    collectives run uniformly per micro-batch): the accumulated step
    equals the unaccumulated one exactly on a data:2,seq:2 mesh."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.dp import replicate
    from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS, make_sp_lm_train_step
    from mpi_cuda_cnn_tpu.train.lm import make_lm_state

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(10)
    toks = jnp.asarray(rng.integers(0, 32, (8, 33)), jnp.int32)
    mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 2}, devices=jax.devices()[:4])
    bspec = NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS))
    tokens = jax.device_put(toks[:, :-1], bspec)
    targets = jax.device_put(toks[:, 1:], bspec)

    outs = {}
    for accum in (1, 2):
        step = make_sp_lm_train_step(
            model, opt, mesh, impl="ring", data_axis=DATA_AXIS,
            donate=False, grad_accum=accum,
        )
        state = replicate(make_lm_state(model, opt, seed=0), mesh)
        new_state, m = step(state, tokens, targets)
        outs[accum] = (float(m["loss"]),
                       jax.device_get(new_state["params"]))
    np.testing.assert_allclose(outs[2][0], outs[1][0], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(outs[2][1]),
                    jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)

    # FSDP x SP x accum: the gather happens once per step, the scan
    # accumulates inside it — still exactly the unaccumulated result.
    from mpi_cuda_cnn_tpu.parallel.fsdp import make_fsdp_state, state_specs

    z_state = make_fsdp_state(model.init(jax.random.key(0)), opt, mesh)
    z_step = make_sp_lm_train_step(
        model, opt, mesh, impl="ring", data_axis=DATA_AXIS,
        donate=False, state_specs=state_specs(z_state), grad_accum=2,
    )
    new_z, m_z = z_step(z_state, tokens, targets)
    np.testing.assert_allclose(float(m_z["loss"]), outs[1][0], rtol=1e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(new_z["params"])),
                    jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_bf16_accum_dtype_within_band_and_trains():
    """accum_dtype=bfloat16 stores the grad-accumulation carry in bf16
    (the HBM-traffic lever, dp._local_grads): the resulting update must
    stay within the bf16 accumulation error band of the exact f32
    accumulation (~sqrt(N)*2^-8 relative at N micro-batches), and the
    step must still train. Exactness is NOT expected — that is what the
    default f32 carry is for."""
    import optax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(9)
    toks = jnp.asarray(rng.integers(0, 32, (8, 33)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]

    f32 = make_lm_train_step(model, opt, attn_impl="oracle", seq_len=32,
                             donate=False, grad_accum=4)
    want_state, want_m = f32(make_lm_state(model, opt, seed=0),
                             tokens, targets)
    bf16 = make_lm_train_step(model, opt, attn_impl="oracle", seq_len=32,
                              donate=False, grad_accum=4,
                              accum_dtype="bfloat16")
    got_state, got_m = bf16(make_lm_state(model, opt, seed=0),
                            tokens, targets)

    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5)  # loss accumulates f32 either way
    # Updated params: bf16 carry rounds each micro-grad add — band, not
    # bitwise. sgd lr 0.1 scales grad error into params; tol covers the
    # 2^-8-per-add band with margin while still failing on e.g. a
    # dropped micro-batch (a 25% gradient error at accum 4).
    for a, b in zip(jax.tree.leaves(got_state["params"]),
                    jax.tree.leaves(want_state["params"])):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(np.abs(b).max(), 1e-3)
        assert np.abs(a - b).max() / scale < 2e-2

    # And it trains: a few steps reduce the loss.
    state = make_lm_state(model, opt, seed=1)
    first = None
    for _ in range(6):
        state, m = bf16(state, tokens, targets)
        if first is None:
            first = float(m["loss"])
    assert np.isfinite(float(m["loss"])) and float(m["loss"]) < first
