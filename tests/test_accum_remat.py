"""Gradient accumulation (--grad-accum) and rematerialization (--remat):
both must be pure implementation choices — identical math, different
memory/FLOPs — so every test here is an exact-parity assertion."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.train.trainer import Trainer
from mpi_cuda_cnn_tpu.utils.config import Config
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _quiet():
    return MetricsLogger(echo=False)


def _ds():
    return synthetic_stripes(num_train=256, num_test=64)


def _final_params(cfg, ds):
    t = Trainer(get_model(cfg.model), ds, cfg, metrics=_quiet())
    em = t.run_epoch(0)
    params = jax.device_get(
        t.state["params"] if "params" in t.state else t.state["flat_params"]
    )
    return params, em


def _assert_trees_close(a, b, rtol=2e-4, atol=2e-5):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


@pytest.mark.parametrize("mesh_shape", ["data", "data:4,model:2"])
def test_grad_accum_matches_plain(mesh_shape, eight_devices):
    """grad_accum=4 must produce the same averaged gradient — and thus the
    same params after an epoch — as one full-batch step (same batch
    permutation by construction: same seed, same steps_per_epoch)."""
    ds = _ds()
    base = dict(model="reference_cnn", epochs=1, batch_size=32, seed=7,
                eval_every=0, log_every=10**9, mesh_shape=mesh_shape,
                donate=False)
    p_plain, m_plain = _final_params(Config(**base), ds)
    p_accum, m_accum = _final_params(Config(grad_accum=4, **base), ds)
    _assert_trees_close(p_plain, p_accum)
    # The logged metrics are per-sample-normalized (squared_error_total
    # divides by batch, losses.py), so accumulation must not rescale them.
    for key in ("loss", "etotal", "acc"):
        np.testing.assert_allclose(m_plain[key], m_accum[key], rtol=1e-4)


def test_grad_accum_rejects_indivisible():
    ds = _ds()
    cfg = Config(batch_size=32, grad_accum=5, num_devices=1)
    with pytest.raises(ValueError, match="grad_accum"):
        Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())


def test_grad_accum_rejected_on_pp_mesh(eight_devices):
    ds = _ds()
    cfg = Config(batch_size=32, grad_accum=2, mesh_shape="pipe:2")
    with pytest.raises(ValueError, match="grad-accum"):
        Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())


def test_pp_remat_matches_plain_pp(eight_devices):
    """--remat on the pipeline path (jax.checkpoint around each stage fn)
    must change the backward schedule, not the math: params after an epoch
    on a pipe:2 mesh match the non-remat pipelined run."""
    ds = _ds()
    base = dict(model="reference_cnn", epochs=1, batch_size=32, seed=11,
                eval_every=0, log_every=10**9, mesh_shape="pipe:2",
                donate=False)
    p_plain, _ = _final_params(Config(**base), ds)
    p_remat, _ = _final_params(Config(remat=True, **base), ds)
    _assert_trees_close(p_plain, p_remat, rtol=1e-6, atol=1e-7)


def test_remat_matches_plain(eight_devices):
    """jax.checkpoint changes the schedule, not the function: params after
    an epoch must match the non-remat run bit-for-bit-ish."""
    ds = _ds()
    base = dict(model="reference_cnn", epochs=1, batch_size=32, seed=3,
                eval_every=0, log_every=10**9, donate=False)
    p_plain, _ = _final_params(Config(**base), ds)
    p_remat, _ = _final_params(Config(remat=True, **base), ds)
    _assert_trees_close(p_plain, p_remat, rtol=1e-6, atol=1e-7)


def test_remat_transformer_grads_match():
    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab=11, dim=16, heads=2, depth=2, max_seq=32)
    params = model.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 11, (2, 16)), jnp.int32
    )
    tgts = jnp.roll(toks, -1, axis=1)

    def loss(params, remat):
        logits = model.apply(params, toks, remat=remat)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, tgts[..., None], -1))

    g0 = jax.grad(lambda p: loss(p, False))(params)
    g1 = jax.grad(lambda p: loss(p, True))(params)
    _assert_trees_close(g0, g1, rtol=1e-5, atol=1e-7)
