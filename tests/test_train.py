"""Training-level tests: asserted convergence (the acceptance check the
reference leaves to a human eyeballing 'ntests=, ncorrect=' — SURVEY.md §4),
determinism, resume."""

import jax
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.train.trainer import Trainer
from mpi_cuda_cnn_tpu.utils.config import Config
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _quiet():
    return MetricsLogger(echo=False)


@pytest.fixture(scope="module")
def ds():
    return synthetic_stripes(num_train=512, num_test=128)


def test_convergence_reference_cnn(ds, eight_devices):
    """The survey's empirical check (SURVEY.md §4): stripes dataset reaches
    ~100% — asserted here, not eyeballed."""
    cfg = Config(epochs=3, eval_every=0, log_every=10**9)
    t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    r = t.train()
    assert r.test_accuracy >= 0.95, r.test_accuracy
    assert r.final_step == 3 * (512 // 32)


def test_convergence_lenet5(ds, eight_devices):
    cfg = Config(model="lenet5", init="he", epochs=3, eval_every=0, log_every=10**9)
    t = Trainer(get_model("lenet5"), ds, cfg, metrics=_quiet())
    assert t.train().test_accuracy >= 0.9


def test_convergence_cifar3conv(eight_devices):
    """The 32x32x3 input path (BASELINE.json configs 4-5) end to end:
    cifar3conv on CIFAR-shaped synthetic stripes over the 8-device mesh."""
    from mpi_cuda_cnn_tpu.data.datasets import get_dataset

    ds = get_dataset("synthetic_cifar", num_train=512, num_test=128)
    assert ds.input_shape == (32, 32, 3)
    cfg = Config(model="cifar3conv", init="he", epochs=3, eval_every=0,
                 log_every=10**9)
    t = Trainer(get_model("cifar3conv"), ds, cfg, metrics=_quiet())
    assert t.train().test_accuracy >= 0.9


def test_determinism_same_seed(ds):
    """Fixed seed -> identical final params, the property the reference's
    srand(0) exists for (cnn.c:413)."""
    cfg = Config(epochs=1, seed=5, eval_every=0, log_every=10**9, num_devices=1)
    t1 = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    t1.train()
    t2 = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    t2.train()
    for a, b in zip(
        jax.tree.leaves(jax.device_get(t1.state["params"])),
        jax.tree.leaves(jax.device_get(t2.state["params"])),
    ):
        np.testing.assert_array_equal(a, b)


def test_irwin_hall_reference_config(ds):
    """The reference's exact hyperparameter set (lr .1, batch 32, nrnd init)
    still trains — the parity configuration of SURVEY.md §7 stage 2."""
    cfg = Config(epochs=2, init="irwin_hall", eval_every=0, log_every=10**9,
                 num_devices=1)
    t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    assert t.train().test_accuracy >= 0.9


def test_checkpoint_resume(ds, tmp_path):
    cfg = Config(epochs=1, eval_every=0, log_every=10**9, num_devices=1,
                 checkpoint_dir=str(tmp_path / "ck"))
    t1 = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    t1.train()
    step1 = int(jax.device_get(t1.state["step"]))

    cfg2 = Config(epochs=2, eval_every=0, log_every=10**9, num_devices=1,
                  checkpoint_dir=str(tmp_path / "ck"), resume=True)
    t2 = Trainer(get_model("reference_cnn"), ds, cfg2, metrics=_quiet())
    r2 = t2.train()
    assert r2.epochs_run == 1  # resumed at epoch 1 of 2
    assert int(jax.device_get(t2.state["step"])) == 2 * step1


def test_scan_matches_per_batch_loop(ds):
    """The scanned-epoch path (one dispatch per log_every steps, HBM-resident
    dataset) and the per-batch dispatch loop are the same math: same seed ->
    same shuffle stream -> near-identical final params."""
    base = dict(epochs=1, seed=3, eval_every=0, log_every=10**9, num_devices=1)
    t_scan = Trainer(get_model("reference_cnn"), ds, Config(scan=True, **base),
                     metrics=_quiet())
    t_scan.train()
    t_loop = Trainer(get_model("reference_cnn"), ds, Config(scan=False, **base),
                     metrics=_quiet())
    t_loop.train()
    for a, b in zip(
        jax.tree.leaves(jax.device_get(t_scan.state["params"])),
        jax.tree.leaves(jax.device_get(t_loop.state["params"])),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_scan_chunked_logging(ds):
    """log_every smaller than steps-per-epoch chunks the scan and produces
    train metric rows exactly at multiples of log_every — the same rows the
    per-batch loop path emits (the short tail chunk trains but never logs)."""
    cfg = Config(epochs=1, eval_every=0, log_every=5, num_devices=1)
    metrics = MetricsLogger(echo=False, capture=True)
    t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=metrics)
    em = t.run_epoch(0)
    nsteps = 512 // 32
    assert em["steps"] == nsteps
    train_rows = [r for r in metrics.rows if r["event"] == "train"]
    assert [r["step"] for r in train_rows] == [5, 10, 15]  # == loop path
    assert len(train_rows) == nsteps // 5


def test_bfloat16_training(ds):
    cfg = Config(epochs=2, compute_dtype="bfloat16", eval_every=0,
                 log_every=10**9, num_devices=1)
    t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    assert t.train().test_accuracy >= 0.9


def test_pp_trainer_end_to_end(ds, eight_devices):
    """--mesh-shape pipe:4: the full Trainer loop (scanned epochs, eval,
    the reference's ntests/ncorrect metric) over the GPipe schedule."""
    cfg = Config(model="lenet5", init="he", epochs=3, eval_every=0,
                 log_every=10**9, mesh_shape="pipe:4", num_devices=4)
    t = Trainer(get_model("lenet5"), ds, cfg, metrics=_quiet())
    assert t.n_pipe == 4
    r = t.train()
    assert r.test_accuracy >= 0.9, r.test_accuracy
    assert r.final_step == 3 * (512 // 32)


def test_pp_bfloat16_training(ds, eight_devices):
    """--compute-dtype bfloat16 reaches the PP stage fns (the plan carries
    the cast; master params and ppermute buffers stay f32) and still
    converges."""
    cfg = Config(model="lenet5", init="he", epochs=3, eval_every=0,
                 log_every=10**9, mesh_shape="pipe:2", num_devices=2,
                 compute_dtype="bfloat16")
    t = Trainer(get_model("lenet5"), ds, cfg, metrics=_quiet())
    assert t._pp_plan.compute_dtype is not None
    assert t.train().test_accuracy >= 0.9


def test_pp_rejects_bfloat16_params(ds):
    cfg = Config(model="lenet5", init="he", param_dtype="bfloat16",
                 mesh_shape="pipe:2", num_devices=2, eval_every=0)
    with pytest.raises(ValueError, match="master params"):
        Trainer(get_model("lenet5"), ds, cfg, metrics=_quiet())


def test_microbatches_require_pipe_axis(ds):
    cfg = Config(num_microbatches=4, num_devices=1, eval_every=0)
    with pytest.raises(ValueError, match="pipe"):
        Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())


def test_pp_trainer_matches_dp(ds):
    """PP is a schedule, not different math: same seed/config under
    pipe:2 and plain DP produce near-identical final params."""
    from mpi_cuda_cnn_tpu.parallel.pp import unpack_params

    base = dict(model="lenet5", init="he", epochs=1, seed=3, eval_every=0,
                log_every=10**9, scan=True)
    t_pp = Trainer(get_model("lenet5"), ds,
                   Config(mesh_shape="pipe:2", num_devices=2, **base),
                   metrics=_quiet())
    t_pp.train()
    t_dp = Trainer(get_model("lenet5"), ds, Config(num_devices=1, **base),
                   metrics=_quiet())
    t_dp.train()
    pp_params = unpack_params(t_pp._pp_plan,
                              jax.device_get(t_pp.state["flat_params"]))
    for a, b in zip(
        jax.tree.leaves(jax.device_get(pp_params)),
        jax.tree.leaves(jax.device_get(t_dp.state["params"])),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_pp_trainer_loop_path(ds, eight_devices):
    """scan=False under PP: the per-batch dispatch loop places (M, mb, ...)
    microbatches and still trains."""
    cfg = Config(model="lenet5", init="he", epochs=2, eval_every=0,
                 log_every=10**9, mesh_shape="pipe:2,data:2", num_devices=4,
                 scan=False, num_microbatches=4)
    t = Trainer(get_model("lenet5"), ds, cfg, metrics=_quiet())
    assert t.train().test_accuracy >= 0.9


def test_pp_checkpoint_resume(ds, tmp_path):
    """Checkpoints are host pytrees; restoring onto the PP path re-places
    the packed stage rows with their pipe shardings (place_state)."""
    base = dict(model="lenet5", init="he", eval_every=0, log_every=10**9,
                mesh_shape="pipe:2", num_devices=2,
                checkpoint_dir=str(tmp_path / "ck"))
    t1 = Trainer(get_model("lenet5"), ds, Config(epochs=1, **base),
                 metrics=_quiet())
    t1.train()
    t2 = Trainer(get_model("lenet5"), ds,
                 Config(epochs=2, resume=True, **base), metrics=_quiet())
    r2 = t2.train()
    assert r2.epochs_run == 1
    assert int(jax.device_get(t2.state["step"])) == 2 * (512 // 32)
