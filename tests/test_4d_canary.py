"""DEFAULT-suite 4D parity canary (VERDICT round-5 #6): the full
pipe:2 x model:2 x seq:2 x data:2 composition must hold exact serial
parity on every fast-suite run, not only under --runslow — the flagship
composition used to be guarded exclusively by slow twins, so it could
regress silently between --runslow runs.

Same spawned-worker pattern as tests/test_4d_full.py (16 virtual
devices need their own process), but at the smallest shapes every axis
admits plus a persistent XLA compile cache (.cache/jax_4d_canary):
steady-state wall-clock < 8 s measured; only the first run on a fresh
checkout pays the ~16 s compile.
"""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
WORKER = REPO / "scripts" / "fourd16_worker.py"


def test_4d_canary_16_devices_matches_serial():
    proc = subprocess.run(
        [sys.executable, str(WORKER), "--fast"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, (
        f"4D canary failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "4D16OK" in proc.stdout
