"""Serving engine (mpi_cuda_cnn_tpu/serve/): paged-cache parity with the
contiguous decode path, page-pool accounting invariants, and the
continuous-vs-static scheduler comparison — all deterministic on CPU."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.generate import decode_step, generate, init_cache
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.serve.paged_cache import (
    PagePool,
    init_paged_cache,
    pages_for,
)
from mpi_cuda_cnn_tpu.serve.scheduler import ContinuousScheduler, Request

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)
GQA = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48,
                    kv_heads=2, pos="rope")


def _identity_paged_cache(model, batch, page_size, dtype=jnp.float32):
    """A paged cache whose block tables cover max_seq per row with
    ascending page indices — the layout the layer-level parity loops
    drive through decode_step's PagedKVCache dispatch."""
    per = pages_for(model.max_seq, page_size)
    cache = init_paged_cache(model, slots=batch,
                             num_pages=batch * per + 1,
                             page_size=page_size, dtype=dtype)
    table = 1 + np.arange(batch * per, dtype=np.int32).reshape(batch, per)
    return dataclasses.replace(cache, block_table=jnp.asarray(table))


@pytest.mark.parametrize("model", [MODEL, GQA], ids=["mha", "gqa_rope"])
def test_paged_decode_step_matches_contiguous_f32(model):
    """decode_step over a PagedKVCache (per-slot positions) must equal
    the contiguous cache BITWISE in f32: the two layouts share the
    attention read (generate.attend_kv) and differ only in how cache
    rows are materialized, so any drift is a layout bug, not rounding.
    Page size 8 does not divide 20 steps evenly — writes cross page
    boundaries mid-sequence."""
    params = model.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, 13, (3, 20)), jnp.int32
    )
    cc = init_cache(model, 3)
    pc = _identity_paged_cache(model, 3, page_size=8)
    for i in range(20):
        want, cc = decode_step(model, params, toks[:, i], i, cc)
        got, pc = decode_step(model, params, toks[:, i],
                              jnp.full((3,), i, jnp.int32), pc)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"step {i}")


@pytest.mark.parametrize("dtype", ["bfloat16", "int8"])
def test_paged_decode_step_matches_contiguous_quantized(dtype):
    """bf16/int8 paged caches quantize EXACTLY like the contiguous ones
    (same per-(position, head) absmax contract), so the two layouts stay
    within tight float tolerance of each other — far inside the
    cache-dtype error bands the contiguous tests pin vs f32."""
    params = MODEL.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(3).integers(0, 13, (2, 16)), jnp.int32
    )
    cc = init_cache(MODEL, 2, jnp.dtype(dtype))
    pc = _identity_paged_cache(MODEL, 2, page_size=8, dtype=jnp.dtype(dtype))
    assert pc.pages[0]["k"].dtype == jnp.dtype(dtype)
    for i in range(16):
        want, cc = decode_step(MODEL, params, toks[:, i], i, cc)
        got, pc = decode_step(MODEL, params, toks[:, i],
                              jnp.full((2,), i, jnp.int32), pc)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5, err_msg=f"step {i}")


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_engine_greedy_generations_match_generate(dtype):
    """End-to-end: the engine's chunked-prefill + paged-decode greedy
    continuations equal models/generate.generate's contiguous ones for
    every request — across cache dtypes, prompt lengths that don't
    divide the prefill chunk, and both scheduler modes."""
    params = MODEL.init(jax.random.key(0))
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 13, (n,)).astype(np.int32)
               for n in (3, 7, 11, 5)]
    new = [9, 4, 12, 7]
    want = [
        np.asarray(generate(MODEL, params, jnp.asarray(p[None, :]), n,
                            cache_dtype=dtype))[0]
        for p, n in zip(prompts, new)
    ]
    engine = PagedEngine(MODEL, params, slots=2, num_pages=4 * 6 + 1,
                         page_size=8, prefill_chunk=4, cache_dtype=dtype)
    for mode in ("continuous", "static"):
        reqs = [Request(rid=i, prompt=p, max_new_tokens=n)
                for i, (p, n) in enumerate(zip(prompts, new))]
        res = engine.run(reqs, mode=mode)
        assert sorted(r.rid for r in res.requests) == [0, 1, 2, 3]
        for r in res.requests:
            np.testing.assert_array_equal(
                np.asarray(r.out), want[r.rid],
                err_msg=f"{mode} request {r.rid} ({dtype})"
            )


def test_static_holds_slot_when_request_finishes_at_prefill():
    """A max_new_tokens=1 request finishes AT prefill completion (its
    only token comes from the last chunk's logits). Under static
    batching that slot must stay reserved until the batch drains —
    finishing it early would release pages mid-batch, breaking the
    reserve-until-drain discipline the comparison measures — and both
    requests must still complete in both modes."""
    params = MODEL.init(jax.random.key(0))
    engine = PagedEngine(MODEL, params, slots=2, num_pages=15, page_size=8)
    for mode in ("static", "continuous"):
        reqs = [Request(rid=0, prompt=np.arange(5) % 13, max_new_tokens=1),
                Request(rid=1, prompt=np.arange(7) % 13, max_new_tokens=10)]
        res = engine.run(reqs, mode=mode)
        assert sorted(r.rid for r in res.requests) == [0, 1]
        assert [len(r.out) for r in
                sorted(res.requests, key=lambda r: r.rid)] == [1, 10]


def test_page_pool_accounting():
    pool = PagePool(8)  # 7 usable, page 0 scratch
    a = pool.try_alloc(3, "a")
    b = pool.try_alloc(2, "b")
    assert a == [1, 2, 3] and b == [4, 5]  # deterministic ascending issue
    assert pool.free_pages == 2
    assert pool.try_alloc(3, "c") is None  # over-ask: no change
    assert pool.free_pages == 2
    pool.check()
    with pytest.raises(RuntimeError, match="owned by"):
        pool.free([4], "a")                # foreign free refused
    pool.free(a, "a")
    with pytest.raises(RuntimeError, match="double free"):
        pool.free(a, "a")
    pool.free(b, "b")
    pool.check()
    assert pool.free_pages == pool.usable


def test_scheduler_admit_finish_preempt_keep_pool_consistent():
    """Drive the continuous scheduler through admit -> decode growth ->
    forced preemption -> finish and assert the pool invariant after
    every transition: no leak, no double-book, scratch page never
    circulates."""
    pool = PagePool(7)  # 6 usable pages of 4 tokens
    sched = ContinuousScheduler(slots=2, pool=pool, page_size=4, max_len=24)
    rng = np.random.default_rng(0)
    reqs = [Request(rid=i, prompt=rng.integers(0, 13, (8,)), arrival=0.0,
                    max_new_tokens=12) for i in range(3)]
    sched.submit(reqs)
    bound = sched.admit(0.0)
    # 8-token prompts need 2 pages each +1 headroom: both slots admit.
    assert [s.req.rid for s in bound] == [0, 1]
    pool.check()
    assert pool.free_pages == 2
    for s in bound:                       # prefill completes, decode grows
        s.cached = s.target
        s.req.out.append(1)
    assert len(sched.grow_for_decode()) == 2
    pool.check()
    # Burn the remaining pages: advance both slots until the pool runs
    # dry and the LATEST-admitted sequence gets preempted.
    while sched.preemptions == 0:
        for s in list(sched.decode_slots()):
            s.cached += 1
            s.req.out.append(1)
        sched.grow_for_decode()
        pool.check()
    assert sched.slots[1].free            # victim = latest admitted
    assert reqs[1].preemptions == 1
    assert sched.queue[0].rid == 1        # requeued at the head
    sched.finish(sched.slots[0], now=1.0)
    pool.check()
    assert reqs[0].finished_at == 1.0
    # Everything freed once the survivor finished.
    assert pool.free_pages == pool.usable - 0 - len(sched.slots[1].pages)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_pagepool_randomized_op_sequence_invariant(dtype):
    """Seeded randomized-sequence invariant (ISSUE 7 satellite,
    extended for ISSUE 9 and again for ISSUE 13): a few hundred random
    admit / prefill-chunk / decode-growth / preempt / cancel / expire
    operations — interleaved with prefix-cache share / acquire / COW /
    insert / LRU-evict / release traffic (half the prompts draw from a
    shared template pool, a reclaim op squeezes retained pages out)
    AND with cross-pool KV-handoff traffic against a SECOND
    engine+pool+scheduler (detach-for-handoff seals pages under the
    transfer token, the receiver adopts via the cross-engine page copy
    and binds decode-ready, and a random half of the transfers are
    REVOKED mid-flight instead — both ends released) — against real
    PagedEngine caches in each storage dtype, with the extended
    sched.check() (pool no-leak / no-double-book / scratch-never-
    circulates PLUS refcount conservation and no-writable-shared-page)
    on BOTH pools after EVERY step. ISSUE 14 adds speculative rounds:
    a spec decode op grows toward the k-row verify width, commits a
    VARIABLE number of tokens (whatever greedy acceptance yields), and
    commit_spec's rejected-draft ROLLBACK hands surplus pages back —
    the walk must observe both a multi-token commit and a rollback.
    ISSUE 17 bolts a bounded HostTier onto scheduler A's prefix cache:
    LRU reclaims SPILL real engine KV pages to host entries, later
    template walks READMIT them through fresh allocations, and a
    corrupt-seal op arms the kv_corrupt injector so at least one
    lookup REFUSES a flipped stamp and degrades to re-prefill — all
    under the same every-step check().
    ISSUE 19 adds the autoscaler's membership moves as walk ops: a
    JOIN op brings up a whole new engine+pool+scheduler member
    mid-walk, a dispatch op routes queued work onto joined members,
    and a GRACEFUL-DRAIN op stops a member's admissions and requeues
    its waiting work back while in-flight slots run to completion —
    every member's pool under the same every-step check(), and every
    drained member's pool must hand back every page.
    ISSUE 20 puts a REAL TransportBus under part of the traffic:
    a bus-dispatch op sends requests to scheduler A over the wire
    (some copies DELAYED in flight — a request on the wire is in no
    scheduler, so the every-step check() proves wire state never
    leaks into a pool), a harvest op reports terminal requests back
    over the bus with DUPLICATED copies the receiver must dedup, and
    two sampled PARTITION windows open and heal mid-walk — reliable
    sends retransmit through them and every bus-dispatched request
    still arrives exactly once.
    The fleet's re-dispatch and disaggregated-handoff paths
    (serve/fleet.py) drive these exact scheduler+pool+prefix triples
    per replica, so they inherit the guarantee."""
    from mpi_cuda_cnn_tpu.serve.host_tier import HostTier
    from mpi_cuda_cnn_tpu.serve.prefix_cache import PrefixCache
    from mpi_cuda_cnn_tpu.serve.spec import LookupProposer, run_round

    params = MODEL.init(jax.random.key(2))
    engine = PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                         prefill_chunk=4, max_len=32, cache_dtype=dtype,
                         spec="lookup", spec_k=4)
    # Host pool sized to the engine's device page arrays — the pairing
    # ReplicaCore uses: page indices from this pool index those arrays.
    pool = PagePool(10)
    # Host tier on A (ISSUE 17): real engine spill/readmit callbacks —
    # evicted KV rows round-trip through host memory — plus an armable
    # corrupt-seal injector (the kv_corrupt@tier.spill path).
    corrupt_pending = [0]

    class _Corrupt:
        kind = "kv_corrupt"

    def tier_poll(seq):
        if corrupt_pending[0]:
            corrupt_pending[0] -= 1
            return [_Corrupt]
        return []

    tier = HostTier(4, spill_fn=engine.spill_page,
                    readmit_fn=engine.readmit_page, fault_poll=tier_poll)
    prefix = PrefixCache(pool, page_size=4, tier=tier)
    sched = ContinuousScheduler(slots=3, pool=pool, page_size=4, max_len=32,
                                prefix=prefix)
    # The decode-side twin (ISSUE 13): its own engine/pool/scheduler —
    # handed-off requests decode (and, after a preemption there,
    # re-prefill) on this pair.
    engine_b = PagedEngine(MODEL, params, slots=3, num_pages=10,
                           page_size=4, prefill_chunk=4, max_len=32,
                           cache_dtype=dtype, spec="lookup", spec_k=4)
    pool_b = PagePool(10)
    sched_b = ContinuousScheduler(slots=3, pool=pool_b, page_size=4,
                                  max_len=32,
                                  prefix=PrefixCache(pool_b, page_size=4))
    transfers = {"done": 0, "revoked": 0}
    next_hid = [0]
    rng = np.random.default_rng(11)
    # Shared template prompts: same-template requests exercise full-page
    # acquire; divergent suffixes at non-page-aligned depths hit COW.
    templates = [rng.integers(0, 13, (9,)).astype(np.int32)
                 for _ in range(2)]
    now = 0.0
    next_rid = 0
    submitted: list[Request] = []

    def submit_one():
        nonlocal next_rid
        if rng.random() < 0.5:
            tmpl = templates[int(rng.integers(len(templates)))]
            keep = int(rng.integers(4, 10))
            tail = rng.integers(0, 13, (int(rng.integers(1, 4)),))
            prompt = np.concatenate([tmpl[:keep], tail.astype(np.int32)])
        else:
            prompt = rng.integers(0, 13, (int(rng.integers(2, 12)),))
        req = Request(
            rid=next_rid, prompt=prompt,
            max_new_tokens=int(rng.integers(2, 14)), arrival=now,
            # ~1 in 4 requests carries a deadline the clock will cross.
            deadline=(now + float(rng.uniform(0.05, 0.6))
                      if rng.random() < 0.25 else None),
        )
        next_rid += 1
        submitted.append(req)
        sched.submit([req])

    def prefill_step(sc=None, en=None):
        sc, en = sc or sched, en or engine
        slot = sc.prefill_slot()
        if slot is None:
            return
        if slot.cow is not None:
            en.copy_page(*slot.cow)
            sc.cow_complete(slot)
        n, nxt = en.run_prefill_chunk(slot)
        slot.cached += n
        if slot.cached >= slot.target:
            sc.note_prefill_complete(slot)
            slot.req.out.append(int(nxt))
            if slot.req.done:
                sc.finish(slot, now)

    def decode_step_op(sc=None, en=None):
        sc, en = sc or sched, en or engine
        dslots = sc.grow_for_decode(now)
        if not dslots:
            return
        toks = en.run_decode_tick(dslots)
        for s in dslots:
            s.cached += 1
            s.req.out.append(int(toks[s.idx]))
            if s.req.done:
                sc.finish(s, now)

    proposer = LookupProposer(ngram=2)
    spec_seen = {"rounds": 0, "multi": 0, "rollbacks": 0}

    def spec_decode_op(sc=None, en=None):
        # Speculative round (ISSUE 14): grow toward the k-row verify
        # width, ONE batched verify, variable-length commit, rollback
        # of rejected-draft pages.
        sc, en = sc or sched, en or engine
        dslots = sc.grow_for_decode(now, spec_k=4)
        if not dslots:
            return
        widths = [sc.spec_width(s, 4) for s in dslots]
        results = run_round(dslots, widths, proposer, en.run_spec_tick)
        for s, w, j, toks in results:
            pages_before = len(s.pages)
            sc.commit_spec(s, j)
            spec_seen["rounds"] += 1
            spec_seen["multi"] += j > 1
            spec_seen["rollbacks"] += len(s.pages) < pages_before
            s.req.out.extend(toks)
            if s.req.done:
                sc.finish(s, now)

    def preempt_op():
        bound = [s for s in sched.slots if not s.free]
        if bound:
            sched.preempt(bound[int(rng.integers(len(bound)))])

    def cancel_op():
        live = [r for r in submitted if not r.terminal]
        if live:
            live[int(rng.integers(len(live)))].cancel()
            sched.sweep(now)
            sched_b.sweep(now)

    def reclaim_op():
        # The squeeze/pressure path: evict up to 2 LRU refcount-0
        # prefix pages (never a referenced one — free() would raise).
        # With the tier attached each eviction SPILLS instead of
        # discarding — the pressure op doubles as the spill op.
        prefix.reclaim(int(rng.integers(1, 3)))

    def corrupt_op():
        # Arm the injector: the NEXT spill seals a flipped stamp, so a
        # later matching tier lookup must refuse it (counted) and fall
        # back to a plain miss — the re-prefill degrade path.
        corrupt_pending[0] += 1

    def handoff_op():
        # Cross-pool transfer (ISSUE 13): seal a decoding slot's page
        # set off scheduler A under the handoff token, then either
        # adopt it into B (cross-engine page copy + decode-ready bind)
        # or REVOKE the transfer mid-flight — both ends released, the
        # request requeued at A's head (the abort-re-prefill path).
        cands = [s for s in sched.slots
                 if s.decoding and not s.req.terminal and s.cow is None]
        if not cands:
            return
        slot = cands[int(rng.integers(len(cands)))]
        req, cached = slot.req, slot.cached
        owner = ("handoff", req.rid, next_hid[0])
        next_hid[0] += 1
        pages, private, nodes = sched.detach_for_handoff(slot, owner)
        dst = pool_b.try_alloc(len(pages), owner)
        if dst is None or rng.random() < 0.5:
            # Revoked (receiver dry, dropped, or CRC-refused): release
            # both ends, requeue for re-prefill on A.
            if dst is not None:
                pool_b.free(dst, owner)
            sched.release_handoff(private, nodes, owner)
            req.status = "queued"
            sched.queue.appendleft(req)
            transfers["revoked"] += 1
            return
        engine_b.adopt_pages(engine, pages, dst)
        bound = sched_b.bind_transfer(req, dst, cached, owner, now)
        if bound is None:
            # No free receiver slot: treat as a revoke (the fleet
            # would keep waiting; the invariant walk releases).
            pool_b.free(dst, owner)
            sched.release_handoff(private, nodes, owner)
            req.status = "queued"
            sched.queue.appendleft(req)
            transfers["revoked"] += 1
            return
        sched.release_handoff(private, nodes, owner)
        transfers["done"] += 1

    # Autoscaler membership moves (ISSUE 19): joined members are whole
    # engine+pool+scheduler triples appearing MID-WALK, exactly what a
    # replica_join brings up; graceful drain is the scale-down leg.
    members: list[dict] = []
    scale = {"joins": 0, "dispatches": 0, "drains": 0}

    def join_op():
        if len(members) >= 2:
            return
        p = PagePool(10)
        e = PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                        prefill_chunk=4, max_len=32, cache_dtype=dtype,
                        spec="lookup", spec_k=4)
        s = ContinuousScheduler(slots=3, pool=p, page_size=4, max_len=32,
                                prefix=PrefixCache(p, page_size=4))
        members.append({"sched": s, "engine": e, "pool": p,
                        "draining": False})
        scale["joins"] += 1

    def member_dispatch_op():
        # Route queued work onto a joined member — the autoscaler's
        # whole point: new capacity takes load off the loaded one.
        live = [m for m in members if not m["draining"]]
        if not live or not sched.queue:
            return
        m = live[int(rng.integers(len(live)))]
        m["sched"].submit([sched.queue.popleft()])
        scale["dispatches"] += 1

    def member_step_op():
        if not members:
            return
        m = members[int(rng.integers(len(members)))]
        m["sched"].sweep(now)
        if not m["draining"]:
            m["sched"].admit(now)
        prefill_step(m["sched"], m["engine"])
        decode_step_op(m["sched"], m["engine"])

    def drain_op():
        # Graceful drain: no new admissions, waiting work requeues back
        # to A, in-flight slots run to completion — the member's pool
        # must end the walk with every page handed back.
        live = [m for m in members if not m["draining"]]
        if not live:
            return
        m = live[int(rng.integers(len(live)))]
        m["draining"] = True
        while m["sched"].queue:
            sched.queue.append(m["sched"].queue.popleft())
        scale["drains"] += 1

    def check_both():
        sched.check()
        sched_b.check()
        for m in members:
            m["sched"].check()

    # Lossy-transport ops (ISSUE 20): a real TransportBus carries part
    # of the dispatch traffic into scheduler A and harvest reports
    # back out, with delayed dispatches, duplicated harvest reports
    # and two partition windows armed on the bus's own fault injector.
    from mpi_cuda_cnn_tpu.faults import FaultInjector
    from mpi_cuda_cnn_tpu.serve.transport import TransportBus

    bus_tick = [0]
    wire = {"dispatched": 0, "harvests": 0}
    wire_rids: set = set()
    harvest_seen: set = set()

    def _router_msg(msg, tick):
        # Receiver-side dedup makes the duplicated harvest report a
        # single logical delivery.
        assert msg.key not in harvest_seen, "bus dedup failed"
        harvest_seen.add(msg.key)

    def _member_msg(msg, tick):
        req = msg.payload
        assert req.rid not in wire_rids, "duplicate dispatch delivery"
        wire_rids.add(req.rid)
        sched.submit([req])

    bus = TransportBus(faults=FaultInjector(
        "msg_delay@fleet.transport:8?kind=dispatch&count=3&ticks=4;"
        "msg_dup@fleet.transport:30?kind=commit&count=3;"
        "partition@fleet.transport:60?replica=0&ticks=10;"
        "partition@fleet.transport:150?replica=0&ticks=8"))
    bus.register("router", _router_msg)
    bus.register("r0#0", _member_msg)

    def bus_dispatch_op():
        nonlocal next_rid
        prompt = rng.integers(0, 13, (int(rng.integers(2, 12)),))
        req = Request(rid=next_rid, prompt=prompt,
                      max_new_tokens=int(rng.integers(2, 14)),
                      arrival=now)
        next_rid += 1
        submitted.append(req)
        wire["dispatched"] += 1
        bus.send("dispatch", "router", "r0#0", req, tick=bus_tick[0],
                 key=(req.rid, "d", 0), reliable=True)

    def bus_harvest_op():
        done = [r for r in submitted if r.terminal]
        if not done:
            return
        r = done[int(rng.integers(len(done)))]
        wire["harvests"] += 1
        bus.send("commit", "r0#0", "router",
                 {"rid": r.rid, "outlen": len(r.out)},
                 tick=bus_tick[0], key=(r.rid, "c", 0, len(r.out)),
                 reliable=True)

    def bus_step():
        bus_tick[0] += 1
        bus.apply_tick_faults(bus_tick[0])
        bus.pump(bus_tick[0])

    ops = [submit_one, lambda: sched.admit(now), prefill_step,
           decode_step_op, preempt_op, cancel_op,
           lambda: sched.sweep(now), reclaim_op, handoff_op,
           lambda: decode_step_op(sched_b, engine_b),
           lambda: sched_b.admit(now),
           lambda: prefill_step(sched_b, engine_b),
           spec_decode_op,
           lambda: spec_decode_op(sched_b, engine_b),
           corrupt_op,
           join_op, member_dispatch_op, member_step_op, drain_op,
           bus_dispatch_op, bus_harvest_op]
    weights = np.array([0.16, 0.14, 0.15, 0.06, 0.06, 0.04, 0.04, 0.04,
                        0.09, 0.04, 0.03, 0.03, 0.06, 0.04, 0.02,
                        0.02, 0.04, 0.05, 0.02,
                        0.05, 0.04])
    weights = weights / weights.sum()
    for _ in range(340):
        now += float(rng.uniform(0.0, 0.02))  # deadlines really expire
        bus_step()
        ops[int(rng.choice(len(ops), p=weights))]()
        check_both()
    # Drain every scheduler AND the wire: the surviving work must
    # complete and hand every page of every pool back — including the
    # autoscaler-joined members', draining or not — and every delayed
    # or unacked bus message must deliver or drop (a bus-dispatched
    # request still on the wire is in no scheduler yet).
    while (sched.unfinished or sched_b.unfinished
           or any(m["sched"].unfinished for m in members)
           or bus.busy()):
        bus_step()
        for sc, en in ((sched, engine), (sched_b, engine_b),
                       *((m["sched"], m["engine"]) for m in members)):
            sc.sweep(now)
            sc.admit(now)
            prefill_step(sc, en)
            decode_step_op(sc, en)
        check_both()
        now += 0.01
    assert all(r.terminal for r in submitted)
    prefix.clear()   # retained LRU pages hand back at teardown
    sched_b.prefix.clear()
    for m in members:
        m["sched"].prefix.clear()
    check_both()
    assert pool.free_pages == pool.usable
    assert pool_b.free_pages == pool_b.usable
    for m in members:
        assert m["pool"].free_pages == m["pool"].usable
    # The randomized walk must have exercised the interesting paths —
    # including the whole ISSUE 9 surface.
    assert sched.preemptions > 0
    statuses = {r.status for r in submitted}
    assert "finished" in statuses
    assert statuses & {"expired", "cancelled"}
    assert prefix.stats["hits"] > 0
    assert prefix.stats["cow_copies"] > 0
    assert prefix.stats["inserts"] > 0
    assert prefix.stats["evictions"] > 0
    # The cross-pool surface (ISSUE 13): both the adopt and the revoke
    # legs of the transfer protocol ran.
    assert transfers["done"] > 0
    assert transfers["revoked"] > 0
    # The speculative surface (ISSUE 14): rounds ran, at least one
    # committed more than one token, and at least one rollback handed
    # rejected-draft pages back through the ownership check.
    assert spec_seen["rounds"] > 0
    assert spec_seen["multi"] > 0
    assert spec_seen["rollbacks"] > 0
    # The autoscaler-membership surface (ISSUE 19): a member joined
    # mid-walk, took dispatched work, and gracefully drained.
    assert scale["joins"] > 0
    assert scale["dispatches"] > 0
    assert scale["drains"] > 0
    # The host-tier surface (ISSUE 17): pages spilled under pressure,
    # readmitted through fresh allocations on later template walks, and
    # at least one corrupt seal refused by the CRC discipline.
    assert tier.stats["spills"] > 0
    assert tier.stats["readmits"] > 0
    assert tier.stats["refusals"] > 0
    # The lossy-transport surface (ISSUE 20): dispatches crossed the
    # wire and every one arrived exactly once (delayed copies and
    # partition retransmissions included); the duplicated harvest
    # report was collapsed by receiver dedup; both partition windows
    # opened and healed; conservation holds at quiesce.
    f = bus.record_fields()
    assert (f["sent"] == f["delivered"] + f["deduped"] + f["dropped"]
            + f["inflight"])
    assert wire["dispatched"] > 0
    assert len(wire_rids) == wire["dispatched"]
    assert wire["harvests"] > 0
    assert bus.counters["delayed"] > 0
    assert bus.counters["duped"] > 0
    assert bus.counters["deduped"] > 0
    assert bus.counters["retransmits"] > 0
    assert bus.counters["partitions"] == 2
    assert not bus.partitions and not bus.busy()


def test_engine_preemption_recovers_and_completes():
    """A pool far smaller than the workload's worst case forces
    preemptions; recompute must still finish every request with its
    full greedy budget, and the engine's end-of-run invariants (no lost
    requests, zero leaked pages) must hold."""
    params = MODEL.init(jax.random.key(1))
    rng = np.random.default_rng(5)
    engine = PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                         prefill_chunk=8, max_len=40)
    reqs = [Request(rid=i, prompt=rng.integers(0, 13, (6,)),
                    max_new_tokens=18) for i in range(5)]
    res = engine.run(reqs, mode="continuous")
    assert res.preemptions > 0
    assert sorted(r.rid for r in res.requests) == list(range(5))
    assert all(len(r.out) == 18 for r in res.requests)


def test_continuous_batching_beats_static_on_mixed_lengths():
    """THE tentpole property, deterministically on CPU: with mixed
    output lengths, iteration-level continuous batching finishes the
    workload in FEWER decode ticks than static batching (vacated slots
    readmit mid-flight instead of idling until the batch drains) — and
    greedy token streams are identical per request across modes."""
    params = MODEL.init(jax.random.key(0))
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 13, (4,)).astype(np.int32) for _ in range(8)]
    lens = [3, 24, 3, 24, 3, 24, 3, 24]   # short/long mix: static pays
    #                                       the long tail in every batch
    engine = PagedEngine(MODEL, params, slots=2, num_pages=33, page_size=4,
                         prefill_chunk=8, max_len=32)

    def workload():
        return [Request(rid=i, prompt=p, max_new_tokens=n)
                for i, (p, n) in enumerate(zip(prompts, lens))]

    static = engine.run(workload(), mode="static")
    cont = engine.run(workload(), mode="continuous")
    assert static.output_tokens == cont.output_tokens == sum(lens)
    assert cont.decode_ticks < static.decode_ticks
    by_rid = {r.rid: r.out for r in static.requests}
    for r in cont.requests:
        assert r.out == by_rid[r.rid], f"request {r.rid} diverged"


def test_request_records_schema_validate_and_report():
    """Per-request engine records round-trip the obs schema (strict
    validation) and surface in `mctpu report`'s serving tables."""
    from mpi_cuda_cnn_tpu.obs.report import summarize
    from mpi_cuda_cnn_tpu.obs.schema import make_record, validate_record

    params = MODEL.init(jax.random.key(0))
    engine = PagedEngine(MODEL, params, slots=2, num_pages=13, page_size=8)
    reqs = [Request(rid=i, prompt=np.arange(4) % 13, max_new_tokens=5)
            for i in range(3)]
    res = engine.run(reqs, mode="continuous")
    records = [validate_record(make_record("request", 0.1, **rec))
               for rec in res.request_records()]
    records.append(validate_record(
        make_record("serve", 0.2, **res.summary())
    ))
    s = summarize(records)
    assert s["requests"][0]["mode"] == "continuous"
    assert s["requests"][0]["requests"] == 3
    assert s["requests"][0]["output_tokens"] == 15
    assert s["serve"][0]["decode_ticks"] == res.decode_ticks
    assert s["serve"][0]["tokens_per_s"] > 0


def test_serve_bench_cli_runs_and_emits_valid_jsonl(tmp_path):
    """The `mctpu serve-bench` surface end-to-end: both modes run, the
    comparison line prints, and the JSONL sink strict-validates."""
    import json

    from mpi_cuda_cnn_tpu.serve.bench import serve_bench_main
    from mpi_cuda_cnn_tpu.obs.schema import load_records

    sink = tmp_path / "serve.jsonl"
    rc = serve_bench_main([
        "--requests", "6", "--dim", "32", "--depth", "1", "--heads", "2",
        "--vocab", "64", "--max-seq", "128", "--prompt-min", "4",
        "--prompt-max", "12", "--out-min", "4", "--out-max", "12",
        "--slots", "2", "--page-size", "8", "--prefill-chunk", "8",
        "--metrics-jsonl", str(sink),
    ])
    assert rc == 0
    recs = load_records(sink, strict=True)
    assert sum(r["event"] == "request" for r in recs) == 12  # 6 x 2 modes
    assert sum(r["event"] == "serve" for r in recs) == 2
    modes = {json.dumps(sorted(r["mode"] for r in recs
                               if r["event"] == "serve"))}
    assert modes == {json.dumps(["continuous", "static"])}


def test_paged_decode_block_rejects_out_of_range_positions():
    """Concrete positions past the block-table extent must raise like
    the contiguous path — past the table the gathered page index would
    clamp to the last column and silently scatter over the sequence's
    final legitimate cache rows."""
    from mpi_cuda_cnn_tpu.models.generate import decode_block

    params = MODEL.init(jax.random.key(0))
    pc = _identity_paged_cache(MODEL, 1, page_size=8)  # covers max_seq=48
    toks = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    with pytest.raises(ValueError, match="out of range"):
        decode_block(MODEL, params, toks, MODEL.max_seq - 2, pc)
    with pytest.raises(ValueError, match="out of range"):
        decode_block(MODEL, params, toks,
                     np.asarray([MODEL.max_seq - 1]), pc)


def test_scheduler_and_engine_rejections():
    params = MODEL.init(jax.random.key(0))
    with pytest.raises(ValueError, match="max_len"):
        sched = ContinuousScheduler(slots=1, pool=PagePool(4), page_size=4,
                                    max_len=16)
        sched.submit([Request(rid=0, prompt=np.zeros(10, np.int32),
                              max_new_tokens=10)])
    with pytest.raises(ValueError, match="empty prompt"):
        Request(rid=0, prompt=np.zeros(0, np.int32), max_new_tokens=1)
    with pytest.raises(ValueError, match="num_pages"):
        PagePool(1)
    # A prompt alone needing more pages than the pool owns could only
    # ever preempt-loop: rejected AT SUBMISSION with a clear error
    # (ISSUE 4 satellite), not discovered as an idle-engine stall.
    engine = PagedEngine(MODEL, params, slots=1, num_pages=2, page_size=4,
                         max_len=16)
    with pytest.raises(ValueError, match="never be admitted"):
        engine.run([Request(rid=0, prompt=np.zeros(8, np.int32),
                            max_new_tokens=4)], mode="continuous")
