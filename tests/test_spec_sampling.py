"""Rejection-sampling speculative decoding (temperature > 0).

The T=0 speculative path's contract is bitwise: output == the target's
greedy continuation (tests/test_generate.py). At temperature > 0 the
contract is DISTRIBUTIONAL: accept draft token x with probability
min(1, p(x)/q(x)), replace a rejected proposal with a sample from the
residual norm(max(p - q, 0)) — the emitted token is then distributed
exactly as p for ANY proposal distribution q (the standard speculative
sampling theorem; the draft changes the speed, never the law).

Reference analog: none — the reference (cnn.c) has no generation at
all; this completes the beyond-parity serving axis the framework chose
(VERDICT round 4, item 3).

Two layers of evidence here:
  1. the acceptance core `_spec_sample_rows` against ANALYTIC
     distributions (sharp: TV < 0.05 at N=4096 on an 8-token vocab);
  2. the end-to-end generators' per-position marginals against the
     target model's own analytic distribution, with an adversarial
     (random-weight) draft so the residual path carries real mass.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.generate import (
    _spec_sample_rows,
    generate,
    lookup_speculative_generate,
    prefill,
    speculative_generate,
)
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM

SMALL = TransformerLM(vocab=8, dim=16, heads=2, depth=1, max_seq=32)


def _tv(p, q):
    return 0.5 * float(np.abs(np.asarray(p) - np.asarray(q)).sum())


def _hist(tokens, vocab):
    return np.bincount(np.asarray(tokens).ravel(), minlength=vocab) / len(tokens)


# ---------------------------------------------------------------------------
# 1. The acceptance core, against analytic distributions


def test_reject_core_emits_exactly_target_distribution():
    """prop ~ q, then accept/residual via _spec_sample_rows: the emitted
    row-0 token must be distributed exactly as the (temperature-scaled)
    target row — the speculative sampling theorem, verified empirically
    at TV < 0.05 where sampling noise alone is ~0.015."""
    rng = np.random.default_rng(0)
    v, temp = 8, 0.8
    tl = jnp.asarray(rng.normal(size=(1, 2, v)) * 1.5, jnp.float32)
    q = jnp.asarray(jax.nn.softmax(jnp.asarray(rng.normal(size=v) * 2.0)))
    p_want = np.asarray(jax.nn.softmax(tl[0, 0] / temp))

    def one(key):
        kp, kc = jax.random.split(key)
        prop = jax.random.categorical(kp, jnp.log(q)).astype(jnp.int32)
        u = jnp.stack([jnp.int32(0), prop])[None, :]
        y, accept = _spec_sample_rows(tl, q[None, :], u, kc, temp, 0, 0.0)
        return y[0, 0], accept[0]

    n = 4096
    toks, accepts = jax.vmap(one)(jax.random.split(jax.random.key(42), n))
    assert _tv(_hist(toks, v), p_want) < 0.05
    # The draft is far from the target here — both branches must carry
    # real mass or the test proves nothing about the residual path.
    acc_rate = float(jnp.mean(accepts.astype(jnp.float32)))
    assert 0.05 < acc_rate < 0.95


def test_reject_core_respects_target_filters():
    """With top_k on the TARGET, emitted tokens must follow the
    filtered-renormalized target distribution — including proposals the
    filter forbids (p=0 ⇒ always rejected, never emitted)."""
    rng = np.random.default_rng(1)
    v, temp, top_k = 8, 1.0, 3
    tl = jnp.asarray(rng.normal(size=(1, 2, v)) * 1.5, jnp.float32)
    q = jnp.full((v,), 1.0 / v)  # uniform draft: proposes forbidden tokens
    scaled = np.asarray(tl[0, 0] / temp)
    keep = scaled >= np.sort(scaled)[-top_k]
    p_want = np.exp(scaled) * keep
    p_want /= p_want.sum()

    def one(key):
        kp, kc = jax.random.split(key)
        prop = jax.random.categorical(kp, jnp.log(q)).astype(jnp.int32)
        u = jnp.stack([jnp.int32(0), prop])[None, :]
        y, _ = _spec_sample_rows(tl, q[None, :], u, kc, temp, top_k, 0.0)
        return y[0, 0]

    toks = jax.vmap(one)(jax.random.split(jax.random.key(7), 4096))
    got = _hist(toks, v)
    assert _tv(got, p_want) < 0.05
    assert got[~keep].sum() == 0.0  # filtered tokens never emitted


def test_reject_core_delta_proposal_is_lookup_semantics():
    """A one-hot q (the prompt-lookup case): accept w.p. p(prop), and the
    residual is p with the proposal zeroed — still exactly p overall."""
    rng = np.random.default_rng(2)
    v, temp, prop_tok = 8, 0.7, 3
    tl = jnp.asarray(rng.normal(size=(1, 2, v)), jnp.float32)
    q = jax.nn.one_hot(prop_tok, v)
    p_want = np.asarray(jax.nn.softmax(tl[0, 0] / temp))

    def one(key):
        u = jnp.asarray([[0, prop_tok]], jnp.int32)
        y, accept = _spec_sample_rows(tl, q[None, :], u, key, temp, 0, 0.0)
        return y[0, 0], accept[0]

    toks, accepts = jax.vmap(one)(jax.random.split(jax.random.key(3), 4096))
    assert _tv(_hist(toks, v), p_want) < 0.05
    # Acceptance of a delta proposal IS p(prop): check it directly.
    assert abs(float(jnp.mean(accepts.astype(jnp.float32)))
               - p_want[prop_tok]) < 0.04


# ---------------------------------------------------------------------------
# 2. End-to-end generators: per-position marginals vs the analytic law


def _analytic_marginals(model, params, prompt, temperature):
    """Exact p(token0) and p(token1) of plain temperature sampling: the
    first from the prefill logits, the second by enumerating token0."""
    logits, _ = prefill(model, params, prompt)
    p0 = np.asarray(jax.nn.softmax(logits[0] / temperature))
    p1 = np.zeros(model.vocab)
    for a in range(model.vocab):
        ext = jnp.concatenate(
            [prompt, jnp.asarray([[a]], jnp.int32)], axis=1
        )
        la = model.apply(params, ext)[0, -1].astype(jnp.float32)
        p1 += p0[a] * np.asarray(jax.nn.softmax(la / temperature))
    return p0, p1


@pytest.mark.parametrize("path", ["draft", "lookup"])
def test_spec_sampling_marginals_match_plain(path):
    """speculative sampling at T=0.8 with an ADVERSARIAL draft (random
    weights / no useful lookup matches → heavy residual traffic): the
    marginal distribution of each emitted position must match plain
    temperature sampling's analytic law. N=400 seeds on an 8-vocab ⇒
    sampling noise TV ≈ 0.056; bound 0.15 catches any systematic skew
    toward the draft (an always-accept bug reads TV > 0.4 here)."""
    params = SMALL.init(jax.random.key(0))
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    temp, n = 0.8, 400
    p0_want, p1_want = _analytic_marginals(SMALL, params, prompt, temp)

    draft = TransformerLM(vocab=8, dim=16, heads=2, depth=1, max_seq=32)
    draft_params = draft.init(jax.random.key(99))

    t0, t1 = [], []
    for seed in range(n):
        key = jax.random.key(seed)
        if path == "draft":
            toks = speculative_generate(
                SMALL, params, draft, draft_params, prompt, 3, k=3,
                temperature=temp, key=key,
            )
        else:
            toks = lookup_speculative_generate(
                SMALL, params, prompt, 3, k=3, ngram=2,
                temperature=temp, key=key,
            )
        t0.append(int(toks[0, 0]))
        t1.append(int(toks[0, 1]))
    assert _tv(_hist(jnp.asarray(t0), 8), p0_want) < 0.15
    assert _tv(_hist(jnp.asarray(t1), 8), p1_want) < 0.15


def test_spec_sampling_t0_exactness_preserved():
    """temperature=0 through the NEW argument surface still produces the
    bitwise greedy continuation (key present but ignored)."""
    params = SMALL.init(jax.random.key(0))
    draft_params = SMALL.init(jax.random.key(9))
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    want = np.asarray(generate(SMALL, params, prompt, 8))
    got = speculative_generate(
        SMALL, params, SMALL, draft_params, prompt, 8, k=3,
        temperature=0.0, key=jax.random.key(5),
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    got = lookup_speculative_generate(
        SMALL, params, prompt, 8, k=3, temperature=0.0,
        key=jax.random.key(5),
    )
    np.testing.assert_array_equal(np.asarray(got), want)


def test_spec_sampling_deterministic_per_key_and_validation():
    params = SMALL.init(jax.random.key(0))
    draft_params = SMALL.init(jax.random.key(9))
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)

    a = speculative_generate(SMALL, params, SMALL, draft_params, prompt,
                             6, k=2, temperature=1.0,
                             key=jax.random.key(1))
    b = speculative_generate(SMALL, params, SMALL, draft_params, prompt,
                             6, k=2, temperature=1.0,
                             key=jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with pytest.raises(ValueError, match="PRNG"):
        speculative_generate(SMALL, params, SMALL, draft_params, prompt,
                             4, temperature=0.5)
    with pytest.raises(ValueError, match="PRNG"):
        lookup_speculative_generate(SMALL, params, prompt, 4,
                                    temperature=0.5)
    with pytest.raises(ValueError, match="temperature"):
        speculative_generate(SMALL, params, SMALL, draft_params, prompt,
                             4, top_k=3)


def test_spec_sampling_stats_capped_at_num_tokens():
    """mean_accepted must count only tokens that land in the returned
    buffer: a perfect draft at k > num_tokens cannot report more
    accepted tokens than were emitted (ADVICE round-4 finding 1)."""
    params = SMALL.init(jax.random.key(0))
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    _, stats = speculative_generate(
        SMALL, params, SMALL, params, prompt, 3, k=6, return_stats=True
    )
    assert stats["mean_accepted"] <= 3.0


def test_trainer_speculative_sampling_reachable():
    """The product surface: LMTrainer.sample with --sample-speculative-k
    AND --sample-temperature > 0 (+ top-k) runs the rejection-sampling
    lookup path and returns valid tokens; a too-short prompt fails with
    the trainer's vocabulary (ADVICE round-4 finding 2)."""
    import pytest

    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    cfg = LMConfig(corpus="synthetic", dim=32, depth=1, heads=2,
                   seq_len=64, steps=2, batch_size=8, log_every=0,
                   lr_schedule="constant", warmup_steps=0,
                   sample_speculative_k=4, sample_temperature=0.8,
                   sample_top_k=6)
    t = LMTrainer(cfg, metrics=MetricsLogger(echo=False))
    t.train()
    # The CLI passes temperature=cfg.sample_temperature (cli.py).
    _, cont = t.sample(8, temperature=cfg.sample_temperature, seed=3)
    assert len(cont) == 8
    assert all(0 <= int(c) < t.model.vocab for c in cont)
    with pytest.raises(ValueError, match="prompt"):
        t.sample(8, prompt_len=1, temperature=cfg.sample_temperature)


def test_reject_core_respects_target_top_p():
    """Nucleus (top_p) on the TARGET: emitted tokens follow the
    smallest-prefix-reaching-mass-p renormalized law — the top_p twin
    of the top_k filter test (the two restrict differently: mass vs
    count)."""
    rng = np.random.default_rng(5)
    v, temp, top_p = 8, 1.0, 0.6
    tl = jnp.asarray(rng.normal(size=(1, 2, v)) * 1.5, jnp.float32)
    q = jnp.full((v,), 1.0 / v)  # uniform draft proposes cut tokens too
    p_full = np.asarray(jax.nn.softmax(tl[0, 0] / temp))
    order = np.argsort(-p_full)
    cum_before = np.cumsum(p_full[order]) - p_full[order]
    keep = np.zeros(v, bool)
    keep[order[cum_before < top_p]] = True  # boundary token stays
    p_want = p_full * keep
    p_want /= p_want.sum()

    def one(key):
        kp, kc = jax.random.split(key)
        prop = jax.random.categorical(kp, jnp.log(q)).astype(jnp.int32)
        u = jnp.stack([jnp.int32(0), prop])[None, :]
        y, _ = _spec_sample_rows(tl, q[None, :], u, kc, temp, 0, top_p)
        return y[0, 0]

    toks = jax.vmap(one)(jax.random.split(jax.random.key(11), 4096))
    got = _hist(toks, v)
    assert _tv(got, p_want) < 0.05
    assert got[~keep].sum() == 0.0  # cut tokens never emitted


def test_reject_core_degenerate_residual_falls_back_to_p():
    """When rounding zeroes the whole residual row (sum(max(p-q,0)) == 0)
    while a rejection is still possible (p < q at the proposal), the
    guard must sample from p instead of a categorical over all -inf —
    which would deterministically emit token 0 even when p[0] == 0
    (ADVICE round 5)."""
    # p ~= [1e-30, .5, .5, 1e-30]; q doubles the proposal token's mass
    # (2e-30) and matches everywhere else — the f32 row cannot represent
    # p's compensating excess, so sum(max(p - q, 0)) == 0 exactly while
    # the accept rule (u * q < p at token 0) still rejects with
    # probability 1/2. A rejection then samples the residual row.
    logits = jnp.log(jnp.asarray([1e-30, 0.5, 0.5, 1e-30], jnp.float32))
    tl = jnp.stack([logits, logits])[None, :, :]          # (1, 2, v)
    p_row = jax.nn.softmax(logits)
    q = jnp.asarray(p_row).at[0].mul(2.0)[None, :]        # (1, v)
    assert float(jnp.sum(jnp.maximum(p_row - q[0], 0.0))) == 0.0
    u = jnp.asarray([[1, 0]], jnp.int32)                  # propose token 0
    rejected = 0
    for seed in range(16):
        y, accept = _spec_sample_rows(
            tl, q, u, jax.random.key(seed), 1.0, 0, 0.0
        )
        if bool(accept[0]):
            continue
        rejected += 1
        emitted = int(y[0, 0])
        assert float(p_row[emitted]) > 1e-6, (
            f"degenerate residual emitted a zero-probability token "
            f"{emitted}"
        )
    assert rejected > 0, "construction never rejected; test is vacuous"
