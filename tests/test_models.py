"""Model/layer tests: shapes, param counts (parity with the reference's
360,810-param net, SURVEY.md 2.10), initializer statistics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.initializers import get_initializer
from mpi_cuda_cnn_tpu.models.presets import MODEL_PRESETS, get_model


@pytest.mark.parametrize("name", sorted(MODEL_PRESETS))
def test_presets_init_and_apply(name):
    model = get_model(name)
    params = model.init(jax.random.key(0), get_initializer("normal"))
    x = jnp.zeros((2, *model.input_shape), jnp.float32)
    logits = model.apply(params, x)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_reference_cnn_param_count():
    """conv1 144+16, conv2 4608+32, fc1 313600+200, fc2 40000+200,
    out 2000+10 = 360,810 (cnn.c:416-428, SURVEY.md 2.10)."""
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    assert model.num_params(params) == 360_810


def test_reference_cnn_feature_shapes():
    """28x28 -> 14x14x16 -> 7x7x32 via k3 s2 p1 (cnn.c:417-418)."""
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    x = jnp.ones((1, 28, 28, 1))
    h1 = model.layers[0].apply(params[0], x)
    assert h1.shape == (1, 14, 14, 16)
    h2 = model.layers[1].apply(params[1], h1)
    assert h2.shape == (1, 7, 7, 32)


def test_bfloat16_compute_path():
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    x = jnp.ones((2, 28, 28, 1))
    logits = model.apply(params, x, compute_dtype=jnp.bfloat16)
    assert logits.dtype == jnp.float32  # logits always f32 for the loss
    ref = model.apply(params, x)
    np.testing.assert_allclose(logits, ref, atol=0.15)


def test_irwin_hall_init_stats():
    """nrnd (cnn.c:46-49) twin: mean ~0, std ~0.1, support within
    +-2*1.724*0.1."""
    init = get_initializer("irwin_hall", std=0.1)
    w = np.asarray(init(jax.random.key(0), (200, 200), jnp.float32))
    assert abs(w.mean()) < 5e-3
    assert abs(w.std() - 0.1) < 1e-2
    assert np.abs(w).max() <= 2 * 1.724 * 0.1 + 1e-6


def test_normal_init_std():
    init = get_initializer("normal", std=0.1)
    w = np.asarray(init(jax.random.key(0), (500, 500), jnp.float32))
    assert abs(w.std() - 0.1) < 2e-3


def test_init_deterministic_across_calls():
    """Same key -> identical params: the synchronized-init fix for
    reference bug 2.6c (divergent srand(0+rank), cnnmpi.c:423)."""
    model = get_model("lenet5")
    p1 = model.init(jax.random.key(3), get_initializer("he"))
    p2 = model.init(jax.random.key(3), get_initializer("he"))
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pooling_shapes():
    model = get_model("lenet5")
    params = model.init(jax.random.key(0), get_initializer("he"))
    x = jnp.ones((3, 28, 28, 1))
    assert model.apply(params, x).shape == (3, 10)


def test_residual_identity_vs_projection():
    """Shape-preserving blocks get an identity shortcut (no proj params);
    downsampling blocks get a 1x1 strided projection."""
    from mpi_cuda_cnn_tpu.models.layers import Conv, Residual

    same = Residual(body=(Conv(8, kernel=3, padding=1, activation="relu"),
                          Conv(8, kernel=3, padding=1, activation=None)))
    p, out = same.init(jax.random.key(0), (16, 16, 8), get_initializer("he"))
    assert out == (16, 16, 8)
    assert "proj" not in p

    down = Residual(body=(Conv(16, kernel=3, stride=2, padding=1, activation="relu"),
                          Conv(16, kernel=3, padding=1, activation=None)))
    p, out = down.init(jax.random.key(0), (16, 16, 8), get_initializer("he"))
    assert out == (8, 8, 16)
    assert p["proj"]["w"].shape == (1, 1, 8, 16)

    x = jnp.ones((2, 16, 16, 8))
    assert down.apply(p, x).shape == (2, 8, 8, 16)


def test_residual_odd_spatial_downsample():
    """Stride-2 on odd dims (7 -> 4): the projection stride is solved from
    (h-1)//s+1 == oh, not h//oh."""
    from mpi_cuda_cnn_tpu.models.layers import Conv, Residual

    blk = Residual(body=(Conv(16, kernel=3, stride=2, padding=1, activation="relu"),
                         Conv(16, kernel=3, padding=1, activation=None)))
    p, out = blk.init(jax.random.key(0), (7, 7, 8), get_initializer("he"))
    assert out == (4, 4, 16)
    x = jnp.ones((2, 7, 7, 8))
    assert blk.apply(p, x).shape == (2, 4, 4, 16)


def test_residual_gradients_flow_through_shortcut():
    """Both branches must receive gradient — the add couples them."""
    model = get_model("resnet8")
    params = model.init(jax.random.key(0), get_initializer("he"))
    x = jnp.ones((2, 32, 32, 3))

    def loss(p):
        return jnp.sum(model.apply(p, x) ** 2)

    grads = jax.grad(loss)(params)
    leaves = jax.tree.leaves(grads)
    assert len(leaves) == len(jax.tree.leaves(params))
    # every parameter (body convs AND projection shortcuts) gets signal
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    assert sum(float(jnp.abs(g).sum()) > 0 for g in leaves) == len(leaves)


def test_residual_downsample_to_1x1():
    """Stride may equal the full spatial extent (body shrinking to 1x1)."""
    from mpi_cuda_cnn_tpu.models.layers import Conv, Residual

    blk = Residual(body=(Conv(16, kernel=4, stride=4, padding=0, activation=None),))
    p, out = blk.init(jax.random.key(0), (4, 4, 8), get_initializer("he"))
    assert out == (1, 1, 16)
    assert blk.apply(p, jnp.ones((2, 4, 4, 8))).shape == (2, 1, 1, 16)


def test_residual_unprojectable_shape_rejected():
    from mpi_cuda_cnn_tpu.models.layers import Conv, Residual

    bad = Residual(body=(Conv(8, kernel=3, padding=0, activation=None),))  # 16->14
    with pytest.raises(ValueError, match="projection"):
        bad.init(jax.random.key(0), (16, 16, 8), get_initializer("he"))
