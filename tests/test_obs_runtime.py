"""Runtime observability layer (ISSUE 6): MetricsRegistry aggregation,
per-request trace timelines, `mctpu top` frames, and the perf-regression
gate — all deterministic under faults.FakeClock.

THE acceptance tests live here:
- a seeded Poisson serve-bench run's tick trail reconstructs every
  request with a status-consistent lifecycle whose per-status counts
  match the engine's own terminal totals;
- `mctpu compare` exits 0 on identical runs and 1 on an injected >=10%
  tokens/s regression;
both driven end-to-end by a FakeClock (no wall-clock in any asserted
number), plus a golden byte-for-byte round-trip of `mctpu report` and
`mctpu trace` on the checked-in sample run (regenerate with
scripts/make_obs_sample.py after deliberate schema/render changes).
"""

import json
from pathlib import Path

import numpy as np
import pytest

import jax

from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector, supervise
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_bucket_bounds,
    percentiles_from_record,
)
from mpi_cuda_cnn_tpu.obs.regress import (
    compare,
    compare_main,
    extract_metrics,
    infer_direction,
)
from mpi_cuda_cnn_tpu.obs.schema import (
    dump_records,
    load_records,
    make_record,
    validate_record,
)
from mpi_cuda_cnn_tpu.obs.timeline import reconstruct, trace_main
from mpi_cuda_cnn_tpu.obs.top import TopState, render, top_main
from mpi_cuda_cnn_tpu.serve.bench import make_workload
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger
from mpi_cuda_cnn_tpu.utils.profiling import StepTimer

REPO = Path(__file__).resolve().parents[1]
DATA = REPO / "tests" / "data"

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)


@pytest.fixture(scope="module")
def engine():
    params = MODEL.init(jax.random.key(0))
    # Pool far below the workload's worst case: preemption/requeue
    # lifecycles appear in the trail, not just the happy path.
    return PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                       prefill_chunk=8, max_len=40)


# ------------------------------------------------- metrics primitives


def test_log_bucket_bounds_pure_and_ascending():
    b = log_bucket_bounds()
    assert b == log_bucket_bounds()  # pure function of its arguments
    assert all(x < y for x, y in zip(b, b[1:]))
    assert b[0] == pytest.approx(1e-2 * 10 ** 0.1)
    with pytest.raises(ValueError):
        log_bucket_bounds(lo=0.0)


def test_counter_monotonic_and_gauge_envelope():
    c = Counter()
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = Gauge()
    g.set(5)
    g.set(1)
    g.set(3)
    assert (g.value, g.lo, g.hi) == (3.0, 1.0, 5.0)


def test_histogram_percentiles_and_roundtrip():
    h = Histogram()
    for v in [1.0, 2.0, 3.0, 4.0, 100.0]:
        h.observe(v)
    assert h.count == 5 and h.min == 1.0 and h.max == 100.0
    # Percentile estimates are clamped to the exact observed envelope.
    assert h.percentile(0) == 1.0
    assert h.percentile(100) == 100.0
    assert 1.0 <= h.percentile(50) <= 4.0
    # Record round-trip: sparse buckets reconstruct identical counts.
    h2 = Histogram.from_fields(h.to_fields())
    assert h2.counts == h.counts and h2.count == h.count
    assert [h2.percentile(q) for q in (50, 95, 99)] == \
        [pytest.approx(h.percentile(q)) for q in (50, 95, 99)]
    assert h.percentile(50) is not None
    assert Histogram().percentile(50) is None


def test_registry_snapshot_is_schema_valid_and_fakeclock_stamped():
    clock = FakeClock()
    reg = MetricsRegistry(clock=clock)
    reg.inc("serve.decode_ticks", 3)
    reg.set("serve.queue_depth", 7)
    reg.observe("serve.ttft_ms", 12.5)
    reg.observe("serve.ttft_ms", None)  # null moments are skipped
    clock.advance(2.5)
    rec = reg.snapshot(mode="continuous")
    validate_record(rec)
    assert rec["event"] == "metrics" and rec["t"] == 2.5
    assert rec["counters"]["serve.decode_ticks"] == 3
    assert rec["gauges"]["serve.queue_depth"]["value"] == 7
    assert rec["histograms"]["serve.ttft_ms"]["count"] == 1
    p = percentiles_from_record(rec, "serve.ttft_ms")
    assert p["p50"] == pytest.approx(12.5)
    assert percentiles_from_record(rec, "absent")["p99"] is None


def test_registry_aggregation_deterministic_under_fake_clock():
    """The determinism contract: aggregation math never reads the
    clock, so two registries fed the same observations — under clocks
    advanced DIFFERENTLY — produce identical aggregate fields."""
    rega = MetricsRegistry(clock=FakeClock())
    fast = FakeClock()
    regb = MetricsRegistry(clock=fast)
    for i in range(100):
        fast.advance(1.0)  # only b's clock moves during aggregation
        for reg in (rega, regb):
            reg.inc("n")
            reg.set("depth", i % 7)
            reg.observe("lat_ms", float(i) * 1.7)
    assert json.dumps(rega.snapshot_fields()) == \
        json.dumps(regb.snapshot_fields())


def test_steptimer_and_metricslogger_accept_fake_clock(tmp_path):
    clock = FakeClock()
    timer = StepTimer(clock=clock)
    timer.start()
    with timer.phase("data"):
        clock.advance(0.010)
    with timer.phase("dispatch"):
        clock.advance(0.030)
    with timer.exclude():
        clock.advance(5.0)  # AOT compile must not pollute the envelope
    clock.advance(0.010)
    timer.stop(2)
    assert timer.total_s == pytest.approx(0.050)
    assert timer.mean_step_ms == pytest.approx(25.0)
    assert timer.phases_ms() == {"data": 5.0, "dispatch": 15.0,
                                 "other": 5.0}

    path = tmp_path / "r.jsonl"
    with MetricsLogger(path, echo=False, clock=clock) as metrics:
        clock.advance(1.5)
        metrics.log("train", step=1, loss=0.5)
    (rec,) = load_records(path)
    assert rec["t"] == 1.5  # stamped by the injected clock, exactly


# ------------------------------------- FakeClock serving e2e + trace


def _clock_serve(engine, mode, *, sink=None, registry=None):
    """One seeded Poisson serve run, fully FakeClock-driven (arrival
    waits and injected slow faults advance the clock; compute is
    instantaneous in clock time)."""
    clock = FakeClock()
    reqs = make_workload(n=8, vocab=13, prompt_min=4, prompt_max=8,
                         out_min=6, out_max=18, rate=40.0, seed=5,
                         deadline_s=0.35)
    faults = FaultInjector(
        "slow@serve.tick:10?s=0.15;slow@serve.tick:20?s=0.15;"
        "slow@serve.tick:30?s=0.15", clock=clock)
    res = engine.run(reqs, mode=mode, time_fn=clock,
                     sleep_fn=clock.advance, faults=faults,
                     registry=registry, tick_sink=sink)
    return res, clock


def _run_records(engine, modes=("static", "continuous")):
    """Records of a two-mode FakeClock run in serve-bench's layout
    (tick + metrics + request + serve events), plus per-mode results."""
    records, results = [], {}
    for mode in modes:
        ticks = []
        registry = MetricsRegistry(clock=FakeClock())
        res, clock = _clock_serve(engine, mode,
                                  sink=lambda r: ticks.append(r),
                                  registry=registry)
        results[mode] = res
        records += [make_record("tick", t["now"], **t) for t in ticks]
        s = res.summary()
        registry.set("serve.tokens_per_s", s["tokens_per_s"])
        records.append(registry.snapshot(mode=mode, final=True))
        records += [make_record("request", clock.now, **r)
                    for r in res.request_records()]
        records.append(make_record("serve", clock.now, **s))
    return records, results


def test_trace_reconstructs_every_request_consistently(engine, tmp_path):
    """THE trace acceptance: lifecycles derived purely from the tick
    trail agree with the engine's own request records — same terminal
    status per request, token counts accounted, and per-status totals
    equal to the engine's returned counts. Preempt/requeue cycles and
    expired requests are exercised (constrained pool + deadlines)."""
    records, results = _run_records(engine)
    assert results["continuous"].preemptions > 0  # requeues exercised
    by_mode = reconstruct(records)
    for mode, res in results.items():
        lifecycles = by_mode[mode]
        assert len(lifecycles) == len(res.requests)
        assert all(lc.consistent for lc in lifecycles.values()), [
            (rid, lc.derived_status, lc.record.get("status"))
            for rid, lc in lifecycles.items() if not lc.consistent
        ]
        derived = {}
        for lc in lifecycles.values():
            derived[lc.derived_status] = derived.get(lc.derived_status,
                                                     0) + 1
        assert derived == res.status_counts()
        # Tick-derived token accounting matches each record exactly.
        for lc in lifecycles.values():
            assert lc.tokens_accounted == lc.record["output_tokens"]

    path = tmp_path / "run.jsonl"
    dump_records(records, path)
    assert trace_main([str(path)]) == 0
    assert trace_main([str(path), "--request", "2", "--mode",
                       "continuous"]) == 0
    assert trace_main([str(path), "--format", "json"]) == 0


def test_trace_flags_engine_telemetry_drift(engine, tmp_path):
    """Tampering with the trail (a dropped decode tick) must exit
    nonzero: the reconstruction is a cross-check, not a rendering."""
    records, _ = _run_records(engine, modes=("continuous",))
    tampered = []
    dropped = False
    for r in records:
        if not dropped and r["event"] == "tick" and r.get("decoded"):
            r = {**r, "decoded": r["decoded"][1:]}
            dropped = True
        tampered.append(r)
    assert dropped
    path = tmp_path / "bad.jsonl"
    dump_records(tampered, path)
    assert trace_main([str(path)]) == 1


def test_tick_records_stream_and_are_never_retained(engine):
    """Tick records flow to the sink as they happen (the JSONL is the
    tick store); ServeResult retains none — an in-memory tick list
    would grow without bound on a long-lived serve. A bare run (no
    registry, no sink) skips building them entirely."""
    ticks = []
    res, _ = _clock_serve(engine, "continuous", sink=ticks.append)
    assert ticks and "ticks" not in vars(res)
    res2, _ = _clock_serve(engine, "continuous")  # bare run still lands
    assert res2.status_counts() == res.status_counts()


def test_gantt_marks_queue_and_preempt_waits_for_focused_request():
    """The --request legend: queue time before first admission renders
    'q', preempted-waiting before readmission renders 'x', both on the
    row of the slot the request next occupies; activity still wins
    inside a column."""
    from mpi_cuda_cnn_tpu.obs.timeline import render_gantt

    def tick(i, **kw):
        return {"event": "tick", "tick": i, "now": round(0.1 * i, 4),
                "mode": "continuous", "queue": 0, "free_pages": 9, **kw}

    records = [
        make_record("request", 1.0, id=7, mode="continuous",
                    status="finished", prompt_tokens=4, output_tokens=2,
                    ttft_ms=1.0, latency_ms=2.0, arrival_s=0.0,
                    queue_wait_ms=100.0, preemptions=1),
        tick(0),                                     # queued (arrival 0)
        tick(1, admitted=[[0, 7]], prefill=[0, 7, 4]),
        tick(2, preempted=[7]),                      # requeued, waiting
        tick(3),
        tick(4, admitted=[[0, 7]], prefill=[0, 7, 4]),
        tick(5, decoded=[[0, 7]], finished=[7]),
    ]
    g = render_gantt(records, "continuous", rid=7)
    assert g.splitlines()[-1] == "slot  0 |qPxxPD"


def test_serve_registry_deterministic_across_runs(engine):
    """Two FakeClock runs of the identical workload produce bitwise-
    identical registry snapshots — the property the regression gate
    stands on (identical runs MUST compare clean)."""
    snaps = []
    for _ in range(2):
        registry = MetricsRegistry(clock=FakeClock())
        _clock_serve(engine, "continuous", registry=registry)
        snaps.append(json.dumps(registry.snapshot_fields()))
    assert snaps[0] == snaps[1]


# --------------------------------------------- perf-regression gate


def test_compare_passes_identical_and_gates_injected_regression(
        engine, tmp_path, capsys):
    """THE gate acceptance: identical FakeClock runs exit 0; scaling
    the candidate's tokens/s down 12% (past the 10% tolerance) exits 1
    and names the regressed metric."""
    records, _ = _run_records(engine)
    base, cand = tmp_path / "base.jsonl", tmp_path / "cand.jsonl"
    dump_records(records, base)
    dump_records(records, cand)
    assert compare_main([str(base), str(cand)]) == 0

    slowed = []
    for r in records:
        if r["event"] == "serve":
            r = {**r, "tokens_per_s": round(r["tokens_per_s"] * 0.88, 2)}
        slowed.append(r)
    dump_records(slowed, cand)
    capsys.readouterr()
    assert compare_main([str(base), str(cand)]) == 1
    err = capsys.readouterr().err
    assert "REGRESSION" in err and "tokens_per_s" in err


def test_compare_gate_file_rules(engine, tmp_path):
    """--gate thresholds: only listed metrics gate, per-metric
    tolerance applies, and a listed metric missing from either side is
    itself a failure (silently vanishing metrics rot gates)."""
    records, _ = _run_records(engine, modes=("continuous",))
    base, cand = tmp_path / "base.jsonl", tmp_path / "cand.jsonl"
    dump_records(records, base)
    slowed = [
        {**r, "tokens_per_s": r["tokens_per_s"] * 0.8}
        if r["event"] == "serve" else r
        for r in records
    ]
    dump_records(slowed, cand)
    gate = tmp_path / "gate.json"
    # Tolerant gate: a 20% drop passes at tol 30.
    gate.write_text(json.dumps({"metrics": {
        "serve.continuous.tokens_per_s": {"tol_pct": 30,
                                          "direction": "higher"},
        "serve.continuous.decode_ticks": {"tol_pct": 0},
    }}))
    assert compare_main([str(base), str(cand), "--gate", str(gate)]) == 0
    # Strict gate: the same drop fails at tol 10.
    gate.write_text(json.dumps({"metrics": {
        "serve.continuous.tokens_per_s": {"tol_pct": 10,
                                          "direction": "higher"},
    }}))
    assert compare_main([str(base), str(cand), "--gate", str(gate)]) == 1
    # A gated metric absent from both sides fails loudly.
    gate.write_text(json.dumps({"metrics": {"no.such.metric": {}}}))
    assert compare_main([str(base), str(cand), "--gate", str(gate)]) == 1


def test_compare_rejects_undirectioned_gate_and_vacuous_runs(
        engine, tmp_path, capsys):
    """Two gate-rot guards: an explicitly gated metric whose direction
    is neither specified nor name-inferable is a config error (not a
    silent demotion to info), and a compare where NOTHING ends up gated
    exits nonzero instead of vacuously green."""
    with pytest.raises(ValueError, match="direction"):
        compare({"serve.continuous.requests": 12.0},
                {"serve.continuous.requests": 5.0},
                {"metrics": {"serve.continuous.requests": {"tol_pct": 0}}})
    records, _ = _run_records(engine, modes=("continuous",))
    base, cand = tmp_path / "base.jsonl", tmp_path / "cand.jsonl"
    dump_records(records, base)
    dump_records(records, cand)
    gate = tmp_path / "gate.json"
    gate.write_text(json.dumps({"metrics": {
        "serve.continuous.requests": {"tol_pct": 0}}}))
    assert compare_main([str(base), str(cand), "--gate", str(gate)]) == 2
    assert "direction" in capsys.readouterr().err
    gate.write_text(json.dumps({"metrics": {}}))  # empty gate: error
    assert compare_main([str(base), str(cand), "--gate", str(gate)]) == 2
    # No gate + no shared direction-inferable metric: nothing gated.
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(json.dumps({"metric": "thing", "value": 1.0}))
    b.write_text(json.dumps({"metric": "thing", "value": 9.0}))
    capsys.readouterr()
    assert compare_main([str(a), str(b)]) == 2
    assert "no metric was gated" in capsys.readouterr().err


def test_compare_direction_inference_and_trajectory():
    assert infer_direction("serve.continuous.tokens_per_s") == "higher"
    assert infer_direction("serve.static.ttft_p99_ms") == "lower"
    assert infer_direction("epoch.last_s") == "lower"
    assert infer_direction("train.last_step") is None
    # Directional evaluation: a big drop in a higher-is-better metric
    # regresses; the same move in an unknown-direction metric is info.
    rows, bad = compare({"a.tokens_per_s": 100.0, "b": 1.0},
                        {"a.tokens_per_s": 80.0, "b": 5.0})
    assert bad == ["a.tokens_per_s"]
    assert [r["verdict"] for r in rows] == ["REGRESS", "info"]


def test_compare_reads_banked_driver_captures():
    """The committed BENCH_r*.json driver captures are first-class
    compare inputs — the trajectory gate CI runs (last file = candidate
    vs directional best of the earlier ones). Failed captures (rc != 0,
    null value) contribute nothing rather than zeros; the committed
    trajectory passes under the committed tolerances (sized for tunnel
    noise — ci/bench_gate.json)."""
    paths = sorted(str(p) for p in REPO.glob("BENCH_r*.json"))
    assert len(paths) >= 3
    m = extract_metrics(paths[0])
    assert "mnist_epoch_wallclock" in m
    assert extract_metrics(paths[1]) == {}  # rc=124 capture: no metrics
    assert compare_main(
        paths + ["--gate", str(REPO / "ci" / "bench_gate.json")]) == 0


def test_compare_reads_stamped_bench_script_output(tmp_path):
    """bench_decode/bench_speculative-style stdout (per-config lines +
    a schema-stamped headline record) parses into gateable metrics."""
    out = tmp_path / "decode.jsonl"
    out.write_text(
        json.dumps({"bench": "lm_decode", "kv_heads": 2,
                    "decode_tokens_per_s": 900}) + "\n"
        + json.dumps(make_record(
            "bench", 12.3, metric="decode_tokens_per_s", value=1000.0,
            unit="tokens/s", config="kv2", plain_tokens_per_s=800.0,
            backend="cpu")) + "\n"
    )
    m = extract_metrics(out)
    assert m["decode_tokens_per_s"] == 1000.0
    assert m["decode_tokens_per_s.plain_tokens_per_s"] == 800.0


# ------------------------------------------------ golden round-trip


def test_sample_run_is_schema_pinned():
    """Every record of the checked-in sample validates strictly, and
    the event families it exercises are exactly the serving set — a
    schema/event-family drift fails here first, loudly."""
    records = load_records(DATA / "sample_serve_run.jsonl", strict=True)
    assert {r["event"] for r in records} == \
        {"tick", "metrics", "request", "fault", "serve", "alert", "blame"}
    # The diversity the goldens depend on: preemptions AND expiries.
    assert any(r["event"] == "tick" and r["preempted"] for r in records)
    # ISSUE 11's additions: causal tick fields (arrival announcements,
    # blocker edges, preemption beneficiaries) and a conserved `blame`
    # summary per mode.
    assert any(r["event"] == "tick" and r.get("blocked") for r in records)
    assert any(r["event"] == "tick" and r.get("preempted_for")
               for r in records)
    assert all("arrived" in r for r in records if r["event"] == "tick")
    assert all(r.get("conserved") for r in records
               if r["event"] == "blame")
    assert any(r["event"] == "request" and r.get("status") == "expired"
               for r in records)
    # ISSUE 8's additions: a tenant mix, per-tick terminal detail, and
    # a live alert trail with both staleness and burn-rate kinds.
    assert {r.get("tenant") for r in records
            if r["event"] == "request"} == {"t0", "t1"}
    assert any(r["event"] == "tick" and r.get("terminal") for r in records)
    assert {r["kind"] for r in records if r["event"] == "alert"} == \
        {"absence", "burn_rate"}


def test_golden_report_roundtrip(monkeypatch, capsys):
    """`mctpu report` output on the sample run is byte-for-byte the
    checked-in golden (regenerate via scripts/make_obs_sample.py)."""
    from mpi_cuda_cnn_tpu.obs.report import report_main

    monkeypatch.chdir(REPO)
    assert report_main(["tests/data/sample_serve_run.jsonl"]) == 0
    assert capsys.readouterr().out == \
        (DATA / "golden_serve_report.md").read_text()


def test_golden_trace_roundtrip(monkeypatch, capsys):
    monkeypatch.chdir(REPO)
    assert trace_main(["tests/data/sample_serve_run.jsonl",
                       "--width", "80"]) == 0
    assert capsys.readouterr().out == \
        (DATA / "golden_serve_trace.md").read_text()


def test_golden_health_roundtrip(monkeypatch, capsys):
    """`mctpu health` on the sample run is byte-for-byte the golden —
    and exits 1: the sample's SLO spec is violated BY DESIGN (the
    golden must show both ok and VIOLATED verdicts)."""
    from mpi_cuda_cnn_tpu.obs.health import health_main

    monkeypatch.chdir(REPO)
    assert health_main(["tests/data/sample_serve_run.jsonl",
                        "--slo", "tests/data/sample_slo.json",
                        "--verify-alerts"]) == 1
    assert capsys.readouterr().out == \
        (DATA / "golden_serve_health.md").read_text()


def test_trace_tenant_filter(monkeypatch, capsys):
    """--tenant restricts the request table to one tenant's rows."""
    monkeypatch.chdir(REPO)
    assert trace_main(["tests/data/sample_serve_run.jsonl",
                       "--tenant", "t1", "--mode", "continuous"]) == 0
    out = capsys.readouterr().out
    assert "| t1 |" in out and "| t0 |" not in out


# ------------------------------------------------------- mctpu top


def test_top_once_frame_renders_engine_and_counts(capsys):
    assert top_main([str(DATA / "sample_serve_run.jsonl"), "--once"]) == 0
    out = capsys.readouterr().out
    assert "ENGINE [continuous]" in out and "ENGINE [static]" in out
    assert "ttft" in out and "tok/s" in out
    # ALERTS panel (ISSUE 8): the sample's live alert trail renders.
    assert "ALERTS" in out and "tick-stale" in out
    assert "\x1b" not in out  # --once is pipe/CI safe: no ANSI codes


def test_top_state_ingest_and_render_train():
    state = TopState()
    reg = MetricsRegistry(clock=FakeClock())
    reg.inc("train.steps", 50)
    reg.inc("train.heartbeats")
    reg.observe("train.step_ms", 20.0)
    state.ingest(reg.snapshot())
    state.ingest(make_record("train", 1.0, step=50, loss=0.5))
    state.ingest(make_record("epoch", 2.0, epoch=0, seconds=2.0))
    frame = render(state, "live.jsonl")
    assert "TRAIN" in frame and "heartbeats 1" in frame
    assert "step ms p50/p95/p99" in frame
    assert top_main(["/nonexistent/x.jsonl", "--once"]) == 2


# ------------------------------------------- report merge + trainers


def test_report_merge_combines_segments(tmp_path, capsys):
    """--merge renders one report over many files/run segments — the
    supervisor pre/post-restart view as a single table."""
    from mpi_cuda_cnn_tpu.obs.report import report_main

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    clock = FakeClock()
    with MetricsLogger(a, echo=False, clock=clock) as m:
        m.log("train", step=1, loss=2.0)
        m.log("epoch", epoch=0, seconds=1.0)
    with MetricsLogger(b, echo=False, clock=clock) as m:
        m.log("train", step=2, loss=1.0)
        m.log("epoch", epoch=1, seconds=3.0)
    assert report_main(["--merge", "--format", "json",
                        str(a), str(b)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["segments"] == 2
    assert out["train"]["last_loss"] == 1.0  # later file's record wins
    assert out["epochs"]["count"] == 2  # epochs from BOTH segments
    assert report_main(["--merge", str(a), str(b)]) == 0


def test_report_merge_folds_registry_snapshots_across_segments(
        tmp_path, capsys):
    """Each relaunched process's registry restarts at zero, so --merge
    must SUM counters and merge histograms across segment-latest
    snapshots — last-snapshot-wins would report only the post-restart
    segment's totals (the exact supervisor view --merge exists for).
    Gauges stay last-segment-wins."""
    from mpi_cuda_cnn_tpu.obs.report import report_main

    a, b = tmp_path / "crashed.jsonl", tmp_path / "resumed.jsonl"
    for path, steps, ms, tps in ((a, 60, [5.0, 7.0], 100.0),
                                 (b, 40, [9.0], 200.0)):
        reg = MetricsRegistry(clock=FakeClock())
        reg.inc("train.steps", steps)
        for v in ms:
            reg.observe("train.step_ms", v)
        reg.set("train.tokens_per_s", tps)
        with MetricsLogger(path, echo=False, clock=FakeClock()) as m:
            # Two snapshots per segment: within a segment the newest
            # subsumes the older (cumulative registry) — only across
            # segments does folding kick in.
            reg.emit(m)
            reg.inc("train.heartbeats")
            reg.emit(m)
    assert report_main(["--merge", "--format", "json",
                        str(a), str(b)]) == 0
    got = json.loads(capsys.readouterr().out)["metrics"]["train"]
    assert got["counters"]["train.steps"] == 100  # 60 + 40, not 40
    assert got["counters"]["train.heartbeats"] == 2  # 1 per segment
    assert got["histograms"]["train.step_ms"]["count"] == 3
    assert got["histograms"]["train.step_ms"]["min"] == 5.0
    assert got["histograms"]["train.step_ms"]["max"] == 9.0
    assert got["gauges"]["train.tokens_per_s"] == 200.0  # last segment


def test_trainer_threads_registry_and_emits_metrics_events(tmp_path):
    """The CNN trainer's epoch fold: steps counter, step-time
    histogram, samples/s gauge, heartbeats — snapshotted as
    schema-valid `metrics` events in the run file."""
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config

    path = tmp_path / "run.jsonl"
    ds = synthetic_stripes(num_train=128, num_test=32)
    cfg = Config(model="reference_cnn", epochs=2, batch_size=32,
                 log_every=0, eval_every=0, num_devices=1)
    reg = MetricsRegistry(clock=FakeClock())
    with MetricsLogger(path, echo=False) as metrics:
        Trainer(get_model("reference_cnn"), ds, cfg, metrics=metrics,
                registry=reg).train()
    assert reg.counters["train.steps"].value == 2 * (128 // 32)
    assert reg.counters["train.heartbeats"].value == 2
    assert reg.histograms["train.step_ms"].count == 2
    assert reg.gauges["train.samples_per_s"].value > 0
    snaps = [r for r in load_records(path, strict=True)
             if r["event"] == "metrics"]
    assert len(snaps) == 2  # one snapshot per epoch
    assert snaps[-1]["counters"]["train.steps"] == 8


def test_supervise_counts_restarts_in_registry(tmp_path):
    reg = MetricsRegistry(clock=FakeClock())
    calls = []

    def attempt(n):
        calls.append(n)
        if n < 2:
            raise RuntimeError("boom")
        return "ok"

    with MetricsLogger(tmp_path / "s.jsonl", echo=False) as metrics:
        out = supervise(attempt, max_restarts=3, metrics=metrics,
                        registry=reg, backoff_base=0, sleep=lambda _: None)
    assert out == "ok" and calls == [0, 1, 2]
    assert reg.counters["train.restarts"].value == 2
    faults = [r for r in load_records(tmp_path / "s.jsonl")
              if r["event"] == "fault"]
    assert [f["kind"] for f in faults] == ["restart", "restart"]
