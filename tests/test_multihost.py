"""Multi-host distributed-backend test: 2 real processes, one global mesh.

The reference's multi-process story was `mpirun -np 8` on one host with
per-sample MPI_Allreduce (Makefile:44, cnnmpi.c:490) and was never tested
multi-node (SURVEY.md §4). Here two OS processes join one JAX runtime via
`jax.distributed.initialize` (parallel/distributed.py) and run the SAME DP
train step the single-host path uses, over a global 8-device CPU mesh —
the collective crosses the process boundary, and both processes must see
the identical loss.
"""

import re
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
WORKER = REPO / "scripts" / "multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_process(mode):
    coord = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", coord, "4", mode],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=REPO,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        for p in procs:  # no orphans on timeout/assert: a stalled worker
            if p.poll() is None:  # would otherwise hold the port for 300s+
                p.kill()
                p.wait()

    losses = []
    for pid, out in enumerate(outs):
        m = re.search(
            rf"MHOK pid={pid} procs=2 gdev=8 loss=([0-9.]+)", out
        )
        assert m, f"no MHOK line from pid {pid}: {out}"
        losses.append(float(m.group(1)))
    assert losses[0] == losses[1]  # one global step, one loss


def test_two_process_dp_step():
    _run_two_process("cnn")


def test_two_process_ring_sp_lm_step():
    """Ring sequence parallelism ACROSS a real OS-process boundary: the
    LM's k/v blocks ppermute through all 8 global devices split over 2
    processes (multi-host long context, GQA + rope included)."""
    _run_two_process("lm")


def test_two_process_pipeline_step():
    """GPipe with the stage boundary ON the process boundary: the 'pipe'
    axis is outermost, so stage 0 is process 0 and stage 1 is process 1 —
    forward activations and backward cotangents ppermute between OS
    processes."""
    _run_two_process("pp")


def test_two_process_4d_lm_step():
    """The LM's pipe:2,model:2,seq:2 mesh split over 2 OS processes —
    the stage handoff crosses the process boundary while the Megatron
    psums and ring-attention ppermutes run within each process (the
    real-pod layout: TP/SP on ICI, PP across hosts); both processes
    must print the identical loss."""
    _run_two_process("4d")
