"""LM pipeline parallelism (parallel/pp_lm.py): the GPipe schedule over
stacked transformer blocks must be a layout choice — exact parity with
the single-device LM step — and the blocks must really be stage-sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, make_mesh
from mpi_cuda_cnn_tpu.parallel.pp_lm import (
    make_pp_lm_state,
    make_pp_lm_train_step,
    pp_lm_microbatch,
    pp_lm_shard_batch,
    stack_blocks,
    unstack_blocks,
)
from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step


def _pieces(depth=4, batch=8, seed=2):
    model = TransformerLM(vocab=32, dim=32, heads=4, depth=depth, max_seq=64)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(rng.integers(0, 32, (batch, 33)), jnp.int32)
    return model, opt, toks[:, :-1], toks[:, 1:]


def test_stack_unstack_roundtrip():
    model, _, _, _ = _pieces()
    params = model.init(jax.random.key(0))
    packed = stack_blocks(params)
    assert packed["blocks"]["wqkv"].shape[0] == model.depth
    back = unstack_blocks(packed, model.depth)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("mesh_axes", [
    {PIPE_AXIS: 2}, {PIPE_AXIS: 2, DATA_AXIS: 2}, {PIPE_AXIS: 4},
])
def test_pp_lm_step_matches_serial(mesh_axes, eight_devices):
    """One GPipe step == one single-device step: same loss, same updated
    params (after unstacking), on pipe-only, pipe x data, and deeper-pipe
    meshes."""
    model, opt, tokens, targets = _pieces()
    n = int(np.prod(list(mesh_axes.values())))
    mesh = make_mesh(mesh_axes, devices=jax.devices()[:n])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    base = make_lm_state(model, opt, seed=0)
    want_state, want_m = serial_step(base, tokens, targets)

    params = model.init(jax.random.key(0))
    state = make_pp_lm_state(model, params, opt, mesh)
    # The blocks really live on their stage: leading dim sharded.
    n_pipe = mesh_axes[PIPE_AXIS]
    wqkv = state["params"]["blocks"]["wqkv"]
    assert wqkv.addressable_shards[0].data.shape[0] == model.depth // n_pipe

    step = make_pp_lm_train_step(model, opt, mesh, state, donate=False)
    M = n_pipe
    toks_mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, M), mesh)
    got_state, got_m = step(state, *toks_mb)

    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got_params = unstack_blocks(
        jax.device_get(got_state["params"]), model.depth
    )
    for a, b in zip(jax.tree.leaves(got_params),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_pp_lm_remat_matches_plain(eight_devices):
    model, opt, tokens, targets = _pieces()
    mesh = make_mesh({PIPE_AXIS: 2}, devices=jax.devices()[:2])
    params = model.init(jax.random.key(0))
    outs = {}
    for remat in (False, True):
        state = make_pp_lm_state(model, params, opt, mesh)
        step = make_pp_lm_train_step(model, opt, mesh, state,
                                     donate=False, remat=remat)
        mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
        new_state, m = step(state, *mb)
        outs[remat] = (float(m["loss"]), jax.device_get(new_state["params"]))
    np.testing.assert_allclose(outs[False][0], outs[True][0], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(outs[False][1]),
                    jax.tree.leaves(outs[True][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_pp_lm_rejects_bad_configs(eight_devices):
    model, opt, _, _ = _pieces(depth=3)
    mesh = make_mesh({PIPE_AXIS: 2}, devices=jax.devices()[:2])
    params = model.init(jax.random.key(0))
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_lm_state(model, params, opt, mesh)


def test_pp_lm_moe_single_microbatch_matches_serial(eight_devices):
    """MoE blocks under the pipe axis: at M=1 the per-microbatch Switch
    aux estimator equals the serial full-batch value exactly, so one
    GPipe step == one serial step (loss AND params); at M=2 the masked
    aux (bubble ticks excluded) still trains — loss decreases and stays
    finite."""
    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64,
                          moe_experts=4)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(5)
    toks = jnp.asarray(rng.integers(0, 32, (8, 33)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]
    mesh = make_mesh({PIPE_AXIS: 2}, devices=jax.devices()[:2])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, want_m = serial_step(make_lm_state(model, opt, seed=0),
                                     tokens, targets)

    params = model.init(jax.random.key(0))
    state = make_pp_lm_state(model, params, opt, mesh)
    step = make_pp_lm_train_step(model, opt, mesh, state, donate=False,
                                 num_microbatches=1)
    mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 1), mesh)
    got_state, got_m = step(state, *mb)
    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got = unstack_blocks(jax.device_get(got_state["params"]), model.depth)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    state2 = make_pp_lm_state(model, params, opt, mesh)
    step2 = make_pp_lm_train_step(model, opt, mesh, state2, donate=False)
    mb2 = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    first = None
    for _ in range(10):
        state2, m2 = step2(state2, *mb2)
        if first is None:
            first = float(m2["loss"])
    assert np.isfinite(float(m2["loss"])) and float(m2["loss"]) < first


def test_lm_trainer_pipeline_e2e(eight_devices):
    """The lm product loop trains on pipe:2,data:2 and pipe:4 meshes —
    including eval and decode, which unstack the packed blocks."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    base = dict(corpus="synthetic", dim=32, depth=4, heads=4, seq_len=64,
                steps=8, batch_size=8, log_every=0,
                lr_schedule="constant", warmup_steps=0, sample_tokens=4)
    for mesh_shape in ("pipe:2,data:2", "pipe:4"):
        t = LMTrainer(LMConfig(mesh_shape=mesh_shape, **base),
                      metrics=MetricsLogger(echo=False))
        r = t.train()
        assert r.steps_run == 8 and np.isfinite(r.eval_ppl)
        _, cont = t.sample(4)
        assert len(cont) == 4
    with pytest.raises(ValueError, match="not with --fsdp"):
        LMTrainer(LMConfig(mesh_shape="pipe:2,data:2", fsdp=True, **base),
                  metrics=MetricsLogger(echo=False))
    # Ring impls shard positions: without a 'seq' axis the pipelined
    # stages see the full sequence — they fail loudly at setup;
    # flash/oracle are routed per stage.
    with pytest.raises(ValueError, match="attn-impl"):
        LMTrainer(LMConfig(mesh_shape="pipe:2", attn_impl="ring", **base),
                  metrics=MetricsLogger(echo=False))
    # --ce-chunk composes with the pipe axis (chunked drain CE) but the
    # chunk must divide the sequence.
    with pytest.raises(ValueError, match="ce-chunk"):
        LMTrainer(LMConfig(mesh_shape="pipe:2", ce_chunk=48, **base),
                  metrics=MetricsLogger(echo=False))
    t = LMTrainer(LMConfig(mesh_shape="pipe:2,data:2", ce_chunk=16, **base),
                  metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.steps_run == 8 and np.isfinite(r.eval_ppl)


def test_pp_lm_flash_matches_oracle(eight_devices):
    """attn_impl='flash' inside the pipelined stages == the oracle: the
    stages see the UNSHARDED sequence, so the fused kernel drops in with
    no ring machinery (VERDICT r3 item 3 — the kernel the path used to
    force to oracle). S=128 = the kernel's block granularity."""
    model = TransformerLM(vocab=32, dim=64, heads=2, depth=2, max_seq=128)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(7)
    toks = jnp.asarray(rng.integers(0, 32, (4, 129)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]
    mesh = make_mesh({PIPE_AXIS: 2}, devices=jax.devices()[:2])
    params = model.init(jax.random.key(0))
    outs = {}
    for impl in ("oracle", "flash"):
        state = make_pp_lm_state(model, params, opt, mesh)
        step = make_pp_lm_train_step(model, opt, mesh, state,
                                     donate=False, attn_impl=impl)
        mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
        ns, m = step(state, *mb)
        outs[impl] = (float(m["loss"]), jax.device_get(ns["params"]))
    np.testing.assert_allclose(outs["flash"][0], outs["oracle"][0],
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(outs["flash"][1]),
                    jax.tree.leaves(outs["oracle"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_pp_lm_ce_chunk_matches_dense(eight_devices):
    """--ce-chunk under the pipe axis: the last stage's chunked drain CE
    (never materializing the (mb, S, V) logits) == the dense drain, loss
    and updated params (VERDICT r3 item 4)."""
    model, opt, tokens, targets = _pieces()
    mesh = make_mesh({PIPE_AXIS: 2, DATA_AXIS: 2}, devices=jax.devices()[:4])
    params = model.init(jax.random.key(0))
    outs = {}
    for chunk in (0, 16):
        state = make_pp_lm_state(model, params, opt, mesh)
        step = make_pp_lm_train_step(model, opt, mesh, state,
                                     donate=False, ce_chunk=chunk)
        mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
        ns, m = step(state, *mb)
        outs[chunk] = (float(m["loss"]), jax.device_get(ns["params"]))
    np.testing.assert_allclose(outs[16][0], outs[0][0], rtol=1e-5,
                               atol=1e-6)
    for a, b in zip(jax.tree.leaves(outs[16][1]),
                    jax.tree.leaves(outs[0][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("mesh_axes", [
    {PIPE_AXIS: 2, "seq": 2}, {PIPE_AXIS: 2, "seq": 2, DATA_AXIS: 2},
])
def test_sp_pp_lm_step_matches_serial(mesh_axes, eight_devices):
    """SP x PP: ring attention inside the GPipe stages (positions over
    'seq', blocks over 'pipe') == the single-device step — the ring is
    exact, so loss AND updated params match."""
    from mpi_cuda_cnn_tpu.parallel.pp_lm import (
        make_sp_pp_lm_train_step,
        sp_pp_shard_batch,
    )

    model, opt, tokens, targets = _pieces()
    n = int(np.prod(list(mesh_axes.values())))
    mesh = make_mesh(mesh_axes, devices=jax.devices()[:n])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, want_m = serial_step(make_lm_state(model, opt, seed=0),
                                     tokens, targets)

    params = model.init(jax.random.key(0))
    state = make_pp_lm_state(model, params, opt, mesh)
    step = make_sp_pp_lm_train_step(model, opt, mesh, state, donate=False)
    mb = sp_pp_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    got_state, got_m = step(state, *mb)

    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got = unstack_blocks(jax.device_get(got_state["params"]), model.depth)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_sp_pp_lm_moe_trains(eight_devices):
    """MoE riding EP x SP inside the SP x PP stages (the README claim):
    expert dispatch all_to_alls over 'seq' run inside the GPipe tick
    loop, uniformly on every tick across seq ranks. EP's per-shard
    capacity dropping makes this a different estimator than the serial
    dense dispatch (exactly as for plain EP x SP — its tests assert
    training, not parity), so the check here is the same: the loss is
    finite and decreases, and a wiring break between the EP collectives
    and the bubble-tick masking would show up as NaNs or divergence."""
    from mpi_cuda_cnn_tpu.parallel.pp_lm import (
        make_sp_pp_lm_train_step,
        sp_pp_shard_batch,
    )

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64,
                          moe_experts=2)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(11)
    toks = jnp.asarray(rng.integers(0, 32, (4, 33)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]
    mesh = make_mesh({PIPE_AXIS: 2, "seq": 2}, devices=jax.devices()[:4])

    params = model.init(jax.random.key(0))
    state = make_pp_lm_state(model, params, opt, mesh)
    step = make_sp_pp_lm_train_step(model, opt, mesh, state, donate=False)
    mb = sp_pp_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    first = None
    for _ in range(10):
        state, m = step(state, *mb)
        if first is None:
            first = float(m["loss"])
    assert np.isfinite(float(m["loss"])) and float(m["loss"]) < first


def test_lm_trainer_sp_pp_e2e(eight_devices):
    """The lm product loop trains on a pipe:2,seq:2 mesh (ring inside
    the stages) with --grad-clip and --ce-chunk, including eval/decode."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    cfg = LMConfig(corpus="synthetic", dim=32, depth=4, heads=4,
                   seq_len=64, steps=6, batch_size=4, log_every=0,
                   lr_schedule="constant", warmup_steps=0,
                   mesh_shape="pipe:2,seq:2", grad_clip=1.0, ce_chunk=16,
                   sample_tokens=4)
    t = LMTrainer(cfg, metrics=MetricsLogger(echo=False))
    assert t.attn_impl == "ring"
    r = t.train()
    assert r.steps_run == 6 and np.isfinite(r.eval_ppl)
    _, cont = t.sample(4)
    assert len(cont) == 4


def test_pp_lm_grad_clip_matches_serial(eight_devices):
    """--grad-clip under the pipelined step: the in-step cross-stage
    global-norm clip (block slices psummed over 'pipe', the repaired
    rest counted once) must equal the serial step's optax
    clip_by_global_norm — with a clip small enough to actually engage."""
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer

    model, _, tokens, targets = _pieces()
    clip = 0.05
    serial_opt = make_optimizer(0.1, grad_clip=clip)
    serial_step = make_lm_train_step(model, serial_opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, _ = serial_step(make_lm_state(model, serial_opt, seed=0),
                                tokens, targets)

    mesh = make_mesh({PIPE_AXIS: 2, DATA_AXIS: 2}, devices=jax.devices()[:4])
    plain_opt = make_optimizer(0.1)  # clip happens IN the step
    params = model.init(jax.random.key(0))
    state = make_pp_lm_state(model, params, plain_opt, mesh)
    step = make_pp_lm_train_step(model, plain_opt, mesh, state,
                                 donate=False, grad_clip=clip)
    mb = pp_lm_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    got_state, _ = step(state, *mb)
    got = unstack_blocks(jax.device_get(got_state["params"]), model.depth)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_lm_pipeline_checkpoint_resume(tmp_path, eight_devices):
    """Checkpoint/resume of the PACKED pipeline state: a run killed at
    step 5 and resumed finishes with the same step count, and the
    restored state re-places onto the pipe-sharded layout."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ck = str(tmp_path / "ck")
    base = dict(corpus="synthetic", dim=32, depth=2, heads=4, seq_len=64,
                batch_size=4, log_every=0, lr_schedule="constant",
                warmup_steps=0, mesh_shape="pipe:2,data:2")
    LMTrainer(LMConfig(steps=5, checkpoint_dir=ck, checkpoint_every=5,
                       **base), metrics=MetricsLogger(echo=False)).train()
    t = LMTrainer(LMConfig(steps=8, checkpoint_dir=ck, resume=True, **base),
                  metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.steps_run == 3  # resumed at 5, ran to 8
    wqkv = t.state["params"]["blocks"]["wqkv"]
    assert wqkv.addressable_shards[0].data.shape[0] == 1  # still sharded
