"""TP x PP and FSDP x TP composition (VERDICT round-1 item 6).

TP x PP: Megatron-style feature slicing inside each pipeline stage
(parallel/pp.py n_model > 1) — one train step on a ('pipe','model'[,
'data']) mesh must match the serial loss AND the serial parameter update
exactly; the pipelined eval forward must match the plain apply.

FSDP x TP: combined GSPMD specs (features over 'model', largest free dim
over 'data'; parallel/fsdp.py base_specs) through the Trainer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.models.initializers import get_initializer
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
from mpi_cuda_cnn_tpu.parallel.pp import (
    make_pipeline_plan,
    make_pp_forward,
    make_pp_state,
    make_pp_train_step,
    microbatch,
    pack_params,
    pp_shard_batch,
    unpack_params,
)
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
from mpi_cuda_cnn_tpu.train.trainer import Trainer, make_loss_fn
from mpi_cuda_cnn_tpu.utils.config import Config
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _serial_step(model, params, opt, x, y):
    loss_fn = make_loss_fn(model)
    (l, _), g = jax.value_and_grad(loss_fn, has_aux=True)(params, x, y)
    upd, _ = opt.update(g, opt.init(params), params)
    return float(l), optax.apply_updates(params, upd)


@pytest.mark.parametrize("mesh_axes,n_model", [
    ({"pipe": 2, "model": 2, "data": 2}, 2),
    ({"pipe": 2, "model": 4}, 4),
])
def test_tp_pp_step_matches_serial(mesh_axes, n_model, rng):
    model = get_model("lenet5_relu")
    params = model.init(jax.random.key(0), get_initializer("he"))
    opt = make_optimizer(0.05)
    x = jnp.asarray(rng.standard_normal((16, 28, 28, 1)), jnp.float32)
    y = jax.nn.one_hot(jnp.asarray(rng.integers(0, 10, 16)), 10)
    serial_loss, serial_params = _serial_step(model, params, opt, x, y)

    mesh = make_mesh(mesh_axes)
    plan = make_pipeline_plan(model, 2, n_model=n_model)
    state = make_pp_state(plan, params, opt, mesh)
    step = make_pp_train_step(plan, opt, mesh, state, donate=False)
    batch = pp_shard_batch(microbatch(x, y, 4), mesh)
    state2, m = step(state, *batch)

    assert float(m["loss"]) == pytest.approx(serial_loss, abs=1e-5)
    got = unpack_params(plan, jax.device_get(state2["flat_params"]))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        got, serial_params,
    )


def test_tp_pp_replicated_upstream_layers_match_serial(rng):
    """resnet8's Residual blocks are parameterized REPLICATED layers that
    sit UPSTREAM of sliced Conv layers — the case where each model rank's
    cotangent is only its slice's partial contribution and the masked
    psum over 'model' (parallel/pp.py _tp_replicated_mask) is load-
    bearing; a plain rescale silently corrupts these gradients."""
    model = get_model("resnet8")
    params = model.init(jax.random.key(0), get_initializer("he"))
    opt = make_optimizer(0.05)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    y = jax.nn.one_hot(jnp.asarray(rng.integers(0, 10, 8)), 10)
    serial_loss, serial_params = _serial_step(model, params, opt, x, y)

    mesh = make_mesh({"pipe": 2, "model": 2, "data": 2})
    plan = make_pipeline_plan(model, 2, n_model=2)
    state = make_pp_state(plan, params, opt, mesh)
    step = make_pp_train_step(plan, opt, mesh, state, donate=False)
    batch = pp_shard_batch(microbatch(x, y, 2), mesh)
    state2, m = step(state, *batch)

    assert float(m["loss"]) == pytest.approx(serial_loss, abs=1e-5)
    got = unpack_params(plan, jax.device_get(state2["flat_params"]))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        got, serial_params,
    )


def test_tp_pp_pack_unpack_roundtrip(rng):
    model = get_model("lenet5_relu")
    params = model.init(jax.random.key(1), get_initializer("he"))
    plan = make_pipeline_plan(model, 2, n_model=2)
    packed = pack_params(plan, params)
    assert packed.ndim == 3 and packed.shape[:2] == (2, 2)
    got = unpack_params(plan, packed)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        got, params,
    )


def test_tp_pp_eval_forward_matches_apply(rng):
    model = get_model("lenet5_relu")
    params = model.init(jax.random.key(0), get_initializer("he"))
    opt = make_optimizer(0.05)
    mesh = make_mesh({"pipe": 2, "model": 2, "data": 2})
    plan = make_pipeline_plan(model, 2, n_model=2)
    state = make_pp_state(plan, params, opt, mesh)
    fwd = make_pp_forward(plan, mesh)
    xm = jnp.asarray(rng.standard_normal((4, 4, 28, 28, 1)), jnp.float32)
    logits = jax.device_get(
        fwd(state["flat_params"], pp_shard_batch(xm, mesh))
    ).reshape(16, -1)
    ref = model.apply(params, xm.reshape(16, 28, 28, 1))
    np.testing.assert_allclose(logits, np.asarray(ref), atol=1e-4)


def _dataset(n=64):
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes

    return synthetic_stripes(num_train=n, num_test=32)


def test_trainer_accepts_tp_pp_mesh():
    cfg = Config(
        dataset="synthetic", model="lenet5_relu", epochs=1, batch_size=16,
        mesh_shape="pipe:2,model:2,data:2", eval_every=1, log_every=0,
        scan=False, init="he", lr=0.05,
    )
    t = Trainer(get_model("lenet5_relu"), _dataset(), cfg,
                metrics=MetricsLogger(echo=False))
    res = t.train()
    assert res.epochs_run == 1 and res.ntests == 32


def test_trainer_fsdp_tp_matches_pure_dp():
    """FSDP x TP (data:4,model:2 with --fsdp) must train to the same loss
    as plain single-device SGD — same seed, same batch order."""
    results = {}
    for mesh_shape, fsdp, ndev in (("data", False, 1), ("data:4,model:2", True, 0)):
        cfg = Config(
            dataset="synthetic", model="lenet5_relu", epochs=2,
            batch_size=16, mesh_shape=mesh_shape, fsdp=fsdp,
            num_devices=ndev, eval_every=0, log_every=0, init="he",
            lr=0.05, seed=3,
        )
        t = Trainer(get_model("lenet5_relu"), _dataset(), cfg,
                    metrics=MetricsLogger(echo=False))
        em = t.run_epoch(0)
        results[mesh_shape] = em["loss"]
    assert results["data"] == pytest.approx(results["data:4,model:2"], rel=1e-4)
