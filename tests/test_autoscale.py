"""Online goodput autoscaler (serve/autoscale.py + Fleet wiring,
ISSUE 18): the control plane must SIZE ITSELF — fold live queue
pressure, SLO burn rate, and the committed autosize frontier into
replica join/leave decisions — deterministically (two identical-seed
storms produce bitwise-equal scale-event logs) and profitably (the
autoscaled fleet attains the SLO gate while spending strictly fewer
cumulative replica-ticks than the static fleet sized for peak).

Same determinism discipline as test_fleet.py: Fleet.run mutates
Request objects, so every comparison run regenerates its workload."""

import json
from pathlib import Path

import pytest

from mpi_cuda_cnn_tpu.obs.health import health_main
from mpi_cuda_cnn_tpu.obs.replay import replay_main
from mpi_cuda_cnn_tpu.obs.slo import Objective, SLOSpec
from mpi_cuda_cnn_tpu.serve.autoscale import (
    AutoscalePolicy,
    Autoscaler,
    load_frontier,
    parse_autoscale,
)
from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main
from mpi_cuda_cnn_tpu.serve.fleet import (
    Fleet,
    SimCompute,
    make_fleet_workload,
)

VOCAB = 512


def diurnal_workload(n=1500, rate=300.0, seed=5):
    """The workload the autoscaler exists for: multi-turn session
    chains arriving on a diurnal wave — crests need capacity the
    troughs would waste."""
    return make_fleet_workload(
        n=n, vocab=VOCAB, prompt_min=8, prompt_max=48, out_min=4,
        out_max=32, rate=rate, seed=seed, sessions=50, prefix_mix=0.5,
        templates=4, turns_dist="geometric:0.5", turn_gap_s=0.02,
        diurnal_amp=0.8, diurnal_period_s=2.0)


def build_fleet(*, replicas, autoscale=None, seed=5):
    return Fleet(
        lambda name: SimCompute(vocab=VOCAB, chunk=16, salt=seed),
        replicas=replicas, slots=4, num_pages=33, page_size=8,
        max_len=96, check_every=8, policy="cache_aware", prefix=True,
        autoscale=autoscale,
    )


POLICY = parse_autoscale("min=1,max=4,high=3,low=1.5,up=3,down=50,"
                         "cooldown=0.01")


# ------------------------------------------------- the policy grammar


def test_parse_autoscale_grammar():
    assert parse_autoscale("on") == AutoscalePolicy()
    assert parse_autoscale("") == AutoscalePolicy()
    pol = parse_autoscale("min=2,max=6,high=5.5,low=0.5,up=4,down=80,"
                          "cooldown=0.2,burn=10")
    assert (pol.min_replicas, pol.max_replicas) == (2, 6)
    assert (pol.high, pol.low) == (5.5, 0.5)
    assert (pol.up_ticks, pol.down_ticks) == (4, 80)
    assert (pol.cooldown_s, pol.max_burn) == (0.2, 10.0)
    for bad in ("nope=1", "min", "min=x", "min=3,max=2", "low=5,high=2",
                "up=0", "down=0", "cooldown=-1", "min=0"):
        with pytest.raises(ValueError):
            parse_autoscale(bad)


def test_load_frontier_reads_last_sweep_and_errors(tmp_path):
    p = tmp_path / "frontier.jsonl"
    p.write_text(
        json.dumps({"event": "goodput", "kind": "frontier",
                    "best_per_chip_rps": 12.5}) + "\n"
        + json.dumps({"event": "goodput", "kind": "frontier",
                      "best_per_chip_rps": 20.0}) + "\n")
    assert load_frontier(p) == 20.0
    (tmp_path / "empty.jsonl").write_text(
        json.dumps({"event": "goodput", "kind": "row"}) + "\n")
    with pytest.raises(ValueError, match="frontier"):
        load_frontier(tmp_path / "empty.jsonl")


# --------------------------------------------- the decision mechanics


def test_hysteresis_streaks_and_cooldown():
    """Hot pressure must HOLD for up_ticks consecutive consults before
    a scale-out; a single calm tick resets the streak; an applied
    decision opens a cooldown that eats would-be decisions."""
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4, high=4.0,
                          low=1.0, up_ticks=3, down_ticks=3,
                          cooldown_s=0.5)
    a = Autoscaler(pol)
    t = 0.0

    def step(load, live=1):
        nonlocal t
        t += 0.001
        return a.step(now=t, live=live, load=load, dispatched=0)

    assert step(10.0) is None          # streak 1
    assert step(10.0) is None          # streak 2
    assert step(0.0) is None           # calm: streak resets
    assert step(10.0) is None
    assert step(10.0) is None
    assert step(10.0) == "up"          # 3 consecutive hot ticks
    for _ in range(20):                # cooldown swallows everything
        assert step(10.0) is None
    # Between the thresholds: left alone even after the cooldown.
    t += 1.0
    for _ in range(10):
        assert step(2.0, live=1) is None


def test_bounds_respected_and_down_needs_long_calm():
    pol = AutoscalePolicy(min_replicas=1, max_replicas=2, high=4.0,
                          low=1.0, up_ticks=1, down_ticks=5,
                          cooldown_s=0.0)
    a = Autoscaler(pol)
    assert a.step(now=0.001, live=2, load=100.0, dispatched=0) is None, \
        "already at max_replicas: no up"
    b = Autoscaler(pol)
    for i in range(4):
        assert b.step(now=0.001 * (i + 1), live=2, load=0.0,
                      dispatched=0) is None
    assert b.step(now=0.005, live=2, load=0.0, dispatched=0) == "down"
    c = Autoscaler(pol)
    for i in range(10):
        assert c.step(now=0.001 * (i + 1), live=1, load=0.0,
                      dispatched=0) is None, "already at min: no down"


def test_flip_reversals_back_off_exponentially():
    """Consecutive direction reversals are backoff_delay's attempt
    counter: an oscillating policy spaces its own decisions out
    instead of thrashing membership."""
    pol = AutoscalePolicy(min_replicas=1, max_replicas=8, high=4.0,
                          low=1.0, up_ticks=1, down_ticks=1,
                          cooldown_s=0.1)
    a = Autoscaler(pol)
    t, gaps, last = 0.0, [], None
    for _ in range(4):
        # Alternate hot and calm until the next decision lands.
        want = "down" if last == "up" else "up"
        load = 0.0 if want == "down" else 100.0
        live = 4
        while True:
            t += 0.01
            d = a.step(now=t, live=live, load=load, dispatched=0)
            if d is not None:
                assert d == want
                if last is not None:
                    gaps.append(t)
                last = d
                break
    deltas = [b - x for x, b in zip(gaps, gaps[1:])]
    assert all(b > x * 1.5 for x, b in zip(deltas, deltas[1:])), \
        f"cooldown must grow with each reversal, got {deltas}"


def test_burn_latch_forces_up_pressure_with_shallow_queues():
    """A tenant burning error budget past max_burn across EVERY window
    (the multiwindow AND) trips up-pressure even while the queues look
    calm — latency SLOs degrade before backlogs form."""
    spec = SLOSpec(tenants={"*": [Objective("availability", 0.9)]},
                   windows=[[2.0, 0.5]])
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4, high=100.0,
                          low=0.0, up_ticks=2, down_ticks=10,
                          cooldown_s=0.0, max_burn=2.0)
    a = Autoscaler(pol, slo_spec=spec)
    t = 0.0
    decisions = []
    for _ in range(6):
        t += 0.1
        a.observe_terminal({"tenant": "t0", "status": "expired"}, t)
        decisions.append(a.step(now=t, live=1, load=0.0, dispatched=0))
    assert "up" in decisions
    # Without the burn feed, the same consults stay quiet.
    b = Autoscaler(pol)
    t = 0.0
    for _ in range(6):
        t += 0.1
        assert b.step(now=t, live=1, load=0.0, dispatched=0) is None


def test_frontier_target_adds_up_pressure_and_gates_scale_in():
    """per_chip_rps converts the observed dispatch rate into a target:
    live below it forces up-pressure with calm queues; live above it
    is what ALLOWS calm-queue scale-in."""
    pol = AutoscalePolicy(min_replicas=1, max_replicas=8, high=100.0,
                          low=10.0, up_ticks=2, down_ticks=2,
                          cooldown_s=0.0)
    a = Autoscaler(pol, per_chip_rps=10.0, rate_window_s=1.0)
    # 100 dispatches over 1s => rate ~100 req/s => target 8.
    t, d, decisions = 0.0, 0, []
    for _ in range(10):
        t += 0.1
        d += 10
        decisions.append(a.step(now=t, live=2, load=0.0, dispatched=d))
    assert "up" in decisions, "live 2 < target 8 must scale out"
    # Calm queues (load < low) but live <= target: scale-in is gated.
    b = Autoscaler(pol, per_chip_rps=10.0, rate_window_s=1.0)
    t, d = 0.0, 0
    for _ in range(10):
        t += 0.1
        d += 10
        assert b.step(now=t, live=8, load=0.0, dispatched=d) is None


# ------------------------------------------ the fleet-level acceptance


def test_autoscaled_fleet_beats_static_peak_on_replica_ticks():
    """THE capacity claim: on the identical diurnal storm, the
    autoscaled fleet finishes every request, breathes in BOTH
    directions, and spends strictly fewer cumulative replica-ticks
    than the static fleet sized for the peak — while producing the
    same per-request outputs (capacity changes schedule, not
    tokens)."""
    auto = build_fleet(replicas=1,
                       autoscale=Autoscaler(POLICY)).run(diurnal_workload())
    static = build_fleet(replicas=4).run(diurnal_workload())
    assert auto.status_counts() == static.status_counts()
    assert set(auto.status_counts()) == {"finished"}
    assert auto.scale_ups > 0
    assert auto.scale_downs > 0
    assert auto.replica_ticks < static.replica_ticks, (
        auto.replica_ticks, static.replica_ticks)
    assert static.scale_ups == static.scale_downs == 0
    assert static.scale_crc == 0
    assert auto.outputs() == static.outputs()


def test_autoscale_bitwise_deterministic():
    """Two identical-seed autoscaled storms are bitwise equal: the
    dispatch trace, the per-tick state-digest chain, AND the
    scale-event chain (scale_crc chains every (tick, direction,
    replica) in order). The CI diurnal storm re-proves this at 4x10^4
    requests through ci/autoscale_gate.json."""
    a = build_fleet(replicas=1,
                    autoscale=Autoscaler(POLICY)).run(diurnal_workload())
    b = build_fleet(replicas=1,
                    autoscale=Autoscaler(POLICY)).run(diurnal_workload())
    assert a.scale_ups == b.scale_ups and a.scale_downs == b.scale_downs
    assert a.scale_crc == b.scale_crc
    assert a.trace_crc == b.trace_crc
    assert a.state_crc == b.state_crc
    assert a.outputs() == b.outputs()


def test_summary_stamps_scale_counters_on_every_run():
    """The gate contract: every gated counter exists (zeros) in every
    fleet run — an autoscale-off, hash-routed run still stamps all
    seven ISSUE 18 keys, so ci/fleet_gate.json holds universally."""
    res = Fleet(lambda name: SimCompute(vocab=VOCAB, chunk=16, salt=0),
                replicas=2, slots=4, num_pages=33, page_size=8,
                max_len=96).run(make_fleet_workload(
                    n=40, vocab=VOCAB, prompt_min=8, prompt_max=48,
                    out_min=4, out_max=16, rate=400.0, seed=0))
    s = res.summary()
    for key in ("route_hits", "route_misses", "route_hit_tokens",
                "scale_ups", "scale_downs", "scale_crc"):
        assert s[key] == 0, key
    assert s["replica_ticks"] > 0, \
        "a static fleet spends replica-ticks too"


# -------------------------------- CLI end-to-end: SLO gate + replay


LENIENT_SLO = {
    "tenants": {"*": {
        "availability": 0.999,
        "ttft_ms": {"target": 0.99, "threshold_ms": 120000},
        "tpot_ms": {"target": 0.99, "threshold_ms": 1000},
    }},
    "burn": {"windows_s": [[10.0, 1.0]], "max_rate": 50.0},
    "max_alerts": 0,
}


def test_cli_autoscaled_run_health_ok_and_replays_bitwise(tmp_path):
    """The full acceptance path through the CLI: a diurnal autoscaled
    cache-aware storm at --log full meets the SLO gate (`mctpu health`
    exit 0) and survives the flight recorder (`mctpu replay` exit 0 —
    every per-tick digest recomputes bitwise even though membership is
    breathing under the autoscaler, because scale decisions act only
    through the mirrored join/leave events)."""
    slo = tmp_path / "slo.json"
    slo.write_text(json.dumps(LENIENT_SLO))
    out = tmp_path / "run.jsonl"
    rc = fleet_bench_main([
        "--replicas", "1", "--requests", "800", "--rate", "300",
        "--slots", "4", "--seed", "5", "--policy", "cache_aware",
        "--prefix-cache", "--prefix-mix", "0.5", "--templates", "4",
        "--sessions", "50", "--turns-dist", "geometric:0.5",
        "--turn-gap-ms", "20", "--diurnal-amp", "0.8",
        "--diurnal-period", "2",
        "--autoscale", "min=1,max=4,high=3,low=1.5,up=3,down=50,"
        "cooldown=0.01",
        "--slo", str(slo), "--log", "full",
        "--metrics-jsonl", str(out),
    ])
    assert rc == 0
    summary = [json.loads(line) for line in out.read_text().splitlines()
               if '"event": "serve"' in line]
    assert len(summary) == 1 and summary[0]["autoscale"] is True
    assert summary[0]["scale_ups"] > 0
    assert summary[0]["route_hits"] > 0
    assert health_main([str(out), "--slo", str(slo)]) == 0
    assert replay_main([str(out)]) == 0


def test_cli_frontier_feeds_the_autoscaler(tmp_path):
    """--autoscale-frontier threads a committed autosize sweep's
    best_per_chip_rps into the policy (exit 0, autoscaled summary);
    a frontier file without the record is a loud config error."""
    frontier = tmp_path / "frontier.jsonl"
    frontier.write_text(json.dumps(
        {"event": "goodput", "kind": "frontier",
         "best_per_chip_rps": 200.0}) + "\n")
    out = tmp_path / "run.jsonl"
    rc = fleet_bench_main([
        "--replicas", "1", "--requests", "200", "--rate", "300",
        "--slots", "4", "--seed", "5",
        "--autoscale", "on", "--autoscale-frontier", str(frontier),
        "--log", "summary", "--metrics-jsonl", str(out),
    ])
    assert rc == 0
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"event": "goodput", "kind": "row"}) + "\n")
    assert fleet_bench_main([
        "--replicas", "1", "--requests", "8",
        "--autoscale", "on", "--autoscale-frontier", str(bad),
    ]) == 2
