"""Prefix-sharing KV cache + SLO-aware scheduling (ISSUE 9):
refcounted read-only pages with copy-on-write, the hash-keyed prefix
tree with LRU retention/reclaim, sharing-on-vs-off bitwise output
parity through COW/preemption/fleet failover, and the priority/quota
scheduler's tenant-protection acceptance — all deterministic on CPU."""

import jax
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.generate import pick_cache_dtype
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.serve.paged_cache import PagePool
from mpi_cuda_cnn_tpu.serve.prefix_cache import PrefixCache
from mpi_cuda_cnn_tpu.serve.scheduler import (
    ContinuousScheduler,
    Request,
    SLOPolicy,
    SLOScheduler,
    parse_tenant_priorities,
    parse_tenant_quotas,
)

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)


# ------------------------------------------------ pool refcount layer


def test_pagepool_refcount_share_adopt_free_guards():
    """The ISSUE 9 PagePool extensions: adoption transfers ownership
    and freezes the page, share/unshare are per-reader ownership-
    checked, a writable page can never be shared, and a page with live
    readers can never be freed — with check() green at every state."""
    pool = PagePool(8)
    pages = pool.try_alloc(3, "rid0")
    pool.check()
    with pytest.raises(RuntimeError, match="writable"):
        pool.share(pages[0], "rid1")     # never share a writable page
    with pytest.raises(RuntimeError, match="owned by"):
        pool.adopt(pages[0], "someone_else", "__prefix__")
    pool.adopt(pages[0], "rid0", "__prefix__", readonly=True)
    pool.share(pages[0], "rid0")
    pool.share(pages[0], "rid1")
    assert pool.refs(pages[0]) == 2
    pool.check()
    with pytest.raises(RuntimeError, match="already holds"):
        pool.share(pages[0], "rid1")     # double grant refused
    with pytest.raises(RuntimeError, match="live reader"):
        pool.free([pages[0]], "__prefix__")   # shared page is pinned
    pool.unshare(pages[0], "rid0")
    with pytest.raises(RuntimeError, match="no reference"):
        pool.unshare(pages[0], "rid0")   # double unshare refused
    pool.unshare(pages[0], "rid1")
    assert pool.refs(pages[0]) == 0
    pool.free([pages[0]], "__prefix__")  # refcount-0: reclaimable
    pool.free(pages[1:], "rid0")
    pool.check()
    assert pool.free_pages == pool.usable


def test_prefix_tree_match_insert_release_lru_reclaim():
    """The tree's whole policy surface, jax-free: insertion adopts full
    prompt pages, an exact-prefix request matches them (capped at
    context-1), release retains pages at refcount 0, and reclaim
    frees only refcount-0 LEAVES in LRU order."""
    pool = PagePool(16)
    cache = PrefixCache(pool, page_size=4)
    sched = ContinuousScheduler(slots=2, pool=pool, page_size=4,
                                max_len=32, prefix=cache)
    prompt = np.arange(10, dtype=np.int32) % 13   # 2 full pages + tail
    sched.submit([Request(rid=0, prompt=prompt, max_new_tokens=4)])
    (slot,) = sched.admit(0.0)
    assert slot.cached == 0 and cache.stats["misses"] == 1
    slot.cached = slot.target
    sched.note_prefill_complete(slot)             # adopt pages 0..1
    assert cache.stats["inserts"] == 2
    assert len(slot.refs) == 2                    # slot reads its own
    sched.check()                                 # shared pages now

    # Same-prefix request: matches both full pages, prefill = suffix.
    sched.submit([Request(rid=1, prompt=prompt.copy(), max_new_tokens=4)])
    (slot2,) = sched.admit(0.0)
    assert slot2.cached == 8 and cache.stats["hits"] == 1
    assert cache.stats["hit_tokens"] == 8
    assert slot2.pages[:2] == slot.pages[:2]      # physical sharing
    sched.check()

    # Release both: pages retained at refcount 0, NOT freed.
    for s in (slot, slot2):
        s.req.status = "finished"
        sched.finished.append(s.req)
        sched._release(s)
    sched.check()
    assert cache.shared_pages == 2
    assert cache.retained_pages() == 2
    free_before = pool.free_pages
    # Reclaim evicts the LEAF first (page of chunk 1), then its parent.
    assert cache.reclaim(1) == 1
    assert cache.shared_pages == 1
    assert cache.reclaim(5) == 1                  # only the root left
    assert pool.free_pages == free_before + 2
    sched.check()
    assert pool.free_pages == pool.usable


def test_prefix_full_match_capped_at_context_minus_one():
    """A prompt fully resident in the tree still computes its last
    token — the completing prefill chunk is where the first generated
    token comes from, so the match is capped at context-1."""
    pool = PagePool(16)
    cache = PrefixCache(pool, page_size=4)
    sched = ContinuousScheduler(slots=2, pool=pool, page_size=4,
                                max_len=32, prefix=cache)
    prompt = (np.arange(8, dtype=np.int32) * 3) % 13  # exactly 2 pages
    sched.submit([Request(rid=0, prompt=prompt, max_new_tokens=2)])
    (slot,) = sched.admit(0.0)
    slot.cached = slot.target
    sched.note_prefill_complete(slot)
    sched.submit([Request(rid=1, prompt=prompt.copy(), max_new_tokens=2)])
    (slot2,) = sched.admit(0.0)
    # 8 tokens resident, but only 7 may match: the last page comes back
    # as a COW page with 3 valid rows.
    assert slot2.cached == 7
    assert slot2.cow is not None
    sched.check()


# ------------------------------------------------ engine e2e parity


def _parity_workload(rng, tmpl, lens=(8, 6, 10, 5, 12), spacing=0.05):
    """Shared template + divergent suffixes at non-page-aligned depths:
    full-page hits, COW branches, and one unrelated prompt."""
    prompts = [
        np.concatenate([tmpl, rng.integers(0, 13, (6,)).astype(np.int32)]),
        np.concatenate([tmpl, rng.integers(0, 13, (7,)).astype(np.int32)]),
        np.concatenate([tmpl[:11], rng.integers(0, 13, (4,)).astype(np.int32)]),
        rng.integers(0, 13, (9,)).astype(np.int32),
        np.concatenate([tmpl, rng.integers(0, 13, (3,)).astype(np.int32)]),
    ]
    return [Request(rid=i, prompt=p, max_new_tokens=n, arrival=spacing * i)
            for i, (p, n) in enumerate(zip(prompts, lens))]


def test_sharing_on_off_bitwise_parity_with_cow_and_preemption():
    """THE acceptance property: with sharing on, cache-hit requests
    prefill only their suffix (strictly fewer prefill chunks on the
    same seeded workload, tick counts pinned by two identical runs)
    and every request's greedy output is BITWISE identical to the
    sharing-off run — through COW divergence and preemption both."""
    params = MODEL.init(jax.random.key(0))
    rng = np.random.default_rng(7)
    tmpl = rng.integers(0, 13, (19,)).astype(np.int32)
    # Pool far below worst case (9 usable vs 2 slots x 5-page worst
    # case), outputs long enough that decode growth collides: the run
    # preempts mid-flight.
    engine = PagedEngine(MODEL, params, slots=2, num_pages=10, page_size=8,
                         prefill_chunk=8, max_len=40)

    def run(prefix):
        return engine.run(
            _parity_workload(np.random.default_rng(7), tmpl,
                             lens=(14, 10, 16, 8, 18), spacing=0.0),
            mode="continuous", prefix=prefix)

    off, on = run(False), run(True)
    assert on.preemptions > 0, "workload must exercise preemption"
    assert on.prefix["prefix_hits"] >= 2
    assert on.prefix["prefix_cow"] >= 1
    assert on.prefill_chunks < off.prefill_chunks
    off_out = {r.rid: r.out for r in off.requests}
    for r in on.requests:
        assert r.out == off_out[r.rid], f"request {r.rid} diverged"
    # Deterministic: identical reruns pin the tick/chunk/hit counts.
    on2 = run(True)
    assert (on2.prefill_chunks, on2.decode_ticks, on2.preemptions,
            on2.prefix) == (on.prefill_chunks, on.decode_ticks,
                            on.preemptions, on.prefix)


@pytest.mark.parametrize("dtype", ["bfloat16", "int8"])
def test_sharing_on_off_identical_quantized(dtype):
    """Quantized caches share pages under the same absmax contract —
    the shared rows ARE the rows the request would have written, so
    outputs stay identical with sharing on vs off in bf16/int8 too."""
    params = MODEL.init(jax.random.key(0))
    rng = np.random.default_rng(3)
    tmpl = rng.integers(0, 13, (19,)).astype(np.int32)
    engine = PagedEngine(MODEL, params, slots=2, num_pages=17, page_size=8,
                         prefill_chunk=8, max_len=40, cache_dtype=dtype)

    def run(prefix):
        return engine.run(_parity_workload(np.random.default_rng(3), tmpl),
                          mode="continuous", prefix=prefix)

    off, on = run(False), run(True)
    assert on.prefix["prefix_hits"] >= 2
    off_out = {r.rid: r.out for r in off.requests}
    for r in on.requests:
        assert r.out == off_out[r.rid], f"request {r.rid} diverged ({dtype})"


def test_lru_reclaim_under_squeeze_frees_only_ref0_pages():
    """An injected squeeze fault drains the free list mid-run; the
    next allocation must reclaim LRU refcount-0 prefix pages
    (evictions > 0) and never a page a live slot references — outputs
    stay bitwise equal to the sharing-off run of the same workload +
    fault plan, and the per-iteration sched.check() (refcount
    conservation, no-leak, no writable-shared) held throughout.
    FakeClock end to end: the whole schedule is pinned."""
    from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector

    params = MODEL.init(jax.random.key(0))
    rng = np.random.default_rng(7)
    tmpl = rng.integers(0, 13, (19,)).astype(np.int32)
    engine = PagedEngine(MODEL, params, slots=2, num_pages=15, page_size=8,
                         prefill_chunk=8, max_len=40)
    plan = "squeeze@serve.tick:40?pages=12&ticks=40"

    def run(prefix):
        clock = FakeClock()
        return engine.run(
            _parity_workload(np.random.default_rng(7), tmpl),
            mode="continuous", prefix=prefix,
            time_fn=clock, sleep_fn=clock.advance,
            faults=FaultInjector(plan, clock=clock),
        )

    off, on = run(False), run(True)
    assert on.prefix["prefix_evictions"] > 0, "squeeze must force reclaim"
    assert on.prefix["prefix_hits"] > 0
    off_out = {r.rid: r.out for r in off.requests}
    for r in on.requests:
        assert r.out == off_out[r.rid]
    # Only refcount-0 pages were freed: every eviction went through
    # PagePool.free, which raises on any page with live readers — the
    # run completing green IS the proof, re-checked every iteration by
    # sched.check().


def test_preempted_request_rehits_its_own_inserted_prefix():
    """Recompute preemption composes with sharing: a preempted
    request's re-admission hits the prompt pages its own first prefill
    inserted, so the recompute prefills (at most) the grown suffix."""
    params = MODEL.init(jax.random.key(1))
    rng = np.random.default_rng(5)
    engine = PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                         prefill_chunk=8, max_len=40)
    reqs = [Request(rid=i, prompt=rng.integers(0, 13, (8,)),
                    max_new_tokens=18) for i in range(5)]
    res = engine.run(reqs, mode="continuous", prefix=True)
    assert res.preemptions > 0
    assert res.prefix["prefix_hits"] > 0  # re-admissions hit
    assert all(len(r.out) == 18 for r in res.requests)
    # And the tokens equal the sharing-off run's (recompute exactness).
    reqs2 = [Request(rid=i, prompt=r.prompt.copy(), max_new_tokens=18)
             for i, r in enumerate(reqs)]
    off = engine.run(reqs2, mode="continuous")
    off_out = {r.rid: r.out for r in off.requests}
    for r in res.requests:
        assert r.out == off_out[r.rid]


# ------------------------------------------------ fleet integration


def test_fleet_crash_redispatch_with_prefix_outputs_bitwise():
    """The acceptance's failover leg, engine-backed: a fleet running
    prefix sharing on every replica crashes one replica mid-run; the
    fenced re-dispatch outputs stay bitwise equal to a crash-free
    sharing-OFF fleet (shared weights, greedy) — sharing changes the
    schedule, never a token."""
    from mpi_cuda_cnn_tpu.faults import FaultInjector
    from mpi_cuda_cnn_tpu.serve.fleet import (
        EngineCompute,
        Fleet,
        make_fleet_workload,
    )

    params = MODEL.init(jax.random.key(0))

    def factory(name):
        return EngineCompute(PagedEngine(
            MODEL, params, slots=2, num_pages=25, page_size=8,
            prefill_chunk=8, max_len=36,
        ))

    def run(prefix, plan):
        fleet = Fleet(factory, replicas=2, slots=2, num_pages=25,
                      page_size=8, max_len=36, heartbeat_miss=2,
                      backoff_base=0.05, prefix=prefix,
                      faults=FaultInjector(plan) if plan else None)
        reqs = make_fleet_workload(n=12, vocab=13, prompt_min=6,
                                   prompt_max=20, out_min=3, out_max=8,
                                   rate=300.0, seed=2, prefix_mix=0.7)
        return fleet.run(reqs)

    crashed = run(True, "replica_crash@fleet.tick:8?replica=1")
    clean = run(False, None)
    assert crashed.crashes == 1 and crashed.redispatches > 0
    assert crashed.prefix["prefix_hits"] > 0
    assert crashed.outputs() == clean.outputs()


def test_fleet_summary_always_carries_prefix_metrics():
    """The fleet-gate contract: every gated metric exists in every
    fleet-bench run — sharing off stamps zeros, never missing keys."""
    from mpi_cuda_cnn_tpu.serve.fleet import (
        Fleet,
        SimCompute,
        make_fleet_workload,
    )

    fleet = Fleet(lambda name: SimCompute(vocab=32, chunk=8), replicas=2,
                  slots=2, num_pages=25, page_size=8, max_len=64)
    reqs = make_fleet_workload(n=10, vocab=32, prompt_min=4, prompt_max=16,
                               out_min=2, out_max=6, rate=200.0, seed=0)
    s = fleet.run(reqs).summary()
    for k in ("prefix_hits", "prefix_misses", "prefix_hit_tokens",
              "prefix_cow", "prefix_inserts", "prefix_evictions"):
        assert s[k] == 0


# ------------------------------------------------ SLO-aware policy


def _storm(seed, *, sched_policy, tenants=2):
    """A deliberately over-subscribed SimCompute storm: arrivals far
    outrun two small replicas, deadlines tight — FCFS expires requests
    indiscriminately across tenants."""
    from mpi_cuda_cnn_tpu.serve.fleet import (
        Fleet,
        SimCompute,
        make_fleet_workload,
    )

    fleet = Fleet(lambda name: SimCompute(vocab=64, chunk=8, salt=seed),
                  replicas=2, slots=2, num_pages=25, page_size=8,
                  max_len=96, sched_policy=sched_policy)
    reqs = make_fleet_workload(n=160, vocab=64, prompt_min=8, prompt_max=48,
                               out_min=6, out_max=20, rate=3000.0,
                               seed=seed, tenants=tenants,
                               deadline_s=0.035)
    return fleet.run(reqs)


def _attainment(result, tenant):
    """Availability attainment for one tenant via the PR-8 verdict
    machinery (obs/slo.py) — the acceptance's measuring stick."""
    from mpi_cuda_cnn_tpu.obs.slo import (
        SLOSpec,
        verdicts_from_terminals,
    )
    from mpi_cuda_cnn_tpu.serve.scheduler import terminal_fields

    spec = SLOSpec.from_dict({"tenants": {"*": {"availability": 0.95}}})
    terms = [(r.finished_at or r.arrival, "fleet", terminal_fields(r))
             for r in result.requests]
    terms.sort(key=lambda p: p[0])
    verdicts = {v.tenant: v for v in verdicts_from_terminals(terms, spec)}
    return verdicts[tenant].attainment


def test_slo_scheduler_protects_tenant_vs_fcfs_and_is_deterministic():
    """THE SLO acceptance: on a seeded multi-tenant storm with the
    fleet over-subscribed, giving tenant t1 a priority class (plus a
    slot quota on the noisy tenant) measurably improves t1's
    availability attainment vs FCFS — judged by obs/slo.py verdicts —
    and the SLO schedule is bitwise-reproducible across identical-seed
    runs (the CI gate's property)."""
    policy = SLOPolicy(priorities={"t1": 2}, slot_quota={"t0": 1})
    fcfs = _storm(0, sched_policy=None)
    slo = _storm(0, sched_policy=policy)
    a_fcfs = _attainment(fcfs, "t1")
    a_slo = _attainment(slo, "t1")
    assert a_slo > a_fcfs, (a_fcfs, a_slo)
    # Determinism: the whole dispatch schedule pins across reruns.
    slo2 = _storm(0, sched_policy=policy)
    assert slo.trace_crc == slo2.trace_crc
    assert slo.status_counts() == slo2.status_counts()
    assert slo.outputs() == slo2.outputs()


def test_slo_scheduler_enforces_tenant_quotas():
    """A slot quota bounds a tenant's concurrency at admission: with
    t0 capped to 1 slot, no engine state ever shows two t0 slots."""
    pool = PagePool(33)
    sched = SLOScheduler(
        slots=4, pool=pool, page_size=4, max_len=32,
        policy=SLOPolicy(slot_quota={"t0": 1}, page_quota={"t0": 8}),
    )
    rng = np.random.default_rng(0)
    reqs = [Request(rid=i, prompt=rng.integers(0, 13, (6,)),
                    max_new_tokens=4, tenant="t0") for i in range(4)]
    reqs.append(Request(rid=9, prompt=rng.integers(0, 13, (6,)),
                        max_new_tokens=4, tenant="t1"))
    sched.submit(reqs)
    bound = sched.admit(0.0)
    tenants = [s.req.tenant for s in bound]
    assert tenants.count("t0") == 1   # quota bites
    assert tenants.count("t1") == 1   # t1 admitted past blocked t0s
    sched.check()


def test_slo_victim_choice_protects_priority_and_burning_tenant():
    """Preemption victims: lowest priority class first, then the
    tenant with the LEAST SLO pressure, replacing latest-admitted only
    as the tie-break."""
    pool = PagePool(7)   # 6 usable pages of 4
    sched = SLOScheduler(
        slots=3, pool=pool, page_size=4, max_len=24,
        policy=SLOPolicy(priorities={"gold": 2}),
    )
    rng = np.random.default_rng(1)
    reqs = [
        Request(rid=0, prompt=rng.integers(0, 13, (4,)),
                max_new_tokens=12, tenant="bulk"),
        Request(rid=1, prompt=rng.integers(0, 13, (4,)),
                max_new_tokens=12, tenant="gold"),
    ]
    sched.submit(reqs)
    bound = sched.admit(0.0)
    # Priority ordering admits gold (rid 1) FIRST despite equal arrival.
    assert [s.req.rid for s in bound] == [1, 0]
    for s in bound:
        s.cached = s.target
        s.req.out.append(1)
    # Burn the pool dry: the victim must be the bulk request even
    # though gold was admitted earlier (FCFS would evict the latest).
    while sched.preemptions == 0:
        for s in list(sched.decode_slots()):
            s.cached += 1
            s.req.out.append(1)
        sched.grow_for_decode()
        sched.check()
    assert reqs[0].preemptions == 1 and reqs[1].preemptions == 0


def test_policy_arg_grammars():
    assert parse_tenant_priorities("t0=2, t1=0") == {"t0": 2, "t1": 0}
    with pytest.raises(ValueError, match="tenant=int"):
        parse_tenant_priorities("t0:high")
    slot_q, page_q = parse_tenant_quotas("t0=pages:8/slots:2,t1=slots:1")
    assert slot_q == {"t0": 2, "t1": 1} and page_q == {"t0": 8}
    with pytest.raises(ValueError, match="'slots' or 'pages'"):
        parse_tenant_quotas("t0=gpus:1")


# ------------------------------------------------ cache-dtype routing


def test_pick_cache_dtype_routing():
    """VERDICT item 7: 'auto' routes int8 for GQA/MQA and bfloat16 for
    MHA per the banked int8 table; explicit dtypes pass through —
    the pick_attn_impl contract applied to the cache."""
    assert pick_cache_dtype("auto", heads=8, kv_heads=2) == "int8"
    assert pick_cache_dtype("auto", heads=8, kv_heads=1) == "int8"
    assert pick_cache_dtype("auto", heads=8, kv_heads=8) == "bfloat16"
    assert pick_cache_dtype("auto", heads=8, kv_heads=None) == "bfloat16"
    assert pick_cache_dtype("float32", heads=8, kv_heads=2) == "float32"
    assert pick_cache_dtype("int8", heads=8, kv_heads=8) == "int8"
    # The engine resolves "auto" against its model's head geometry.
    gqa = TransformerLM(vocab=13, dim=32, heads=4, depth=1, max_seq=32,
                        kv_heads=2, pos="rope")
    params = gqa.init(jax.random.key(0))
    eng = PagedEngine(gqa, params, slots=1, num_pages=5, page_size=8,
                      cache_dtype="auto")
    assert eng.cache_dtype == np.dtype("int8")
    params = MODEL.init(jax.random.key(0))
    eng = PagedEngine(MODEL, params, slots=1, num_pages=5, page_size=8,
                      cache_dtype="auto")
    assert str(eng.cache_dtype) == "bfloat16"


def test_trainer_config_accepts_auto_cache_dtype():
    from mpi_cuda_cnn_tpu.utils.config import LMConfig

    cfg = LMConfig(decode_cache_dtype="auto")
    assert cfg.decode_cache_dtype == "auto"


# ------------------------------------------------ workload + CLI


def test_prefix_mix_workload_stream_invariance():
    """--prefix-mix must not perturb the base stream: lengths,
    arrivals, outputs budgets, and tenants are bitwise-identical at
    any mix (committed baselines stay valid); mix > 0 makes requests
    genuinely share template prefixes."""
    from mpi_cuda_cnn_tpu.serve.bench import make_workload

    kw = dict(n=40, vocab=64, prompt_min=8, prompt_max=32, out_min=4,
              out_max=12, rate=100.0, seed=5, tenants=3)
    base = make_workload(**kw)
    mixed = make_workload(**kw, prefix_mix=0.7)
    for a, b in zip(base, mixed):
        assert a.prompt.size == b.prompt.size
        assert a.arrival == b.arrival
        assert a.max_new_tokens == b.max_new_tokens
        assert a.tenant == b.tenant
    # Sharing really happens: some pair of mixed prompts agrees on a
    # long prefix while the base pair doesn't.
    def longest_shared(reqs):
        best = 0
        for i in range(len(reqs)):
            for j in range(i + 1, len(reqs)):
                a, b = reqs[i].prompt, reqs[j].prompt
                n = min(a.size, b.size)
                neq = np.nonzero(a[:n] != b[:n])[0]
                best = max(best, int(neq[0]) if neq.size else n)
        return best
    assert longest_shared(mixed) >= 16 > longest_shared(base)


def test_serve_bench_cli_prefix_and_slo_flags(tmp_path):
    """`mctpu serve-bench --prefix-cache --prefix-mix --scheduler slo`
    end-to-end: runs green, the summary carries nonzero prefix hits,
    and the JSONL strict-validates with the new tick fields."""
    import json

    from mpi_cuda_cnn_tpu.obs.schema import load_records
    from mpi_cuda_cnn_tpu.serve.bench import serve_bench_main

    sink = tmp_path / "serve_prefix.jsonl"
    rc = serve_bench_main([
        "--requests", "8", "--dim", "32", "--depth", "1", "--heads", "2",
        "--vocab", "64", "--max-seq", "128", "--prompt-min", "8",
        "--prompt-max", "24", "--out-min", "4", "--out-max", "8",
        "--slots", "2", "--page-size", "8", "--prefill-chunk", "8",
        "--prefix-mix", "0.8", "--prefix-cache", "--scheduler", "slo",
        "--tenants", "2", "--tenant-priority", "t1=2",
        "--metrics-jsonl", str(sink),
    ])
    assert rc == 0
    recs = load_records(sink, strict=True)
    serves = [r for r in recs if r["event"] == "serve"]
    assert len(serves) == 1 and serves[0]["mode"] == "continuous"
    assert serves[0]["prefix_hits"] > 0
    assert any(r.get("prefix_hits") for r in recs if r["event"] == "tick")
    # The trace surface renders the prefix-hit lifecycle markers.
    from mpi_cuda_cnn_tpu.obs.timeline import trace_main
    assert trace_main([str(sink), "--format", "json"]) == 0

    # Bad grammar / contradictory flags die loudly, not silently.
    assert serve_bench_main(["--scheduler", "slo",
                             "--tenant-priority", "bad"]) == 2
    assert serve_bench_main(["--tenant-quota", "t0=slots:1"]) == 2
    assert serve_bench_main(["--mode", "static", "--prefix-cache"]) == 2
