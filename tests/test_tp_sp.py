"""TP x SP (parallel/tp_sp.py): Megatron tensor parallelism inside the
ring-attention shard_map. Layout + schedule must be math-free: exact
parity with the single-device LM step."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh
from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS
from mpi_cuda_cnn_tpu.parallel.tp_sp import (
    from_tp_layout,
    make_tp_sp_lm_train_step,
    make_tp_sp_state,
    to_tp_layout,
)
from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step


def _pieces(kv_heads=0, pos="learned", seed=4):
    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64,
                          kv_heads=kv_heads, pos=pos)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(rng.integers(0, 32, (4, 33)), jnp.int32)
    return model, opt, toks[:, :-1], toks[:, 1:]


def test_tp_layout_roundtrip():
    model, _, _, _ = _pieces(kv_heads=2)
    params = model.init(jax.random.key(0))
    back = from_tp_layout(to_tp_layout(params, model), model)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("kv_heads,pos,mesh_axes", [
    (0, "learned", {SEQ_AXIS: 2, MODEL_AXIS: 2}),
    (2, "rope", {SEQ_AXIS: 2, MODEL_AXIS: 2}),
    (0, "learned", {DATA_AXIS: 2, SEQ_AXIS: 2, MODEL_AXIS: 2}),
    (0, "learned", {SEQ_AXIS: 2, MODEL_AXIS: 4}),
])
def test_tp_sp_step_matches_serial(kv_heads, pos, mesh_axes, eight_devices):
    """One Megatron x ring step == the single-device step (loss AND
    updated params after converting back to the standard layout), incl.
    GQA + rope, a data axis, and 4-way model sharding."""
    model, opt, tokens, targets = _pieces(kv_heads=kv_heads, pos=pos)
    n = int(np.prod(list(mesh_axes.values())))
    mesh = make_mesh(mesh_axes, devices=jax.devices()[:n])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    base = make_lm_state(model, opt, seed=0)
    want_state, want_m = serial_step(base, tokens, targets)

    params = model.init(jax.random.key(0))
    state, specs = make_tp_sp_state(model, params, opt, mesh)
    # Sliced for real: wo (H, hd, d) has its H dim over 'model'.
    wo = state["params"]["blocks"][0]["wo"]
    n_tp = mesh_axes[MODEL_AXIS]
    assert wo.addressable_shards[0].data.shape[0] == model.heads // n_tp

    step = make_tp_sp_lm_train_step(
        model, opt, mesh, specs,
        data_axis=DATA_AXIS if DATA_AXIS in mesh_axes else None,
        donate=False,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    bspec = NamedSharding(
        mesh,
        P(DATA_AXIS if DATA_AXIS in mesh_axes else None, SEQ_AXIS),
    )
    got_state, got_m = step(
        state,
        jax.device_put(tokens, bspec),
        jax.device_put(targets, bspec),
    )
    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got_params = from_tp_layout(
        jax.device_get(got_state["params"]), model
    )
    for a, b in zip(jax.tree.leaves(got_params),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_tp_sp_rejects_bad_configs(eight_devices):
    # MoE composes now (round 4: TP inside every expert —
    # test_tp_sp_moe_trains); head divisibility still fails loudly.
    opt = optax.sgd(0.1)
    mesh = make_mesh({SEQ_AXIS: 2, MODEL_AXIS: 2}, devices=jax.devices()[:4])
    mqa = TransformerLM(vocab=32, dim=32, heads=4, depth=1, max_seq=64,
                        kv_heads=1)
    with pytest.raises(ValueError, match="divide"):
        make_tp_sp_state(mqa, mqa.init(jax.random.key(0)), opt, mesh)


def test_lm_trainer_tp_sp_e2e(eight_devices):
    """The lm product loop trains on data:2,seq:2,model:2 — Megatron x
    ring x DP in one mesh — including eval and decode (the
    head-structured params convert back for both)."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    cfg = LMConfig(corpus="synthetic", dim=32, depth=2, heads=4,
                   seq_len=64, steps=8, batch_size=4, log_every=0,
                   lr_schedule="constant", warmup_steps=0,
                   mesh_shape="data:2,seq:2,model:2", sample_tokens=4)
    t = LMTrainer(cfg, metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.steps_run == 8 and np.isfinite(r.eval_ppl)
    _, cont = t.sample(4)
    assert len(cont) == 4


def test_tp_sp_ring_flash_matches_serial(eight_devices):
    """impl='ring_flash': the fused flash kernel as the per-hop fold
    INSIDE the Megatron block (the on-chip TP x SP configuration) —
    exact parity with the serial step at 128-token shards."""
    model = TransformerLM(vocab=17, dim=32, heads=2, depth=1, max_seq=256)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(8)
    toks = jnp.asarray(rng.integers(0, 17, (1, 257)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]
    mesh = make_mesh({SEQ_AXIS: 2, MODEL_AXIS: 2}, devices=jax.devices()[:4])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=256, donate=False)
    want_state, want_m = serial_step(make_lm_state(model, opt, seed=0),
                                     tokens, targets)

    params = model.init(jax.random.key(0))
    state, specs = make_tp_sp_state(model, params, opt, mesh)
    step = make_tp_sp_lm_train_step(model, opt, mesh, specs,
                                    donate=False, impl="ring_flash")
    from jax.sharding import NamedSharding, PartitionSpec as P

    bs = NamedSharding(mesh, P(None, SEQ_AXIS))
    got_state, got_m = step(state, jax.device_put(tokens, bs),
                            jax.device_put(targets, bs))
    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got = from_tp_layout(jax.device_get(got_state["params"]), model)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_tp_sp_grad_clip_matches_serial(eight_devices):
    """--grad-clip under TP x SP: the in-step global-norm clip (sliced
    leaves psummed over 'model', replicated leaves counted once) must
    equal the serial step's optax clip_by_global_norm."""
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer

    model, _, tokens, targets = _pieces()
    clip = 0.05
    serial_opt = make_optimizer(0.1, grad_clip=clip)
    serial_step = make_lm_train_step(model, serial_opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, _ = serial_step(make_lm_state(model, serial_opt, seed=0),
                                tokens, targets)

    mesh = make_mesh({SEQ_AXIS: 2, MODEL_AXIS: 2}, devices=jax.devices()[:4])
    plain_opt = make_optimizer(0.1)  # clip happens IN the step
    params = model.init(jax.random.key(0))
    state, specs = make_tp_sp_state(model, params, plain_opt, mesh)
    step = make_tp_sp_lm_train_step(model, plain_opt, mesh, specs,
                                    donate=False, grad_clip=clip)
    from jax.sharding import NamedSharding, PartitionSpec as P

    bs = NamedSharding(mesh, P(None, SEQ_AXIS))
    got_state, _ = step(state, jax.device_put(tokens, bs),
                        jax.device_put(targets, bs))
    got = from_tp_layout(jax.device_get(got_state["params"]), model)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_tp_sp_ulysses_matches_serial(eight_devices):
    """impl='ulysses' INSIDE the Megatron block (the former rejection):
    the all-to-all trades the local sequence for a further head split —
    each device holds the full sequence for H/(n_tp*n_seq) heads — and
    must still equal the serial step exactly. Divisibility is checked
    loudly (TP-local heads % n_seq)."""
    import pytest

    model = TransformerLM(vocab=17, dim=32, heads=4, depth=1, max_seq=64)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(12)
    toks = jnp.asarray(rng.integers(0, 17, (2, 33)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]
    mesh = make_mesh({SEQ_AXIS: 2, MODEL_AXIS: 2}, devices=jax.devices()[:4])

    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=32, donate=False)
    want_state, want_m = serial_step(make_lm_state(model, opt, seed=0),
                                     tokens, targets)

    params = model.init(jax.random.key(0))
    state, specs = make_tp_sp_state(model, params, opt, mesh)
    step = make_tp_sp_lm_train_step(model, opt, mesh, specs,
                                    donate=False, impl="ulysses")
    from jax.sharding import NamedSharding, PartitionSpec as P

    bs = NamedSharding(mesh, P(None, SEQ_AXIS))
    got_state, got_m = step(state, jax.device_put(tokens, bs),
                            jax.device_put(targets, bs))
    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got = from_tp_layout(jax.device_get(got_state["params"]), model)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)

    # 2 heads / model:2 = 1 local head, not divisible by seq:2 -> loud.
    narrow = TransformerLM(vocab=17, dim=32, heads=2, depth=1, max_seq=64)
    _, nspecs = make_tp_sp_state(narrow, narrow.init(jax.random.key(0)),
                                 opt, mesh)
    with pytest.raises(ValueError, match="ulysses"):
        make_tp_sp_lm_train_step(narrow, opt, mesh, nspecs,
                                 donate=False, impl="ulysses")


def test_tp_sp_moe_trains(eight_devices):
    """MoE under TP x SP (round 4: TP inside every expert): dispatch is
    per-seq-shard local (the same estimator as EP x SP), so the check is
    training — finite, decreasing loss over a model:2,seq:2 mesh with
    the expert hidden dims really sliced over 'model'."""
    from mpi_cuda_cnn_tpu.parallel.tp_sp import (
        make_tp_sp_lm_train_step,
        make_tp_sp_state,
    )

    model = TransformerLM(vocab=17, dim=32, heads=4, depth=2, max_seq=64,
                          moe_experts=2)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(14)
    toks = jnp.asarray(rng.integers(0, 17, (2, 33)), jnp.int32)
    mesh = make_mesh({SEQ_AXIS: 2, MODEL_AXIS: 2}, devices=jax.devices()[:4])

    params = model.init(jax.random.key(0))
    state, specs = make_tp_sp_state(model, params, opt, mesh)
    w1 = state["params"]["blocks"][0]["moe"]["w1"]  # (E, d, 4d)
    assert w1.addressable_shards[0].data.shape[-1] == 128 // 2
    step = make_tp_sp_lm_train_step(model, opt, mesh, specs, donate=False)
    from jax.sharding import NamedSharding, PartitionSpec as P

    bs = NamedSharding(mesh, P(None, SEQ_AXIS))
    tokens = jax.device_put(toks[:, :-1], bs)
    targets = jax.device_put(toks[:, 1:], bs)
    first = None
    for _ in range(10):
        state, m = step(state, tokens, targets)
        if first is None:
            first = float(m["loss"])
    assert np.isfinite(float(m["loss"])) and float(m["loss"]) < first
