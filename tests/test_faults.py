"""Fault-injection harness + crash-safe training (ISSUE 4).

The tentpole contracts, all deterministic on CPU:

- plan parsing / injector determinism (faults fire exactly once, at the
  named site and value, and record obs-shaped events);
- the supervisor e2e: a run killed mid-epoch by an injected crash,
  restarted by `supervise`, reaches a final state BITWISE-identical to
  the uninterrupted run (the step-exact-resume contract proven through
  an actual crash path, not just a polite resume);
- the NaN/Inf guard: --nan-policy abort raises, skip drops exactly the
  poisoned update, restore rolls back to the last valid checkpoint
  after K consecutive bad steps;
- checkpoint integrity: per-array checksums in the manifest, corrupt
  checkpoints detected and skipped by restore_latest, crash-during-save
  leaves only an ignorable dotfile tmp, AsyncCheckpointer's deferred
  error re-raise fires.
"""

import json

import numpy as np
import pytest

import jax

from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
from mpi_cuda_cnn_tpu.faults import (
    FakeClock,
    FaultInjector,
    InjectedCrash,
    NonFiniteLossError,
    parse_plan,
    supervise,
)
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.train.checkpoint import (
    CheckpointCorruptError,
    latest_checkpoint,
    restore_checkpoint,
    restore_latest,
    save_checkpoint,
)
from mpi_cuda_cnn_tpu.train.trainer import Trainer
from mpi_cuda_cnn_tpu.utils.config import Config
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _quiet(capture=False):
    return MetricsLogger(echo=False, capture=capture)


def _cfg(**kw):
    base = dict(
        dataset="synthetic", model="reference_cnn", epochs=2,
        batch_size=16, num_devices=1, eval_every=0, log_every=0,
        lr=0.05, seed=7,
    )
    base.update(kw)
    return Config(**base)


def _ds():
    return synthetic_stripes(num_train=64, num_test=32)  # 4 steps/epoch


def _params_of(t):
    return jax.device_get(t.state["params"])


def _assert_trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------- plan / injector


def test_parse_plan_grammar():
    plan = parse_plan(
        "crash@train.step:6; nan@train.batch:3?rows=2;"
        "squeeze@serve.tick:2?pages=4&ticks=8;slow@serve.tick:5?s=2.5"
    )
    assert [(f.kind, f.site, f.at) for f in plan] == [
        ("crash", "train.step", 6), ("nan", "train.batch", 3),
        ("squeeze", "serve.tick", 2), ("slow", "serve.tick", 5),
    ]
    assert plan[1].arg("rows") == 2
    assert plan[2].args == {"pages": 4, "ticks": 8}
    assert plan[3].arg("s") == 2.5
    for bad in ("boom@x:1", "crash@:3", "crash@a.b", "crash@a.b:x",
                "nan@train.batch:1?rows"):
        with pytest.raises(ValueError, match="bad fault"):
            parse_plan(bad)


def test_injector_fires_once_at_site_and_value():
    inj = FaultInjector("nan@train.batch:3;crash@train.step:5")
    assert inj.poll("train.batch", 2) == []
    assert inj.poll("train.step", 3) == []   # site must match too
    hits = inj.poll("train.batch", 3)
    assert [f.kind for f in hits] == ["nan"]
    assert inj.poll("train.batch", 3) == []  # fires exactly once
    with pytest.raises(InjectedCrash):
        inj.fire("train.step", 5)
    assert inj.fire("train.step", 5) == []   # consumed by the raise
    evs = inj.drain_events()
    assert [e["kind"] for e in evs] == ["injected_nan", "injected_crash"]
    assert inj.drain_events() == []


def test_fake_clock_drives_injector_sleep():
    clock = FakeClock()
    inj = FaultInjector("slow@serve.tick:0?s=2.5", clock=clock)
    (f,) = inj.poll("serve.tick", 0)
    inj.sleep(f.arg("s"))
    assert clock() == 2.5


def test_supervisor_backs_off_exponentially_with_jitter(tmp_path):
    """ISSUE 5 satellite: restarts are paced — delay_k = base * 2^k *
    (1 + jitter) — and each restart's fault event records the delay.
    sleep/jitter injected, so no wall-clock in the test."""
    slept = []
    metrics = MetricsLogger(echo=False, capture=True)

    def attempt(n):
        raise RuntimeError(f"boom {n}")

    with pytest.raises(RuntimeError):
        supervise(attempt, max_restarts=3, metrics=metrics,
                  backoff_base=0.5, sleep=slept.append, jitter=lambda: 0.0)
    assert slept == [0.5, 1.0, 2.0]  # exponential, 3 restarts
    delays = [r["delay_s"] for r in metrics.rows
              if r["event"] == "fault" and r["kind"] == "restart"]
    assert delays == [0.5, 1.0, 2.0]
    # backoff_base=0 keeps the old immediate-restart behavior.
    slept.clear()
    with pytest.raises(RuntimeError):
        supervise(attempt, max_restarts=2, backoff_base=0)
    assert slept == []


# ---------------------------------------------------------------- supervisor e2e


@pytest.mark.parametrize("scan", [True, False])
def test_supervised_crash_restart_is_bitwise_exact(tmp_path, scan):
    """THE acceptance e2e: a training run killed mid-epoch by an
    injected crash (after step 6 of 8; checkpoints every 3 steps),
    restarted by the supervisor, ends bitwise-identical to the
    uninterrupted run."""
    ds = _ds()
    full = Trainer(get_model("reference_cnn"), ds, _cfg(scan=scan),
                   metrics=_quiet())
    full.train()
    want = _params_of(full)

    ck = tmp_path / "ck"
    faults = FaultInjector("crash@train.step:6")
    metrics = _quiet(capture=True)
    attempts = []

    def attempt(n):
        cfg = _cfg(scan=scan, checkpoint_dir=str(ck),
                   checkpoint_every_steps=3, resume=n > 0)
        t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=metrics,
                    faults=faults)
        attempts.append(t)
        return t.train()

    res = supervise(attempt, max_restarts=2, metrics=metrics)
    assert len(attempts) == 2          # one crash, one clean finish
    assert res.final_step == full._global_step()
    _assert_trees_equal(want, _params_of(attempts[-1]))
    kinds = [r["kind"] for r in metrics.rows if r["event"] == "fault"]
    assert "injected_crash" in kinds
    assert "restart" in kinds


def test_supervisor_exhausts_restarts_and_reraises(tmp_path):
    ds = _ds()
    faults = FaultInjector("crash@train.step:2;crash@train.step:3")

    def attempt(n):
        cfg = _cfg(checkpoint_dir=str(tmp_path / "ck"),
                   checkpoint_every_steps=1, resume=n > 0)
        return Trainer(get_model("reference_cnn"), ds, cfg,
                       metrics=_quiet(), faults=faults).train()

    with pytest.raises(InjectedCrash):
        supervise(attempt, max_restarts=1)  # two crashes, one restart


def test_cli_train_supervisor_e2e(tmp_path):
    """`mctpu train --max-restarts N --fault-plan crash@...` end to end
    through the CLI: the crashed attempt restarts, resumes from the
    checkpoint, exits 0, and the JSONL sink carries the fault events."""
    from mpi_cuda_cnn_tpu import cli
    from mpi_cuda_cnn_tpu.obs.schema import load_records

    sink = tmp_path / "run.jsonl"
    rc = cli.main([
        "train", "--dataset", "synthetic", "--model", "reference_cnn",
        "--epochs", "1", "--batch-size", "500", "--num-devices", "1",
        "--eval-every", "0", "--log-every", "0", "--device", "cpu",
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--checkpoint-every-steps", "2", "--max-restarts", "1",
        "--fault-plan", "crash@train.step:2",
        "--metrics-jsonl", str(sink),
    ])
    assert rc == 0
    kinds = [r["kind"] for r in load_records(sink, strict=True)
             if r["event"] == "fault"]
    assert "restart" in kinds
    assert "injected_crash" in kinds
    # Supervisor without a checkpoint dir is a config error, caught
    # before any training.
    assert cli.main(["train", "--dataset", "synthetic",
                     "--max-restarts", "1"]) == 2


# ---------------------------------------------------------------- NaN guard


def test_nan_policy_abort_raises():
    ds = _ds()
    t = Trainer(
        get_model("reference_cnn"), ds,
        _cfg(epochs=1, nan_policy="abort"), metrics=_quiet(),
        faults=FaultInjector("nan@train.batch:2"),
    )
    with pytest.raises(NonFiniteLossError):
        t.train()


def test_supervisor_does_not_retry_nan_abort(tmp_path):
    """Regression (review finding): the NaN guard's abort verdict is a
    policy decision, not a crash — an organic NaN replays
    deterministically from the checkpoint, so the supervisor must pass
    it through instead of burning every restart reproducing it."""
    ds = _ds()
    attempts = []

    def attempt(n):
        cfg = _cfg(epochs=1, nan_policy="abort",
                   checkpoint_dir=str(tmp_path / "ck"),
                   checkpoint_every_steps=1, resume=n > 0)
        t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet(),
                    faults=FaultInjector("nan@train.batch:2"))
        attempts.append(t)
        return t.train()

    with pytest.raises(NonFiniteLossError):
        supervise(attempt, max_restarts=3)
    assert len(attempts) == 1  # no futile replays


def test_skipped_step_still_fires_planned_step_faults(tmp_path):
    """Regression (review finding): a NaN-skipped step must not swallow
    a planned crash at the same step value — the batch was consumed, so
    the train.step hook fires and the chaos run exercises its crash."""
    ds = _ds()
    faults = FaultInjector("nan@train.batch:3;crash@train.step:4")
    metrics = _quiet(capture=True)
    attempts = []

    def attempt(n):
        cfg = _cfg(epochs=1, nan_policy="skip",
                   checkpoint_dir=str(tmp_path / "ck"),
                   checkpoint_every_steps=2, resume=n > 0)
        t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=metrics,
                    faults=faults)
        attempts.append(t)
        return t.train()

    res = supervise(attempt, max_restarts=1, metrics=metrics)
    assert len(attempts) == 2  # the crash DID fire, then recovery ran
    assert res.final_step == 4
    kinds = [r["kind"] for r in metrics.rows if r["event"] == "fault"]
    assert "injected_crash" in kinds
    assert "nonfinite_step" in kinds


def test_nan_policy_skip_drops_exactly_the_poisoned_update():
    """skip counts and drops the bad update: params stay finite,
    exactly one step is dropped, and state["step"] still counts batches
    CONSUMED (4) — not updates applied — so a later crash-restart's
    resume position can never go short by the skipped steps."""
    ds = _ds()
    metrics = _quiet(capture=True)
    t = Trainer(
        get_model("reference_cnn"), ds,
        _cfg(epochs=1, nan_policy="skip"), metrics=metrics,
        faults=FaultInjector("nan@train.batch:2"),
    )
    res = t.train()
    assert t._nan.skipped == 1
    # 4 batches consumed (one update dropped): the step counter tracks
    # the DATA position, keeping resume exact after skips.
    assert res.final_step == 4
    for leaf in jax.tree.leaves(_params_of(t)):
        assert np.all(np.isfinite(np.asarray(leaf)))
    kinds = [r["kind"] for r in metrics.rows if r["event"] == "fault"]
    assert kinds.count("nonfinite_step") == 1
    assert kinds.count("injected_nan") == 1


def test_nan_policy_restore_rolls_back_to_checkpoint(tmp_path):
    """Two consecutive poisoned batches with nan_max_bad=2: the guard
    skips both, then rolls the state back to the last checkpoint and
    replays — the run completes with finite params and a nan_restore
    event."""
    ds = _ds()
    metrics = _quiet(capture=True)
    t = Trainer(
        get_model("reference_cnn"), ds,
        _cfg(epochs=1, nan_policy="restore", nan_max_bad=2,
             checkpoint_dir=str(tmp_path / "ck"), checkpoint_every_steps=1),
        metrics=metrics,
        faults=FaultInjector("nan@train.batch:1;nan@train.batch:2"),
    )
    res = t.train()
    assert res.final_step == 4  # every batch's update eventually lands
    for leaf in jax.tree.leaves(_params_of(t)):
        assert np.all(np.isfinite(np.asarray(leaf)))
    kinds = [r["kind"] for r in metrics.rows if r["event"] == "fault"]
    assert "nan_restore" in kinds
    assert kinds.count("nonfinite_step") == 2


def test_skip_then_crash_restart_stays_bitwise_exact(tmp_path):
    """Regression (review finding): nan-policy=skip must not
    desynchronize the resume position from the data position. A run
    that SKIPS batch 4 and then crashes after batch 5 must, once
    restarted, land bitwise on the reference guarded run (same skip, no
    crash) — i.e. batch 5's update is never applied twice."""
    ds = _ds()
    ref = Trainer(
        get_model("reference_cnn"), ds, _cfg(nan_policy="skip"),
        metrics=_quiet(), faults=FaultInjector("nan@train.batch:4"),
    )
    ref.train()
    want = _params_of(ref)

    ck = tmp_path / "ck"
    faults = FaultInjector("nan@train.batch:4;crash@train.step:6")
    attempts = []

    def attempt(n):
        cfg = _cfg(nan_policy="skip", checkpoint_dir=str(ck),
                   checkpoint_every_steps=3, resume=n > 0)
        t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet(),
                    faults=faults)
        attempts.append(t)
        return t.train()

    res = supervise(attempt, max_restarts=2)
    assert len(attempts) == 2
    assert res.final_step == 8  # batches consumed, skip included
    _assert_trees_equal(want, _params_of(attempts[-1]))


def test_nan_guard_forces_per_batch_stepping():
    ds = _ds()
    t = Trainer(get_model("reference_cnn"), ds,
                _cfg(nan_policy="skip"), metrics=_quiet())
    assert not t._use_scan()
    t2 = Trainer(get_model("reference_cnn"), ds, _cfg(), metrics=_quiet())
    assert t2._use_scan()


def test_bad_nan_policy_rejected():
    with pytest.raises(ValueError, match="nan-policy"):
        Trainer(get_model("reference_cnn"), _ds(),
                _cfg(nan_policy="bogus"), metrics=_quiet())


# ---------------------------------------------------------------- checkpoint integrity


def _state(seed=0):
    model = get_model("reference_cnn")
    from mpi_cuda_cnn_tpu.models.initializers import get_initializer
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer

    params = model.init(jax.random.key(seed), get_initializer("normal"))
    opt = make_optimizer(0.1, momentum=0.9)
    import jax.numpy as jnp

    return {"params": params, "opt_state": opt.init(params),
            "step": jnp.asarray(7, jnp.int32)}


def test_manifest_records_checksums_and_is_atomic(tmp_path):
    state = _state()
    save_checkpoint(tmp_path, state, 3)
    mf = json.loads((tmp_path / "manifest.json").read_text())
    assert mf["latest_step"] == 3
    assert set(mf["checksums"]) == {"ckpt_3.npz"}
    assert set(mf["checksums"]["ckpt_3.npz"]) == set(mf["keys"])
    # No tmp litter from the atomic writes.
    assert not list(tmp_path.glob(".manifest*"))
    # Pruned checkpoints leave the manifest too.
    for step in (6, 9, 12):
        save_checkpoint(tmp_path, state, step, keep=2)
    mf = json.loads((tmp_path / "manifest.json").read_text())
    assert set(mf["checksums"]) == {"ckpt_9.npz", "ckpt_12.npz"}


def test_corrupt_checkpoint_detected_and_skipped(tmp_path):
    good = _state(seed=0)
    save_checkpoint(tmp_path, good, 1)
    save_checkpoint(tmp_path, _state(seed=1), 2)
    # Corrupt ckpt_2 with a VALID npz holding different bytes — only
    # the manifest checksums can catch this class of corruption.
    other = {k: np.asarray(v) + 1.0 if np.issubdtype(
        np.asarray(v).dtype, np.floating) else np.asarray(v)
        for k, v in _flat(_state(seed=1)).items()}
    np.savez(tmp_path / "ckpt_2.npz", **other)
    with pytest.raises(CheckpointCorruptError):
        restore_checkpoint(tmp_path / "ckpt_2.npz", _state(seed=2))
    restored, path = restore_latest(tmp_path, _state(seed=2))
    assert path.name == "ckpt_1.npz"
    _assert_trees_equal(jax.device_get(good), restored)
    # Torn-file corruption (not even a zip) also falls back.
    (tmp_path / "ckpt_2.npz").write_bytes(b"torn write")
    restored, path = restore_latest(tmp_path, _state(seed=2))
    assert path.name == "ckpt_1.npz"


def _flat(state):
    from mpi_cuda_cnn_tpu.train.checkpoint import _flatten

    return _flatten(jax.device_get(state))


def test_restore_without_manifest_globs_and_skips_verification(tmp_path):
    state = _state()
    save_checkpoint(tmp_path, state, 5)
    (tmp_path / "manifest.json").unlink()
    restored = restore_checkpoint(latest_checkpoint(tmp_path), _state(1))
    _assert_trees_equal(jax.device_get(state), restored)
    # Unparsable manifest: same degradation, not an error.
    (tmp_path / "manifest.json").write_text("{torn json")
    restored, path = restore_latest(tmp_path, _state(1))
    assert path.name == "ckpt_5.npz"
    _assert_trees_equal(jax.device_get(state), restored)


def test_crash_between_tmp_write_and_rename(tmp_path):
    """ISSUE 4 satellite: kill the writer between the npz tmp write and
    the rename — the dotfile tmp is invisible to the glob, the previous
    checkpoint restores, and the manifest still names only live files."""
    state = _state()
    save_checkpoint(tmp_path, state, 3)
    faults = FaultInjector("crash@ckpt.pre_rename:6")
    with pytest.raises(InjectedCrash):
        save_checkpoint(tmp_path, _state(seed=1), 6, faults=faults)
    assert (tmp_path / ".ckpt_6.tmp.npz").exists()  # the torn write
    assert latest_checkpoint(tmp_path).name == "ckpt_3.npz"
    restored, path = restore_latest(tmp_path, _state(seed=2))
    assert path.name == "ckpt_3.npz"
    _assert_trees_equal(jax.device_get(state), restored)
    mf = json.loads((tmp_path / "manifest.json").read_text())
    assert "ckpt_6.npz" not in mf["checksums"]


def test_async_checkpointer_deferred_crash_reraises(tmp_path):
    """A crash injected inside the BACKGROUND write must re-raise at the
    next save()/wait() — the deferred-error contract under faults."""
    from mpi_cuda_cnn_tpu.train.checkpoint import AsyncCheckpointer

    faults = FaultInjector("crash@ckpt.pre_rename:2")
    ck = AsyncCheckpointer(tmp_path, faults=faults)
    ck.save(_state(), 1)
    ck.wait()
    ck.save(_state(), 2)  # the worker hits the injected crash
    with pytest.raises(InjectedCrash):
        ck.wait()
    assert latest_checkpoint(tmp_path).name == "ckpt_1.npz"
    ck.close()


def test_trainer_resume_skips_corrupt_latest(tmp_path):
    """End to end through Trainer: corrupt the newest checkpoint after a
    checkpointed run; a resumed trainer must fall back to the previous
    valid one instead of crashing or silently training on garbage."""
    ds = _ds()
    ck = tmp_path / "ck"
    t = Trainer(get_model("reference_cnn"), ds,
                _cfg(epochs=1, checkpoint_dir=str(ck),
                     checkpoint_every_steps=1, scan=False),
                metrics=_quiet())
    t.train()
    newest = latest_checkpoint(ck)
    newest.write_bytes(b"torn")
    metrics = _quiet(capture=True)
    resumed = Trainer(get_model("reference_cnn"), ds,
                      _cfg(epochs=1, checkpoint_dir=str(ck), resume=True,
                           scan=False),
                      metrics=metrics)
    resumed.train()
    kinds = [r["kind"] for r in metrics.rows if r["event"] == "fault"]
    assert "ckpt_fallback" in kinds
