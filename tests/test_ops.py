"""Op-level tests: conv/dense forward vs naive numpy (the reference's loop
semantics, cnn.c:113-247), named gradient ops vs jax.grad, loss gradient ==
the reference's softmax - onehot error seeding (SURVEY.md 2.5)."""

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.ops import (
    conv2d,
    conv2d_input_grad,
    conv2d_kernel_grad,
    dense,
    softmax_cross_entropy,
    stable_softmax,
)


def naive_conv2d(x, w, stride, padding):
    """Direct re-expression of Layer_feedForw_conv's loop nest
    (cnn.c:175-210): zero padding via bounds check, NHWC/HWIO layouts."""
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    oh = (h + 2 * padding - kh) // stride + 1
    ow = (wd + 2 * padding - kw) // stride + 1
    out = np.zeros((n, oh, ow, cout), np.float32)
    for b in range(n):
        for oy in range(oh):
            for ox in range(ow):
                for oc in range(cout):
                    acc = 0.0
                    for ky in range(kh):
                        for kx in range(kw):
                            iy = oy * stride + ky - padding
                            ix = ox * stride + kx - padding
                            if 0 <= iy < h and 0 <= ix < wd:
                                acc += float(x[b, iy, ix] @ w[ky, kx, :, oc])
                    out[b, oy, ox, oc] = acc
    return out


def test_conv2d_matches_naive_stride2_pad1():
    """The reference's exact conv config: k3 s2 p1 (cnn.c:417)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 8, 8, 3)).astype(np.float32)
    w = rng.standard_normal((3, 3, 3, 4)).astype(np.float32)
    got = np.asarray(conv2d(jnp.asarray(x), jnp.asarray(w), stride=2, padding=1))
    want = naive_conv2d(x, w, 2, 1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_conv2d_matches_naive_stride1_nopad():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((1, 6, 6, 2)).astype(np.float32)
    w = rng.standard_normal((5, 5, 2, 3)).astype(np.float32)
    got = np.asarray(conv2d(jnp.asarray(x), jnp.asarray(w)))
    np.testing.assert_allclose(got, naive_conv2d(x, w, 1, 0), rtol=1e-4, atol=1e-5)


def _conv_cfg():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 9, 9, 3)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((3, 3, 3, 5)).astype(np.float32))
    return x, w, dict(stride=2, padding=1)


def test_conv2d_input_grad_matches_autodiff():
    """The named dx op (twin of cnn.c:228-236) must equal jax.grad."""
    x, w, cfg = _conv_cfg()
    f = lambda x_: jnp.sum(conv2d(x_, w, **cfg) ** 2)
    want = jax.grad(f)(x)
    g = 2 * conv2d(x, w, **cfg)
    got = conv2d_input_grad(g, w, input_hw=(9, 9), **cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)


def test_conv2d_kernel_grad_matches_autodiff():
    """The named dw op (twin of cnn.c:238-242) must equal jax.grad."""
    x, w, cfg = _conv_cfg()
    f = lambda w_: jnp.sum(conv2d(x, w_, **cfg) ** 2)
    want = jax.grad(f)(w)
    g = 2 * conv2d(x, w, **cfg)
    got = conv2d_kernel_grad(x, g, **cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)


def test_dense():
    x = jnp.asarray([[1.0, 2.0]])
    w = jnp.asarray([[1.0, 0.0, 1.0], [0.0, 1.0, 1.0]])
    b = jnp.asarray([0.5, 0.5, 0.0])
    np.testing.assert_allclose(np.asarray(dense(x, w, b)), [[1.5, 2.5, 3.0]])


def test_softmax_stability():
    """Max-subtracted form (cnn.c:125-143) survives huge logits."""
    probs = stable_softmax(jnp.asarray([[1e4, 1e4 - 1.0, 0.0]]))
    assert np.all(np.isfinite(np.asarray(probs)))
    np.testing.assert_allclose(float(probs.sum()), 1.0, rtol=1e-6)


def test_ce_gradient_is_softmax_minus_onehot():
    """d(CE)/dlogits == (softmax - onehot)/N — exactly the reference's
    error seeding errors = outputs - onehot (cnn.c:284-286 + 2.5 hack)."""
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.standard_normal((4, 10)).astype(np.float32))
    y = np.zeros((4, 10), np.float32)
    y[np.arange(4), [1, 5, 0, 9]] = 1
    y = jnp.asarray(y)
    grad = jax.grad(lambda l: softmax_cross_entropy(l, y))(logits)
    want = (stable_softmax(logits) - y) / 4
    np.testing.assert_allclose(np.asarray(grad), np.asarray(want), rtol=1e-5, atol=1e-6)
