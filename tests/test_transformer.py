"""Transformer LM + sequence-parallel training (parallel/sp.py).

End-to-end coverage of the long-context path: the decoder-only LM trains
under ring / Ulysses sequence parallelism on the 8-virtual-device CPU
mesh, with exact parity against the single-device program.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, make_mesh
from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS, make_sp_lm_train_step

MODEL = TransformerLM(vocab=17, dim=32, heads=8, depth=2, max_seq=64)


def _data(batch=4, s=64, seed=0):
    """Cyclic-successor sequences: token[t+1] = token[t] + 1 (mod vocab) —
    learnable by a 1-layer causal model, deterministic to evaluate."""
    rng = np.random.default_rng(seed)
    start = rng.integers(0, MODEL.vocab, size=(batch, 1))
    toks = (start + np.arange(s)[None, :]) % MODEL.vocab
    inputs = jnp.asarray(toks[:, :-1], jnp.int32)
    targets = jnp.asarray(toks[:, 1:], jnp.int32)
    return inputs, targets


def _single_device_loss(params, inputs, targets):
    logits = MODEL.apply(params, inputs)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], axis=-1))


def test_apply_shapes():
    params = MODEL.init(jax.random.key(0))
    inputs, _ = _data(batch=2, s=33)
    logits = MODEL.apply(params, inputs)
    assert logits.shape == (2, 32, MODEL.vocab)


def test_apply_causality():
    """Changing future tokens must not change past logits."""
    params = MODEL.init(jax.random.key(0))
    inputs, _ = _data(batch=2, s=33)
    l1 = MODEL.apply(params, inputs)
    mutated = inputs.at[:, 20:].set(0)
    l2 = MODEL.apply(params, mutated)
    np.testing.assert_allclose(
        np.asarray(l1[:, :20]), np.asarray(l2[:, :20]), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sp_step_parity_with_single_device(impl):
    """One SP train step over Mesh({'seq': 8}) == the same step computed
    globally on one device (loss and updated params)."""
    mesh = make_mesh({SEQ_AXIS: 8}, devices=jax.devices()[:8])
    params = MODEL.init(jax.random.key(1))
    opt = optax.sgd(0.1)
    inputs, targets = _data(batch=2, s=65)  # 64 positions / 8 shards
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    step = make_sp_lm_train_step(MODEL, opt, mesh, impl=impl, donate=False)
    new_state, metrics = step(state, inputs, targets)

    want_loss = _single_device_loss(params, inputs, targets)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(want_loss), rtol=1e-5, atol=1e-5
    )
    grads = jax.grad(_single_device_loss)(params, inputs, targets)
    updates, _ = opt.update(grads, opt.init(params), params)
    want_params = optax.apply_updates(params, updates)
    for a, b in zip(jax.tree.leaves(new_state["params"]),
                    jax.tree.leaves(want_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_sp_step_with_chunked_ce_matches_dense():
    """ce_chunk under SP (shard-local fused CE, ops/losses.chunked_ce_mean)
    must be placement, not math: one SP step with ce_chunk == the same SP
    step with the dense shard-local logits."""
    mesh = make_mesh({SEQ_AXIS: 8}, devices=jax.devices()[:8])
    params = MODEL.init(jax.random.key(5))
    opt = optax.sgd(0.1)
    inputs, targets = _data(batch=2, s=65)  # 8 positions per shard
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}

    outs = {}
    for chunk in (0, 4):
        step = make_sp_lm_train_step(MODEL, opt, mesh, impl="ring",
                                     donate=False, ce_chunk=chunk)
        new_state, metrics = step(state, inputs, targets)
        outs[chunk] = (float(metrics["loss"]), new_state["params"])
    np.testing.assert_allclose(outs[0][0], outs[4][0], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(outs[0][1]), jax.tree.leaves(outs[4][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_sp_step_parity_ring_flash():
    """impl='ring_flash': the fused-kernel ring inside a REAL train step
    (value_and_grad through the custom VJP, optimizer update) matches the
    single-device program. Shards are 128 tokens — the flash kernel's
    block granularity."""
    model = TransformerLM(vocab=17, dim=32, heads=2, depth=1, max_seq=1024)
    mesh = make_mesh({SEQ_AXIS: 8}, devices=jax.devices()[:8])
    params = model.init(jax.random.key(3))
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(3)
    start = rng.integers(0, model.vocab, size=(1, 1))
    toks = (start + np.arange(1025)[None, :]) % model.vocab
    inputs = jnp.asarray(toks[:, :-1], jnp.int32)
    targets = jnp.asarray(toks[:, 1:], jnp.int32)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    step = make_sp_lm_train_step(model, opt, mesh, impl="ring_flash",
                                 donate=False)
    new_state, metrics = step(state, inputs, targets)

    def loss_fn(params):
        logits = model.apply(params, inputs)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], axis=-1))

    want_loss, grads = jax.value_and_grad(loss_fn)(params)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(want_loss), rtol=1e-5, atol=1e-5
    )
    updates, _ = opt.update(grads, opt.init(params), params)
    want_params = optax.apply_updates(params, updates)
    for a, b in zip(jax.tree.leaves(new_state["params"]),
                    jax.tree.leaves(want_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_sp_dp_mesh_composes():
    """SP x DP: Mesh({'data': 2, 'seq': 4}) — batch AND sequence sharded."""
    mesh = make_mesh({DATA_AXIS: 2, SEQ_AXIS: 4}, devices=jax.devices()[:8])
    params = MODEL.init(jax.random.key(2))
    opt = optax.sgd(0.1)
    inputs, targets = _data(batch=4, s=65)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    step = make_sp_lm_train_step(
        MODEL, opt, mesh, data_axis=DATA_AXIS, donate=False
    )
    new_state, metrics = step(state, inputs, targets)
    want_loss = _single_device_loss(params, inputs, targets)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(want_loss), rtol=1e-5, atol=1e-5
    )


def test_moe_lm_dense_oracle_shapes_and_aux():
    """MoE blocks (single-device dense routing): logits shape, finite aux,
    and causality all hold."""
    model = TransformerLM(vocab=17, dim=32, heads=4, depth=2, max_seq=64,
                          moe_experts=4)
    params = model.init(jax.random.key(0))
    inputs, _ = _data(batch=2, s=33)
    logits, aux = model.apply(params, inputs, return_aux=True)
    assert logits.shape == (2, 32, 17)
    assert np.isfinite(float(aux)) and float(aux) > 0
    # Causality: Switch routing flattens (batch, seq) in row-major order,
    # so capacity eviction for a LATER batch row can depend on an earlier
    # row's future tokens (standard Switch semantics). Row 0 queues behind
    # nothing, so its early positions must be strictly causal.
    mutated = inputs.at[:, 20:].set(0)
    l2, _ = model.apply(params, mutated, return_aux=True)
    np.testing.assert_allclose(
        np.asarray(logits[0, :20]), np.asarray(l2[0, :20]),
        rtol=1e-5, atol=1e-5,
    )


def test_moe_lm_trains_under_ring_sp():
    """EP x SP: MoE experts sharded over the SAME 'seq' axis as the
    sequence — the full composition must train the cyclic task."""
    model = TransformerLM(vocab=17, dim=32, heads=8, depth=2, max_seq=64,
                          moe_experts=8)
    mesh = make_mesh({SEQ_AXIS: 8}, devices=jax.devices()[:8])
    params = model.init(jax.random.key(4))
    opt = optax.adam(3e-3)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    step = make_sp_lm_train_step(model, opt, mesh)
    losses = []
    for i in range(150):
        inputs, targets = _data(batch=8, s=65, seed=100 + i)
        state, metrics = step(state, inputs, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < 0.25, f"did not learn: {losses[::30]}"
    assert losses[-1] < losses[0] / 5


def test_sp_remat_composition():
    """The two long-context memory levers together: ring attention
    (O(S/P) activations) + per-block remat — one step must match the
    plain SP step exactly."""
    mesh = make_mesh({SEQ_AXIS: 8}, devices=jax.devices()[:8])
    params = MODEL.init(jax.random.key(9))
    opt = optax.sgd(0.1)
    inputs, targets = _data(batch=2, s=65)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    plain = make_sp_lm_train_step(MODEL, opt, mesh, donate=False)
    remat = make_sp_lm_train_step(MODEL, opt, mesh, donate=False, remat=True)
    s_plain, m_plain = plain(dict(state), inputs, targets)
    s_remat, m_remat = remat(dict(state), inputs, targets)
    np.testing.assert_allclose(float(m_plain["loss"]), float(m_remat["loss"]),
                               rtol=1e-6)
    # The UPDATED params are where a broken remat backward would show
    # (the forward loss is identical by construction).
    for a, b in zip(jax.tree.leaves(s_plain["params"]),
                    jax.tree.leaves(s_remat["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_sp_lm_learns_cyclic_task():
    """Ring-SP training drives the loss to ~0 on the cyclic-successor task
    (the model must actually learn through the sharded attention)."""
    mesh = make_mesh({SEQ_AXIS: 8}, devices=jax.devices()[:8])
    params = MODEL.init(jax.random.key(3))
    opt = optax.adam(3e-3)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    step = make_sp_lm_train_step(MODEL, opt, mesh)
    losses = []
    for i in range(150):
        inputs, targets = _data(batch=8, s=65, seed=i)
        state, metrics = step(state, inputs, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < 0.15, f"did not learn: {losses[::30]}"
    assert losses[-1] < losses[0] / 10
