"""KV-cache decoding (models/generate.py): teacher-forcing parity with
the training forward, and end-to-end generation from a trained model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.models.generate import decode_step, generate, init_cache
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)


def test_decode_matches_training_forward():
    """Cached one-token-at-a-time logits must equal the full teacher-forced
    forward at every position (same params, same tokens)."""
    params = MODEL.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, 13, (3, 20)), jnp.int32
    )
    want = MODEL.apply(params, toks)          # (3, 20, vocab)

    cache = init_cache(MODEL, 3)
    got = []
    for i in range(20):
        logits, cache = decode_step(MODEL, params, toks[:, i], i, cache)
        got.append(logits)
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_bf16_cache_decode_close_and_really_bf16():
    """cache_dtype='bfloat16' must (a) actually store the cache in bf16
    — the bandwidth lever is the storage dtype — and (b) keep the cached
    decode logits within bf16 rounding of the f32-cache path (scores and
    softmax stay f32; only the stored k/v round)."""
    from mpi_cuda_cnn_tpu.models.generate import prefill

    params = MODEL.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(3).integers(0, 13, (2, 12)), jnp.int32
    )
    _, cache16 = prefill(MODEL, params, toks, cache_dtype=jnp.bfloat16)
    assert cache16[0]["k"].dtype == jnp.bfloat16
    assert cache16[0]["v"].dtype == jnp.bfloat16

    cache32 = init_cache(MODEL, 2)
    cache16 = init_cache(MODEL, 2, jnp.bfloat16)
    for i in range(12):
        l32, cache32 = decode_step(MODEL, params, toks[:, i], i, cache32)
        l16, cache16 = decode_step(MODEL, params, toks[:, i], i, cache16)
        np.testing.assert_allclose(np.asarray(l16), np.asarray(l32),
                                   rtol=3e-2, atol=3e-2)

    # The generate() surface takes the dtype as a string (the CLI's
    # --decode-cache-dtype form) and still produces valid tokens.
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = generate(MODEL, params, prompt, 4, cache_dtype="bfloat16")
    assert out.shape == (1, 4)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < MODEL.vocab))


def test_filter_logits_and_restricted_sampling():
    """filter_logits: top_k keeps exactly the k largest (plus boundary
    ties), top_p the smallest prefix reaching mass p; generate() with
    top_k=1 at temperature > 0 equals greedy (the restriction leaves one
    candidate), and sampled tokens stay inside the top_k set."""
    from mpi_cuda_cnn_tpu.models.generate import filter_logits
    from mpi_cuda_cnn_tpu.ops.attention import NEG_INF

    l = jnp.asarray([[2.0, -1.0, 3.0, 0.5, -2.0]])
    k2 = np.asarray(filter_logits(l, top_k=2))
    assert (k2[0] > NEG_INF / 2).tolist() == [True, False, True, False, False]

    # probs of l: softmax — top_p just over the largest prob keeps the
    # top-2; a tiny top_p keeps exactly the argmax.
    p = np.asarray(jax.nn.softmax(l, axis=-1))[0]
    keep2 = np.asarray(filter_logits(l, top_p=float(p.max()) + 1e-3))
    assert (keep2[0] > NEG_INF / 2).tolist() == [True, False, True, False, False]
    keep1 = np.asarray(filter_logits(l, top_p=1e-6))
    assert (keep1[0] > NEG_INF / 2).tolist() == [False, False, True, False, False]
    # top_p=1 keeps everything.
    assert (np.asarray(filter_logits(l, top_p=1.0))[0] > NEG_INF / 2).all()

    params = MODEL.init(jax.random.key(0))
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    greedy = np.asarray(generate(MODEL, params, prompt, 6))
    k1 = np.asarray(generate(MODEL, params, prompt, 6, temperature=1.0,
                             key=jax.random.key(7), top_k=1))
    np.testing.assert_array_equal(k1, greedy)

    with pytest.raises(ValueError, match="temperature"):
        generate(MODEL, params, prompt, 2, top_k=3)
    with pytest.raises(ValueError, match="top_p"):
        generate(MODEL, params, prompt, 2, temperature=1.0, top_p=1.5,
                 key=jax.random.key(0))


def test_decode_block_matches_decode_steps():
    """decode_block(k tokens) must equal k sequential decode_steps —
    same logits, same cache — on MHA and on a GQA+RoPE model."""
    from mpi_cuda_cnn_tpu.models.generate import decode_block

    for m in (MODEL, TransformerLM(vocab=13, dim=32, heads=4, depth=2,
                                   max_seq=48, kv_heads=2, pos="rope")):
        params = m.init(jax.random.key(1))
        toks = jnp.asarray(
            np.random.default_rng(5).integers(0, 13, (2, 11)), jnp.int32
        )
        pre, blk = toks[:, :6], toks[:, 6:]

        cache = init_cache(m, 2)
        for i in range(6):
            _, cache = decode_step(m, params, pre[:, i], i, cache)
        want, want_cache = [], cache
        for i in range(5):
            l, want_cache = decode_step(m, params, blk[:, i], 6 + i,
                                        want_cache)
            want.append(l)
        got, got_cache = decode_block(m, params, blk, 6, cache)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(jnp.stack(want, axis=1)),
                                   rtol=2e-4, atol=2e-4)
        for a, b in zip(jax.tree.leaves(got_cache),
                        jax.tree.leaves(want_cache)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


def test_speculative_equals_greedy():
    """The gold property of greedy speculative decoding: the output is
    EXACTLY the target's own greedy continuation, for ANY draft — an
    untrained random draft (near-zero acceptance), the target itself
    (full acceptance), and a differently-shaped draft, at several k."""
    from mpi_cuda_cnn_tpu.models.generate import speculative_generate

    params = MODEL.init(jax.random.key(0))
    prompt = jnp.asarray([[1, 2, 3, 4, 5]], jnp.int32)
    want = np.asarray(generate(MODEL, params, prompt, 10))

    drafts = [
        (MODEL, MODEL.init(jax.random.key(9))),        # random weights
        (MODEL, params),                               # perfect draft
        (TransformerLM(vocab=13, dim=16, heads=2, depth=1, max_seq=48),
         None),                                        # shallower draft
    ]
    for k in (2, 4):
        for dm, dp in drafts:
            dp = dm.init(jax.random.key(3)) if dp is None else dp
            got = speculative_generate(MODEL, params, dm, dp, prompt, 10,
                                       k=k)
            np.testing.assert_array_equal(np.asarray(got), want)

    with pytest.raises(ValueError, match="B=1"):
        speculative_generate(MODEL, params, MODEL, params,
                             jnp.asarray([[1], [2]], jnp.int32), 4)
    with pytest.raises(ValueError, match="vocab"):
        bad = TransformerLM(vocab=7, dim=16, heads=2, depth=1, max_seq=48)
        speculative_generate(MODEL, params, bad, bad.init(jax.random.key(0)),
                             prompt, 4)


def test_lookup_speculative_equals_greedy():
    """Prompt-lookup speculation (draft-free) keeps the same gold
    property: output == the target's greedy continuation — on a random
    model (no useful matches, proposals degrade to repeat-current) and
    on a trained cyclic model (near-perfect acceptance), across k and
    ngram."""
    from mpi_cuda_cnn_tpu.models.generate import (
        lookup_speculative_generate,
    )

    prompt = jnp.asarray([np.arange(8) % 13], jnp.int32)

    params = MODEL.init(jax.random.key(0))
    want = np.asarray(generate(MODEL, params, prompt, 12))
    for k in (2, 4):
        for ngram in (1, 2):
            got = lookup_speculative_generate(MODEL, params, prompt, 12,
                                              k=k, ngram=ngram)
            np.testing.assert_array_equal(np.asarray(got), want)

    # Trained on the cyclic task: the continuation repeats the prompt's
    # pattern, so lookup proposals should be accepted nearly always —
    # and the output must STILL match plain greedy exactly.
    import optax

    from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step

    opt = optax.adam(1e-2)
    step = make_lm_train_step(MODEL, opt, attn_impl="oracle", seq_len=24)
    state = make_lm_state(MODEL, opt, seed=0)
    rng = np.random.default_rng(0)
    for _ in range(150):
        starts = rng.integers(0, 13, size=(8, 1))
        w = (starts + np.arange(25)[None, :]) % 13
        toks = jnp.asarray(w, jnp.int32)
        state, _ = step(state, toks[:, :-1], toks[:, 1:])
    tp = state["params"]
    # A prompt that already CONTAINS the repetition (1.6 cycles): every
    # continuation n-gram has an earlier occurrence, so lookup proposals
    # hit from the first round.
    rep = jnp.asarray([np.arange(21) % 13], jnp.int32)
    want = np.asarray(generate(MODEL, tp, rep, 20))
    got, stats = lookup_speculative_generate(
        MODEL, tp, rep, 20, k=4, return_stats=True
    )
    np.testing.assert_array_equal(np.asarray(got), want)
    assert stats["mean_accepted"] > 3.0  # lookup really speculates here

    with pytest.raises(ValueError, match="ngram"):
        lookup_speculative_generate(MODEL, params,
                                    jnp.asarray([[1]], jnp.int32), 4,
                                    ngram=2)


def test_generate_shapes_and_budget():
    params = MODEL.init(jax.random.key(0))
    prompt = jnp.asarray([[1, 2, 3], [7, 8, 9]], jnp.int32)
    out = generate(MODEL, params, prompt, 5)
    assert out.shape == (2, 5) and out.dtype == jnp.int32
    with pytest.raises(ValueError, match="max_seq"):
        generate(MODEL, params, prompt, MODEL.max_seq)
    with pytest.raises(ValueError, match="PRNG"):
        generate(MODEL, params, prompt, 2, temperature=1.0)


def test_sampling_deterministic_per_key():
    params = MODEL.init(jax.random.key(0))
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    a = generate(MODEL, params, prompt, 6, temperature=1.0, key=jax.random.key(5))
    b = generate(MODEL, params, prompt, 6, temperature=1.0, key=jax.random.key(5))
    c = generate(MODEL, params, prompt, 6, temperature=1.0, key=jax.random.key(6))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert np.all((np.asarray(a) >= 0) & (np.asarray(a) < MODEL.vocab))


def test_trained_model_generates_the_cycle():
    """Train on the cyclic-successor task, then greedy-decode: the
    continuation must follow token[t+1] = token[t] + 1 (mod vocab)."""
    params = MODEL.init(jax.random.key(2))
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)

    @jax.jit
    def step(params, opt_state, toks):
        def loss_fn(p):
            logits = MODEL.apply(p, toks[:, :-1])
            logp = jax.nn.log_softmax(logits, -1)
            return -jnp.mean(
                jnp.take_along_axis(logp, toks[:, 1:, None], axis=-1)
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    for _ in range(250):
        start = rng.integers(0, 13, (16, 1))
        toks = jnp.asarray((start + np.arange(33)) % 13, jnp.int32)
        params, opt_state, loss = step(params, opt_state, toks)
    assert float(loss) < 0.1, f"did not learn: {float(loss)}"

    prompt = jnp.asarray([[4, 5, 6, 7]], jnp.int32)
    out = np.asarray(generate(MODEL, params, prompt, 8))
    want = (7 + 1 + np.arange(8)) % 13
    np.testing.assert_array_equal(out[0], want)


def test_decode_matches_inference_forward_moe():
    """MoE decode parity: cached per-token decoding must equal the
    teacher-forced forward under the same no-drop inference routing."""
    model = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=32,
                          moe_experts=4)
    params = model.init(jax.random.key(1))
    toks = jnp.asarray(
        np.random.default_rng(2).integers(0, 13, (2, 12)), jnp.int32
    )
    want = model.apply(params, toks, moe_inference=True)

    cache = init_cache(model, 2)
    got = []
    for i in range(12):
        logits, cache = decode_step(model, params, toks[:, i], i, cache)
        got.append(logits)
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_moe_inference_routing_is_per_token():
    """moe_mlp_inference: a token's output must not depend on other
    tokens in the batch (the property capacity dropping violates)."""
    from mpi_cuda_cnn_tpu.parallel.ep import init_moe_params, moe_mlp_inference

    p = init_moe_params(jax.random.key(0), 16, 32, 4)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((8, 16)),
                    jnp.float32)
    full = moe_mlp_inference(x, p, n_experts=4)
    solo = jnp.concatenate([
        moe_mlp_inference(x[i : i + 1], p, n_experts=4) for i in range(8)
    ])
    np.testing.assert_allclose(np.asarray(full), np.asarray(solo),
                               rtol=1e-5, atol=1e-6)


def test_generate_moe_model_runs():
    model = TransformerLM(vocab=13, dim=32, heads=4, depth=1, max_seq=32,
                          moe_experts=4)
    params = model.init(jax.random.key(0))
    prompt = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    out = generate(model, params, prompt, 4)
    assert out.shape == (2, 4)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < 13))


def test_decode_matches_inference_forward_moe_top2():
    """Top-2 MoE decode parity: the KV-cache path must route with the
    model's moe_top_k, not silently fall back to top-1."""
    model = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=32,
                          moe_experts=4, moe_top_k=2)
    params = model.init(jax.random.key(1))
    toks = jnp.asarray(
        np.random.default_rng(2).integers(0, 13, (2, 10)), jnp.int32
    )
    want = model.apply(params, toks, moe_inference=True)

    cache = init_cache(model, 2)
    got = []
    for i in range(10):
        logits, cache = decode_step(model, params, toks[:, i], i, cache)
        got.append(logits)
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_int8_cache_decode_close_and_really_int8():
    """cache_dtype='int8' must (a) actually store k/v as int8 with f32
    absmax scales alongside (the bandwidth lever is the storage bytes),
    and (b) keep the cached decode logits within the quantization error
    band of the f32-cache path — absmax per (position, head) bounds each
    stored element's relative error by 1/254, and the scales are applied
    OUTSIDE the dots (to logits for k, folded into probs for v), so the
    error does not compound."""
    from mpi_cuda_cnn_tpu.models.generate import prefill

    params = MODEL.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(3).integers(0, 13, (2, 12)), jnp.int32
    )
    _, cache8 = prefill(MODEL, params, toks, cache_dtype=jnp.int8)
    assert cache8[0]["k"].dtype == jnp.int8
    assert cache8[0]["v"].dtype == jnp.int8
    assert cache8[0]["ks"].dtype == jnp.float32
    assert cache8[0]["ks"].shape == cache8[0]["k"].shape[:-1] + (1,)

    cache32 = init_cache(MODEL, 2)
    cache8 = init_cache(MODEL, 2, jnp.int8)
    for i in range(12):
        l32, cache32 = decode_step(MODEL, params, toks[:, i], i, cache32)
        l8, cache8 = decode_step(MODEL, params, toks[:, i], i, cache8)
        np.testing.assert_allclose(np.asarray(l8), np.asarray(l32),
                                   rtol=5e-2, atol=5e-2)

    # The generate() surface takes the dtype as a string (the CLI's
    # --decode-cache-dtype form) and still produces valid tokens —
    # including through the speculative path (same decode_block).
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = generate(MODEL, params, prompt, 4, cache_dtype="int8")
    assert out.shape == (1, 4)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < MODEL.vocab))
    from mpi_cuda_cnn_tpu.models.generate import (
        lookup_speculative_generate,
    )

    out = lookup_speculative_generate(MODEL, params, prompt, 4, k=2,
                                      cache_dtype="int8")
    assert out.shape == (1, 4)


def test_filter_logits_top_k_clamps_to_vocab():
    """Direct filter_logits callers with top_k > vocab get the whole
    vocabulary kept (clamp), not an opaque negative-index sort error
    (ADVICE round-4 finding 4)."""
    from mpi_cuda_cnn_tpu.models.generate import filter_logits
    from mpi_cuda_cnn_tpu.ops.attention import NEG_INF

    l = jnp.asarray([[2.0, -1.0, 3.0]])
    out = np.asarray(filter_logits(l, top_k=10))
    assert (out > NEG_INF / 2).all()
    np.testing.assert_allclose(out, np.asarray(l))
