"""Donation verified MECHANICALLY (ISSUE 2 tentpole front 1): for the
CNN scanned-epoch step, the LM step, and the grad-accum step, the
compiled HLO's input_output_alias table + XLA memory analysis must show
the state's buffers aliased input->output (obs.cost.assert_donation) —
"we passed donate_argnums" is not evidence, because a shape/layout
mismatch silently degrades donation to a copy. The accum step's
bytes_accessed is additionally pinned against the pre-PR compile so the
accumulation path cannot quietly grow HBM traffic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs import cost as obs_cost
from mpi_cuda_cnn_tpu.parallel.dp import (
    dp_shard_batch,
    dp_shard_perm,
    make_dp_train_step,
    replicate,
)
from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, make_mesh
from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step

# Pre-PR bytes_accessed of the reference accum config (d64x2, v64, s64,
# b8, grad_accum 4, adamw, donate=True, oracle attention, CPU XLA under
# jax 0.4.37 — the version the number was measured on; scan-body
# counted once, "static-body"). The guard allows 2% headroom for
# cost-model jitter; a real accumulation-path traffic regression lands
# far outside it. The pin only applies on the measured jax version:
# CI installs unpinned jax, and a different XLA's cost model produces a
# legitimately different absolute count with no code change.
ACCUM_BYTES_BASELINE = 33_757_588
ACCUM_BASELINE_JAX = "0.4.37"


def _lm_setup(grad_accum=1, donate=True):
    model = TransformerLM(vocab=64, dim=64, heads=4, depth=2, max_seq=64)
    opt = optax.adamw(1e-3)
    step = make_lm_train_step(
        model, opt, attn_impl="oracle", seq_len=64, donate=donate,
        grad_accum=grad_accum,
    )
    state = make_lm_state(model, opt, 0)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (8, 65)), jnp.int32
    )
    return step, state, toks[:, :-1], toks[:, 1:]


def test_lm_step_state_fully_aliased():
    step, state, tokens, targets = _lm_setup()
    rep = obs_cost.assert_donation(step, state, tokens, targets,
                                   label="lm_step")
    # params + opt_state + step counter all alias: the whole state.
    assert rep["fraction"] == pytest.approx(1.0, abs=0.01)
    assert rep["aliased_outputs"] > 0


def test_lm_accum_step_aliased_and_bytes_pinned():
    step, state, tokens, targets = _lm_setup(grad_accum=4)
    rep = obs_cost.assert_donation(step, state, tokens, targets,
                                   label="lm_accum_step")
    assert rep["fraction"] == pytest.approx(1.0, abs=0.01)
    costs = obs_cost.analyze(step, state, tokens, targets)
    assert costs.bytes_accessed is not None
    if jax.__version__ == ACCUM_BASELINE_JAX:
        assert costs.bytes_accessed <= ACCUM_BYTES_BASELINE * 1.02, (
            f"accum step bytes_accessed {costs.bytes_accessed:.0f} "
            f"regressed past the recorded pre-PR baseline "
            f"{ACCUM_BYTES_BASELINE}"
        )


def test_donation_guard_detects_donate_off():
    step, state, tokens, targets = _lm_setup(donate=False)
    with pytest.raises(AssertionError, match="donation was dropped"):
        obs_cost.assert_donation(step, state, tokens, targets,
                                 label="lm_step_nodonate")


def test_dp_train_step_state_aliased(eight_devices):
    """The shard_map DP step: donation must survive the shard_map +
    jit wrapping (parallel/dp.make_dp_train_step)."""
    mesh = make_mesh({DATA_AXIS: 8}, devices=jax.devices()[:8])

    def loss_fn(params, x, y):
        logits = x.reshape(x.shape[0], -1) @ params["w"] + params["b"]
        p = jax.nn.log_softmax(logits, -1)
        loss = -jnp.mean(jnp.sum(p * y, -1))
        return loss, {"acc": jnp.float32(0)}

    opt = optax.sgd(0.1, momentum=0.9)
    params = {
        "w": jnp.zeros((64, 10), jnp.float32),
        "b": jnp.zeros((10,), jnp.float32),
    }
    state = replicate(
        {"params": params, "opt_state": opt.init(params),
         "step": jnp.zeros((), jnp.int32)}, mesh,
    )
    step = make_dp_train_step(loss_fn, opt, mesh)
    rng = np.random.default_rng(1)
    x = dp_shard_batch(jnp.asarray(
        rng.standard_normal((16, 8, 8, 1)), jnp.float32), mesh)
    y = dp_shard_batch(jnp.asarray(
        jax.nn.one_hot(rng.integers(0, 10, 16), 10)), mesh)
    rep = obs_cost.assert_donation(step, state, x, y, label="dp_step")
    assert rep["fraction"] == pytest.approx(1.0, abs=0.01)


def test_cnn_scan_epoch_state_aliased():
    """The CNN scanned-epoch program — the EXACT program bench.py
    dispatches for the headline metric (Trainer._scan_epoch_fn on the
    reference model): the state threaded through the whole epoch's
    lax.scan must alias in place."""
    from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ds = synthetic_stripes(num_train=128, num_test=32)
    cfg = Config(model="reference_cnn", epochs=1, batch_size=32,
                 eval_every=0, log_every=10**9, num_devices=1)
    t = Trainer(get_model("reference_cnn"), ds, cfg,
                metrics=MetricsLogger(echo=False))
    t._stage_dataset()
    nsteps = t.steps_per_epoch
    perm = (t._epoch_order(0)[: nsteps * cfg.batch_size]
            .reshape(nsteps, cfg.batch_size).astype(np.int32))
    rep = obs_cost.assert_donation(
        t._scan_epoch_fn, t.state, t._dev_images, t._dev_labels,
        dp_shard_perm(perm, t.mesh), label="cnn_scan_epoch",
    )
    assert rep["fraction"] == pytest.approx(1.0, abs=0.01)


def test_program_record_carries_alias_fields():
    """The telemetry side of the guard: log_program's "program" record
    must carry the aliasing ledger so `mctpu report` can show it."""
    step, state, tokens, targets = _lm_setup()

    class Sink:
        rec = None

        def log(self, event, **fields):
            Sink.rec = {"event": event, **fields}

    assert obs_cost.log_program(Sink(), "lm_step", step, state, tokens,
                                targets)
    rec = Sink.rec
    assert rec["event"] == "program"
    assert rec["aliased_outputs"] > 0
    assert rec["alias_bytes"] and rec["alias_bytes"] > 0
