"""IDX parser/writer tests: round-trip + the validation the reference does
(cnn.c:361-363) + rejection of the truncation its other variants silently
trained on (SURVEY.md 2.8)."""

import struct

import numpy as np
import pytest

from mpi_cuda_cnn_tpu.data.idx import IdxError, read_idx, write_idx


def test_roundtrip_images(tmp_path):
    arr = np.arange(2 * 5 * 4, dtype=np.uint8).reshape(2, 5, 4)
    p = tmp_path / "imgs.idx"
    write_idx(p, arr)
    out = read_idx(p)
    np.testing.assert_array_equal(arr, out)
    assert out.dtype == np.uint8


def test_roundtrip_labels(tmp_path):
    arr = np.array([0, 3, 9, 1], dtype=np.uint8)
    p = tmp_path / "labels.idx"
    write_idx(p, arr)
    np.testing.assert_array_equal(arr, read_idx(p))


def test_roundtrip_gzip(tmp_path):
    arr = np.random.default_rng(0).integers(0, 255, (3, 7, 7)).astype(np.uint8)
    p = tmp_path / "imgs.idx.gz"
    write_idx(p, arr)
    with open(p, "rb") as f:
        assert f.read(2) == b"\x1f\x8b"  # actually gzipped
    np.testing.assert_array_equal(arr, read_idx(p))


@pytest.mark.parametrize("dtype", [np.int8, np.int16, np.int32, np.float32, np.float64])
def test_roundtrip_other_dtypes(tmp_path, dtype):
    arr = (np.random.default_rng(1).standard_normal((4, 3)) * 10).astype(dtype)
    p = tmp_path / "t.idx"
    write_idx(p, arr)
    out = read_idx(p)
    assert out.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(arr, out)


def test_bad_magic_rejected(tmp_path):
    p = tmp_path / "bad.idx"
    p.write_bytes(struct.pack(">HBB", 7, 0x08, 1) + struct.pack(">I", 0))
    with pytest.raises(IdxError, match="magic"):
        read_idx(p)


def test_bad_type_code_rejected(tmp_path):
    p = tmp_path / "bad.idx"
    p.write_bytes(struct.pack(">HBB", 0, 0x42, 1) + struct.pack(">I", 0))
    with pytest.raises(IdxError, match="type"):
        read_idx(p)


def test_truncated_payload_rejected(tmp_path):
    """The reference's MPI/CUDA variants malloc the payload and never read
    it (SURVEY.md 2.8) — we must hard-fail instead."""
    p = tmp_path / "trunc.idx"
    p.write_bytes(struct.pack(">HBB", 0, 0x08, 2) + struct.pack(">II", 10, 10) + b"\x00" * 5)
    with pytest.raises(IdxError, match="payload"):
        read_idx(p)


def test_truncated_dims_rejected(tmp_path):
    p = tmp_path / "trunc.idx"
    p.write_bytes(struct.pack(">HBB", 0, 0x08, 3) + struct.pack(">I", 1))
    with pytest.raises(IdxError, match="dimension"):
        read_idx(p)


def test_big_endian_dims(tmp_path):
    """Dims are big-endian u32 (be32toh in the reference, cnn.c:374)."""
    p = tmp_path / "be.idx"
    payload = bytes(range(6))
    p.write_bytes(struct.pack(">HBB", 0, 0x08, 2) + struct.pack(">II", 2, 3) + payload)
    out = read_idx(p)
    assert out.shape == (2, 3)
    assert out[1, 2] == 5
