"""Batched speculative decoding inside the serving engine (ISSUE 14).

The acceptance contract: at T=0 a spec-on engine's per-request outputs
are BITWISE the spec-off engine's — through cache dtypes, prefix
sharing on/off, preemption, crash/failover, and a disaggregated
prefill->decode handoff — while the decode-tick count drops with the
acceptance rate. The greedy acceptance law itself is pinned against
models/generate's jitted core so the two dialects can never drift
(the T>0 law stays generate.py's, gated by test_spec_sampling.py's
distribution-equality tests).
"""

import copy

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.serve.fleet import Fleet, SimCompute, make_fleet_workload
from mpi_cuda_cnn_tpu.serve.scheduler import ContinuousScheduler, Request
from mpi_cuda_cnn_tpu.serve.spec import (
    accept_len,
    empty_spec_fields,
    lookup_propose,
)

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=64)
DRAFT = TransformerLM(vocab=13, dim=16, heads=2, depth=1, max_seq=64)


def _params():
    return MODEL.init(jax.random.key(0))


def _workload(rng, n=5, max_new=16, prompt_len=(4, 10)):
    return [
        Request(rid=i,
                prompt=rng.integers(0, 13, (int(rng.integers(*prompt_len)),))
                .astype(np.int32),
                max_new_tokens=int(rng.integers(4, max_new)))
        for i in range(n)
    ]


def _outputs(res):
    return {r.rid: list(r.out) for r in res.requests}


# -- the shared acceptance core ----------------------------------------


def test_accept_len_matches_generate_acceptance_core():
    """THE no-drift gate: serve/spec.accept_len (numpy host dialect)
    and models/generate._accept_and_emit (the jitted lax dialect the
    B=1 speculative paths run) implement ONE greedy acceptance law.
    Randomized verify-input/target-pick pairs must produce the same
    emitted count j and the same emitted rows."""
    from jax import lax

    from mpi_cuda_cnn_tpu.models.generate import _accept_and_emit

    rng = np.random.default_rng(0)
    for trial in range(64):
        k = int(rng.integers(2, 9))
        u = rng.integers(0, 5, (k,)).astype(np.int32)
        y = rng.integers(0, 5, (k,)).astype(np.int32)
        # Force long accepted prefixes in half the trials (uniform
        # draws rarely match, and the all-accept path must be covered).
        if trial % 2:
            n_match = int(rng.integers(0, k))
            u[1 : 1 + n_match] = y[:n_match]
        j_host = accept_len(u, y)
        out = jnp.zeros((1, k + 8), jnp.int32)
        j_jit, cur, out = _accept_and_emit(
            jnp.asarray(u)[None, :], jnp.asarray(y)[None, :], out, 0
        )
        assert int(j_jit) == j_host, (trial, u, y)
        np.testing.assert_array_equal(
            np.asarray(out)[0, :j_host], y[:j_host], err_msg=str(trial)
        )
        assert int(cur[0]) == y[j_host - 1]


def test_lookup_propose_contract():
    ctx = np.asarray([1, 2, 3, 9, 9, 1, 2], np.int32)
    # Tail 2-gram (1, 2) occurred at positions 0-1 -> proposals follow
    # it: 3, 9, 9.
    np.testing.assert_array_equal(lookup_propose(ctx, 3, 2), [3, 9, 9])
    # No earlier occurrence -> repeat the current token.
    np.testing.assert_array_equal(
        lookup_propose(np.asarray([5, 6, 7], np.int32), 3, 2), [7, 7, 7]
    )
    # Match so late the continuation runs out -> pad with the last
    # available token.
    ctx2 = np.asarray([4, 8, 4, 8], np.int32)  # (4, 8) recurs at the end
    np.testing.assert_array_equal(lookup_propose(ctx2, 3, 2), [4, 8, 8])
    # MOST RECENT occurrence wins.
    ctx3 = np.asarray([1, 2, 5, 1, 2, 6, 1, 2], np.int32)
    np.testing.assert_array_equal(lookup_propose(ctx3, 2, 2), [6, 1])


# -- scheduler: acceptance-aware page accounting -----------------------


def test_scheduler_spec_growth_and_rollback():
    """grow_for_decode(spec_k=) extends a decoding slot's pages toward
    its speculative width WITHOUT preempting; commit_spec commits j
    tokens and rolls pages holding only rejected rows back into the
    pool — ownership-checked, invariant-checked after every step."""
    from mpi_cuda_cnn_tpu.serve.pool import PagePool, pages_for

    pool = PagePool(12)  # 11 usable pages of 4
    sched = ContinuousScheduler(slots=2, pool=pool, page_size=4, max_len=44)
    req = Request(rid=0, prompt=np.arange(6, dtype=np.int32) % 13,
                  max_new_tokens=24)
    sched.submit([req])
    (slot,) = sched.admit(0.0)
    slot.cached = slot.target
    req.out.append(1)
    sched.check()
    # Spec growth: want pages for cached + min(k, remaining) rows.
    dslots = sched.grow_for_decode(0.0, spec_k=8)
    assert dslots == [slot]
    assert len(slot.pages) == pages_for(slot.cached + 8, 4)
    assert sched.spec_width(slot, 8) == 8
    sched.check()
    # Commit 3 of 8: pages past the committed extent (rejected-draft
    # rows only) return to the pool.
    free_before = pool.free_pages
    sched.commit_spec(slot, 3)
    assert slot.cached == slot.target + 3
    assert len(slot.pages) == pages_for(slot.cached, 4)
    assert pool.free_pages > free_before
    sched.check()
    # A dry pool degrades the width instead of preempting: fill the
    # pool with a second request, then grow again.
    req2 = Request(rid=1, prompt=np.arange(4, dtype=np.int32) % 13,
                   max_new_tokens=4)
    sched.submit([req2])
    (slot2,) = sched.admit(0.0)
    blocker = pool.try_alloc(pool.free_pages, "blocker")
    dslots = sched.grow_for_decode(0.0, spec_k=8)
    assert slot in dslots
    assert sched.preemptions == 0          # speculation never evicts
    w = sched.spec_width(slot, 8)
    assert 1 <= w < 8
    pool.free(blocker, "blocker")
    assert not slot2.free                  # untouched by spec growth
    sched.check()


# -- engine parity ------------------------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_engine_spec_on_off_bitwise_parity(dtype):
    """T=0 spec-on outputs are bitwise spec-off's per request, across
    cache dtypes — the tentpole acceptance gate. The spec run must
    also stamp nonzero round counters."""
    params = _params()
    rng = np.random.default_rng(7)
    reqs = _workload(rng, n=5, max_new=18)
    off = PagedEngine(MODEL, params, slots=2, num_pages=31, page_size=8,
                      prefill_chunk=4, max_len=40, cache_dtype=dtype)
    res_off = off.run(copy.deepcopy(reqs), mode="continuous")
    on = PagedEngine(MODEL, params, slots=2, num_pages=31, page_size=8,
                     prefill_chunk=4, max_len=40, cache_dtype=dtype,
                     spec="lookup", spec_k=6)
    res_on = on.run(copy.deepcopy(reqs), mode="continuous", spec=True)
    assert _outputs(res_on) == _outputs(res_off), dtype
    assert res_on.spec["spec_rounds"] > 0
    assert res_on.spec["spec_proposed"] > 0
    assert res_off.spec == empty_spec_fields()


def test_engine_spec_parity_through_preemption_and_prefix():
    """The same bitwise contract through recompute preemption (tiny
    pool) and prefix sharing (shared templates, COW at divergence) —
    the interactions ISSUE 14 forces through the page accounting."""
    params = _params()
    rng = np.random.default_rng(5)
    reqs = _workload(rng, n=6, max_new=16, prompt_len=(5, 9))
    # Preemption leg: a pool far smaller than the worst case.
    off = PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                      prefill_chunk=8, max_len=40)
    r_off = off.run(copy.deepcopy(reqs), mode="continuous")
    assert r_off.preemptions > 0
    on = PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                     prefill_chunk=8, max_len=40, spec="lookup", spec_k=8)
    r_on = on.run(copy.deepcopy(reqs), mode="continuous", spec=True)
    assert _outputs(r_on) == _outputs(r_off)
    # Prefix leg: shared template prompts, sharing on both sides.
    tmpl = rng.integers(0, 13, (12,)).astype(np.int32)
    shared = [
        Request(rid=i,
                prompt=np.concatenate(
                    [tmpl, rng.integers(0, 13, (3,)).astype(np.int32)]),
                max_new_tokens=10)
        for i in range(6)
    ]
    px_off = PagedEngine(MODEL, params, slots=3, num_pages=25, page_size=4,
                         prefill_chunk=4, max_len=40)
    p_off = px_off.run(copy.deepcopy(shared), mode="continuous", prefix=True)
    px_on = PagedEngine(MODEL, params, slots=3, num_pages=25, page_size=4,
                        prefill_chunk=4, max_len=40, spec="lookup",
                        spec_k=8)
    p_on = px_on.run(copy.deepcopy(shared), mode="continuous", prefix=True,
                     spec=True)
    assert p_off.prefix["prefix_hits"] > 0
    assert _outputs(p_on) == _outputs(p_off)


def test_engine_spec_draft_parity():
    """Model-draft behind the same interface: a genuinely different
    draft model changes the speed only — outputs stay the target's
    greedy continuations, bitwise."""
    params = _params()
    dparams = DRAFT.init(jax.random.key(1))
    rng = np.random.default_rng(3)
    reqs = _workload(rng, n=4, max_new=14)
    off = PagedEngine(MODEL, params, slots=2, num_pages=25, page_size=8,
                      prefill_chunk=4, max_len=40)
    r_off = off.run(copy.deepcopy(reqs), mode="continuous")
    on = PagedEngine(MODEL, params, slots=2, num_pages=25, page_size=8,
                     prefill_chunk=4, max_len=40, spec="draft", spec_k=4,
                     draft_model=DRAFT, draft_params=dparams)
    r_on = on.run(copy.deepcopy(reqs), mode="continuous", spec=True)
    assert _outputs(r_on) == _outputs(r_off)
    assert r_on.spec["spec_rounds"] > 0


def test_engine_spec_tick_count_drops_on_template_traffic():
    """The perf pin, CPU-banked: on the --prefix-mix-style template
    workload (greedy continuations of a small random-init model are
    highly repetitive, exactly the regime prompt lookup exists for)
    the spec-on run finishes in strictly fewer decode ticks with a
    nonzero acceptance count."""
    from mpi_cuda_cnn_tpu.serve.bench import make_workload

    params = _params()
    reqs = make_workload(n=10, vocab=13, prompt_min=6, prompt_max=14,
                         out_min=8, out_max=24, rate=0.0, seed=2,
                         prefix_mix=0.9)
    off = PagedEngine(MODEL, params, slots=3, num_pages=40, page_size=8,
                      prefill_chunk=8, max_len=48)
    r_off = off.run(copy.deepcopy(reqs), mode="continuous")
    on = PagedEngine(MODEL, params, slots=3, num_pages=40, page_size=8,
                     prefill_chunk=8, max_len=48, spec="lookup", spec_k=8)
    r_on = on.run(copy.deepcopy(reqs), mode="continuous", spec=True)
    assert _outputs(r_on) == _outputs(r_off)
    assert r_on.decode_ticks < r_off.decode_ticks
    assert r_on.spec["spec_accepted"] > 0


def test_engine_spec_misconfig_raises():
    params = _params()
    with pytest.raises(ValueError, match="spec"):
        PagedEngine(MODEL, params, spec="nope")
    with pytest.raises(ValueError, match="spec_k"):
        PagedEngine(MODEL, params, spec="lookup", spec_k=1)
    with pytest.raises(ValueError, match="draft"):
        PagedEngine(MODEL, params, spec="draft")
    eng = PagedEngine(MODEL, params, slots=2, num_pages=13, page_size=8)
    req = [Request(rid=0, prompt=np.arange(4) % 13, max_new_tokens=4)]
    with pytest.raises(ValueError, match="spec='off'"):
        eng.run(req, mode="continuous", spec=True)
    spec_eng = PagedEngine(MODEL, params, slots=2, num_pages=13,
                           page_size=8, spec="lookup")
    with pytest.raises(ValueError, match="static"):
        spec_eng.run(req, mode="static", spec=True)


# -- fleet: crash/failover and disaggregated handoff --------------------


def test_sim_fleet_spec_parity_determinism_and_crash():
    """Sim fleet: spec-on outputs equal spec-off's (the sim verify is
    the token mix itself), two identical-seed spec runs are bitwise
    equal (trace CRC + spec counters), and a zombie crash changes
    nothing — the committed-token account carries across failover."""
    from mpi_cuda_cnn_tpu.faults import FaultInjector

    def factory(name):
        return SimCompute(vocab=512, chunk=32, salt=0)

    reqs = make_fleet_workload(n=250, vocab=512, prompt_min=8,
                               prompt_max=96, out_min=8, out_max=96,
                               rate=300.0, seed=0, prefix_mix=0.5)

    def run(spec, plan=None):
        fleet = Fleet(
            factory, replicas=3, slots=4, page_size=16, max_len=192,
            spec=spec, spec_k=8,
            faults=FaultInjector(plan) if plan else None,
        )
        return fleet.run(copy.deepcopy(reqs))

    r_off = run("off")
    r_on = run("lookup")
    assert r_on.outputs() == r_off.outputs()
    assert r_on.spec["spec_rounds"] > 0
    r_on2 = run("lookup")
    assert r_on2.trace_crc == r_on.trace_crc
    assert r_on2.spec == r_on.spec
    assert r_on2.status_counts() == r_on.status_counts()
    r_crash = run("lookup",
                  "replica_crash@fleet.tick:40?replica=1&zombie_ticks=3")
    assert r_crash.outputs() == r_off.outputs()
    assert r_crash.crashes == 1
    assert r_crash.redispatches > 0


def test_engine_fleet_spec_crash_parity():
    """Engine-backed fleet: spec-on with a mid-run crash produces the
    crash-free spec-off fleet's outputs per request — the crash/
    failover leg of the ISSUE 14 acceptance gate."""
    from mpi_cuda_cnn_tpu.faults import FaultInjector

    model = TransformerLM(vocab=13, dim=32, heads=2, depth=1, max_seq=64)
    params = model.init(jax.random.key(0))

    def factory_for(spec):
        def factory(name):
            return EngineOf(spec)
        return factory

    def EngineOf(spec):
        from mpi_cuda_cnn_tpu.serve.fleet import EngineCompute

        return EngineCompute(PagedEngine(
            model, params, slots=3, num_pages=31, page_size=8,
            prefill_chunk=8, max_len=56, spec=spec, spec_k=6,
        ))

    reqs = make_fleet_workload(n=24, vocab=13, prompt_min=4, prompt_max=12,
                               out_min=4, out_max=20, rate=200.0, seed=1)
    base = Fleet(factory_for("off"), replicas=2, slots=3, num_pages=31,
                 page_size=8, max_len=56)
    r_base = base.run(copy.deepcopy(reqs))
    crash = Fleet(factory_for("lookup"), replicas=2, slots=3, num_pages=31,
                  page_size=8, max_len=56, spec="lookup", spec_k=6,
                  faults=FaultInjector(
                      "replica_crash@fleet.tick:30?replica=0"))
    r_crash = crash.run(copy.deepcopy(reqs))
    assert r_crash.crashes == 1
    assert r_crash.outputs() == r_base.outputs()
    assert r_crash.spec["spec_rounds"] > 0


def test_engine_disagg_spec_parity_through_handoff():
    """Disaggregated pools with speculation on the decode side: the
    handed-off page sets decode speculatively and the outputs stay
    bitwise the unified spec-off fleet's — the through-a-handoff leg
    of the acceptance gate."""
    from mpi_cuda_cnn_tpu.serve.fleet import EngineCompute

    model = TransformerLM(vocab=13, dim=32, heads=2, depth=1, max_seq=64)
    params = model.init(jax.random.key(0))

    def factory_for(spec):
        def factory(name):
            return EngineCompute(PagedEngine(
                model, params, slots=3, num_pages=31, page_size=8,
                prefill_chunk=8, max_len=56, spec=spec, spec_k=6,
            ))
        return factory

    reqs = make_fleet_workload(n=20, vocab=13, prompt_min=4, prompt_max=12,
                               out_min=4, out_max=20, rate=200.0, seed=4)
    unified = Fleet(factory_for("off"), replicas=2, slots=3, num_pages=31,
                    page_size=8, max_len=56)
    r_uni = unified.run(copy.deepcopy(reqs))
    disagg = Fleet(factory_for("lookup"), slots=3, num_pages=31,
                   page_size=8, max_len=56, spec="lookup", spec_k=6,
                   pools={"prefill": 1, "decode": 1}, handoff_ticks=2)
    r_dis = disagg.run(copy.deepcopy(reqs))
    assert r_dis.handoffs > 0
    assert r_dis.outputs() == r_uni.outputs()
    assert r_dis.spec["spec_rounds"] > 0


# -- observability ------------------------------------------------------


def test_spec_tick_records_trace_and_report(tmp_path):
    """Tick records carry the spec round detail, `mctpu trace`'s token
    cross-check stays exact under variable-length commits (exit 0),
    and the report's serve table renders the acceptance column."""
    from mpi_cuda_cnn_tpu.obs.report import render_markdown, summarize
    from mpi_cuda_cnn_tpu.obs.schema import dump_records, make_record
    from mpi_cuda_cnn_tpu.obs.timeline import reconstruct, trace_main

    params = _params()
    rng = np.random.default_rng(9)
    reqs = _workload(rng, n=4, max_new=14)
    eng = PagedEngine(MODEL, params, slots=2, num_pages=25, page_size=8,
                      prefill_chunk=4, max_len=40, spec="lookup", spec_k=6)
    records = []

    def sink(rec):
        records.append(make_record("tick", rec["now"], **rec))

    res = eng.run(reqs, mode="continuous", spec=True, tick_sink=sink)
    assert any(r.get("spec") for r in records)
    for rec in res.request_records():
        records.append(make_record("request", 1.0, **rec))
    records.append(make_record("serve", 1.0, bench="serve",
                               **res.summary()))
    path = tmp_path / "spec_run.jsonl"
    dump_records(records, path)
    # Lifecycle reconstruction: token account exact, spec counters up.
    lcs = reconstruct(records)["continuous"]
    assert all(lc.consistent for lc in lcs.values())
    assert sum(lc.spec_rounds for lc in lcs.values()) \
        == res.spec["spec_rounds"]
    assert sum(lc.spec_accepted for lc in lcs.values()) \
        == res.spec["spec_accepted"]
    assert trace_main([str(path)]) == 0
    # Report: the serving table's acceptance column.
    md = render_markdown(summarize(records))
    assert "spec accept" in md
    prop, acc = res.spec["spec_proposed"], res.spec["spec_accepted"]
    assert f"{100.0 * acc / prop:.1f}%" in md


def test_spec_registry_metrics():
    """The serve.spec.* registry family: round/proposal/acceptance
    counters plus the accepted-per-round histogram."""
    from mpi_cuda_cnn_tpu.obs.metrics import MetricsRegistry

    params = _params()
    rng = np.random.default_rng(11)
    reqs = _workload(rng, n=4, max_new=12)
    eng = PagedEngine(MODEL, params, slots=2, num_pages=25, page_size=8,
                      prefill_chunk=4, max_len=40, spec="lookup", spec_k=6)
    registry = MetricsRegistry()
    res = eng.run(reqs, mode="continuous", spec=True, registry=registry)
    assert registry.counters["serve.spec.rounds"].value \
        == res.spec["spec_rounds"]
    assert registry.counters["serve.spec.proposed"].value \
        == res.spec["spec_proposed"]
    assert registry.counters["serve.spec.accepted_total"].value \
        == res.spec["spec_accepted"]
    h = registry.histograms["serve.spec.accepted"]
    assert h.count == res.spec["spec_rounds"]


def test_serve_bench_cli_spec_e2e_and_compare_flattening(tmp_path):
    """`mctpu serve-bench --spec lookup` end-to-end: strict-valid
    JSONL, spec fields stamped in the serve summary, and `mctpu
    compare` flattening exposes serve.<mode>.spec_* metrics."""
    from mpi_cuda_cnn_tpu.obs.regress import metrics_from_records
    from mpi_cuda_cnn_tpu.obs.schema import load_records
    from mpi_cuda_cnn_tpu.serve.bench import serve_bench_main

    sink = tmp_path / "serve_spec.jsonl"
    rc = serve_bench_main([
        "--requests", "6", "--dim", "32", "--depth", "1", "--heads", "2",
        "--vocab", "64", "--max-seq", "128", "--prompt-min", "4",
        "--prompt-max", "12", "--out-min", "4", "--out-max", "12",
        "--slots", "2", "--page-size", "8", "--prefill-chunk", "8",
        "--mode", "continuous", "--spec", "lookup", "--spec-k", "4",
        "--metrics-jsonl", str(sink),
    ])
    assert rc == 0
    recs = load_records(sink, strict=True)
    serve = [r for r in recs if r["event"] == "serve"]
    assert serve and serve[-1]["spec"] == "lookup"
    assert serve[-1]["spec_rounds"] > 0
    flat = metrics_from_records(recs)
    for k in ("spec_rounds", "spec_proposed", "spec_accepted"):
        assert f"serve.continuous.{k}" in flat
    # Config errors exit 2 with one-line messages.
    assert serve_bench_main(["--spec", "lookup", "--mode", "static"]) == 2
    assert serve_bench_main(["--spec", "lookup", "--spec-k", "1"]) == 2
