"""Goodput observatory + `mctpu autosize` (ISSUE 16).

THE acceptance tests live here:
- sweep determinism: two identical-(seed, spec) autosize sweeps are
  bitwise-identical — emitted record file, rendered frontier, and the
  recommendation CRC — and pass the CI gate (ci/autosize_gate.json)
  at 0%/equal;
- blame-seeded pruning: a --seed-from profile evaluates measurably
  fewer candidates than the exhaustive sweep while selecting the SAME
  recommendation (equal recommendation_crc);
- harness transparency: the unified candidate's trace/blame/state CRCs
  equal a same-config `mctpu fleet-bench` run's — the sweep harness
  changes nothing about the storms it measures;
- goodput math: the exact (terminal-trail) and histogram-estimate
  paths agree on the checked-in sample run, and the joint good/bad
  judgment treats an unmeasured latency moment as not-good;
- --len-dist stream isolation: the default uniform workload stream is
  bitwise-unchanged (pinned CRC), and tenant labels are invariant
  across mixes (the heavy-tail draws come from a separate spawn);
- the `mctpu report` goodput-frontier rendering is byte-pinned against
  the checked-in golden (regenerate via scripts/make_obs_sample.py).
"""

import json
import zlib
from pathlib import Path

import pytest

from mpi_cuda_cnn_tpu.obs.autosize import (
    autosize_main,
    blame_profile,
    candidate_topologies,
    dominant_category,
    seeded_topologies,
)
from mpi_cuda_cnn_tpu.obs.goodput import (
    default_goodput_spec,
    goodput_from_records,
    is_good,
    tenant_goodput_rps,
)
from mpi_cuda_cnn_tpu.obs.regress import compare_main
from mpi_cuda_cnn_tpu.obs.report import report_main
from mpi_cuda_cnn_tpu.obs.schema import load_records
from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main, make_workload

REPO = Path(__file__).resolve().parents[1]
DATA = REPO / "tests" / "data"

# The canonical pinned workload-stream CRC (arrival, prompt len, output
# len, tenant) at seed 0 — the bitwise-unchanged contract every len_dist
# change must preserve for the DEFAULT stream.
WORKLOAD_KW = dict(n=8, vocab=64, prompt_min=8, prompt_max=96,
                   out_min=8, out_max=96, rate=50.0, seed=0, tenants=2)
WORKLOAD_CRC = 1883835671


def _canon(reqs):
    return [[round(r.arrival, 9), int(r.prompt.size), r.max_new_tokens,
             r.tenant] for r in reqs]


# ------------------------------------------- len-dist stream isolation


def test_len_dist_default_stream_bitwise_pinned():
    """The default (and explicit uniform) workload stream is bitwise
    what it was before --len-dist existed — committed baselines and
    every pinned tick count stay valid."""
    base = make_workload(**WORKLOAD_KW)
    uni = make_workload(**WORKLOAD_KW, len_dist="uniform")
    crc = zlib.crc32(json.dumps(_canon(base)).encode())
    assert crc == WORKLOAD_CRC
    assert _canon(uni) == _canon(base)


def test_len_dist_lognormal_differs_but_tenants_invariant():
    """The heavy-tail mix draws lengths from a separate (seed, 3)
    spawn: lengths change, the tenant stream never moves."""
    base = make_workload(**WORKLOAD_KW)
    log = make_workload(**WORKLOAD_KW, len_dist="lognormal")
    assert _canon(log) != _canon(base)
    assert [r.tenant for r in log] == [r.tenant for r in base]
    lens = [int(r.prompt.size) for r in log]
    assert all(8 <= v <= 96 for v in lens)  # clipped to the range
    with pytest.raises(ValueError):
        make_workload(**WORKLOAD_KW, len_dist="zipf")


# ------------------------------------------------------- goodput math


def test_is_good_joint_over_all_latency_objectives():
    """A request is good iff finished AND every declared latency
    objective holds; an unmeasured moment is NOT good (goodput is a
    guarantee, and an unmeasured TTFT guarantees nothing)."""
    spec = default_goodput_spec(ttft_ms=100.0, tpot_ms=10.0)
    ok = {"status": "finished", "ttft_ms": 50.0, "tpot_ms": 5.0}
    assert is_good(ok, spec)
    assert not is_good({**ok, "status": "expired"}, spec)
    assert not is_good({**ok, "tpot_ms": 10.1}, spec)   # one axis blown
    assert not is_good({**ok, "ttft_ms": None}, spec)   # unmeasured
    assert is_good({**ok, "ttft_ms": 100.0}, spec)      # at threshold


def test_goodput_exact_vs_estimate_agree_on_sample(monkeypatch):
    """The histogram-estimate path (summary-only files) agrees with the
    exact terminal-trail path on the checked-in sample run — same
    request totals, good count within one, and the fidelity flag set."""
    monkeypatch.chdir(REPO)
    recs = load_records("tests/data/sample_serve_run.jsonl")
    spec = default_goodput_spec(ttft_ms=200.0, tpot_ms=50.0)
    exact = goodput_from_records(recs, spec)
    summary_only = [r for r in recs
                    if r.get("event") not in ("tick", "request")]
    est = goodput_from_records(summary_only, spec)
    assert not exact.estimated and est.estimated
    assert est.requests == exact.requests
    assert abs(est.good - exact.good) <= 1
    assert est.duration_s == exact.duration_s


def test_tenant_goodput_rps_shares_the_one_is_good(monkeypatch):
    """The health column's per-tenant fold: exact-trail only, None for
    tenants whose spec declares no latency objectives."""
    monkeypatch.chdir(REPO)
    recs = load_records("tests/data/sample_serve_run.jsonl")
    spec = default_goodput_spec(ttft_ms=200.0, tpot_ms=50.0)
    per = tenant_goodput_rps(recs, spec)
    assert set(per) == {"t0", "t1"}
    assert all(v is not None and v >= 0 for v in per.values())
    # Availability-only spec: the column is em-dash (None), not zero —
    # no latency objectives means goodput is undefined, not absent.
    from mpi_cuda_cnn_tpu.obs.slo import default_spec
    assert all(v is None
               for v in tenant_goodput_rps(recs, default_spec()).values())
    # Summary-only file: no exact trail, no estimate — empty.
    summary_only = [r for r in recs
                    if r.get("event") not in ("tick", "request")]
    assert tenant_goodput_rps(summary_only, spec) == {}


# -------------------------------------------------- candidate grammar


def test_seeded_topologies_prune_rules():
    """The blame-dominance pruning grammar, pinned: each dominant
    category keeps unified plus its implicated split family, ordered
    decode-heaviest first."""
    assert candidate_topologies(4) == [
        ("unified", None), ("1:3", {"prefill": 1, "decode": 3}),
        ("2:2", {"prefill": 2, "decode": 2}),
        ("3:1", {"prefill": 3, "decode": 1})]

    def names(dom):
        return [t[0] for t in seeded_topologies(4, dom)]

    assert names(None) == ["unified", "1:3", "2:2", "3:1"]
    assert names("handoff_wait") == ["unified", "1:3"]
    assert names("queued_behind") == ["unified", "2:2"]
    assert names("preempted_by") == ["unified", "1:3", "2:2"]

    assert dominant_category({"handoff_wait": 5, "queued_behind": 3}) \
        == "handoff_wait"
    # Tie resolves toward the earlier SEED_CATEGORIES entry.
    assert dominant_category({"handoff_wait": 5, "queued_behind": 5}) \
        == "handoff_wait"
    # All-zero profile: nothing to seed from.
    assert dominant_category({"handoff_wait": 0}) is None
    assert blame_profile([{"event": "tick"}]) is None


# --------------------------------------- sweep determinism + CI gate


def _sweep(tmp_path, tag, extra=()):
    out = tmp_path / f"{tag}.jsonl"
    rc = autosize_main(["--budget", "3", "--requests", "120",
                        "--rate", "200", "--seed", "0",
                        "--metrics-jsonl", str(out), *extra])
    assert rc == 0
    return out


def test_autosize_sweep_determinism_bitwise_and_gate(tmp_path, capsys,
                                                     monkeypatch):
    """Two identical-(seed, spec) sweeps are bitwise-identical — record
    file AND rendered frontier (recommendation CRC included) — and the
    CI gate holds them to 0%/equal."""
    a = _sweep(tmp_path, "a")
    out_a = capsys.readouterr().out
    b = _sweep(tmp_path, "b")
    out_b = capsys.readouterr().out
    assert a.read_bytes() == b.read_bytes()
    assert out_a == out_b
    assert "recommendation crc:" in out_a
    monkeypatch.chdir(REPO)
    assert compare_main([str(a), str(b),
                         "--gate", "ci/autosize_gate.json"]) == 0
    capsys.readouterr()


def test_autosize_blame_seeded_prunes_same_recommendation(tmp_path,
                                                          capsys):
    """--seed-from evaluates measurably fewer candidates than the
    exhaustive sweep while selecting the SAME recommendation (equal
    recommendation_crc) — the whole point of reading telemetry before
    burning sweep compute."""
    rc = autosize_main(["--budget", "3", "--requests", "120",
                        "--rate", "200", "--seed", "0",
                        "--format", "json"])
    assert rc == 0
    full = json.loads(capsys.readouterr().out)

    profile = tmp_path / "profile.jsonl"
    profile.write_text(json.dumps(
        {"schema": 1, "event": "blame", "t": 1.0, "mode": "fleet",
         "requests": 120,
         "categories": {"handoff_wait": 900, "queued_behind": 10,
                        "preempted_by": 0}}) + "\n")
    rc = autosize_main(["--budget", "3", "--requests", "120",
                        "--rate", "200", "--seed", "0",
                        "--seed-from", str(profile),
                        "--format", "json"])
    assert rc == 0
    pruned = json.loads(capsys.readouterr().out)

    assert pruned["seeded_from"] == "handoff_wait"
    assert pruned["evaluated"] < full["evaluated"]
    assert pruned["pruned"] > 0
    assert pruned["recommendation"]["cand"] == \
        full["recommendation"]["cand"]
    assert pruned["recommendation_crc"] == full["recommendation_crc"]
    # Pruning reorders/drops candidates, so the FRONTIER crc differs —
    # only the recommendation is promised stable.
    assert pruned["frontier_crc"] != full["frontier_crc"]


def test_autosize_frontier_rediscovers_one_three_over_two_two(capsys):
    """The frontier reproduces PERF.md's hand-found disagg conclusion:
    at this decode-heavy mix the 1:3 split outranks 2:2 — the same
    ordering the 20k-request banked table shows, pinned here at tier-1
    scale so a ranking regression can't hide behind determinism."""
    rc = autosize_main(["--budget", "4", "--requests", "120",
                        "--rate", "200", "--seed", "0",
                        "--format", "json"])
    assert rc == 0
    res = json.loads(capsys.readouterr().out)
    rank = {r["cand"]: i for i, r in enumerate(res["frontier"])}
    assert rank["1:3/fcfs/uniform/noprefix/off"] < \
        rank["2:2/fcfs/uniform/noprefix/off"]


def test_autosize_storm_crc_parity_with_fleet_bench(tmp_path, capsys):
    """The sweep harness is transparent: the unified candidate's
    trace/blame/state CRCs equal a same-config fleet-bench run's —
    autosize changes nothing about the storms it measures."""
    rc = autosize_main(["--budget", "3", "--requests", "120",
                        "--rate", "200", "--seed", "0",
                        "--format", "json"])
    assert rc == 0
    sweep = json.loads(capsys.readouterr().out)
    unified = next(r for r in sweep["frontier"]
                   if r["topology"] == "unified")

    run = tmp_path / "fleet.jsonl"
    rc = fleet_bench_main(["--replicas", "3", "--requests", "120",
                           "--rate", "200", "--seed", "0",
                           "--log", "summary",
                           "--metrics-jsonl", str(run)])
    assert rc == 0
    capsys.readouterr()
    serve = next(r for r in load_records(run)
                 if r.get("event") == "serve")
    assert unified["trace_crc"] == serve["trace_crc"]
    assert unified["state_crc"] == serve["state_crc"]
    assert unified["blame_crc"] == serve["blame_crc"]
    assert unified["tokens_per_s"] == serve["tokens_per_s"]


# ------------------------------------------------------- error paths


def test_autosize_error_paths(tmp_path, capsys):
    """Budget < 2 and a --seed-from file without a blame record are
    config errors (exit 2), not silent exhaustive fallbacks."""
    assert autosize_main(["--budget", "1"]) == 2
    assert "nothing to decide" in capsys.readouterr().err
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps(
        {"schema": 1, "event": "epoch", "t": 0.0, "epoch": 0,
         "seconds": 1.0}) + "\n")
    assert autosize_main(["--budget", "2", "--requests", "8",
                          "--seed-from", str(empty)]) == 2
    assert "no blame record" in capsys.readouterr().err
    assert autosize_main(["--budget", "2", "--requests", "8",
                          "--seed-from", str(tmp_path / "nope.jsonl")]) \
        == 2


# -------------------------------------------------- golden round-trip


def test_golden_autosize_roundtrip(monkeypatch, capsys):
    """`mctpu report` on the checked-in autosize sample run is
    byte-for-byte the golden (regenerate via
    scripts/make_obs_sample.py)."""
    monkeypatch.chdir(REPO)
    assert report_main(["tests/data/sample_autosize_run.jsonl"]) == 0
    assert capsys.readouterr().out == \
        (DATA / "golden_serve_autosize.md").read_text()
