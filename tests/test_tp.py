"""Tensor-parallelism tests on the 8-device virtual CPU mesh.

The reference has no TP (SURVEY.md §2 checklist: every rank holds all
params, cnnmpi.c:93-103); parallel/tp.py adds it over the 'model' mesh
axis the GSPMD way. These tests pin down the two things that matter:
(1) params are REALLY sharded (per-device bytes shrink), and (2) the
TP(+DP) result equals the single-device result — parallelism must be a
layout choice, not a numerics choice.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
from mpi_cuda_cnn_tpu.models.initializers import get_initializer
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.parallel.mesh import MODEL_AXIS, make_mesh
from mpi_cuda_cnn_tpu.parallel.tp import (
    make_tp_state,
    make_tp_train_step,
    shard_batch_2d,
    tp_param_specs,
)
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
from mpi_cuda_cnn_tpu.train.trainer import Trainer, make_loss_fn
from mpi_cuda_cnn_tpu.utils.config import Config
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _quiet():
    return MetricsLogger(echo=False)


def _batch(batch=16, seed=42):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.random((batch, 28, 28, 1), np.float32))
    y = np.zeros((batch, 10), np.float32)
    y[np.arange(batch), rng.integers(0, 10, batch)] = 1.0
    return x, jnp.asarray(y)


def test_param_specs_shard_divisible_features(eight_devices):
    mesh = make_mesh({"data": 2, "model": 4}, devices=eight_devices)
    model = get_model("reference_cnn")
    specs = tp_param_specs(model, mesh)
    # conv16, conv32, fc200, fc200 divide 4 -> sharded on the last dim.
    assert specs[0]["w"] == P(None, None, None, MODEL_AXIS)
    assert specs[2]["w"] == P(None, MODEL_AXIS)
    assert specs[2]["b"] == P(MODEL_AXIS)
    # the 10-class head does not divide 4 -> replicated.
    assert specs[4]["w"] == P()


def test_params_really_sharded(eight_devices):
    mesh = make_mesh({"data": 2, "model": 4}, devices=eight_devices)
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    opt = make_optimizer(0.1, momentum=0.9)
    state = make_tp_state(model, params, opt, mesh)
    w = state["params"][2]["w"]  # fc200: (1568, 200) sharded to (1568, 50)
    shard_shape = w.addressable_shards[0].data.shape
    assert shard_shape == (w.shape[0], w.shape[1] // 4)
    # momentum buffers inherit the same sharding leaf-for-leaf.
    mom = jax.tree.leaves(state["opt_state"])
    assert any(
        getattr(m, "sharding", None) == w.sharding and m.shape == w.shape
        for m in mom
    )


def test_tp_step_matches_single_device(eight_devices):
    """One train step on a data:2 x model:4 mesh == the same step on one
    device: TP+DP is a layout, not different math."""
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    opt = make_optimizer(0.1)
    loss_fn = make_loss_fn(model)
    x, y = _batch()

    mesh = make_mesh({"data": 2, "model": 4}, devices=eight_devices)
    state = make_tp_state(model, params, opt, mesh)
    step = make_tp_train_step(loss_fn, opt, donate=False)
    xs, ys = shard_batch_2d((x, y), mesh)
    tp_state, tp_metrics = step(state, xs, ys)

    ref_state = {"params": params, "opt_state": opt.init(params),
                 "step": jnp.zeros((), jnp.int32)}
    ref_state, ref_metrics = step(ref_state, x, y)

    np.testing.assert_allclose(
        float(tp_metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
    )
    for a, b in zip(
        jax.tree.leaves(jax.device_get(tp_state["params"])),
        jax.tree.leaves(jax.device_get(ref_state["params"])),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("scan", [True, False])
def test_tp_trainer_end_to_end(eight_devices, scan):
    """Trainer on mesh data:2,model:4 trains and converges; both the
    scanned and per-batch paths."""
    ds = synthetic_stripes(num_train=512, num_test=128)
    cfg = Config(
        epochs=2, eval_every=0, log_every=10**9, scan=scan,
        mesh_shape="data:2,model:4", num_devices=8,
    )
    t = Trainer(get_model("reference_cnn"), ds, cfg, metrics=_quiet())
    assert t.n_model == 4
    r = t.train()
    assert r.test_accuracy >= 0.95


def test_tp_resume_keeps_sharding(eight_devices, tmp_path):
    """Checkpoint resume on a TP mesh must re-place the restored state with
    the model-axis shardings, not fall back to full replication."""
    ds = synthetic_stripes(num_train=128, num_test=32)
    base = dict(eval_every=0, log_every=10**9, mesh_shape="data:2,model:4",
                num_devices=8, checkpoint_dir=str(tmp_path / "ck"))
    Trainer(get_model("reference_cnn"), ds, Config(epochs=1, **base),
            metrics=_quiet()).train()
    t2 = Trainer(get_model("reference_cnn"), ds,
                 Config(epochs=2, resume=True, **base), metrics=_quiet())
    t2.train()
    w = t2.state["params"][2]["w"]
    assert w.addressable_shards[0].data.shape == (w.shape[0], w.shape[1] // 4)


def test_tp_trainer_matches_dp_trainer(eight_devices):
    """Same seed, same data: the TP(+DP) trainer and the pure-DP trainer
    land on near-identical params after an epoch."""
    ds = synthetic_stripes(num_train=256, num_test=32)
    base = dict(epochs=1, seed=5, eval_every=0, log_every=10**9, scan=True)
    t_tp = Trainer(
        get_model("reference_cnn"), ds,
        Config(mesh_shape="data:2,model:4", num_devices=8, **base),
        metrics=_quiet(),
    )
    t_tp.train()
    t_dp = Trainer(
        get_model("reference_cnn"), ds,
        Config(mesh_shape="data", num_devices=8, **base),
        metrics=_quiet(),
    )
    t_dp.train()
    for a, b in zip(
        jax.tree.leaves(jax.device_get(t_tp.state["params"])),
        jax.tree.leaves(jax.device_get(t_dp.state["params"])),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# LM tensor parallelism (lm_tp_specs / make_lm_tp_state)
# ---------------------------------------------------------------------------


def _lm_pieces(seed=3):
    import optax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=64)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(rng.integers(0, 32, (4, 33)), jnp.int32)
    return model, opt, toks[:, :-1], toks[:, 1:]


def test_lm_tp_specs_shard_the_big_matmuls(eight_devices):
    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.tp import lm_tp_specs

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=1, max_seq=64)
    mesh = make_mesh({"data": 2, MODEL_AXIS: 4}, devices=jax.devices()[:8])
    specs = lm_tp_specs(model, mesh)
    blk = specs["blocks"][0]
    assert blk["wqkv"] == P(None, MODEL_AXIS)   # column parallel
    assert blk["w1"] == P(None, MODEL_AXIS)
    assert blk["w2"] == P(MODEL_AXIS, None)     # row parallel
    assert blk["wo"] == P(MODEL_AXIS, None)
    assert specs["head"] == P(None, MODEL_AXIS)  # vocab parallel
    assert specs["tok_emb"] == P(MODEL_AXIS, None)
    assert specs["ln_f"]["g"] == P()


def test_lm_tp_state_is_sharded_and_step_matches_serial(eight_devices):
    """TP placement must be a layout choice: one LM step on a
    (data:2, model:4) mesh == the single-device step (loss AND params),
    and the MLP kernel is REALLY 4-way sharded on device."""
    from mpi_cuda_cnn_tpu.parallel.tp import make_lm_tp_state
    from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step

    model, opt, tokens, targets = _lm_pieces()
    step = make_lm_train_step(model, opt, attn_impl="oracle", seq_len=32,
                              donate=False)

    base = make_lm_state(model, opt, seed=0)
    want_state, want_m = step(base, tokens, targets)

    mesh = make_mesh({"data": 2, MODEL_AXIS: 4}, devices=jax.devices()[:8])
    tp_state = make_lm_tp_state(
        model, model.init(jax.random.key(0)), opt, mesh
    )
    w1 = tp_state["params"]["blocks"][0]["w1"]  # (32, 128) -> shard cols
    assert w1.addressable_shards[0].data.shape == (32, 128 // 4)
    from jax.sharding import NamedSharding

    xs = jax.device_put(tokens, NamedSharding(mesh, P("data")))
    ys = jax.device_put(targets, NamedSharding(mesh, P("data")))
    got_state, got_m = step(tp_state, xs, ys)

    np.testing.assert_allclose(
        float(got_m["loss"]), float(want_m["loss"]), rtol=1e-5
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(got_state["params"])),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_lm_trainer_accepts_model_axis(eight_devices):
    """End to end: the lm product loop trains on a data:2,model:4 mesh."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig

    cfg = LMConfig(corpus="synthetic", dim=32, depth=1, heads=4,
                   seq_len=64, steps=10, batch_size=4, log_every=0,
                   lr_schedule="constant", warmup_steps=0,
                   mesh_shape="data:2,model:4")
    r = LMTrainer(cfg, metrics=MetricsLogger(echo=False)).train()
    assert r.steps_run == 10 and np.isfinite(r.final_loss)


def test_lm_model_and_seq_axes_route_to_tp_sp(eight_devices):
    """A model+seq mesh routes to the Megatron x ring step (round 3:
    parallel/tp_sp.py — the former hard rejection); incompatible knobs
    still fail loudly at setup."""
    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig

    base = dict(corpus="synthetic", dim=32, depth=1, heads=4,
                seq_len=64, steps=3, batch_size=4, log_every=0,
                lr_schedule="constant", warmup_steps=0)
    t = LMTrainer(LMConfig(mesh_shape="model:2,seq:4", **base),
                  metrics=MetricsLogger(echo=False))
    assert t.attn_impl == "ring"
    with pytest.raises(ValueError, match="fsdp"):
        LMTrainer(LMConfig(mesh_shape="model:2,seq:4", fsdp=True, **base),
                  metrics=MetricsLogger(echo=False))
    # Ulysses composes with TP x SP now (round 4) — but its divisibility
    # (TP-local heads % n_seq) still fails loudly: 4/2 = 2 local heads
    # over seq:4.
    with pytest.raises(ValueError, match="ulysses"):
        LMTrainer(LMConfig(mesh_shape="model:2,seq:4", attn_impl="ulysses",
                           **base), metrics=MetricsLogger(echo=False))
    t3 = LMTrainer(LMConfig(mesh_shape="model:2,seq:2",
                            attn_impl="ulysses", **base),
                   metrics=MetricsLogger(echo=False))
    assert t3.attn_impl == "ulysses"
    # An explicit ring/ring_flash request is honored, not auto-overridden.
    t2 = LMTrainer(LMConfig(mesh_shape="model:2,seq:2", attn_impl="ring",
                            **base), metrics=MetricsLogger(echo=False))
    assert t2.attn_impl == "ring"


def test_tp_sharded_decode_matches_single_device(eight_devices):
    """Sharded serving (parallel/tp.shard_lm_params): generate()'s
    prefill + KV-cached decode scan partitioned by GSPMD from the
    Megatron placement alone must emit EXACTLY the single-device tokens
    (greedy), with the weights really sharded over 'model'."""
    from mpi_cuda_cnn_tpu.models.generate import generate
    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.tp import shard_lm_params

    from mpi_cuda_cnn_tpu.models.generate import prefill

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=32)
    params = model.init(jax.random.key(3))
    rng = np.random.default_rng(4)
    prompt = jnp.asarray(rng.integers(0, 32, (2, 8)), jnp.int32)

    want = generate(model, params, prompt, 8)

    mesh = make_mesh({MODEL_AXIS: 4}, devices=jax.devices()[:4])
    tp_params = shard_lm_params(model, params, mesh)
    w1 = tp_params["blocks"][0]["w1"]  # (32, 128): columns over 4
    assert w1.addressable_shards[0].data.shape == (32, 128 // 4)

    # Row-parallel matmuls change float reduction order, so guard the
    # greedy-token equality: the prefill logits must agree to float
    # tolerance AND the single-device top-2 gap must dwarf that noise
    # (random init at vocab 32: gaps ~1e-1 vs reduction noise ~1e-6).
    lw, _ = prefill(model, params, prompt)
    lg, _ = prefill(model, tp_params, prompt)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(lw),
                               rtol=1e-5, atol=1e-5)
    top2 = np.sort(np.asarray(lw), axis=-1)[:, -2:]
    assert (top2[:, 1] - top2[:, 0]).min() > 1e-3

    got = generate(model, tp_params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_sharded_decode_int8_cache(eight_devices):
    """The round-5 int8 KV cache composes with sharded serving: the
    quantized cache + scale buffers are created INSIDE the jitted decode
    program, so GSPMD partitions them from the Megatron placement like
    any other decode intermediate. Tokens must match the single-device
    int8-cache run exactly (same quantization, same math, different
    layout)."""
    from mpi_cuda_cnn_tpu.models.generate import generate
    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.tp import shard_lm_params

    from mpi_cuda_cnn_tpu.models.generate import prefill

    model = TransformerLM(vocab=32, dim=32, heads=4, depth=2, max_seq=32)
    params = model.init(jax.random.key(3))
    rng = np.random.default_rng(4)
    prompt = jnp.asarray(rng.integers(0, 32, (2, 8)), jnp.int32)

    want = generate(model, params, prompt, 8, cache_dtype="int8")

    mesh = make_mesh({MODEL_AXIS: 4}, devices=jax.devices()[:4])
    tp_params = shard_lm_params(model, params, mesh)
    # Same reduction-order guard as the sibling f32 test: token equality
    # is only meaningful while the top-2 logit gap dwarfs the TP
    # row-parallel float noise (int8 adds a second tie hazard — a k/v
    # value at a .5 quantization boundary could round differently).
    lw, _ = prefill(model, params, prompt)
    lg, _ = prefill(model, tp_params, prompt)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(lw),
                               rtol=1e-5, atol=1e-5)
    top2 = np.sort(np.asarray(lw), axis=-1)[:, -2:]
    assert (top2[:, 1] - top2[:, 0]).min() > 1e-3
    got = generate(model, tp_params, prompt, 8, cache_dtype="int8")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
