"""Checkpoint save/restore round-trip (capability absent from the
reference, SURVEY.md §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.initializers import get_initializer
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.train.checkpoint import (
    AsyncCheckpointer,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer


def _state(seed=0, momentum=0.9):
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(seed), get_initializer("normal"))
    opt = make_optimizer(0.1, momentum=momentum)
    return {"params": params, "opt_state": opt.init(params),
            "step": jnp.asarray(7, jnp.int32)}


def test_roundtrip(tmp_path):
    state = _state()
    save_checkpoint(tmp_path, state, 7)
    template = _state(seed=1)  # different values, same structure
    restored = restore_checkpoint(latest_checkpoint(tmp_path), template)
    for a, b in zip(jax.tree.leaves(jax.device_get(state)), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_picks_numeric_max(tmp_path):
    state = _state()
    for step in (2, 10, 9):
        save_checkpoint(tmp_path, state, step)
    assert latest_checkpoint(tmp_path).name == "ckpt_10.npz"


def test_prune_keeps_k(tmp_path):
    state = _state()
    for step in range(6):
        save_checkpoint(tmp_path, state, step, keep=3)
    names = sorted(p.name for p in tmp_path.glob("ckpt_*.npz"))
    assert names == ["ckpt_3.npz", "ckpt_4.npz", "ckpt_5.npz"]


def test_structure_mismatch_raises(tmp_path):
    save_checkpoint(tmp_path, {"a": jnp.zeros(3)}, 1)
    with pytest.raises(ValueError, match="mismatch"):
        restore_checkpoint(latest_checkpoint(tmp_path), {"b": jnp.zeros(3)})


def test_no_checkpoint_returns_none(tmp_path):
    assert latest_checkpoint(tmp_path / "void") is None


@pytest.mark.parametrize("async_", [True, False])
def test_async_checkpointer_matches_sync(tmp_path, async_):
    """The background writer must produce byte-identical checkpoints to
    the synchronous path; wait() guarantees the file has landed."""
    state = _state()
    ck = AsyncCheckpointer(tmp_path / "a", async_=async_)
    ck.save(state, 3)
    ck.save(state, 6)  # drains the first write before snapshotting
    ck.wait()
    assert latest_checkpoint(tmp_path / "a").name == "ckpt_6.npz"
    restored = restore_checkpoint(
        latest_checkpoint(tmp_path / "a"), _state(seed=1)
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(state)),
                    jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_checkpointer_snapshot_precedes_mutation(tmp_path):
    """save() must snapshot synchronously: mutating (donating) the state
    right after save() cannot corrupt the written checkpoint."""
    state = {"a": jnp.arange(4, dtype=jnp.float32)}
    ck = AsyncCheckpointer(tmp_path)
    ck.save(state, 1)
    state["a"] = state["a"] * 0 - 1  # "donated"/overwritten immediately
    ck.wait()
    restored = restore_checkpoint(
        latest_checkpoint(tmp_path), {"a": jnp.zeros(4)}
    )
    np.testing.assert_array_equal(np.asarray(restored["a"]),
                                  np.arange(4, dtype=np.float32))


def test_async_checkpointer_propagates_errors(tmp_path):
    """A failed background write re-raises at the next wait() — it cannot
    pass silently."""
    target = tmp_path / "f"
    ck = AsyncCheckpointer(target)
    ck.save(_state(), 1)
    ck.wait()
    # Make the directory unwritable by replacing it with a file.
    import shutil

    shutil.rmtree(target)
    target.write_text("not a directory")
    ck.save(_state(), 2)
    with pytest.raises(OSError):
        ck.wait()
