"""Fused Pallas paged-attention kernel + int8 decode-weight GEMVs
(ISSUE 12): parity with the gather path — BITWISE in f32, within the
1e-5 band in bf16/int8 — across MHA/GQA/MQA and decode/prefill query
widths, the paged-layout edge cases the gather hides, and the
quantized-weight error bound. Everything runs the real kernels in
Pallas interpret mode on CPU (tier-1 scope)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.generate import (
    _quant_kv,
    decode_step,
    generate,
    init_cache,
    pick_cache_dtype,
    pick_weights_dtype,
)
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.ops.pallas_gemv import (
    QuantW,
    dequantize_weight,
    int8_gemv,
    qmatmul,
    quantize_decode_params,
    quantize_weight,
)
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.serve.paged_cache import (
    init_paged_cache,
    paged_update_attend,
    pages_for,
)
from mpi_cuda_cnn_tpu.serve.scheduler import Request

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)
GQA = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48,
                    kv_heads=2, pos="rope")
MQA = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48,
                    kv_heads=1, pos="rope")

HEAD_CONFIGS = {"mha": 4, "gqa": 2, "mqa": 1}


def _rand_case(dtype, hkv, kk, seed, *, b=3, h=4, hd=8, ps=4, per=5,
               pool=16):
    """One random paged-attention call: q/k/v for the incoming tokens,
    a populated page pool, per-slot block tables of distinct non-scratch
    pages, and in-range positions. Returns (inputs..., call kwargs)."""
    rng = np.random.default_rng(seed)
    L = per * ps
    q = jnp.asarray(rng.normal(size=(b, kk, h, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, kk, hkv, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, kk, hkv, hd)), jnp.float32)
    kv = rng.normal(size=(2, pool, ps, hkv, hd)).astype(np.float32)
    if dtype == "int8":
        qk, sk = _quant_kv(jnp.asarray(kv[0]).reshape(1, pool * ps, hkv, hd))
        qv, sv = _quant_kv(jnp.asarray(kv[1]).reshape(1, pool * ps, hkv, hd))
        c = {"k": qk.reshape(pool, ps, hkv, hd),
             "ks": sk.reshape(pool, ps, hkv, 1),
             "v": qv.reshape(pool, ps, hkv, hd),
             "vs": sv.reshape(pool, ps, hkv, 1)}
    else:
        dt = jnp.dtype(dtype)
        c = {"k": jnp.asarray(kv[0], dt), "v": jnp.asarray(kv[1], dt)}
    table = np.zeros((b, per), np.int32)
    for i in range(b):
        table[i] = rng.choice(np.arange(1, pool), per, replace=False)
    pos0 = rng.integers(0, L - kk, (b, 1))
    positions = jnp.asarray(pos0 + np.arange(kk)[None, :], jnp.int32)
    return q, k, v, c, jnp.asarray(table), positions, ps


def _both(q, k, v, c, table, positions, ps):
    valid = jnp.ones(positions.shape, bool)
    og, _ = paged_update_attend(dict(c), q, k, v, positions, valid,
                                table, ps, kernel="gather")
    op, _ = paged_update_attend(dict(c), q, k, v, positions, valid,
                                table, ps, kernel="pallas")
    return np.asarray(og), np.asarray(op)


@pytest.mark.parametrize("kk", [1, 4], ids=["decode", "chunk"])
@pytest.mark.parametrize("head", ["mha", "gqa", "mqa"])
def test_kernel_matches_gather_f32_bitwise(head, kk):
    """THE f32 gate: the fused kernel's output equals the gather path's
    BITWISE — every contraction mirrors attend_kv's formulation, so any
    drift is a layout/indexing bug, not rounding. Covers the decode
    tick (kk=1) and the chunked-prefill query width (kk=4) at every
    head mapping."""
    for seed in range(3):
        want, got = _both(*_rand_case("float32", HEAD_CONFIGS[head], kk,
                                      seed))
        np.testing.assert_array_equal(got, want,
                                      err_msg=f"{head} kk={kk} seed={seed}")


@pytest.mark.parametrize("kk", [1, 4], ids=["decode", "chunk"])
@pytest.mark.parametrize("head", ["mha", "gqa", "mqa"])
@pytest.mark.parametrize("dtype", ["bfloat16", "int8"])
def test_kernel_matches_gather_quantized(dtype, head, kk):
    """bf16/int8 pages: identical elementwise math (same absmax
    contract, scales applied outside the dots), reduction order differs
    by at most the page split — the 1e-5 band of the existing
    quantized paged-vs-contiguous parity."""
    for seed in range(3):
        want, got = _both(*_rand_case(dtype, HEAD_CONFIGS[head], kk, seed))
        np.testing.assert_allclose(
            got, want, rtol=1e-5, atol=1e-5,
            err_msg=f"{dtype} {head} kk={kk} seed={seed}")


def _identity_paged_cache(model, batch, page_size, dtype=jnp.float32,
                          kernel="gather"):
    per = pages_for(model.max_seq, page_size)
    cache = init_paged_cache(model, slots=batch,
                             num_pages=batch * per + 1,
                             page_size=page_size, dtype=dtype,
                             kernel=kernel)
    table = 1 + np.arange(batch * per, dtype=np.int32).reshape(batch, per)
    return dataclasses.replace(cache, block_table=jnp.asarray(table))


@pytest.mark.parametrize("model", [MODEL, GQA], ids=["mha", "gqa_rope"])
def test_paged_kernel_decode_step_matches_contiguous_f32(model):
    """Transitivity of the layout contracts: kernel == gather (this
    file's bitwise gate) and gather == contiguous (test_serve's), so
    decode_step over a kernel="pallas" cache must equal the contiguous
    cache BITWISE through a 20-step decode, page boundaries crossed
    mid-sequence."""
    params = model.init(jax.random.key(0))
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, 13, (3, 20)), jnp.int32
    )
    cc = init_cache(model, 3)
    pc = _identity_paged_cache(model, 3, page_size=8, kernel="pallas")
    for i in range(20):
        want, cc = decode_step(model, params, toks[:, i], i, cc)
        got, pc = decode_step(model, params, toks[:, i],
                              jnp.full((3,), i, jnp.int32), pc)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"step {i}")


def test_slot_extent_ending_mid_page():
    """A slot whose extent ends mid-page must mask the page's written
    tail out of the softmax: corrupting rows BEYOND the slot's position
    (same page, later offsets) changes nothing; corrupting the position
    row itself does. The gather hides this case behind XLA's masked
    reads — the kernel's VMEM strip must reproduce it."""
    q, k, v, c, table, _, ps = _rand_case("float32", 2, 1, 7)
    # DISJOINT tables for this test: the poison targets one slot's page
    # tail, so no other slot may share that physical page.
    table = jnp.asarray(
        1 + np.arange(3 * 5, dtype=np.int32).reshape(3, 5) % 15)
    positions = jnp.asarray([[ps + 1], [2 * ps + 2], [1]], jnp.int32)
    want, got = _both(q, k, v, c, table, positions, ps)
    np.testing.assert_array_equal(got, want)
    # Poison the offsets just past each slot's position, inside the
    # same (mid-extent) page — outputs must not move.
    poisoned = dict(c)
    tab = np.asarray(table)
    for s, pos in enumerate(np.asarray(positions)[:, 0]):
        page = tab[s, pos // ps]
        off = pos % ps
        if off + 1 < ps:
            poisoned = {
                n: poisoned[n].at[page, off + 1:].set(1e30)
                if n in ("k", "v") else poisoned[n]
                for n in poisoned
            }
    want2, got2 = _both(q, k, v, poisoned, table, positions, ps)
    np.testing.assert_array_equal(got2, got)
    np.testing.assert_array_equal(want2, want)


def test_scratch_page_never_read():
    """Block-table columns beyond a slot's live pages hold 0 — the
    scratch page. Its contents are masked out of every softmax, so
    poisoning page 0 with huge finite values must not move any output
    (kernel and gather alike). This is the page-0 contract the pool
    invariants assume."""
    q, k, v, c, table, _, ps = _rand_case("float32", 2, 1, 11)
    # Short extents: positions inside page 1 of 5, so table columns
    # 2..4 are dead weight — point them at scratch like the engine does.
    tab = np.asarray(table).copy()
    tab[:, 2:] = 0
    positions = jnp.asarray([[ps - 1], [2], [ps + 1]], jnp.int32)
    want, got = _both(q, k, v, c, jnp.asarray(tab), positions, ps)
    poisoned = {n: (c[n].at[0].set(1e30) if n in ("k", "v") else c[n])
                for n in c}
    want2, got2 = _both(q, k, v, poisoned, jnp.asarray(tab), positions, ps)
    np.testing.assert_array_equal(got2, got)
    np.testing.assert_array_equal(want2, want)


def test_cow_private_page_read_after_copy():
    """The COW discipline (ISSUE 9) on the kernel path: after a page is
    copied src -> dst and the slot's table repointed at dst, the kernel
    must read the COPY — later writes to the shared source must not
    leak into the reader. Mirrors engine.copy_page's per-layer
    .at[dst].set(c[src]) exactly."""
    q, k, v, c, table, positions, ps = _rand_case("float32", 2, 1, 13)
    tab = np.asarray(table).copy()
    src = int(tab[0, 0])
    dst = 15  # a free pool page outside every table
    assert not (tab == dst).any()
    copied = {n: c[n].at[dst].set(c[n][src]) for n in c}
    tab2 = tab.copy()
    tab2[0, 0] = dst
    want_before, got_before = _both(q, k, v, copied, jnp.asarray(tab2),
                                    positions, ps)
    np.testing.assert_array_equal(got_before, want_before)
    # Diverge the source AFTER the copy: the dst reader sees nothing.
    diverged = {n: (copied[n].at[src].set(-7.0) if n in ("k", "v")
                    else copied[n]) for n in copied}
    want_after, got_after = _both(q, k, v, diverged, jnp.asarray(tab2),
                                  positions, ps)
    np.testing.assert_array_equal(got_after, got_before)
    np.testing.assert_array_equal(want_after, want_before)


def test_preempted_then_resumed_slot_kernel_on():
    """Recompute preemption under a starved pool, with the fused kernel
    serving every read: the resumed slot re-prefills into DIFFERENT
    physical pages, and its greedy stream must still equal generate()'s
    — the block-table indirection is the only thing that changed."""
    params = MODEL.init(jax.random.key(1))
    rng = np.random.default_rng(5)
    engine = PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                         prefill_chunk=8, max_len=40, attn_kernel="pallas")
    prompts = [rng.integers(0, 13, (6,)).astype(np.int32) for _ in range(5)]
    want = [np.asarray(generate(MODEL, params, jnp.asarray(p[None, :]),
                                18))[0] for p in prompts]
    reqs = [Request(rid=i, prompt=p, max_new_tokens=18)
            for i, p in enumerate(prompts)]
    res = engine.run(reqs, mode="continuous")
    assert res.preemptions > 0
    for r in res.requests:
        np.testing.assert_array_equal(np.asarray(r.out), want[r.rid],
                                      err_msg=f"request {r.rid}")


def test_randomized_block_table_fuzz_kernel_equals_gather():
    """Seeded fuzz over the block-table space: random pool sizes, page
    sizes, table permutations (slots may SHARE pages — the prefix-
    sharing read pattern), ragged per-slot depths, MHA/GQA/MQA — kernel
    == gather bitwise in f32, every draw."""
    rng = np.random.default_rng(1234)
    for trial in range(12):
        hkv = int(rng.choice([1, 2, 4]))
        ps = int(rng.choice([2, 4, 8]))
        per = int(rng.integers(2, 6))
        pool = per * 3 + 2
        b = int(rng.integers(1, 4))
        kk = int(rng.choice([1, 2]))
        L = per * ps
        q = jnp.asarray(rng.normal(size=(b, kk, 4, 8)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, kk, hkv, 8)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, kk, hkv, 8)), jnp.float32)
        c = {"k": jnp.asarray(rng.normal(size=(pool, ps, hkv, 8)),
                              jnp.float32),
             "v": jnp.asarray(rng.normal(size=(pool, ps, hkv, 8)),
                              jnp.float32)}
        # Pages drawn WITH replacement across slots: shared pages are
        # legal reads (refcounted prefix pages).
        table = jnp.asarray(
            rng.integers(1, pool, (b, per)), jnp.int32)
        positions = jnp.asarray(
            rng.integers(0, L - kk + 1, (b, 1))
            + np.arange(kk)[None, :], jnp.int32)
        want, got = _both(q, k, v, c, table, positions, ps)
        np.testing.assert_array_equal(
            got, want, err_msg=f"trial {trial}: hkv={hkv} ps={ps} "
                               f"per={per} b={b} kk={kk}")


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_engine_greedy_matches_generate_kernel_on(dtype):
    """End-to-end engine-vs-generate greedy equality with the fused
    kernel serving both jitted programs (prefill chunks AND decode
    ticks), across cache dtypes and both scheduler modes — the same
    acceptance the gather path holds in test_serve.py."""
    params = MODEL.init(jax.random.key(0))
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 13, (n,)).astype(np.int32)
               for n in (3, 7, 11, 5)]
    new = [9, 4, 12, 7]
    want = [
        np.asarray(generate(MODEL, params, jnp.asarray(p[None, :]), n,
                            cache_dtype=dtype))[0]
        for p, n in zip(prompts, new)
    ]
    engine = PagedEngine(MODEL, params, slots=2, num_pages=4 * 6 + 1,
                         page_size=8, prefill_chunk=4, cache_dtype=dtype,
                         attn_kernel="pallas")
    for mode in ("continuous", "static"):
        reqs = [Request(rid=i, prompt=p, max_new_tokens=n)
                for i, (p, n) in enumerate(zip(prompts, new))]
        res = engine.run(reqs, mode=mode)
        for r in res.requests:
            np.testing.assert_array_equal(
                np.asarray(r.out), want[r.rid],
                err_msg=f"{mode} request {r.rid} ({dtype})")


def test_engine_vs_generate_with_both_levers_on():
    """THE both-levers acceptance: Pallas paged read + int8 decode
    weights in the engine, against generate() running the SAME
    quantized params over the contiguous cache — greedy streams equal
    per request (one forward implementation, two storage formats)."""
    params = GQA.init(jax.random.key(3))
    qparams = quantize_decode_params(params, "int8")
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, 13, (n,)).astype(np.int32)
               for n in (4, 9, 6)]
    new = [8, 5, 11]
    want = [np.asarray(generate(GQA, qparams, jnp.asarray(p[None, :]), n,
                                cache_dtype="int8"))[0]
            for p, n in zip(prompts, new)]
    engine = PagedEngine(GQA, params, slots=2, num_pages=4 * 6 + 1,
                         page_size=8, prefill_chunk=4, cache_dtype="int8",
                         attn_kernel="pallas", weights_dtype="int8")
    assert engine.weights_dtype == "int8"
    assert isinstance(engine.params["head"], QuantW)
    reqs = [Request(rid=i, prompt=p, max_new_tokens=n)
            for i, (p, n) in enumerate(zip(prompts, new))]
    res = engine.run(reqs, mode="continuous")
    for r in res.requests:
        np.testing.assert_array_equal(np.asarray(r.out), want[r.rid],
                                      err_msg=f"request {r.rid}")


def test_int8_weights_logit_error_bound():
    """int8 decode weights hold the same error discipline as the int8
    KV cache (test_generate's 5e-2 pin): per-channel absmax bounds each
    weight's relative error by 1/254 and the scales are exact f32
    multiplies outside the dots, so cached decode logits stay within
    the quantization band of the f32-weight path at every step."""
    params = MODEL.init(jax.random.key(0))
    qparams = quantize_decode_params(params, "int8")
    assert isinstance(qparams["blocks"][0]["wqkv"], QuantW)
    assert qparams["blocks"][0]["wqkv"].q.dtype == jnp.int8
    # Non-GEMV leaves stay untouched (gathers/layernorms).
    assert qparams["tok_emb"].dtype == jnp.float32
    assert qparams["blocks"][0]["ln1"]["g"].dtype == jnp.float32
    toks = jnp.asarray(
        np.random.default_rng(3).integers(0, 13, (2, 12)), jnp.int32
    )
    c32 = init_cache(MODEL, 2)
    c8 = init_cache(MODEL, 2)
    for i in range(12):
        l32, c32 = decode_step(MODEL, params, toks[:, i], i, c32)
        l8, c8 = decode_step(MODEL, qparams, toks[:, i], i, c8)
        np.testing.assert_allclose(np.asarray(l8), np.asarray(l32),
                                   rtol=5e-2, atol=5e-2,
                                   err_msg=f"step {i}")


def test_int8_gemv_matches_dequantized_matmul():
    """The fused GEMV's contract is (x @ q) * s — the scale stays
    OUTSIDE the contraction (the absmax discipline; it is constant
    along the contracted din). Pin it against the same jnp formulation
    to float rounding, and against the scale-inside dequantized matmul
    within the reassociation band, across tile counts (dout both
    128-divisible and not)."""
    rng = np.random.default_rng(0)
    for n, din, dout in ((8, 64, 256), (3, 32, 48), (1, 128, 128)):
        x = jnp.asarray(rng.normal(size=(n, din)), jnp.float32)
        w = quantize_weight(jnp.asarray(rng.normal(size=(din, dout)),
                                        jnp.float32))
        got = np.asarray(int8_gemv(x, w))
        want = np.asarray((x @ w.q.astype(jnp.float32)) * w.s)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
        # Scale-inside (x @ dequant) reassociates one multiply — same
        # value to ~1 ulp of the accumulated dot.
        np.testing.assert_allclose(got,
                                   np.asarray(x @ dequantize_weight(w)),
                                   rtol=1e-5, atol=1e-5)
        # qmatmul dispatch: QuantW routes to the kernel, arrays to @.
        np.testing.assert_allclose(np.asarray(qmatmul(x, w)), got,
                                   rtol=0, atol=0)
        plain = jnp.asarray(rng.normal(size=(din, dout)), jnp.float32)
        np.testing.assert_array_equal(np.asarray(qmatmul(x, plain)),
                                      np.asarray(x @ plain))


def test_quantize_weight_error_bound():
    """Per-channel absmax: every dequantized weight within
    max|w_col|/254 of the original, per column."""
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(64, 96)), jnp.float32)
    qw = quantize_weight(w)
    err = np.abs(np.asarray(dequantize_weight(qw)) - np.asarray(w))
    bound = np.max(np.abs(np.asarray(w)), axis=0) / 254.0 + 1e-7
    assert (err <= bound[None, :]).all()


def test_pick_weights_dtype_routing_shares_table_with_cache():
    """The two auto routers live on ONE table (_AUTO_DTYPE_ROUTING):
    weights route int8 under GQA/MQA (weight stream dominates once the
    cache is int8) and float32 at MHA (measured bf16-weights non-win);
    cache routes int8/bfloat16 as banked. Explicit dtypes pass through
    both."""
    from mpi_cuda_cnn_tpu.models.generate import _AUTO_DTYPE_ROUTING

    assert set(_AUTO_DTYPE_ROUTING) == {"cache", "weights"}
    assert pick_weights_dtype("auto", heads=8, kv_heads=2) == "int8"
    assert pick_weights_dtype("auto", heads=8, kv_heads=1) == "int8"
    assert pick_weights_dtype("auto", heads=8, kv_heads=None) == "float32"
    assert pick_weights_dtype("auto", heads=8, kv_heads=8) == "float32"
    assert pick_weights_dtype("bfloat16", heads=8, kv_heads=1) == "bfloat16"
    assert pick_cache_dtype("auto", heads=8, kv_heads=2) == "int8"
    assert pick_cache_dtype("auto", heads=8, kv_heads=None) == "bfloat16"


def test_bad_kernel_and_weights_dtype_rejected():
    params = MODEL.init(jax.random.key(0))
    with pytest.raises(ValueError, match="kernel"):
        init_paged_cache(MODEL, slots=1, num_pages=4, page_size=4,
                         kernel="fused")
    with pytest.raises(ValueError, match="decode weights dtype"):
        PagedEngine(MODEL, params, slots=1, num_pages=4, page_size=4,
                    weights_dtype="fp8")
