"""LM training path (train/lm.py): mixed precision, attention impl
selection, and the single-device train step the MFU bench runs.

The SP (sharded) LM step is covered by test_transformer.py; this file
covers the plain jitted step and the bf16 numerics contract: master
params f32, matmuls in compute_dtype, loss softmax in f32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.train.lm import (
    get_attn_fn,
    lm_flops_per_token,
    lm_loss,
    make_lm_state,
    make_lm_train_step,
    pick_attn_impl,
)
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer

MODEL = TransformerLM(vocab=31, dim=32, heads=4, depth=2, max_seq=128)


def _data(batch=4, s=128, seed=0):
    rng = np.random.default_rng(seed)
    start = rng.integers(0, MODEL.vocab, size=(batch, 1))
    toks = (start + np.arange(s + 1)[None, :]) % MODEL.vocab
    return (jnp.asarray(toks[:, :-1], jnp.int32),
            jnp.asarray(toks[:, 1:], jnp.int32))


def test_bf16_loss_close_to_f32():
    params = MODEL.init(jax.random.key(0))
    tokens, targets = _data()
    l32 = float(lm_loss(MODEL, params, tokens, targets))
    lbf = float(lm_loss(MODEL, params, tokens, targets,
                        compute_dtype=jnp.bfloat16))
    assert abs(l32 - lbf) < 0.05 * abs(l32)


def test_bf16_keeps_master_params_f32():
    """A bf16 step must update f32 master params (mixed precision, not
    low-precision storage)."""
    opt = make_optimizer(1e-3, opt="adamw")
    step = make_lm_train_step(MODEL, opt, attn_impl="oracle",
                              compute_dtype=jnp.bfloat16, donate=False)
    state = make_lm_state(MODEL, opt, 0)
    state2, m = step(state, *_data())
    assert jnp.isfinite(m["loss"])
    for leaf in jax.tree.leaves(state2["params"]):
        assert leaf.dtype == jnp.float32
    # And the params actually moved.
    moved = jax.tree.map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))),
        state["params"], state2["params"],
    )
    assert max(jax.tree.leaves(moved)) > 0


def test_train_step_learns_cyclic_task():
    """200 AdamW steps on the deterministic successor task should drive
    the loss near zero — the step optimizes, not just runs."""
    opt = make_optimizer(3e-3, opt="adamw")
    step = make_lm_train_step(MODEL, opt, attn_impl="oracle")
    state = make_lm_state(MODEL, opt, 0)
    tokens, targets = _data()
    for _ in range(200):
        state, m = step(state, tokens, targets)
    assert float(m["loss"]) < 0.3


def test_flash_impl_matches_oracle_in_step():
    """One train step with the fused flash kernel (interpret mode on CPU)
    == one step with the quadratic oracle."""
    opt = make_optimizer(1e-3, opt="adamw")
    tokens, targets = _data(batch=2, s=128)
    outs = {}
    for impl in ("oracle", "flash"):
        step = make_lm_train_step(MODEL, opt, attn_impl=impl, donate=False)
        state = make_lm_state(MODEL, opt, 0)
        state, m = step(state, tokens, targets)
        outs[impl] = (float(m["loss"]), state["params"])
    assert outs["oracle"][0] == pytest.approx(outs["flash"][0], rel=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        ),
        outs["oracle"][1], outs["flash"][1],
    )


def test_pick_attn_impl(monkeypatch):
    # On the CPU test backend "auto" must not pick the interpret-mode
    # flash kernel (orders of magnitude slower than XLA).
    assert pick_attn_impl("auto", 2048) == "oracle"
    assert pick_attn_impl("flash", 2048) == "flash"
    with pytest.raises(ValueError):
        get_attn_fn("nope")


def test_pick_attn_impl_routing_table(monkeypatch):
    """Pin "auto" to the measured crossovers (one v5e): bf16 -> flash at
    any 128-aligned s (wins 2.2x at s=2048, round-4 capture: 56.4 vs
    125.7 ms/step); f32 -> flash from s=3072 up (round-4
    bench_crossover, two captures: flash wins both runs at every point
    in {3072, 4096, 6144}; s=2048 flips run-to-run, so it routes to the
    oracle with the rest of the short/noise band); unaligned s ->
    oracle always."""
    from mpi_cuda_cnn_tpu.train import lm as lm_mod

    monkeypatch.setattr(lm_mod.jax, "default_backend", lambda: "tpu")
    bf16 = jnp.bfloat16
    assert pick_attn_impl("auto", 2048, bf16) == "flash"
    assert pick_attn_impl("auto", 128, bf16) == "flash"
    assert pick_attn_impl("auto", 1024, None) == "oracle"       # f32 short
    assert pick_attn_impl("auto", 2048, None) == "oracle"       # f32 flip zone
    assert pick_attn_impl("auto", 2048, jnp.float32) == "oracle"
    assert pick_attn_impl("auto", 3072, None) == "flash"        # f32 crossover
    assert pick_attn_impl("auto", 4096, None) == "flash"        # f32 long
    assert pick_attn_impl("auto", 8192, jnp.float32) == "flash"
    assert pick_attn_impl("auto", 2000, bf16) == "oracle"       # unaligned
    # Explicit impls are never overridden.
    assert pick_attn_impl("oracle", 8192, bf16) == "oracle"
    assert pick_attn_impl("flash", 2048, None) == "flash"


@pytest.mark.parametrize("dtype", [None, jnp.bfloat16])
def test_chunked_ce_matches_dense(dtype):
    """ce_chunk fuses the head into a scanned chunked cross-entropy; it
    must be an implementation choice, not a different loss: value AND
    gradients match the dense (B,S,V)-logits path."""
    from mpi_cuda_cnn_tpu.train.lm import lm_loss

    params = MODEL.init(jax.random.key(1))
    rng = np.random.default_rng(4)
    toks = jnp.asarray(rng.integers(0, MODEL.vocab, (2, 33)), jnp.int32)
    tokens, targets = toks[:, :-1], toks[:, 1:]

    def loss(ce_chunk):
        return lambda p: lm_loss(
            MODEL, p, tokens, targets, compute_dtype=dtype,
            ce_chunk=ce_chunk,
        )

    tol = dict(rtol=2e-5, atol=1e-6) if dtype is None else \
        dict(rtol=2e-2, atol=2e-3)
    l_dense, g_dense = jax.value_and_grad(loss(0))(params)
    l_chunk, g_chunk = jax.value_and_grad(loss(8))(params)
    np.testing.assert_allclose(float(l_dense), float(l_chunk), **tol)
    for a, b in zip(jax.tree.leaves(g_dense), jax.tree.leaves(g_chunk)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **tol)

    with pytest.raises(ValueError, match="must divide"):
        loss(7)(params)


def test_flops_accounting_scales():
    small = lm_flops_per_token(MODEL, 128)
    # Double depth ~= double the per-layer FLOPs share.
    deep = lm_flops_per_token(
        TransformerLM(vocab=31, dim=32, heads=4, depth=4, max_seq=128), 128
    )
    assert deep > small
    # fwd+bwd = 3x fwd: per-token FLOPs must exceed 6x params-ex-embedding.
    d, l = MODEL.dim, MODEL.depth
    assert small > 6 * (12 * d * d) * l
