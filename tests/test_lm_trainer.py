"""LMTrainer + the `lm` CLI subcommand (train/lm_trainer.py, cli.run_lm).

The product surface of the long-context path: corpus loading, the
data/seq mesh dispatch (plain step vs shard_map SP step), checkpointing,
and eval perplexity.
"""

import numpy as np
import pytest

from mpi_cuda_cnn_tpu.cli import main
from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer, load_corpus
from mpi_cuda_cnn_tpu.utils.config import LMConfig
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _cfg(**kw):
    base = dict(
        corpus="synthetic", dim=32, depth=2, heads=4, seq_len=64,
        steps=20, batch_size=4, log_every=0, lr_schedule="constant",
        warmup_steps=0, num_devices=1,
    )
    if "mesh_shape" in kw:
        base.pop("num_devices")  # mesh tests use all 8 virtual devices
    base.update(kw)
    return LMConfig(**base)


def test_load_corpus_self_is_real_text():
    toks = load_corpus("self")
    assert len(toks) > 10_000
    # It is the package's own source: ASCII-dominated, contains newlines.
    assert toks.max() < 256 and (toks == ord("\n")).sum() > 100


def test_load_corpus_rejects_tiny_file(tmp_path):
    p = tmp_path / "tiny.txt"
    p.write_text("too small")
    with pytest.raises(ValueError, match="too small"):
        load_corpus(str(p))


def test_single_device_trains_and_evals():
    r = LMTrainer(_cfg(), metrics=MetricsLogger(echo=False)).train()
    assert r.steps_run == 20
    assert np.isfinite(r.final_loss) and np.isfinite(r.eval_ppl)


def test_sp_mesh_learns_synthetic_cycle():
    """seq:8 mesh on the deterministic successor corpus: loss must drop
    well below ln(vocab) — the SP step is optimizing, not just running."""
    cfg = _cfg(mesh_shape="seq:8", seq_len=128, steps=150, lr=3e-3)
    r = LMTrainer(cfg, metrics=MetricsLogger(echo=False)).train()
    assert r.final_loss < 2.0  # ln(251) ~ 5.5 at init


def test_data_seq_mesh_with_moe():
    cfg = _cfg(mesh_shape="data:2,seq:4", moe_experts=8, seq_len=128)
    r = LMTrainer(cfg, metrics=MetricsLogger(echo=False)).train()
    assert np.isfinite(r.final_loss)


def test_checkpoint_resume_continues_at_step(tmp_path):
    ck = str(tmp_path / "ck")
    cfg = _cfg(steps=10, checkpoint_dir=ck, checkpoint_every=5)
    LMTrainer(cfg, metrics=MetricsLogger(echo=False)).train()
    cfg2 = _cfg(steps=15, checkpoint_dir=ck, resume=True)
    r = LMTrainer(cfg2, metrics=MetricsLogger(echo=False)).train()
    assert r.steps_run == 5  # resumed at 10, ran to 15


def test_sample_batch_is_step_derived():
    """Sampling at step k depends only on (seed, k): a trainer that never
    ran steps 0..k-1 draws the same windows as one that did, so a resumed
    run continues the uninterrupted run's exact data order (the LM twin of
    the CNN trainer's (seed, epoch)-derived shuffle)."""
    a = LMTrainer(_cfg(), metrics=MetricsLogger(echo=False))
    b = LMTrainer(_cfg(), metrics=MetricsLogger(echo=False))
    for _ in range(3):  # advance a's stream-independence: draw step 7 late
        a._sample_batch(0)
    ta, _ = a._sample_batch(7)
    tb, _ = b._sample_batch(7)
    np.testing.assert_array_equal(np.asarray(ta), np.asarray(tb))
    t0, _ = b._sample_batch(8)
    assert not np.array_equal(np.asarray(ta), np.asarray(t0))


def test_seq_len_must_divide():
    with pytest.raises(ValueError, match="not divisible"):
        LMTrainer(_cfg(mesh_shape="seq:8", seq_len=100),
                  metrics=MetricsLogger(echo=False))


def test_cli_lm_subcommand():
    rc = main([
        "lm", "--device", "cpu", "--corpus", "synthetic", "--dim", "32",
        "--depth", "1", "--heads", "4", "--seq-len", "64", "--steps", "5",
        "--batch-size", "2", "--log-every", "0", "--num-devices", "1",
        "--lr-schedule", "constant", "--warmup-steps", "0",
        "--sample-tokens", "8",
    ])
    assert rc == 0


def test_sample_generates_within_budget():
    """sample() runs the KV-cache decode path off the trained state:
    right length, tokens in-vocab, deterministic at temperature 0."""
    t = LMTrainer(_cfg(steps=3), metrics=MetricsLogger(echo=False))
    t.train()
    p, c = t.sample(8)
    p2, c2 = t.sample(8)
    assert len(c) == 8 and c.dtype == np.int32
    assert len(p) + len(c) <= t.cfg.seq_len
    assert (c >= 0).all() and (c < t.model.vocab).all()
    np.testing.assert_array_equal(c, c2)  # greedy = deterministic
    with pytest.raises(ValueError, match="no room"):
        t.sample(t.cfg.seq_len)
    # A bad --sample-tokens must fail at SETUP, not after training.
    with pytest.raises(ValueError, match="sample-tokens"):
        LMTrainer(_cfg(sample_tokens=64), metrics=MetricsLogger(echo=False))
