"""Config/flag-system tests, including the reference CLI contract
(4 positional paths, exit 100 on wrong count — cnn.c:408-412)."""

import pytest

from mpi_cuda_cnn_tpu.utils.config import Config, parse_args, parse_mesh_shape


def test_defaults_are_reference_constants():
    cfg = Config()
    assert cfg.lr == 0.1          # cnn.c:446
    assert cfg.epochs == 10       # cnn.c:448
    assert cfg.batch_size == 32   # cnn.c:449
    assert cfg.seed == 0          # cnn.c:413


def test_four_positional_paths():
    cfg = parse_args(["a", "b", "c", "d"])
    assert cfg.dataset == "idx"
    assert (cfg.train_images, cfg.train_labels, cfg.test_images, cfg.test_labels) == (
        "a", "b", "c", "d")


def test_wrong_positional_count_exits():
    with pytest.raises(SystemExit):
        parse_args(["a", "b"])


def test_flags():
    cfg = parse_args(["--model", "lenet5", "--epochs", "3", "--lr", "0.01",
                      "--use-pallas", "--compute-dtype", "bfloat16"])
    assert cfg.model == "lenet5" and cfg.epochs == 3 and cfg.lr == 0.01
    assert cfg.use_pallas and cfg.compute_dtype == "bfloat16"


def test_json_roundtrip():
    cfg = Config(model="vgg_small", epochs=2)
    assert Config.from_json(cfg.to_json()) == cfg


def test_mesh_spec():
    assert parse_mesh_shape("data", 8) == {"data": 8}
    assert parse_mesh_shape("data:4,model:2", 8) == {"data": 4, "model": 2}
    assert parse_mesh_shape("data,model:2", 8) == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        parse_mesh_shape("data:3,model", 8)  # 3 does not divide 8
    with pytest.raises(ValueError):
        parse_mesh_shape("data,model", 8)  # two unsized axes


def test_fault_plan_validated_at_argparse_time(capsys):
    """ISSUE 5 satellite: a malformed --fault-plan dies AT THE COMMAND
    LINE with parse_plan's one-line message (argparse exit 2), never as
    a traceback from deep inside the trainer."""
    with pytest.raises(SystemExit) as ei:
        parse_args(["--fault-plan", "boom@train.step:1"])
    assert ei.value.code == 2
    err = capsys.readouterr().err
    assert "bad fault spec" in err and "unknown kind" in err
    # A valid plan parses through unchanged (the trainer re-parses it).
    cfg = parse_args(["--fault-plan", "crash@train.step:6"])
    assert cfg.fault_plan == "crash@train.step:6"


def test_nan_policy_validated_at_argparse_time(capsys):
    from mpi_cuda_cnn_tpu.utils.config import parse_lm_args

    for parse in (parse_args, parse_lm_args):
        with pytest.raises(SystemExit) as ei:
            parse(["--nan-policy", "bogus"])
        assert ei.value.code == 2
        assert "invalid choice: 'bogus'" in capsys.readouterr().err


def test_lm_fault_plan_validated_at_argparse_time(capsys):
    from mpi_cuda_cnn_tpu.utils.config import parse_lm_args

    with pytest.raises(SystemExit) as ei:
        parse_lm_args(["--fault-plan", "crash@a.b"])  # missing :at
    assert ei.value.code == 2
    assert "bad fault spec" in capsys.readouterr().err
