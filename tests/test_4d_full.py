"""The FULL 4D mesh with every axis populated: pipe x model x seq x data
on 16 virtual devices (VERDICT round 4, weak item 6).

The in-process suite runs on 8 virtual devices (conftest.py), which fits
any THREE of the four axes at size 2; the 2x2x2x2 composition needs 16,
so it runs in a spawned worker process with its own
xla_force_host_platform_device_count=16 — same pattern as the multihost
tests. The worker asserts exact serial parity (loss + updated params)
and prints 4D16OK; this test just audits the spawn.
"""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
WORKER = REPO / "scripts" / "fourd16_worker.py"


def test_full_4d_mesh_16_devices_matches_serial():
    proc = subprocess.run(
        [sys.executable, str(WORKER)],
        capture_output=True, text=True, timeout=600, cwd=REPO,
    )  # the worker forces its own XLA_FLAGS device count / platform
    assert proc.returncode == 0, (
        f"4D16 worker failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "4D16OK" in proc.stdout
