"""Cache-aware fleet routing (serve/router.py cache_aware policy +
the route-key digests serve/prefix_cache.py / serve/host_tier.py
maintain, ISSUE 18): the router must EARN the prefill win — score
candidates by expected prefix overlap from each replica's host-side
key set — without moving a single token of any output, through
crash/failover and host-tier spill.

Also here: the seeded multi-turn session workload family (turn N+1
re-arrives carrying turn N's context) and the diurnal arrival warp,
with CRC pins proving their [seed,5] RNG stream and the warp's amp=0
identity leave every existing seeded workload bitwise unchanged, and
the byte-for-byte golden round-trips of the fleet sample's routing/
autoscale observability surfaces.

The determinism discipline from test_fleet.py applies throughout:
Fleet.run MUTATES Request objects, so every comparison run gets a
freshly generated workload — never a shared list."""

import zlib
from pathlib import Path

import numpy as np
import pytest

from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector
from mpi_cuda_cnn_tpu.serve.bench import (
    add_session_turns,
    diurnal_warp,
    fleet_bench_main,
    parse_turns_dist,
)
from mpi_cuda_cnn_tpu.serve.fleet import (
    Fleet,
    SimCompute,
    make_fleet_workload,
)
from mpi_cuda_cnn_tpu.serve.router import Router

REPO = Path(__file__).resolve().parent.parent
DATA = REPO / "tests" / "data"
VOCAB = 512

CRASH_PLAN = ("replica_crash@fleet.tick:40?replica=1&zombie_ticks=4;"
              "replica_crash@fleet.tick:120?replica=2;"
              "replica_join@fleet.tick:160")


def workload(n=400, rate=600.0, seed=3, **kw):
    kw.setdefault("vocab", VOCAB)
    kw.setdefault("prompt_min", 8)
    kw.setdefault("prompt_max", 48)
    kw.setdefault("out_min", 4)
    kw.setdefault("out_max", 32)
    kw.setdefault("sessions", 8)
    kw.setdefault("prefix_mix", 0.5)
    kw.setdefault("templates", 4)
    kw.setdefault("turns_dist", "uniform:2-3")
    kw.setdefault("turn_gap_s", 0.02)
    return make_fleet_workload(n=n, rate=rate, seed=seed, **kw)


def sim_fleet(*, policy, plan=None, seed=3, host_pages=0, **kw):
    kw.setdefault("replicas", 3)
    kw.setdefault("slots", 4)
    kw.setdefault("num_pages", 33)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_len", 96)
    kw.setdefault("check_every", 8)
    return Fleet(
        lambda name: SimCompute(vocab=VOCAB, chunk=16, salt=seed),
        policy=policy,
        prefix=True,
        host_pages=host_pages,
        faults=FaultInjector(plan) if plan else None,
        **kw,
    )


# --------------------------------------- the routed-prefill acceptance


def test_cache_aware_beats_hash_affinity_same_outputs():
    """The tentpole claim, tier-1 sized: on the IDENTICAL seeded
    multi-turn session storm, cache-aware routing yields strictly more
    fleet-wide prefix hit tokens than rendezvous-hash session affinity
    AND strictly more than least-loaded, while every per-request
    output stays bitwise identical across all three policies —
    routing decides WHERE prefill work happens, never WHAT any replica
    generates (the CI diurnal storm re-proves determinism at 4x10^4
    requests through ci/autoscale_gate.json)."""
    results = {}
    for policy in ("cache_aware", "session", "least_loaded"):
        res = sim_fleet(policy=policy).run(workload())
        assert all(r.terminal for r in res.requests)
        results[policy] = res
    cache, sess, ll = (results["cache_aware"], results["session"],
                       results["least_loaded"])
    # The routed counters only exist under cache_aware...
    assert cache.route_hits > 0
    assert cache.route_hit_tokens > 0
    assert sess.route_hits == sess.route_hit_tokens == 0
    # ...and the promised overlap is real: strictly more prefix hit
    # tokens than either fallback policy on the same requests.
    hit_tokens = {p: r.summary()["prefix_hit_tokens"]
                  for p, r in results.items()}
    assert hit_tokens["cache_aware"] > hit_tokens["session"], hit_tokens
    assert hit_tokens["cache_aware"] > hit_tokens["least_loaded"], \
        hit_tokens
    # Output parity: bitwise-equal tokens for every request.
    assert cache.outputs() == sess.outputs() == ll.outputs()
    assert cache.status_counts() == sess.status_counts()


def test_cache_aware_parity_through_crash_and_spill():
    """Routing-on vs routing-off output parity under the hard
    composition: two injected crashes (one a partitioned zombie), an
    elastic join, and a bounded host tier spilling/readmitting prefix
    pages under page pressure. The route-key digest shrinks and grows
    through all of it (evictions discard, spills re-register on the
    tier's side) and not one output token moves."""
    cache = sim_fleet(policy="cache_aware", plan=CRASH_PLAN,
                      host_pages=16).run(workload())
    plain = sim_fleet(policy="least_loaded", plan=CRASH_PLAN,
                      host_pages=16).run(workload())
    assert cache.crashes == plain.crashes == 2
    assert cache.summary()["tier_spills"] > 0
    assert cache.route_hits > 0
    assert cache.outputs() == plain.outputs()
    assert cache.status_counts() == plain.status_counts()


def test_cache_aware_bitwise_deterministic():
    """Two identical-seed routed runs are bitwise equal in dispatch
    trace, state digest chain, and routed-hit accounting (workloads
    regenerated per run — Fleet.run mutates requests)."""
    a = sim_fleet(policy="cache_aware", host_pages=16).run(workload())
    b = sim_fleet(policy="cache_aware", host_pages=16).run(workload())
    assert a.trace_crc == b.trace_crc
    assert a.state_crc == b.state_crc
    assert (a.route_hits, a.route_misses, a.route_hit_tokens) == \
        (b.route_hits, b.route_misses, b.route_hit_tokens)
    assert a.outputs() == b.outputs()


def test_route_keys_mirror_tree_and_tier_exactly():
    """The digest invariant: after a spill-heavy routed run, every
    live replica's route_keys is EXACTLY the set of its device-tree
    node paths plus its host-tier keys — not one key leaked by an
    evict, not one dropped by a readmit."""
    fleet = sim_fleet(policy="cache_aware", host_pages=16)
    res = fleet.run(workload())
    assert res.summary()["tier_spills"] > 0
    checked = 0
    for m in fleet.router.members.values():
        core = m.replica.core
        want = set(core.tier._entries) if core.tier is not None else set()
        stack = list(core.prefix.root_children.values())
        while stack:
            node = stack.pop()
            want.add(node.path)
            stack.extend(node.children.values())
        assert m.replica.route_keys == want, m.replica.name
        checked += 1
    assert checked >= 1


def test_router_overlap_walk_stops_at_first_miss():
    """_overlap walks cumulative chunk keys in depth order and stops
    at the first miss (the tree is prefix-closed): a replica holding
    depth-2 but missing depth-1 scores zero, not one."""

    class Rep:
        def __init__(self, keys):
            self.name = "r0"
            self.route_keys = keys

    router = Router("cache_aware", page_size=4)
    req = type("R", (), {"prompt": np.arange(12, dtype=np.int32)})()
    keys = router._chunk_keys(req)
    assert len(keys) == 3
    r = Router("cache_aware", page_size=4)
    m = type("M", (), {"replica": Rep(set(keys))})()
    assert r._overlap(m, keys) == 12
    # Drop the SHALLOWEST key: deeper survivors must not count.
    m.replica.route_keys = set(keys[1:])
    assert r._overlap(m, keys) == 0
    # Hold only the shallowest: exactly one chunk's worth.
    m.replica.route_keys = {keys[0]}
    assert r._overlap(m, keys) == 4


def test_cache_aware_router_requires_page_size():
    with pytest.raises(ValueError, match="page_size"):
        Router("cache_aware")


# ------------------------------ multi-turn sessions + diurnal arrivals


def test_default_workload_crcs_are_pinned():
    """THE stream-isolation pin: session turns draw from spawned
    stream [seed,5] and the diurnal warp is draw-free, so every
    workload that does not opt in is BITWISE what the previous PR
    generated. The constants below were computed against the pre-PR
    tree — if either moves, a new feature leaked draws into an
    existing stream."""

    def crc(reqs):
        h = 0
        for r in reqs:
            h = zlib.crc32(
                repr((r.rid, round(r.arrival, 12), r.max_new_tokens,
                      r.session, r.tenant, r.deadline,
                      np.asarray(r.prompt, np.int32).tobytes())).encode(),
                h)
        return h

    plain = make_fleet_workload(
        n=500, vocab=512, prompt_min=8, prompt_max=48, out_min=4,
        out_max=32, rate=800.0, seed=0)
    assert crc(plain) == 2719747999
    rich = make_fleet_workload(
        n=400, vocab=512, prompt_min=8, prompt_max=48, out_min=4,
        out_max=32, rate=600.0, seed=3, sessions=8, tenants=4,
        prefix_mix=0.5, templates=4, len_dist="lognormal")
    assert crc(rich) == 3209773015


def test_session_turns_extend_context_and_stay_sorted():
    """Structure of the turn chains: every follow-up turn keeps its
    anchor's session and tenant, its prompt EXTENDS the previous
    turn's full context (prompt + drawn continuation) as a strict
    prefix-preserving concatenation, it arrives strictly after the
    turn it continues, rids stay dense, and the stream is re-sorted
    by (arrival, rid)."""
    base = workload(turns_dist=None, turn_gap_s=0.0)
    turned = workload()
    assert len(turned) > len(base)
    assert [r.rid for r in sorted(turned, key=lambda r: r.rid)] == \
        list(range(len(turned)))
    arrivals = [(r.arrival, r.rid) for r in turned]
    assert arrivals == sorted(arrivals)
    # Chain reconstruction: a session's FIRST base request anchors the
    # conversation; the generated turns (rid >= len(base)) continue it
    # in rid order. Other base requests of the same session are just
    # independent arrivals — not part of the chain.
    chains = {}
    for r in sorted(turned, key=lambda r: r.rid):
        if r.rid < len(base):
            chains.setdefault(r.session, [r])
            continue
        prev = chains[r.session][-1]
        assert r.tenant == prev.tenant
        assert r.arrival > prev.arrival
        prev_toks = np.asarray(prev.prompt, np.int32)
        toks = np.asarray(r.prompt, np.int32)
        assert toks.size > prev_toks.size
        assert np.array_equal(toks[:prev_toks.size], prev_toks)
        chains[r.session].append(r)
    assert any(len(c) > 1 for c in chains.values())


def test_turns_need_sessions_and_bitwise_repeatable():
    with pytest.raises(ValueError, match="sessions"):
        make_fleet_workload(n=10, vocab=64, prompt_min=4, prompt_max=8,
                            out_min=2, out_max=4, rate=100.0, seed=0,
                            turns_dist="uniform:2-3")
    a, b = workload(), workload()
    assert [(r.rid, r.arrival, r.session,
             np.asarray(r.prompt).tobytes()) for r in a] == \
        [(r.rid, r.arrival, r.session,
          np.asarray(r.prompt).tobytes()) for r in b]


def test_parse_turns_dist_grammar():
    lo, hi = 2, 5
    draw = parse_turns_dist(f"uniform:{lo}-{hi}")
    rng = np.random.default_rng(0)
    vals = {int(draw(rng)) for _ in range(200)}
    assert vals == set(range(lo, hi + 1))
    draw = parse_turns_dist("geometric:0.5")
    rng = np.random.default_rng(0)
    assert all(int(draw(rng)) >= 1 for _ in range(50))
    for bad in ("uniform:5-2", "uniform:x-3", "geometric:0",
                "geometric:1.5", "zipf:2", "uniform", ""):
        with pytest.raises(ValueError):
            parse_turns_dist(bad)


def test_diurnal_warp_identity_monotone_and_deadline_preserving():
    """amp=0 is the bitwise identity; amp>0 keeps the arrival order
    monotone (the warp solves a monotone fixed point), preserves every
    request's RELATIVE deadline offset, and changes no prompt."""
    base = workload(turns_dist=None, deadline_s=0.5)
    ident = diurnal_warp(workload(turns_dist=None, deadline_s=0.5),
                         amp=0.0, period_s=10.0)
    assert [(r.arrival, r.deadline) for r in base] == \
        [(r.arrival, r.deadline) for r in ident]
    warped = diurnal_warp(workload(turns_dist=None, deadline_s=0.5),
                          amp=0.8, period_s=0.1)
    arr = [r.arrival for r in warped]
    assert arr == sorted(arr)
    assert any(abs(w.arrival - b.arrival) > 1e-6
               for w, b in zip(warped, base))
    for w, b in zip(warped, base):
        assert w.deadline - w.arrival == pytest.approx(
            b.deadline - b.arrival)
        assert np.array_equal(w.prompt, b.prompt)


# ---------------------------------------------- loud CLI config errors


@pytest.mark.parametrize("argv", [
    ["--policy", "cache_aware", "--requests", "4"],
    ["--turns-dist", "uniform:2-3", "--requests", "4"],
    ["--sessions", "2", "--turns-dist", "zipf:2", "--requests", "4"],
    ["--sessions", "2", "--turn-gap-ms", "5", "--requests", "4"],
    ["--diurnal-amp", "0.5", "--rate", "0", "--requests", "4"],
    ["--diurnal-amp", "1.5", "--requests", "4"],
    ["--autoscale", "min=3,max=2", "--requests", "4"],
    ["--autoscale", "nope=1", "--requests", "4"],
    ["--autoscale-frontier", "missing.jsonl", "--requests", "4"],
])
def test_fleet_bench_config_errors_exit_2(argv, capsys):
    """Misconfiguration is a loud rc-2 `error:` line, never a silent
    default: cache_aware without the prefix cache, turns without
    sessions, a turn gap without turns, the diurnal warp without a
    rate (or with amp > 1, which would fold time), a structurally
    invalid autoscale spec, and a frontier without the autoscaler to
    feed."""
    assert fleet_bench_main(argv) == 2
    assert "error:" in capsys.readouterr().err


# ----------------------------------------------- golden round-trips


def test_fleet_sample_golden_report_roundtrip(monkeypatch, capsys):
    """`mctpu report` on the checked-in routed/autoscaled fleet sample
    is byte-for-byte the golden — the routing, per-replica routing,
    and autoscale tables included (regenerate via
    scripts/make_obs_sample.py)."""
    from mpi_cuda_cnn_tpu.obs.report import report_main

    monkeypatch.chdir(REPO)
    assert report_main(["tests/data/sample_fleet_run.jsonl"]) == 0
    out = capsys.readouterr().out
    assert out == (DATA / "golden_fleet_report.md").read_text()
    assert "| routing | cache_aware |" in out.replace("policy", "routing") \
        or "cache_aware" in out
    assert "| autoscale |" in out


def test_fleet_sample_golden_top_roundtrip(monkeypatch, capsys):
    """`mctpu top --once` on the fleet sample pins the ROUTER panel
    (per-replica hit-rate bars) and the SCALE line (replica-count
    sparkline) byte-for-byte."""
    from mpi_cuda_cnn_tpu.obs.top import top_main

    monkeypatch.chdir(REPO)
    assert top_main(["tests/data/sample_fleet_run.jsonl", "--once"]) == 0
    out = capsys.readouterr().out
    assert out == (DATA / "golden_fleet_top.md").read_text()
    assert "ROUTER" in out and "SCALE" in out


def test_fleet_sample_golden_trace_roundtrips(monkeypatch, capsys):
    """`mctpu trace` on the fleet sample: the summary Gantt golden and
    the per-request detail golden (where the routed lifecycle marker
    renders) both hold byte-for-byte."""
    from mpi_cuda_cnn_tpu.obs.timeline import trace_main

    monkeypatch.chdir(REPO)
    assert trace_main(["tests/data/sample_fleet_run.jsonl",
                       "--width", "80"]) == 0
    assert capsys.readouterr().out == \
        (DATA / "golden_fleet_trace.md").read_text()
    assert trace_main(["tests/data/sample_fleet_run.jsonl",
                       "--request", "3"]) == 0
    out = capsys.readouterr().out
    assert out == (DATA / "golden_fleet_trace_detail.md").read_text()
    assert "routed" in out
