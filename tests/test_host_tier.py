"""Host-tier KV spill + paged draft cache (ISSUE 17).

The acceptance surface of serve/host_tier.py and the paged draft-model
cache: spill-on serving must be BITWISE output-identical to spill-off
(the tier only moves KV pages between storage tiers — it never changes
what is computed), through COW, preemption, LRU squeeze, fleet crash /
re-dispatch, and the disaggregated prefill->decode handoff; a corrupt
spill must be refused by the seal-CRC discipline and degrade to
re-prefill (never decoded); the paged draft cache must be bitwise
equal to the cacheless draft proposer and to spec-off; readmission
must measurably CUT prefill chunks when the shared working set
exceeds the device pool; and the whole schedule must be deterministic
(state_crc/trace twice-bitwise) and replayable with zero drift."""

import jax
import numpy as np
import pytest

from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.serve.fleet import (
    Fleet,
    SimCompute,
    make_fleet_workload,
)
from mpi_cuda_cnn_tpu.serve.host_tier import HostTier, chunk_crc
from mpi_cuda_cnn_tpu.serve.scheduler import Request

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=64)
DRAFT = TransformerLM(vocab=13, dim=16, heads=2, depth=1, max_seq=64)
PARAMS = MODEL.init(jax.random.key(0))
DPARAMS = DRAFT.init(jax.random.key(1))

# Two 16-token templates (two full pages at page_size=8) revisited in
# alternating waves: wave k's requests hit the template wave k-2 used,
# whose pages the k-1 wave's pressure evicted — the readmission storm.
TMPL_A = (np.arange(16, dtype=np.int32) * 3) % 13
TMPL_B = (np.arange(16, dtype=np.int32) * 5 + 1) % 13


def _wave_requests():
    out, rid = [], 0
    for wave, tmpl in enumerate([TMPL_A, TMPL_B, TMPL_A, TMPL_B]):
        for _ in range(2):
            p = np.concatenate([tmpl,
                                np.full(4, (rid * 2 + 1) % 13, np.int32)])
            out.append(Request(rid=rid, prompt=p, max_new_tokens=13,
                               arrival=wave * 2.0))
            rid += 1
    return out


def _outs(res):
    return {r.rid: list(r.out) for r in res.requests}


def _engine_run(host_pages, *, faults=None, num_pages=9):
    """The seeded readmission storm on a real f32 engine whose device
    pool (8 usable pages) is SMALLER than the shared working set (two
    templates x 2 pages + suffixes): spill-off re-prefills every
    revisited template, spill-on readmits it from the host tier."""
    clk = FakeClock()
    e = PagedEngine(MODEL, PARAMS, slots=2, num_pages=num_pages,
                    page_size=8, prefill_chunk=8)
    return e.run(_wave_requests(), prefix=True, host_pages=host_pages,
                 faults=faults, time_fn=clk, sleep_fn=clk.advance)


def test_spill_parity_bitwise_and_prefill_chunk_reduction():
    """The tentpole acceptance: spill-on outputs are BITWISE equal to
    spill-off in f32, the tier actually spilled and readmitted, and
    readmission cut prefill chunks (the capacity win the tier exists
    for — the working set exceeds the device pool, so spill-off pays a
    full template re-prefill every wave)."""
    off = _engine_run(0)
    on = _engine_run(8)
    assert _outs(off) == _outs(on)
    assert on.prefix["tier_spills"] > 0
    assert on.prefix["tier_readmits"] > 0
    assert on.prefix["tier_refusals"] == 0
    assert on.prefill_chunks < off.prefill_chunks
    # Spill-off stamps the tier block as zeros (the gate contract).
    assert off.prefix["tier_spills"] == 0
    assert off.prefix["tier_readmits"] == 0


def test_spill_schedule_deterministic_twice_bitwise():
    """Identical seeds -> identical spill/readmit schedule: state_crc
    (the per-tick digest chain folds the tier tuple) and the whole
    prefix/tier counter block repeat bitwise."""
    a = _engine_run(8)
    b = _engine_run(8)
    assert a.state_crc == b.state_crc
    assert a.prefix == b.prefix
    assert _outs(a) == _outs(b)


def test_spill_parity_through_cow_and_preemption():
    """Parity holds when the storm also preempts mid-decode and COWs a
    shared page at a divergent suffix: preempted requests requeue,
    re-acquire through the tree (possibly via readmission), and still
    produce spill-off's exact tokens."""
    def run(host_pages):
        rng = np.random.default_rng(3)
        reqs, rid = [], 0
        for wave, tmpl in enumerate([TMPL_A, TMPL_B, TMPL_A]):
            for _ in range(3):
                # Divergence INSIDE the template's second page -> COW.
                p = tmpl.copy()
                p[12] = (p[12] + 1 + rid) % 13
                reqs.append(Request(
                    rid=rid,
                    prompt=np.concatenate(
                        [p, rng.integers(0, 13, (3,)).astype(np.int32)]),
                    max_new_tokens=14, arrival=wave * 1.0))
                rid += 1
        clk = FakeClock()
        e = PagedEngine(MODEL, PARAMS, slots=3, num_pages=9, page_size=8,
                        prefill_chunk=8)
        return e.run(reqs, prefix=True, host_pages=host_pages,
                     time_fn=clk, sleep_fn=clk.advance)

    off = run(0)
    on = run(8)
    assert _outs(off) == _outs(on)
    assert on.preemptions > 0
    assert on.prefix["prefix_cow"] > 0
    assert on.prefix["tier_spills"] > 0


def test_corrupt_spill_refused_and_degrades_to_reprefill():
    """kv_corrupt@tier.spill flips the seal stamp of one spilled page:
    the later matching lookup must REFUSE it (counted), fall back to a
    plain miss (re-prefill), and leave every output bitwise equal to
    the clean run — corrupted KV is never decoded."""
    clean = _engine_run(8)
    bad = _engine_run(8, faults=FaultInjector("kv_corrupt@tier.spill:0"))
    assert bad.prefix["tier_refusals"] >= 1
    assert _outs(bad) == _outs(clean)


def test_inert_tier_fault_rejected_without_tier():
    """A kv_corrupt@tier.spill plan on a run WITHOUT a host tier would
    silently never fire — both the engine and the fleet must reject it
    loudly instead."""
    with pytest.raises(ValueError, match="tier.spill"):
        _engine_run(0, faults=FaultInjector("kv_corrupt@tier.spill:0"))
    with pytest.raises(ValueError, match="host tier"):
        Fleet(lambda name: SimCompute(vocab=64, chunk=8), replicas=2,
              slots=2, num_pages=11, page_size=4, max_len=64, prefix=True,
              clock=FakeClock(),
              faults=FaultInjector("kv_corrupt@tier.spill:0"))


def test_host_pages_without_prefix_rejected():
    """host_pages > 0 without the prefix tree has nothing to spill —
    loud config error, in the engine and the fleet alike."""
    clk = FakeClock()
    e = PagedEngine(MODEL, PARAMS, slots=2, num_pages=9, page_size=8,
                    prefill_chunk=8)
    with pytest.raises(ValueError, match="prefix"):
        e.run(_wave_requests(), prefix=False, host_pages=8,
              time_fn=clk, sleep_fn=clk.advance)
    with pytest.raises(ValueError, match="prefix"):
        Fleet(lambda name: SimCompute(vocab=64, chunk=8), replicas=2,
              slots=2, num_pages=11, page_size=4, max_len=64,
              prefix=False, host_pages=8, clock=FakeClock())


def test_host_tier_bounded_lru_and_crc_unit():
    """Unit laws of the tier itself: capacity >= 1 enforced, a full
    tier evicts its oldest entry (counted), a refused lookup drops the
    entry, and the seal stamp is handoff.page_crcs' per-page law."""
    with pytest.raises(ValueError):
        HostTier(0)
    tier = HostTier(2)
    t = [np.arange(8, dtype=np.int32) + i for i in range(3)]
    for i, toks in enumerate(t):
        tier.spill(toks.tobytes(), toks, page=i + 1)
    assert tier.host_used == 2                  # bounded
    assert tier.stats["host_evictions"] == 1    # oldest evicted
    assert tier.lookup(t[0].tobytes(), t[0]) is None   # genuinely gone
    # CRC refusal: ask for entry 1's key with entry 2's tokens.
    assert tier.lookup(t[1].tobytes(), t[2]) is None
    assert tier.stats["refusals"] == 1
    assert tier.host_used == 1                  # refused entry dropped
    entry = tier.lookup(t[2].tobytes(), t[2])
    assert entry is not None and entry.crc == chunk_crc(t[2])
    tier.take(entry, page=5)
    assert tier.host_used == 0 and tier.stats["readmits"] == 1


# -- draft-model paged cache ------------------------------------------


def test_draft_paged_parity_vs_cacheless_and_spec_off():
    """T=0 greedy outputs must be bitwise identical across spec-off,
    cacheless draft speculation, and the PAGED draft cache: the target
    verifies every proposal, so the draft's storage layout can never
    change what is committed."""
    def wl():
        rng = np.random.default_rng(7)
        return [Request(
            rid=i,
            prompt=rng.integers(0, 13,
                                (int(rng.integers(4, 10)),)).astype(np.int32),
            max_new_tokens=int(rng.integers(4, 16))) for i in range(5)]

    def eng(**kw):
        return PagedEngine(MODEL, PARAMS, slots=2, num_pages=24,
                           page_size=8, prefill_chunk=8, **kw)

    def run(e, spec):
        clk = FakeClock()
        return e.run(wl(), spec=spec, time_fn=clk, sleep_fn=clk.advance)

    base = run(eng(), False)
    cacheless = run(eng(spec="draft", spec_k=4, draft_model=DRAFT,
                        draft_params=DPARAMS), True)
    paged = run(eng(spec="draft", spec_k=4, draft_model=DRAFT,
                    draft_params=DPARAMS, draft_cache="paged"), True)
    assert _outs(base) == _outs(cacheless) == _outs(paged)
    assert paged.spec["spec_rounds"] > 0
    # Same proposals -> same acceptance account, layout-independent.
    assert paged.spec == cacheless.spec


# -- fleet composition -------------------------------------------------


def _fleet_run(host_pages, *, faults=None, pools=None, n=60):
    reqs = make_fleet_workload(n=n, vocab=64, prompt_min=24, prompt_max=32,
                               out_min=4, out_max=8, rate=200.0, seed=7,
                               prefix_mix=0.9, templates=6)
    fl = Fleet(lambda name: SimCompute(vocab=64, chunk=8),
               replicas=2, slots=2, num_pages=11, page_size=4, max_len=64,
               prefix=True, host_pages=host_pages, clock=FakeClock(),
               faults=faults, pools=pools, handoff_ticks=1)
    return fl.run(reqs)


def test_fleet_spill_parity_and_tier_stamps():
    """Fleet sim storm: spill-on outputs equal spill-off's bitwise, the
    per-replica tiers spilled and readmitted, and the spill-off run
    stamps the tier block as zeros (every gated metric exists in every
    run)."""
    off = _fleet_run(0)
    on = _fleet_run(8)
    assert off.outputs() == on.outputs()
    s = on.summary()
    assert s["tier_spills"] > 0
    assert s["tier_readmits"] > 0
    so = off.summary()
    assert so["tier_spills"] == 0 and "tier_refusals" in so


def test_fleet_crash_cold_restart_drops_tier_parity_holds():
    """A replica crash mid-storm rebuilds the replica — pool, prefix
    tree, AND host tier die with the incarnation (no stale spilled KV
    survives into the new one) — and outputs still equal the spill-off
    twin under the same fault plan."""
    plan = "replica_crash@fleet.tick:6?replica=0"
    on = _fleet_run(8, faults=FaultInjector(plan))
    off = _fleet_run(0, faults=FaultInjector(plan))
    assert on.outputs() == off.outputs()
    assert on.summary()["restarts"] >= 1


def test_disagg_handoff_spill_parity():
    """The 2-pool disaggregated storm with per-replica host tiers: the
    prefill->decode KV handoff composes with spill/readmission at
    bitwise output parity, and the tiers saw traffic."""
    off = _fleet_run(0, pools="prefill:1,decode:1", n=50)
    on = _fleet_run(8, pools="prefill:1,decode:1", n=50)
    assert off.outputs() == on.outputs()
    s = on.summary()
    assert s["handoffs"] > 0
    assert s["tier_spills"] > 0
    assert s["tier_readmits"] > 0


def test_fleet_corrupt_spill_refusal_parity():
    """kv_corrupt@tier.spill in the fleet: refused (or the corrupt
    entry aged out of the bounded tier first), outputs bitwise equal
    the spill-off run — garbage never decodes anywhere in the fleet."""
    bad = _fleet_run(8, faults=FaultInjector("kv_corrupt@tier.spill:0"))
    off = _fleet_run(0)
    assert bad.outputs() == off.outputs()
    s = bad.summary()
    assert s["tier_refusals"] >= 1 or s["tier_host_evictions"] > 0


def test_spill_determinism_storm_1e5_twice_bitwise():
    """The 10^5-request seeded sim storm with spill on, run twice:
    trace_crc, state_crc, and the whole tier counter block repeat
    bitwise — the CI fleet-gate discipline at full scale."""
    def run():
        reqs = make_fleet_workload(n=100_000, vocab=64, prompt_min=8,
                                   prompt_max=32, out_min=4, out_max=16,
                                   rate=2000.0, seed=0, prefix_mix=0.5,
                                   templates=8)
        fl = Fleet(lambda name: SimCompute(vocab=64, chunk=8),
                   replicas=4, slots=8, num_pages=33, page_size=4,
                   max_len=64, prefix=True, host_pages=33,
                   clock=FakeClock())
        return fl.run(reqs)

    a, b = run(), run()
    sa, sb = a.summary(), b.summary()
    assert sa["trace_crc"] == sb["trace_crc"]
    assert sa["state_crc"] == sb["state_crc"]
    for k in ("tier_spills", "tier_readmits", "tier_refusals",
              "tier_host_evictions"):
        assert sa[k] == sb[k]
    assert sa["tier_spills"] > 0 and sa["tier_readmits"] > 0


# -- replay ------------------------------------------------------------


def test_replay_zero_drift_on_spill_and_draft_trails(tmp_path):
    """`mctpu replay` reconstructs a spill-enabled full-log trail and a
    paged-draft trail with zero per-tick digest drift: the SchedMirror
    page/tier/draft-pool laws match the engine's actual accounting at
    every tick."""
    from mpi_cuda_cnn_tpu.obs.replay import replay_main
    from mpi_cuda_cnn_tpu.serve.bench import serve_bench_main

    spill = str(tmp_path / "spill.jsonl")
    assert serve_bench_main(
        ["--requests", "12", "--vocab", "13", "--dim", "32", "--heads",
         "4", "--depth", "2", "--slots", "2", "--pages", "9",
         "--page-size", "8", "--prefill-chunk", "8", "--prompt-min", "8",
         "--prompt-max", "20", "--out-min", "4", "--out-max", "8",
         "--rate", "100", "--seed", "5", "--mode", "continuous",
         "--prefix-cache", "--prefix-mix", "0.8", "--templates", "3",
         "--spill", "--host-pages", "8",
         "--metrics-jsonl", spill]) == 0
    assert replay_main([spill]) == 0

    draft = str(tmp_path / "draft.jsonl")
    assert serve_bench_main(
        ["--requests", "8", "--vocab", "13", "--dim", "32", "--heads",
         "4", "--depth", "2", "--slots", "2", "--pages", "24",
         "--page-size", "8", "--prefill-chunk", "8", "--prompt-min", "4",
         "--prompt-max", "10", "--out-min", "4", "--out-max", "12",
         "--rate", "100", "--seed", "5", "--mode", "continuous",
         "--spec", "draft", "--spec-k", "4", "--draft-dim", "16",
         "--draft-depth", "1",
         "--draft-cache", "paged", "--metrics-jsonl", draft]) == 0
    assert replay_main([draft]) == 0
