"""Expert parallelism (parallel/ep.py): routing semantics + EP parity +
training integration on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.parallel.ep import (
    EXPERT_AXIS,
    init_moe_params,
    make_moe_layer,
    moe_mlp,
    top1_dispatch,
)
from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh

D, H, E = 16, 32, 8


def _params(seed=0):
    return init_moe_params(jax.random.key(seed), D, H, E)


def _tokens(t=64, seed=1):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal((t, D)), jnp.float32
    )


def _mesh(n=8):
    return make_mesh({EXPERT_AXIS: n}, devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# Routing semantics
# ---------------------------------------------------------------------------


def test_dispatch_at_most_one_slot_per_token():
    x, p = _tokens(), _params()
    dispatch, combine, _ = top1_dispatch(x, p["gate"], E, capacity=16)
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert set(np.unique(per_token)) <= {0.0, 1.0}
    # combine = dispatch * gate, gate in (0, 1]
    assert np.all(np.asarray(jnp.sum(combine, axis=(1, 2))) <= per_token + 1e-6)


def test_dispatch_respects_capacity():
    x, p = _tokens(t=256), _params()
    cap = 4
    dispatch, _, _ = top1_dispatch(x, p["gate"], E, capacity=cap)
    per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))
    assert np.all(per_expert <= cap)
    # Each (expert, slot) pair holds at most one token.
    per_slot = np.asarray(jnp.sum(dispatch, axis=0))
    assert per_slot.max() <= 1.0 + 1e-6


def test_overflow_tokens_get_zero_output():
    """With capacity 1, most tokens drop: their MoE output must be 0."""
    x, p = _tokens(t=64), _params()
    dispatch, _, _ = top1_dispatch(x, p["gate"], E, capacity=1)
    y, _ = moe_mlp(x, p, n_experts=E, capacity_factor=E / 64.0, axis=None)
    kept = np.asarray(jnp.sum(dispatch, axis=(1, 2))) > 0
    dropped_rows = np.asarray(y)[~kept]
    np.testing.assert_allclose(dropped_rows, 0.0, atol=1e-6)


# ---------------------------------------------------------------------------
# EP parity: the all_to_all relocation must not change the math
# ---------------------------------------------------------------------------


def test_ep_matches_per_shard_oracle():
    mesh = _mesh()
    p = _params()
    t_global = 8 * 16
    x = _tokens(t=t_global, seed=2)
    layer = make_moe_layer(mesh, n_experts=E)
    y_ep, aux_ep = layer(p, x)

    # Oracle: identical routing runs per shard (EP only relocates the
    # expert compute), dense experts on one device.
    shards = np.split(np.asarray(x), 8)
    outs, auxes = [], []
    for sh in shards:
        y, aux = moe_mlp(jnp.asarray(sh), p, n_experts=E, axis=None)
        outs.append(np.asarray(y))
        auxes.append(float(aux))
    np.testing.assert_allclose(
        np.asarray(y_ep), np.concatenate(outs), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(float(aux_ep), np.mean(auxes), rtol=1e-5)


def test_ep_rejects_indivisible_experts():
    mesh = _mesh()
    with pytest.raises(ValueError, match="experts"):
        make_moe_layer(mesh, n_experts=6)  # 6 % 8 != 0


# ---------------------------------------------------------------------------
# Training integration: gradients flow through routing + all_to_all
# ---------------------------------------------------------------------------


def test_ep_layer_trains():
    """Tiny regression task through the EP layer: loss must drop and all
    param groups (gate included) must receive gradients."""
    mesh = _mesh()
    params = _params(seed=3)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((128, D)), jnp.float32)
    target = jnp.asarray(np.roll(np.asarray(x), 1, axis=1))

    from jax.sharding import PartitionSpec as P
    from functools import partial as _partial
    from mpi_cuda_cnn_tpu.parallel.ep import moe_mlp as _moe, moe_param_specs

    def loss_fn(params, x, target):
        body = _partial(_moe, n_experts=E, axis=EXPERT_AXIS)

        def shard_body(p_, x_, t_):
            y, aux = body(x_, p_)
            local = jnp.mean((y - t_) ** 2)
            return (jax.lax.pmean(local, EXPERT_AXIS)
                    + 0.01 * jax.lax.pmean(aux, EXPERT_AXIS))

        return jax.shard_map(
            shard_body, mesh=mesh,
            in_specs=(moe_param_specs(), P(EXPERT_AXIS), P(EXPERT_AXIS)),
            out_specs=P(), check_vma=False,
        )(params, x, target)

    step = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    for _ in range(60):
        loss, grads = step(params, x, target)
        for leaf in jax.tree.leaves(grads):
            assert np.all(np.isfinite(np.asarray(leaf)))
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, f"no learning: {losses[::15]}"
