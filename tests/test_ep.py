"""Expert parallelism (parallel/ep.py): routing semantics + EP parity +
training integration on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from mpi_cuda_cnn_tpu.parallel.ep import (
    EXPERT_AXIS,
    init_moe_params,
    make_moe_layer,
    moe_mlp,
    top1_dispatch,
)
from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh

D, H, E = 16, 32, 8


def _params(seed=0):
    return init_moe_params(jax.random.key(seed), D, H, E)


def _tokens(t=64, seed=1):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal((t, D)), jnp.float32
    )


def _mesh(n=8):
    return make_mesh({EXPERT_AXIS: n}, devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# Routing semantics
# ---------------------------------------------------------------------------


def test_dispatch_at_most_one_slot_per_token():
    x, p = _tokens(), _params()
    dispatch, combine, _ = top1_dispatch(x, p["gate"], E, capacity=16)
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert set(np.unique(per_token)) <= {0.0, 1.0}
    # combine = dispatch * gate, gate in (0, 1]
    assert np.all(np.asarray(jnp.sum(combine, axis=(1, 2))) <= per_token + 1e-6)


def test_dispatch_respects_capacity():
    x, p = _tokens(t=256), _params()
    cap = 4
    dispatch, _, _ = top1_dispatch(x, p["gate"], E, capacity=cap)
    per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))
    assert np.all(per_expert <= cap)
    # Each (expert, slot) pair holds at most one token.
    per_slot = np.asarray(jnp.sum(dispatch, axis=0))
    assert per_slot.max() <= 1.0 + 1e-6


def test_overflow_tokens_get_zero_output():
    """With capacity 1, most tokens drop: their MoE output must be 0."""
    x, p = _tokens(t=64), _params()
    dispatch, _, _ = top1_dispatch(x, p["gate"], E, capacity=1)
    y, _ = moe_mlp(x, p, n_experts=E, capacity_factor=E / 64.0, axis=None)
    kept = np.asarray(jnp.sum(dispatch, axis=(1, 2))) > 0
    dropped_rows = np.asarray(y)[~kept]
    np.testing.assert_allclose(dropped_rows, 0.0, atol=1e-6)


# ---------------------------------------------------------------------------
# EP parity: the all_to_all relocation must not change the math
# ---------------------------------------------------------------------------


def test_ep_matches_per_shard_oracle():
    mesh = _mesh()
    p = _params()
    t_global = 8 * 16
    x = _tokens(t=t_global, seed=2)
    layer = make_moe_layer(mesh, n_experts=E)
    y_ep, aux_ep = layer(p, x)

    # Oracle: identical routing runs per shard (EP only relocates the
    # expert compute), dense experts on one device.
    shards = np.split(np.asarray(x), 8)
    outs, auxes = [], []
    for sh in shards:
        y, aux = moe_mlp(jnp.asarray(sh), p, n_experts=E, axis=None)
        outs.append(np.asarray(y))
        auxes.append(float(aux))
    np.testing.assert_allclose(
        np.asarray(y_ep), np.concatenate(outs), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(float(aux_ep), np.mean(auxes), rtol=1e-5)


def test_ep_rejects_indivisible_experts():
    mesh = _mesh()
    with pytest.raises(ValueError, match="experts"):
        make_moe_layer(mesh, n_experts=6)  # 6 % 8 != 0


# ---------------------------------------------------------------------------
# Training integration: gradients flow through routing + all_to_all
# ---------------------------------------------------------------------------


def test_ep_layer_trains():
    """Tiny regression task through the EP layer: loss must drop and all
    param groups (gate included) must receive gradients."""
    mesh = _mesh()
    params = _params(seed=3)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((128, D)), jnp.float32)
    target = jnp.asarray(np.roll(np.asarray(x), 1, axis=1))

    from jax.sharding import PartitionSpec as P
    from functools import partial as _partial
    from mpi_cuda_cnn_tpu.parallel.ep import moe_mlp as _moe, moe_param_specs

    def loss_fn(params, x, target):
        body = _partial(_moe, n_experts=E, axis=EXPERT_AXIS)

        def shard_body(p_, x_, t_):
            y, aux = body(x_, p_)
            local = jnp.mean((y - t_) ** 2)
            return (jax.lax.pmean(local, EXPERT_AXIS)
                    + 0.01 * jax.lax.pmean(aux, EXPERT_AXIS))

        return jax.shard_map(
            shard_body, mesh=mesh,
            in_specs=(moe_param_specs(), P(EXPERT_AXIS), P(EXPERT_AXIS)),
            out_specs=P(), check_vma=False,
        )(params, x, target)

    step = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    for _ in range(60):
        loss, grads = step(params, x, target)
        for leaf in jax.tree.leaves(grads):
            assert np.all(np.isfinite(np.asarray(leaf)))
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, f"no learning: {losses[::15]}"


# ---------------------------------------------------------------------------
# Top-k (k=2) routing — round-2 item 9
# ---------------------------------------------------------------------------


def test_topk_k1_matches_top1_exactly():
    from mpi_cuda_cnn_tpu.parallel.ep import topk_dispatch

    x, p = _tokens(t=64), _params()
    d1, c1, a1 = top1_dispatch(x, p["gate"], E, capacity=16)
    dk, ck, ak = topk_dispatch(x, p["gate"], E, capacity=16, k=1)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(dk))
    np.testing.assert_allclose(np.asarray(c1), np.asarray(ck), atol=1e-7)
    assert float(a1) == pytest.approx(float(ak))


def test_top2_dispatch_invariants():
    from mpi_cuda_cnn_tpu.parallel.ep import topk_dispatch

    x, p = _tokens(t=128), _params()
    cap = 40
    dispatch, combine, _ = topk_dispatch(x, p["gate"], E, capacity=cap, k=2)
    d = np.asarray(dispatch)
    # Each token occupies at most 2 slots, in 2 DIFFERENT experts.
    per_token = d.sum(axis=(1, 2))
    assert per_token.max() <= 2.0 + 1e-6
    per_token_expert = d.sum(axis=2)
    assert per_token_expert.max() <= 1.0 + 1e-6
    # Each (expert, slot) pair holds at most one token; capacity respected.
    assert d.sum(axis=0).max() <= 1.0 + 1e-6
    assert d.sum(axis=(0, 2)).max() <= cap
    # Combined gates are renormalized: a fully-kept token's combine sums
    # to ~1 (both choices kept), a half-dropped one to < 1.
    kept_both = per_token >= 2.0 - 1e-6
    csum = np.asarray(combine).sum(axis=(1, 2))
    np.testing.assert_allclose(csum[kept_both], 1.0, atol=1e-5)
    assert np.all(csum <= 1.0 + 1e-5)


def test_top2_first_choices_never_evicted():
    """Choice-priority capacity: adding 2nd choices must not change which
    FIRST choices are kept."""
    from mpi_cuda_cnn_tpu.parallel.ep import topk_dispatch

    x, p = _tokens(t=128), _params()
    cap = 8
    d1, _, _ = topk_dispatch(x, p["gate"], E, capacity=cap, k=1)
    d2, _, _ = topk_dispatch(x, p["gate"], E, capacity=cap, k=2)
    probs = jax.nn.softmax(x @ p["gate"], axis=-1)
    first = np.asarray(jnp.argmax(probs, axis=-1))
    # Project d2 onto first-choice experts only.
    d2_first = np.asarray(d2).sum(axis=2)[np.arange(128), first]
    d1_first = np.asarray(d1).sum(axis=2)[np.arange(128), first]
    np.testing.assert_array_equal(d1_first, d2_first)


def test_top2_ep_matches_oracle():
    """Sharded top-2 EP layer == the axis=None oracle on the same tokens."""
    mesh = _mesh()
    p = _params()
    x = _tokens(t=8 * 16, seed=4)
    layer = make_moe_layer(mesh, n_experts=E, top_k=2)
    y_ep, aux_ep = layer(p, x)
    y_or, aux_or = moe_mlp(x, p, n_experts=E, axis=None, top_k=2)
    # The sharded layer routes per device shard (16 tokens each) while the
    # oracle routes globally — compare per-shard oracles.
    ys = []
    for s in range(8):
        y_s, _ = moe_mlp(x[s * 16:(s + 1) * 16], p, n_experts=E, axis=None,
                         top_k=2)
        ys.append(np.asarray(y_s))
    np.testing.assert_allclose(
        np.asarray(y_ep), np.concatenate(ys), rtol=1e-5, atol=1e-5
    )


def test_top2_moe_lm_trains():
    """A top-2 MoE TransformerLM trains end to end under SP x EP."""
    import optax as _optax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS, make_sp_lm_train_step

    mesh = make_mesh({SEQ_AXIS: 4}, devices=jax.devices()[:4])
    lm = TransformerLM(vocab=17, dim=32, heads=4, depth=2, max_seq=64,
                       moe_experts=4, moe_top_k=2)
    params = lm.init(jax.random.key(0))
    opt = _optax.adam(3e-3)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    step = make_sp_lm_train_step(lm, opt, mesh)
    rng = np.random.default_rng(0)
    start = rng.integers(0, 17, size=(4, 1))
    toks = jnp.asarray((start + np.arange(65)) % 17, jnp.int32)
    losses = []
    for _ in range(40):
        state, m = step(state, toks[:, :-1], toks[:, 1:])
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7


def test_top2_inference_weights_two_experts():
    from mpi_cuda_cnn_tpu.parallel.ep import moe_mlp_inference

    x, p = _tokens(t=16), _params()
    y1 = moe_mlp_inference(x, p, n_experts=E, top_k=1)
    y2 = moe_mlp_inference(x, p, n_experts=E, top_k=2)
    assert y1.shape == y2.shape == x.shape
    # k=2 mixes a second expert: outputs must differ from pure top-1.
    assert float(jnp.max(jnp.abs(y1 - y2))) > 1e-4


def test_ep_dp_lm_trains(eight_devices):
    """EP x DP WITHOUT a sequence axis (parallel/ep.py
    make_ep_lm_train_step — the standard Switch deployment): batch
    sharded over (data, expert) jointly, MoE dispatch all_to_alling
    over 'expert'; the product loop trains, eval/decode work off the
    replicated state, and the composition/requirement checks fail
    loudly."""
    import pytest

    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    base = dict(corpus="synthetic", dim=32, depth=2, heads=4, seq_len=64,
                steps=8, batch_size=8, log_every=0,
                lr_schedule="constant", warmup_steps=0, sample_tokens=4)
    t = LMTrainer(LMConfig(mesh_shape="data:2,expert:4", moe_experts=4,
                           **base), metrics=MetricsLogger(echo=False))
    r = t.train()
    assert r.steps_run == 8 and np.isfinite(r.eval_ppl)
    _, cont = t.sample(4)
    assert len(cont) == 4

    # --grad-accum rides the EP shard_map too (per-micro-batch capacity
    # is the documented estimator change).
    t2 = LMTrainer(LMConfig(mesh_shape="data:2,expert:2", moe_experts=4,
                            grad_accum=2, **base),
                   metrics=MetricsLogger(echo=False))
    r2 = t2.train()
    assert r2.steps_run == 8 and np.isfinite(r2.final_loss)

    with pytest.raises(ValueError, match="expert"):  # dense model
        LMTrainer(LMConfig(mesh_shape="expert:4", **base),
                  metrics=MetricsLogger(echo=False))
    with pytest.raises(ValueError, match="composes with 'data' only"):
        LMTrainer(LMConfig(mesh_shape="expert:2,seq:2", moe_experts=4,
                           **base), metrics=MetricsLogger(echo=False))
    # --moe-dispatch-dtype is threaded only through the plain jitted
    # step; the shard_map meshes must reject it rather than silently
    # building f32 dispatch tensors.
    with pytest.raises(ValueError, match="moe-dispatch-dtype"):
        LMTrainer(LMConfig(mesh_shape="data:2,expert:4", moe_experts=4,
                           moe_dispatch_dtype="bfloat16", **base),
                  metrics=MetricsLogger(echo=False))


# ---------------------------------------------------------------------------
# Chunked dispatch (the single-chip quadratic-dispatch lever)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("top_k", [1, 2])
def test_dispatch_chunk_matches_unchunked_when_nothing_drops(top_k):
    """With capacity ample enough that no token drops, per-chunk routing
    assigns every token to the same expert with the same gate as
    whole-batch routing — identical outputs (routing is per-token;
    capacity boundaries are the ONLY coupling between tokens, and the
    fused router's gate reassociation is exact — each token's expert
    rows hold one occupied slot each). Top-1 is BITWISE (one product per
    token); top-2 sums two products inside reductions of different
    capacity extents, so the contraction order may differ by 1 ulp."""
    p = _params()
    x = _tokens(64)
    want, want_aux = moe_mlp(x, p, n_experts=E, capacity_factor=8.0,
                             axis=None, top_k=top_k)
    got, got_aux = moe_mlp(x, p, n_experts=E, capacity_factor=8.0,
                           axis=None, top_k=top_k, dispatch_chunk=16)
    if top_k == 1:
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    else:
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-7, atol=3e-7)
    # aux is the GLOBAL balance loss formed once from count/prob sums
    # accumulated across the chunk scan — the same objective as
    # unchunked routing, agreeing to float summation-order rounding
    # (the old per-chunk-mean form was a biased estimator and needed a
    # 0.2-absolute band here).
    assert float(got_aux) == pytest.approx(float(want_aux), rel=1e-5,
                                           abs=1e-6)


def test_router_dispatch_fused_equals_dense_pair():
    """router_dispatch's (dispatch, gate_te) fused form must reproduce
    the dense (dispatch, combine) pair exactly: combine == dispatch *
    gate_te (distinct chosen experts put at most one choice's gate on
    any (t, e) pair)."""
    from mpi_cuda_cnn_tpu.parallel.ep import router_dispatch, topk_dispatch

    x, p = _tokens(t=128), _params()
    for k in (1, 2):
        d, c, a = topk_dispatch(x, p["gate"], E, capacity=24, k=k)
        df, gte, af = router_dispatch(x, p["gate"], E, 24, k=k,
                                      dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(d), np.asarray(df))
        np.testing.assert_array_equal(
            np.asarray(c), np.asarray(df * gte[:, :, None])
        )
        assert float(a) == pytest.approx(float(af))


def test_dispatch_chunk_no_batch_extent_routing_alloc():
    """ISSUE 2 front 2, asserted mechanically: the compiled CHUNKED MoE
    program must never allocate a routing tensor at batch extent — its
    live scratch (XLA memory analysis temp bytes) stays below one
    (T, E, C_full) f32 tensor, while the unchunked program's scratch is
    at least that (it materializes the batch-extent dispatch)."""
    p = _params()
    t, chunk = 512, 64
    x = jnp.asarray(
        np.random.default_rng(5).standard_normal((t, D)), jnp.float32
    )
    cap_full = max(1, -int(-t * 2 * 1.25 // E))
    tec_bytes = t * E * cap_full * 4

    def temp_bytes(dc):
        f = jax.jit(lambda x, p: moe_mlp(
            x, p, n_experts=E, axis=None, top_k=2, dispatch_chunk=dc
        ))
        ma = f.lower(x, p).compile().memory_analysis()
        assert ma is not None, "backend exposes no memory analysis"
        return int(ma.temp_size_in_bytes)

    assert temp_bytes(chunk) < tec_bytes, (
        "chunked MoE step allocates batch-extent routing scratch"
    )
    # The contrast that proves the method — only meaningful while this
    # XLA:CPU materializes the unchunked batch-extent dispatch (true on
    # the measured 0.4.37; a future compiler that fuses it away would
    # invalidate the contrast, not the guarantee above).
    if jax.__version__ == "0.4.37":
        assert temp_bytes(0) >= tec_bytes


def test_dispatch_chunk_capacity_is_per_chunk():
    """At tight capacity the chunked form drops per chunk: a token
    burst routed to one expert overflows a whole-batch queue but fits
    per-chunk queues — the documented estimator change, visible as
    different outputs, both finite."""
    p = _params()
    x = _tokens(64, seed=3)
    y_whole, _ = moe_mlp(x, p, n_experts=E, capacity_factor=0.25,
                         axis=None)
    y_chunk, _ = moe_mlp(x, p, n_experts=E, capacity_factor=0.25,
                         axis=None, dispatch_chunk=16)
    assert np.isfinite(np.asarray(y_whole)).all()
    assert np.isfinite(np.asarray(y_chunk)).all()


def test_dispatch_chunk_rejections():
    p = _params()
    x = _tokens(64)
    with pytest.raises(ValueError, match="EP"):
        moe_mlp(x, p, n_experts=E, axis=EXPERT_AXIS, dispatch_chunk=16)
    with pytest.raises(ValueError, match="divisible"):
        moe_mlp(x, p, n_experts=E, axis=None, dispatch_chunk=60)


def test_dispatch_chunk_grads_flow_and_lm_step_runs():
    """The chunked path differentiates (scan grads) and is reachable
    from the LM train step (make_lm_train_step moe_dispatch_chunk)."""
    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step

    p = _params()
    x = _tokens(32)

    def loss(p, x):
        y, aux = moe_mlp(x, p, n_experts=E, axis=None, top_k=2,
                         dispatch_chunk=16)
        return jnp.sum(y ** 2) + aux

    g = jax.grad(loss)(p, x)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(g))

    model = TransformerLM(vocab=32, dim=16, heads=2, depth=1, max_seq=32,
                          moe_experts=2, moe_top_k=2)
    opt = optax.sgd(0.1)
    step = make_lm_train_step(model, opt, attn_impl="oracle", seq_len=16,
                              donate=False, moe_dispatch_chunk=8)
    state = make_lm_state(model, opt, 0)
    toks = jnp.asarray(
        np.random.default_rng(7).integers(0, 32, (2, 17)), jnp.int32
    )
    state, m = step(state, toks[:, :-1], toks[:, 1:])
    assert np.isfinite(float(m["loss"]))
