"""Step-granular (mid-epoch) checkpoint/resume — VERDICT round-1 item 7.

The elastic-recovery contract (SURVEY.md §5.3/5.4): a run killed after k
optimizer steps and resumed from the k-step checkpoint must produce the
SAME final parameters, bitwise, as an uninterrupted run — including when
k falls mid-epoch. Works because the shuffle order is a pure function of
(seed, epoch) (Trainer._epoch_order), so the resumed process recomputes
the epoch's permutation and skips the first k % steps_per_epoch batches.
"""

import numpy as np
import pytest

import jax

from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes
from mpi_cuda_cnn_tpu.models.presets import get_model
from mpi_cuda_cnn_tpu.train.trainer import Trainer
from mpi_cuda_cnn_tpu.utils.config import Config
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger


def _quiet():
    return MetricsLogger(echo=False)


def _cfg(**kw):
    base = dict(
        dataset="synthetic", model="reference_cnn", epochs=2,
        batch_size=16, num_devices=1, eval_every=0, log_every=0,
        lr=0.05, seed=7,
    )
    base.update(kw)
    return Config(**base)


def _params_of(t):
    return jax.device_get(t.state["params"])


@pytest.mark.parametrize("scan", [True, False])
def test_mid_epoch_resume_is_bitwise_exact(tmp_path, scan):
    """Uninterrupted 2-epoch run == run killed at step 6 (mid-epoch 1:
    4 steps/epoch) + resume from the 6-step checkpoint. Bitwise."""
    ds = synthetic_stripes(num_train=64, num_test=32)  # 4 steps/epoch

    full = Trainer(get_model("reference_cnn"), ds, _cfg(scan=scan),
                   metrics=_quiet())
    full.train()
    want = _params_of(full)

    # "Killed" run: checkpoint every 3 steps; simulate the kill by keeping
    # ONLY the step-6 checkpoint (mid-epoch 1) for the resumed process.
    ck = tmp_path / "ck"
    killed = Trainer(
        get_model("reference_cnn"), ds,
        _cfg(scan=scan, checkpoint_dir=str(ck), checkpoint_every_steps=3),
        metrics=_quiet(),
    )
    killed.train()
    kept = ck / "ckpt_6.npz"
    assert kept.exists(), sorted(p.name for p in ck.iterdir())
    for p in ck.glob("ckpt_*.npz"):
        if p != kept:
            p.unlink()

    resumed = Trainer(
        get_model("reference_cnn"), ds,
        _cfg(scan=scan, checkpoint_dir=str(ck), resume=True),
        metrics=_quiet(),
    )
    res = resumed.train()
    got = _params_of(resumed)

    assert res.final_step == full._global_step()
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("mesh_shape", ["data:8", "pipe:2,data:2"])
def test_mid_epoch_resume_under_mesh(tmp_path, mesh_shape, eight_devices):
    """Elastic recovery where it will actually be used: the same
    kill-at-step-6 / resume contract, bitwise, on a DP mesh and on a
    PP x DP mesh (the state is sharded; restore must re-place it with
    the live shardings, Trainer.place_state)."""
    ds = synthetic_stripes(num_train=64, num_test=32)  # 4 steps/epoch
    cfg_kw = dict(mesh_shape=mesh_shape, scan=True, num_devices=0)
    cfg_kw["batch_size"] = 16  # divisible by data axis and microbatches

    def params_of(t):
        return jax.device_get(
            t.state["flat_params"] if "flat_params" in t.state
            else t.state["params"]
        )

    def mk(**kw):
        c = _cfg(**cfg_kw, **kw)
        return Trainer(get_model("reference_cnn"), ds, c, metrics=_quiet())

    full = mk()
    full.train()
    want = params_of(full)

    ck = tmp_path / "ck"
    killed = mk(checkpoint_dir=str(ck), checkpoint_every_steps=3)
    killed.train()
    kept = ck / "ckpt_6.npz"
    assert kept.exists(), sorted(p.name for p in ck.iterdir())
    for p in ck.glob("ckpt_*.npz"):
        if p != kept:
            p.unlink()

    resumed = mk(checkpoint_dir=str(ck), resume=True)
    res = resumed.train()
    got = params_of(resumed)

    assert res.final_step == full._global_step()
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scan_and_loop_paths_share_batch_order():
    """The derived (seed, epoch) order must make the scanned and per-batch
    paths interchangeable — same params after one epoch."""
    ds = synthetic_stripes(num_train=64, num_test=32)
    outs = []
    for scan in (True, False):
        t = Trainer(get_model("reference_cnn"), ds, _cfg(scan=scan, epochs=1),
                    metrics=_quiet())
        t.train()
        outs.append(_params_of(t))
    for a, b in zip(jax.tree.leaves(outs[0]), jax.tree.leaves(outs[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_scan_falls_back_for_oversized_datasets():
    """A dataset past --scan-max-bytes must stream per-batch (O(batch)
    device memory) instead of staging the whole set in HBM — same math,
    different residency; the fallback is a size check, not a crash."""
    ds = synthetic_stripes(num_train=64, num_test=32)
    t_small = Trainer(get_model("reference_cnn"), ds, _cfg(epochs=1),
                      metrics=_quiet())
    assert t_small._use_scan()
    t_big = Trainer(get_model("reference_cnn"), ds,
                    _cfg(epochs=1, scan_max_bytes=1), metrics=_quiet())
    assert not t_big._use_scan()
    em = t_big.run_epoch(0)  # runs the streaming path end to end
    assert np.isfinite(em["loss"])
    # Explicit --no-scan is unconditional.
    assert not Trainer(get_model("reference_cnn"), ds,
                       _cfg(epochs=1, scan=False),
                       metrics=_quiet())._use_scan()


def test_epoch_order_is_stateless():
    ds = synthetic_stripes(num_train=64, num_test=32)
    t1 = Trainer(get_model("reference_cnn"), ds, _cfg(), metrics=_quiet())
    t2 = Trainer(get_model("reference_cnn"), ds, _cfg(), metrics=_quiet())
    np.testing.assert_array_equal(t1._epoch_order(3), t2._epoch_order(3))
    assert not np.array_equal(t1._epoch_order(0), t1._epoch_order(1))


def test_global_batch_sequence_is_width_independent(eight_devices):
    """Data-order elasticity (ISSUE 5): the GLOBAL batch sequence is a
    pure function of (seed, epoch/step) — never of the mesh — so a run
    resumed on a different dp width consumes exactly the batches the
    original would have. Each host's shard is then derived from the
    global batch + (process_index, process_count), not a stored cursor
    (parallel/elastic.host_shard_rows)."""
    ds = synthetic_stripes(num_train=64, num_test=32)
    orders = []
    for n in (1, 2, 4):
        t = Trainer(get_model("reference_cnn"), ds,
                    _cfg(mesh_shape=f"data:{n}", num_devices=0),
                    metrics=_quiet())
        orders.append(t._epoch_order(1))
    np.testing.assert_array_equal(orders[0], orders[1])
    np.testing.assert_array_equal(orders[0], orders[2])

    from mpi_cuda_cnn_tpu.train.lm_trainer import LMTrainer
    from mpi_cuda_cnn_tpu.utils.config import LMConfig

    def lm_batches(n):
        t = LMTrainer(LMConfig(corpus="synthetic", dim=32, depth=1,
                               heads=4, seq_len=32, steps=1, batch_size=8,
                               mesh_shape=f"data:{n}", num_devices=0),
                      metrics=_quiet())
        return np.asarray(t._sample_batch(5)[0])

    np.testing.assert_array_equal(lm_batches(1), lm_batches(4))

    # The per-host shard bounds tile the same global batch exactly.
    from mpi_cuda_cnn_tpu.parallel.elastic import host_shard_rows

    spans = [host_shard_rows(8, i, 4) for i in range(4)]
    assert [s for s, _ in spans] == [0, 2, 4, 6]
    assert all(b - a == 2 for a, b in spans)
