"""Dataset registry + pipeline tests."""

import numpy as np
import pytest

from mpi_cuda_cnn_tpu.data.datasets import (
    get_dataset,
    load_idx_dataset,
    synthetic_stripes,
    write_synthetic_idx,
)
from mpi_cuda_cnn_tpu.data.pipeline import epoch_batches, normalize_images, one_hot


def test_synthetic_shapes():
    ds = synthetic_stripes(num_train=100, num_test=20)
    assert ds.train_images.shape == (100, 28, 28)
    assert ds.test_labels.shape == (20,)
    assert ds.input_shape == (28, 28, 1)
    assert ds.num_classes == 10


def test_synthetic_cifar_shape():
    ds = get_dataset("synthetic_cifar", num_train=10, num_test=4)
    assert ds.train_images.shape == (10, 32, 32, 3)
    assert ds.input_shape == (32, 32, 3)


def test_synthetic_deterministic():
    a = synthetic_stripes(num_train=10, num_test=4, seed=7)
    b = synthetic_stripes(num_train=10, num_test=4, seed=7)
    np.testing.assert_array_equal(a.train_images, b.train_images)


def test_registry_unknown():
    with pytest.raises(KeyError, match="unknown dataset"):
        get_dataset("nope")


def test_idx_dataset_roundtrip(tmp_path):
    """The 4-IDX-file CLI contract (cnn.c:408-411) end to end."""
    ds = synthetic_stripes(num_train=30, num_test=10)
    paths = write_synthetic_idx(tmp_path, ds)
    loaded = load_idx_dataset("mnist", *paths.values())
    np.testing.assert_array_equal(loaded.train_images, ds.train_images)
    np.testing.assert_array_equal(loaded.test_labels, ds.test_labels)


def test_normalize():
    """uint8 -> [0,1] f32, matching x[j]=img[j]/255.0 (cnn.c:457)."""
    imgs = np.array([[[0, 128], [255, 51]]], dtype=np.uint8)
    out = normalize_images(imgs)
    assert out.shape == (1, 2, 2, 1)
    assert out.dtype == np.float32
    np.testing.assert_allclose(out[0, 1, 0, 0], 1.0)
    np.testing.assert_allclose(out[0, 0, 1, 0], 128 / 255.0)


def test_one_hot():
    out = one_hot(np.array([1, 0, 9]), 10)
    assert out.shape == (3, 10)
    assert out.dtype == np.float32
    assert out[0, 1] == 1.0 and out[0].sum() == 1.0
    assert out[2, 9] == 1.0


def test_epoch_batches_cover_epoch(rng):
    x = np.arange(100).reshape(100, 1)
    y = np.arange(100).reshape(100, 1)
    seen = []
    for bx, by in epoch_batches(x, y, 32, rng=rng):
        assert bx.shape == (32, 1)  # static shapes: tail dropped
        np.testing.assert_array_equal(bx, by)
        seen.extend(bx[:, 0].tolist())
    assert len(seen) == 96
    assert len(set(seen)) == 96  # a permutation: no repeats


def test_epoch_batches_sequential_without_rng():
    x = np.arange(8).reshape(8, 1)
    batches = list(epoch_batches(x, x, 4, rng=None))
    np.testing.assert_array_equal(batches[0][0][:, 0], [0, 1, 2, 3])


def test_cifar10_converter_selftest(tmp_path):
    """scripts/get_cifar10.py --selftest: CIFAR binary-batch -> IDX
    conversion is exact, and the output feeds the dataset registry
    (the fetch itself is network-gated; the converter is not)."""
    import subprocess
    import sys as _sys
    from pathlib import Path as _Path

    script = _Path(__file__).resolve().parents[1] / "scripts" / "get_cifar10.py"
    out = tmp_path / "cifar"
    res = subprocess.run(
        [_sys.executable, str(script), "--selftest", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr
    from mpi_cuda_cnn_tpu.data.datasets import get_dataset

    ds = get_dataset("cifar10", data_dir=str(out))
    assert ds.input_shape == (32, 32, 3)
    assert len(ds.train_images) == 100 and len(ds.test_images) == 20
