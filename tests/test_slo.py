"""Multi-tenant SLO accounting + streaming alerts + health (ISSUE 8).

THE acceptance tests live here:
- running the alert engine LIVE (MetricsLogger observer / tick sinks)
  during a seeded FakeClock serve run and replaying the finished JSONL
  produce the bitwise-identical alert sequence (CRC-pinned);
- two identical-seed fleet-storm runs produce identical `mctpu health`
  verdict tables;
- a seeded run with an injected slow / squeeze / replica_crash fault
  plan fires the expected burn-rate / staleness alerts (pinned by kind
  and tick) while the clean twin fires none.
"""

import json

import pytest

import jax

from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs.alerts import AlertEngine, alerts_crc
from mpi_cuda_cnn_tpu.obs.health import health_main
from mpi_cuda_cnn_tpu.obs.metrics import MetricsRegistry
from mpi_cuda_cnn_tpu.obs.regress import extract_metrics
from mpi_cuda_cnn_tpu.obs.schema import load_records, make_record
from mpi_cuda_cnn_tpu.obs.slo import (
    Objective,
    SLOSpec,
    WindowedEvents,
    budget_remaining,
    collect_terminals,
    verdicts_from_terminals,
)
from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main, make_workload
from mpi_cuda_cnn_tpu.serve.engine import PagedEngine
from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

MODEL = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)

# The sample spec (tests/data/sample_slo.json's shape), inlined so unit
# tests don't depend on the checked-in file.
SPEC = {
    "tenants": {"*": {"availability": 0.9,
                      "ttft_ms": {"target": 0.9, "threshold_ms": 200.0}}},
    "burn": {"windows_s": [[0.5, 0.1]], "max_rate": 2.0},
    "rules": [{"name": "tick-stale", "kind": "absence", "event": "tick",
               "max_gap_s": 0.1}],
}


@pytest.fixture(scope="module")
def engine():
    params = MODEL.init(jax.random.key(0))
    return PagedEngine(MODEL, params, slots=3, num_pages=10, page_size=4,
                       prefill_chunk=8, max_len=40)


def run_serve(engine, path, *, fault_plan=None, deadline_s=0.0,
              spec=None, tenants=2):
    """One seeded FakeClock serve run with the alert engine attached
    live through the MetricsLogger observer; returns (engine, result)."""
    clock = FakeClock()
    ae = AlertEngine(slo=SLOSpec.from_dict(spec or SPEC))
    with MetricsLogger(path=path, echo=False, clock=clock) as metrics:
        ae.attach(metrics)
        registry = MetricsRegistry(clock=clock)

        def sink(rec):
            metrics.log("tick", **rec)

        reqs = make_workload(n=8, vocab=13, prompt_min=4, prompt_max=8,
                             out_min=6, out_max=18, rate=40.0, seed=5,
                             deadline_s=deadline_s, tenants=tenants)
        faults = FaultInjector(fault_plan, clock=clock) if fault_plan \
            else None
        res = engine.run(reqs, mode="continuous", time_fn=clock,
                         sleep_fn=clock.advance, faults=faults,
                         registry=registry, tick_sink=sink)
        for rec in res.request_records():
            metrics.log("request", **rec)
        metrics.log("serve", bench="serve", **res.summary())
    return ae, res


# ------------------------------------------------------ SLO math


def test_objective_classify_and_budget_math():
    avail = Objective("availability", 0.99)
    lat = Objective("ttft_ms", 0.9, threshold_ms=100.0)
    assert avail.classify({"status": "finished"}) is True
    assert avail.classify({"status": "expired"}) is False
    assert avail.classify({"status": "cancelled"}) is None  # client's call
    assert lat.classify({"status": "finished", "ttft_ms": 99.0}) is True
    assert lat.classify({"status": "finished", "ttft_ms": 101.0}) is False
    # Failures are charged to availability, not double-charged here.
    assert lat.classify({"status": "failed"}) is None
    # Null-moment convention: a latency that was never measured (old
    # record shapes) is not an event, never a bad one.
    assert lat.classify({"status": "finished", "ttft_ms": None}) is None
    # Budget: target 0.9 over 100 events allows 10 bad.
    assert budget_remaining(95, 5, 0.9) == pytest.approx(0.5)
    assert budget_remaining(90, 10, 0.9) == pytest.approx(0.0)
    assert budget_remaining(80, 20, 0.9) == pytest.approx(-1.0)
    assert budget_remaining(0, 0, 0.9) is None
    with pytest.raises(ValueError):
        Objective("availability", 1.0)  # target must leave a budget
    with pytest.raises(ValueError):
        Objective("ttft_ms", 0.9)  # latency objective needs a threshold
    with pytest.raises(ValueError):
        Objective("latency_p99", 0.9, threshold_ms=1.0)  # unknown metric


def test_windowed_burn_rate_hand_computed():
    we = WindowedEvents([[10.0, 2.0]])
    target = 0.9  # budget = 10% bad
    for i in range(8):
        we.observe(float(i), True, target)
    # 2 bad of 10 in the 10s window -> bad_frac 0.2 -> burn 2.0.
    we.observe(8.0, False, target)
    we.observe(9.0, False, target)
    assert we.burn_rate(10.0, target) == pytest.approx(2.0)
    # Short window (2s, events at t>7]: 2 bad of 2 -> burn 10.
    assert we.burn_rate(2.0, target) == pytest.approx(10.0)
    assert we.worst_burn() == pytest.approx(10.0)
    # Time passes, bad events leave the short window.
    we.observe(12.0, True, target)
    assert we.burn_rate(2.0, target) == pytest.approx(0.0)
    assert we.good == 9 and we.bad == 2


def test_spec_parse_wildcard_and_errors():
    spec = SLOSpec.from_dict(SPEC)
    assert [o.metric for o in spec.objectives("anyone")] == \
        ["availability", "ttft_ms"]
    named = SLOSpec.from_dict({
        "tenants": {"*": {"availability": 0.9},
                    "vip": {"availability": 0.999}}})
    assert named.objectives("vip")[0].target == 0.999
    assert named.objectives("other")[0].target == 0.9
    with pytest.raises(ValueError):
        SLOSpec.from_dict({})  # no tenants
    with pytest.raises(ValueError):
        SLOSpec.from_dict({"tenants": {"*": {"availability": 0.9}},
                           "burn": {"windows_s": [[5, 10]]}})  # short>long
    with pytest.raises(ValueError):
        AlertEngine(rules=[{"name": "x", "kind": "burn_rate"}])
    with pytest.raises(ValueError):
        AlertEngine(rules=[{"name": "x", "kind": "nope"}])


# ------------------------------------------------------ rule engine


def tick(t, n, **kw):
    return make_record("tick", t, tick=n, now=t, queue=kw.pop("queue", 0),
                       free_pages=9, **kw)


def test_threshold_rule_edge_trigger_and_each():
    ae = AlertEngine(rules=[{"name": "q", "kind": "threshold",
                             "event": "tick", "field": "queue", "op": ">",
                             "value": 3, "for_count": 2}])
    assert ae.ingest(tick(0.0, 0, queue=5)) == []          # streak 1
    assert len(ae.ingest(tick(0.1, 1, queue=6))) == 1      # streak 2: fire
    assert ae.ingest(tick(0.2, 2, queue=7)) == []          # still firing
    assert ae.ingest(tick(0.3, 3, queue=1)) == []          # re-arm
    assert ae.ingest(tick(0.4, 4, queue=9)) == []
    assert len(ae.ingest(tick(0.5, 5, queue=9))) == 1      # fires again
    each = AlertEngine(rules=[{"name": "crash", "kind": "threshold",
                               "event": "replica", "field": "kind",
                               "op": "==", "value": "crash", "each": True}])
    rec = make_record("replica", 1.0, name="r1", kind="crash")
    assert len(each.ingest(rec)) == 1
    assert len(each.ingest(rec)) == 1  # discrete events: every match


def test_rate_of_change_rule():
    ae = AlertEngine(rules=[{"name": "loss-spike",
                             "kind": "rate_of_change", "event": "train",
                             "field": "loss", "max_rise_pct": 50.0}])
    assert ae.ingest(make_record("train", 1.0, step=1, loss=1.0)) == []
    assert ae.ingest(make_record("train", 2.0, step=2, loss=1.2)) == []
    fired = ae.ingest(make_record("train", 3.0, step=3, loss=2.0))
    assert len(fired) == 1 and fired[0]["delta_pct"] == pytest.approx(66.667)


def test_absence_rule_fires_on_gap_and_rearms():
    ae = AlertEngine(rules=[{"name": "stale", "kind": "absence",
                             "event": "tick", "max_gap_s": 0.1}])
    assert ae.ingest(tick(0.00, 0)) == []
    assert ae.ingest(tick(0.05, 1)) == []
    fired = ae.ingest(tick(0.30, 2))  # late tick proves the gap it ends
    assert len(fired) == 1 and fired[0]["gap_s"] == pytest.approx(0.25)
    assert ae.ingest(tick(0.35, 3)) == []  # re-armed, no gap
    # Records without "now" never advance the staleness clock: end-of-
    # run records on the logger timeline cannot fabricate a gap.
    assert ae.ingest(make_record("serve", 99.0, mode="x", requests=1,
                                 tokens_per_s=1.0)) == []


# --------------------------------------- live == replay (acceptance)


def test_alert_engine_live_vs_replay_bitwise(engine, tmp_path):
    path = tmp_path / "run.jsonl"
    ae, _ = run_serve(engine, path, deadline_s=0.3,
                      fault_plan="slow@serve.tick:10?s=0.15;"
                                 "slow@serve.tick:20?s=0.15;"
                                 "slow@serve.tick:30?s=0.15")
    assert ae.alerts, "the faulted run must fire alerts"
    replay = AlertEngine(slo=SLOSpec.from_dict(SPEC))
    replay.replay(load_records(path))
    assert [dict(a) for a in replay.alerts] == [dict(a) for a in ae.alerts]
    assert replay.crc == ae.crc
    # The file's logged alert records ARE the live sequence.
    logged = [r for r in load_records(path) if r["event"] == "alert"]
    assert alerts_crc(logged) == ae.crc


def test_faulted_run_fires_pinned_alerts_clean_run_none(engine, tmp_path):
    clean_ae, _ = run_serve(engine, tmp_path / "clean.jsonl")
    assert clean_ae.alerts == []

    slow_ae, _ = run_serve(engine, tmp_path / "slow.jsonl",
                           deadline_s=0.3,
                           fault_plan="slow@serve.tick:10?s=0.15;"
                                      "slow@serve.tick:20?s=0.15;"
                                      "slow@serve.tick:30?s=0.15")
    # Pinned by kind and tick: each slow fault lands the next tick 0.15s
    # late (staleness), and the expiry/late TTFTs it causes push the
    # burn windows over max_rate.
    assert [(a["kind"], a["tick"]) for a in slow_ae.alerts] == [
        ("absence", 10), ("absence", 20), ("burn_rate", 29),
        ("absence", 30), ("burn_rate", 30),
    ]
    assert {a["rule"] for a in slow_ae.alerts if a["kind"] == "burn_rate"} \
        == {"burn:t1:availability", "burn:t1:ttft_ms"}

    # A squeeze starves the pool: deadline expiries burn availability.
    sq_ae, sq_res = run_serve(engine, tmp_path / "squeeze.jsonl",
                              deadline_s=0.05,
                              fault_plan="squeeze@serve.tick:2"
                                         "?pages=9&ticks=120")
    assert sq_res.status_counts().get("expired", 0) > 0
    assert any(a["kind"] == "burn_rate" for a in sq_ae.alerts)


# -------------------------------------------- fleet storm (acceptance)


FLEET_SLO = {
    "tenants": {"*": {"availability": 0.99,
                      "ttft_ms": {"target": 0.9, "threshold_ms": 60000}}},
    "burn": {"windows_s": [[2.0, 0.25]], "max_rate": 10.0},
    "rules": [{"name": "replica-stale", "kind": "absence", "event": "tick",
               "per": "mode", "max_gap_s": 0.01}],
    "max_alerts": 0,
}


def run_fleet(tmp_path, tag, *, fault_plan=None, log="full", slo=True):
    spec_path = tmp_path / "slo.json"
    spec_path.write_text(json.dumps(FLEET_SLO))
    out = tmp_path / f"fleet_{tag}.jsonl"
    argv = ["--replicas", "3", "--requests", "200", "--rate", "300",
            "--seed", "0", "--tenants", "2", "--log", log,
            "--metrics-jsonl", str(out)]
    if slo:
        argv += ["--slo", str(spec_path)]
    if fault_plan:
        argv += ["--fault-plan", fault_plan]
    assert fleet_bench_main(argv) == 0
    return out, spec_path


def test_fleet_crash_fires_staleness_and_health_tables_identical(
        tmp_path, capsys):
    """Two identical-seed crash storms: identical health verdict
    tables, and the dead replica's tick silence fires the staleness
    rule while the crash-free twin fires nothing."""
    plan = "replica_crash@fleet.tick:40?replica=1"
    out_a, spec_path = run_fleet(tmp_path, "a", fault_plan=plan)
    out_b, _ = run_fleet(tmp_path, "b", fault_plan=plan)
    capsys.readouterr()

    alerts_a = [r for r in load_records(out_a) if r["event"] == "alert"]
    assert any(a["kind"] == "absence" and a.get("group") == "fleet/r1"
               for a in alerts_a), "dead replica must trip staleness"

    rc_a = health_main([str(out_a), "--slo", str(spec_path)])
    table_a = capsys.readouterr().out.split("\n", 2)[2]  # drop the path line
    rc_b = health_main([str(out_b), "--slo", str(spec_path)])
    table_b = capsys.readouterr().out.split("\n", 2)[2]
    assert table_a == table_b
    # max_alerts 0 + the staleness alert -> both runs unhealthy, alike.
    assert rc_a == rc_b == 1

    clean, _ = run_fleet(tmp_path, "clean")
    capsys.readouterr()
    assert [r for r in load_records(clean) if r["event"] == "alert"] == []
    assert health_main([str(clean), "--slo", str(spec_path)]) == 0
    capsys.readouterr()


def test_fleet_summary_mode_health_fallback_and_tenant_keys(
        tmp_path, capsys):
    """--log summary: no tick records land in the file, yet the summary
    carries per-tenant blocks + alert totals, health falls back to the
    histogram estimate, and `mctpu compare` sees flattened per-tenant
    metric names."""
    out, spec_path = run_fleet(tmp_path, "sum", log="summary")
    capsys.readouterr()
    records = load_records(out)
    assert not any(r["event"] == "tick" for r in records)
    serve = next(r for r in records if r["event"] == "serve")
    assert serve["alerts_fired"] == 0
    assert set(serve["tenants"]) == {"t0", "t1"}

    assert health_main([str(out), "--slo", str(spec_path)]) == 0
    out_text = capsys.readouterr().out
    assert "[summary]" in out_text and "(est)" in out_text

    m = extract_metrics(out)
    assert "serve.fleet.tenant.t0.requests" in m
    assert "serve.fleet.tenant.t1.status.finished" in m
    assert "serve.fleet.alerts_crc" in m
    assert m["serve.fleet.alerts_fired"] == 0
    # Without --slo the totals still exist (gated metrics must exist in
    # EVERY fleet-bench run), as zero/empty-CRC.
    out2, _ = run_fleet(tmp_path, "noslo", log="summary", slo=False)
    capsys.readouterr()
    m2 = extract_metrics(out2)
    assert m2["serve.fleet.alerts_fired"] == 0
    assert m2["serve.fleet.alerts_crc"] == alerts_crc([])


def test_total_outage_reaches_slo_layer(tmp_path):
    """Every replica dead with work outstanding: the mass-failed
    requests land in the registry twins, in a router-attributed tick's
    `terminal` entries (so burn-rate rules and health see the outage),
    and the availability verdict is violated."""
    from mpi_cuda_cnn_tpu.serve.fleet import Fleet, SimCompute
    from mpi_cuda_cnn_tpu.faults import FaultInjector

    reqs = make_workload(n=20, vocab=64, prompt_min=4, prompt_max=8,
                         out_min=4, out_max=8, rate=500.0, seed=3,
                         tenants=2)
    ticks = []
    registry = MetricsRegistry(clock=FakeClock())
    ae = AlertEngine(slo=SLOSpec.from_dict({
        "tenants": {"*": {"availability": 0.9}},
        "burn": {"windows_s": [[1.0, 0.1]], "max_rate": 2.0},
    }))

    def sink(rec):
        ticks.append(rec)
        ae.ingest(rec, event="tick")

    fleet = Fleet(lambda name: SimCompute(vocab=64), replicas=1,
                  slots=2, num_pages=17, page_size=4, max_len=16,
                  max_flaps=0, heartbeat_miss=1, registry=registry,
                  replica_tick_sink=sink,
                  faults=FaultInjector("replica_crash@fleet.tick:2"))
    res = fleet.run(reqs)
    failed = [r for r in res.requests if r.status == "failed"]
    assert failed, "the circuit-opened fleet must fail the remainder"
    # Registry twins observed the outage.
    assert registry.counters["serve.requests_failed"].value == len(failed)
    # The router tick carries every mass-failed rid as a terminal entry.
    router_terms = [t for rec in ticks if rec["mode"] == "fleet/router"
                    for t in rec["terminal"]]
    assert sorted(t["id"] for t in router_terms) == \
        sorted(r.rid for r in failed)
    # The live burn rule paged on the outage.
    assert any(a["kind"] == "burn_rate" for a in ae.alerts)


# ----------------------------------------------------- health verdicts


def test_health_verdicts_exact_path_and_exit_codes(engine, tmp_path,
                                                   capsys):
    path = tmp_path / "run.jsonl"
    run_serve(engine, path, deadline_s=0.3,
              fault_plan="slow@serve.tick:10?s=0.15;"
                         "slow@serve.tick:20?s=0.15;"
                         "slow@serve.tick:30?s=0.15")
    spec_path = tmp_path / "slo.json"
    spec_path.write_text(json.dumps(SPEC))
    assert health_main([str(path), "--slo", str(spec_path),
                        "--verify-alerts", "--format", "json"]) == 1
    ev = json.loads(capsys.readouterr().out)
    assert ev["source"] == "events"
    assert ev["alert_crc_ok"] is True
    t1_avail = next(v for v in ev["verdicts"]
                    if v["tenant"] == "t1" and v["metric"] == "availability")
    assert t1_avail["violated"] and t1_avail["budget_left"] < 0
    # A generous spec over the same file is healthy (alerts replay under
    # ITS rules, which fire nothing; without --verify-alerts the live
    # records from the tight spec are not held against it).
    loose = {"tenants": {"*": {"availability": 0.5}}, "max_alerts": 0}
    spec_path.write_text(json.dumps(loose))
    assert health_main([str(path), "--slo", str(spec_path)]) == 0
    capsys.readouterr()
    # Tamper proof: drop one live alert record and the verified replay
    # catches the drift (the trace-style cross-check, alert flavored).
    records = load_records(path)
    tampered = [r for r in records if not (r["event"] == "alert"
                                           and r.get("seq") == 0)]
    from mpi_cuda_cnn_tpu.obs.schema import dump_records

    p3 = tmp_path / "tampered.jsonl"
    dump_records(tampered, p3)
    spec_path.write_text(json.dumps(SPEC))
    assert health_main([str(p3), "--slo", str(spec_path),
                        "--verify-alerts", "--format", "json"]) == 1
    ev = json.loads(capsys.readouterr().out)
    assert ev["alert_crc_ok"] is False
    assert "alert_crc_mismatch" in ev["violations"]
    # Config errors are exit 2, not a verdict.
    spec_path.write_text("{}")
    assert health_main([str(path), "--slo", str(spec_path)]) == 2
    assert health_main([str(tmp_path / "missing.jsonl")]) == 2
    capsys.readouterr()


def test_health_train_rules(tmp_path, capsys):
    """Loss spikes, restarts, and non-finite steps judge a training
    stream; a clean trajectory is healthy."""
    from mpi_cuda_cnn_tpu.obs.schema import dump_records

    good = [make_record("train", float(i), step=i, loss=2.0 - 0.1 * i)
            for i in range(10)]
    p = tmp_path / "train_ok.jsonl"
    dump_records(good, p)
    assert health_main([str(p)]) == 0
    capsys.readouterr()

    bad = list(good)
    bad.insert(5, make_record("train", 4.5, step=45, loss=9.0))
    bad.append(make_record("fault", 10.0, kind="restart"))
    bad.append(make_record("fault", 11.0, kind="nonfinite_step"))
    p2 = tmp_path / "train_bad.jsonl"
    dump_records(bad, p2)
    assert health_main([str(p2)]) == 1
    out = capsys.readouterr().out
    assert "loss_spike" in out and "VIOLATED" in out
    assert "restarts" in out and "nonfinite_steps" in out


# -------------------------------------------------- tenant plumbing


def test_tenant_mix_is_seeded_and_leaves_rng_stream_untouched():
    base = make_workload(n=6, vocab=13, prompt_min=4, prompt_max=8,
                         out_min=6, out_max=18, rate=40.0, seed=5)
    tagged = make_workload(n=6, vocab=13, prompt_min=4, prompt_max=8,
                           out_min=6, out_max=18, rate=40.0, seed=5,
                           tenants=3)
    # Tenant labels come from a separate generator: prompts, lengths,
    # and arrivals are bitwise-identical with tagging on/off —
    # committed baselines and pinned tick counts stay valid.
    for a, b in zip(base, tagged):
        assert a.arrival == b.arrival and a.max_new_tokens == b.max_new_tokens
        assert (a.prompt == b.prompt).all()
        assert a.tenant is None
        assert b.tenant in ("t0", "t1", "t2")
    again = make_workload(n=6, vocab=13, prompt_min=4, prompt_max=8,
                          out_min=6, out_max=18, rate=40.0, seed=5,
                          tenants=3)
    assert [r.tenant for r in again] == [r.tenant for r in tagged]


def test_per_tenant_registry_and_terminal_entries(engine, tmp_path):
    path = tmp_path / "run.jsonl"
    clock = FakeClock()
    registry = MetricsRegistry(clock=clock)
    reqs = make_workload(n=8, vocab=13, prompt_min=4, prompt_max=8,
                         out_min=6, out_max=18, rate=40.0, seed=5,
                         tenants=2)
    ticks = []
    res = engine.run(reqs, mode="continuous", time_fn=clock,
                     sleep_fn=clock.advance, registry=registry,
                     tick_sink=ticks.append)
    # Per-tenant histograms exist alongside the global twins, counts
    # matching the per-tenant summary.
    s = res.summary()
    for tenant, block in s["tenants"].items():
        n_fin = block["statuses"].get("finished", 0)
        h = registry.histograms[f"serve.tenant.{tenant}.ttft_ms"]
        assert h.count == n_fin
        assert registry.counters[
            f"serve.tenant.{tenant}.requests_finished"].value == n_fin
    assert registry.histograms["serve.ttft_ms"].count == \
        len(res.finished_requests)
    # Tick terminal entries cover every request exactly once, with the
    # same latency numbers as the request records.
    terms = [t for rec in ticks for t in rec["terminal"]]
    assert sorted(t["id"] for t in terms) == sorted(r.rid for r in reqs)
    by_id = {t["id"]: t for t in terms}
    for rec in res.request_records():
        assert by_id[rec["id"]]["tenant"] == rec["tenant"]
        assert by_id[rec["id"]]["ttft_ms"] == rec["ttft_ms"]
    # collect_terminals prefers the tick trail and tags the mode.
    recs = [make_record("tick", t["now"], **t) for t in ticks]
    collected = collect_terminals(recs)
    assert len(collected) == len(reqs)
    assert {mode for _, mode, _ in collected} == {"continuous"}
    verdicts = verdicts_from_terminals(
        collected, SLOSpec.from_dict(
            {"tenants": {"*": {"availability": 0.9}}}))
    assert sum(v.events for v in verdicts) == len(reqs)
