# Harness targets mirroring the reference Makefile's test_* form
# (reference Makefile:38-49: test_serial / test_mpi / test_cuda + get_mnist),
# plus the real test suite the reference never had.

PY ?= python
DATA_DIR ?= data/mnist
CPU8 := XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: bench_decode bench_speculative bench_serve bench_serve_spec bench_serve_hosttier bench_serve_pagedraft bench_fleet autosize chaos serve-baseline profile_lm profile_moe report health lint test test_all test_serial test_dp8 test_sp8 test_ep8 test_4d8 test_4d16 test_lm_tpu test_tpu bench bench_configs bench_configs_cpu8 bench_lm northstar northstar_digits native test_native test_native_tpu get_mnist get_cifar10 get_fashion clean

# Native C driver (CPU numerical reference + embedded-JAX TPU path).
native:
	$(MAKE) -C native

test_native: native
	$(MAKE) -C native test
	$(MAKE) -C native test_abi
	$(MAKE) -C native test_abi_lm

# C driver -> embedded JAX -> the real chip (run on a TPU host).
test_native_tpu: native
	$(MAKE) -C native test_tpu

# Unit/integration suite (CPU, 8 virtual devices — set in tests/conftest.py).
# Fast default: the heavy tests in conftest.SLOW_TESTS are skipped and the
# run fans out over cores (pytest-xdist -n auto; each worker gets its own
# 8-virtual-device jax). Measured 2026-07-31 (round 4, ~190 fast
# tests): 4:35-5:00 SERIAL across repeat runs on a loaded 1-core box
# (5:30 once while TPU benches shared the box) — the fast set meets the
# 5-min bar WITHOUT xdist on a quiet box; multicore boxes divide
# further. Every skipped subsystem keeps a fast representative
# (or a dryrun_multichip path with a serial-parity assert); `make
# test_all` is the full superset (367 tests, 32:53 measured serial at
# round-5 close, zero failures).
# pytest-xdist is optional: fan out when importable, serial otherwise.
XDIST := $(shell $(PY) -c "import xdist" 2>/dev/null && echo "-n auto")

test:
	$(PY) -m pytest tests/ -x -q $(XDIST)

test_all:
	$(PY) -m pytest tests/ -x -q --runslow

# Serial e2e smoke run (twin of `make test_serial`, reference Makefile:38).
# Uses synthetic data when $(DATA_DIR) has no MNIST IDX files.
test_serial:
	$(PY) -m mpi_cuda_cnn_tpu --dataset synthetic --model reference_cnn \
	  --epochs 2 --num-devices 1

# 8-way data-parallel e2e smoke run (twin of `make test_mpi`'s
# mpirun -np 8, reference Makefile:44) on a virtual CPU mesh.
# --device cpu (not the JAX_PLATFORMS env var): a pre-registered TPU
# plugin can intercept the env-var path; the in-process config is reliable.
test_dp8:
	$(CPU8) $(PY) -m mpi_cuda_cnn_tpu --dataset synthetic \
	  --model reference_cnn --epochs 2 --device cpu

# 8-way sequence-parallel LM e2e smoke (ring attention over seq:8,
# char-level on the framework's own sources) — the SP twin of test_dp8.
test_sp8:
	$(CPU8) $(PY) -m mpi_cuda_cnn_tpu lm --device cpu --corpus self \
	  --dim 64 --depth 2 --heads 8 --seq-len 128 --steps 30 \
	  --batch-size 4 --mesh-shape seq:8 --log-every 10

# Expert-parallel MoE LM e2e smoke: SP x DP mesh, 8 experts riding the
# 'seq' axis all_to_alls (parallel/ep.py) — the EP twin of test_dp8.
test_ep8:
	$(CPU8) $(PY) -m mpi_cuda_cnn_tpu lm --device cpu --corpus self \
	  --dim 64 --depth 2 --heads 8 --seq-len 128 --steps 30 \
	  --batch-size 4 --mesh-shape data:2,seq:4 --moe-experts 8 \
	  --log-every 10

# LM pipe x model x seq e2e smoke: Megatron blocks inside GPipe stages
# with ring attention over the sequence shards. Three of the four axes
# — 8 virtual devices can't also fit data:2; the FULL 2x2x2x2
# composition runs on 16 virtual devices via `make test_4d16` (serial
# parity asserted) and in dryrun path 15b.
test_4d8:
	$(CPU8) $(PY) -m mpi_cuda_cnn_tpu lm --device cpu --corpus self \
	  --dim 64 --depth 4 --heads 8 --seq-len 128 --steps 20 \
	  --batch-size 4 --mesh-shape pipe:2,model:2,seq:2 --grad-clip 1.0 \
	  --ce-chunk 32 --log-every 10

# The FULL 4D mesh — all four axes populated (pipe:2,model:2,seq:2,data:2
# = 16 virtual devices): one train step, exact serial parity (loss +
# updated params). The worker forces its own device count.
test_4d16:
	$(PY) scripts/fourd16_worker.py

# LM training on the visible accelerator (bf16 + flash kernel on TPU).
test_lm_tpu:
	$(PY) -m mpi_cuda_cnn_tpu lm --corpus self --dim 256 --depth 4 \
	  --seq-len 512 --steps 100 --batch-size 8 --compute-dtype bfloat16 \
	  --log-every 25

# Same on whatever accelerator is visible (TPU on a TPU VM).
# lr 0.02: with momentum 0.9 the effective step is ~10x lr, and plain
# constant-lr 0.1 diverges on lenet5_relu (the northstar recipe tames
# lr 0.1 with cosine decay instead).
test_tpu:
	$(PY) -m mpi_cuda_cnn_tpu --dataset synthetic --model lenet5_relu \
	  --init he --momentum 0.9 --lr 0.02 --epochs 2

bench:
	$(PY) bench.py

# All five BASELINE.json configs, one JSON line each, on the visible
# accelerator (multi-way DP configs clamp to the device count — the
# "mesh" field records what ran). bench_configs_cpu8 provisions the
# 8-virtual-device CPU mesh so the DP4/DP8 configs really fan out.
bench_configs:
	$(PY) scripts/bench_configs.py

# CPU variant: the four CPU-tractable configs with real 4/8-way DP on the
# virtual mesh (vgg_small needs an accelerator — run `make bench_configs`
# on a TPU host for all five).
bench_configs_cpu8:
	$(CPU8) $(PY) scripts/bench_configs.py --device cpu --num-train 1024 \
	  --configs lenet5,cifar3conv

# MFU-honest LM pretraining benchmark: ~34M-param transformer, s=2048,
# {f32,bf16} x {oracle,flash} matrix; prints tokens/s + MFU per config.
bench_lm:
	$(PY) scripts/bench_lm.py

# KV-cache decode benchmark: prefill + steady-state generation tokens/s,
# MHA vs GQA vs MQA cache sizes (two-point timing; scripts/bench_decode.py).
bench_decode:
	$(PY) scripts/bench_decode.py

# Speculative decoding benchmark: plain greedy vs model-draft vs draft-free
# prompt-lookup, acceptance measured end to end on trained models; output
# exactness asserted in-run (scripts/bench_speculative.py).
bench_speculative:
	$(PY) scripts/bench_speculative.py

# Serving benchmark: paged-KV continuous batching vs static batching
# under Poisson arrivals — throughput, TTFT, p50/p99 per-token latency
# (scripts/bench_serve.py == `mctpu serve-bench`).
bench_serve:
	$(PY) scripts/bench_serve.py

# Speculative serving (ISSUE 14): the spec-on/off tick-count pair on
# template traffic — per-slot prompt-lookup proposal + one batched
# verify per tick; outputs bitwise-equal, ticks drop with acceptance.
bench_serve_spec:
	$(PY) scripts/bench_serve.py --mode continuous --prefix-mix 0.9 \
	  --spec lookup --spec-k 8
	$(PY) scripts/bench_serve.py --mode continuous --prefix-mix 0.9

# Host-tier KV spill (ISSUE 17): the spill-on/off pair over a device
# pool tight against the template working set — spilled prefix pages
# readmit on the next hit instead of re-prefilling; outputs bitwise
# equal, the win is the prefill-chunk / hit-token counters (PERF.md).
bench_serve_hosttier:
	$(PY) scripts/bench_serve.py --mode continuous --prefix-mix 0.9 \
	  --templates 4 --pages 16 --prefix-cache --spill --host-pages 16
	$(PY) scripts/bench_serve.py --mode continuous --prefix-mix 0.9 \
	  --templates 4 --pages 16 --prefix-cache

# Paged draft-model KV cache (ISSUE 17): draft speculation with the
# persistent paged draft cache vs the cacheless ~W-row-recompute
# window draft — outputs bitwise equal, the win is draft FLOPs/round.
bench_serve_pagedraft:
	$(PY) scripts/bench_serve.py --mode continuous --prefix-mix 0.9 \
	  --spec draft --spec-k 8 --draft-cache paged
	$(PY) scripts/bench_serve.py --mode continuous --prefix-mix 0.9 \
	  --spec draft --spec-k 8 --draft-cache window

# Fleet storm benchmark: N replicas behind the failure-aware router,
# seeded Poisson arrivals, optional injected replica crashes/joins
# (`mctpu fleet-bench`; serve/fleet.py).
bench_fleet:
	$(PY) -m mpi_cuda_cnn_tpu fleet-bench --replicas 4 --requests 2000 \
	  --rate 500 --log summary

# Offline goodput-frontier capacity search (ISSUE 16, obs/autosize.py):
# candidate fleet topologies at a fixed chip budget, each a seeded
# SimCompute storm scored by SLO-attained goodput; deterministic,
# CRC-stamped (ci/autosize_gate.json pins the CI twin at 0%/equal).
# Seed the sweep from a finished run's blame profile with
#   make autosize SEED_FROM=run.jsonl
autosize:
	$(PY) -m mpi_cuda_cnn_tpu autosize --budget 4 --requests 2000 \
	  --rate 300 --len-dist both $(if $(SEED_FROM),--seed-from $(SEED_FROM))

# Seeded fault-schedule search (ISSUE 19, chaos/): N sampled
# (axes, plan) episodes, each a small fleet storm under a multi-fault
# plan drawn from faults.SITES, held to the global invariant oracle
# (exactly-once terminals with closed-form outputs, blame
# conservation, clean pools at exit, zero-drift replay, same-seed
# bitwise). On a violation the plan is ddmin-shrunk to a one-line
# `--plan` repro and the minimal episode's twin trails land in
# chaos_out/ pre-wired for `mctpu diverge`. CI runs the seed-7
# 50-episode sweep twice under ci/chaos_gate.json; vary locally with
#   make chaos EPISODES=200 SEED=3
EPISODES ?= 50
SEED ?= 7
chaos:
	$(PY) -m mpi_cuda_cnn_tpu chaos --episodes $(EPISODES) \
	  --seed $(SEED) --out-dir chaos_out

# Regenerate the committed CI serving baseline (ci/serve_baseline.jsonl)
# with the pinned arguments CI's candidate run uses — refresh after a
# DELIBERATE scheduling change, commit alongside it; procedure in
# scripts/make_serve_baseline.py and ci/serve_gate.json.
serve-baseline:
	$(PY) scripts/make_serve_baseline.py

# Step-time attribution by ablation (full vs fwd-only vs identity-attn vs
# no-head vs chunked-CE) — where the LM step's milliseconds go.
profile_lm:
	$(PY) scripts/profile_lm.py

# MoE component attribution (router/dispatch-einsum/expert-FFN/combine in
# isolation + the moe_mlp body per dispatch_chunk + E x cf sweep) — the
# single-chip quadratic-dispatch evidence (scripts/profile_moe.py).
profile_moe:
	$(PY) scripts/profile_moe.py --sweep

# Summarize a metrics JSONL run (--metrics-jsonl sink) as markdown tables:
#   make report RUN=run.jsonl
report:
	$(PY) scripts/obs_report.py $(RUN)

# Per-tenant SLO verdict table + alert replay for a finished run
# (obs/health.py; exit 1 on violation — the CI health gate):
#   make health RUN=run.jsonl SLO=ci/slo_gate.json
health:
	$(PY) -m mpi_cuda_cnn_tpu health $(RUN) $(if $(SLO),--slo $(SLO))

# Deterministic flight-recorder replay (ISSUE 15, obs/replay.py):
# reconstruct the full serving state from a --log full trail,
# cross-checking the stamped per-tick state_crc (exit 1 on drift):
#   make replay RUN=run.jsonl [TICK=4000]
# First-divergence localization between two identical-seed trails:
#   make diverge A=run_a.jsonl B=run_b.jsonl
replay:
	$(PY) -m mpi_cuda_cnn_tpu replay $(RUN) $(if $(TICK),--at-tick $(TICK))

diverge:
	$(PY) -m mpi_cuda_cnn_tpu diverge $(A) $(B)

# Style gate + the framework-invariant analyzer (ISSUE 10): ruff at
# the pyproject scope, then `mctpu lint` (rules MCT001-MCT007 — jax
# purity, clock/RNG/donation discipline, schema/fault-site
# cross-checks, hot-loop host-sync) as JSON against the committed
# zero-entry baseline. Exit nonzero on any finding — the same pair CI
# runs. ruff is optional locally (skipped with a note if absent).
lint:
	@if command -v ruff >/dev/null 2>&1; then ruff check .; \
	  else echo "ruff not installed — skipping style half (CI runs it)"; fi
	$(PY) -m mpi_cuda_cnn_tpu lint --format json \
	  --baseline ci/lint_baseline.json

# North-star recipe (BASELINE.json): LeNet-5(relu) to >=99% MNIST test
# accuracy — he init, momentum, cosine decay, random-shift augmentation.
# Trains on real MNIST when $(DATA_DIR) holds the IDX files (make
# get_mnist; needs network), synthetic stripes otherwise.
northstar:
	$(PY) -m mpi_cuda_cnn_tpu \
	  $(if $(wildcard $(DATA_DIR)/train-images-idx3-ubyte),\
	  $(DATA_DIR)/train-images-idx3-ubyte $(DATA_DIR)/train-labels-idx1-ubyte \
	  $(DATA_DIR)/t10k-images-idx3-ubyte $(DATA_DIR)/t10k-labels-idx1-ubyte,\
	  --dataset synthetic) \
	  --model lenet5_relu --init he --epochs 20 --batch-size 128 --lr 0.1 \
	  --momentum 0.9 --lr-schedule cosine --augment shift --eval-every 5

# Same recipe on REAL handwritten digits (scikit-learn's bundled UCI set
# — available with zero network). Measured 99.4% test accuracy on a v5e
# chip (2026-07-30), clearing the >=99% north-star bar on real data.
northstar_digits:
	$(PY) -m mpi_cuda_cnn_tpu --dataset digits --model lenet5_relu \
	  --init he --epochs 30 --batch-size 128 --lr 0.05 --momentum 0.9 \
	  --lr-schedule cosine --augment shift --aug-pad 1 --eval-every 10

# Fetch MNIST as the four IDX files (twin of get_mnist, reference
# Makefile:24-35). Requires network access.
get_mnist:
	mkdir -p $(DATA_DIR)
	$(PY) scripts/get_mnist.py $(DATA_DIR)

# Fetch + convert CIFAR-10 (binary batches -> IDX, md5/sha256-checked)
# and Fashion-MNIST (IDX upstream). Network-gated; the CIFAR converter
# itself is selftested offline (tests/test_data.py).
get_cifar10:
	$(PY) scripts/get_cifar10.py data/cifar10

get_fashion:
	$(PY) scripts/get_fashion.py data/fashion_mnist

clean:
	rm -rf __pycache__ */__pycache__ .pytest_cache build dist
