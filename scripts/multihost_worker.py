"""One process of a multi-host DP training demo.

The multi-host twin of the reference's `mpirun -np 8` world (Makefile:44):
each process calls `jax.distributed.initialize` (the MPI_Init replacement,
cnnmpi.c:419), after which `jax.devices()` is the GLOBAL device list and
the ordinary DP train step runs unchanged — collectives cross process
boundaries via the runtime (ICI/DCN on a real pod; TCP here on CPU).

Usage (one line per "host"):
    python scripts/multihost_worker.py <pid> <nproc> <coordinator> \
        [devs_per_proc] [mode]

mode "cnn" (default): the DP CNN step. mode "lm": RING sequence
parallelism for the transformer LM over the GLOBAL mesh — the k/v blocks
ppermute across the OS-process boundary (multi-host long context).
mode "pp": GPipe pipeline parallelism with the stage boundary ON the
process boundary — a ('pipe': 2, 'data': gdev/2) mesh places stage 0's
devices in process 0 and stage 1's in process 1, so every microbatch
activation (and its cotangent in backward) ppermutes between processes.

Every process feeds the SAME global batch (the reference's every-rank-
loads-the-full-dataset pattern, cnnmpi.c:426-454, made correct); the
printed loss must therefore be identical on every process.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parent.parent))


def _synthetic_batch(batch):
    """Same seed in every process -> the SAME global batch everywhere (the
    reference's every-rank-loads-the-full-dataset pattern, made correct)."""
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.random((batch, 28, 28, 1), np.float32))
    y = np.zeros((batch, 10), np.float32)
    y[np.arange(batch), rng.integers(0, 10, batch)] = 1.0
    return x, jnp.asarray(y)


def _print_mhok(info, metrics) -> int:
    """The one line tests/test_multihost.py greps; metrics are replicated
    (P() out-specs), so float() is safe in every process."""
    import jax

    jax.block_until_ready(metrics)
    print(
        f"MHOK pid={info.process_index} procs={info.process_count} "
        f"gdev={info.global_devices} loss={float(metrics['loss']):.6f}",
        flush=True,
    )
    return 0


def main() -> int:
    pid, nproc = int(sys.argv[1]), int(sys.argv[2])
    coordinator = sys.argv[3]
    devs = int(sys.argv[4]) if len(sys.argv) > 4 else 4
    mode = sys.argv[5] if len(sys.argv) > 5 else "cnn"

    import jax

    # In-process CPU selection (the env-var path can be intercepted by a
    # pre-registered TPU plugin — same reason as tests/conftest.py).
    jax.config.update("jax_platforms", "cpu")
    # Replace (don't append to) any inherited device-count flag — e.g. the
    # one tests/conftest.py exports — so XLA never sees two conflicting
    # occurrences.
    import re

    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={devs}"
    ).strip()
    from mpi_cuda_cnn_tpu.parallel.distributed import initialize_distributed

    info = initialize_distributed(
        coordinator_address=coordinator, num_processes=nproc, process_id=pid
    )
    assert info.process_count == nproc, info

    import jax.numpy as jnp

    if mode == "lm":
        return _lm_main(info)
    if mode == "pp":
        return _pp_main(info)
    if mode == "4d":
        return _4d_main(info)

    from mpi_cuda_cnn_tpu.models.initializers import get_initializer
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.parallel.dp import (
        dp_shard_batch,
        make_dp_train_step,
        replicate,
    )
    from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
    from mpi_cuda_cnn_tpu.train.trainer import make_loss_fn

    mesh = make_mesh()  # all GLOBAL devices on the data axis
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    optimizer = make_optimizer(0.1)
    state = replicate(
        {"params": params, "opt_state": optimizer.init(params),
         "step": jnp.zeros((), jnp.int32)},
        mesh,
    )
    step = make_dp_train_step(make_loss_fn(model), optimizer, mesh, donate=False)

    x, y = _synthetic_batch(2 * info.global_devices)
    xs, ys = dp_shard_batch((x, y), mesh)

    state, metrics = step(state, xs, ys)
    return _print_mhok(info, metrics)


def _lm_main(info) -> int:
    """Ring-SP LM step over the global mesh: every device holds S/gdev
    tokens; k/v blocks rotate through EVERY device — including across
    the process boundary (the multi-host long-context path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.mesh import make_mesh
    from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS, make_sp_lm_train_step

    gdev = info.global_devices
    mesh = make_mesh({SEQ_AXIS: gdev})
    # GQA + rope: the round-2 features ride the multi-host ring too.
    model = TransformerLM(vocab=13, dim=16, heads=4, depth=1,
                          max_seq=8 * gdev, kv_heads=2, pos="rope")
    params = model.init(jax.random.key(0))
    opt = optax.sgd(0.1)
    state = {"params": params, "opt_state": opt.init(params),
             "step": jnp.zeros((), jnp.int32)}
    step = make_sp_lm_train_step(model, opt, mesh, impl="ring",
                                 donate=False)
    rng = np.random.default_rng(7)  # same seed everywhere -> same tokens
    toks = jnp.asarray(rng.integers(0, 13, (2, 8 * gdev + 1)), jnp.int32)
    _, metrics = step(state, toks[:, :-1], toks[:, 1:])
    return _print_mhok(info, metrics)


def _pp_main(info) -> int:
    """2-stage GPipe across the process boundary: with 2 processes and
    the 'pipe' axis outermost, stage 0 lives entirely in process 0 and
    stage 1 in process 1 — the forward activation handoff and the
    backward cotangent handoff both cross OS processes (the multi-host
    pipeline path; the reference never pipelined at all)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_cuda_cnn_tpu.models.initializers import get_initializer
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, make_mesh
    from mpi_cuda_cnn_tpu.parallel.pp import (
        make_pipeline_plan,
        make_pp_state,
        make_pp_train_step,
        microbatch,
        pp_shard_batch,
    )
    from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer

    gdev = info.global_devices
    mesh = make_mesh({PIPE_AXIS: 2, DATA_AXIS: gdev // 2})
    model = get_model("reference_cnn")
    params = model.init(jax.random.key(0), get_initializer("normal"))
    optimizer = make_optimizer(0.1)
    plan = make_pipeline_plan(model, 2)
    state = make_pp_state(plan, params, optimizer, mesh)
    step = make_pp_train_step(plan, optimizer, mesh, state, donate=False)

    x, y = _synthetic_batch(2 * gdev)  # divisible by M x data = 2 x gdev/2
    x_mb, y_mb = pp_shard_batch(microbatch(x, y, 2), mesh)

    state, metrics = step(state, x_mb, y_mb)
    return _print_mhok(info, metrics)


def _4d_main(info) -> int:
    """The LM's full pipe x model x seq mesh split over 2 OS processes:
    'pipe' outermost puts the GPipe stage boundary ON the process
    boundary, while the Megatron psums (over 'model') and the ring
    attention ppermutes (over 'seq') run within each process — the
    layout a real pod uses (TP/SP inside a host on ICI, PP across on
    DCN). Every collective family the framework has crosses or rides
    the distributed runtime in ONE step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.parallel.mesh import MODEL_AXIS, PIPE_AXIS, make_mesh
    from mpi_cuda_cnn_tpu.parallel.pp_lm import (
        pp_lm_microbatch,
        sp_pp_shard_batch,
    )
    from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS
    from mpi_cuda_cnn_tpu.parallel.tp_pp_lm import (
        make_tp_pp_lm_state,
        make_tp_pp_lm_train_step,
    )

    assert info.global_devices == 8, info
    mesh = make_mesh({PIPE_AXIS: 2, MODEL_AXIS: 2, SEQ_AXIS: 2})
    model = TransformerLM(vocab=13, dim=16, heads=2, depth=2, max_seq=16)
    params = model.init(jax.random.key(0))
    opt = optax.sgd(0.1)
    state = make_tp_pp_lm_state(model, params, opt, mesh)
    step = make_tp_pp_lm_train_step(model, opt, mesh, state,
                                    donate=False, attn_impl="ring")
    rng = np.random.default_rng(7)  # same seed everywhere -> same tokens
    toks = jnp.asarray(rng.integers(0, 13, (2, 17)), jnp.int32)
    mb = sp_pp_shard_batch(
        pp_lm_microbatch(toks[:, :-1], toks[:, 1:], 2), mesh
    )
    state, metrics = step(state, *mb)
    return _print_mhok(info, metrics)


if __name__ == "__main__":
    sys.exit(main())
