"""Fetch Fashion-MNIST (already IDX; gzip-compressed upstream).

Same four-file contract as get_mnist.py; checksummed. Zero-network
environments get a clear error (the files are plain IDX — no converter
to selftest beyond the reader, which tests/test_idx.py covers).

    python scripts/get_fashion.py data/fashion_mnist
"""

from __future__ import annotations

import gzip
import hashlib
import json
import sys
import urllib.request
from pathlib import Path

BASE = "https://fashion-mnist.s3-website.eu-central-1.amazonaws.com/"
FILES = {
    "train-images-idx3-ubyte": "8d4fb7e6c68d591d4c3dfef9ec88bf0d",
    "train-labels-idx1-ubyte": "25c81989df183df01b3e8a0aad5dffbe",
    "t10k-images-idx3-ubyte": "bef4ecab320f06d8554ea6380940ec79",
    "t10k-labels-idx1-ubyte": "bb300cfdad3c16e7a12a480ee83cd310",
}


def main(out_dir: str) -> int:
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    manifest = {}
    old = {}
    mpath = out / "manifest.json"
    if mpath.exists():
        old = json.loads(mpath.read_text())
    for name, md5 in FILES.items():
        dest = out / name
        # A pre-existing file counts only if it matches the recorded
        # sha256 (a truncated leftover from an interrupted run must not
        # be accepted just for existing); otherwise re-fetch.
        if dest.exists() and (
            hashlib.sha256(dest.read_bytes()).hexdigest() != old.get(name)
        ):
            dest.unlink()
        if not dest.exists():
            url = BASE + name + ".gz"
            print(f"fetching {url}", file=sys.stderr)
            try:
                gz = urllib.request.urlopen(url, timeout=60).read()
            except Exception as e:
                print(
                    f"fetch failed ({e}); no network egress here — rerun "
                    "where the Fashion-MNIST mirror is reachable.",
                    file=sys.stderr,
                )
                return 1
            if hashlib.md5(gz).hexdigest() != md5:
                print(f"md5 mismatch for {name}.gz", file=sys.stderr)
                return 1
            dest.write_bytes(gzip.decompress(gz))
        manifest[name] = hashlib.sha256(dest.read_bytes()).hexdigest()
    (out / "manifest.json").write_text(json.dumps(manifest, indent=2))
    print(json.dumps(manifest, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "data/fashion_mnist"))
