"""Attribute the LM train-step wall-clock to its components.

VERDICT round 2: "32.4% MFU is good; the remaining 68% is unexplained."
This script explains it by ABLATION — each row times a program with one
component removed or swapped, all with the same two-point method as
scripts/bench_lm.py ((T2N - TN)/N cancels the fixed tunnel round-trip),
completion forced by a host fetch:

  full_step        fwd + bwd + AdamW update (the real train step)
  fwd_only         loss forward alone -> bwd+update = full - fwd
  fwd_identity_attn  forward with attention replaced by (q,k,v)->v
                     -> attention fwd share = fwd_only - this
  fwd_no_head      forward returning mean(features) (no head matmul, no
                     CE) -> head+CE share = fwd_only - this
  full_ce_chunked  the fused chunked-CE step (train/lm.lm_loss ce_chunk)
                     -> what the (B,S,V) f32 logits materialization costs

Differences of measurements, not a tracer: coarse (shares overlap where
XLA fuses across seams) but honest, and enough to rank where the next
milliseconds are. A jax.profiler trace dir can be captured alongside
(--profile-dir) for manual inspection in TensorBoard.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs import cost as obs_cost
from mpi_cuda_cnn_tpu.train.lm import (
    get_attn_fn,
    lm_loss,
    make_lm_state,
    make_lm_train_step,
)
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
from mpi_cuda_cnn_tpu.utils.sync import hard_block as _force
from mpi_cuda_cnn_tpu.utils.sync import two_point


def _two_point(fn, steps):
    return two_point(fn, steps, warmup=2)


def _timed_loop(step_fn, state0, *args):
    def run(n):
        state = state0
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            state, out = step_fn(state, *args)
        _force(out)
        return time.perf_counter() - t0

    return run


def _timed_fwd(loss_fn, params, *args):
    def run(n):
        t0 = time.perf_counter()
        acc = None
        for _ in range(n):
            # Chain through the loss scalar so iterations are dependent
            # (XLA cannot elide or overlap them into one).
            out = loss_fn(params, *args) + (acc if acc is not None else 0.0)
            acc = out * 0.0
        _force(out)
        return time.perf_counter() - t0

    return run


def _accum_ablation(model, opt, state, tokens, targets, *, accum, cd,
                    attn_impl, attn_fn, steps):
    """Attribute the per-microbatch grad-accumulation overhead (the
    fitted ~8 ms/microbatch at the flagship, PERF.md) by ABLATION, the
    same differences-of-measurements method as the main rows:

      accum_full          the real accum step (scan + tree carry + AdamW)
      accum_no_update     same accumulation, optimizer update removed
                          -> update share = full - no_update
      accum_scalar_carry  scan runs every fwd+bwd but the carry holds
                          per-leaf SCALAR sums (backward cannot be
                          DCE'd; no grad-tree-extent add/read/write)
                          -> tree-carry share/microbatch =
                             (no_update - scalar_carry) / accum
      plain_no_update     one full-batch fwd+bwd, no scan, no update
                          -> scan/microbatching share/microbatch =
                             (scalar_carry - plain_no_update) / accum

    Coarse where XLA fuses across the seams (the carry add can ride the
    backward epilogue — then the tree-carry share reads ~0 and the floor
    is proven fused), but honest: every row is a measured program.
    """
    from mpi_cuda_cnn_tpu.parallel.dp import local_grads_no_aux
    from mpi_cuda_cnn_tpu.train.lm import lm_loss as _lm_loss

    def loss_fn(p, t, y):
        return _lm_loss(model, p, t, y, attn_fn=attn_fn, compute_dtype=cd)

    def split(t):
        a = accum
        return t.reshape(t.shape[0] // a, a, *t.shape[1:]).swapaxes(0, 1)

    @jax.jit
    def accum_no_update(state, tokens, targets):
        l, grads = local_grads_no_aux(
            loss_fn, state["params"], tokens, targets, accum
        )
        # Consume the grads at scalar extent so the accumulation isn't
        # dead code; the optimizer update is the only thing removed.
        return state, {"loss": l + 0.0 * sum(
            jnp.sum(g) for g in jax.tree.leaves(grads)
        )}

    @jax.jit
    def accum_scalar_carry(state, tokens, targets):
        xs, ys = split(tokens), split(targets)

        def body(c, xy):
            l, grads = jax.value_and_grad(loss_fn)(state["params"], *xy)
            s = sum(jnp.sum(g) for g in jax.tree.leaves(grads))
            return (c[0] + l, c[1] + s), None

        (l, s), _ = jax.lax.scan(
            body, (jnp.float32(0), jnp.float32(0)), (xs, ys)
        )
        return state, {"loss": l / accum + 0.0 * s}

    @jax.jit
    def plain_no_update(state, tokens, targets):
        l, grads = jax.value_and_grad(loss_fn)(
            state["params"], tokens, targets
        )
        return state, {"loss": l + 0.0 * sum(
            jnp.sum(g) for g in jax.tree.leaves(grads)
        )}

    from mpi_cuda_cnn_tpu.train.lm import make_lm_train_step

    accum_full = make_lm_train_step(
        model, opt, attn_impl=attn_impl, seq_len=tokens.shape[1],
        compute_dtype=cd, donate=False, grad_accum=accum,
    )

    rows = {}
    for name, fn in (
        ("accum_full", accum_full),
        ("accum_no_update", accum_no_update),
        ("accum_scalar_carry", accum_scalar_carry),
        ("plain_no_update", plain_no_update),
    ):
        rows[name] = _two_point(
            _timed_loop(fn, state, tokens, targets), steps
        )
    ms = {k: round(v * 1e3, 2) for k, v in rows.items()}
    a = accum
    derived = {
        "update_ms": round(ms["accum_full"] - ms["accum_no_update"], 2),
        "tree_carry_ms_per_microbatch": round(
            (ms["accum_no_update"] - ms["accum_scalar_carry"]) / a, 3
        ),
        "scan_overhead_ms_per_microbatch": round(
            (ms["accum_scalar_carry"] - ms["plain_no_update"]) / a, 3
        ),
    }
    costs = obs_cost.try_analyze(accum_full, state, tokens, targets)
    return ms, derived, costs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    ap.add_argument("--attn", default="flash", choices=["flash", "oracle"])
    ap.add_argument("--ce-chunk", type=int, default=512)
    ap.add_argument("--grad-accum", type=int, default=0,
                    help="> 1: run the grad-accumulation overhead "
                         "ablation instead of the step-component rows "
                         "(attributes the per-microbatch cost to tree "
                         "carry vs scan machinery vs update)")
    ap.add_argument("--profile-dir", default=None,
                    help="also capture a jax.profiler trace of one step")
    ap.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    args = ap.parse_args()

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        raise SystemExit(1)

    cd = jnp.bfloat16 if args.dtype == "bfloat16" else None
    model = TransformerLM(vocab=args.vocab, dim=args.dim, heads=args.heads,
                          depth=args.depth, max_seq=args.seq)
    opt = make_optimizer(3e-4, opt="adamw", schedule="constant")
    state = make_lm_state(model, opt, 0)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(
        rng.integers(0, model.vocab, (args.batch, args.seq + 1)), jnp.int32
    )
    tokens, targets = toks[:, :-1], toks[:, 1:]
    attn_fn = get_attn_fn(args.attn)

    if args.grad_accum > 1:
        if args.batch % args.grad_accum:
            raise SystemExit(
                f"--batch {args.batch} not divisible by --grad-accum "
                f"{args.grad_accum}"
            )
        ms, derived, costs = _accum_ablation(
            model, opt, state, tokens, targets, accum=args.grad_accum,
            cd=cd, attn_impl=args.attn, attn_fn=attn_fn, steps=args.steps,
        )
        print(json.dumps({
            "bench": "lm_accum_profile",
            "model": f"d{args.dim}x{args.depth} h{args.heads} "
                     f"s{args.seq} v{args.vocab} b{args.batch} "
                     f"{args.dtype}+{args.attn} accum{args.grad_accum}",
            **ms, **derived,
            "flops_per_step": costs.flops if costs else None,
            "bytes_per_step": costs.bytes_accessed if costs else None,
            "aliased_outputs": costs.aliased_outputs if costs else None,
            "alias_bytes": costs.alias_bytes if costs else None,
            "backend": jax.default_backend(),
        }))
        return

    rows = {}

    # full train step (fwd+bwd+update), dense CE — the bench_lm headline.
    step = make_lm_train_step(model, opt, attn_impl=args.attn,
                              seq_len=args.seq, compute_dtype=cd,
                              donate=False)
    rows["full_step"] = _two_point(_timed_loop(step, state, tokens, targets),
                                   args.steps)

    # fused chunked-CE step.
    step_cc = make_lm_train_step(model, opt, attn_impl=args.attn,
                                 seq_len=args.seq, compute_dtype=cd,
                                 donate=False, ce_chunk=args.ce_chunk)
    rows["full_ce_chunked"] = _two_point(
        _timed_loop(step_cc, state, tokens, targets), args.steps
    )

    # forward-only ablations.
    def fwd(attn, no_head):
        if no_head:
            def f(p, t, y):
                feats = model.apply(p, t, attn_fn=attn, compute_dtype=cd,
                                    return_features=True)
                return jnp.mean(feats.astype(jnp.float32))
        else:
            def f(p, t, y):
                return lm_loss(model, p, t, y, attn_fn=attn,
                               compute_dtype=cd)
        return jax.jit(f)

    rows["fwd_only"] = _two_point(
        _timed_fwd(fwd(attn_fn, False), state["params"], tokens, targets),
        args.steps,
    )
    rows["fwd_identity_attn"] = _two_point(
        _timed_fwd(fwd(lambda q, k, v: v, False), state["params"],
                   tokens, targets),
        args.steps,
    )
    rows["fwd_no_head"] = _two_point(
        _timed_fwd(fwd(attn_fn, True), state["params"], tokens, targets),
        args.steps,
    )

    if args.profile_dir:
        with jax.profiler.trace(args.profile_dir):
            _force(step(state, tokens, targets)[1])

    ms = {k: round(v * 1e3, 2) for k, v in rows.items()}
    derived = {
        "bwd_update_ms": round(ms["full_step"] - ms["fwd_only"], 2),
        "attn_fwd_ms": round(ms["fwd_only"] - ms["fwd_identity_attn"], 2),
        "head_ce_fwd_ms": round(ms["fwd_only"] - ms["fwd_no_head"], 2),
        "ce_chunk_delta_ms": round(
            ms["full_ce_chunked"] - ms["full_step"], 2
        ),
    }
    tokens_per_step = args.batch * args.seq
    # FLOPs of the COMPILED full step (obs/cost.py XLA cost analysis),
    # not an analytic formula — the number matches the program the rows
    # above timed, byte-accounting included.
    costs = obs_cost.try_analyze(step, state, tokens, targets)
    print(json.dumps({
        "bench": "lm_profile",
        "model": f"d{args.dim}x{args.depth} h{args.heads} s{args.seq} "
                 f"v{args.vocab} b{args.batch} {args.dtype}+{args.attn}",
        **ms, **derived,
        "tokens_per_s": round(tokens_per_step / rows["full_step"]),
        "flops_per_step": costs.flops if costs else None,
        "bytes_per_step": costs.bytes_accessed if costs else None,
        "collectives": costs.collectives if costs else None,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
