#!/bin/sh
# Session-long TPU watcher: probe the backend every PERIOD seconds and run
# the full capture (scripts/tpu_capture.sh) in the FIRST healthy window.
# A dead axon backend HANGS at init rather than erroring, so the probe is
# a subprocess under timeout. Every attempt is recorded in capture.log and
# PERF_capture.jsonl — if the backend stays dead all round, that log IS
# the deliverable (VERDICT round-3 item 1).
# Usage: sh scripts/tpu_watch.sh [period_s] [max_tries]

set -u
PERIOD=${1:-1200}
MAX=${2:-40}
i=0
while [ "$i" -lt "$MAX" ]; do
    i=$((i + 1))
    ts=$(date -u +%FT%TZ)
    echo "== watch probe $i/$MAX $ts ==" >> capture.log
    timeout 120 python -c "
import sys, jax, jax.numpy as jnp
x = jnp.ones((1024, 1024), jnp.bfloat16)
(x @ x).block_until_ready()
backend = jax.default_backend()
print('probe', backend)
# A CPU fallback must NOT trigger the capture — its numbers would be
# recorded as the round's TPU perf deliverable.
sys.exit(0 if backend == 'tpu' else 2)" >> capture.log 2>&1
    rc=$?
    printf '{"watch_probe": %d, "rc": %d, "utc": "%s"}\n' "$i" "$rc" "$ts" \
        >> PERF_capture.jsonl
    if [ "$rc" -eq 0 ]; then
        echo "backend ALIVE at probe $i; running full capture" >> capture.log
        sh scripts/tpu_capture.sh
        exit $?
    fi
    sleep "$PERIOD"
done
echo "watcher exhausted $MAX probes; backend never came up" >> capture.log
exit 1
