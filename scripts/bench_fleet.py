"""Thin wrapper for the fleet bench (mpi_cuda_cnn_tpu.serve.bench) —
`python scripts/bench_fleet.py ...` == `mctpu fleet-bench ...`: N
single-engine replicas behind the failure-aware router under a seeded
Poisson storm, with optional injected replica crashes/joins/leaves,
deterministic under FakeClock (serve/fleet.py, ISSUE 7)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_cnn_tpu.serve.bench import fleet_bench_main

if __name__ == "__main__":
    sys.exit(fleet_bench_main())
