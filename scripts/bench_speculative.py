"""Speculative-decoding benchmark: plain greedy vs draft-verified.

Decode at B=1 is latency-bound: every token pays a full sequential
target forward. speculative_generate (models/generate.py) lets a cheap
draft propose k-token chains the target verifies in ONE decode_block
forward — tokens/s scales with the acceptance rate, and the output is
bit-identical to plain greedy by construction (the equality test in
tests/test_generate.py pins it; this bench asserts it again on the real
run).

Acceptance depends on how well the draft predicts the target, so the
bench constructs the honest best case END TO END: both models train on
the cyclic-successor corpus (the deterministic task the test suite's
convergence tests use) until both predict it near-perfectly, then
decode measures plain vs speculative at several k with the REAL
acceptance the trained pair achieves — plus the random-draft worst case
(acceptance ~1/vocab) so both ends of the curve are on record.

One JSON line per row + a summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.models.generate import generate, speculative_generate
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs.schema import make_record
from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
from mpi_cuda_cnn_tpu.utils.sync import hard_block, two_point

_T0 = time.perf_counter()


def train_on_cycle(model, *, steps, batch, seq, lr=3e-3, seed=0):
    """Fit `model` to token[t+1] = token[t] + 1 (mod vocab)."""
    opt = make_optimizer(lr, opt="adamw", schedule="constant")
    step_fn = make_lm_train_step(model, opt, attn_impl="oracle",
                                 seq_len=seq)
    state = make_lm_state(model, opt, seed)
    rng = np.random.default_rng(seed)
    loss = float("nan")
    for _ in range(steps):
        starts = rng.integers(0, model.vocab, size=(batch, 1))
        w = (starts + np.arange(seq + 1)[None, :]) % model.vocab
        toks = jnp.asarray(w, jnp.int32)
        state, m = step_fn(state, toks[:, :-1], toks[:, 1:])
        loss = m["loss"]
    return state["params"], float(loss)


def train_on_text(model, tokens, *, steps, batch, seq, lr=1e-3, seed=0):
    """Fit `model` to a real token stream (random windows, the
    LMTrainer._sample_batch scheme) — for the self-corpus lookup row."""
    opt = make_optimizer(lr, opt="adamw", schedule="constant")
    step_fn = make_lm_train_step(model, opt, attn_impl="oracle",
                                 seq_len=seq)
    state = make_lm_state(model, opt, seed)
    rng = np.random.default_rng(seed)
    n = len(tokens) - seq
    loss = float("nan")
    for _ in range(steps):
        starts = rng.integers(0, n, size=batch)
        idx = starts[:, None] + np.arange(seq + 1)[None, :]
        w = jnp.asarray(tokens[idx], jnp.int32)
        state, m = step_fn(state, w[:, :-1], w[:, 1:])
        loss = m["loss"]
    return state["params"], float(loss)


def timed_tokens(fn, n, attempts=3, floor=0.0):
    """(s/token, suspect) of a generate-style call via the shared
    two-point core: fn(m) must produce m tokens and force completion.
    A backend transient can push even the median-of-3 slope NEGATIVE
    (observed: a banked -0.095 ms/tok row) or impossibly FAST (observed
    round 5: a lookup-k8 slope reading 85x speedup, ~7x above every
    healthy window's measurement) — a value at or below `floor` is
    re-measured up to `attempts` times. Callers pass plain/(k*4) for
    speculative modes (per-round emit <= k tokens; banked legitimate
    rows reach ~2x k because the verify block + while_loop amortize far
    better than one plain step per round, and a first 3x-k margin was
    itself outrun by a healthy window). If every attempt stays at or
    below the floor the LAST positive sample is returned with
    suspect=True — the row is emitted flagged, never silently dropped
    and never allowed to kill the remaining bench rows (a raise here
    cost one banked capture its speculative section; non-positive
    slopes with no positive sample at all still raise)."""

    def run(m):
        t0 = time.perf_counter()
        hard_block(fn(m))
        return time.perf_counter() - t0

    run(n), run(2 * n)  # warm both program sizes
    last_positive = None
    for _ in range(attempts):
        t = two_point(run, n, warmup=0)
        if t > floor:
            return t, False
        if t > 0:
            last_positive = t
    if last_positive is not None:
        return last_positive, True
    raise RuntimeError(
        f"two-point slope stayed non-positive over {attempts} "
        "median-of-3 attempts — backend too unstable to measure"
    )


def try_timed(fn, n, floor):
    """timed_tokens for the SPECULATIVE rows: an unmeasurable mode
    (persistently non-positive slope) returns (None, True) so the
    caller emits a skipped row and the bench CONTINUES — one jittery
    mode must not cost the capture every later row (it did once:
    banked bench_speculative_final_r5 rc=1). The plain baselines keep
    the raise — without them the speedup columns mean nothing."""
    try:
        return timed_tokens(fn, n, floor=floor)
    except RuntimeError:
        return None, True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--draft-dim", type=int, default=128)
    ap.add_argument("--draft-depth", type=int, default=1)
    ap.add_argument("--vocab", type=int, default=251)
    ap.add_argument("--max-seq", type=int, default=2048)
    ap.add_argument("--prompt", type=int, default=64)
    ap.add_argument("--tokens", type=int, default=256)
    ap.add_argument("--ks", default="2,4,8")
    ap.add_argument("--train-steps", type=int, default=150)
    ap.add_argument("--self-corpus-steps", type=int, default=300,
                    help="train a fresh target on the framework's own "
                         "sources and measure lookup speculation on real "
                         "code — the technique's claimed use case; 0 "
                         "disables the row")
    ap.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    args = ap.parse_args()

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        raise SystemExit(1)

    target = TransformerLM(vocab=args.vocab, dim=args.dim,
                           heads=args.heads, depth=args.depth,
                           max_seq=args.max_seq)
    draft = TransformerLM(vocab=args.vocab, dim=args.draft_dim,
                          heads=2, depth=args.draft_depth,
                          max_seq=args.max_seq)
    t_params, t_loss = train_on_cycle(
        target, steps=args.train_steps, batch=8, seq=128
    )
    d_params, d_loss = train_on_cycle(
        draft, steps=4 * args.train_steps, batch=8, seq=128
    )
    prompt = jnp.asarray(
        (np.arange(args.prompt)[None, :] % args.vocab), jnp.int32
    )

    t_plain, _ = timed_tokens(
        lambda m: generate(target, t_params, prompt, m), args.tokens
    )
    want = np.asarray(generate(target, t_params, prompt, args.tokens))
    rows = [{
        "bench": "speculative", "mode": "plain_greedy",
        "ms_per_tok": round(t_plain * 1e3, 3),
        "tokens_per_s": round(1.0 / t_plain),
        "target_loss": round(t_loss, 4), "draft_loss": round(d_loss, 4),
    }]
    print(json.dumps(rows[0]), flush=True)

    best = (rows[0]["tokens_per_s"], "plain")
    for k in (int(x) for x in args.ks.split(",")):
        got, stats = speculative_generate(
            target, t_params, draft, d_params, prompt, args.tokens,
            k=k, return_stats=True,
        )
        exact = bool(np.array_equal(np.asarray(got), want))
        t_spec, sus = try_timed(
            lambda m, k=k: speculative_generate(
                target, t_params, draft, d_params, prompt, m, k=k
            ),
            args.tokens, t_plain / (k * 4.0),
        )
        if t_spec is None:
            print(json.dumps({"bench": "speculative",
                              "mode": f"draft_k{k}",
                              "skipped": "unmeasurable"}), flush=True)
            continue
        row = {
            "bench": "speculative", "mode": f"draft_k{k}",
            "ms_per_tok": round(t_spec * 1e3, 3),
            "tokens_per_s": round(1.0 / t_spec),
            "mean_accepted": round(stats["mean_accepted"], 2),
            "speedup_vs_plain": round(t_plain / t_spec, 2),
            "greedy_exact": exact,
            **({"suspect_fast": True} if sus else {}),
        }
        print(json.dumps(row), flush=True)
        rows.append(row)
        if row["tokens_per_s"] > best[0] and exact and not sus:
            best = (row["tokens_per_s"], f"k={k}")

    # Draft-FREE prompt-lookup speculation (the CLI-reachable form):
    # needs the continuation's n-grams to have earlier occurrences, so
    # its prompt spans > one full cycle of the corpus.
    from mpi_cuda_cnn_tpu.models.generate import lookup_speculative_generate

    lk_prompt = jnp.asarray(
        (np.arange(args.vocab + 49)[None, :] % args.vocab), jnp.int32
    )
    lk_want = np.asarray(generate(target, t_params, lk_prompt, args.tokens))
    lk_plain, _ = timed_tokens(
        lambda m: generate(target, t_params, lk_prompt, m), args.tokens
    )
    for k in (int(x) for x in args.ks.split(",")):
        lk_toks, lstats = lookup_speculative_generate(
            target, t_params, lk_prompt, args.tokens, k=k,
            return_stats=True,
        )
        lk_got = np.asarray(lk_toks)
        t_lk, sus = try_timed(
            lambda m, k=k: lookup_speculative_generate(
                target, t_params, lk_prompt, m, k=k
            ),
            args.tokens, lk_plain / (k * 4.0),
        )
        if t_lk is None:
            print(json.dumps({"bench": "speculative",
                              "mode": f"lookup_k{k}",
                              "skipped": "unmeasurable"}), flush=True)
            continue
        row = {
            "bench": "speculative", "mode": f"lookup_k{k}",
            "ms_per_tok": round(t_lk * 1e3, 3),
            "tokens_per_s": round(1.0 / t_lk),
            "mean_accepted": round(lstats["mean_accepted"], 2),
            "speedup_vs_plain": round(lk_plain / t_lk, 2),
            "greedy_exact": bool(np.array_equal(lk_got, lk_want)),
            **({"suspect_fast": True} if sus else {}),
        }
        print(json.dumps(row), flush=True)
        if row["tokens_per_s"] > best[0] and row["greedy_exact"] \
                and not sus:
            best = (row["tokens_per_s"], f"lookup_k{k}")

    # Rejection-sampling speculation at temperature 0.8 (round 5): the
    # same trained pair, now SAMPLING — acceptance is min(1, p/q) per
    # proposal instead of argmax matching, output law == plain
    # temperature sampling's (tests/test_spec_sampling.py pins the
    # distribution equality; no bitwise assert is possible for sampling).
    temp = 0.8
    skey = jax.random.key(11)
    t_plain_T, _ = timed_tokens(
        lambda m: generate(target, t_params, prompt, m, temperature=temp,
                           key=skey),
        args.tokens,
    )
    print(json.dumps({
        "bench": "speculative", "mode": f"plain_sample_T{temp}",
        "ms_per_tok": round(t_plain_T * 1e3, 3),
        "tokens_per_s": round(1.0 / t_plain_T),
    }), flush=True)
    for k in (int(x) for x in args.ks.split(",")):
        _, sst = speculative_generate(
            target, t_params, draft, d_params, prompt, args.tokens,
            k=k, temperature=temp, key=skey, return_stats=True,
        )
        t_sT, susT = try_timed(
            lambda m, k=k: speculative_generate(
                target, t_params, draft, d_params, prompt, m, k=k,
                temperature=temp, key=skey,
            ),
            args.tokens, t_plain_T / (k * 4.0),
        )
        if t_sT is None:
            print(json.dumps({"bench": "speculative",
                              "mode": f"draft_k{k}_T{temp}",
                              "skipped": "unmeasurable"}), flush=True)
            continue
        print(json.dumps({
            "bench": "speculative", "mode": f"draft_k{k}_T{temp}",
            "ms_per_tok": round(t_sT * 1e3, 3),
            "tokens_per_s": round(1.0 / t_sT),
            "mean_accepted": round(sst["mean_accepted"], 2),
            "speedup_vs_plain": round(t_plain_T / t_sT, 2),
            **({"suspect_fast": True} if susT else {}),
        }), flush=True)
    # Lookup sampling on the cycle-spanning prompt.
    lk_plain_T, _ = timed_tokens(
        lambda m: generate(target, t_params, lk_prompt, m,
                           temperature=temp, key=skey),
        args.tokens,
    )
    for k in (int(x) for x in args.ks.split(",")):
        _, lst = lookup_speculative_generate(
            target, t_params, lk_prompt, args.tokens, k=k,
            temperature=temp, key=skey, return_stats=True,
        )
        t_lkT, susLT = try_timed(
            lambda m, k=k: lookup_speculative_generate(
                target, t_params, lk_prompt, m, k=k, temperature=temp,
                key=skey,
            ),
            args.tokens, lk_plain_T / (k * 4.0),
        )
        if t_lkT is None:
            print(json.dumps({"bench": "speculative",
                              "mode": f"lookup_k{k}_T{temp}",
                              "skipped": "unmeasurable"}), flush=True)
            continue
        print(json.dumps({
            "bench": "speculative", "mode": f"lookup_k{k}_T{temp}",
            "ms_per_tok": round(t_lkT * 1e3, 3),
            "tokens_per_s": round(1.0 / t_lkT),
            "mean_accepted": round(lst["mean_accepted"], 2),
            "speedup_vs_plain": round(lk_plain_T / t_lkT, 2),
            **({"suspect_fast": True} if susLT else {}),
        }), flush=True)

    # Lookup on REAL text: a fresh target trained briefly on the
    # framework's own sources (char-level — `--corpus self`), prompt =
    # the corpus head. Acceptance here is the honest answer to "does
    # prompt-lookup help on code?", not a cyclic-toy upper bound.
    if args.self_corpus_steps:
        from mpi_cuda_cnn_tpu.train.lm_trainer import load_corpus

        text = load_corpus("self")
        st = TransformerLM(vocab=256, dim=args.dim, heads=args.heads,
                           depth=args.depth, max_seq=args.max_seq)
        st_params, st_loss = train_on_text(
            st, text, steps=args.self_corpus_steps, batch=8, seq=256
        )
        sp = jnp.asarray(np.asarray(text[:512])[None, :], jnp.int32)
        sp_want = np.asarray(generate(st, st_params, sp, args.tokens))
        t_sp_plain, _ = timed_tokens(
            lambda m: generate(st, st_params, sp, m), args.tokens
        )
        got, sstats = lookup_speculative_generate(
            st, st_params, sp, args.tokens, k=8, return_stats=True
        )
        t_sp_lk, sus_sp = try_timed(
            lambda m: lookup_speculative_generate(st, st_params, sp, m,
                                                  k=8),
            args.tokens, t_sp_plain / (8 * 4.0),
        )
        if t_sp_lk is None:
            print(json.dumps({"bench": "speculative",
                              "mode": "self_corpus_lookup_k8",
                              "skipped": "unmeasurable"}), flush=True)
            t_sp_lk = None
        if t_sp_lk is not None:
            print(json.dumps({
                "bench": "speculative", "mode": "self_corpus_lookup_k8",
                "train_steps": args.self_corpus_steps,
                "train_loss": round(st_loss, 3),
                "plain_ms_per_tok": round(t_sp_plain * 1e3, 3),
                "ms_per_tok": round(t_sp_lk * 1e3, 3),
                "mean_accepted": round(sstats["mean_accepted"], 2),
                "speedup_vs_plain": round(t_sp_plain / t_sp_lk, 2),
                "greedy_exact": bool(
                    np.array_equal(np.asarray(got), sp_want)
                ),
                **({"suspect_fast": True} if sus_sp else {}),
            }), flush=True)

    # Worst case on record: an untrained draft accepts ~1/vocab.
    rand = draft.init(jax.random.key(99))
    _, rstats = speculative_generate(
        target, t_params, draft, rand, prompt, args.tokens, k=4,
        return_stats=True,
    )
    t_rand, sus_r = try_timed(
        lambda m: speculative_generate(
            target, t_params, draft, rand, prompt, m, k=4
        ),
        args.tokens, t_plain / (4 * 4.0),
    )
    if t_rand is None:
        print(json.dumps({"bench": "speculative",
                          "mode": "random_draft_k4",
                          "skipped": "unmeasurable"}), flush=True)
    else:
        print(json.dumps({
            "bench": "speculative", "mode": "random_draft_k4",
            "ms_per_tok": round(t_rand * 1e3, 3),
            "mean_accepted": round(rstats["mean_accepted"], 2),
            "speedup_vs_plain": round(t_plain / t_rand, 2),
            **({"suspect_fast": True} if sus_r else {}),
        }), flush=True)

    # Schema-stamped headline record (obs.schema `bench` event), like
    # bench.py's: `mctpu compare` reads every bench output the same way.
    print(json.dumps(make_record(
        "bench", time.perf_counter() - _T0,
        metric="speculative_decode_tokens_per_s",
        value=best[0], unit="tokens/s", config=best[1],
        plain_tokens_per_s=rows[0]["tokens_per_s"],
        model=f"d{args.dim}x{args.depth} draft d{args.draft_dim}x"
              f"{args.draft_depth} v{args.vocab} B=1",
        backend=jax.default_backend(),
    )))


if __name__ == "__main__":
    main()
