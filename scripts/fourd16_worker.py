"""Full 4D-mesh parity worker: pipe:2 x model:2 x seq:2 x data:2 on 16
virtual CPU devices — ALL FOUR axes populated at once.

The 8-virtual-device suite can run any three of the four axes together
(tests/test_tp_pp_lm.py); this worker is the missing composition's
witness: one train step on the full 16-device mesh must equal the
single-device serial step exactly (loss AND updated params), proving the
data-axis pmean composes with the pipe psum, the Megatron model-axis
collectives, and the ring-attention seq axis in one program.

Run standalone (`python scripts/fourd16_worker.py`) or via
tests/test_4d_full.py / `make test_4d16`. Prints `4D16OK loss=<x>` on
success, exits nonzero otherwise.
"""

import os
import sys

# Must precede the first jax import: 16 virtual CPU devices. FORCE the
# count — when spawned from the test suite the inherited XLA_FLAGS
# already pins 8 (tests/conftest.py) and must be overridden, not kept.
import re

flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=16"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM  # noqa: E402
from mpi_cuda_cnn_tpu.parallel.mesh import (  # noqa: E402
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    make_mesh,
)
from mpi_cuda_cnn_tpu.parallel.pp_lm import (  # noqa: E402
    pp_lm_microbatch,
    sp_pp_shard_batch,
)
from mpi_cuda_cnn_tpu.parallel.sp import SEQ_AXIS  # noqa: E402
from mpi_cuda_cnn_tpu.parallel.tp_pp_lm import (  # noqa: E402
    make_tp_pp_lm_state,
    make_tp_pp_lm_train_step,
    unstack_tp_blocks,
)
from mpi_cuda_cnn_tpu.train.lm import make_lm_state, make_lm_train_step  # noqa: E402


def main(fast: bool = False) -> None:
    devices = jax.devices()
    assert len(devices) >= 16, f"need 16 virtual devices, got {len(devices)}"

    # --fast: the default-suite CANARY (tests/test_4d_canary.py) — the
    # same 2x2x2x2 composition at the smallest shapes every axis allows
    # (pipe:2 -> 2 blocks, model:2 -> 2 heads, seq:2 -> 2 seq shards,
    # data:2 x 2 microbatches -> batch 4), so the flagship 4D program
    # cannot regress between --runslow runs while the spawn stays in
    # the fast suite's time budget. XLA compile dominates the spawn
    # (~12 s of its ~16 s cold); the persistent compilation cache under
    # .cache/ brings the steady-state run to < 8 s (measured), and only
    # the first run on a fresh checkout pays the compile.
    if fast:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".cache", "jax_4d_canary"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        model = TransformerLM(vocab=16, dim=16, heads=2, depth=2,
                              max_seq=32)
        toks_shape = (4, 17)
    else:
        model = TransformerLM(vocab=32, dim=32, heads=4, depth=4,
                              max_seq=64)
        toks_shape = (8, 33)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(2)
    toks = jnp.asarray(
        rng.integers(0, model.vocab, toks_shape), jnp.int32
    )
    tokens, targets = toks[:, :-1], toks[:, 1:]

    seq = toks_shape[1] - 1
    serial_step = make_lm_train_step(model, opt, attn_impl="oracle",
                                     seq_len=seq, donate=False)
    want_state, want_m = serial_step(make_lm_state(model, opt, seed=0),
                                     tokens, targets)

    mesh = make_mesh(
        {PIPE_AXIS: 2, MODEL_AXIS: 2, SEQ_AXIS: 2, DATA_AXIS: 2},
        devices=devices[:16],
    )
    params = model.init(jax.random.key(0))
    state = make_tp_pp_lm_state(model, params, opt, mesh)
    step = make_tp_pp_lm_train_step(model, opt, mesh, state, donate=False,
                                    attn_impl="ring")
    mb = sp_pp_shard_batch(pp_lm_microbatch(tokens, targets, 2), mesh)
    got_state, got_m = step(state, *mb)

    np.testing.assert_allclose(float(got_m["loss"]), float(want_m["loss"]),
                               rtol=1e-5, atol=1e-6)
    got = unstack_tp_blocks(jax.device_get(got_state["params"]), model)
    for a, b in zip(jax.tree.leaves(got),
                    jax.tree.leaves(jax.device_get(want_state["params"]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
    print(f"4D16OK loss={float(got_m['loss']):.6f} devices=16 "
          f"mesh=pipe:2,model:2,seq:2,data:2")


if __name__ == "__main__":
    main(fast="--fast" in sys.argv[1:])
