"""Regenerate the committed CI serving baseline, reproducibly.

ci/serve_baseline.jsonl is DATA: the telemetry of one serve-bench run
with pinned arguments, which CI re-runs fresh and compares against
under ci/serve_gate.json's thresholds. Before this script the file was
captured by hand, so "what arguments produced it?" lived only in the
gate's _doc comment and drifted silently when the bench grew flags.
Now there is exactly one spelling:

    make serve-baseline            # or:
    JAX_PLATFORMS=cpu python scripts/make_serve_baseline.py

Refresh procedure (also in ci/serve_gate.json's _doc): rerun after any
DELIBERATE scheduling change (admission order, chunking, preemption
policy — anything that legitimately moves tick/chunk/token counts),
commit the new ci/serve_baseline.jsonl with the change that moved it,
and say so in the commit message. Never refresh to silence a red gate
you can't explain — the 0%-tolerance structural counts exist to catch
exactly that drift. The fleet gate (ci/fleet_gate.json) needs no
baseline file: it compares two fresh identical-seed runs against each
other, so there is nothing to regenerate.

The arguments below MUST stay in lockstep with the CI candidate run in
.github/workflows/ci.yml ("Perf-regression gate" step) — same seed,
same shape, --device cpu so the schedule is a pure function of the
seed; only then do the structural counts gate at 0%.
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = Path(__file__).resolve().parents[1]
BASELINE = REPO / "ci" / "serve_baseline.jsonl"

# One flag list, shared verbatim with CI's candidate run (minus the
# output path). Growing the bench must not change these silently: the
# gate compares baseline vs fresh run, so both sides have to move
# together — through this file and ci.yml in the same commit.
BASELINE_ARGS = ["--requests", "12", "--seed", "0", "--device", "cpu"]


def main() -> int:
    if len(sys.argv) > 1:
        # No knobs on purpose: the whole point is ONE pinned spelling.
        # A stray flag (even --help) must not silently overwrite the
        # committed baseline with a default run.
        print("usage: make_serve_baseline.py  (takes no arguments; "
              "pinned args: " + " ".join(BASELINE_ARGS) + ")",
              file=sys.stderr)
        return 0 if sys.argv[1] in ("-h", "--help") else 2
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from mpi_cuda_cnn_tpu.serve.bench import serve_bench_main

    tmp = BASELINE.with_suffix(".jsonl.tmp")
    # MetricsLogger appends: a stale tmp from an interrupted run would
    # otherwise smuggle a second segment into the committed baseline.
    tmp.unlink(missing_ok=True)
    rc = serve_bench_main([*BASELINE_ARGS, "--metrics-jsonl", str(tmp)])
    if rc != 0:
        print(f"serve-bench failed (exit {rc}); baseline untouched",
              file=sys.stderr)
        tmp.unlink(missing_ok=True)
        return rc
    os.replace(tmp, BASELINE)  # atomic: never leave a torn baseline
    print(f"wrote {BASELINE.relative_to(REPO)}")
    print("Verify it gates green against itself, then commit it together "
          "with the change that moved the schedule:")
    print("  python -m mpi_cuda_cnn_tpu compare ci/serve_baseline.jsonl "
          "ci/serve_baseline.jsonl --gate ci/serve_gate.json")
    return 0


if __name__ == "__main__":
    sys.exit(main())
