"""Fetch CIFAR-10 and convert its binary batches to IDX files.

Twin of the reference's data-fetch harness (its Makefile:24-35 pulls
MNIST; CIFAR-10 has no IDX distribution at all) — real CIFAR-10 ships as
a tarball of 6 binary batches, each record 1 label byte + 3072 pixel
bytes in CHW plane order (cs.toronto.edu/~kriz/cifar.html). This script
downloads the tarball (md5-verified), converts to the four IDX files the
CLI contract expects (images as 4-D (N,32,32,3) uint8 IDX — the reader
supports any ndims), and writes a checksum manifest.

Zero-network environments: `--selftest` synthesizes a tarball in the
exact CIFAR byte format, runs the same conversion, and verifies the
round-trip — so the converter itself is CI-testable offline (the fetch
is the only network-gated step; see PERF.md).

    python scripts/get_cifar10.py data/cifar10
    python scripts/get_cifar10.py --selftest /tmp/cifar_selftest
"""

from __future__ import annotations

import hashlib
import io
import json
import sys
import tarfile
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

from mpi_cuda_cnn_tpu.data.idx import read_idx, write_idx

URL = "https://www.cs.toronto.edu/~kriz/cifar-10-binary.tar.gz"
MD5 = "c32a1d4ab5d03f1284b67883e8d87530"
TRAIN_BATCHES = [f"data_batch_{i}.bin" for i in range(1, 6)]
TEST_BATCH = "test_batch.bin"
RECORD = 1 + 3072  # label byte + 3 x 32 x 32 pixel planes


def parse_batch(raw: bytes) -> tuple[np.ndarray, np.ndarray]:
    """One CIFAR binary batch -> (images (N,32,32,3) u8, labels (N,) u8)."""
    if len(raw) % RECORD:
        raise ValueError(f"batch size {len(raw)} not a multiple of {RECORD}")
    rec = np.frombuffer(raw, np.uint8).reshape(-1, RECORD)
    labels = rec[:, 0].copy()
    # CHW planes (R then G then B, row-major) -> HWC.
    images = rec[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).copy()
    return images, labels


def convert(batches: dict[str, bytes], out: Path) -> dict[str, str]:
    """Named batch payloads -> the four IDX files; returns sha256 manifest."""
    train = [parse_batch(batches[n]) for n in TRAIN_BATCHES if n in batches]
    if not train or TEST_BATCH not in batches:
        missing = [n for n in TRAIN_BATCHES + [TEST_BATCH] if n not in batches]
        raise ValueError(f"archive is missing batches: {missing}")
    tx = np.concatenate([t[0] for t in train])
    ty = np.concatenate([t[1] for t in train])
    ex, ey = parse_batch(batches[TEST_BATCH])
    out.mkdir(parents=True, exist_ok=True)
    files = {
        "train-images-idx3-ubyte": tx,
        "train-labels-idx1-ubyte": ty,
        "t10k-images-idx3-ubyte": ex,
        "t10k-labels-idx1-ubyte": ey,
    }
    manifest = {}
    for name, arr in files.items():
        write_idx(out / name, arr)
        manifest[name] = hashlib.sha256((out / name).read_bytes()).hexdigest()
    (out / "manifest.json").write_text(json.dumps(manifest, indent=2))
    return manifest


def extract_batches(tar_bytes: bytes) -> dict[str, bytes]:
    batches = {}
    with tarfile.open(fileobj=io.BytesIO(tar_bytes), mode="r:*") as tf:
        for member in tf.getmembers():
            base = Path(member.name).name
            if base in TRAIN_BATCHES + [TEST_BATCH]:
                batches[base] = tf.extractfile(member).read()
    return batches


def fetch(out: Path) -> int:
    print(f"fetching {URL}", file=sys.stderr)
    try:
        data = urllib.request.urlopen(URL, timeout=120).read()
    except Exception as e:
        print(
            f"fetch failed ({e}); this environment has no network egress.\n"
            "The converter is selftested offline (--selftest); rerun this "
            "script where the CIFAR mirror is reachable.",
            file=sys.stderr,
        )
        return 1
    digest = hashlib.md5(data).hexdigest()
    if digest != MD5:
        print(f"md5 mismatch: got {digest}, want {MD5}", file=sys.stderr)
        return 1
    manifest = convert(extract_batches(data), out)
    print(json.dumps(manifest, indent=2))
    return 0


def selftest(out: Path) -> int:
    """Synthesize a CIFAR-format tarball, convert, verify round-trip."""
    rng = np.random.default_rng(0)
    payloads = {}
    want = {}
    for name in TRAIN_BATCHES + [TEST_BATCH]:
        n = 20
        labels = rng.integers(0, 10, n, dtype=np.uint8)
        images = rng.integers(0, 256, (n, 32, 32, 3), dtype=np.uint8)
        rec = np.zeros((n, RECORD), np.uint8)
        rec[:, 0] = labels
        rec[:, 1:] = images.transpose(0, 3, 1, 2).reshape(n, 3072)
        payloads[name] = rec.tobytes()
        want[name] = (images, labels)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, blob in payloads.items():
            info = tarfile.TarInfo(f"cifar-10-batches-bin/{name}")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    convert(extract_batches(buf.getvalue()), out)

    tx = read_idx(out / "train-images-idx3-ubyte")
    ty = read_idx(out / "train-labels-idx1-ubyte")
    ex = read_idx(out / "t10k-images-idx3-ubyte")
    ey = read_idx(out / "t10k-labels-idx1-ubyte")
    assert tx.shape == (100, 32, 32, 3) and ty.shape == (100,)
    np.testing.assert_array_equal(
        tx[:20], want[TRAIN_BATCHES[0]][0]
    )
    np.testing.assert_array_equal(ex, want[TEST_BATCH][0])
    np.testing.assert_array_equal(ey, want[TEST_BATCH][1])
    print("selftest ok: CIFAR binary -> IDX round-trip exact")
    return 0


def main() -> int:
    args = [a for a in sys.argv[1:]]
    run_selftest = "--selftest" in args
    if run_selftest:
        args.remove("--selftest")
    out = Path(args[0]) if args else Path("data/cifar10")
    return selftest(out) if run_selftest else fetch(out)


if __name__ == "__main__":
    sys.exit(main())
