"""On-chip compile + parity + perf check for the GQA flash kernels.

VERDICT round 2 item 2: the 5-D (b, hkv, group, qblock, kblock) grid
restructure of ops/pallas_attention.py landed after the round-2 backend
outage and has "never compiled on real hardware" — the reference's own
cautionary tale (CUDAcnn.cu:167, committed but never built). This script
closes that hole the moment a chip is reachable:

for each (s, kv_heads) in the matrix it
  1. compiles + runs the fused flash forward on the real backend,
  2. checks parity against the jnp oracle (f32, rtol 2e-2 for bf16),
  3. times fwd and fwd+bwd with the two-point method,
printing one JSON line per config and a final summary line. Any compile
failure or parity miss makes the process exit nonzero — this is a check,
not just a bench.
"""

from __future__ import annotations

import argparse
import json
import sys

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.ops.attention import (
    attention,
    blockwise_attention,
    repeat_kv,
)
from mpi_cuda_cnn_tpu.ops.pallas_attention import flash_attention
from mpi_cuda_cnn_tpu.utils.sync import (
    grad_stacked,
    hard_block,
    scan_two_point,
)


def check_config(*, b, h, hkv, s, d, dtype, bwd, rng):
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, s, hkv, d)), dtype)

    # c is a zero scalar threaded through iterations purely as a data
    # dependency (q + c is numerically q).
    fwd = jax.jit(
        lambda q, k, v, c: flash_attention(q + c, k, v, True)
    )
    zero = jnp.zeros((), dtype)
    out = hard_block(fwd(q, k, v, zero))  # the compile that must not fail

    # Parity vs the oracle (repeat_kv handles GQA). The quadratic oracle
    # materializes an O(S^2) score tensor — ~2 GB at s=8192 — so large s
    # uses the bounded-memory blockwise oracle (exact same math, online
    # softmax) to keep a reference OOM from masquerading as a kernel
    # failure.
    if s <= 4096:
        want = attention(q.astype(jnp.float32), k.astype(jnp.float32),
                         v.astype(jnp.float32), causal=True)
    else:
        want = blockwise_attention(
            q.astype(jnp.float32),
            repeat_kv(k.astype(jnp.float32), h),
            repeat_kv(v.astype(jnp.float32), h),
            block_size=1024, causal=True,
        )
    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-4
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - want)))
    ref = float(jnp.max(jnp.abs(want))) or 1.0
    rel = err / ref
    ok = rel < tol

    # Timing via the shared on-device-scan recipe (host-dispatch chains
    # cannot resolve these sub-10 ms kernels through the tunnel's jitter
    # — observed negative columns at n=3 AND n=25); the fwd+bwd target
    # is the shared grad_stacked wrapper.
    def timed(fn, n, *args):
        t = scan_two_point(fn, n, *args)
        if t * n < 0.05:
            # The s=2048 kernels are ~0.1 ms: n=25 gives ~2.5 ms of
            # window signal, below the tunnel's jitter — the source of
            # the round-4/5 captures' occasional negative columns.
            # Re-measure with enough iterations for ~100 ms of signal.
            # A non-positive first read says nothing about the kernel's
            # real cost, so grow boundedly (10x) rather than jumping to
            # the iteration cap — at a ~5 ms kernel the cap would mean
            # ~90 s for one cell and blow the capture step's timeout.
            n2 = 10 * n if t <= 0 else min(max(50, int(0.1 / t)), 2000)
            t = scan_two_point(fn, n2, *args)
        return t

    fwd_fn = lambda q, k, v: flash_attention(q, k, v, True)
    t_fwd = timed(fwd_fn, 25, q, k, v)
    t_bwd = None
    if bwd:
        t_bwd = timed(grad_stacked(fwd_fn), 10, q, k, v)
    return {
        "s": s, "kv_heads": hkv, "dtype": str(jnp.dtype(dtype)),
        "parity_rel_err": round(rel, 6), "parity_ok": ok,
        "fwd_ms": round(t_fwd * 1e3, 2),
        "fwd_bwd_ms": round(t_bwd * 1e3, 2) if t_bwd is not None else None,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--seqs", default="2048,8192")
    ap.add_argument("--kv-heads", default="8,2,1",
                    help="matrix of kv head counts (heads = MHA)")
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    ap.add_argument("--no-bwd", action="store_true")
    args = ap.parse_args()

    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    rows, failed = [], 0
    for s in (int(x) for x in args.seqs.split(",")):
        for hkv in (int(x) for x in args.kv_heads.split(",")):
            try:
                r = check_config(
                    b=args.batch, h=args.heads, hkv=hkv, s=s,
                    d=args.head_dim, dtype=dtype, bwd=not args.no_bwd,
                    rng=rng,
                )
            except Exception as exc:  # noqa: BLE001 — a compile failure IS the finding
                r = {"s": s, "kv_heads": hkv, "error": repr(exc)[:400],
                     "parity_ok": False}
            failed += not r.get("parity_ok", False)
            rows.append(r)
            print(json.dumps({"bench": "gqa_flash_check", **r}), flush=True)

    print(json.dumps({
        "metric": "gqa_flash_check",
        "configs": len(rows),
        "failed": failed,
        "backend": jax.default_backend(),
    }))
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
