"""KV-cache decode benchmark: prefill + steady-state generation tokens/s.

The training benches (bench_lm.py) measure the MXU-bound step; decode is
the other regime — one token per forward, bound by reading the KV cache
and weights from HBM. This bench times models/generate.py's real product
path (prefill -> jitted decode scan) and shows the GQA effect: the cache
is (B, max_seq, Hkv, D), so kv_heads < heads cuts cache reads by
heads/kv_heads — the reason serving stacks use GQA (generate.init_cache).

Timing: a generate(num_tokens=N) run costs fixed dispatch + prefill +
N * per_token; timing N and 2N and reporting (T2N - TN)/N cancels the
fixed and prefill parts exactly, leaving the steady-state per-token
decode cost (the same two-point method as scripts/bench_lm.py, which
measured ~100 ms fixed tunnel round-trips that would otherwise smear
into the number). Prefill is timed separately on its own jitted
function, also two-point (loops of n and 2n calls).

Completion is forced with a HOST FETCH of real values, not
block_until_ready (under this environment's remote-TPU tunnel the latter
returns at enqueue — utils/sync.py).

One JSON line per (kv_heads) config + a summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.models.generate import generate, prefill
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs.schema import make_record
from mpi_cuda_cnn_tpu.train.lm import count_params
from mpi_cuda_cnn_tpu.utils.sync import hard_block as _force
from mpi_cuda_cnn_tpu.utils.sync import two_point

_T0 = time.perf_counter()


def bench_decode_config(model, *, batch, prompt_len, gen_tokens,
                        cache_dtype="float32", weights_dtype="float32",
                        seed=0):
    params = model.init(jax.random.key(seed))
    if weights_dtype != "float32":
        # Serving-weights cast: decode reads every weight once per token
        # (~4 bytes/param in f32 — the dominant HBM stream once the
        # cache is GQA- and bf16-shrunk); bf16 halves it.
        wdt = jnp.dtype(weights_dtype)
        params = jax.tree.map(
            lambda a: a.astype(wdt) if a.dtype == jnp.float32 else a, params
        )
    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(
        rng.integers(0, model.vocab, (batch, prompt_len)), jnp.int32
    )

    def timed_gen(n):
        t0 = time.perf_counter()
        toks = generate(model, params, prompt, n, cache_dtype=cache_dtype)
        _force(toks)
        return time.perf_counter() - t0

    # Warm both compile-cache entries (generate() compiles per n), then
    # the shared two-point core: window cancellation + median-of-3.
    timed_gen(gen_tokens)
    timed_gen(2 * gen_tokens)
    per_tok = two_point(timed_gen, gen_tokens, warmup=0)

    # Prefill alone (jitted once here; generate()'s fused program includes
    # it, which is exactly why the two-point difference above excludes it).
    cdt = jnp.dtype(cache_dtype)
    pf = jax.jit(lambda p, t: prefill(model, p, t, cache_dtype=cdt)[0])
    _force(pf(params, prompt))

    def timed_pf(loops):
        t0 = time.perf_counter()
        for _ in range(loops):
            out = pf(params, prompt)
        _force(out)
        return time.perf_counter() - t0

    prefill_s = two_point(timed_pf, 4, warmup=0)
    return per_tok, prefill_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=str, default="0,2,1",
                    help="comma list; 0 = MHA, else GQA/MQA cache sizes")
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--max-seq", type=int, default=2048)
    ap.add_argument("--prompt", type=int, default=1024)
    ap.add_argument("--tokens", type=int, default=256,
                    help="N for the two-point (N, 2N) decode timing; "
                         "prompt + 2N must fit --max-seq")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--cache-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8"],
                    help="KV-cache storage dtype; bfloat16 halves the "
                         "bytes decode reads per token, int8 quarters "
                         "them (+4 f32 scale bytes per (position, head) "
                         "row — 0.8%% of the f32 cache at head_dim 128)")
    ap.add_argument("--weights-dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="serving weights dtype; decode reads every "
                         "weight once per token")
    ap.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    args = ap.parse_args()

    if args.device == "cpu":
        # In-process selection, like the CLI: the JAX_PLATFORMS env var can
        # be intercepted by a pre-registered TPU plugin (see cli.py).
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        raise SystemExit(1)
    if args.prompt + 2 * args.tokens > args.max_seq:
        print(f"prompt {args.prompt} + 2x{args.tokens} tokens exceeds "
              f"--max-seq {args.max_seq}", file=sys.stderr)
        raise SystemExit(1)

    results = {}
    # Normalize requested kv values to their effective head count (0 means
    # MHA = heads) and dedupe, so e.g. "--kv-heads 0,8" with --heads 8
    # runs once instead of silently overwriting its own results row.
    kvs = list(dict.fromkeys(
        (int(s) or args.heads) for s in args.kv_heads.split(",")
    ))
    for kv in kvs:
        model = TransformerLM(
            vocab=args.vocab, dim=args.dim, heads=args.heads,
            depth=args.depth, max_seq=args.max_seq, kv_heads=kv,
        )
        per_tok, prefill_s = bench_decode_config(
            model, batch=args.batch, prompt_len=args.prompt,
            gen_tokens=args.tokens, cache_dtype=args.cache_dtype,
            weights_dtype=args.weights_dtype,
        )
        hkv = model.n_kv
        # cache k+v bytes actually resident per decoded token's attention
        itemsize = jnp.dtype(args.cache_dtype).itemsize
        cache_mb = (
            args.batch * args.max_seq * hkv * model.head_dim * itemsize * 2
            * args.depth / 1e6
        )
        if args.cache_dtype == "int8":
            # + the per-(position, head) f32 absmax scales.
            cache_mb += (
                args.batch * args.max_seq * hkv * 4 * 2 * args.depth / 1e6
            )
        label = f"kv{hkv}" + ("(MHA)" if hkv == args.heads else "")
        if args.cache_dtype != "float32":
            label += f"+{args.cache_dtype}"
        # A non-positive two-point delta means the per-token cost is below
        # the timer's noise floor at these shapes — report null, never a
        # negative throughput.
        ok = per_tok > 0
        results[label] = {
            "decode_ms_per_tok": round(per_tok * 1e3, 3) if ok else None,
            "decode_tokens_per_s": round(args.batch / per_tok) if ok else None,
            "prefill_ms": round(prefill_s * 1e3, 2),
            "cache_mb": round(cache_mb, 1),
        }
        print(json.dumps({
            "bench": "lm_decode", "kv_heads": hkv,
            "cache_dtype": args.cache_dtype,
            "weights_dtype": args.weights_dtype,
            "params": count_params(model.init(jax.random.key(0))),
            **results[label],
        }))

    best = max(results.items(),
               key=lambda kv_: kv_[1]["decode_tokens_per_s"] or 0)
    # Schema-stamped headline record (obs.schema `bench` event), like
    # bench.py's: `mctpu compare` reads every bench output the same way.
    print(json.dumps(make_record(
        "bench", time.perf_counter() - _T0,
        metric="decode_tokens_per_s",
        value=best[1]["decode_tokens_per_s"],
        unit="tokens/s",
        config=best[0],
        model=f"d{args.dim}x{args.depth} h{args.heads} v{args.vocab} "
              f"b{args.batch} prompt{args.prompt} cache{args.max_seq}",
        backend=jax.default_backend(),
    )))


if __name__ == "__main__":
    main()
