"""KV-cache decode benchmark: prefill + steady-state generation tokens/s.

The training benches (bench_lm.py) measure the MXU-bound step; decode is
the other regime — one token per forward, bound by reading the KV cache
and weights from HBM. This bench times models/generate.py's real product
path (prefill -> jitted decode scan) and shows the GQA effect: the cache
is (B, max_seq, Hkv, D), so kv_heads < heads cuts cache reads by
heads/kv_heads — the reason serving stacks use GQA (generate.init_cache).

ISSUE 12 axes: `--paged` switches to the PAGED cache (identity block
tables over a page pool — the serving layout) and `--kernel
{gather,pallas}` picks the read (XLA gather vs the fused
ops/pallas_paged_attention kernel), so kernel-on vs kernel-off is an
A/B on an identical seeded workload; `--weights-dtype int8` turns on
the per-channel quantized decode GEMVs (ops/pallas_gemv, quantized once
before timing). Every paged row carries the greedy token CRC — in f32
the kernel is bitwise vs the gather, so `mctpu compare` gates the CRCs
at exact equality (ci/decode_gate.json, run in CI on the CPU interpret
path).

Timing: a generate(num_tokens=N) run costs fixed dispatch + prefill +
N * per_token; timing N and 2N and reporting (T2N - TN)/N cancels the
fixed and prefill parts exactly, leaving the steady-state per-token
decode cost (the same two-point method as scripts/bench_lm.py, which
measured ~100 ms fixed tunnel round-trips that would otherwise smear
into the number). Prefill is timed separately on its own jitted
function, also two-point (loops of n and 2n calls).

Completion is forced with a HOST FETCH of real values, not
block_until_ready (under this environment's remote-TPU tunnel the latter
returns at enqueue — utils/sync.py).

Output: one schema `bench` record per config row (metric + value + unit
— `mctpu compare` reads every row) plus the headline record.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.models.generate import decode_step, generate, prefill
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs.schema import make_record
from mpi_cuda_cnn_tpu.ops.pallas_gemv import quantize_decode_params
from mpi_cuda_cnn_tpu.train.lm import count_params
from mpi_cuda_cnn_tpu.utils.sync import hard_block as _force
from mpi_cuda_cnn_tpu.utils.sync import two_point

_T0 = time.perf_counter()


def _emit(metric, value, unit, **fields):
    """One schema-stamped `bench` row (ISSUE 12 satellite: every row a
    schema record with unit, so `mctpu compare` gates any of them)."""
    print(json.dumps(make_record(
        "bench", time.perf_counter() - _T0,
        metric=metric, value=value, unit=unit, **fields,
    )))


def bench_decode_config(model, *, batch, prompt_len, gen_tokens,
                        cache_dtype="float32", weights_dtype="float32",
                        seed=0):
    params = quantize_decode_params(
        model.init(jax.random.key(seed)), weights_dtype)
    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(
        rng.integers(0, model.vocab, (batch, prompt_len)), jnp.int32
    )

    def timed_gen(n):
        t0 = time.perf_counter()
        toks = generate(model, params, prompt, n, cache_dtype=cache_dtype)
        _force(toks)
        return time.perf_counter() - t0

    # Warm both compile-cache entries (generate() compiles per n), then
    # the shared two-point core: window cancellation + median-of-3.
    timed_gen(gen_tokens)
    timed_gen(2 * gen_tokens)
    per_tok = two_point(timed_gen, gen_tokens, warmup=0)

    # Prefill alone (jitted once here; generate()'s fused program includes
    # it, which is exactly why the two-point difference above excludes it).
    cdt = jnp.dtype(cache_dtype)
    pf = jax.jit(lambda p, t: prefill(model, p, t, cache_dtype=cdt)[0])
    _force(pf(params, prompt))

    def timed_pf(loops):
        t0 = time.perf_counter()
        for _ in range(loops):
            out = pf(params, prompt)
        _force(out)
        return time.perf_counter() - t0

    prefill_s = two_point(timed_pf, 4, warmup=0)
    return per_tok, prefill_s


@functools.lru_cache(maxsize=16)
def _compiled_paged_run(model, s0: int, num_tokens: int, batch: int,
                        cache_dtype: str, kernel: str, page_size: int):
    """One jitted paged prefill-block + greedy decode scan per config:
    the paged twin of generate()'s program, driven through the SAME
    decode_step dispatch the engine uses (PagedKVCache with per-slot
    positions), over identity block tables sized to s0 + num_tokens."""
    import dataclasses

    from mpi_cuda_cnn_tpu.serve.paged_cache import (
        init_paged_cache,
        pages_for,
    )

    cdt = jnp.dtype(cache_dtype)
    max_len = s0 + num_tokens
    per = pages_for(max_len, page_size)
    table = 1 + np.arange(batch * per, dtype=np.int32).reshape(batch, per)

    @jax.jit
    def run(params, prompt):
        from mpi_cuda_cnn_tpu.models.generate import decode_block

        cache = init_paged_cache(
            model, slots=batch, num_pages=batch * per + 1,
            page_size=page_size, dtype=cdt, max_len=max_len,
            kernel=kernel,
        )
        cache = dataclasses.replace(cache, block_table=jnp.asarray(table))
        # Paged prefill: the whole prompt as one cached block forward
        # (teacher-forced writes, causal reads — decode_block's k>1
        # form), then the greedy decode scan at per-slot positions.
        logits, cache = decode_block(
            model, params, prompt, jnp.zeros((batch,), jnp.int32), cache
        )
        logits = logits[:, -1, :]

        def body(carry, i):
            cache, logits = carry
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            nl, cache = decode_step(
                model, params, tok, jnp.full((batch,), s0 + i, jnp.int32),
                cache,
            )
            return (cache, nl), tok

        (_, logits), toks = jax.lax.scan(
            body, (cache, logits), jnp.arange(num_tokens - 1)
        )
        last = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jnp.concatenate([toks, last[None, :]], axis=0).T

    return run


def bench_paged_config(model, *, batch, prompt_len, gen_tokens,
                       cache_dtype, weights_dtype, kernel, page_size,
                       seed=0):
    """Two-point paged decode timing + the greedy token CRC the A/B
    gate pins (identical seeded workload across --kernel values; f32
    kernel parity is bitwise, so the CRCs must be EQUAL)."""
    params = quantize_decode_params(
        model.init(jax.random.key(seed)), weights_dtype)
    rng = np.random.default_rng(seed)
    prompt = jnp.asarray(
        rng.integers(0, model.vocab, (batch, prompt_len)), jnp.int32
    )

    def timed(n):
        run = _compiled_paged_run(model, prompt_len, n, batch,
                                  cache_dtype, kernel, page_size)
        t0 = time.perf_counter()
        toks = run(params, prompt)
        _force(toks)
        return time.perf_counter() - t0

    # Warm the N-program AND capture its tokens for the CRC in one run
    # (greedy decode is deterministic — a ninth decode purely for the
    # CRC would be wasted wall-clock on the interpret path).
    run = _compiled_paged_run(model, prompt_len, gen_tokens, batch,
                              cache_dtype, kernel, page_size)
    toks = np.asarray(run(params, prompt), np.int32)
    timed(2 * gen_tokens)
    per_tok = two_point(timed, gen_tokens, warmup=0)
    crc = zlib.crc32(toks.tobytes())
    return per_tok, crc, toks


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=str, default="0,2,1",
                    help="comma list; 0 = MHA, else GQA/MQA cache sizes")
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--max-seq", type=int, default=2048)
    ap.add_argument("--prompt", type=int, default=1024)
    ap.add_argument("--tokens", type=int, default=256,
                    help="N for the two-point (N, 2N) decode timing; "
                         "prompt + 2N must fit --max-seq")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--cache-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8"],
                    help="KV-cache storage dtype; bfloat16 halves the "
                         "bytes decode reads per token, int8 quarters "
                         "them (+4 f32 scale bytes per (position, head) "
                         "row — 0.8%% of the f32 cache at head_dim 128)")
    ap.add_argument("--weights-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8"],
                    help="serving weights dtype; decode reads every "
                         "weight once per token. int8 = per-channel "
                         "absmax QuantW through the fused GEMV "
                         "(ops/pallas_gemv), quantized once up front")
    ap.add_argument("--paged", action="store_true",
                    help="bench the PAGED cache (serving layout: "
                         "identity block tables over a page pool) "
                         "instead of the contiguous one")
    ap.add_argument("--kernel", default="gather",
                    choices=["gather", "pallas"],
                    help="paged read (with --paged): gather = XLA, "
                         "pallas = the fused paged-attention kernel "
                         "(ops/pallas_paged_attention)")
    ap.add_argument("--page-size", type=int, default=16,
                    help="tokens per KV page (with --paged)")
    ap.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    args = ap.parse_args()

    if args.device == "cpu":
        # In-process selection, like the CLI: the JAX_PLATFORMS env var can
        # be intercepted by a pre-registered TPU plugin (see cli.py).
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        raise SystemExit(1)
    if args.prompt + 2 * args.tokens > args.max_seq:
        print(f"prompt {args.prompt} + 2x{args.tokens} tokens exceeds "
              f"--max-seq {args.max_seq}", file=sys.stderr)
        raise SystemExit(1)

    results = {}
    paged_crcs: list[tuple[int, np.ndarray]] = []
    # Normalize requested kv values to their effective head count (0 means
    # MHA = heads) and dedupe, so e.g. "--kv-heads 0,8" with --heads 8
    # runs once instead of silently overwriting its own results row.
    kvs = list(dict.fromkeys(
        (int(s) or args.heads) for s in args.kv_heads.split(",")
    ))
    for kv in kvs:
        model = TransformerLM(
            vocab=args.vocab, dim=args.dim, heads=args.heads,
            depth=args.depth, max_seq=args.max_seq, kv_heads=kv,
        )
        hkv = model.n_kv
        label = f"kv{hkv}" + ("(MHA)" if hkv == args.heads else "")
        if args.cache_dtype != "float32":
            label += f"+{args.cache_dtype}"
        if args.weights_dtype != "float32":
            label += f"+w{args.weights_dtype}"
        common = dict(
            kv_heads=hkv, cache_dtype=args.cache_dtype,
            weights_dtype=args.weights_dtype,
            model=f"d{args.dim}x{args.depth} h{args.heads} "
                  f"v{args.vocab} b{args.batch} prompt{args.prompt}",
            backend=jax.default_backend(),
            params=count_params(model.init(jax.random.key(0))),
        )
        if args.paged:
            label = f"paged/{args.kernel}/" + label
            per_tok, crc, toks = bench_paged_config(
                model, batch=args.batch, prompt_len=args.prompt,
                gen_tokens=args.tokens, cache_dtype=args.cache_dtype,
                weights_dtype=args.weights_dtype, kernel=args.kernel,
                page_size=args.page_size,
            )
            ok = per_tok > 0
            results[label] = {
                "decode_ms_per_tok": round(per_tok * 1e3, 3) if ok
                else None,
                "decode_tokens_per_s": round(args.batch / per_tok) if ok
                else None,
            }
            _emit("paged_decode_tokens_per_s",
                  results[label]["decode_tokens_per_s"], "tokens/s",
                  kernel=args.kernel, page_size=args.page_size,
                  decode_ms_per_tok=results[label]["decode_ms_per_tok"],
                  config=label, **common)
            # Per-config CRC row (metric name carries the kv count:
            # `mctpu compare` keeps same-named bench metrics last-wins,
            # so distinct names are what keep a multi-config run fully
            # gateable) + the cross-config accumulator for the combined
            # headline row below.
            _emit(f"paged_greedy_crc_kv{hkv}", int(crc), "crc32",
                  kernel=args.kernel, tokens=int(toks.size),
                  batch=args.batch, gen_tokens=args.tokens,
                  page_size=args.page_size, **common)
            paged_crcs.append((hkv, toks))
            continue
        per_tok, prefill_s = bench_decode_config(
            model, batch=args.batch, prompt_len=args.prompt,
            gen_tokens=args.tokens, cache_dtype=args.cache_dtype,
            weights_dtype=args.weights_dtype,
        )
        # cache k+v bytes actually resident per decoded token's attention
        itemsize = jnp.dtype(args.cache_dtype).itemsize
        cache_mb = (
            args.batch * args.max_seq * hkv * model.head_dim * itemsize * 2
            * args.depth / 1e6
        )
        if args.cache_dtype == "int8":
            # + the per-(position, head) f32 absmax scales.
            cache_mb += (
                args.batch * args.max_seq * hkv * 4 * 2 * args.depth / 1e6
            )
        # A non-positive two-point delta means the per-token cost is below
        # the timer's noise floor at these shapes — report null, never a
        # negative throughput.
        ok = per_tok > 0
        results[label] = {
            "decode_ms_per_tok": round(per_tok * 1e3, 3) if ok else None,
            "decode_tokens_per_s": round(args.batch / per_tok) if ok else None,
            "prefill_ms": round(prefill_s * 1e3, 2),
            "cache_mb": round(cache_mb, 1),
        }
        _emit("decode_tokens_per_s",
              results[label]["decode_tokens_per_s"], "tokens/s",
              config=label, **common, **{
                  k: v for k, v in results[label].items()
                  if k != "decode_tokens_per_s"
              })

    if paged_crcs:
        # The structural A/B row `mctpu compare` gates at exact
        # equality (ci/decode_gate.json): ONE combined CRC over every
        # config's greedy tokens, in kv order — a kernel divergence in
        # ANY config changes it, so a multi-config run is as gated as a
        # single-config one. In f32 the pallas kernel is BITWISE vs the
        # gather, so kernel-on vs kernel-off runs must agree exactly.
        combined = 0
        total = 0
        for _, toks in sorted(paged_crcs, key=lambda kv_: kv_[0]):
            combined = zlib.crc32(toks.tobytes(), combined)
            total += int(toks.size)
        _emit("paged_greedy_crc", int(combined), "crc32",
              kernel=args.kernel, tokens=total,
              configs=len(paged_crcs), batch=args.batch,
              gen_tokens=args.tokens, page_size=args.page_size,
              backend=jax.default_backend())
    best = max(results.items(),
               key=lambda kv_: kv_[1]["decode_tokens_per_s"] or 0)
    # Schema-stamped headline record (obs.schema `bench` event), like
    # bench.py's: `mctpu compare` reads every bench output the same way.
    _emit("decode_best_tokens_per_s", best[1]["decode_tokens_per_s"],
          "tokens/s", config=best[0],
          model=f"d{args.dim}x{args.depth} h{args.heads} v{args.vocab} "
                f"b{args.batch} prompt{args.prompt} cache{args.max_seq}",
          backend=jax.default_backend())


if __name__ == "__main__":
    main()
