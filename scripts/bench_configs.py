"""Run the five BASELINE.json benchmark configurations and print one JSON
line per config: {"config", "model", "dataset", "mesh", "epochs",
"epoch_seconds", "test_accuracy"}.

The five configs (BASELINE.json "configs"):
  1. LeNet-5 on MNIST, single-process          (cnn.c reference twin)
  2. LeNet-5 on MNIST, 4-way data-parallel     (cnnmpi.c twin)
  3. LeNet-5 on Fashion-MNIST, 8-way DP
  4. 3-conv CNN on CIFAR-10 (32x32x3 path)
  5. VGG-small on CIFAR-10, 8-way DP

Real IDX data is used when --data-dir has it; otherwise shape-identical
synthetic sets (this environment has no network — SURVEY.md §4). Multi-way
DP configs need >= that many devices: on a single TPU chip they fall back
to a 1-device mesh and say so in the JSON ("mesh" reports what actually
ran).

Usage: python scripts/bench_configs.py [--epochs N] [--data-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


CONFIGS = [
    # (name, model, dataset, requested data-axis size)
    ("lenet5_mnist_serial", "lenet5", "mnist", 1),
    ("lenet5_mnist_dp4", "lenet5", "mnist", 4),
    ("lenet5_fashion_dp8", "lenet5", "fashion_mnist", 8),
    ("cifar3conv_cifar10", "cifar3conv", "cifar10", 1),
    ("vgg_small_cifar10_dp8", "vgg_small", "cifar10", 8),
]

SYNTHETIC_FALLBACK = {
    "mnist": "synthetic",
    "fashion_mnist": "synthetic",
    "cifar10": "synthetic_cifar",
}


def steady_epoch_seconds(trainer) -> float | None:
    """Tunnel-stable steady-state epoch seconds — the shared
    implementation is Trainer.device_epoch_seconds (two-point over
    pipelined scanned epochs; the round-4 rows measured single
    wall-clocks and "tracked tunnel conditions, not kernels" — PERF.md
    five-config caveat). reps=5: median-of-3 still let one-window
    transients through on ~10% of rows across four banked round-5 runs
    (a dp4 9.4 ms against three ~7.1 ms runs; a vgg 109 ms against
    three ~90 ms); five windows cost ~2 s more and pin the median.
    None -> wall-clock fallback (non-TPU backend — the gate lives in
    the shared method — or a persistently non-positive slope, the same
    guard as bench_decode's `ok = per_tok > 0`)."""
    return trainer.device_epoch_seconds(reps=5)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--num-train", type=int, default=8192,
                    help="synthetic-set size when real data is absent")
    ap.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    ap.add_argument("--configs", default=None,
                    help="comma-separated substring filter on config names "
                         "(e.g. 'lenet5,cifar3conv')")
    args = ap.parse_args()

    import jax

    if args.device == "cpu":
        # In-process selection, like the CLI: the JAX_PLATFORMS env var can
        # be intercepted by a pre-registered TPU plugin (see cli.py).
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and all(
        d.platform == "cpu" for d in jax.devices()
    ):
        print("--device=tpu requested but no accelerator is visible",
              file=sys.stderr)
        raise SystemExit(1)

    from mpi_cuda_cnn_tpu.data.datasets import get_dataset
    from mpi_cuda_cnn_tpu.models.presets import get_model
    from mpi_cuda_cnn_tpu.train.trainer import Trainer
    from mpi_cuda_cnn_tpu.utils.config import Config
    from mpi_cuda_cnn_tpu.utils.logging import MetricsLogger

    ndev = len(jax.devices())
    wanted = args.configs.split(",") if args.configs else None
    for name, model, dataset, want_dp in CONFIGS:
        if wanted is not None and not any(w in name for w in wanted):
            continue
        data_dir = args.data_dir and Path(args.data_dir) / dataset
        if data_dir and (data_dir / "train-images-idx3-ubyte").exists():
            ds = get_dataset(dataset, data_dir=data_dir)
            ds_name = dataset
        else:
            ds_name = SYNTHETIC_FALLBACK[dataset]
            ds = get_dataset(ds_name, num_train=args.num_train, num_test=512)
        n_data = min(want_dp, ndev)
        cfg = Config(
            model=model, dataset=ds_name, epochs=args.epochs, init="he",
            batch_size=32 * n_data, num_devices=n_data, eval_every=0,
            log_every=10**9,
        )
        trainer = Trainer(
            get_model(model), ds, cfg, metrics=MetricsLogger(echo=False)
        )
        result = trainer.train()
        stable = steady_epoch_seconds(trainer)
        print(json.dumps({
            "config": name,
            "model": model,
            "dataset": ds_name,
            "mesh": {"data": n_data},
            "epochs": args.epochs,
            # Primary: two-point steady state (tunnel round-trip
            # cancelled); wall-clock of the last trained epoch stays as
            # a secondary column (it includes one dispatch window).
            "epoch_seconds": round(
                stable if stable is not None else result.epoch_seconds[-1],
                4,
            ),
            "epoch_wallclock_seconds": round(result.epoch_seconds[-1], 4),
            "timing": "two_point" if stable is not None else "wallclock",
            "test_accuracy": round(result.test_accuracy, 4),
        }), flush=True)


if __name__ == "__main__":
    main()
