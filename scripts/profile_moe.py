"""Attribute the single-chip MoE step's milliseconds (VERDICT r4 item 4).

The round-4 measurement: the d512x8 MoE LM step (E=8, top-2, b=8,
s=2048) runs at 235 ms / 11.6% MFU vs the dense twin's 53 ms / 33.8% —
a 6x efficiency cliff explained only by a paragraph. This script turns
the paragraph into numbers, by timing the moe_mlp body's components in
isolation (shared scan_two_point recipe) and the full step under
ablations.

The hypothesis the micro rows test: the dense one-hot dispatch/combine
einsums are QUADRATIC in tokens. dispatch is (T, E, C) with
C = ceil(T*k*cf/E), so the "tec,td->ecd" contraction costs
2*(E*C)*T*D ~ 2*k*cf*T^2*D FLOPs — at T = b*s = 16384 that is ~0.7
TFLOP per MoE layer per direction, several times the expert FFN's
useful work. Under EP over a P-device mesh each shard dispatches its
LOCAL T/P tokens (the cost falls P^2), which is why the design point is
fine and ONE chip is the pathology. The fix measured alongside:
`dispatch_chunk` (parallel/ep.py) — route in fixed-size token chunks,
making the term linear in T while staying pure MXU einsums.

One JSON line per row + a summary attribution line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.obs import cost as obs_cost
from mpi_cuda_cnn_tpu.parallel.ep import (
    _expert_ffn,
    init_moe_params,
    moe_mlp,
    topk_dispatch,
)
from mpi_cuda_cnn_tpu.utils.sync import scan_two_point


def _cap(t: int, k: int, cf: float, e: int) -> int:
    return max(1, -int(-t * k * cf // e))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--tokens", type=int, default=16384,
                    help="T = batch*seq of the round-4 MoE bench row")
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--cf", type=float, default=1.25)
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    ap.add_argument("--chunks", default="0,2048,4096",
                    help="dispatch_chunk values to measure (0 = off)")
    ap.add_argument("--sweep", action="store_true",
                    help="also run the E x cf full-body sweep")
    ap.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    args = ap.parse_args()

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        raise SystemExit(1)

    t, d, e, k = args.tokens, args.dim, args.experts, args.top_k
    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((t, d)), jnp.float32).astype(dt)
    params = init_moe_params(jax.random.key(0), d, args.hidden, e)
    cap = _cap(t, k, args.cf, e)

    def emit(row):
        print(json.dumps(row), flush=True)

    # --- micro rows: each pipeline component in isolation -------------
    # router+dispatch-build: gating softmax, top-k, cumsum position
    # masking, the (T, E, C) one-hot assembly (VPU work, no big matmul).
    def build(xx):
        disp, comb, aux = topk_dispatch(xx, params["gate"], e, cap, k)
        return disp[:, 0, :] + comb[:, 0, :] + aux

    ms_build = scan_two_point(build, args.iters, x) * 1e3

    # The (T, E, C) routing tensors and expert stacks are passed as
    # ARGUMENTS, never closed over: a closure constant is baked into the
    # jitted program body, and at T=16k the dispatch tensor alone is
    # 2.7 GB — this environment's remote-compile tunnel rejects such a
    # program outright (HTTP 413).
    disp, comb, _ = topk_dispatch(x, params["gate"], e, cap, k)
    disp = disp.astype(dt)
    comb = comb.astype(dt)
    w1c = params["w1"].astype(dt)
    w2c = params["w2"].astype(dt)

    # dispatch einsum: (T,E,C) x (T,D) -> (E,C,D) — the suspected
    # quadratic term (2*E*C*T*D FLOPs).
    ms_disp = scan_two_point(
        lambda xx, dd: jnp.einsum("tec,td->ecd", dd, xx), args.iters,
        x, disp,
    ) * 1e3

    expert_in = jnp.einsum("tec,td->ecd", disp, x)

    # expert FFN: the USEFUL MoE compute (2 batched GEMMs over E*C slots).
    ms_ffn = scan_two_point(
        lambda h, w1, w2: _expert_ffn(h, w1, w2),
        args.iters, expert_in, w1c, w2c,
    ) * 1e3

    expert_out = _expert_ffn(expert_in, w1c, w2c)

    # combine einsum: (T,E,C) x (E,C,D) -> (T,D) — the quadratic twin.
    ms_comb = scan_two_point(
        lambda ee, cc: jnp.einsum("tec,ecd->td", cc, ee), args.iters,
        expert_out, comb,
    ) * 1e3

    # GFLOPs of each timed component from XLA cost analysis of the SAME
    # jitted program (obs/cost.py) — the hypothesis's 2*E*C*T*D algebra
    # is now checked against the compiler's count instead of asserted.
    def _gflop(fn, *a):
        c = obs_cost.try_analyze(jax.jit(fn), *a)
        return round(c.flops / 1e9, 1) if c and c.flops else None

    flops = {
        "dispatch_gflop": _gflop(
            lambda xx, dd: jnp.einsum("tec,td->ecd", dd, xx), x, disp
        ),
        "ffn_gflop": _gflop(_expert_ffn, expert_in, w1c, w2c),
        "combine_gflop": _gflop(
            lambda ee, cc: jnp.einsum("tec,ecd->td", cc, ee),
            expert_out, comb,
        ),
    }
    emit({
        "bench": "moe_profile", "T": t, "E": e, "top_k": k, "cf": args.cf,
        "capacity": cap, "dtype": args.dtype,
        "router_dispatch_build_ms": round(ms_build, 3),
        "dispatch_einsum_ms": round(ms_disp, 3),
        "expert_ffn_ms": round(ms_ffn, 3),
        "combine_einsum_ms": round(ms_comb, 3),
        **flops,
        "backend": jax.default_backend(),
    })

    # --- full moe_mlp body at each dispatch_chunk ---------------------
    gate = params["gate"]  # (D, E) — small enough to close over
    for chunk in (int(c) for c in args.chunks.split(",")):
        kw = {"n_experts": e, "capacity_factor": args.cf, "axis": None,
              "top_k": k}
        if chunk:
            kw["dispatch_chunk"] = chunk

        def body(xx, w1, w2, kw=kw):
            y, aux = moe_mlp(xx, {"gate": gate, "w1": w1, "w2": w2}, **kw)
            return y + aux

        # Expert stacks in the COMPUTE dtype, like the micro rows and
        # the scatter prototype — one dtype across every compared row.
        ms_body = scan_two_point(body, args.iters, x, w1c, w2c) * 1e3
        emit({
            "bench": "moe_profile_body", "dispatch_chunk": chunk,
            "T": t, "E": e, "top_k": k, "cf": args.cf,
            "moe_mlp_ms": round(ms_body, 3),
            "backend": jax.default_backend(),
        })

    # --- scatter-dispatch prototype (round-5 experiment) --------------
    # The dense formulation's quadratic terms come from the (T, E, C)
    # routing tensors; a scatter/gather formulation has none: tokens
    # scatter-add into their (expert, slot) rows (one trash row absorbs
    # drops), experts run the same batched GEMMs, outputs gather back.
    # O(T*D) data movement — but XLA lowers scatter on TPU via sort
    # machinery, so whether it BEATS the chunked einsums is an
    # empirical question this row answers.
    def scatter_body(xx, w1, w2, g=params["gate"], e=e, cap=cap, k=k):
        t_, d_ = xx.shape
        probs = jax.nn.softmax((xx @ g).astype(jnp.float32), axis=-1)
        vals, idx = jax.lax.top_k(probs, k)
        gates = vals if k == 1 else vals / jnp.sum(vals, -1, keepdims=True)
        used = jnp.zeros((e,), jnp.float32)
        slots, gsel = [], []
        for j in range(k):
            onehot = jax.nn.one_hot(idx[:, j], e, dtype=jnp.float32)
            pos = jnp.cumsum(onehot, 0) - 1.0 + used[None, :]
            pos_j = jnp.take_along_axis(
                pos, idx[:, j : j + 1], 1
            )[:, 0].astype(jnp.int32)
            keep = pos_j < cap
            slots.append(jnp.where(keep, idx[:, j] * cap + pos_j,
                                   e * cap))
            gsel.append(jnp.where(keep, gates[:, j], 0.0))
            used = used + jnp.sum(onehot * (pos < cap), axis=0)
        expert_in = jnp.zeros((e * cap + 1, d_), xx.dtype)
        for slot in slots:
            expert_in = expert_in.at[slot].add(xx)
        out = _expert_ffn(
            expert_in[: e * cap].reshape(e, cap, d_), w1, w2
        ).reshape(e * cap, d_)
        out = jnp.concatenate(
            [out, jnp.zeros((1, d_), out.dtype)], axis=0
        )
        y = sum(
            gs[:, None].astype(out.dtype) * out[slot]
            for gs, slot in zip(gsel, slots)
        )
        return y

    ms_scatter = scan_two_point(
        scatter_body, args.iters, x, params["w1"].astype(dt),
        params["w2"].astype(dt),
    ) * 1e3
    emit({
        "bench": "moe_profile_scatter", "T": t, "E": e, "top_k": k,
        "cf": args.cf, "moe_scatter_ms": round(ms_scatter, 3),
        "backend": jax.default_backend(),
    })

    # --- E x cf sweep (fixed total params: E experts of hidden H) -----
    if args.sweep:
        for ee in (4, 8):
            p_e = init_moe_params(jax.random.key(0), d, args.hidden, ee)
            for cf in (1.0, 1.25, 2.0):
                def body(xx, w1, w2, g=p_e["gate"], ee=ee, cf=cf):
                    y, aux = moe_mlp(xx, {"gate": g, "w1": w1, "w2": w2},
                                     n_experts=ee, capacity_factor=cf,
                                     axis=None, top_k=k)
                    return y + aux

                ms_body = scan_two_point(
                    body, args.iters, x, p_e["w1"].astype(dt),
                    p_e["w2"].astype(dt),
                ) * 1e3
                emit({
                    "bench": "moe_profile_sweep", "E": ee, "cf": cf,
                    "top_k": k, "T": t,
                    "moe_mlp_ms": round(ms_body, 3),
                    "capacity": _cap(t, k, cf, ee),
                    "backend": jax.default_backend(),
                })


if __name__ == "__main__":
    main()
