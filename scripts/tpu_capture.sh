#!/bin/sh
# One-command TPU measurement capture — run the moment the backend is
# healthy. Every step has its own timeout (a dead axon tunnel HANGS at
# init rather than erroring), appends to PERF_capture.jsonl, and a
# failure of one step does not stop the rest. Order: cheapest probe
# first, then the VERDICT round-3 captures:
#   1. backend probe (matmul compiles + runs)
#   2. GQA flash 5-D grid check (compile + parity + perf; VERDICT #2)
#   3. bench.py            (headline epoch; VERDICT #1)
#   4. bench_lm full matrix incl. fused-CE row (MFU table at HEAD)
#   5. bench_lm d=1024 config (MXU saturation lever; VERDICT #3)
#   6. bench_lm d=1024 + fused chunked CE (the two levers together)
#   7. bench_lm MoE row    (one measured MoE number; VERDICT #7)
#   7b. bench_lm MoE + dispatch-chunk 512 (round-5 2x single-chip lever)
#   7c. bench_lm flagship  (head_dim-128 MFU config — 67.8% measured r4)
#   7d. bench_lm flagship + grad-accum 4 (round-5 update-amortization)
#   8. bench_decode        (KV-cache tokens/s, GQA cache win; VERDICT #5)
#   8b. bench_decode bf16 cache (the round-4 serving lever)
#   8c. bench_decode int8 cache (round-5: quarter bytes + absmax scales)
#   8d. bench_configs      (five-config rows, two-point — round-5 form)
#   8e. bench_speculative  (draft/lookup speculation incl. T=0.8 rows)
#   8f. bench_serve        (paged-KV continuous vs static batching; PR-3)
#   8g. bench_serve_spec   (batched speculative serving pair; ISSUE 14)
#   8h. bench_serve_hosttier (host-tier KV spill pair; ISSUE 17)
#   8i. bench_serve_spec_pagedraft (paged vs window draft; ISSUE 17)
#   8j. autosize_frontier  (goodput capacity sweep; ISSUE 16 — CPU-side)
#   9. profile_lm          (step-time attribution; VERDICT #3)
#   9b. profile_moe        (MoE component attribution + chunk sweep)
#  10. make -C native test_tpu  (C driver on the chip)
# Usage:  sh scripts/tpu_capture.sh   (from the repo root)

set -u
OUT=PERF_capture.jsonl
note() { printf '{"capture_step": "%s", "rc": %d, "utc": "%s"}\n' \
         "$1" "$2" "$(date -u +%FT%TZ)" >> "$OUT"; }

step() {  # step <name> <timeout_s> <cmd...>
    name=$1; secs=$2; shift 2
    echo "== $name (timeout ${secs}s) ==" >&2
    timeout "$secs" "$@" >> "$OUT" 2>> capture.log
    rc=$?
    note "$name" "$rc"
    return $rc
}

: > capture.log
echo "# capture $(date -u +%FT%TZ)" >> "$OUT"

step probe 300 python -c "
import jax, jax.numpy as jnp, json
x = jnp.ones((1024,1024), jnp.bfloat16)
(x@x).block_until_ready()
print(json.dumps({'probe': 'ok', 'backend': jax.default_backend()}))" \
    || { echo 'backend unreachable; aborting capture' >&2; exit 1; }

step gqa_flash_check 900 python scripts/check_gqa_flash.py
step f32_crossover 900 python scripts/bench_crossover.py
step bench_epoch 600 python bench.py
step bench_lm 1200 python scripts/bench_lm.py
step bench_lm_d1024 900 python scripts/bench_lm.py --quick --dim 1024 \
    --depth 8 --heads 16 --batch 4
step bench_lm_d1024_ce 900 python scripts/bench_lm.py --quick --dim 1024 \
    --depth 8 --heads 16 --batch 4 --ce-chunk 512
step bench_lm_moe 900 python scripts/bench_lm.py --quick --moe-experts 8 \
    --moe-top-k 2
# Round-5 lever: chunked dispatch kills the quadratic routing terms
# (PERF.md "MoE single-chip attribution"; 512 = measured optimum).
step bench_lm_moe_chunked 900 python scripts/bench_lm.py --quick \
    --moe-experts 8 --moe-top-k 2 --moe-dispatch-chunk 512
step bench_lm_flagship 900 python scripts/bench_lm.py --quick --dim 4096 \
    --depth 3 --heads 32 --batch 2
# Round-5 lever: grad-accum amortizes the AdamW update's HBM traffic
# (77.4% MFU at accum 16; the accum-4 point is the cheap re-check).
step bench_lm_flagship_ga4 1200 python scripts/bench_lm.py --quick \
    --dim 4096 --depth 3 --heads 32 --batch 8 --grad-accum 4
# PR-2 re-verification: flagship at accum 32 with whole-state donation —
# the >= 80% MFU target (pre-PR banked 78.2%; donation halves live state
# at the update, the headroom the asymptote model leaves).
step bench_lm_flagship_ga32 1800 python scripts/bench_lm.py --quick \
    --dim 4096 --depth 3 --heads 32 --batch 64 --grad-accum 32
# PR-2: grad-accum overhead attribution (tree carry vs scan machinery vs
# update, the fitted ~8 ms/microbatch term) at the flagship shape.
step profile_lm_accum 1200 python scripts/profile_lm.py --dim 4096 \
    --depth 3 --heads 32 --batch 16 --grad-accum 8 --steps 5
# PR-2: MoE with the router-fused dispatch (one routing tensor built in
# the einsum dtype, gate as a (T,E)/scalar map) — the >= 28% MFU target
# at the d512x8 bench config (pre-PR banked 23.0% at chunk 512).
step bench_lm_moe_fused 900 python scripts/bench_lm.py --quick \
    --moe-experts 8 --moe-top-k 2 --moe-dispatch-chunk 512 --grad-accum 4
step bench_decode 900 python scripts/bench_decode.py
step bench_decode_bf16 900 python scripts/bench_decode.py \
    --cache-dtype bfloat16
# Round-5: int8 KV cache (quarter bytes; absmax scales outside the dots).
step bench_decode_int8 900 python scripts/bench_decode.py \
    --cache-dtype int8
# ISSUE 12: the fused paged-attention kernel A/B on chip — same seeded
# workload, gather vs pallas read at the serving dtype (GQA + int8
# cache). The greedy CRCs must match (f32 parity is bitwise; int8 is
# compared on the CPU interpret gate) and the tokens/s pair is the
# FIRST real measurement of the gather's materialization cost.
step bench_decode_paged_gather 900 python scripts/bench_decode.py \
    --paged --kernel gather --kv-heads 2 --cache-dtype int8
step bench_decode_paged_pallas 900 python scripts/bench_decode.py \
    --paged --kernel pallas --kv-heads 2 --cache-dtype int8
# ISSUE 12: int8 decode-weight GEMVs — with the cache already int8 at
# MQA the weight stream dominates; this row banks the quartered-bytes
# effect (f32 weights twin = the bench_decode_int8 step above).
step bench_decode_w8 900 python scripts/bench_decode.py \
    --kv-heads 1 --cache-dtype int8 --weights-dtype int8
# Round-5: stabilized five-config rows (two-point; tunnel-independent).
step bench_configs 1200 python scripts/bench_configs.py
step profile_moe 900 python scripts/profile_moe.py
step bench_speculative 900 python scripts/bench_speculative.py
# PR-3: serving — paged-KV continuous vs static batching (Poisson
# arrivals, mixed lengths): banks chip TTFT/p99-per-token/tokens-per-s
# for the PERF.md "Serving" table (CPU rows measured; schedule effects
# are chip-independent, bandwidth effects are not).
step bench_serve 900 python scripts/bench_serve.py --requests 32 \
    --rate 200
step bench_serve_gqa_int8 900 python scripts/bench_serve.py \
    --requests 32 --rate 200 --kv-heads 1 --cache-dtype int8
# ISSUE 9: prefix sharing on-chip — the sharing-on/off pair at a high
# shared-template mix banks hit rate vs tokens/s + TTFT percentiles
# for the PERF.md "Prefix-sharing" table (skipped prefill FLOPs
# meeting real HBM bandwidth; the CPU rows pin the schedule side).
step bench_serve_prefix 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9 \
    --prefix-cache
step bench_serve_prefix_off 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9
# ISSUE 12: the engine-serve capture — the FIRST real serving rows with
# the fused levers on: tokens/s + TTFT/TPOT percentiles at the serving
# configuration (GQA, auto-routed int8 cache + int8 weights, Pallas
# paged read), its kernel-off twin on the identical seeded workload,
# and the prefix-sharing hit-rate pair with the kernel on — the rows
# PERF.md's "Paged decode kernel" table holds open next to the CPU
# tick counts.
step bench_serve_kernel 900 python scripts/bench_serve.py \
    --requests 32 --rate 200 --kv-heads 2 --cache-dtype auto \
    --attn-kernel pallas --decode-weights-dtype auto
step bench_serve_kernel_off 900 python scripts/bench_serve.py \
    --requests 32 --rate 200 --kv-heads 2 --cache-dtype auto \
    --attn-kernel gather --decode-weights-dtype auto
step bench_serve_prefix_kernel 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9 \
    --prefix-cache --kv-heads 2 --cache-dtype auto \
    --attn-kernel pallas --decode-weights-dtype auto
# ISSUE 14 (speculative serving): the spec-on/off pair on a real chip —
# batched speculative decoding inside the continuous-batching engine
# (per-slot prompt-lookup proposal + ONE batched verify per tick).
# Banks chip tokens/s + TTFT/TPOT for PERF.md's "Speculative serving"
# table next to the CPU tick counts: on CPU the verify block costs ~k
# one-token ticks so only the TICK count drops; on chip the k-row
# verify is bandwidth-bound like the 1-row tick (same cache reads) and
# the tick drop converts to wall-clock. Run with a REAL checkpoint when
# one is at hand — random-init weights only loop weakly, so acceptance
# (and the win) is floor, not ceiling, here.
step bench_serve_spec 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9 \
    --kv-heads 2 --cache-dtype auto --attn-kernel pallas \
    --decode-weights-dtype auto --spec lookup --spec-k 8
step bench_serve_spec_off 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9 \
    --kv-heads 2 --cache-dtype auto --attn-kernel pallas \
    --decode-weights-dtype auto
# ISSUE 17 (host-tier KV spill): the spill-on/off pair on a real chip —
# a device pool tight against the template working set, so LRU churn
# discards prefix pages the tier would have kept. On CPU the readmit
# memcpy competes with a tiny model's prefill; on chip a readmit is
# one page of HBM writes vs a full chunk's prefill FLOPs, so the
# banked chunk-count drop converts to TTFT. Banks tokens/s +
# TTFT/TPOT for PERF.md's ISSUE 17 table next to the CPU counters.
step bench_serve_hosttier 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9 \
    --templates 4 --pages 16 --prefix-cache --spill --host-pages 16
step bench_serve_hosttier_off 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9 \
    --templates 4 --pages 16 --prefix-cache
# ISSUE 17 (paged draft cache): the draft-model speculation pair on a
# real chip — paged draft (persistent KV, catch-up + one row/step) vs
# the cacheless window draft (~W-row recompute per step). Outputs
# bitwise equal; the FLOPs-per-round gap is what the chip measures.
step bench_serve_spec_pagedraft 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9 \
    --spec draft --spec-k 8 --draft-cache paged
step bench_serve_spec_windowdraft 900 python scripts/bench_serve.py \
    --mode continuous --requests 32 --rate 200 --prefix-mix 0.9 \
    --spec draft --spec-k 8 --draft-cache window
step profile_lm 900 python scripts/profile_lm.py
# PR-7 (fleet): the engine-backed fleet on a real chip — N PagedEngine
# replicas (shared weights) behind the failure-aware router, one crash
# + re-dispatch mid-storm. Banks chip tokens/s for the PERF.md fleet
# section (the sim-compute storm rows are chip-independent scheduling;
# this step measures the device-backed replica path).
step bench_fleet_engine 900 python scripts/bench_fleet.py \
    --compute engine --replicas 2 --requests 32 --rate 200 \
    --log summary --fault-plan "replica_crash@fleet.tick:30?replica=0"
# ISSUE 13 (disaggregated serving): the engine-backed 1+1 pool split on
# real chips — prefill and decode replicas stop sharing an accelerator,
# KV page sets move through engine.adopt_pages. Banks the chip
# disagg-vs-unified pair for PERF.md's ISSUE 13 section (the CPU sim
# charges both phases one tick, so the phase-asymmetry win is ONLY
# measurable here): run the unified twin right after with identical
# workload flags and compare tokens/s + TTFT/TPOT percentiles.
step bench_fleet_disagg 900 python scripts/bench_fleet.py \
    --compute engine --pools prefill:1,decode:1 --handoff-ticks 1 \
    --requests 32 --rate 200 --log summary
step bench_fleet_disagg_unified_twin 900 python scripts/bench_fleet.py \
    --compute engine --replicas 2 --requests 32 --rate 200 --log summary
# ISSUE 16 (capacity planning): the offline goodput frontier at the
# banked PERF.md mix — SimCompute storms, so this runs on the CPU side
# of the host and needs no chip time; captured here so every TPU
# session banks the frontier alongside the chip numbers it contextualises
# (per-chip good r/s is what decides how many of THESE chips to buy).
# Deterministic: the JSON row is bitwise-reproducible from the seed.
step autosize_frontier 900 python -m mpi_cuda_cnn_tpu autosize \
    --budget 4 --requests 20000 --rate 2000 --slots 8 --seed 0 \
    --len-dist both --format json
# ISSUE 18 (cache-aware routing): the engine-backed routed-vs-hash
# pair on real chips — identical multi-turn session workload with
# cross-session template shares, once dispatched by prefix/route-key
# overlap and once by session rendezvous hash. The CPU rows prove the
# hit-token win and bitwise output parity; the chip pair banks what a
# routed hit token is WORTH in device prefill seconds (skipped chunks
# are real FLOPs here, not sim ticks) for PERF.md's ISSUE 18 table.
step bench_fleet_routed 900 python scripts/bench_fleet.py \
    --compute engine --replicas 2 --requests 48 --rate 200 \
    --policy cache_aware --prefix-cache --prefix-mix 0.5 \
    --sessions 8 --turns-dist uniform:2-3 --turn-gap-ms 20 \
    --log summary
step bench_fleet_routed_hash_twin 900 python scripts/bench_fleet.py \
    --compute engine --replicas 2 --requests 48 --rate 200 \
    --policy session --prefix-cache --prefix-mix 0.5 \
    --sessions 8 --turns-dist uniform:2-3 --turn-gap-ms 20 \
    --log summary
# ISSUE 18 (online autoscaler): the routed fleet breathing with a
# diurnal wave on real chips — scale decisions (join/drain) pay
# device init/teardown here, so this banks the true cost of a scale
# event next to the CPU rows' tick arithmetic. replica_ticks vs the
# static twin above is the capacity actually burned.
step bench_fleet_autoscale 900 python scripts/bench_fleet.py \
    --compute engine --replicas 1 --requests 48 --rate 200 \
    --policy cache_aware --prefix-cache --prefix-mix 0.5 \
    --sessions 8 --turns-dist uniform:2-3 --turn-gap-ms 20 \
    --diurnal-amp 0.8 --diurnal-period 2 \
    --autoscale 'min=1,max=2,high=2,low=0.5,up=2,down=20,cooldown=0.01' \
    --log summary
# PR-5 (elasticity): the width-invariant canonical-tree step on a real
# chip mesh — banks the elastic-vs-plain step-time ratio for PERF.md
# (CPU-banked 2x at the reference config; TPU fusion/collective costs
# differ) and smoke-proves a preempt -> exit-75 -> cross-width resume
# cycle on real hardware.
# (exits 75 by design — the preemption snapshot; the note records it)
step elastic_bench 900 python -m mpi_cuda_cnn_tpu train \
    --dataset synthetic --model reference_cnn --epochs 2 --batch-size 32 \
    --elastic-width 16 --mesh-shape data:4 --eval-every 0 \
    --checkpoint-dir /tmp/elastic_ck --checkpoint-every-steps 50 \
    --fault-plan "preempt@train.step:100"
step elastic_resume 900 python -m mpi_cuda_cnn_tpu train \
    --dataset synthetic --model reference_cnn --epochs 2 --batch-size 32 \
    --elastic-width 16 --mesh-shape data:2 --eval-every 0 \
    --checkpoint-dir /tmp/elastic_ck --resume
# make prints recipes/compiler lines on stdout — keep the JSONL clean by
# sending this step's stdout to the log; its result is the note() line.
echo "== native_tpu (timeout 900s) ==" >&2
timeout 900 make -C native test_tpu >> capture.log 2>&1
note native_tpu $?

echo "capture done; results in $OUT, stderr in capture.log" >&2
