"""Per-op conv benchmark: XLA emitter vs the Pallas direct kernels.

Produces the per-shape table in PERF.md ("Pallas conv/dense kernels:
per-shape analysis"). Device time = lax.scan of `--iters` calls inside
one jit with a perturbed carry (defeats CSE) and a summed output fetched
to host (forces completion through the tunnel; block_until_ready alone
returns at enqueue here — utils/sync.py). The fixed tunnel round-trip
(~110 ms) amortizes across iterations; 200 is enough to make it noise.

    python scripts/bench_conv_shapes.py [--iters 200]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.ops.conv import conv2d
from mpi_cuda_cnn_tpu.ops.pallas_ops import conv2d_pallas

# The round-1 verdict's question shapes: cifar3conv/vgg_small layers +
# the reference's own conv1.
SHAPES = [
    (128, 32, 32, 3, 3, 64, 1, 1),
    (128, 32, 32, 64, 3, 64, 1, 1),
    (128, 16, 16, 64, 3, 128, 1, 1),
    (128, 8, 8, 128, 3, 256, 1, 1),
    (32, 28, 28, 1, 3, 16, 2, 1),
]


def _timed(fn, x, w, iters):
    @jax.jit
    def run(x0, wt):
        def body(c, _):
            y = fn(c, wt)
            return c + 1e-6, jnp.sum(y.astype(jnp.float32))

        _, ys = jax.lax.scan(body, x0, None, length=iters)
        return jnp.sum(ys)

    float(run(x, w))  # compile + warm
    t0 = time.perf_counter()
    float(run(x, w))
    return time.perf_counter() - t0


def dev_time(fn, x, w, iters, reps=3):
    """Per-op ms via TWO-POINT measurement: time scans of N and 2N
    iterations and report (T2N - TN) / N — the fixed per-dispatch cost
    (the tunnel's ~100 ms round-trip, which would otherwise add
    ~0.5 ms/op at N=200 and compress every ratio toward 1.0) cancels
    exactly. Median of `reps` repetitions (sub-10% differences are not
    resolvable from one sample through a jittery tunnel)."""
    samples = []
    for _ in range(reps):
        t1 = _timed(fn, x, w, iters)
        t2 = _timed(fn, x, w, 2 * iters)
        samples.append((t2 - t1) / iters * 1e3)
    return sorted(samples)[len(samples) // 2]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=200)
    args = ap.parse_args()
    rng = np.random.default_rng(0)
    for dt_name, cast in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        for (n, h, w, ci, k, co, s, p) in SHAPES:
            x = jnp.asarray(rng.standard_normal((n, h, w, ci)), cast)
            wt = jnp.asarray(rng.standard_normal((k, k, ci, co)), cast)
            t_xla = dev_time(partial(conv2d, stride=s, padding=p), x, wt,
                             args.iters)
            t_pl = dev_time(partial(conv2d_pallas, stride=s, padding=p), x,
                            wt, args.iters)
            print(
                f"{dt_name} {n}x{h}x{w}x{ci} k{k} -> {co} s{s}: "
                f"xla {t_xla:7.3f} ms  pallas {t_pl:7.3f} ms  "
                f"ratio {t_pl / t_xla:5.2f}"
            )


if __name__ == "__main__":
    main()
