"""Per-op conv benchmark: XLA emitter vs the Pallas direct kernels.

Produces the per-shape table in PERF.md ("Pallas conv/dense kernels:
per-shape analysis"). Timing = `utils/sync.scan_two_point` (the shared
two-point on-device-scan recipe: (T(2N) - T(N)) / N over jitted scans,
median of 3 — the fixed ~110 ms tunnel round-trip per window cancels
exactly instead of needing to be amortized).

    python scripts/bench_conv_shapes.py [--iters 200]
"""

from __future__ import annotations

import argparse
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.ops.conv import conv2d
from mpi_cuda_cnn_tpu.ops.pallas_conv_gemm import conv2d_pallas_gemm
from mpi_cuda_cnn_tpu.ops.pallas_ops import conv2d_pallas
from mpi_cuda_cnn_tpu.utils.sync import scan_two_point

# The round-1 verdict's question shapes: cifar3conv/vgg_small layers +
# the reference's own conv1.
SHAPES = [
    (128, 32, 32, 3, 3, 64, 1, 1),
    (128, 32, 32, 64, 3, 64, 1, 1),
    (128, 16, 16, 64, 3, 128, 1, 1),
    (128, 8, 8, 128, 3, 256, 1, 1),
    (32, 28, 28, 1, 3, 16, 2, 1),
]


def dev_time(fn, x, w, iters, reps=3):
    """Per-op ms via the shared two-point scan recipe
    (utils/sync.scan_two_point): (T(2N) - T(N)) / N over jitted
    on-device scans, median of `reps` — the fixed per-window dispatch
    cost (the tunnel's ~100 ms round-trip, which would otherwise add
    ~0.5 ms/op at N=200 and compress every ratio toward 1.0) cancels
    exactly, and sub-10% differences are not resolvable from one sample
    through a jittery tunnel."""
    return scan_two_point(fn, iters, x, w, reps=reps) * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=200)
    args = ap.parse_args()
    rng = np.random.default_rng(0)
    for dt_name, cast in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        for (n, h, w, ci, k, co, s, p) in SHAPES:
            x = jnp.asarray(rng.standard_normal((n, h, w, ci)), cast)
            wt = jnp.asarray(rng.standard_normal((k, k, ci, co)), cast)
            t_xla = dev_time(partial(conv2d, stride=s, padding=p), x, wt,
                             args.iters)
            t_pl = dev_time(partial(conv2d_pallas, stride=s, padding=p), x,
                            wt, args.iters)
            # Implicit-GEMM formulation (stride-1 only): the round-5
            # answer to "was the direct kernel's deep-shape loss
            # structural or a formulation gap?"
            t_gemm = (
                dev_time(partial(conv2d_pallas_gemm, stride=s, padding=p),
                         x, wt, args.iters)
                if s == 1 else float("nan")
            )
            print(
                f"{dt_name} {n}x{h}x{w}x{ci} k{k} -> {co} s{s}: "
                f"xla {t_xla:7.3f} ms  pallas {t_pl:7.3f} ms  "
                f"gemm {t_gemm:7.3f} ms  "
                f"ratio {t_pl / t_xla:5.2f}/{t_gemm / t_xla:5.2f}"
            )


if __name__ == "__main__":
    main()
