"""Thin wrapper for the serving bench (mpi_cuda_cnn_tpu.serve.bench) —
`python scripts/bench_serve.py ...` == `mctpu serve-bench ...`: static
vs continuous batching under Poisson arrivals on a paged KV cache,
reporting throughput, TTFT, and p50/p99 per-token latency."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_cnn_tpu.serve.bench import serve_bench_main

if __name__ == "__main__":
    sys.exit(serve_bench_main())
