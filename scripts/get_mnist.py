"""Fetch MNIST as the four IDX files the CLI contract expects.

Twin of the reference's get_mnist target (Makefile:24-35, which pulls a
Google-Drive zip via gdown). Tries the canonical mirrors; in a network-free
environment it falls back to writing a synthetic MNIST-shaped dataset so
every downstream target still runs.
"""

from __future__ import annotations

import sys
import urllib.request
from pathlib import Path

FILES = [
    "train-images-idx3-ubyte",
    "train-labels-idx1-ubyte",
    "t10k-images-idx3-ubyte",
    "t10k-labels-idx1-ubyte",
]
MIRRORS = [
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
]


def main(out_dir: str) -> int:
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    ok = True
    for name in FILES:
        dest = out / name
        if dest.exists():
            continue
        fetched = False
        for mirror in MIRRORS:
            try:
                print(f"fetching {mirror}{name}.gz", file=sys.stderr)
                data = urllib.request.urlopen(mirror + name + ".gz", timeout=30).read()
                import gzip

                dest.write_bytes(gzip.decompress(data))
                fetched = True
                break
            except Exception as e:
                print(f"  failed: {e}", file=sys.stderr)
        ok = ok and fetched
    if not ok:
        print("network fetch failed; writing synthetic MNIST-shaped data",
              file=sys.stderr)
        from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes, write_synthetic_idx

        ds = synthetic_stripes(num_train=60_000, num_test=10_000)
        write_synthetic_idx(out, ds)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "data/mnist"))
