"""Fetch MNIST as the four IDX files the CLI contract expects.

Twin of the reference's get_mnist target (Makefile:24-35, which pulls a
Google-Drive zip via gdown). Tries the canonical mirrors; in a network-free
environment it falls back to writing a synthetic MNIST-shaped dataset so
every downstream target still runs.

Cache-poisoning guard (VERDICT round-5 weak #1): the fallback used to
write synthetic bytes under the REAL filenames, and the next run's
`dest.exists()` would then keep them forever — a later networked
`make get_mnist && make northstar` would silently train on stripes and
label the run MNIST. Now every synthetic fallback also writes a
`SYNTHETIC-DATA` sentinel next to the files; any run that sees the
sentinel re-fetches every file (the cache is known-poisoned) and only a
fully real fetch removes it. Legacy poisoned caches (written before the
sentinel existed) are detected by hashing the files against the
deterministic synthetic generator's bytes. The CLI side refuses to load
a sentinel-marked directory at all (data/datasets.load_idx_dataset), so
a synthetic run can never be labeled MNIST.
"""

from __future__ import annotations

import hashlib
import importlib.util
import random
import sys
import time
import urllib.request
from pathlib import Path

# Load utils/retry.py by FILE PATH, not through the package: this is the
# environment-bootstrap script (runs before the training stack matters),
# and `import mpi_cuda_cnn_tpu` would drag in jax + every subpackage —
# a hard dependency and ~seconds of import for a 3-line delay formula.
# The formula still has exactly ONE definition (utils/retry.py, shared
# with the crash-restart supervisor's pacing).
_ROOT = Path(__file__).resolve().parent.parent
_retry_spec = importlib.util.spec_from_file_location(
    "_mctpu_retry", _ROOT / "mpi_cuda_cnn_tpu" / "utils" / "retry.py",
)
_retry = importlib.util.module_from_spec(_retry_spec)
_retry_spec.loader.exec_module(_retry)
backoff_delay = _retry.backoff_delay

# The package itself is imported ONLY on the no-network fallback (to
# write the synthetic dataset); make that lazy import work when the
# script is run directly (`python scripts/get_mnist.py`, where
# sys.path[0] is scripts/, not the repo root).
sys.path.insert(0, str(_ROOT))

FILES = [
    "train-images-idx3-ubyte",
    "train-labels-idx1-ubyte",
    "t10k-images-idx3-ubyte",
    "t10k-labels-idx1-ubyte",
]
MIRRORS = [
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
]

# Written next to the IDX files whenever they hold the synthetic
# fallback; its presence means "this directory is NOT MNIST".
SENTINEL = "SYNTHETIC-DATA"

# sha256 per filename of the deterministic synthetic fallback
# (synthetic_stripes(60_000, 10_000), fixed seed — recorded under this
# container's numpy so a healthy real cache is cleared by hashing four
# files, never by regenerating the 60k-image dataset). Only LEGACY
# poisoned caches (written before the sentinel existed) depend on these
# constants; every new fallback writes the sentinel, which detects
# poisoning regardless of any numpy stream drift.
SYNTHETIC_SHA256S = {
    "train-images-idx3-ubyte":
        "1544bbf5aa63a24eeb30829a6911698741cf5acc47f8412acb693c9a0ff91adc",
    "train-labels-idx1-ubyte":
        "870475875dab919ab3dc68b95a4c11b0e031bfb77496ddc17685333364c02090",
    "t10k-images-idx3-ubyte":
        "628849af7016c939da39da2109895c831d67770b91c55822fd0427ac0969f91f",
    "t10k-labels-idx1-ubyte":
        "8e03b6600d0575a8451252bebef44f746835c192f2c398bf17aacfd1ee0ea706",
}


def fetch_with_retry(url: str, *, opener=None,
                     tries: int = 3, base_delay: float = 0.5,
                     # injectable U[0,1) default: tests pass a constant
                     # mctpu: disable=MCT004
                     sleep=time.sleep, jitter=random.random,
                     timeout: float = 30.0) -> bytes:
    """Fetch `url`, retrying transient failures with exponential backoff
    plus jitter (utils/retry.backoff_delay — the ONE delay formula,
    shared with the crash-restart supervisor's pacing; the jitter
    de-synchronizes parallel fetchers hammering a recovering mirror).

    `opener`/`sleep`/`jitter` are injection points: tests drive this
    with a flaky opener and a recording sleep, no network and no
    monkeypatching (tests/test_get_mnist.py). Raises the last error
    after `tries` attempts — the caller's mirror loop then moves on.
    """
    if opener is None:
        # Resolved at CALL time so tests patching urllib.request.urlopen
        # (or passing opener=) always win over the import-time binding.
        opener = urllib.request.urlopen
    last: Exception | None = None
    for attempt in range(tries):
        try:
            return opener(url, timeout=timeout).read()
        except Exception as e:  # noqa: BLE001 — any fetch error retries
            last = e
            if attempt + 1 < tries:
                delay = backoff_delay(attempt, base_delay, jitter)
                print(f"  attempt {attempt + 1}/{tries} failed: {e}; "
                      f"retrying in {delay:.2f}s", file=sys.stderr)
                sleep(delay)
    assert last is not None
    raise last


def _sha256(path: Path) -> str:
    h = hashlib.sha256()
    with path.open("rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _cache_is_poisoned(out: Path) -> bool:
    """True when existing files under the real names hold synthetic
    bytes: the sentinel says so, or (legacy caches) the file hashes
    match the deterministic fallback's recorded constants."""
    if (out / SENTINEL).exists():
        return True
    existing = [out / n for n in FILES if (out / n).exists()]
    if not existing:
        return False
    return any(_sha256(p) == SYNTHETIC_SHA256S[p.name] for p in existing)


def main(out_dir: str, *, opener=None, sleep=time.sleep,
         tries: int = 3) -> int:
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    poisoned = _cache_is_poisoned(out)
    if poisoned:
        print(
            f"{out} holds synthetic fallback bytes under MNIST names; "
            "ignoring the cache and re-fetching every file",
            file=sys.stderr,
        )
    ok = True
    for name in FILES:
        dest = out / name
        if dest.exists() and not poisoned:
            continue
        fetched = False
        for mirror in MIRRORS:
            try:
                print(f"fetching {mirror}{name}.gz", file=sys.stderr)
                # Bounded retry + backoff PER mirror fetch: one transient
                # hiccup must not dump a healthy mirror (ISSUE 4).
                data = fetch_with_retry(mirror + name + ".gz",
                                        opener=opener, tries=tries,
                                        sleep=sleep)
                import gzip

                dest.write_bytes(gzip.decompress(data))
                fetched = True
                break
            except Exception as e:
                print(f"  failed: {e}", file=sys.stderr)
        ok = ok and fetched
    if not ok:
        print("network fetch failed; writing synthetic MNIST-shaped data",
              file=sys.stderr)
        from mpi_cuda_cnn_tpu.data.datasets import synthetic_stripes, write_synthetic_idx

        ds = synthetic_stripes(num_train=60_000, num_test=10_000)
        write_synthetic_idx(out, ds)
        (out / SENTINEL).write_text(
            "The IDX files in this directory are SYNTHETIC fallback data\n"
            "(scripts/get_mnist.py could not reach any mirror), not MNIST.\n"
            "Training runs must not be labeled MNIST; the CLI refuses to\n"
            "load this directory. Re-run `make get_mnist` with network to\n"
            "replace them (this marker makes that run ignore the cache).\n"
        )
    else:
        # Every file is a real fetch (or a pre-existing real cache):
        # clear the poisoned marker so the CLI accepts the directory.
        (out / SENTINEL).unlink(missing_ok=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "data/mnist"))
