"""MFU-honest transformer-LM pretraining benchmark.

The CNN epoch benchmark (bench.py) is dispatch/VPU-bound at the
reference's 361k-param model and cannot show the MXU being fed; this
bench does: a ~34M-param decoder-only LM (d=512, 8 layers, 8 heads,
s=2048, vocab 8192) trained with AdamW on the real train step
(train/lm.py), measuring tokens/s and model FLOPs utilization against
the chip's peak.

Runs the matrix {f32, bf16} x {oracle, flash} by default (--quick runs
bf16+flash only) and prints one JSON line per config plus a summary
line. Two FLOPs accountings per row, both computed (obs/cost.py — no
hand-typed constants): `mfu` uses the analytic model FLOPs
(lm_flops_per_token — the standard MFU numerator: remat must not
inflate utilization), `mfu_xla` uses XLA cost analysis of the compiled
step (the FLOPs actually executed). Peaks come from the one registry
(obs.cost.PEAK_TFLOPS); --peak-tflops overrides for other chips.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
from mpi_cuda_cnn_tpu.obs import cost as obs_cost
from mpi_cuda_cnn_tpu.train.lm import (
    count_params,
    lm_flops_per_token,
    make_lm_state,
    make_lm_train_step,
)
from mpi_cuda_cnn_tpu.train.optimizer import make_optimizer
from mpi_cuda_cnn_tpu.utils.sync import two_point


def bench_config(model, *, batch, seq, compute_dtype, attn_impl,
                 steps=20, warmup=3, seed=0, ce_chunk=0,
                 moe_dispatch_chunk=0, grad_accum=1, remat=False,
                 accum_dtype=None):
    opt = make_optimizer(3e-4, opt="adamw", schedule="constant")
    step_fn = make_lm_train_step(
        model, opt, attn_impl=attn_impl, seq_len=seq,
        compute_dtype=compute_dtype, remat=remat, ce_chunk=ce_chunk,
        moe_dispatch_chunk=moe_dispatch_chunk, grad_accum=grad_accum,
        accum_dtype=accum_dtype,
    )
    state = make_lm_state(model, opt, seed)
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(
        rng.integers(0, model.vocab, (batch, seq + 1)), jnp.int32
    )
    tokens, targets = toks[:, :-1], toks[:, 1:]

    # Completion is forced with a HOST FETCH of the final loss, not
    # block_until_ready: under this environment's remote-TPU tunnel,
    # block_until_ready returns once dispatch is queued (measured: a
    # "1.2 ms" step that really takes 300 ms), while a device->host
    # transfer cannot complete before the value exists. The fetched loss
    # depends on the whole step chain, so one fetch drains it all.
    def run(state, n):
        t0 = time.perf_counter()
        m = None
        for _ in range(n):
            state, m = step_fn(state, tokens, targets)
        loss = float(m["loss"])
        return state, time.perf_counter() - t0, loss

    for _ in range(warmup):
        state, m = step_fn(state, tokens, targets)
    float(m["loss"])

    # Shared two-point core (utils/sync.two_point): (T2N - TN)/N cancels
    # the tunnel's fixed ~100 ms window cost, median-of-3 absorbs backend
    # transients (observed round 4: one s=8192 sample pair read 15x
    # slow, the re-run was normal). warmup=0 — warmed above.
    box = {"state": state, "loss": None}

    def timed(k):
        box["state"], dt, box["loss"] = run(box["state"], k)
        return dt

    dt = two_point(timed, steps, warmup=0)
    # Compiled-step accounting (obs/cost.py): the FLOPs XLA actually
    # executes for THIS program — the mfu_xla numerator.
    costs = obs_cost.try_analyze(step_fn, box["state"], tokens, targets)
    return dt, box["loss"], costs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="0 = MHA; < heads = GQA (flash kernel zero-copy)")
    ap.add_argument("--pos", type=str, default="learned",
                    help="learned | rope")
    ap.add_argument("--moe-experts", type=int, default=0,
                    help="0 = dense MLP; >0 = Switch/GShard MoE blocks")
    ap.add_argument("--moe-top-k", type=int, default=1,
                    help="experts per token (1 = Switch, 2 = GShard); "
                         "lm_flops_per_token scales the MLP term by k")
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--peak-tflops", type=float, default=None,
                    help="bf16 peak of the chip (MFU denominator); f32 "
                         "configs use it scaled by the v5e f32/bf16 ratio. "
                         "Default: v5e (197, f32 49)")
    ap.add_argument("--quick", action="store_true",
                    help="bf16+flash only (the headline config)")
    ap.add_argument("--ce-chunk", type=int, default=0,
                    help="chunked fused cross-entropy (train/lm.lm_loss): "
                         "S-chunk size, 0 = dense (B,S,V) logits")
    ap.add_argument("--moe-dispatch-chunk", type=int, default=0,
                    help="chunked MoE routing (ep.moe_mlp): token-chunk "
                         "size, 0 = whole-batch dispatch. Single-chip "
                         "lever for the quadratic dispatch einsum")
    ap.add_argument("--grad-accum", type=int, default=1,
                    help="micro-batch accumulation (must divide batch); "
                         "amortizes the optimizer update's HBM traffic")
    ap.add_argument("--accum-dtype", default=None,
                    choices=["bfloat16", "float32"],
                    help="grad-accumulation carry dtype (default: the "
                         "param dtype, f32 — exact); measured a TIE "
                         "on v5e (XLA fuses the accumulate into the bwd "
                         "epilogue — PERF.md) but kept for backends "
                         "where it isn't (~1-2%% grad error band)")
    ap.add_argument("--remat", action="store_true",
                    help="jax.checkpoint per block (recompute-in-bwd)")
    ap.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    args = ap.parse_args()

    # "float32" == the default exact carry: normalize to None so the
    # accumulation path never does a silent f32->f32 cast round-trip
    # (ADVICE.md — the old choices list also made None unreachable).
    if args.accum_dtype == "float32":
        args.accum_dtype = None

    if args.device == "cpu":
        # In-process selection, like the CLI: the JAX_PLATFORMS env var can
        # be intercepted by a pre-registered TPU plugin (see cli.py).
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        raise SystemExit(1)

    model = TransformerLM(
        vocab=args.vocab, dim=args.dim, heads=args.heads,
        depth=args.depth, max_seq=args.seq, kv_heads=args.kv_heads,
        pos=args.pos, moe_experts=args.moe_experts,
        moe_top_k=args.moe_top_k,
    )

    def peak_for(dtype_name):
        """MFU denominator (TFLOP/s) per compute dtype — the ONE peak
        formula, obs.cost.peak_flops: f32 matmuls have their own (4x
        lower) MXU peak, a --peak-tflops override names the chip's bf16
        peak and f32 scales by the same ratio as v5e."""
        peak = obs_cost.peak_flops(
            dtype_name, override_tflops=args.peak_tflops
        )
        return peak / 1e12 if peak else None

    tokens_per_step = args.batch * args.seq
    flops_per_step = lm_flops_per_token(model, args.seq) * tokens_per_step

    # MFU is only meaningful against a real chip peak: emit it when the
    # backend is a TPU or the caller supplied --peak-tflops; otherwise
    # report tokens/s with mfu=null rather than an MFU against a peak the
    # backend doesn't have.
    backend = jax.default_backend()
    mfu_valid = backend == "tpu" or args.peak_tflops is not None

    # (dtype, attn, ce_chunk) rows. The default matrix ends with the
    # fused chunked-CE variant of the headline config so the dense-vs-
    # chunked comparison is measured in the same run; --ce-chunk applies
    # its value to EVERY row instead.
    if args.quick:
        configs = [("bfloat16", "flash", args.ce_chunk)]
    elif args.ce_chunk:
        configs = [
            ("float32", "oracle", args.ce_chunk),
            ("float32", "flash", args.ce_chunk),
            ("bfloat16", "oracle", args.ce_chunk),
            ("bfloat16", "flash", args.ce_chunk),
        ]
    else:
        ce_default = 512 if args.seq % 512 == 0 else args.seq
        configs = [
            ("float32", "oracle", 0), ("float32", "flash", 0),
            ("bfloat16", "oracle", 0), ("bfloat16", "flash", 0),
            ("bfloat16", "flash", ce_default),
        ]

    results = {}
    nparams = count_params(model.init(jax.random.key(0)))
    for dtype_name, impl, ce in configs:
        cd = jnp.bfloat16 if dtype_name == "bfloat16" else None
        dt, loss, costs = bench_config(
            model, batch=args.batch, seq=args.seq,
            compute_dtype=cd, attn_impl=impl, steps=args.steps,
            ce_chunk=ce, moe_dispatch_chunk=args.moe_dispatch_chunk,
            grad_accum=args.grad_accum, remat=args.remat,
            accum_dtype=args.accum_dtype,
        )
        tok_s = tokens_per_step / dt
        mfu = (
            round(flops_per_step / dt / (peak_for(dtype_name) * 1e12), 4)
            if mfu_valid else None
        )
        xla_flops = costs.flops if costs else None
        mfu_xla = (
            round(xla_flops / dt / (peak_for(dtype_name) * 1e12), 4)
            if mfu_valid and xla_flops else None
        )
        key = f"{dtype_name}+{impl}" + (f"+ce{ce}" if ce else "")
        results[key] = {
            "step_ms": round(dt * 1e3, 2),
            "tokens_per_s": round(tok_s),
            "mfu": mfu,
            "mfu_xla": mfu_xla,
            "xla_flops_per_step": xla_flops,
            "collectives": costs.collectives if costs else None,
            "loss": round(loss, 4),
        }
        extras = {}
        if args.moe_dispatch_chunk:
            extras["moe_dispatch_chunk"] = args.moe_dispatch_chunk
        if args.grad_accum > 1:
            extras["grad_accum"] = args.grad_accum
        if args.accum_dtype:
            extras["accum_dtype"] = args.accum_dtype
        if args.remat:
            extras["remat"] = True
        print(json.dumps({
            "bench": "lm_pretrain", "dtype": dtype_name, "attn": impl,
            "ce_chunk": ce, **extras, **results[key],
        }))

    best = max(results.items(), key=lambda kv: kv[1]["tokens_per_s"])
    print(json.dumps({
        "metric": "lm_tokens_per_s",
        "value": best[1]["tokens_per_s"],
        "unit": "tokens/s",
        "config": best[0],
        "mfu": best[1]["mfu"],
        "params": nparams,
        "model": f"d{args.dim}x{args.depth} h{args.heads} "
                 f"s{args.seq} v{args.vocab} b{args.batch}"
                 + (f" moe{args.moe_experts}k{args.moe_top_k}"
                    if args.moe_experts else ""),
        "peak_tflops": peak_for(best[0].split("+")[0]) if mfu_valid else None,
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
