"""Device-time benchmark for the attention paths (PERF.md methodology).

Times each implementation with `utils/sync.scan_two_point`: jitted
`lax.scan` windows of n and 2n calls, per-call time = (T(2n) − T(n)) / n
(the tunnel's fixed ~100 ms window cost cancels), median of 3 samples.
The original single-window scan-of-3 harness smeared that fixed cost
across 3 iterations and overstated the s=8192 flash forward 8x (37.6 vs
4.6 ms) — the round-4 measurement correction in PERF.md. Prints one
line per implementation.

Usage: python scripts/bench_attention.py [--seq 32768] [--iters 10]
                                         [--dtype bfloat16] [--head-dim 128]
"""

from __future__ import annotations

import argparse
import os
import sys
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_cuda_cnn_tpu.utils.sync import grad_stacked
from mpi_cuda_cnn_tpu.utils.sync import scan_two_point as device_time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=32768)
    ap.add_argument("--iters", type=int, default=10,
                    help="n for the two-point (T(2n)-T(n))/n windows")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--block", type=int, default=1024,
                    help="block size for the jnp blockwise path")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--bwd", action="store_true",
                    help="time fwd+bwd (gradients of sum(o^2) wrt "
                         "q, k, v) instead of the forward alone — the "
                         "PERF.md fused-backward table's command")
    args = ap.parse_args()

    from mpi_cuda_cnn_tpu.ops.attention import blockwise_attention
    from mpi_cuda_cnn_tpu.ops.pallas_attention import flash_attention
    from mpi_cuda_cnn_tpu.parallel.sp import make_ring_flash_attention

    b, s, h, d = 1, args.seq, args.heads, args.head_dim
    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), dt)
               for _ in range(3))
    n = args.iters
    tag = "fwd+bwd" if args.bwd else "causal "

    def measured(fn):
        """The forward itself, or fwd+bwd of sum(o²) via the shared
        grad_stacked wrapper (utils/sync.py)."""
        return grad_stacked(fn) if args.bwd else fn

    t = device_time(measured(partial(flash_attention, causal=True)),
                    n, q, k, v)
    print(f"flash_attention   {tag} s={s}: {t * 1000:8.1f} ms/call")

    # Ring-flash over however many devices are visible (p=1 on one chip:
    # the ring reduces to one diag fold — kernel cost + one merge).
    # Measured through the library's own wrapper so the benchmark and
    # the shipped program can't drift apart.
    devs = jax.devices()
    mesh = jax.sharding.Mesh(np.array(devs), ("seq",))
    ring = make_ring_flash_attention(mesh)
    t = device_time(measured(partial(ring, causal=True)), n, q, k, v)
    print(f"ring_flash (p={len(devs)}) {tag} s={s}: {t * 1000:8.1f} ms/call")

    t = device_time(
        measured(partial(blockwise_attention, block_size=args.block,
                         causal=True)),
        n, q, k, v,
    )
    print(f"jnp blockwise b{args.block} {tag} s={s}: {t * 1000:8.1f} ms/call")


if __name__ == "__main__":
    main()
