"""Device-time benchmark for the attention paths (PERF.md methodology).

Times each implementation as a `lax.scan` of N calls inside ONE jit —
inputs perturbed per step (defeats CSE), outputs summed (defeats DCE),
`float()` on the result (forces completion through this environment's
TPU tunnel; block_until_ready alone can return early). Prints one line
per implementation.

Usage: python scripts/bench_attention.py [--seq 32768] [--iters 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def device_time(fn, n, *args):
    """Mean seconds per call of fn(*args) over n on-device iterations."""

    @jax.jit
    def run(args):
        def body(acc, i):
            # Perturb the first operand so each iteration is fresh work.
            a0 = args[0] * (1.0 + i * 1e-9)
            out = fn(a0, *args[1:])
            return acc + jnp.sum(out.astype(jnp.float32)), None

        acc, _ = lax.scan(body, jnp.zeros((), jnp.float32),
                          jnp.arange(n, dtype=jnp.float32))
        return acc

    float(run(args))  # compile + warmup
    t0 = time.perf_counter()
    float(run(args))
    return (time.perf_counter() - t0) / n


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=32768)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--block", type=int, default=1024,
                    help="block size for the jnp blockwise path")
    args = ap.parse_args()

    from mpi_cuda_cnn_tpu.ops.attention import blockwise_attention
    from mpi_cuda_cnn_tpu.ops.pallas_attention import flash_attention
    from mpi_cuda_cnn_tpu.parallel.sp import make_ring_flash_attention

    b, s, h, d = 1, args.seq, args.heads, args.head_dim
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
               for _ in range(3))
    n = args.iters

    t = device_time(partial(flash_attention, causal=True), n, q, k, v)
    print(f"flash_attention   causal s={s}: {t * 1000:8.1f} ms/call")

    # Ring-flash over however many devices are visible (p=1 on one chip:
    # the ring reduces to one diag fold — kernel cost + one merge).
    # Measured through the library's own wrapper so the benchmark and
    # the shipped program can't drift apart.
    devs = jax.devices()
    mesh = jax.sharding.Mesh(np.array(devs), ("seq",))
    ring = make_ring_flash_attention(mesh)
    t = device_time(partial(ring, causal=True), n, q, k, v)
    print(f"ring_flash (p={len(devs)}) causal s={s}: {t * 1000:8.1f} ms/call")

    t = device_time(
        partial(blockwise_attention, block_size=args.block, causal=True),
        n, q, k, v,
    )
    print(f"jnp blockwise b{args.block} causal s={s}: {t * 1000:8.1f} ms/call")


if __name__ == "__main__":
    main()
