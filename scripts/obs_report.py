"""Aggregate metrics JSONL run(s) into markdown tables (or JSON).

The script twin of `mctpu report` — one implementation (obs/report.py),
two entry points:

    python scripts/obs_report.py run.jsonl [--format md|json]
                                           [--peak-tflops 197]

Reads any file of obs.schema records; '#' comment lines and pre-schema
rows (old PERF_capture.jsonl) pass through without validation.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_cnn_tpu.obs.report import report_main

if __name__ == "__main__":
    raise SystemExit(report_main())
