"""Regenerate the checked-in observability sample run + goldens.

Produces tests/data/sample_serve_run.jsonl — a small, fully
deterministic serving run (FakeClock everywhere: engine time, fault
injection, record stamps; no wall-clock leaks into any number) — plus
the golden renderings tests/test_obs_runtime.py pins byte-for-byte:

    tests/data/golden_serve_report.md   (`mctpu report` output)
    tests/data/golden_serve_trace.md    (`mctpu trace` output)
    tests/data/golden_serve_health.md   (`mctpu health` output, ISSUE 8)
    tests/data/golden_serve_explain.md  (`mctpu explain` output, ISSUE 11)

The workload is chosen for lifecycle diversity: a page pool far smaller
than the worst case forces preemption/requeue cycles, an injected
`slow` fault plus short deadlines expires one request mid-run, and
Poisson arrivals stagger admissions — so the goldens exercise queued /
prefill / decode / preempted / expired segments, not just the happy
path. ISSUE 8 adds a two-tenant seeded mix and a live alert engine
over a deliberately tight SLO spec (tests/data/sample_slo.json), so
the sample carries `alert` events whose replay-equality and CRC the
round-trip tests pin, and the health golden shows violated AND met
objectives. ISSUE 9 turns on prefix sharing for the continuous run
over a --prefix-mix workload (shared template prompts), so the sample
carries `prefix_hits` tick markers and the `prefix` cache-panel
fields the trace/top surfaces render. ISSUE 14 turns on batched
speculative decoding (prompt lookup, k=4) for the same continuous
run, so the sample carries `spec` tick round markers
([rid, proposed, accepted] — variable-length commits the trace token
cross-check must absorb) and the report's serving table renders the
acceptance-rate column. Rerun after any deliberate schema or
rendering change:

    JAX_PLATFORMS=cpu python scripts/make_obs_sample.py
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = Path(__file__).resolve().parents[1]
DATA = REPO / "tests" / "data"

# The sample's SLO spec: thresholds tight enough that the injected
# slow faults push SOME events bad (burn-rate + staleness alerts and a
# mixed health table), loose enough that others stay good.
SAMPLE_SLO = {
    "_doc": ["SLO spec for the checked-in sample run (make_obs_sample)."],
    "tenants": {"*": {"availability": 0.9,
                      "ttft_ms": {"target": 0.9, "threshold_ms": 200.0}}},
    "burn": {"windows_s": [[0.5, 0.1]], "max_rate": 2.0},
    "rules": [{"name": "tick-stale", "kind": "absence", "event": "tick",
               "max_gap_s": 0.1}],
    "max_alerts": 0,
}


def build_records():
    import jax

    from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector
    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.obs.alerts import AlertEngine
    from mpi_cuda_cnn_tpu.obs.causal import BlameAccumulator
    from mpi_cuda_cnn_tpu.obs.metrics import MetricsRegistry
    from mpi_cuda_cnn_tpu.obs.schema import make_record, validate_record
    from mpi_cuda_cnn_tpu.obs.slo import SLOSpec
    from mpi_cuda_cnn_tpu.serve.bench import make_workload
    from mpi_cuda_cnn_tpu.serve.engine import PagedEngine

    model = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)
    params = model.init(jax.random.key(0))
    # ONE geometry definition: the engine construction AND the serve
    # records' replay-geometry stamps read it (ISSUE 15 — a drifted
    # stamp would fail `mctpu replay` with a confusing per-tick digest
    # error instead of an obvious config mismatch).
    geom = dict(slots=3, num_pages=10, page_size=4, spec="lookup",
                spec_k=4)
    engine = PagedEngine(model, params, prefill_chunk=8, max_len=40,
                         **geom)
    records: list[dict] = []
    # ONE alert engine across both modes, fed every record in file
    # order — exactly what a replay of the finished file folds, so the
    # golden's alert records satisfy the live==replay contract (the
    # round-trip test re-derives them and compares CRCs).
    alerts = AlertEngine(slo=SLOSpec.from_dict(SAMPLE_SLO))

    def emit(rec: dict, clock) -> None:
        records.append(validate_record(rec))
        for a in alerts.ingest(rec):
            records.append(validate_record(
                make_record("alert", clock.now, **a)))

    for mode in ("static", "continuous"):
        clock = FakeClock()
        registry = MetricsRegistry(clock=clock)
        # Causal blame (ISSUE 11): folded off the same tick stream the
        # file gets, then stamped as the `blame` summary record the
        # report golden renders and the explain golden drills into.
        blame = BlameAccumulator()

        def sink(rec, clock=clock, registry=registry, blame=blame):
            blame.ingest_tick(rec)
            emit(make_record("tick", clock.now, **rec), clock)
            if (rec["tick"] + 1) % 32 == 0:
                emit(registry.snapshot(mode=rec["mode"]), clock)

        reqs = make_workload(n=8, vocab=13, prompt_min=4, prompt_max=8,
                             out_min=6, out_max=18, rate=40.0, seed=5,
                             deadline_s=0.3, tenants=2, prefix_mix=0.6)
        # Under a FakeClock, in-engine service is instantaneous (the
        # clock only advances on idle waits), so deadlines would be
        # all-or-nothing; the staggered slow faults ratchet the clock
        # past SOME requests' deadlines mid-run — finished + expired +
        # preempted lifecycles all appear in one small file.
        faults = FaultInjector(
            "slow@serve.tick:10?s=0.15;slow@serve.tick:20?s=0.15;"
            "slow@serve.tick:30?s=0.15", clock=clock)
        res = engine.run(reqs, mode=mode, time_fn=clock,
                         sleep_fn=clock.advance, faults=faults,
                         registry=registry, tick_sink=sink,
                         # Prefix sharing and speculation are
                         # continuous-only (static is the reservation /
                         # one-token baseline): the continuous half of
                         # the sample carries the ISSUE 9
                         # prefix_hits/prefix tick fields AND the
                         # ISSUE 14 spec round markers. ISSUE 17 adds a
                         # small host tier so the same half carries the
                         # spill/readmit tier fields and
                         # prefix_readmits markers the trace/top/report
                         # tier surfaces render.
                         prefix=(mode == "continuous"),
                         spec=(mode == "continuous"),
                         host_pages=(6 if mode == "continuous" else 0))
        s = res.summary()
        emit(make_record("blame", clock.now, **blame.summary_fields(mode)),
             clock)
        registry.set("serve.tokens_per_s", s["tokens_per_s"])
        emit(registry.snapshot(mode=mode, final=True), clock)
        for rec in res.request_records():
            emit(make_record("request", clock.now, **rec), clock)
        for ev in res.events:
            emit(make_record("fault", clock.now, **{"mode": mode, **ev}),
                 clock)
        # Geometry stamps (ISSUE 15): what `mctpu replay` rebuilds the
        # mirrors from — the bench mains stamp the same keys, and the
        # values come from the ONE `geom` the engine was built with.
        emit(make_record("serve", clock.now, bench="serve",
                         slots=geom["slots"], pages=geom["num_pages"],
                         page_size=geom["page_size"], spec=geom["spec"],
                         spec_k=geom["spec_k"],
                         prefix_cache=(mode == "continuous"),
                         host_pages=(6 if mode == "continuous" else 0),
                         **s), clock)
        print(f"{mode}: statuses={s['statuses']} "
              f"preemptions={s['preemptions']} ticks={s['decode_ticks']}")
    print(f"alerts: {len(alerts.alerts)} fired, crc={alerts.crc}")
    return records


def build_fleet():
    """A small deterministic FLEET run (ISSUE 18): cache-aware routing
    + the online autoscaler over a diurnal multi-turn session storm —
    every record FakeClock-stamped in-process (the bench main's wall_s
    stamp would leak wall-clock into the checked-in sample). The
    sample carries `fleet` records with the `route`/`route_hits`
    fields (the ROUTER top panel + report routing tables + trace
    routed markers), and scale_up/scale_down replica lifecycle
    markers (the SCALE sparkline + autoscale table). ISSUE 20 runs
    the same storm over the lossy message bus (--transport) with a
    small delay/partition/dup plan, so the sample carries non-zero
    wire counters (msgs_* / retransmits / lease_refusals), per-tick
    fleet `transport` blocks, and `transport` partition-lifecycle
    records — the report's transport table and the trace/top wire
    surfaces render real numbers, not stamped zeros."""
    from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector
    from mpi_cuda_cnn_tpu.obs.causal import BlameAccumulator
    from mpi_cuda_cnn_tpu.obs.metrics import MetricsRegistry
    from mpi_cuda_cnn_tpu.obs.schema import make_record, validate_record
    from mpi_cuda_cnn_tpu.serve.autoscale import (
        Autoscaler,
        parse_autoscale,
    )
    from mpi_cuda_cnn_tpu.serve.fleet import (
        Fleet,
        SimCompute,
        make_fleet_workload,
    )

    records: list[dict] = []
    clock = FakeClock()

    def emit(ev: str, **rec) -> None:
        records.append(validate_record(make_record(ev, clock.now, **rec)))

    registry = MetricsRegistry(clock=clock)
    blame = BlameAccumulator()

    def fleet_sink(rec):
        blame.ingest_fleet(rec)
        emit("fleet", **rec)

    def tick_sink(rec):
        blame.ingest_tick(rec)
        emit("tick", **rec)

    reqs = make_fleet_workload(
        n=24, vocab=13, prompt_min=8, prompt_max=16, out_min=4,
        out_max=8, rate=300.0, seed=7, sessions=6, prefix_mix=0.7,
        templates=4, turns_dist="uniform:2-3", turn_gap_s=0.01,
        diurnal_amp=0.8, diurnal_period_s=0.15)
    # ISSUE 20: a short delay/partition/dup schedule — enough to put
    # retransmits, dedup hits, a false-positive failover, and (via the
    # partitioned replica's post-lease commits) lease refusals into
    # the checked-in sample without swamping the 24-request run.
    faults = FaultInjector(
        "msg_delay@fleet.transport:6?kind=dispatch&count=2&ticks=3;"
        "partition@fleet.transport:18?replica=0&ticks=6;"
        "msg_dup@fleet.transport:40?count=2", clock=clock)
    fleet = Fleet(
        lambda name: SimCompute(vocab=13, chunk=8, salt=7),
        replicas=1, slots=2, num_pages=9, page_size=4, max_len=24,
        policy="cache_aware", prefix=True, host_pages=6, clock=clock,
        registry=registry, fleet_sink=fleet_sink,
        replica_tick_sink=tick_sink, transport=True, faults=faults,
        autoscale=Autoscaler(parse_autoscale(
            "min=1,max=3,high=2,low=0.2,up=2,down=40,cooldown=0.02")))
    res = fleet.run(reqs)
    s = res.summary()
    emit("blame", **blame.summary_fields("fleet"))
    registry.set("serve.tokens_per_s", s["tokens_per_s"])
    records.append(validate_record(
        registry.snapshot(mode="fleet", final=True)))
    for rec in res.replica_log:
        emit("replica", **rec)
    for rec in res.transport_log:
        emit("transport", **rec)
    for rec in res.request_records():
        emit("request", **rec)
    emit("serve", bench="fleet", policy="cache_aware", autoscale=True,
         redispatch="resume", spec="off", replicas_initial=1,
         rate=300.0, slots=2, page_size=4, pages=9, compute="sim",
         prefix_cache=True, host_pages=6, transport=True, **s)
    print(f"fleet: statuses={s['statuses']} "
          f"route_hits={s['route_hits']}/{s['route_hits'] + s['route_misses']} "
          f"ups={s['scale_ups']} downs={s['scale_downs']} "
          f"replica_ticks={s['replica_ticks']}")
    return records


def build_autosize() -> int:
    """Run a tiny-but-real `mctpu autosize` sweep (jax-free SimCompute
    storms) into tests/data/sample_autosize_run.jsonl — the `goodput`
    schema-family sample the report golden renders and the round-trip
    tests replay (ISSUE 16). Small on purpose: budget 3 x both length
    mixes = 6 seeded storms, a couple of seconds."""
    from mpi_cuda_cnn_tpu.obs.autosize import autosize_main

    run = DATA / "sample_autosize_run.jsonl"
    run.unlink(missing_ok=True)
    rc = autosize_main([
        "--budget", "3", "--requests", "400", "--rate", "200",
        "--seed", "0", "--len-dist", "both",
        "--metrics-jsonl", str(run),
    ])
    if rc != 0:
        print(f"error: autosize sample sweep exited {rc}",
              file=sys.stderr)
        return rc
    print(f"wrote {run}")
    return 0


def main() -> int:
    from mpi_cuda_cnn_tpu.obs.causal import explain_main
    from mpi_cuda_cnn_tpu.obs.health import health_main
    from mpi_cuda_cnn_tpu.obs.replay import replay_main
    from mpi_cuda_cnn_tpu.obs.report import report_main
    from mpi_cuda_cnn_tpu.obs.schema import dump_records
    from mpi_cuda_cnn_tpu.obs.timeline import trace_main
    from mpi_cuda_cnn_tpu.obs.top import top_main

    DATA.mkdir(parents=True, exist_ok=True)
    run = DATA / "sample_serve_run.jsonl"
    dump_records(build_records(), run)
    print(f"wrote {run}")
    fleet_run = DATA / "sample_fleet_run.jsonl"
    dump_records(build_fleet(), fleet_run)
    print(f"wrote {fleet_run}")
    slo = DATA / "sample_slo.json"
    slo.write_text(json.dumps(SAMPLE_SLO, indent=2) + "\n")
    print(f"wrote {slo}")
    rc = build_autosize()
    if rc != 0:
        return rc
    autosize_run = DATA / "sample_autosize_run.jsonl"

    # Render with the repo-relative path (and from the repo root) so
    # the golden titles are machine-independent — the round-trip test
    # invokes the renderers the same way. `health` exits 1 BY DESIGN:
    # the sample's tight SLO is violated (that is what makes the golden
    # show both verdicts); the round-trip test pins that exit code too.
    os.chdir(REPO)
    rel = str(run.relative_to(REPO))
    for golden, fn, argv, want_rc in (
        ("golden_serve_report.md", report_main, [rel], 0),
        ("golden_serve_trace.md", trace_main, [rel, "--width", "80"], 0),
        ("golden_serve_health.md", health_main,
         [rel, "--slo", str(slo.relative_to(REPO)), "--verify-alerts"], 1),
        # ISSUE 11: aggregate blame + top blockers + the two worst-TTFT
        # blame trees — exits 0 because the sample conserves (the
        # round-trip test pins bytes AND exit code).
        ("golden_serve_explain.md", explain_main,
         [rel, "--worst", "ttft", "-k", "2"], 0),
        # ISSUE 15: the flight-recorder replay — every tick's stamped
        # state digest cross-checked against the reconstruction, final
        # state rendered (exit 0: the sample replays bitwise).
        ("golden_serve_replay.md", replay_main, [rel], 0),
        # ISSUE 16: the goodput frontier + recommendation tables the
        # report renders for an `mctpu autosize` sweep's record file.
        ("golden_serve_autosize.md", report_main,
         [str(autosize_run.relative_to(REPO))], 0),
        # ISSUE 18: the fleet sample's routing/autoscale surfaces —
        # report's routing + autoscale tables, top's ROUTER/SCALE
        # panel, trace's routed lifecycle markers.
        ("golden_fleet_report.md", report_main,
         [str(fleet_run.relative_to(REPO))], 0),
        ("golden_fleet_top.md", top_main,
         [str(fleet_run.relative_to(REPO)), "--once"], 0),
        ("golden_fleet_trace.md", trace_main,
         [str(fleet_run.relative_to(REPO)), "--width", "80"], 0),
        # The routed lifecycle marker only renders in the per-request
        # detail view — rid 3 is cache-aware routed (8 matched tokens).
        ("golden_fleet_trace_detail.md", trace_main,
         [str(fleet_run.relative_to(REPO)), "--request", "3"], 0),
    ):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = fn(argv)
        if rc != want_rc:
            print(f"error: {golden} renderer exited {rc} (want {want_rc})",
                  file=sys.stderr)
            return rc or 1
        (DATA / golden).write_text(buf.getvalue())
        print(f"wrote {DATA / golden}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
