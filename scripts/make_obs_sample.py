"""Regenerate the checked-in observability sample run + goldens.

Produces tests/data/sample_serve_run.jsonl — a small, fully
deterministic serving run (FakeClock everywhere: engine time, fault
injection, record stamps; no wall-clock leaks into any number) — plus
the golden renderings tests/test_obs_runtime.py pins byte-for-byte:

    tests/data/golden_serve_report.md   (`mctpu report` output)
    tests/data/golden_serve_trace.md    (`mctpu trace` output)

The workload is chosen for lifecycle diversity: a page pool far smaller
than the worst case forces preemption/requeue cycles, an injected
`slow` fault plus short deadlines expires one request mid-run, and
Poisson arrivals stagger admissions — so the goldens exercise queued /
prefill / decode / preempted / expired segments, not just the happy
path. Rerun after any deliberate schema or rendering change:

    JAX_PLATFORMS=cpu python scripts/make_obs_sample.py
"""

from __future__ import annotations

import contextlib
import io
import os
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = Path(__file__).resolve().parents[1]
DATA = REPO / "tests" / "data"


def build_records():
    import jax

    from mpi_cuda_cnn_tpu.faults import FakeClock, FaultInjector
    from mpi_cuda_cnn_tpu.models.transformer import TransformerLM
    from mpi_cuda_cnn_tpu.obs.metrics import MetricsRegistry
    from mpi_cuda_cnn_tpu.obs.schema import make_record, validate_record
    from mpi_cuda_cnn_tpu.serve.bench import make_workload
    from mpi_cuda_cnn_tpu.serve.engine import PagedEngine

    model = TransformerLM(vocab=13, dim=32, heads=4, depth=2, max_seq=48)
    params = model.init(jax.random.key(0))
    engine = PagedEngine(model, params, slots=3, num_pages=10, page_size=4,
                         prefill_chunk=8, max_len=40)
    records: list[dict] = []
    for mode in ("static", "continuous"):
        clock = FakeClock()
        registry = MetricsRegistry(clock=clock)

        def sink(rec, clock=clock, registry=registry):
            records.append(validate_record(
                make_record("tick", clock.now, **rec)))
            if (rec["tick"] + 1) % 32 == 0:
                records.append(registry.snapshot(mode=rec["mode"]))

        reqs = make_workload(n=8, vocab=13, prompt_min=4, prompt_max=8,
                             out_min=6, out_max=18, rate=40.0, seed=5,
                             deadline_s=0.35)
        # Under a FakeClock, in-engine service is instantaneous (the
        # clock only advances on idle waits), so deadlines would be
        # all-or-nothing; the staggered slow faults ratchet the clock
        # past SOME requests' deadlines mid-run — finished + expired +
        # preempted lifecycles all appear in one small file.
        faults = FaultInjector(
            "slow@serve.tick:10?s=0.15;slow@serve.tick:20?s=0.15;"
            "slow@serve.tick:30?s=0.15", clock=clock)
        res = engine.run(reqs, mode=mode, time_fn=clock,
                         sleep_fn=clock.advance, faults=faults,
                         registry=registry, tick_sink=sink)
        s = res.summary()
        registry.set("serve.tokens_per_s", s["tokens_per_s"])
        records.append(registry.snapshot(mode=mode, final=True))
        for rec in res.request_records():
            records.append(validate_record(
                make_record("request", clock.now, **rec)))
        for ev in res.events:
            records.append(validate_record(
                make_record("fault", clock.now, **{"mode": mode, **ev})))
        records.append(validate_record(
            make_record("serve", clock.now, bench="serve", **s)))
        print(f"{mode}: statuses={s['statuses']} "
              f"preemptions={s['preemptions']} ticks={s['decode_ticks']}")
    return records


def main() -> int:
    from mpi_cuda_cnn_tpu.obs.report import report_main
    from mpi_cuda_cnn_tpu.obs.schema import dump_records
    from mpi_cuda_cnn_tpu.obs.timeline import trace_main

    DATA.mkdir(parents=True, exist_ok=True)
    run = DATA / "sample_serve_run.jsonl"
    dump_records(build_records(), run)
    print(f"wrote {run}")

    # Render with the repo-relative path (and from the repo root) so
    # the golden titles are machine-independent — the round-trip test
    # invokes the renderers the same way.
    os.chdir(REPO)
    rel = str(run.relative_to(REPO))
    for golden, fn, argv in (
        ("golden_serve_report.md", report_main, [rel]),
        ("golden_serve_trace.md", trace_main, [rel, "--width", "80"]),
    ):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = fn(argv)
        if rc != 0:
            print(f"error: {golden} renderer exited {rc}", file=sys.stderr)
            return rc
        (DATA / golden).write_text(buf.getvalue())
        print(f"wrote {DATA / golden}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
