"""Measure the f32 oracle/flash attention crossover on the real chip.

`train/lm.py pick_attn_impl` routes f32 short-sequence training to the
oracle because the f32 flash kernel's HIGHEST-precision MXU dots run at
1/4 rate; the bound `_F32_FLASH_MIN_SEQ` was interpolated between
measured endpoints at s=2048 (oracle wins) and s=8192 (flash wins).
This script measures the actual crossover: the full f32 train step with
each impl at s in {2048, 3072, 4096, 6144}, two-point timing through
the tunnel (scripts/bench_lm.bench_config), one JSON row per (s, impl)
plus a final row recommending the smallest measured s where flash wins
— the value `_F32_FLASH_MIN_SEQ` should pin, citing data instead of an
interpolation (VERDICT r3 item 6).

Batch is small (default 2): the f32 oracle at s=6144 materializes
(B, H, S, S) scores — 9.6 GB at b=8, within HBM at b=2 — and the
routing constant is a per-shape decision, not a throughput headline.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from bench_lm import bench_config  # noqa: E402  (scripts/ sibling)
from mpi_cuda_cnn_tpu.models.transformer import TransformerLM


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=int, nargs="+",
                    default=[2048, 3072, 4096, 6144])
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    args = ap.parse_args()

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu" and jax.default_backend() != "tpu":
        print("--device=tpu requested but the backend is "
              f"{jax.default_backend()}", file=sys.stderr)
        raise SystemExit(1)

    crossover = None
    for s in args.seqs:
        model = TransformerLM(
            vocab=args.vocab, dim=args.dim, heads=args.heads,
            depth=args.depth, max_seq=s,
        )
        row = {"bench": "f32_crossover", "seq": s, "batch": args.batch}
        for impl in ("oracle", "flash"):
            dt, _ = bench_config(
                model, batch=args.batch, seq=s, compute_dtype=None,
                attn_impl=impl, steps=args.steps,
            )
            row[f"{impl}_ms"] = round(dt * 1e3, 2)
        row["flash_wins"] = row["flash_ms"] < row["oracle_ms"]
        if crossover is None and row["flash_wins"]:
            crossover = s
        print(json.dumps(row), flush=True)

    note = (
        "smallest measured s where the f32 flash train step beats the "
        "oracle; pin train/lm._F32_FLASH_MIN_SEQ to this"
        if crossover is not None else
        f"no crossover: the oracle won at every measured s (max "
        f"{max(args.seqs)}); keep _F32_FLASH_MIN_SEQ above that bound"
    )
    print(json.dumps({
        "metric": "f32_flash_min_seq",
        "value": crossover,
        "unit": "positions",
        "note": note,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
