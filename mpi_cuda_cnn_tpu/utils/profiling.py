"""Profiling hooks.

The reference has none (SURVEY.md §5.1: no timers, no NVTX, no cudaEvent).
Here: a wall-clock step timer that understands JAX async dispatch — and
now attributes the wall-clock to PHASES (host data prep, async dispatch,
device-compute wait, checkpointing), the split bench.py used to estimate
by hand — plus a context manager around jax.profiler for device traces
viewable in TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
import time

import jax

# Canonical phase names (the "step_phases" record's phases_ms keys).
# data:       host-side batch assembly (indexing, normalize, device_put)
# dispatch:   time inside the jitted call before it returns (async: this
#             is tracing/enqueue, NOT device compute)
# device:     waiting on device completion at sync points (block/fetch)
# checkpoint: snapshot + enqueue of checkpoint saves
STEP_PHASES = ("data", "dispatch", "device", "checkpoint")


class StepTimer:
    """Accumulates per-step wall-clock, optionally attributed to phases.

    Call block_until_ready on the step output before stop() — JAX
    dispatch is async and returns before the TPU finishes. Phase usage:

        timer.start()
        with timer.phase("data"):     bx, by = make_batch()
        with timer.phase("dispatch"): state, m = step(state, bx, by)
        with timer.phase("device"):   hard_block(state)
        timer.stop(n_steps)

    Phases nest with the start/stop envelope, not with each other.

    `clock` has the time.perf_counter call shape; fault-harness tests
    drive it with a faults.FakeClock so telemetry assertions are
    deterministic — the timer itself never reads wall time elsewhere.
    """

    def __init__(self, *, clock=None):
        self._clock = clock if clock is not None else time.perf_counter
        self.reset()

    def reset(self) -> None:
        """Zero all counters (a fresh timer without reallocating)."""
        self.steps = 0
        self.total_s = 0.0
        self.excluded_s = 0.0
        self.phase_s: dict[str, float] = {}
        self._t0 = None

    def start(self) -> None:
        self._t0 = self._clock()

    def stop(self, n_steps: int = 1) -> float:
        if self._t0 is None:
            raise RuntimeError(
                "StepTimer.stop() before start() — call start() at the "
                "top of the timed region (or reset() after an aborted one)"
            )
        dt = self._clock() - self._t0
        self._t0 = None
        self.steps += n_steps
        self.total_s += dt
        return dt

    @contextlib.contextmanager
    def phase(self, name: str):
        """Attribute the enclosed wall-clock to `name` (accumulates)."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.phase_s[name] = (
                self.phase_s.get(name, 0.0) + self._clock() - t0
            )

    @contextlib.contextmanager
    def exclude(self):
        """Remove the enclosed wall-clock from the running envelope (by
        shifting the start mark forward) — for one-off work inside the
        timed region that must not pollute the per-step attribution,
        e.g. the obs cost-analysis AOT compile. The cumulative total is
        kept in `excluded_s` so callers can subtract it from their own
        independent wall-clocks too."""
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            self.excluded_s += dt
            if self._t0 is not None:
                self._t0 += dt

    def add(self, seconds: float, n_steps: int = 1) -> None:
        """Fold an externally measured interval into the accumulators —
        for callers aggregating sub-timers that already excluded what
        must not count (e.g. Trainer.train over run_epoch's seconds)."""
        self.total_s += seconds
        self.steps += n_steps

    @property
    def mean_step_ms(self) -> float:
        return 1000.0 * self.total_s / max(self.steps, 1)

    def phases_ms(self) -> dict[str, float]:
        """Mean per-step milliseconds by phase, plus the unattributed
        remainder as "other" (total envelope minus the phase sum)."""
        n = max(self.steps, 1)
        out = {k: round(1000.0 * v / n, 4) for k, v in self.phase_s.items()}
        other = self.total_s - sum(self.phase_s.values())
        if self.phase_s and other > 0:
            out["other"] = round(1000.0 * other / n, 4)
        return out


@contextlib.contextmanager
def profile_trace(logdir: str | None):
    """Capture a device trace with jax.profiler when logdir is set."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
